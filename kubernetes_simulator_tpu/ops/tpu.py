"""Scheduling kernels — JAX device edition (SURVEY.md §3.5).

Same math as :mod:`.cpu`, re-expressed for XLA: everything is static-shape
jnp over ``[N]``/``[G, N]`` tensors, composable under ``jit``/``vmap``/
``lax.scan``. One pending pod (a "slot" row pytree) is evaluated against
all nodes at once; the mutable scheduling state is a small pytree updated
by masked elementwise adds so the whole replay runs as one compiled scan
on device.

Design notes (TPU-first):
- **No gathers or scatters anywhere in the hot loop.** Batched
  gather/scatter with per-scenario dynamic indices lowers to a serialized
  per-batch loop on TPU (~135 µs per op measured on v5e — 100× the cost of
  the math). Every dynamic-index access is instead expressed as a one-hot
  contraction (MXU matvec) or a masked elementwise update (VPU), which are
  effectively free at these shapes.
- Count-group state lives in **node space** ``[G, N]`` (the value each node
  *sees*: ``count[g, domain_of(g, n)]``), not domain space ``[G, D]``.
  Reads become row contractions; a bind updates every node in the bound
  node's domain via an equality mask — one fused elementwise op.
- masks stay bool, scores f32; per-pod term loops (tolerations, affinity
  terms, spread constraints) are python-unrolled over SMALL static widths.
- no data-dependent shapes: padded slots are neutralized with `where`, a
  `valid` flag multiplies every state update.
"""

from __future__ import annotations

import os
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.encode import PAD, TOL_PAD, TOL_WILDCARD, EncodedCluster, EncodedPods
from ..models.core import Effect, Operator

MAX_NODE_SCORE = 100.0
NEG_INF = -jnp.inf

#: Policy-vector column order (round 9 policy tuner). A wave step built
#: with ``wvec`` (a traced f32 [len(POLICY_COLS)] vector) reads Score
#: weights from these columns instead of the static spec, so one compiled
#: program serves a whole population of scheduler policies — the vector
#: rides the scenario (vmap/mesh) axis and only its VALUES change between
#: candidates. The first five columns are plugin weights; ``fit_least`` is
#: the NodeResourcesFit scoring-strategy selector (> 0.5 → LeastAllocated,
#: else MostAllocated; ignored when the static base strategy is
#: RequestedToCapacityRatio, whose shape table has no cheap traced form).
POLICY_WEIGHT_COLS = (
    "NodeResourcesFit",
    "TaintToleration",
    "NodeAffinity",
    "InterPodAffinity",
    "PodTopologySpread",
)
IDX_FIT_LEAST = len(POLICY_WEIGHT_COLS)
POLICY_COLS = POLICY_WEIGHT_COLS + ("fit_least",)


def policy_weight_fns(spec, wvec):
    """(_w, _on) weight accessors for the score fold.

    Static mode (wvec is None): ``_w`` returns the np.float32 config weight
    and ``_on`` gates zero-weight rows OUT of the program (the historical
    behaviour). Traced mode: ``_w`` indexes wvec and ``_on`` keeps every
    spec-enabled row IN the program — a zero weight then contributes an
    exact ``0.0 * normalized`` term, and because each row's hi/lo extrema
    never depend on the weights, totals bit-match the static program at
    equal weight values."""
    if wvec is None:
        w = dict(spec.weights)

        def _w(name):
            return np.float32(w.get(name, 1.0))

        def _on(name):
            return w.get(name, 1.0) != 0

    else:

        def _w(name):
            return wvec[POLICY_WEIGHT_COLS.index(name)]

        def _on(name):
            return True

    return _w, _on
# One-hot contractions must accumulate exactly (integer-valued f32 counts).
_HI = jax.lax.Precision.HIGHEST


class DevCluster(NamedTuple):
    """Static per-scenario node-side tensors (device copies of
    EncodedCluster). Leading axes may gain a scenario dimension under vmap."""

    allocatable: jax.Array  # [N, R] f32
    node_label_key: jax.Array  # [N, L] i32
    node_label_kv: jax.Array  # [N, L] i32
    node_label_num: jax.Array  # [N, L] f32
    taint_key: jax.Array  # [N, TT] i32
    taint_kv: jax.Array  # [N, TT] i32
    taint_effect: jax.Array  # [N, TT] i32
    node_domain: jax.Array  # [T, N] i32
    num_domains: jax.Array  # [T] i32
    expr_key: jax.Array  # [E] i32
    expr_op: jax.Array  # [E] i32
    expr_vals: jax.Array  # [E, V] i32
    expr_num: jax.Array  # [E] f32
    group_topo: jax.Array  # [G] i32

    @classmethod
    def from_encoded(cls, ec: EncodedCluster) -> "DevCluster":
        return cls(
            allocatable=jnp.asarray(ec.allocatable),
            node_label_key=jnp.asarray(ec.node_label_key),
            node_label_kv=jnp.asarray(ec.node_label_kv),
            node_label_num=jnp.asarray(ec.node_label_num),
            taint_key=jnp.asarray(ec.taint_key),
            taint_kv=jnp.asarray(ec.taint_kv),
            taint_effect=jnp.asarray(ec.taint_effect),
            node_domain=jnp.asarray(ec.node_domain),
            num_domains=jnp.asarray(ec.num_domains),
            expr_key=jnp.asarray(ec.expr_key),
            expr_op=jnp.asarray(ec.expr_op),
            expr_vals=jnp.asarray(ec.expr_vals),
            expr_num=jnp.asarray(ec.expr_num),
            group_topo=jnp.asarray(ec.group_topo),
        )


class DevState(NamedTuple):
    """Mutable scheduling state carried through lax.scan (device twin of
    models.state.SchedState, **node space**): ``match_count[g, n]`` is the
    number of placed pods matching group g in node n's domain under g's
    topology key (0 where the node has no domain). ``match_total[g]`` is the
    cluster-wide count (needed for the bootstrap self-match rule — a plain
    sum over node space would overcount domains with many nodes)."""

    used: jax.Array  # [N, R] f32
    match_count: jax.Array  # [G, N] f32
    anti_active: jax.Array  # [G, N] f32
    pref_wsum: jax.Array  # [G, N] f32
    match_total: jax.Array  # [G] f32

    @classmethod
    def init(cls, ec: EncodedCluster) -> "DevState":
        G = max(ec.num_groups, 1)
        N = ec.num_nodes
        return cls(
            used=jnp.zeros((N, ec.num_resources), jnp.float32),
            match_count=jnp.zeros((G, N), jnp.float32),
            anti_active=jnp.zeros((G, N), jnp.float32),
            pref_wsum=jnp.zeros((G, N), jnp.float32),
            match_total=jnp.zeros((G,), jnp.float32),
        )


def domain_to_node_space(arr_gd: np.ndarray, gdom: np.ndarray) -> np.ndarray:
    """Host: [G, D] domain-space counts → [G, N] node-space (0 where the
    node has no domain under that group's topology key)."""
    safe = np.clip(gdom, 0, None)
    out = np.take_along_axis(arr_gd, safe, axis=1).astype(np.float32)
    return np.where(gdom >= 0, out, 0.0)


def node_space_to_domain(arr_gn: np.ndarray, gdom: np.ndarray, D: int) -> np.ndarray:
    """Host: inverse of :func:`domain_to_node_space` (every domain has ≥1
    node by construction; values agree across a domain's nodes)."""
    G, N = arr_gn.shape
    out = np.zeros((G, D), np.float32)
    valid = gdom >= 0
    gi = np.broadcast_to(np.arange(G)[:, None], (G, N))
    out[gi[valid], gdom[valid]] = arr_gn[valid]
    return out


class PodSlot(NamedTuple):
    """One pending pod's row pytree (scan element)."""

    pod_id: jax.Array  # i32 scalar (PAD = padding slot)
    valid: jax.Array  # bool scalar
    req: jax.Array  # [R] f32
    tol_key: jax.Array  # [TO] i32
    tol_kv: jax.Array  # [TO] i32
    tol_effect: jax.Array  # [TO] i32
    na_req: jax.Array  # [TR, TE] i32
    na_has_req: jax.Array  # bool
    na_pref: jax.Array  # [TP, TE] i32
    na_pref_w: jax.Array  # [TP] f32
    aff_req: jax.Array  # [AR] i32
    anti_req: jax.Array  # [AA] i32
    pref_aff: jax.Array  # [PA] i32
    pref_aff_w: jax.Array  # [PA] f32
    spread_g: jax.Array  # [SP] i32
    spread_skew: jax.Array  # [SP] i32
    spread_dns: jax.Array  # [SP] bool
    pmg: jax.Array  # [G] bool
    group: jax.Array  # i32 scalar (wave-local gang handling)


class SlotSource(NamedTuple):
    """All per-pod slot arrays resident ON DEVICE, uploaded once per
    engine. Per-chunk slot batches are then gathered inside jit from these
    (gather_slots_device) — only the [C, W] index array crosses the host
    boundary per chunk. (Round-3 profile: the host-side numpy gather +
    tunnel H2D of ~18 arrays cost ~127 ms per 2048-wave chunk — more than
    10% of the whole north-star replay.)"""

    requests: jax.Array
    tol_key: jax.Array
    tol_kv: jax.Array
    tol_effect: jax.Array
    na_req: jax.Array
    na_has_req: jax.Array
    na_pref: jax.Array
    na_pref_w: jax.Array
    aff_req: jax.Array
    anti_req: jax.Array
    pref_aff: jax.Array
    pref_aff_w: jax.Array
    spread_g: jax.Array
    spread_skew: jax.Array
    spread_dns: jax.Array
    pmg: jax.Array
    group_id: jax.Array

    @classmethod
    def build(cls, ep: EncodedPods) -> "SlotSource":
        return cls(
            requests=jnp.asarray(ep.requests),
            tol_key=jnp.asarray(ep.tol_key),
            tol_kv=jnp.asarray(ep.tol_kv),
            tol_effect=jnp.asarray(ep.tol_effect),
            na_req=jnp.asarray(ep.na_req),
            na_has_req=jnp.asarray(ep.na_has_req),
            na_pref=jnp.asarray(ep.na_pref),
            na_pref_w=jnp.asarray(ep.na_pref_w),
            aff_req=jnp.asarray(ep.aff_req),
            anti_req=jnp.asarray(ep.anti_req),
            pref_aff=jnp.asarray(ep.pref_aff),
            pref_aff_w=jnp.asarray(ep.pref_aff_w),
            spread_g=jnp.asarray(ep.spread_g),
            spread_skew=jnp.asarray(ep.spread_skew),
            spread_dns=jnp.asarray(ep.spread_dns),
            pmg=jnp.asarray(ep.pod_matches_group),
            group_id=jnp.asarray(ep.group_id.astype(np.int32)),
        )

    @classmethod
    def page(cls, ep: EncodedPods, flat: np.ndarray) -> "SlotSource":
        """One PAGE of the slot source (round 14 paged pod waves): the
        rows at flat pod ids ``flat`` (PAD → neutral row-0 copy; the
        page-local index array keeps those slots invalid), host-gathered
        and uploaded as a fixed-shape SlotSource so the compiled chunk
        program is reused page after page. The full ``build`` keeps every
        pod resident; a page holds chunk_waves × wave_width rows."""
        safe = np.clip(flat, 0, None)
        take = lambda a: jnp.asarray(a[safe])
        return cls(
            requests=take(ep.requests),
            tol_key=take(ep.tol_key),
            tol_kv=take(ep.tol_kv),
            tol_effect=take(ep.tol_effect),
            na_req=take(ep.na_req),
            na_has_req=take(ep.na_has_req),
            na_pref=take(ep.na_pref),
            na_pref_w=take(ep.na_pref_w),
            aff_req=take(ep.aff_req),
            anti_req=take(ep.anti_req),
            pref_aff=take(ep.pref_aff),
            pref_aff_w=take(ep.pref_aff_w),
            spread_g=take(ep.spread_g),
            spread_skew=take(ep.spread_skew),
            spread_dns=take(ep.spread_dns),
            pmg=take(ep.pod_matches_group),
            group_id=jnp.asarray(
                np.where(flat >= 0, ep.group_id[safe], PAD).astype(np.int32)
            ),
        )


@jax.jit
def gather_slots_device(src: SlotSource, idx: jax.Array) -> PodSlot:
    """jnp twin of gather_slots: row-gather on device (value-identical)."""
    safe = jnp.clip(idx, 0, None)
    take = lambda a: a[safe]
    return PodSlot(
        pod_id=idx.astype(jnp.int32),
        valid=idx >= 0,
        req=take(src.requests),
        tol_key=take(src.tol_key),
        tol_kv=take(src.tol_kv),
        tol_effect=take(src.tol_effect),
        na_req=take(src.na_req),
        na_has_req=take(src.na_has_req),
        na_pref=take(src.na_pref),
        na_pref_w=take(src.na_pref_w),
        aff_req=take(src.aff_req),
        anti_req=take(src.anti_req),
        pref_aff=take(src.pref_aff),
        pref_aff_w=take(src.pref_aff_w),
        spread_g=take(src.spread_g),
        spread_skew=take(src.spread_skew),
        spread_dns=take(src.spread_dns),
        pmg=take(src.pmg),
        group=jnp.where(idx >= 0, src.group_id[safe], PAD).astype(jnp.int32),
    )


def gather_slots(ep: EncodedPods, idx: np.ndarray) -> PodSlot:
    """Host-side gather of pod rows at ``idx`` (any leading shape); PAD ids
    become invalid slots."""
    safe = np.clip(idx, 0, None)
    take = lambda a: jnp.asarray(a[safe])
    return PodSlot(
        pod_id=jnp.asarray(idx.astype(np.int32)),
        valid=jnp.asarray(idx >= 0),
        req=take(ep.requests),
        tol_key=take(ep.tol_key),
        tol_kv=take(ep.tol_kv),
        tol_effect=take(ep.tol_effect),
        na_req=take(ep.na_req),
        na_has_req=take(ep.na_has_req),
        na_pref=take(ep.na_pref),
        na_pref_w=take(ep.na_pref_w),
        aff_req=take(ep.aff_req),
        anti_req=take(ep.anti_req),
        pref_aff=take(ep.pref_aff),
        pref_aff_w=take(ep.pref_aff_w),
        spread_g=take(ep.spread_g),
        spread_skew=take(ep.spread_skew),
        spread_dns=take(ep.spread_dns),
        pmg=take(ep.pod_matches_group),
        group=jnp.asarray(np.where(idx >= 0, ep.group_id[safe], PAD).astype(np.int32)),
    )


# ---------------------------------------------------------------------------
# Per-replay derived tensors (computed INSIDE jit so scenario perturbations
# to labels/taints/capacity flow through without host re-encode)
# ---------------------------------------------------------------------------

def expr_match_matrix(dc: DevCluster) -> jax.Array:
    """[N, E] bool — jnp twin of ops.cpu.expr_match_matrix."""
    nk = dc.node_label_key[:, :, None]  # [N, L, 1]
    nv = dc.node_label_kv[:, :, None]
    ek = dc.expr_key[None, None, :]
    key_present = jnp.any((nk == ek) & (nk != PAD), axis=1)  # [N, E]
    in_set = jnp.any(
        (nv[:, :, :, None] == dc.expr_vals[None, None, :, :]) & (nv[:, :, :, None] != PAD),
        axis=(1, 3),
    )
    num = dc.node_label_num[:, :, None]
    gt = jnp.any((nk == ek) & (num > dc.expr_num[None, None, :]), axis=1)
    lt = jnp.any((nk == ek) & (num < dc.expr_num[None, None, :]), axis=1)
    op = dc.expr_op[None, :]
    return (
        ((op == Operator.IN) & key_present & in_set)
        | ((op == Operator.NOT_IN) & ~(key_present & in_set))
        | ((op == Operator.EXISTS) & key_present)
        | ((op == Operator.DOES_NOT_EXIST) & ~key_present)
        | ((op == Operator.GT) & gt)
        | ((op == Operator.LT) & lt)
    )


def group_dom_per_node(dc: DevCluster) -> jax.Array:
    """[G, N] f32 — domain of each node under each count-group's topology
    key (PAD = -1 where none). f32 so node one-hots can contract with it on
    the MXU; domain ids ≤ N are exact in f32."""
    gt = jnp.clip(dc.group_topo, 0, None)
    dom = dc.node_domain[gt]  # [G, N] (static indices — fine)
    return jnp.where(dc.group_topo[:, None] >= 0, dom, PAD).astype(jnp.float32)


class Derived(NamedTuple):
    M: jax.Array  # [N, E] expr match
    gdom_f: jax.Array  # [G, N] f32 (PAD = -1)

    @classmethod
    def build(cls, dc: DevCluster) -> "Derived":
        return cls(expr_match_matrix(dc), group_dom_per_node(dc))


def _term_onehot(gs: jax.Array, G: int) -> jax.Array:
    """[..., A, G] f32 — one-hot rows for term group ids (zero row for
    PAD). Broadcasts over any leading axes (e.g. a wave axis)."""
    return ((gs[..., None] == jnp.arange(G)) & (gs[..., None] >= 0)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# Filters
# ---------------------------------------------------------------------------

def fit_mask(dc: DevCluster, st: DevState, s: PodSlot) -> jax.Array:
    return jnp.all(st.used + s.req[None, :] <= dc.allocatable + 1e-6, axis=1)


def taint_untolerated(dc: DevCluster, s: PodSlot, effects) -> jax.Array:
    t_eff = dc.taint_effect  # [N, TT]
    active = (dc.taint_key != PAD)
    eff_match = jnp.zeros_like(active)
    for e in effects:
        eff_match = eff_match | (t_eff == e)
    active = active & eff_match
    tk = s.tol_key  # [TO]
    valid_tol = tk != TOL_PAD
    key_ok = (tk[None, None, :] == TOL_WILDCARD) | (tk[None, None, :] == dc.taint_key[:, :, None])
    val_ok = (s.tol_kv[None, None, :] == PAD) | (s.tol_kv[None, None, :] == dc.taint_kv[:, :, None])
    eff_ok = (s.tol_effect[None, None, :] == 0) | (s.tol_effect[None, None, :] == t_eff[:, :, None])
    tolerated = jnp.any(key_ok & val_ok & eff_ok & valid_tol[None, None, :], axis=2)
    return active & ~tolerated


def taint_mask(dc: DevCluster, s: PodSlot) -> jax.Array:
    bad = taint_untolerated(dc, s, (int(Effect.NO_SCHEDULE), int(Effect.NO_EXECUTE)))
    return ~jnp.any(bad, axis=1)


def taint_prefer_count(dc: DevCluster, s: PodSlot) -> jax.Array:
    bad = taint_untolerated(dc, s, (int(Effect.PREFER_NO_SCHEDULE),))
    return jnp.sum(bad, axis=1).astype(jnp.float32)


def _terms_match(M: jax.Array, terms: jax.Array) -> jax.Array:
    """[N] — OR over terms of AND over exprs (PAD exprs auto-true; a term is
    valid iff slot 0 is a real expr)."""
    valid_term = terms[:, 0] >= 0  # [T]
    safe = jnp.clip(terms, 0, None)
    per_expr = M[:, safe] | (terms[None, :, :] < 0)  # [N, T, E]
    per_term = jnp.all(per_expr, axis=2) & valid_term[None, :]
    return jnp.any(per_term, axis=1)


def node_affinity_mask(d: Derived, s: PodSlot) -> jax.Array:
    return jnp.where(s.na_has_req, _terms_match(d.M, s.na_req), True)


def node_affinity_score(d: Derived, s: PodSlot) -> jax.Array:
    terms = s.na_pref  # [TP, TE]
    valid_term = terms[:, 0] >= 0
    safe = jnp.clip(terms, 0, None)
    per_expr = d.M[:, safe] | (terms[None, :, :] < 0)
    per_term = jnp.all(per_expr, axis=2) & valid_term[None, :]
    return jnp.sum(per_term * s.na_pref_w[None, :], axis=1).astype(jnp.float32)


def _term_rows(st_counts: jax.Array, oh: jax.Array) -> jax.Array:
    """[A, N] — node-space count rows for A term groups (one-hot matmul —
    exact: each output is a single selected element)."""
    return jnp.einsum("ag,gn->an", oh, st_counts, precision=_HI)


def interpod_filter_mask(d: Derived, st: DevState, s: PodSlot) -> jax.Array:
    """Required (anti-)affinity + the SYMMETRIC existing-pods'-anti check,
    all as one-hot contractions over node-space counts — no gathers."""
    G = st.match_count.shape[0]
    N = d.gdom_f.shape[1]
    pmg_f = s.pmg.astype(jnp.float32)
    ok = jnp.ones(N, dtype=bool)
    gvalid_all = d.gdom_f >= 0  # [G, N]

    ohA = _term_onehot(s.aff_req, G)  # [A, G]
    if ohA.shape[0]:
        cnt = _term_rows(st.match_count, ohA)  # [A, N]
        gvalid = jnp.einsum("ag,gn->an", ohA, gvalid_all.astype(jnp.float32), precision=_HI) > 0.5
        total = jnp.einsum("ag,g->a", ohA, st.match_total, precision=_HI)  # [A]
        selfm = jnp.einsum("ag,g->a", ohA, pmg_f, precision=_HI) > 0.5  # [A]
        boot = (total == 0) & selfm
        term_ok = (cnt >= 1) & gvalid
        ok = ok & jnp.all(
            jnp.where((s.aff_req >= 0)[:, None], term_ok | boot[:, None], True), axis=0
        )

    ohB = _term_onehot(s.anti_req, G)
    if ohB.shape[0]:
        cntb = _term_rows(st.match_count, ohB)
        gvalidb = jnp.einsum("ag,gn->an", ohB, gvalid_all.astype(jnp.float32), precision=_HI) > 0.5
        viol = (cntb >= 1) & gvalidb
        ok = ok & jnp.all(jnp.where((s.anti_req >= 0)[:, None], ~viol, True), axis=0)

    # Symmetric: a node is blocked if any placed pod with a required anti
    # term g sits in its domain and this pod matches g.
    blocked = (
        jnp.einsum("g,gn->n", pmg_f, (st.anti_active > 0).astype(jnp.float32), precision=_HI)
        > 0.5
    )
    return ok & ~blocked


def interpod_score(d: Derived, st: DevState, s: PodSlot, has_symmetric_pref: bool = True) -> jax.Array:
    G = st.match_count.shape[0]
    N = d.gdom_f.shape[1]
    raw = jnp.zeros(N, dtype=jnp.float32)
    ohP = _term_onehot(s.pref_aff, G)
    if ohP.shape[0]:
        cnt = _term_rows(st.match_count, ohP)  # [P, N]
        w = jnp.where(s.pref_aff >= 0, s.pref_aff_w, 0.0)
        raw = raw + jnp.einsum("p,pn->n", w, cnt, precision=_HI)
    if has_symmetric_pref:
        # pref_wsum is already node-space — the old [G, N] sweep is now a
        # single matvec.
        raw = raw + jnp.einsum(
            "g,gn->n", s.pmg.astype(jnp.float32), st.pref_wsum, precision=_HI
        )
    return raw


def spread_filter_mask(d: Derived, st: DevState, s: PodSlot) -> jax.Array:
    G = st.match_count.shape[0]
    N = d.gdom_f.shape[1]
    ohS = _term_onehot(s.spread_g, G)  # [A, G]
    if not ohS.shape[0]:
        return jnp.ones(N, dtype=bool)
    cnt = _term_rows(st.match_count, ohS)  # [A, N]
    gvalid = jnp.einsum("ag,gn->an", ohS, (d.gdom_f >= 0).astype(jnp.float32), precision=_HI) > 0.5
    # min over valid domains == min over nodes that have a domain (every
    # domain has ≥1 node by construction).
    minv = jnp.min(jnp.where(gvalid, cnt, jnp.inf), axis=1)  # [A]
    has_domains = jnp.isfinite(minv)
    selfm = jnp.einsum("ag,g->a", ohS, s.pmg.astype(jnp.float32), precision=_HI)
    c_ok = (
        gvalid
        & has_domains[:, None]
        & (cnt + selfm[:, None] - jnp.where(has_domains, minv, 0.0)[:, None]
           <= s.spread_skew[:, None])
    )
    return jnp.all(jnp.where(((s.spread_g >= 0) & s.spread_dns)[:, None], c_ok, True), axis=0)


def spread_score_upstream(d: Derived, st: DevState, s: PodSlot, w_g) -> tuple:
    """Upstream podtopologyspread raw score (mirrors ops.cpu.spread_score):
    ``floor(Σ_scored cnt·log(size+2) + (maxSkew−1))`` per node over the
    ScheduleAnyway constraints, plus the ignored mask (node missing a
    scored key) and the dynamic any-scored flag (PreScore Skip). ``w_g`` is
    the static [G] weight table."""
    G = st.match_count.shape[0]
    N = d.gdom_f.shape[1]
    ohS = _term_onehot(s.spread_g, G)
    if not ohS.shape[0]:
        return (
            jnp.zeros(N, jnp.float32),
            jnp.zeros(N, bool),
            jnp.zeros((), bool),
        )
    cnt = _term_rows(st.match_count, ohS)  # [A, N]
    gvalid = (
        jnp.einsum("ag,gn->an", ohS, (d.gdom_f >= 0).astype(jnp.float32), precision=_HI)
        > 0.5
    )
    scored = (s.spread_g >= 0) & ~s.spread_dns  # [A]
    wrow = jnp.einsum("ag,g->a", ohS, jnp.asarray(w_g, jnp.float32), precision=_HI)
    raw = jnp.zeros(N, jnp.float32)
    ignored = jnp.zeros(N, bool)
    for i in range(ohS.shape[0]):
        contrib = cnt[i] * wrow[i] + (s.spread_skew[i].astype(jnp.float32) - 1.0)
        raw = raw + jnp.where(scored[i], contrib, 0.0)
        ignored = ignored | (scored[i] & ~gvalid[i])
    # Upstream int64(math.Round(score)): floor(x+0.5), non-negative x.
    return jnp.floor(raw + 0.5), ignored, jnp.any(scored)


def spread_norm_from_extrema(raw, ignored, hi, lo, any_scored, f32ok=False) -> jax.Array:
    """The normalize half of :func:`spread_upstream_normalize`, with the
    extrema (over feasible & ~ignored nodes, ±inf-masked reductions)
    supplied by the caller — so they can ride a shared stacked reduce.

    ``f32ok`` (static): when the trace bound guarantees raw ≤ 83886,
    ``floor((100·(hi+lo−s)) / hi)`` computed in f32 equals the integer
    division exactly (numerator ≤ 200·83886 < 2²⁴ is exactly
    representable, and a misround needs hi·quotient > 2²⁴ — impossible
    under the bound), so the slow int32 floordiv (no hardware int div on
    TPU) is skipped."""
    has = hi > -jnp.inf
    if f32ok:
        hi_f = jnp.where(has, hi, 0.0)
        lo_f = jnp.where(has, lo, 0.0)
        pos = hi_f > 0
        vals = jnp.floor(
            (np.float32(MAX_NODE_SCORE) * (hi_f + lo_f - raw))
            / jnp.where(pos, hi_f, 1.0)
        )
        out = jnp.where(pos, vals, np.float32(MAX_NODE_SCORE))
        return jnp.where(ignored | ~has | ~any_scored, 0.0, out)
    hi_i = jnp.where(has, hi, 0.0).astype(jnp.int32)
    lo_i = jnp.where(has, lo, 0.0).astype(jnp.int32)
    vals = (np.int32(MAX_NODE_SCORE) * (hi_i + lo_i - raw.astype(jnp.int32))) // jnp.where(
        hi_i > 0, hi_i, 1
    )
    out = jnp.where(hi_i > 0, vals.astype(jnp.float32), np.float32(MAX_NODE_SCORE))
    return jnp.where(ignored | ~has | ~any_scored, 0.0, out)


def spread_upstream_normalize(raw, ignored, feasible, any_scored, f32ok=False) -> jax.Array:
    """Upstream two-pass NormalizeScore (mirrors ops.cpu.spread_normalize
    bit-for-bit): int32-exact ``100·(max+min−s) // max`` with extrema over
    non-ignored feasible nodes; ignored → 0; max == 0 → 100; no scored
    constraints → all 0."""
    okn = feasible & ~ignored
    hi = jnp.max(jnp.where(okn, raw, -jnp.inf))
    lo = jnp.min(jnp.where(okn, raw, jnp.inf))
    return spread_norm_from_extrema(raw, ignored, hi, lo, any_scored, f32ok)


# ---------------------------------------------------------------------------
# Resource scores
# ---------------------------------------------------------------------------

# Scores are INTEGER-valued f32, floored through single-op chains — nothing
# XLA can FMA-fuse — so device scores are bit-identical to ops.cpu and
# argmax ties break the same way (SURVEY.md §7 hard part #6). Mirrors
# upstream's int64 node scores.


def _int_resource_score(frac: jax.Array, weights) -> jax.Array:
    s = jnp.floor(frac * np.float32(MAX_NODE_SCORE))  # [N, R], integral
    acc = jnp.zeros(frac.shape[0], dtype=jnp.float32)
    wsum = 0.0
    for r in range(frac.shape[1]):
        w = float(weights[r])
        if w != 0:
            acc = acc + s[:, r] * np.float32(w)  # exact: small ints
            wsum += w
    if wsum == 0:
        return acc
    return jnp.floor(acc / np.float32(wsum))


def least_allocated_score_from_used(dc: DevCluster, used: jax.Array, s: PodSlot, weights) -> jax.Array:
    alloc = dc.allocatable
    denom = jnp.where(alloc > 0, alloc, 1.0)
    frac = jnp.where(alloc > 0, (alloc - used - s.req[None, :]) / denom, 0.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    return _int_resource_score(frac, weights)


def least_allocated_score(dc: DevCluster, st: DevState, s: PodSlot, weights) -> jax.Array:
    return least_allocated_score_from_used(dc, st.used, s, weights)


def most_allocated_score_from_used(dc: DevCluster, used: jax.Array, s: PodSlot, weights) -> jax.Array:
    alloc = dc.allocatable
    denom = jnp.where(alloc > 0, alloc, 1.0)
    frac = jnp.where(alloc > 0, (used + s.req[None, :]) / denom, 0.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    return _int_resource_score(frac, weights)


def most_allocated_score(dc: DevCluster, st: DevState, s: PodSlot, weights) -> jax.Array:
    return most_allocated_score_from_used(dc, st.used, s, weights)


def piecewise_interp_int(util: jax.Array, xs, ys) -> jax.Array:
    """Mirror of ops.cpu.piecewise_interp_int (seg = y0 + floor(t·Δy))."""
    out = jnp.full(util.shape, np.float32(ys[-1]), dtype=jnp.float32)
    for i in range(len(xs) - 2, -1, -1):
        x0, x1 = np.float32(xs[i]), np.float32(xs[i + 1])
        y0, y1 = np.float32(ys[i]), np.float32(ys[i + 1])
        t = (util.astype(jnp.float32) - x0) * (np.float32(1.0) / (x1 - x0))
        seg = y0 + jnp.floor(t * (y1 - y0))
        out = jnp.where(util <= x1, seg, out)
    return jnp.where(util <= np.float32(xs[0]), np.float32(ys[0]), out).astype(jnp.float32)


def requested_to_capacity_ratio_score(
    dc: DevCluster, st: DevState, s: PodSlot, weights, shape_x, shape_y
) -> jax.Array:
    return requested_to_capacity_ratio_score_from_used(
        dc, st.used, s, weights, shape_x, shape_y
    )


def requested_to_capacity_ratio_score_from_used(
    dc: DevCluster, used: jax.Array, s: PodSlot, weights, shape_x, shape_y
) -> jax.Array:
    alloc = dc.allocatable
    denom = jnp.where(alloc > 0, alloc, 1.0)
    frac = jnp.where(alloc > 0, (used + s.req[None, :]) / denom, 0.0)
    util = jnp.floor(jnp.clip(frac, 0.0, 1.0) * np.float32(100.0))
    score_r = piecewise_interp_int(util, list(shape_x), list(shape_y))
    acc = jnp.zeros(alloc.shape[0], dtype=jnp.float32)
    wsum = 0.0
    for r in range(score_r.shape[1]):
        w = float(weights[r])
        if w != 0:
            acc = acc + score_r[:, r] * np.float32(w)
            wsum += w
    if wsum == 0:
        return acc
    return jnp.floor(acc / np.float32(wsum))


# ---------------------------------------------------------------------------
# Normalization + selection + state update
# ---------------------------------------------------------------------------

def _normalize_row(raw, lo, hi, any_f, minmax: bool, reverse: bool) -> jax.Array:
    """The one copy of the normalize arithmetic (mirrors ops.cpu). Callers
    supply the masked extrema; ``minmax`` picks min-max vs max-only form.
    For the max-only form, a −inf-filled ``hi`` is equivalent to the CPU
    path's 0-filled max because raws are non-negative."""
    if minmax:
        span = hi - lo
        ok = any_f & (span > 0)
        out = jnp.floor(
            (raw - jnp.where(ok, lo, 0.0))
            * (np.float32(MAX_NODE_SCORE) / jnp.where(ok, span, 1.0))
        )
        out = jnp.where(ok, out, 0.0)
        if reverse:
            out = jnp.where(ok, np.float32(MAX_NODE_SCORE) - out, 0.0)
    else:
        pos = hi > 0
        out = jnp.floor((raw * np.float32(MAX_NODE_SCORE)) / jnp.where(pos, hi, 1.0))
        out = jnp.where(pos, out, 0.0)
        if reverse:
            out = jnp.where(
                pos, np.float32(MAX_NODE_SCORE) - out, np.float32(MAX_NODE_SCORE)
            )
    return out.astype(jnp.float32)


def normalize_max(raw: jax.Array, feasible: jax.Array, reverse: bool = False) -> jax.Array:
    """Mirror of ops.cpu.normalize_max: floor(raw·100/max), integer scores."""
    mx = jnp.max(jnp.where(feasible, raw, 0.0))
    return _normalize_row(raw, None, mx, None, False, reverse)


def normalize_min_max(raw: jax.Array, feasible: jax.Array, reverse: bool = False) -> jax.Array:
    """Mirror of ops.cpu.normalize_min_max: floor((raw−lo)·(100/span))."""
    any_f = jnp.any(feasible)
    lo = jnp.min(jnp.where(feasible, raw, jnp.inf)).astype(jnp.float32)
    hi = jnp.max(jnp.where(feasible, raw, -jnp.inf)).astype(jnp.float32)
    return _normalize_row(raw, lo, hi, any_f, True, reverse)


def select_node(scores: jax.Array, feasible: jax.Array):
    """(choice i32, placed bool) — lowest-index argmax tie-break, matching
    numpy argmax (SURVEY.md §7 hard part #6).

    ONE variadic reduce computes (max, argmax-with-min-index-ties) — and
    ``placed`` falls out as max > −inf (a node is feasible iff its masked
    score is finite), instead of a second full reduce_or pass over
    ``feasible`` (profile round 3: the separate any() was 19% of north-star
    device time)."""
    masked = jnp.where(feasible, scores, NEG_INF)
    iota = jax.lax.broadcasted_iota(jnp.int32, masked.shape, masked.ndim - 1)

    def comb(a, b):
        av, ai = a
        bv, bi = b
        better = (bv > av) | ((bv == av) & (bi < ai))
        return jnp.where(better, bv, av), jnp.where(better, bi, ai)

    mx, choice = jax.lax.reduce(
        (masked, iota),
        (np.float32(-np.inf), np.int32(np.iinfo(np.int32).max)),
        comb,
        dimensions=(masked.ndim - 1,),
    )
    placed = mx > NEG_INF
    return jnp.where(placed, choice.astype(jnp.int32), PAD), placed


class ShardCtx(NamedTuple):
    """Static description of a node-plane shard (round 14 big-scenario
    mode): inside ``shard_map`` over ``parallel.mesh.NODE_AXIS`` each
    device holds a contiguous ``n_local``-wide block of the (padded)
    node axis. ``n_real`` is the unpadded node count — pad rows are
    masked infeasible so they can never win selection."""

    axis: str  # mesh axis name (parallel.mesh.NODE_AXIS)
    n_local: int  # nodes per shard (padded total / nshards)
    n_real: int  # real (unpadded) node count
    nshards: int


def shard_gids(ctx: ShardCtx) -> jax.Array:
    """[n_local] i32 — GLOBAL node ids of this shard's rows (contiguous
    blocks, so global id order equals the replicated program's node
    order — the property that makes the two-stage tie-break exact)."""
    off = jax.lax.axis_index(ctx.axis).astype(jnp.int32) * np.int32(ctx.n_local)
    return off + jnp.arange(ctx.n_local, dtype=jnp.int32)


def masked_argmin(scores: jax.Array, mask: jax.Array):
    """(choice i32, any bool) — lowest-index argmin over the masked
    entries, in ONE variadic reduce (the ``select_node`` comparator with
    the sign flipped). Selection is identical to
    ``argmax(where(mask, -scores, -inf))`` + a separate ``any(mask)``
    (numpy first-occurrence tie-break) but pays one pass instead of two —
    the preempt-select's victim-node rank is the hot consumer (round 10
    fused tier-preemption). ``choice`` is PAD when nothing is masked
    in."""
    masked = jnp.where(mask, -scores, NEG_INF)
    iota = jax.lax.broadcasted_iota(jnp.int32, masked.shape, masked.ndim - 1)

    def comb(a, b):
        av, ai = a
        bv, bi = b
        better = (bv > av) | ((bv == av) & (bi < ai))
        return jnp.where(better, bv, av), jnp.where(better, bi, ai)

    mx, choice = jax.lax.reduce(
        (masked, iota),
        (np.float32(-np.inf), np.int32(np.iinfo(np.int32).max)),
        comb,
        dimensions=(masked.ndim - 1,),
    )
    ok = mx > NEG_INF
    return jnp.where(ok, choice.astype(jnp.int32), PAD), ok


def first_reject_counts(masks, failed) -> jax.Array:
    """[K] i32 — per-plugin first-reject node counts for one slot, the
    device form of the kube "0/N nodes available" attribution
    (ops.cpu.first_reject_update is the host edition). ``masks`` is the
    ordered list of per-plugin [N] bool masks from the fused eval;
    ``failed`` gates the whole vector (a placed or PAD slot charges
    nothing). Only fully-failed attempts are ever counted, so the K
    entries always sum to N per counted slot — matching the event
    engine's episode semantics at W=1/C=1."""
    so_far = jnp.ones_like(masks[0])
    outs = []
    for m in masks:
        outs.append(jnp.sum(so_far & ~m).astype(jnp.int32))
        so_far = so_far & m
    return jnp.where(failed, jnp.stack(outs), 0)


# Packed-select bounds: scores are packed as total·2^14 + (2^14−1−n), which
# is exact in f32 iff every packed value is an integer < 2^24.
PACK_SHIFT = 16384.0  # 2^14
PACK_MAX_TOTAL = 1023  # (1023·2^14 + 16383) < 2^24
PACK_MAX_NODES = 16384


def select_node_packed(scores: jax.Array, feasible: jax.Array):
    """select_node via a single native max reduce: pack (total, node) into
    one f32 so argmax-with-min-index-ties becomes max over
    ``total·2^14 + (2^14−1−n)``, decoded from the scalar afterwards.

    EXACT only under the caller-checked static gate: integer non-negative
    plugin weights with Σw·100 ≤ PACK_MAX_TOTAL (every normalized plugin
    score is an integer in [0, 100], so total is an integer), and
    N ≤ PACK_MAX_NODES — then every packed value is an integer < 2^24,
    exactly representable in f32, and max/decode are bit-exact. A native
    single-output max reduce is ~2× the throughput of the variadic
    (value, index) comparator reduce on TPU."""
    N = scores.shape[-1]
    iota_f = jnp.arange(N, dtype=jnp.float32)
    packed = jnp.where(
        feasible,
        scores * np.float32(PACK_SHIFT)
        + (np.float32(PACK_SHIFT - 1.0) - iota_f),
        NEG_INF,
    )
    mx = jnp.max(packed, axis=-1)
    placed = mx > NEG_INF
    safe = jnp.where(placed, mx, 0.0)
    t = jnp.floor(safe / np.float32(PACK_SHIFT))  # power-of-2 divide: exact
    idx = np.float32(PACK_SHIFT - 1.0) - (safe - t * np.float32(PACK_SHIFT))
    return jnp.where(placed, idx.astype(jnp.int32), PAD), placed


def _bind_deltas(d: Derived, node: jax.Array):
    """Shared pieces of a masked bind: the node one-hot, the [G, N]
    domain-equality mask (node n is in the same domain as `node` under
    group g's topology key), and the [G] has-domain flags for the bound
    node."""
    N = d.gdom_f.shape[1]
    oh_n = ((jnp.arange(N) == node) & (node >= 0)).astype(jnp.float32)  # [N]
    # Domain id of the bound node per group (one selected element — exact).
    gdom_at = jnp.einsum("gn,n->g", d.gdom_f, oh_n, precision=_HI)  # [G]
    node_has_dom = (
        jnp.einsum("gn,n->g", (d.gdom_f >= 0).astype(jnp.float32), oh_n, precision=_HI) > 0.5
    )
    dom_sel = (
        (d.gdom_f == gdom_at[:, None]) & node_has_dom[:, None] & (d.gdom_f >= 0)
    ).astype(jnp.float32)  # [G, N]
    return oh_n, dom_sel, node_has_dom.astype(jnp.float32)


def _pod_group_vectors(s: PodSlot, G: int):
    """([..., G] anti-term one-hot sum, [..., G] pref weight sum); term axes
    may carry a leading wave axis."""
    ohB = _term_onehot(s.anti_req, G)
    anti_g = jnp.sum(ohB, axis=-2)
    ohP = _term_onehot(s.pref_aff, G)
    w = jnp.where(s.pref_aff >= 0, s.pref_aff_w, 0.0)
    pref_g = jnp.einsum("...a,...ag->...g", w, ohP, precision=_HI)
    return anti_g, pref_g


def apply_binding(
    d: Derived, st: DevState, s: PodSlot, node: jax.Array, on: jax.Array
) -> DevState:
    """Masked bind. ``on`` is a bool scalar; when False the update is a
    no-op — keeps the scan branch-free. All updates are elementwise (no
    scatters). Gang rollback goes through :func:`apply_unbind_wave`."""
    G = st.match_count.shape[0]
    w = jnp.where(on & s.valid, 1.0, 0.0).astype(jnp.float32)
    oh_n, dom_sel, has_dom = _bind_deltas(d, node)
    used = st.used + (w * oh_n)[:, None] * s.req[None, :]
    pmg_f = s.pmg.astype(jnp.float32)
    match_count = st.match_count + (w * pmg_f)[:, None] * dom_sel
    # Total counts only domain-carrying binds — it must stay exactly
    # sum-over-domains of match_count (ops.cpu's bootstrap total).
    match_total = st.match_total + w * pmg_f * has_dom
    anti_g, pref_g = _pod_group_vectors(s, G)
    anti = st.anti_active + (w * anti_g)[:, None] * dom_sel
    pref = st.pref_wsum + (w * pref_g)[:, None] * dom_sel
    return DevState(
        used=used, match_count=match_count, anti_active=anti, pref_wsum=pref,
        match_total=match_total,
    )


# ---------------------------------------------------------------------------
# Fused wave evaluation (the hot path)
#
# The naive per-pod chain (eval_pod in sim.jax_runtime) issues ~30
# non-fusable ops per pod (einsums + reductions); at ~1-3 µs fixed cost per
# op inside a TPU scan, the replay is dispatch-latency-bound, not
# FLOP-bound.  Two fixes, both exact (bit-identical results):
#
# 1. Everything state-INDEPENDENT (taint matrices, node-affinity expression
#    matching, term one-hots, bind vectors) is precomputed for the whole
#    wave in one batched shot (WavePre) — W pods' worth of the biggest
#    tensors leave the sequential chain.
# 2. The per-pod state reads collapse into ONE stacked one-hot matmul
#    against match_count (+3 small matvecs), and the per-plugin score
#    normalizations collapse into one stacked masked min+max pair.
# ---------------------------------------------------------------------------


class WavePre(NamedTuple):
    """Per-wave precomputed tensors (leading axis W). Static widths:
    A = #required-affinity terms, B = #required-anti terms, SP = #spread
    constraints; lhs row layout is [A aff | B anti | SP spread | 1 pref]."""

    lhs: jax.Array  # [W, K, G] f32 stacked one-hot rows (K = A+B+SP+1 or 0)
    gvalid: jax.Array  # [W, KT, N] bool (KT = A+B+SP) domain-valid per term row
    taint_ok: jax.Array  # [W, N] bool
    taint_raw: jax.Array  # [W, N] f32 (PreferNoSchedule counts)
    na_ok: jax.Array  # [W, N] bool
    na_raw: jax.Array  # [W, N] f32
    aff_valid: jax.Array  # [W, A] bool
    aff_selfm: jax.Array  # [W, A] bool (pod matches its own aff term)
    anti_valid: jax.Array  # [W, B] bool
    sp_valid: jax.Array  # [W, SP] bool
    sp_dns: jax.Array  # [W, SP] bool (valid & DoNotSchedule)
    sp_scored: jax.Array  # [W, SP] bool (valid & ScheduleAnyway — scoring rows)
    sp_selfm: jax.Array  # [W, SP] f32
    sp_skew: jax.Array  # [W, SP] f32
    sp_w: jax.Array  # [W, SP] f32 (upstream log(size+2) topology weights)
    pmg_f: jax.Array  # [W, G] f32


def _padded_w_table(sp_w_g, G: int) -> np.ndarray:
    """Static [G] spread-weight table from spec.sp_w_g, padded/clipped to
    the one-hot group axis width."""
    tab = np.zeros(G, np.float32)
    arr = np.asarray(sp_w_g, np.float32)
    n = min(G, arr.shape[0])
    tab[:n] = arr[:n]
    return tab


def wave_widths(s: "PodSlot", spec) -> tuple:
    """(A, B, SP) static term widths after spec gating."""
    A = s.aff_req.shape[-1] if spec.interpod else 0
    B = s.anti_req.shape[-1] if spec.interpod else 0
    SP = s.spread_g.shape[-1] if spec.spread else 0
    return A, B, SP


def build_wave_pre(dc: DevCluster, d: Derived, sb: PodSlot, spec) -> WavePre:
    """Batched (over the wave axis) precompute of every state-independent
    piece of eval. ``sb`` fields carry a leading W axis."""
    W = sb.pod_id.shape[0]
    G = d.gdom_f.shape[0]
    N = d.gdom_f.shape[1]
    A, B, SP = wave_widths(sb, spec)
    pmg_f = sb.pmg.astype(jnp.float32)  # [W, G]

    pieces = []
    if spec.interpod:
        ohA = _term_onehot(sb.aff_req, G)  # [W, A, G]
        ohB = _term_onehot(sb.anti_req, G)
        pieces += [ohA, ohB]
    else:
        ohA = jnp.zeros((W, 0, G), jnp.float32)
        ohB = ohA
    if spec.spread:
        ohS = _term_onehot(sb.spread_g, G)
        pieces.append(ohS)
    else:
        ohS = jnp.zeros((W, 0, G), jnp.float32)
    if spec.interpod:
        ohP = _term_onehot(sb.pref_aff, G)  # [W, PA, G]
        wp = jnp.where(sb.pref_aff >= 0, sb.pref_aff_w, 0.0)
        pref_row = jnp.einsum("wp,wpg->wg", wp, ohP, precision=_HI)[:, None, :]
        pieces.append(pref_row)
    lhs = (
        jnp.concatenate(pieces, axis=1)
        if pieces
        else jnp.zeros((W, 0, G), jnp.float32)
    )
    terms = lhs[:, : A + B + SP]
    gvalid = (
        jnp.einsum(
            "wkg,gn->wkn", terms, (d.gdom_f >= 0).astype(jnp.float32), precision=_HI
        )
        > 0.5
        if A + B + SP
        else jnp.zeros((W, 0, N), bool)
    )

    if spec.taints:
        taint_ok = jax.vmap(lambda s: taint_mask(dc, s))(sb)
        taint_raw = jax.vmap(lambda s: taint_prefer_count(dc, s))(sb)
    else:
        taint_ok = jnp.ones((W, N), bool)
        taint_raw = jnp.zeros((W, N), jnp.float32)
    if spec.node_affinity:
        na_ok = jax.vmap(lambda s: node_affinity_mask(d, s))(sb)
        na_raw = jax.vmap(lambda s: node_affinity_score(d, s))(sb)
    else:
        na_ok = jnp.ones((W, N), bool)
        na_raw = jnp.zeros((W, N), jnp.float32)

    return WavePre(
        lhs=lhs,
        gvalid=gvalid,
        taint_ok=taint_ok,
        taint_raw=taint_raw,
        na_ok=na_ok,
        na_raw=na_raw,
        aff_valid=sb.aff_req[:, :A] >= 0,
        aff_selfm=jnp.einsum("wag,wg->wa", ohA, pmg_f, precision=_HI) > 0.5,
        anti_valid=sb.anti_req[:, :B] >= 0,
        sp_valid=sb.spread_g[:, :SP] >= 0,
        sp_dns=(sb.spread_g[:, :SP] >= 0) & sb.spread_dns[:, :SP],
        sp_scored=(sb.spread_g[:, :SP] >= 0) & ~sb.spread_dns[:, :SP],
        sp_selfm=jnp.einsum("wag,wg->wa", ohS, pmg_f, precision=_HI),
        sp_skew=sb.spread_skew[:, :SP].astype(jnp.float32),
        sp_w=jnp.einsum(
            "wag,g->wa", ohS, _padded_w_table(spec.sp_w_g, G), precision=_HI
        )
        if SP
        else jnp.zeros((W, 0), jnp.float32),
        pmg_f=pmg_f,
    )


def eval_pod_fused(
    dc: DevCluster,
    d: Derived,
    st: DevState,
    s: PodSlot,
    p: WavePre,
    spec,
    widths: tuple,
    wvec=None,
    shard_ctx: "ShardCtx | None" = None,
):
    """Fused Filter+Score for one slot using wave-precomputed tensors.
    Bit-identical to the reference chain (sim.jax_runtime.eval_pod) — the
    parity suites pin this. Returns (feasible [N], scores [N], any_f).

    ``wvec`` (optional [len(POLICY_COLS)] traced f32) swaps the static
    config weights for per-scenario policy-vector columns (round 9 tuner);
    filtering is weight-independent and unchanged.

    ``shard_ctx`` (round 14): evaluate one NODE SHARD inside shard_map —
    every per-node op is local; the only cross-shard values are the
    score-normalization extrema (one packed ``pmax`` carrying the stacked
    hi/lo rows + the global any-feasible bit) and the spread filter's
    per-constraint domain minimum (one ``pmin``), both exact in f32
    (max-of-per-shard-maxes IS the global max). Traces whose score rows
    are all absolute (fit-only — the Borg shape) compile with NO
    collective here at all. With ``shard_ctx=None`` the program is
    token-identical to before. NOTE: in sharded mode the returned
    ``any_f`` is only global when a normalization row forced the packed
    pmax; callers must take placement from select_node_sharded (whose
    reduce spans shards), never from ``any_f``."""
    N = dc.allocatable.shape[0]
    A, B, SP = widths
    K = p.lhs.shape[0]

    used1 = st.used + s.req[None, :]  # shared by fit mask + fit score
    feasible = jnp.ones(N, dtype=bool)
    if shard_ctx is not None and shard_ctx.nshards * shard_ctx.n_local > shard_ctx.n_real:
        # Pad rows (node axis rounded up to a multiple of nshards) are
        # never feasible — their capacity/label/taint fill is neutral but
        # this mask is the guarantee.
        feasible = shard_gids(shard_ctx) < np.int32(shard_ctx.n_real)
    if spec.fit:
        feasible = jnp.all(used1 <= dc.allocatable + 1e-6, axis=1)
    if spec.taints:
        feasible = feasible & p.taint_ok
    if spec.node_affinity:
        feasible = feasible & p.na_ok

    reads = (
        jnp.einsum("kg,gn->kn", p.lhs, st.match_count, precision=_HI)
        if K
        else jnp.zeros((0, N), jnp.float32)
    )
    if spec.interpod:
        if A:
            totals = jnp.einsum("ag,g->a", p.lhs[:A], st.match_total, precision=_HI)
            boot = (totals == 0) & p.aff_selfm  # bootstrap self-match
            term_ok = (reads[:A] >= 1) & p.gvalid[:A]
            feasible = feasible & jnp.all(
                jnp.where(p.aff_valid[:, None], term_ok | boot[:, None], True), axis=0
            )
        if B:
            viol = (reads[A : A + B] >= 1) & p.gvalid[A : A + B]
            feasible = feasible & jnp.all(
                jnp.where(p.anti_valid[:, None], ~viol, True), axis=0
            )
        blocked = (
            jnp.einsum("g,gn->n", p.pmg_f, st.anti_active, precision=_HI) > 0.5
        )  # symmetric: anti_active entries are non-negative counts
        feasible = feasible & ~blocked
    if spec.spread and SP:
        cnts = reads[A + B : A + B + SP]  # [SP, N]
        gval = p.gvalid[A + B : A + B + SP]
        minv = jnp.min(jnp.where(gval, cnts, jnp.inf), axis=1)
        if shard_ctx is not None:
            # Per-constraint min over the GLOBAL domain set (pad nodes
            # carry gdom = -1 → gval False, auto-excluded).
            minv = jax.lax.pmin(minv, shard_ctx.axis)
        has = jnp.isfinite(minv)
        c_ok = (
            gval
            & has[:, None]
            & (cnts + p.sp_selfm[:, None] - jnp.where(has, minv, 0.0)[:, None]
               <= p.sp_skew[:, None])
        )
        feasible = feasible & jnp.all(
            jnp.where(p.sp_dns[:, None], c_ok, True), axis=0
        )

    any_f = jnp.any(feasible)

    # ---- scores: stack raw rows, one masked min+max, per-row normalize ----
    _w, _on = policy_weight_fns(spec, wvec)
    total = jnp.zeros(N, dtype=jnp.float32)
    if spec.fit and _on("NodeResourcesFit"):
        rw = np.asarray(spec.resource_weights, dtype=np.float32)
        if spec.fit_strategy not in ("LeastAllocated", "MostAllocated"):
            raw = requested_to_capacity_ratio_score(
                dc, st, s, rw, spec.shape_x, spec.shape_y
            )
        elif wvec is None:
            raw = (
                least_allocated_score(dc, st, s, rw)
                if spec.fit_strategy == "LeastAllocated"
                else most_allocated_score(dc, st, s, rw)
            )
        else:
            raw = jnp.where(
                wvec[IDX_FIT_LEAST] > 0.5,
                least_allocated_score(dc, st, s, rw),
                most_allocated_score(dc, st, s, rw),
            )
        total = total + _w("NodeResourcesFit") * raw

    # (raw, weight, minmax?, reverse?) rows, in the reference accumulation
    # order: taint, node-affinity, interpod, spread.
    rows = []
    if spec.taints and spec.taint_score and _on("TaintToleration"):
        rows.append((p.taint_raw, _w("TaintToleration"), False, True))
    if spec.node_affinity and _on("NodeAffinity"):
        rows.append((p.na_raw, _w("NodeAffinity"), False, False))
    if spec.interpod and _on("InterPodAffinity"):
        raw = reads[A + B + SP]
        if spec.has_symmetric_pref:
            raw = raw + jnp.einsum("g,gn->n", p.pmg_f, st.pref_wsum, precision=_HI)
        rows.append((raw, _w("InterPodAffinity"), True, False))
    sp_pack = None
    if spec.spread and _on("PodTopologySpread") and SP:
        # Upstream scoring: raw + ignored mask computed here; the extrema
        # (over feasible & ~ignored) ride the shared stacked reduce below
        # as an extra row with the ignored nodes pre-masked to ±inf.
        cnts = reads[A + B : A + B + SP]
        gval = p.gvalid[A + B : A + B + SP]
        raw_sp = jnp.zeros(N, jnp.float32)
        ignored = jnp.zeros(N, bool)
        for i in range(SP):
            contrib = cnts[i] * p.sp_w[i] + (p.sp_skew[i] - 1.0)
            raw_sp = raw_sp + jnp.where(p.sp_scored[i], contrib, 0.0)
            ignored = ignored | (p.sp_scored[i] & ~gval[i])
        sp_pack = (jnp.floor(raw_sp + 0.5), ignored)
    if rows or sp_pack is not None:
        hi_rows = [r[0] for r in rows]
        lo_rows = list(hi_rows)
        if sp_pack is not None:
            raw_sp, ignored = sp_pack
            hi_rows.append(jnp.where(ignored, -jnp.inf, raw_sp))
            lo_rows.append(jnp.where(ignored, jnp.inf, raw_sp))
        hi_stack = jnp.where(feasible[None, :], jnp.stack(hi_rows), -jnp.inf)
        lo_stack = jnp.where(feasible[None, :], jnp.stack(lo_rows), jnp.inf)
        hi = jnp.max(hi_stack, axis=1)
        lo = jnp.min(lo_stack, axis=1)
        if shard_ctx is not None:
            # ONE packed pmax carries every row's hi, −lo, and the global
            # any-feasible bit. Exact: f32 max of per-shard maxes is the
            # global max (same value set), and −(+inf) = −inf is a clean
            # identity for the empty-shard rows.
            nrm = hi.shape[0]
            packed = jnp.concatenate(
                [hi, -lo, jnp.where(any_f, 1.0, 0.0)[None].astype(jnp.float32)]
            )
            packed = jax.lax.pmax(packed, shard_ctx.axis)
            hi = packed[:nrm]
            lo = -packed[nrm : 2 * nrm]
            any_f = packed[-1] > 0.5
        for i, (raw, wt, minmax, reverse) in enumerate(rows):
            out = _normalize_row(raw, lo[i], hi[i], any_f, minmax, reverse)
            total = total + wt * out
        if sp_pack is not None:
            raw_sp, ignored = sp_pack
            out = spread_norm_from_extrema(
                raw_sp, ignored, hi[-1], lo[-1], jnp.any(p.sp_scored),
                getattr(spec, "sp_norm_f32", False),
            )
            total = total + _w("PodTopologySpread") * out
    return feasible, total, any_f


def apply_unbind_wave(
    d: Derived, st: DevState, sb: PodSlot, choice: jax.Array, revert: jax.Array
) -> DevState:
    """Batched gang rollback: subtract every reverted slot's bind in ONE
    set of elementwise updates (sb fields have leading wave axis W)."""
    G = st.match_count.shape[0]
    N = d.gdom_f.shape[1]
    w = jnp.where(revert & sb.valid, 1.0, 0.0).astype(jnp.float32)  # [W]
    oh = ((jnp.arange(N)[None, :] == choice[:, None]) & (choice[:, None] >= 0)).astype(
        jnp.float32
    )  # [W, N]
    used = st.used - jnp.einsum("w,wn,wr->nr", w, oh, sb.req, precision=_HI)
    gdom_at = jnp.einsum("gn,wn->wg", d.gdom_f, oh, precision=_HI)  # [W, G]
    has_dom = jnp.einsum("gn,wn->wg", (d.gdom_f >= 0).astype(jnp.float32), oh, precision=_HI) > 0.5
    dom_sel = (
        (d.gdom_f[None] == gdom_at[:, :, None]) & has_dom[:, :, None] & (d.gdom_f >= 0)[None]
    ).astype(jnp.float32)  # [W, G, N]
    pmg_f = sb.pmg.astype(jnp.float32)  # [W, G]
    match_count = st.match_count - jnp.einsum("w,wg,wgn->gn", w, pmg_f, dom_sel, precision=_HI)
    match_total = st.match_total - jnp.einsum(
        "w,wg->g", w, pmg_f * has_dom.astype(jnp.float32), precision=_HI
    )
    anti_wg, pref_wg = _pod_group_vectors(sb, G)  # [W, G] each
    anti = st.anti_active - jnp.einsum("w,wg,wgn->gn", w, anti_wg, dom_sel, precision=_HI)
    pref = st.pref_wsum - jnp.einsum("w,wg,wgn->gn", w, pref_wg, dom_sel, precision=_HI)
    return DevState(
        used=used, match_count=match_count, anti_active=anti, pref_wsum=pref,
        match_total=match_total,
    )


# ---------------------------------------------------------------------------
# Node-sharded selection + state update (round 14 big-scenario mode)
#
# Each device carries one contiguous node block; the wave step stays the
# same math with three changes, all exact:
# 1. eval_pod_fused(shard_ctx=...) localizes every per-node op and routes
#    the normalization extrema through one packed pmax (f32 max-of-maxes
#    is the global max — scores stay bit-identical).
# 2. selection is two-stage: the per-shard variadic reduce, then ONE tiny
#    all_gather of (score, global node id, bind-domain row) with a static
#    fold — lowest-global-id tie-break at equal score equals the
#    replicated argmax because shards are contiguous blocks.
# 3. the winning bind broadcasts back as a masked per-shard plane update:
#    only the owner shard's one-hot is nonzero, while the [G] domain row
#    (gdom_at/has_dom, exchanged with the winner) applies the count-plane
#    update to every shard's slice of the winner's domain.
# ---------------------------------------------------------------------------


def two_phase_exchange() -> bool:
    """Round-19 A/B gate for the slim two-phase selection exchange.
    Read at TRACE time (engine build), not import time, so tests and the
    ``overlap:`` config section can flip it per engine: set
    ``KSIM_TWO_PHASE_EXCHANGE=0`` before building an engine to compile
    the legacy single-gather program."""
    return os.environ.get("KSIM_TWO_PHASE_EXCHANGE", "1") not in ("", "0")


def exchange_payload_bytes(nshards: int, groups: int, two_phase: bool) -> int:
    """Bytes RECEIVED per shard per selection slot by the exchange —
    the latency-proportional payload scaling_probe/bench pin.

    Legacy single-phase: one all_gather of a ``[2 + 2G]`` f32 row from
    every shard. Two-phase: an all_gather of the ``[2]`` f32
    (score, gid) pair plus an all-reduce of the owner-masked ``[2G]``
    f32 domain row, charged at the standard ring all-reduce cost of
    2·(n−1)/n of the row per shard — so the two-phase payload equals
    legacy at n = 2 (the reduce degenerates to a peer swap) and is
    strictly smaller at every n ≥ 3."""
    g2 = 2 * int(groups)
    n = max(int(nshards), 1)
    if n <= 1:
        return 0  # no collective compiles on a single shard
    if not two_phase:
        return 4 * (n - 1) * (2 + g2)
    return 4 * ((n - 1) * 2 + (2 * (n - 1) * g2) // n)


def select_node_sharded(
    scores: jax.Array, feasible: jax.Array, gdom_f: jax.Array, ctx: ShardCtx
):
    """Two-stage select over node shards → (choice GLOBAL i32, placed,
    gdom_at [G] f32, has_dom [G] f32). Bit-identical to
    :func:`select_node` on the unsharded planes: global node ids < 2²⁴
    are exact in f32 and the (max score, min id) fold reproduces numpy's
    first-occurrence argmax.

    Two exchange programs compile behind :func:`two_phase_exchange`:

    * legacy (round 14): ONE all_gather of a ``[2 + 2G]`` f32 row
      (score, gid, domain row) per shard, folded statically.
    * two-phase (round 19): phase 1 all_gathers only the ``[2]`` f32
      (score, gid) pair and folds the winner — replicated on every
      shard; phase 2 moves the winner's ``[2G]`` domain row with a
      single owner-selected exchange, a psum of the row masked to the
      owner shard (``winner_gid // n_local`` — shards are contiguous
      blocks, and the owner's LOCAL argmax IS the global winner, so its
      candidate row is exactly the winner's row). The mask makes every
      non-owner contribution ±0.0, so the f32 sum returns the owner's
      row exactly; when nothing is feasible anywhere the psum of
      all-masked rows is the same zero row the legacy fold returns, and
      downstream ``has_dom > 0.5`` gates keep it inert. Payload per
      shard drops from ``nshards·(2+2G)`` to ``nshards·2 + ~2·2G`` f32
      per slot — the latency term the ROADMAP flags at 40+ shards.
    """
    masked = jnp.where(feasible, scores, NEG_INF)
    iota = jax.lax.broadcasted_iota(jnp.int32, masked.shape, masked.ndim - 1)

    def comb(a, b):
        av, ai = a
        bv, bi = b
        better = (bv > av) | ((bv == av) & (bi < ai))
        return jnp.where(better, bv, av), jnp.where(better, bi, ai)

    mx, loc = jax.lax.reduce(
        (masked, iota),
        (np.float32(-np.inf), np.int32(np.iinfo(np.int32).max)),
        comb,
        dimensions=(masked.ndim - 1,),
    )
    ok = mx > NEG_INF
    off = jax.lax.axis_index(ctx.axis).astype(jnp.int32) * np.int32(ctx.n_local)
    gid = off + jnp.where(ok, loc, 0)  # guard the int32-max empty sentinel
    # Empty shards advertise a giant-but-finite id so the fold's
    # min-id tie-break stays well-ordered (their −inf score loses anyway).
    gid_f = jnp.where(ok, gid.astype(jnp.float32), np.float32(2.0**31))
    oh = ((jnp.arange(ctx.n_local) == loc) & ok).astype(jnp.float32)
    gdom_cand = jnp.einsum("gn,n->g", gdom_f, oh, precision=_HI)
    hasdom_cand = jnp.einsum(
        "gn,n->g", (gdom_f >= 0).astype(jnp.float32), oh, precision=_HI
    )
    G = gdom_f.shape[0]

    def fold(rows):
        best = rows[0]
        for k in range(1, ctx.nshards):
            cand = rows[k]
            better = (cand[0] > best[0]) | (
                (cand[0] == best[0]) & (cand[1] < best[1])
            )
            best = jnp.where(better, cand, best)
        return best

    if not two_phase_exchange():
        row = jnp.concatenate([mx[None], gid_f[None], gdom_cand, hasdom_cand])
        best = fold(jax.lax.all_gather(row, ctx.axis))  # [nshards, 2 + 2G]
        placed = best[0] > NEG_INF
        choice = jnp.where(placed, best[1], 0.0).astype(jnp.int32)
        choice = jnp.where(placed, choice, PAD)
        return choice, placed, best[2 : 2 + G], best[2 + G : 2 + 2 * G]

    # Phase 1: winner election on the [2] f32 (score, gid) pair only.
    best = fold(jax.lax.all_gather(jnp.stack([mx, gid_f]), ctx.axis))
    placed = best[0] > NEG_INF
    choice = jnp.where(placed, best[1], 0.0).astype(jnp.int32)
    # Phase 2: owner-selected domain-row exchange. The owner's local
    # candidate row is the winner's row; everyone else contributes ±0.0.
    owner = choice // np.int32(ctx.n_local)
    mine = (
        (jax.lax.axis_index(ctx.axis).astype(jnp.int32) == owner) & placed
    ).astype(jnp.float32)
    dom = jax.lax.psum(
        jnp.concatenate([gdom_cand, hasdom_cand]) * mine, ctx.axis
    )
    choice = jnp.where(placed, choice, PAD)
    return choice, placed, dom[:G], dom[G:]


def apply_binding_sharded(
    d: Derived, st: DevState, s: PodSlot, node: jax.Array, on: jax.Array,
    gdom_at: jax.Array, has_dom: jax.Array, ctx: ShardCtx,
) -> DevState:
    """apply_binding on one node shard. ``node`` is the GLOBAL winner id
    (replicated from select_node_sharded) — only the owner shard's
    one-hot fires for the [N, R] resource row, while ``gdom_at``/
    ``has_dom`` (the winner's [G] domain row) drive each shard's slice of
    the domain-equality count-plane update. ``match_total`` is replicated
    state: every shard applies the identical scalar-per-group add."""
    G = st.match_count.shape[0]
    w = jnp.where(on & s.valid, 1.0, 0.0).astype(jnp.float32)
    oh_n = ((shard_gids(ctx) == node) & (node >= 0)).astype(jnp.float32)
    dom_sel = (
        (d.gdom_f == gdom_at[:, None]) & (has_dom[:, None] > 0.5) & (d.gdom_f >= 0)
    ).astype(jnp.float32)
    used = st.used + (w * oh_n)[:, None] * s.req[None, :]
    pmg_f = s.pmg.astype(jnp.float32)
    match_count = st.match_count + (w * pmg_f)[:, None] * dom_sel
    match_total = st.match_total + w * pmg_f * has_dom
    anti_g, pref_g = _pod_group_vectors(s, G)
    anti = st.anti_active + (w * anti_g)[:, None] * dom_sel
    pref = st.pref_wsum + (w * pref_g)[:, None] * dom_sel
    return DevState(
        used=used, match_count=match_count, anti_active=anti, pref_wsum=pref,
        match_total=match_total,
    )


def apply_unbind_wave_sharded(
    d: Derived, st: DevState, sb: PodSlot, choice: jax.Array,
    revert: jax.Array, gdom_at_w: jax.Array, has_dom_w: jax.Array,
    ctx: ShardCtx,
) -> DevState:
    """apply_unbind_wave on one node shard: ``choice`` carries GLOBAL ids
    and ``gdom_at_w``/``has_dom_w`` ([W, G], stacked from the wave's
    selections) replace the local one-hot domain recovery — the bound
    node's domain row lives on its owner shard, so it must ride in from
    selection rather than be recomputed locally."""
    G = st.match_count.shape[0]
    w = jnp.where(revert & sb.valid, 1.0, 0.0).astype(jnp.float32)  # [W]
    gids = shard_gids(ctx)
    oh = ((gids[None, :] == choice[:, None]) & (choice[:, None] >= 0)).astype(
        jnp.float32
    )  # [W, n_local]
    used = st.used - jnp.einsum("w,wn,wr->nr", w, oh, sb.req, precision=_HI)
    dom_sel = (
        (d.gdom_f[None] == gdom_at_w[:, :, None])
        & (has_dom_w[:, :, None] > 0.5)
        & (d.gdom_f >= 0)[None]
    ).astype(jnp.float32)  # [W, G, n_local]
    pmg_f = sb.pmg.astype(jnp.float32)  # [W, G]
    match_count = st.match_count - jnp.einsum(
        "w,wg,wgn->gn", w, pmg_f, dom_sel, precision=_HI
    )
    match_total = st.match_total - jnp.einsum(
        "w,wg->g", w, pmg_f * has_dom_w, precision=_HI
    )
    anti_wg, pref_wg = _pod_group_vectors(sb, G)
    anti = st.anti_active - jnp.einsum("w,wg,wgn->gn", w, anti_wg, dom_sel, precision=_HI)
    pref = st.pref_wsum - jnp.einsum("w,wg,wgn->gn", w, pref_wg, dom_sel, precision=_HI)
    return DevState(
        used=used, match_count=match_count, anti_active=anti, pref_wsum=pref,
        match_total=match_total,
    )
