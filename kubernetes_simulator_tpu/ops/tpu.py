"""Scheduling kernels — JAX device edition (SURVEY.md §3.5).

Same math as :mod:`.cpu`, re-expressed for XLA: everything is static-shape
jnp over ``[N]``/``[G, D]`` tensors, composable under ``jit``/``vmap``/
``lax.scan``. One pending pod (a "slot" row pytree) is evaluated against
all nodes at once; the mutable scheduling state is a small pytree updated
by scatter-adds so the whole replay runs as one compiled scan on device.

Design notes (TPU-first):
- masks stay bool, scores f32; the [N]-wide ops map onto VPU lanes and the
  [N, R] contractions onto the MXU-friendly layouts XLA picks.
- no data-dependent shapes: padded slots are neutralized with `where`, a
  `valid` flag multiplies every state update.
- per-pod term loops (tolerations, affinity terms, spread constraints) are
  python-unrolled over SMALL static widths — they trace once and fuse.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.encode import PAD, TOL_PAD, TOL_WILDCARD, EncodedCluster, EncodedPods
from ..models.core import Effect, Operator

MAX_NODE_SCORE = 100.0
NEG_INF = -jnp.inf


class DevCluster(NamedTuple):
    """Static per-scenario node-side tensors (device copies of
    EncodedCluster). Leading axes may gain a scenario dimension under vmap."""

    allocatable: jax.Array  # [N, R] f32
    node_label_key: jax.Array  # [N, L] i32
    node_label_kv: jax.Array  # [N, L] i32
    node_label_num: jax.Array  # [N, L] f32
    taint_key: jax.Array  # [N, TT] i32
    taint_kv: jax.Array  # [N, TT] i32
    taint_effect: jax.Array  # [N, TT] i32
    node_domain: jax.Array  # [T, N] i32
    num_domains: jax.Array  # [T] i32
    expr_key: jax.Array  # [E] i32
    expr_op: jax.Array  # [E] i32
    expr_vals: jax.Array  # [E, V] i32
    expr_num: jax.Array  # [E] f32
    group_topo: jax.Array  # [G] i32

    @classmethod
    def from_encoded(cls, ec: EncodedCluster) -> "DevCluster":
        return cls(
            allocatable=jnp.asarray(ec.allocatable),
            node_label_key=jnp.asarray(ec.node_label_key),
            node_label_kv=jnp.asarray(ec.node_label_kv),
            node_label_num=jnp.asarray(ec.node_label_num),
            taint_key=jnp.asarray(ec.taint_key),
            taint_kv=jnp.asarray(ec.taint_kv),
            taint_effect=jnp.asarray(ec.taint_effect),
            node_domain=jnp.asarray(ec.node_domain),
            num_domains=jnp.asarray(ec.num_domains),
            expr_key=jnp.asarray(ec.expr_key),
            expr_op=jnp.asarray(ec.expr_op),
            expr_vals=jnp.asarray(ec.expr_vals),
            expr_num=jnp.asarray(ec.expr_num),
            group_topo=jnp.asarray(ec.group_topo),
        )


def num_bit_words(num_groups: int) -> int:
    return max((max(num_groups, 1) + 31) // 32, 1)


def pack_group_bits(mat: np.ndarray) -> np.ndarray:
    """[..., G] bool → [..., W32] uint32 little-endian bit words."""
    G = mat.shape[-1]
    W = num_bit_words(G)
    out = np.zeros(mat.shape[:-1] + (W,), dtype=np.uint32)
    for g in range(G):
        out[..., g // 32] |= mat[..., g].astype(np.uint32) << np.uint32(g % 32)
    return out


def anti_bits_from_counts(anti_active: np.ndarray, gdom: np.ndarray) -> np.ndarray:
    """Host build of the [N, W32] symmetric-anti bit tensor: bit g of node n
    is set iff a placed pod with required anti-affinity term g sits in n's
    domain under g's topology key."""
    G, N = gdom.shape
    at_nodes = np.where(
        gdom >= 0, np.take_along_axis(anti_active, np.clip(gdom, 0, None), axis=1), 0.0
    )  # [G, N]
    return pack_group_bits((at_nodes > 0).T)  # [N, W32]


class DevState(NamedTuple):
    """Mutable scheduling state carried through lax.scan (device twin of
    models.state.SchedState). ``anti_bits`` is a packed accelerator for the
    symmetric anti-affinity check: bit g of node n ⇔
    anti_active[g, dom(g, n)] > 0 — it turns a per-slot [G, N] sweep into a
    [N, G/32] AND."""

    used: jax.Array  # [N, R] f32
    match_count: jax.Array  # [G, D] f32
    anti_active: jax.Array  # [G, D] f32
    pref_wsum: jax.Array  # [G, D] f32
    anti_bits: jax.Array  # [N, W32] uint32

    @classmethod
    def init(cls, ec: EncodedCluster) -> "DevState":
        G = max(ec.num_groups, 1)
        D = max(ec.max_domains, 1)
        return cls(
            used=jnp.zeros((ec.num_nodes, ec.num_resources), jnp.float32),
            match_count=jnp.zeros((G, D), jnp.float32),
            anti_active=jnp.zeros((G, D), jnp.float32),
            pref_wsum=jnp.zeros((G, D), jnp.float32),
            anti_bits=jnp.zeros((ec.num_nodes, num_bit_words(G)), jnp.uint32),
        )


class PodSlot(NamedTuple):
    """One pending pod's row pytree (scan element)."""

    pod_id: jax.Array  # i32 scalar (PAD = padding slot)
    valid: jax.Array  # bool scalar
    req: jax.Array  # [R] f32
    tol_key: jax.Array  # [TO] i32
    tol_kv: jax.Array  # [TO] i32
    tol_effect: jax.Array  # [TO] i32
    na_req: jax.Array  # [TR, TE] i32
    na_has_req: jax.Array  # bool
    na_pref: jax.Array  # [TP, TE] i32
    na_pref_w: jax.Array  # [TP] f32
    aff_req: jax.Array  # [AR] i32
    anti_req: jax.Array  # [AA] i32
    pref_aff: jax.Array  # [PA] i32
    pref_aff_w: jax.Array  # [PA] f32
    spread_g: jax.Array  # [SP] i32
    spread_skew: jax.Array  # [SP] i32
    spread_dns: jax.Array  # [SP] bool
    pmg: jax.Array  # [G] bool
    pmg_bits: jax.Array  # [W32] uint32 (packed pmg)
    group: jax.Array  # i32 scalar (wave-local gang handling)


def gather_slots(ep: EncodedPods, idx: np.ndarray) -> PodSlot:
    """Host-side gather of pod rows at ``idx`` (any leading shape); PAD ids
    become invalid slots."""
    safe = np.clip(idx, 0, None)
    take = lambda a: jnp.asarray(a[safe])
    return PodSlot(
        pod_id=jnp.asarray(idx.astype(np.int32)),
        valid=jnp.asarray(idx >= 0),
        req=take(ep.requests),
        tol_key=take(ep.tol_key),
        tol_kv=take(ep.tol_kv),
        tol_effect=take(ep.tol_effect),
        na_req=take(ep.na_req),
        na_has_req=take(ep.na_has_req),
        na_pref=take(ep.na_pref),
        na_pref_w=take(ep.na_pref_w),
        aff_req=take(ep.aff_req),
        anti_req=take(ep.anti_req),
        pref_aff=take(ep.pref_aff),
        pref_aff_w=take(ep.pref_aff_w),
        spread_g=take(ep.spread_g),
        spread_skew=take(ep.spread_skew),
        spread_dns=take(ep.spread_dns),
        pmg=take(ep.pod_matches_group),
        pmg_bits=jnp.asarray(pack_group_bits(ep.pod_matches_group[safe])),
        group=jnp.asarray(np.where(idx >= 0, ep.group_id[safe], PAD).astype(np.int32)),
    )


# ---------------------------------------------------------------------------
# Per-replay derived tensors (computed INSIDE jit so scenario perturbations
# to labels/taints/capacity flow through without host re-encode)
# ---------------------------------------------------------------------------

def expr_match_matrix(dc: DevCluster) -> jax.Array:
    """[N, E] bool — jnp twin of ops.cpu.expr_match_matrix."""
    nk = dc.node_label_key[:, :, None]  # [N, L, 1]
    nv = dc.node_label_kv[:, :, None]
    ek = dc.expr_key[None, None, :]
    key_present = jnp.any((nk == ek) & (nk != PAD), axis=1)  # [N, E]
    in_set = jnp.any(
        (nv[:, :, :, None] == dc.expr_vals[None, None, :, :]) & (nv[:, :, :, None] != PAD),
        axis=(1, 3),
    )
    num = dc.node_label_num[:, :, None]
    gt = jnp.any((nk == ek) & (num > dc.expr_num[None, None, :]), axis=1)
    lt = jnp.any((nk == ek) & (num < dc.expr_num[None, None, :]), axis=1)
    op = dc.expr_op[None, :]
    return (
        ((op == Operator.IN) & key_present & in_set)
        | ((op == Operator.NOT_IN) & ~(key_present & in_set))
        | ((op == Operator.EXISTS) & key_present)
        | ((op == Operator.DOES_NOT_EXIST) & ~key_present)
        | ((op == Operator.GT) & gt)
        | ((op == Operator.LT) & lt)
    )


def group_dom_per_node(dc: DevCluster) -> jax.Array:
    """[G, N] — domain of each node under each count-group's topology key."""
    gt = jnp.clip(dc.group_topo, 0, None)
    dom = dc.node_domain[gt]  # [G, N]
    return jnp.where(dc.group_topo[:, None] >= 0, dom, PAD)


def domain_valid_mask(dc: DevCluster, D: int) -> jax.Array:
    """[G, D] — which domain slots exist for each group's topology key."""
    gt = jnp.clip(dc.group_topo, 0, None)
    nd = dc.num_domains[gt]  # [G]
    return (jnp.arange(D)[None, :] < nd[:, None]) & (dc.group_topo[:, None] >= 0)


class Derived(NamedTuple):
    M: jax.Array  # [N, E] expr match
    gdom: jax.Array  # [G, N]
    dom_valid: jax.Array  # [G, D]

    @classmethod
    def build(cls, dc: DevCluster, D: int) -> "Derived":
        return cls(expr_match_matrix(dc), group_dom_per_node(dc), domain_valid_mask(dc, D))


# ---------------------------------------------------------------------------
# Filters
# ---------------------------------------------------------------------------

def fit_mask(dc: DevCluster, st: DevState, s: PodSlot) -> jax.Array:
    return jnp.all(st.used + s.req[None, :] <= dc.allocatable + 1e-6, axis=1)


def taint_untolerated(dc: DevCluster, s: PodSlot, effects) -> jax.Array:
    t_eff = dc.taint_effect  # [N, TT]
    active = (dc.taint_key != PAD)
    eff_match = jnp.zeros_like(active)
    for e in effects:
        eff_match = eff_match | (t_eff == e)
    active = active & eff_match
    tk = s.tol_key  # [TO]
    valid_tol = tk != TOL_PAD
    key_ok = (tk[None, None, :] == TOL_WILDCARD) | (tk[None, None, :] == dc.taint_key[:, :, None])
    val_ok = (s.tol_kv[None, None, :] == PAD) | (s.tol_kv[None, None, :] == dc.taint_kv[:, :, None])
    eff_ok = (s.tol_effect[None, None, :] == 0) | (s.tol_effect[None, None, :] == t_eff[:, :, None])
    tolerated = jnp.any(key_ok & val_ok & eff_ok & valid_tol[None, None, :], axis=2)
    return active & ~tolerated


def taint_mask(dc: DevCluster, s: PodSlot) -> jax.Array:
    bad = taint_untolerated(dc, s, (int(Effect.NO_SCHEDULE), int(Effect.NO_EXECUTE)))
    return ~jnp.any(bad, axis=1)


def taint_prefer_count(dc: DevCluster, s: PodSlot) -> jax.Array:
    bad = taint_untolerated(dc, s, (int(Effect.PREFER_NO_SCHEDULE),))
    return jnp.sum(bad, axis=1).astype(jnp.float32)


def _terms_match(M: jax.Array, terms: jax.Array) -> jax.Array:
    """[N] — OR over terms of AND over exprs (PAD exprs auto-true; a term is
    valid iff slot 0 is a real expr)."""
    valid_term = terms[:, 0] >= 0  # [T]
    safe = jnp.clip(terms, 0, None)
    per_expr = M[:, safe] | (terms[None, :, :] < 0)  # [N, T, E]
    per_term = jnp.all(per_expr, axis=2) & valid_term[None, :]
    return jnp.any(per_term, axis=1)


def node_affinity_mask(d: Derived, s: PodSlot) -> jax.Array:
    return jnp.where(s.na_has_req, _terms_match(d.M, s.na_req), True)


def node_affinity_score(d: Derived, s: PodSlot) -> jax.Array:
    terms = s.na_pref  # [TP, TE]
    valid_term = terms[:, 0] >= 0
    safe = jnp.clip(terms, 0, None)
    per_expr = d.M[:, safe] | (terms[None, :, :] < 0)
    per_term = jnp.all(per_expr, axis=2) & valid_term[None, :]
    return jnp.sum(per_term * s.na_pref_w[None, :], axis=1).astype(jnp.float32)


def _term_counts(counts: jax.Array, d: Derived, gs: jax.Array) -> jax.Array:
    """[N] — counts[gs, dom(gs, n)] for ONE term group (a [D] row gather
    then a [N] map through the node→domain table; no [G, N] sweep)."""
    row = jnp.take(counts, gs, axis=0)  # [D]
    gdom_g = jnp.take(d.gdom, gs, axis=0)  # [N]
    vals = jnp.take(row, jnp.clip(gdom_g, 0, None))
    return jnp.where(gdom_g >= 0, vals, 0.0)


def interpod_filter_mask(d: Derived, st: DevState, s: PodSlot) -> jax.Array:
    """Per-term [N] row ops; the symmetric existing-pods'-anti-affinity
    check is one packed-bit AND over [N, G/32] (see DevState.anti_bits)."""
    N = d.gdom.shape[1]
    ok = jnp.ones(N, dtype=bool)
    for a in range(s.aff_req.shape[0]):  # small static unroll
        g = s.aff_req[a]
        gs = jnp.clip(g, 0, None)
        cnt_n = _term_counts(st.match_count, d, gs)
        total = jnp.sum(jnp.take(st.match_count, gs, axis=0))
        boot = (total == 0) & s.pmg[gs]
        gdom_g = jnp.take(d.gdom, gs, axis=0)
        term_ok = (cnt_n >= 1) & (gdom_g >= 0)
        ok = ok & jnp.where(g >= 0, term_ok | boot, True)
    for a in range(s.anti_req.shape[0]):
        g = s.anti_req[a]
        gs = jnp.clip(g, 0, None)
        cnt_n = _term_counts(st.match_count, d, gs)
        gdom_g = jnp.take(d.gdom, gs, axis=0)
        viol = (cnt_n >= 1) & (gdom_g >= 0)
        ok = ok & jnp.where(g >= 0, ~viol, True)
    blocked = jnp.zeros(N, dtype=bool)
    for w in range(st.anti_bits.shape[1]):
        blocked = blocked | ((st.anti_bits[:, w] & s.pmg_bits[w]) != 0)
    return ok & ~blocked


def interpod_score(d: Derived, st: DevState, s: PodSlot, has_symmetric_pref: bool = True) -> jax.Array:
    N = d.gdom.shape[1]
    raw = jnp.zeros(N, dtype=jnp.float32)
    for a in range(s.pref_aff.shape[0]):
        g = s.pref_aff[a]
        gs = jnp.clip(g, 0, None)
        cnt_n = _term_counts(st.match_count, d, gs)
        raw = raw + jnp.where(g >= 0, s.pref_aff_w[a] * cnt_n, 0.0)
    if has_symmetric_pref:
        # Needs every group's weight sum — the one remaining [G, N] sweep;
        # statically skipped when the trace has no preferred terms.
        safe = jnp.clip(d.gdom, 0, None)
        wsum = jnp.where(d.gdom >= 0, jnp.take_along_axis(st.pref_wsum, safe, axis=1), 0.0)
        raw = raw + jnp.sum(wsum * s.pmg[:, None], axis=0)
    return raw


def spread_filter_mask(d: Derived, st: DevState, s: PodSlot) -> jax.Array:
    N = d.gdom.shape[1]
    ok = jnp.ones(N, dtype=bool)
    for a in range(s.spread_g.shape[0]):
        g = s.spread_g[a]
        gs = jnp.clip(g, 0, None)
        row = jnp.take(st.match_count, gs, axis=0)  # [D]
        valid_row = jnp.take(d.dom_valid, gs, axis=0)  # [D]
        min_cnt = jnp.min(jnp.where(valid_row, row, jnp.inf))
        cnt_n = _term_counts(st.match_count, d, gs)
        gdom_g = jnp.take(d.gdom, gs, axis=0)
        self_match = s.pmg[gs].astype(jnp.float32)
        has_domains = jnp.isfinite(min_cnt)
        c_ok = (
            (gdom_g >= 0)
            & has_domains
            & (cnt_n + self_match - jnp.where(has_domains, min_cnt, 0.0) <= s.spread_skew[a])
        )
        ok = ok & jnp.where((g >= 0) & s.spread_dns[a], c_ok, True)
    return ok


def spread_score(d: Derived, st: DevState, s: PodSlot) -> jax.Array:
    N = d.gdom.shape[1]
    raw = jnp.zeros(N, dtype=jnp.float32)
    for a in range(s.spread_g.shape[0]):
        g = s.spread_g[a]
        gs = jnp.clip(g, 0, None)
        cnt_n = _term_counts(st.match_count, d, gs)
        raw = raw + jnp.where(g >= 0, cnt_n + s.pmg[gs].astype(jnp.float32), 0.0)
    return raw


# ---------------------------------------------------------------------------
# Resource scores
# ---------------------------------------------------------------------------

# Scores are INTEGER-valued f32, floored through single-op chains — nothing
# XLA can FMA-fuse — so device scores are bit-identical to ops.cpu and
# argmax ties break the same way (SURVEY.md §7 hard part #6). Mirrors
# upstream's int64 node scores.


def _int_resource_score(frac: jax.Array, weights) -> jax.Array:
    s = jnp.floor(frac * np.float32(MAX_NODE_SCORE))  # [N, R], integral
    acc = jnp.zeros(frac.shape[0], dtype=jnp.float32)
    wsum = 0.0
    for r in range(frac.shape[1]):
        w = float(weights[r])
        if w != 0:
            acc = acc + s[:, r] * np.float32(w)  # exact: small ints
            wsum += w
    if wsum == 0:
        return acc
    return jnp.floor(acc / np.float32(wsum))


def least_allocated_score(dc: DevCluster, st: DevState, s: PodSlot, weights) -> jax.Array:
    alloc = dc.allocatable
    denom = jnp.where(alloc > 0, alloc, 1.0)
    frac = jnp.where(alloc > 0, (alloc - st.used - s.req[None, :]) / denom, 0.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    return _int_resource_score(frac, weights)


def most_allocated_score(dc: DevCluster, st: DevState, s: PodSlot, weights) -> jax.Array:
    alloc = dc.allocatable
    denom = jnp.where(alloc > 0, alloc, 1.0)
    frac = jnp.where(alloc > 0, (st.used + s.req[None, :]) / denom, 0.0)
    frac = jnp.clip(frac, 0.0, 1.0)
    return _int_resource_score(frac, weights)


def piecewise_interp_int(util: jax.Array, xs, ys) -> jax.Array:
    """Mirror of ops.cpu.piecewise_interp_int (seg = y0 + floor(t·Δy))."""
    out = jnp.full(util.shape, np.float32(ys[-1]), dtype=jnp.float32)
    for i in range(len(xs) - 2, -1, -1):
        x0, x1 = np.float32(xs[i]), np.float32(xs[i + 1])
        y0, y1 = np.float32(ys[i]), np.float32(ys[i + 1])
        t = (util.astype(jnp.float32) - x0) * (np.float32(1.0) / (x1 - x0))
        seg = y0 + jnp.floor(t * (y1 - y0))
        out = jnp.where(util <= x1, seg, out)
    return jnp.where(util <= np.float32(xs[0]), np.float32(ys[0]), out).astype(jnp.float32)


def requested_to_capacity_ratio_score(
    dc: DevCluster, st: DevState, s: PodSlot, weights, shape_x, shape_y
) -> jax.Array:
    alloc = dc.allocatable
    denom = jnp.where(alloc > 0, alloc, 1.0)
    frac = jnp.where(alloc > 0, (st.used + s.req[None, :]) / denom, 0.0)
    util = jnp.floor(jnp.clip(frac, 0.0, 1.0) * np.float32(100.0))
    score_r = piecewise_interp_int(util, list(shape_x), list(shape_y))
    acc = jnp.zeros(alloc.shape[0], dtype=jnp.float32)
    wsum = 0.0
    for r in range(score_r.shape[1]):
        w = float(weights[r])
        if w != 0:
            acc = acc + score_r[:, r] * np.float32(w)
            wsum += w
    if wsum == 0:
        return acc
    return jnp.floor(acc / np.float32(wsum))


# ---------------------------------------------------------------------------
# Normalization + selection + state update
# ---------------------------------------------------------------------------

def normalize_max(raw: jax.Array, feasible: jax.Array, reverse: bool = False) -> jax.Array:
    """Mirror of ops.cpu.normalize_max: floor(raw·100/max), integer scores."""
    vals = jnp.where(feasible, raw, 0.0)
    mx = jnp.max(vals)
    pos = mx > 0
    out = jnp.floor((raw * np.float32(MAX_NODE_SCORE)) / jnp.where(pos, mx, 1.0))
    out = jnp.where(pos, out, 0.0)
    if reverse:
        out = jnp.where(pos, np.float32(MAX_NODE_SCORE) - out, np.float32(MAX_NODE_SCORE))
    return out.astype(jnp.float32)


def normalize_min_max(raw: jax.Array, feasible: jax.Array, reverse: bool = False) -> jax.Array:
    """Mirror of ops.cpu.normalize_min_max: floor((raw−lo)·(100/span))."""
    any_f = jnp.any(feasible)
    lo = jnp.min(jnp.where(feasible, raw, jnp.inf)).astype(jnp.float32)
    hi = jnp.max(jnp.where(feasible, raw, -jnp.inf)).astype(jnp.float32)
    span = hi - lo
    ok = any_f & (span > 0)
    out = jnp.floor(
        (raw - jnp.where(ok, lo, 0.0)) * (np.float32(MAX_NODE_SCORE) / jnp.where(ok, span, 1.0))
    )
    out = jnp.where(ok, out, 0.0)
    if reverse:
        out = jnp.where(ok, np.float32(MAX_NODE_SCORE) - out, 0.0)
    return out.astype(jnp.float32)


def select_node(scores: jax.Array, feasible: jax.Array):
    """(choice i32, placed bool) — lowest-index argmax tie-break, matching
    numpy argmax (SURVEY.md §7 hard part #6)."""
    masked = jnp.where(feasible, scores, NEG_INF)
    choice = jnp.argmax(masked).astype(jnp.int32)
    placed = jnp.any(feasible)
    return jnp.where(placed, choice, PAD), placed


def apply_binding(
    dc: DevCluster, d: Derived, st: DevState, s: PodSlot, node: jax.Array, on: jax.Array, sign: float = 1.0
) -> DevState:
    """Masked bind (sign=+1) / unbind (sign=-1). ``on`` is a bool scalar;
    when False the update is a no-op — keeps the scan branch-free."""
    w = jnp.where(on & s.valid, sign, 0.0).astype(jnp.float32)
    ns = jnp.clip(node, 0, None)
    used = st.used.at[ns].add(w * s.req)
    G = st.match_count.shape[0]
    dom_g = d.gdom[:, ns]  # [G]
    dval = dom_g >= 0
    doms = jnp.clip(dom_g, 0, None)
    match_count = st.match_count.at[jnp.arange(G), doms].add(
        w * (s.pmg & dval).astype(jnp.float32)
    )
    anti = st.anti_active
    bits = st.anti_bits
    for a in range(s.anti_req.shape[0]):
        g = s.anti_req[a]
        gs = jnp.clip(g, 0, None)
        ok = (g >= 0) & dval[gs]
        anti = anti.at[gs, doms[gs]].add(w * ok.astype(jnp.float32))
        # Refresh bit plane g of anti_bits from the updated count row: bit
        # set ⇔ count > 0 in the node's domain. Only term groups of the
        # bound pod can change, so this is a few [N] ops per bind.
        row = jnp.take(anti, gs, axis=0)  # [D]
        gdom_g = jnp.take(d.gdom, gs, axis=0)  # [N]
        on_nodes = (jnp.take(row, jnp.clip(gdom_g, 0, None)) > 0) & (gdom_g >= 0)
        bit = jnp.left_shift(jnp.uint32(1), (gs % 32).astype(jnp.uint32))
        apply_g = ok & (on & s.valid)
        for wd in range(bits.shape[1]):
            in_word = apply_g & (gs // 32 == wd)
            old = bits[:, wd]
            new = jnp.where(on_nodes, old | bit, old & ~bit)
            bits = bits.at[:, wd].set(jnp.where(in_word, new, old))
    pref = st.pref_wsum
    for a in range(s.pref_aff.shape[0]):
        g = s.pref_aff[a]
        gs = jnp.clip(g, 0, None)
        ok = (g >= 0) & dval[gs]
        pref = pref.at[gs, doms[gs]].add(w * s.pref_aff_w[a] * ok.astype(jnp.float32))
    return DevState(
        used=used, match_count=match_count, anti_active=anti, pref_wsum=pref, anti_bits=bits
    )
