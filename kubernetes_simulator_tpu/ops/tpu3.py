"""Scheduling kernels v3 — domain-space state + wave-deferred commits.

Why: profiling the v2 node-space design showed the replay is HBM-bound at
scale: every pod step streamed the ``[S, G, N]`` count planes ~10× (reads
+ functional rewrites), saturating ~270k placements/s regardless of
scenario count. v3 restructures the STATE, not the semantics:

- **Domain-space planes** ``[G, Dcap]`` for groups whose topology has few
  domains (zone/rack): tiny (KBs), so reads are micro-matmuls and commits
  are dense one-hot adds — no [N]-wide traffic at all.
- **Host planes** ``[Gh, N]`` only for groups keyed by hostname-scale
  topologies (domain ≈ node), kept per *referenced plane section* so a
  trace with no such terms (Borg shape) carries none.
- **Wave-deferred commits**: within a wave the carried tensors are never
  rewritten; each pod's evaluation adds exact in-wave correction terms
  (rank-1 in the bound node / bound domain) for the pods before it, and
  the wave commits once — with the gang all-or-nothing mask folded in, so
  rollback is free. ``used`` is read once per pod (the unavoidable fit
  stream) but written once per wave.
- **Node-value expansion** of domain-space rows rides a fused masked-sum
  over the ≤Dcap domains (``val[n] = rows[dom(n)]`` without gathers, which
  serialize on TPU — measured 100× slower than the arithmetic forms).

Semantics match the v2 chain (ops.tpu.eval_pod_fused) and the CPU oracle:
same greedy arrival order, same speculative in-wave visibility, same
normalization arithmetic (shared helpers), same tie-breaks. Pinned by
tests/test_jax_parity.py (which drives this path) and test_tpu3_equiv.

Exactness caveat: the wave-deferred ``used`` commit sums a wave's requests
in one reduction instead of v2's per-pod sequential adds. Both are f32
sums of the same multiset, so results are bit-identical whenever the
per-node accumulations are exactly representable (bucketed k8s quantities
— powers-of-two multiples — at realistic magnitudes are); a pathological
trace mixing ~2^24-ulp-apart magnitudes on one node could flip a
floor-quantized score by one. The parity suites pin equality on realistic
traces; whatif batches pick v2/v3 per batch (labels_dirty), so keep that
caveat in mind when comparing across batches at extreme magnitudes.

Scenario batches whose label perturbations change topology domains
(whatif ``labels_dirty``) stay on v3 via per-scenario DynTables (round
3): append-style domain ids plus K sparse node→domain overrides applied
as a correction matmul on top of the scenario-SHARED base expansion
tables — see ``DynTables``/``make_wave_step3(dyn=...)`` below. Callers
fall back to v2 only outside the DynTables envelope (host-scale topology
changes, >32 perturbed nodes/scenario, pre-bound pods, preemption,
forks — sim/whatif.py gates and reports via ``WhatIfEngine.engine``).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.encode import PAD, EncodedCluster, EncodedPods
from . import tpu as T2
from .tpu import (
    DevCluster,
    Derived,
    PodSlot,
    _HI,
    _normalize_row,
    _term_onehot,
    select_node,
)

# Compat shim: some jax versions ship optimization_barrier without a vmap
# batching rule, and the what-if engine vmaps wave_step (which uses the
# barrier to pin the feasibility/plane-update schedule). The barrier is
# identity-per-operand, so under vmap we DROP it entirely (pass the
# batched operands through unbound) rather than re-binding the
# primitive: the SPMD partitioner has no sharding rule for it, and a
# barrier surviving into the mesh-sharded what-if program makes GSPMD
# replicate its operands — all-gathers on the scenario axis
# (test_mesh_hlo pins their absence). Values are unaffected either way
# (the barrier is a scheduling hint, not an op); the non-vmapped
# single-replay path keeps the real barrier.
try:  # pragma: no cover - version-dependent
    from jax._src.lax.control_flow import optimization_barrier_p as _ob_p
    from jax.interpreters import batching as _batching

    if _ob_p not in _batching.primitive_batchers:

        def _ob_batch(args, dims, **params):
            del params
            return list(args), list(dims)

        _batching.primitive_batchers[_ob_p] = _ob_batch
except Exception:
    pass

# Round 10 (fused tier-preemption, PR 2's measured 4.1× standalone cost):
# when on, the preemption wave program (a) packs the three prefix-over-
# tiers stacks into ONE [Tt+1, R+2, N] tensor so each slot pays a single
# dynamic gather instead of three, (b) takes the victim-node rank through
# one variadic (value, index) reduce (tpu.masked_argmin) instead of
# argmax + any, and (c) commits all Tt tier planes in one batched
# einsum pass instead of a per-tier Python loop. Same summands in the
# same w-order per output element — bit-identical to the pre-fusion
# program (tests/test_preemption_device.py pins fused≡prefusion≡oracle).
# Read at TRACE time: monkeypatch ops.tpu3.FUSED_PREEMPT (or set
# KSIM_FUSED_PREEMPT=0) before building an engine to get the old program.
FUSED_PREEMPT = os.environ.get("KSIM_FUSED_PREEMPT", "1") not in ("", "0")

# ---------------------------------------------------------------------------
# Static (per-trace) structure
# ---------------------------------------------------------------------------

# Topologies with more domains than this live in node-space host planes
# instead of [G, Dcap] domain planes. ONE shared constant: V3Static.build's
# default and whatif.ScenarioSet's DynTables eligibility must agree on it.
DMAX_COARSE = 128



@dataclass(frozen=True)
class V3Static:
    """Host-side, numpy. Row layout over the unified term axis KT:
    [A aff | B anti | SP spread | PA pref | MA sym-anti | MP sym-pref];
    every row is one (group, plane) read. Sections read planes:
    aff/anti/spread/pref → match-count; sym-anti → anti; sym-pref → pref."""

    A: int
    B: int
    SP: int
    PA: int
    MA: int
    MP: int
    # Static maintenance gates: a plane is carried only if some row can
    # ever read it (match counts also need A>0 for bootstrap totals).
    maintain_mc: bool
    maintain_anti: bool
    maintain_pref: bool
    Dcap: int  # max #domains over coarse groups (≥1)
    G: int
    is_host: np.ndarray  # [G] bool — hostname-scale topology
    nd_g: np.ndarray  # [G] i32 — #domains of each group's topology
    single_g: np.ndarray  # [G] bool — every domain holds exactly one node
    # (hostname). Host commits then collapse to bound-node one-hots; host
    # groups over multi-node domains need the dom-equality commit path.
    # Host-plane group lists per plane kind (global group ids).
    mc_h_ids: np.ndarray  # [Hmc]
    anti_h_ids: np.ndarray  # [Ha]
    pref_h_ids: np.ndarray  # [Hp]
    g2mc_h: np.ndarray  # [G] local id or -1
    g2anti_h: np.ndarray
    g2pref_h: np.ndarray
    # Per-pod matched-group index lists for the symmetric checks,
    # restricted to groups actually referenced by anti/pref terms.
    anti_midx: np.ndarray  # [P, MA]
    pref_midx: np.ndarray  # [P, MP]
    has_gangs: bool
    # Any DoNotSchedule spread constraint in the trace: when False the
    # node-space spread FILTER block is statically absent (sp_dns is traced
    # data, so XLA cannot DCE it; ScheduleAnyway-only traces — the Borg
    # shape — would otherwise pay the [S, KT, N] count expansion for a
    # filter that never fires). Profile round 3: _expand_rows was ~10% of
    # device time on the north-star shape purely from this.
    has_dns: bool
    # All domain-bearing groups share one topology key (the Borg shape:
    # zone-only): bound-node domain lookups collapse to one shared [N] map.
    # ``topo0`` is that topology's id (PAD when no group carries domains);
    # Shared3.build consumes it — ONE detection site.
    single_topo: bool
    topo0: int
    # Structured shared-topology layout: "stride" (dom = n % D) or "block"
    # (dom = n // (N/D)) — per-domain feasibility then reduces over a plain
    # reshape instead of the [S, N]×[N, D] one-hot matmul. "" = no pattern.
    seg_mode: str
    seg_D: int
    # Toleration / node-affinity equivalence classes: pods sharing identical
    # term rows share one per-chunk [N] mask+raw (C ≪ P in real traces, e.g.
    # one class per workload template). class id PAD → fall back row 0 is a
    # never-used zero row only when C == 0.
    tol_class: np.ndarray  # [P] i32
    tol_rep: np.ndarray  # [Ct] i32 representative pod index per class
    na_class: np.ndarray  # [P] i32
    na_rep: np.ndarray  # [Cn] i32
    # Tier preemption (opt-in; see sim.greedy docstring for the semantics).
    preemption: bool = False
    Tt: int = 0  # number of priority tiers (0 = feature off)
    pod_tier: np.ndarray = None  # [P] i32
    # bf16 host planes: exact when every plane value is an integer ≤ 256,
    # i.e. singleton (hostname) domains with bounded pods-per-node. Halves
    # the dominant host-read/commit traffic. pref stays f32 (fractional).
    mc_h_bf16: bool = False
    anti_h_bf16: bool = False

    @property
    def KT(self) -> int:
        return self.A + self.B + self.SP + self.PA + self.MA + self.MP

    @property
    def has_host_rows(self) -> bool:
        """Any term row can hit a host plane (else the host-value paths
        compile away entirely)."""
        return bool(len(self.mc_h_ids) or len(self.anti_h_ids) or len(self.pref_h_ids))

    # Class-mask fallback guard: degenerate traces (every pod distinct)
    # would make the per-chunk class tensors [C, N] bigger than the work
    # they save; fall back to per-wave vmap evaluation there.
    MAX_CLASSES = 256

    @property
    def use_tol_classes(self) -> bool:
        return 0 < len(self.tol_rep) <= self.MAX_CLASSES

    @property
    def use_na_classes(self) -> bool:
        return 0 < len(self.na_rep) <= self.MAX_CLASSES

    @property
    def sections(self) -> Tuple[int, ...]:
        """Start offsets of (aff, anti, spread, pref, symanti, sympref, end)."""
        a = self.A
        b = a + self.B
        s = b + self.SP
        p = s + self.PA
        ma = p + self.MA
        return (0, a, b, s, p, ma, ma + self.MP)

    MAX_TIERS = 8

    @classmethod
    def build(
        cls,
        ec: EncodedCluster,
        ep: EncodedPods,
        spec,
        dmax_coarse: int = DMAX_COARSE,
        preemption: bool = False,
        allow_bf16_host: bool = True,
        dcap_min: int = 0,
    ) -> "V3Static":
        """``dcap_min``: widen the domain axis past the base cluster's
        count — labels_dirty what-if batches append per-scenario domain
        ids for new label values (whatif.ScenarioDyn)."""
        G = max(ec.num_groups, 1)
        gt = ec.group_topo[:G] if ec.group_topo.shape[0] >= G else np.full(G, PAD, np.int32)
        nd_g = np.where(gt >= 0, ec.num_domains[np.clip(gt, 0, None)], 0).astype(np.int32)
        is_host = nd_g > dmax_coarse
        Dcap = int(
            max(nd_g[~is_host].max() if (~is_host).any() else 1, 1, dcap_min)
        )
        # Per topology: does every domain hold exactly one node?
        Tn = ec.node_domain.shape[0]
        topo_single = np.zeros(Tn, bool)
        for ti in range(Tn):
            dom = ec.node_domain[ti]
            labeled = dom[dom >= 0]
            topo_single[ti] = labeled.size == 0 or (
                np.bincount(labeled).max() == 1
            )
        single_g = np.where(gt >= 0, topo_single[np.clip(gt, 0, None)], True)

        interpod = spec.interpod
        spread = spec.spread
        A = ec_width(ep.aff_req) if interpod else 0
        B = ec_width(ep.anti_req) if interpod else 0
        SP = ec_width(ep.spread_g) if spread else 0
        PA = ec_width(ep.pref_aff) if interpod else 0

        pmg = ep.pod_matches_group  # [P, G']
        Pg = pmg.shape[1]
        anti_ref = np.zeros(G, bool)
        pref_ref = np.zeros(G, bool)
        if interpod:
            for g in np.unique(ep.anti_req[ep.anti_req >= 0]):
                anti_ref[g] = True
            for g in np.unique(ep.pref_aff[ep.pref_aff >= 0]):
                pref_ref[g] = True
        anti_midx = _matched_idx(pmg, anti_ref[:Pg]) if interpod else np.zeros((ep.num_pods, 0), np.int32)
        pref_midx = (
            _matched_idx(pmg, pref_ref[:Pg])
            if (interpod and spec.has_symmetric_pref)
            else np.zeros((ep.num_pods, 0), np.int32)
        )

        mc_ref = np.zeros(G, bool)  # groups whose match-count a row can read
        for arr, on in ((ep.aff_req, interpod), (ep.anti_req, interpod),
                        (ep.spread_g, spread), (ep.pref_aff, interpod)):
            if on and arr.size:
                for g in np.unique(arr[arr >= 0]):
                    mc_ref[g] = True
        mc_h_ids = np.nonzero(mc_ref & is_host)[0].astype(np.int32)
        anti_h_ids = np.nonzero(anti_ref & is_host)[0].astype(np.int32)
        pref_h_ids = np.nonzero(pref_ref & is_host)[0].astype(np.int32)

        def inv(ids):
            m = np.full(G, -1, np.int32)
            m[ids] = np.arange(len(ids), dtype=np.int32)
            return m

        tol_class, tol_rep = _row_classes(
            np.concatenate([ep.tol_key, ep.tol_kv, ep.tol_effect], axis=1)
        )
        na_class, na_rep = _row_classes(
            np.concatenate(
                [
                    ep.na_req.reshape(ep.num_pods, -1),
                    ep.na_has_req[:, None].astype(np.int32),
                    ep.na_pref.reshape(ep.num_pods, -1),
                    ep.na_pref_w.view(np.int32).reshape(ep.num_pods, -1),
                ],
                axis=1,
            )
        )
        Tt = 0
        pod_tier = np.zeros(ep.num_pods, np.int32)
        if preemption:
            from ..sim.greedy import priority_tiers

            tiers, pod_tier = priority_tiers(ep)
            Tt = len(tiers)
            if Tt > cls.MAX_TIERS:
                raise ValueError(
                    f"device preemption supports <= {cls.MAX_TIERS} priority "
                    f"tiers; trace has {Tt}"
                )
        # bf16 exactness bound: integers ≤ 256. Counts at singleton
        # (hostname) domains are bounded by pods-per-node; anti activations
        # additionally by the per-pod anti-term width. Callers that mutate
        # capacity at runtime (node events / what-if perturbations scaling
        # the "pods" resource) must pass allow_bf16_host=False — the bound
        # is baked into the jitted kernel.
        pods_ri = ec.vocab._r.get("pods")
        # The per-node count bound only holds if NodeResourcesFit actually
        # enforces the "pods" resource (spec.fit); otherwise counts are
        # unbounded and bf16 would round silently past 256.
        max_pods = (
            float(ec.allocatable[:, pods_ri].max())
            if (spec.fit and pods_ri is not None and ec.num_nodes)
            else np.inf
        )
        mc_h_bf16 = bool(
            allow_bf16_host
            and len(mc_h_ids) and single_g[mc_h_ids].all() and max_pods <= 256
        )
        anti_h_bf16 = bool(
            allow_bf16_host
            and len(anti_h_ids)
            and single_g[anti_h_ids].all()
            and max_pods * max(B, 1) <= 256
        )
        topo_groups = (gt >= 0) & (nd_g > 0)
        single_topo = bool(len(set(gt[topo_groups].tolist())) <= 1)
        topo0 = int(gt[topo_groups][0]) if topo_groups.any() else PAD
        seg_mode, seg_D = "", 0
        if single_topo and topo0 != PAD:
            dom = ec.node_domain[topo0]
            D0 = int(ec.num_domains[topo0])
            N = ec.num_nodes
            if 0 < D0 <= Dcap and N % D0 == 0:
                if (dom == np.arange(N) % D0).all():
                    seg_mode, seg_D = "stride", D0
                elif (dom == np.arange(N) // (N // D0)).all():
                    seg_mode, seg_D = "block", D0
        out = cls(
            seg_mode=seg_mode, seg_D=seg_D, topo0=topo0,
            tol_class=tol_class, tol_rep=tol_rep,
            na_class=na_class, na_rep=na_rep,
            preemption=preemption, Tt=Tt, pod_tier=pod_tier,
            mc_h_bf16=mc_h_bf16, anti_h_bf16=anti_h_bf16,
            A=A, B=B, SP=SP, PA=PA,
            MA=anti_midx.shape[1], MP=pref_midx.shape[1],
            maintain_mc=bool(mc_ref.any()),
            maintain_anti=bool(anti_midx.shape[1]),
            maintain_pref=bool(pref_midx.shape[1]),
            Dcap=Dcap, G=G, is_host=is_host, nd_g=nd_g, single_g=single_g,
            mc_h_ids=mc_h_ids, anti_h_ids=anti_h_ids, pref_h_ids=pref_h_ids,
            g2mc_h=inv(mc_h_ids), g2anti_h=inv(anti_h_ids), g2pref_h=inv(pref_h_ids),
            anti_midx=anti_midx, pref_midx=pref_midx,
            has_gangs=spec.has_gangs,
            has_dns=bool(
                SP and (ep.spread_dns[:, :SP] & (ep.spread_g[:, :SP] >= 0)).any()
            ),
            single_topo=single_topo,
        )
        if preemption and out.has_host_rows:
            raise ValueError(
                "device preemption is not supported together with "
                "hostname-scale topology terms (host planes); use the CPU "
                "event engine for full kube PostFilter semantics"
            )
        return out


def ec_width(arr: np.ndarray) -> int:
    """Static term width, treating the all-PAD placeholder column as 0."""
    return arr.shape[1] if arr.size and (arr >= 0).any() else 0


def _row_classes(rows: np.ndarray):
    """(class_of [P] i32, rep [C] i32): group identical rows; rep[c] is the
    first pod index exhibiting class c."""
    if rows.shape[0] == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int32)
    uniq, first, inv = np.unique(
        np.ascontiguousarray(rows), axis=0, return_index=True, return_inverse=True
    )
    order = np.argsort(first)
    rank = np.empty(len(uniq), np.int32)
    rank[order] = np.arange(len(uniq), dtype=np.int32)
    return rank[inv].astype(np.int32), first[order].astype(np.int32)


def _matched_idx(pmg: np.ndarray, ref: np.ndarray) -> np.ndarray:
    """[P, M] group ids each pod matches, restricted to ``ref`` groups."""
    sel = pmg & ref[None, :]
    counts = sel.sum(axis=1)
    M = int(counts.max()) if counts.size else 0
    out = np.full((pmg.shape[0], M), PAD, np.int32)
    for p in np.nonzero(counts)[0]:
        ids = np.nonzero(sel[p])[0]
        out[p, : len(ids)] = ids
    return out


def _gdom_table(ec: EncodedCluster, G: int) -> np.ndarray:
    """[G, N] i32 — domain of node n under group g's topology (PAD=-1).
    One derivation, shared with the CPU kernels."""
    from .cpu import _group_dom_per_node

    return _group_dom_per_node(ec)[:G]


class Shared3(NamedTuple):
    """Scenario-shared device tensors (v3 requires shared topology)."""

    gdom_f: jax.Array  # [G, N] f32 domain of node n under group g (PAD=-1)
    coarse_f: jax.Array  # [G] f32 1.0 where coarse
    mt_mask: jax.Array  # [G] f32 1.0 where group has domains (for totals)
    # single_topo fast path: the one shared node→domain map and the groups
    # it applies to (all-PAD rows stay PAD through has_dom_g masking).
    topo1_f: jax.Array  # [N] f32 (all-PAD when single_topo is False/vacuous)
    has_dom_g: jax.Array  # [G] f32 1.0 where the group carries domains

    @classmethod
    def build(cls, ec: EncodedCluster, st: V3Static) -> "Shared3":
        gdom = _gdom_table(ec, st.G)
        gt = (
            ec.group_topo[: st.G]
            if ec.group_topo.shape[0] >= st.G
            else np.full(st.G, PAD, np.int32)
        )
        # Single source of truth: V3Static.build already certified topo0 /
        # single_topo; this only materializes the corresponding tensors.
        if st.topo0 != PAD:
            topo1 = ec.node_domain[st.topo0].astype(np.float32)
        else:
            topo1 = np.full(ec.num_nodes, float(PAD), np.float32)
        return cls(
            gdom_f=jnp.asarray(gdom.astype(np.float32)),
            coarse_f=jnp.asarray((~st.is_host).astype(np.float32)),
            mt_mask=jnp.asarray((st.nd_g > 0).astype(np.float32)),
            topo1_f=jnp.asarray(topo1),
            has_dom_g=jnp.asarray(((gt >= 0) & (st.nd_g > 0)).astype(np.float32)),
        )


class DevState3(NamedTuple):
    """Carried state. Domain planes are [G, Dcap] (host-group rows stay
    zero); host planes are [H*, N] per plane kind.

    ``used`` is stored TRANSPOSED [R, N]: with R tiny (3-5), [N, R] minor-R
    tensors force every fit/score op to carry a dead minor axis; [R, N]
    planes keep all hot elementwise work at [S, N] shape and let the R loop
    unroll statically."""

    used: jax.Array  # [R, N] f32
    mc_dom: jax.Array  # [G, Dcap] f32
    anti_dom: jax.Array  # [G, Dcap] f32
    pref_dom: jax.Array  # [G, Dcap] f32
    mc_host: jax.Array  # [Hmc, N] f32
    anti_host: jax.Array  # [Ha, N] f32
    pref_host: jax.Array  # [Hp, N] f32
    match_total: jax.Array  # [G] f32
    # Preemption-only planes ([0, ...] when off): non-gang usage / pod
    # counts by priority tier.
    used_tier: jax.Array  # [Tt, R, N] f32
    npods_tier: jax.Array  # [Tt, N] f32

    @classmethod
    def from_host(
        cls, used: np.ndarray, mc: np.ndarray, aa: np.ndarray, pw: np.ndarray,
        ec: EncodedCluster, st: V3Static, ep: Optional[EncodedPods] = None,
    ) -> "DevState3":
        """Domain-space host arrays [G, D] (models.state layout) → v3.
        ``ep`` is required when preemption is on (tier planes rebuild from
        the pre-bound pods)."""
        G, Dcap = st.G, st.Dcap

        def dom_part(arr):
            out = np.zeros((G, Dcap), np.float32)
            w = min(arr.shape[1], Dcap)
            out[: arr.shape[0], :w] = np.where(st.is_host[: arr.shape[0], None], 0.0, arr[:, :w])
            return out

        gdom = _gdom_table(ec, G)

        def host_part(arr, ids):
            out = np.zeros((len(ids), ec.num_nodes), np.float32)
            for li, g in enumerate(ids):
                if g < arr.shape[0]:
                    out[li] = T2.domain_to_node_space(arr[g : g + 1], gdom[g : g + 1])[0]
            return out

        mt = np.zeros(G, np.float32)
        mt[: mc.shape[0]] = mc.sum(axis=1)
        N, R = ec.num_nodes, ec.num_resources
        used_tier = np.zeros((st.Tt, R, N), np.float32)
        npods_tier = np.zeros((st.Tt, N), np.float32)
        if st.Tt and ep is not None:
            pre = np.nonzero((ep.bound_node >= 0) & (ep.group_id == PAD))[0]
            for p in pre:
                t, n = int(st.pod_tier[p]), int(ep.bound_node[p])
                used_tier[t, :, n] += ep.requests[p]
                npods_tier[t, n] += 1.0
        return cls(
            used=jnp.asarray(np.ascontiguousarray(used.T).astype(np.float32)),
            mc_dom=jnp.asarray(dom_part(mc)),
            anti_dom=jnp.asarray(dom_part(aa)),
            pref_dom=jnp.asarray(dom_part(pw)),
            mc_host=_host_plane(host_part(mc, st.mc_h_ids), st.mc_h_bf16),
            anti_host=_host_plane(host_part(aa, st.anti_h_ids), st.anti_h_bf16),
            pref_host=jnp.asarray(host_part(pw, st.pref_h_ids)),
            match_total=jnp.asarray(mt),
            used_tier=jnp.asarray(used_tier),
            npods_tier=jnp.asarray(npods_tier),
        )

    def to_host(self, ec: EncodedCluster, st: V3Static, D: int):
        """v3 → domain-space [G, D] host arrays (checkpoint/result layout)."""
        gdom = _gdom_table(ec, st.G)

        def back(dom_arr, host_arr, ids):
            out = np.zeros((st.G, D), np.float32)
            w = min(st.Dcap, D)
            out[:, :w] = np.asarray(dom_arr)[:, :w]
            host_np = np.asarray(host_arr)  # one device→host transfer
            for li, g in enumerate(ids):
                out[g] = T2.node_space_to_domain(
                    host_np[li : li + 1], gdom[g : g + 1], D
                )[0]
            return out

        return (
            np.ascontiguousarray(np.asarray(self.used).T),  # back to [N, R]
            back(self.mc_dom, self.mc_host, st.mc_h_ids),
            back(self.anti_dom, self.anti_host, st.anti_h_ids),
            back(self.pref_dom, self.pref_host, st.pref_h_ids),
        )


def _host_plane(vals: np.ndarray, bf16: bool) -> jax.Array:
    """Host plane → device, validating the bf16 exactness bound before a
    lossy cast (resumed/trace-provided state could exceed it)."""
    if bf16:
        if vals.size and not (
            (vals <= 256).all() and (vals == np.round(vals)).all()
        ):
            raise ValueError(
                "host-plane values exceed the bf16 exactness bound "
                "(integers <= 256); rebuild with allow_bf16_host=False"
            )
        return jnp.asarray(vals, dtype=jnp.bfloat16)
    return jnp.asarray(vals)


class SlotExtra(NamedTuple):
    """v3-only per-slot rows gathered alongside PodSlot."""

    anti_midx: jax.Array  # [MA] i32
    pref_midx: jax.Array  # [MP] i32
    tol_class: jax.Array  # i32 scalar
    na_class: jax.Array  # i32 scalar
    tier: jax.Array  # i32 scalar (0 when preemption off)


class ExtraSource(NamedTuple):
    """Device-resident twins of the V3Static per-pod rows (see
    ops.tpu.SlotSource — same once-per-engine upload pattern)."""

    anti_midx: jax.Array  # [P, MA]
    pref_midx: jax.Array  # [P, MP]
    tol_class: jax.Array  # [P]
    na_class: jax.Array  # [P]
    tier: jax.Array  # [P]

    @classmethod
    def build(cls, st: V3Static, num_pods: int) -> "ExtraSource":
        z = np.zeros(num_pods, np.int32)
        return cls(
            anti_midx=jnp.asarray(st.anti_midx.astype(np.int32)),
            pref_midx=jnp.asarray(st.pref_midx.astype(np.int32)),
            tol_class=jnp.asarray(
                st.tol_class.astype(np.int32) if st.tol_class.size else z
            ),
            na_class=jnp.asarray(
                st.na_class.astype(np.int32) if st.na_class.size else z
            ),
            tier=jnp.asarray(st.pod_tier.astype(np.int32) if st.Tt else z),
        )

    @classmethod
    def page(cls, st: V3Static, flat: np.ndarray) -> "ExtraSource":
        """One PAGE of the extra source (round 14 paged pod waves — the
        v3 twin of ops.tpu.SlotSource.page): rows at flat pod ids
        ``flat``, PAD ids mapped to neutral zero rows. Keeps the pod
        axis streamable — only chunk_waves × wave_width rows are
        device-resident at once instead of all P."""
        safe = np.clip(flat, 0, None)
        n = safe.shape[0]
        z = np.zeros(n, np.int32)
        return cls(
            anti_midx=jnp.asarray(st.anti_midx[safe].astype(np.int32)),
            pref_midx=jnp.asarray(st.pref_midx[safe].astype(np.int32)),
            tol_class=jnp.asarray(
                st.tol_class[safe].astype(np.int32) if st.tol_class.size else z
            ),
            na_class=jnp.asarray(
                st.na_class[safe].astype(np.int32) if st.na_class.size else z
            ),
            tier=jnp.asarray(st.pod_tier[safe].astype(np.int32) if st.Tt else z),
        )


@jax.jit
def gather_extra_device(src: ExtraSource, idx: jax.Array) -> SlotExtra:
    """jnp twin of gather_extra (value-identical)."""
    safe = jnp.clip(idx, 0, None)
    ok = (idx >= 0)[..., None]
    return SlotExtra(
        anti_midx=jnp.where(ok, src.anti_midx[safe], PAD).astype(jnp.int32),
        pref_midx=jnp.where(ok, src.pref_midx[safe], PAD).astype(jnp.int32),
        tol_class=src.tol_class[safe],
        na_class=src.na_class[safe],
        tier=src.tier[safe],
    )


def gather_extra(st: V3Static, idx: np.ndarray) -> SlotExtra:
    safe = np.clip(idx, 0, None)
    ok = (idx >= 0)[..., None]
    tol_c = st.tol_class[safe] if st.tol_class.size else np.zeros_like(safe)
    na_c = st.na_class[safe] if st.na_class.size else np.zeros_like(safe)
    tier = st.pod_tier[safe] if st.Tt else np.zeros_like(safe)
    return SlotExtra(
        anti_midx=jnp.asarray(np.where(ok, st.anti_midx[safe], PAD).astype(np.int32)),
        pref_midx=jnp.asarray(np.where(ok, st.pref_midx[safe], PAD).astype(np.int32)),
        tol_class=jnp.asarray(tol_c.astype(np.int32)),
        na_class=jnp.asarray(na_c.astype(np.int32)),
        tier=jnp.asarray(tier.astype(np.int32)),
    )


# ---------------------------------------------------------------------------
# Wave machinery
# ---------------------------------------------------------------------------


class DynTables(NamedTuple):
    """Per-scenario domain tables for labels_dirty what-if batches (one
    scenario's slice under vmap; built by whatif.ScenarioDyn). The base
    (scenario-shared) expansion tables stay untouched — these carry only
    the per-scenario corrections: K label-perturbed nodes with their
    old/new domains per group, the domain-existence mask, and the
    per-scenario spread weights. All tiny next to the [S, N] planes."""

    ov_nodes: jax.Array  # [K] i32 (PAD-padded)
    ov_gdom: jax.Array  # [G, K] f32 new domain (PAD where inapplicable)
    ov_old: jax.Array  # [G, K] f32 base domain (PAD likewise)
    dexist: jax.Array  # [G, Dcap] f32 1.0 where the domain has ≥1 node
    sp_w_g: jax.Array  # [G] f32 log(size+2), size = #existing domains


class WavePre3(NamedTuple):
    """Per-wave precompute. Scenario-independent unless noted."""

    row_g: jax.Array  # [W, KT] i32 global group id (PAD invalid)
    oh_row: jax.Array  # [W, KT, G] f32 one-hot
    coarse_row: jax.Array  # [W, KT] f32 row's group is coarse
    dmap: jax.Array  # [W, KT, N] f32 node→domain per row (PAD=-1)
    ov: jax.Array  # [W(j), W(k), KT] f32 bind-of-j → read-of-(k,row) coupling
    oh_mc_h: jax.Array  # [W, KT, Hmc] f32 host-plane one-hots
    oh_anti_h: jax.Array  # [W, KT, Ha] f32
    oh_pref_h: jax.Array  # [W, KT, Hp] f32
    row_w: jax.Array  # [W, KT] f32 per-row weight (pref rows; 1/0 elsewhere)
    aff_selfm: jax.Array  # [W, A] bool
    sp_selfm: jax.Array  # [W, SP] f32
    sp_skew: jax.Array  # [W, SP] f32
    sp_dns: jax.Array  # [W, SP] bool
    sp_scored: jax.Array  # [W, SP] bool (valid & ScheduleAnyway)
    sp_w: jax.Array  # [W, SP] f32 (upstream log(size+2) weights)
    pmg_f: jax.Array  # [W, G] f32
    anti_g: jax.Array  # [W, G] f32 (required-anti term one-hot sums)
    pref_g: jax.Array  # [W, G] f32 (preferred term weight sums)
    taint_ok: jax.Array  # [W, N] bool (PER-SCENARIO under vmap)
    taint_raw: jax.Array  # [W, N] f32 (per-scenario)
    na_ok: jax.Array  # [W, N] bool (per-scenario)
    na_raw: jax.Array  # [W, N] f32 (per-scenario)
    # labels_dirty (DynTables) rows — zero-width when dyn is None.
    ov_new_row: jax.Array  # [W, KT, K] f32 new dom per row at override j
    ov_old_row: jax.Array  # [W, KT, K] f32 base dom likewise
    dex_row: jax.Array  # [W, SP, Dcap] bool domain-exists per spread row


def build_wave_pre3(
    dc: DevCluster, d: Derived, sh: Shared3, st: V3Static,
    sb: PodSlot, sx: SlotExtra, spec, dyn: Optional[DynTables] = None,
) -> WavePre3:
    W = sb.pod_id.shape[0]
    G = st.G
    N = sh.gdom_f.shape[1]
    pmg_f = sb.pmg.astype(jnp.float32)[:, :G] if sb.pmg.shape[1] >= G else jnp.pad(
        sb.pmg.astype(jnp.float32), ((0, 0), (0, G - sb.pmg.shape[1]))
    )

    secs = []
    if st.A:
        secs.append(sb.aff_req[:, : st.A])
    if st.B:
        secs.append(sb.anti_req[:, : st.B])
    if st.SP:
        secs.append(sb.spread_g[:, : st.SP])
    if st.PA:
        secs.append(sb.pref_aff[:, : st.PA])
    if st.MA:
        secs.append(sx.anti_midx)
    if st.MP:
        secs.append(sx.pref_midx)
    row_g = (
        jnp.concatenate(secs, axis=1) if secs else jnp.zeros((W, 0), jnp.int32)
    )
    oh_row = _term_onehot(row_g, G)  # [W, KT, G]
    coarse_row = jnp.einsum("wkg,g->wk", oh_row, sh.coarse_f, precision=_HI)
    dmap = jnp.einsum("wkg,gn->wkn", oh_row, sh.gdom_f, precision=_HI)
    # Rows of PAD groups must read nothing and match no node.
    dmap = jnp.where((row_g >= 0)[:, :, None], dmap, float(PAD))

    anti_g, pref_g = T2._pod_group_vectors(sb, G)

    # Coupling: how much does pod j's bind add to row (k, r)'s count when
    # the bound node shares the row-group's domain — per plane kind.
    kmask = kind_masks(st)
    ov = (
        (
            jnp.einsum("jg,wkg->jwk", pmg_f, oh_row, precision=_HI)
            * kmask["mc"][None, None, :]
            + jnp.einsum("jg,wkg->jwk", anti_g, oh_row, precision=_HI)
            * kmask["anti"][None, None, :]
            + jnp.einsum("jg,wkg->jwk", pref_g, oh_row, precision=_HI)
            * kmask["pref"][None, None, :]
        )
        if st.KT
        else jnp.zeros((W, W, 0), jnp.float32)
    )

    def hostoh(g2local, H):
        if H == 0:
            return jnp.zeros((W, st.KT, 0), jnp.float32)
        loc = jnp.asarray(g2local)  # [G] static table
        # one-hot over local host ids; zero for coarse/PAD rows
        lrow = jnp.einsum("wkg,g->wk", oh_row, loc.astype(jnp.float32), precision=_HI)
        valid = (1.0 - coarse_row) * (row_g >= 0)
        return (
            (lrow[:, :, None] == jnp.arange(H)[None, None, :])
            & (valid > 0.5)[:, :, None]
        ).astype(jnp.float32)

    # Host reads per plane kind: mask rows to the right sections.
    oh_mc_h = hostoh(st.g2mc_h, len(st.mc_h_ids)) * kmask["mc"][None, :, None]
    oh_anti_h = hostoh(st.g2anti_h, len(st.anti_h_ids)) * kmask["anti"][None, :, None]
    oh_pref_h = hostoh(st.g2pref_h, len(st.pref_h_ids)) * kmask["pref"][None, :, None]

    o0, o1, o2, o3, o4, o5, o6 = st.sections
    row_w = jnp.ones((W, st.KT), jnp.float32)
    if st.PA:
        w = jnp.where(sb.pref_aff[:, : st.PA] >= 0, sb.pref_aff_w[:, : st.PA], 0.0)
        row_w = row_w.at[:, o3:o4].set(w)
    row_w = row_w * (row_g >= 0)

    if st.A:
        ohA = oh_row[:, :o1]
        aff_selfm = jnp.einsum("wag,wg->wa", ohA, pmg_f, precision=_HI) > 0.5
    else:
        aff_selfm = jnp.zeros((W, 0), bool)
    if st.SP:
        ohS = oh_row[:, o2:o3]
        sp_selfm = jnp.einsum("wag,wg->wa", ohS, pmg_f, precision=_HI)
        sp_skew = sb.spread_skew[:, : st.SP].astype(jnp.float32)
        sp_dns = (sb.spread_g[:, : st.SP] >= 0) & sb.spread_dns[:, : st.SP]
        sp_scored = (sb.spread_g[:, : st.SP] >= 0) & ~sb.spread_dns[:, : st.SP]
        if dyn is not None:
            # Per-scenario weights: domain sizes change under set_label.
            sp_w = jnp.einsum("wag,g->wa", ohS, dyn.sp_w_g, precision=_HI)
        else:
            # One source of truth for the upstream topologyNormalizingWeight
            # table: spec.sp_w_g (jax_runtime._spread_w_table).
            w_tab = T2._padded_w_table(spec.sp_w_g, G)
            sp_w = jnp.einsum(
                "wag,g->wa", ohS, jnp.asarray(w_tab), precision=_HI
            )
    else:
        sp_selfm = jnp.zeros((W, 0), jnp.float32)
        sp_skew = jnp.zeros((W, 0), jnp.float32)
        sp_dns = jnp.zeros((W, 0), bool)
        sp_scored = jnp.zeros((W, 0), bool)
        sp_w = jnp.zeros((W, 0), jnp.float32)

    # Taint/NA per-wave tensors only exist on the non-class fallback path;
    # with classes the per-chunk [C, N] masks are read via tiny one-hots.
    if spec.taints and not st.use_tol_classes:
        taint_ok = jax.vmap(lambda s: T2.taint_mask(dc, s))(sb)
        taint_raw = jax.vmap(lambda s: T2.taint_prefer_count(dc, s))(sb)
    else:
        taint_ok = jnp.ones((W, 1), bool)
        taint_raw = jnp.zeros((W, 1), jnp.float32)
    if spec.node_affinity and not st.use_na_classes:
        na_ok = jax.vmap(lambda s: T2.node_affinity_mask(d, s))(sb)
        na_raw = jax.vmap(lambda s: T2.node_affinity_score(d, s))(sb)
    else:
        na_ok = jnp.ones((W, 1), bool)
        na_raw = jnp.zeros((W, 1), jnp.float32)

    if dyn is not None:
        K = dyn.ov_nodes.shape[0]
        valid_row = (row_g >= 0)[:, :, None]
        ov_new_row = jnp.where(
            valid_row,
            jnp.einsum("wkg,gj->wkj", oh_row, dyn.ov_gdom, precision=_HI),
            float(PAD),
        )
        ov_old_row = jnp.where(
            valid_row,
            jnp.einsum("wkg,gj->wkj", oh_row, dyn.ov_old, precision=_HI),
            float(PAD),
        )
        if st.SP:
            dex_row = (
                jnp.einsum(
                    "wag,gd->wad", oh_row[:, o2:o3], dyn.dexist, precision=_HI
                )
                > 0.5
            )
        else:
            dex_row = jnp.zeros((W, 0, st.Dcap), bool)
    else:
        ov_new_row = jnp.zeros((W, st.KT, 0), jnp.float32)
        ov_old_row = jnp.zeros((W, st.KT, 0), jnp.float32)
        dex_row = jnp.zeros((W, st.SP, st.Dcap), bool)

    return WavePre3(
        row_g=row_g, oh_row=oh_row, coarse_row=coarse_row, dmap=dmap, ov=ov,
        oh_mc_h=oh_mc_h, oh_anti_h=oh_anti_h, oh_pref_h=oh_pref_h,
        row_w=row_w, aff_selfm=aff_selfm,
        sp_selfm=sp_selfm, sp_skew=sp_skew, sp_dns=sp_dns,
        sp_scored=sp_scored, sp_w=sp_w,
        pmg_f=pmg_f, anti_g=anti_g, pref_g=pref_g,
        taint_ok=taint_ok, taint_raw=taint_raw, na_ok=na_ok, na_raw=na_raw,
        ov_new_row=ov_new_row, ov_old_row=ov_old_row, dex_row=dex_row,
    )


def _fit_score_r(used1_r, alloc_r, weights, strategy, shape_x, shape_y) -> jax.Array:
    """NodeResourcesFit scoring over per-resource [N] planes, statically
    unrolled over R. Arithmetic mirrors ops.tpu._int_resource_score /
    piecewise_interp_int bit-for-bit (same floor chain, same r order)."""
    N = used1_r[0].shape[0]
    acc = jnp.zeros(N, jnp.float32)
    wsum = 0.0
    for r in range(len(used1_r)):
        w = float(weights[r])
        if w == 0:
            continue
        alloc = alloc_r[r]
        denom = jnp.where(alloc > 0, alloc, 1.0)
        if strategy == "LeastAllocated":
            frac = jnp.where(alloc > 0, (alloc - used1_r[r]) / denom, 0.0)
        else:
            frac = jnp.where(alloc > 0, used1_r[r] / denom, 0.0)
        frac = jnp.clip(frac, 0.0, 1.0)
        if strategy in ("LeastAllocated", "MostAllocated"):
            s = jnp.floor(frac * np.float32(T2.MAX_NODE_SCORE))
        else:
            util = jnp.floor(frac * np.float32(100.0))
            s = T2.piecewise_interp_int(util, list(shape_x), list(shape_y))
        acc = acc + s * np.float32(w)
        wsum += w
    if wsum == 0:
        return acc
    return jnp.floor(acc / np.float32(wsum))


def _hi_lo_premasked(hi_in: jax.Array, lo_in: jax.Array):
    """(hi, lo) per row from caller-masked inputs (−inf/+inf at excluded
    nodes) — ONE variadic reduce kernel instead of two passes."""

    def comb(a, b):
        return jnp.maximum(a[0], b[0]), jnp.minimum(a[1], b[1])

    return jax.lax.reduce(
        (hi_in, lo_in),
        (np.float32(-np.inf), np.float32(np.inf)),
        comb,
        dimensions=(1,),
    )




def _expand_rows(rows: jax.Array, dom_oh_k: jax.Array) -> jax.Array:
    """[KT, Dcap] domain rows → [KT, N] node values: one-hot matmul against
    the per-wave node→domain one-hot (exact selection; rides the MXU —
    gathers serialize on TPU). PAD map entries have all-zero one-hots → 0."""
    return jnp.einsum("kd,knd->kn", rows, dom_oh_k, precision=_HI)


def class_masks(dc: DevCluster, d: Derived, st: V3Static, spec, rep_slots):
    """Per-chunk [C, N] taint/NA masks+raws for the toleration / NA
    equivalence classes (rep_slots: PodSlot of class representatives,
    gathered host-side at engine build). Computed ONCE per chunk."""
    tol_reps, na_reps = rep_slots
    out = {}
    # 0/1 masks are bf16-exact; the per-pod row reads (dynamic_index in the
    # wave step) then cost half the bytes. Raw score planes stay f32.
    if spec.taints and st.use_tol_classes:
        out["tol_ok"] = jax.vmap(lambda s: T2.taint_mask(dc, s))(tol_reps).astype(
            jnp.bfloat16
        )
        out["tol_raw"] = jax.vmap(lambda s: T2.taint_prefer_count(dc, s))(tol_reps)
    if spec.node_affinity and st.use_na_classes:
        out["na_ok"] = jax.vmap(lambda s: T2.node_affinity_mask(d, s))(na_reps).astype(
            jnp.bfloat16
        )
        out["na_raw"] = jax.vmap(lambda s: T2.node_affinity_score(d, s))(na_reps)
    return out


def make_wave_step3(
    dc: DevCluster, d: Derived, sh: Shared3, st: V3Static,
    wave_width: int, spec, cmasks=None, dyn: Optional[DynTables] = None,
    dyn_flip: bool = True, wvec=None,
):
    """Scan body over (PodSlot, SlotExtra) wave batches. Bit-identical to
    the v2 step; see module docstring for the traffic model. ``cmasks``:
    per-chunk class masks from :func:`class_masks`. ``dyn``: per-scenario
    DynTables for labels_dirty batches — base expansion tables stay
    shared; corrections apply as K-term fused elementwise updates.
    ``wvec``: optional traced policy vector (T2.POLICY_COLS) replacing the
    static score weights — the round 9 tuner's population axis; disables
    the packed select (its integer-weight bound needs static weights)."""
    cmasks = cmasks or {}
    G = st.G
    Dcap = st.Dcap
    o0, o1, o2, o3, o4, o5, o6 = st.sections
    w_cfg = dict(spec.weights)
    _w, _on = T2.policy_weight_fns(spec, wvec)
    kmask = kind_masks(st)
    # Bound-node domain vectors are only needed when some plane is carried.
    maintain_dom = st.maintain_mc or st.maintain_anti or st.maintain_pref
    # Single coarse spread constraint: its raw score takes one value per
    # domain (+ one for label-less nodes), so the normalize extrema reduce
    # over [Dcap+1] buckets instead of [N] nodes — with the taint row
    # statically gone (no PreferNoSchedule), the whole [S, K, N] hi/lo
    # pass disappears from Borg-shaped traces.
    spread_dom_hilo = bool(
        spec.spread and st.SP == 1 and not st.has_host_rows and dyn is None
    )
    Kdyn = dyn.ov_nodes.shape[0] if dyn is not None else 0
    # Node-space expansion of the domain rows ([S, KT, N] via the dom_oh
    # one-hot matmul) is only needed when some section actually consumes
    # node values: interpod sections, host planes, a real DoNotSchedule
    # spread filter, or the node-space spread scoring path. The Borg shape
    # (ScheduleAnyway-only spread, no interpod) statically skips it.
    need_vals = bool(
        st.A or st.B or st.MA or st.PA or st.MP
        or st.has_host_rows
        or (st.SP and (st.has_dns or not spread_dom_hilo))
    )
    pack_select = wvec is None and pack_select_ok(spec, w_cfg, dc.allocatable.shape[0])

    def wave_step(carry: DevState3, batch):
        sb, sx = batch
        N = dc.allocatable.shape[0]
        pre = build_wave_pre3(dc, d, sh, st, sb, sx, spec, dyn)

        # Wave-start reads (identical for every pod in the wave).
        if st.KT:
            lhs_c = pre.oh_row * pre.coarse_row[:, :, None]  # [W, KT, G]
            rows0 = (
                jnp.einsum("wkg,gd->wkd", lhs_c * kmask["mc"][None, :, None],
                           carry.mc_dom, precision=_HI)
                + jnp.einsum("wkg,gd->wkd", lhs_c * kmask["anti"][None, :, None],
                             carry.anti_dom, precision=_HI)
                + jnp.einsum("wkg,gd->wkd", lhs_c * kmask["pref"][None, :, None],
                             carry.pref_dom, precision=_HI)
            )  # [W, KT, Dcap]
            if st.has_host_rows:
                # One-hot LHS cast to the plane dtype: bf16×bf16 einsums
                # with f32 accumulation stay exact (0/1 × small ints).
                vals_h0 = jnp.zeros((wave_width, st.KT, N), jnp.float32)
                if len(st.mc_h_ids):
                    vals_h0 = vals_h0 + jnp.einsum(
                        "wkh,hn->wkn", pre.oh_mc_h.astype(carry.mc_host.dtype),
                        carry.mc_host, precision=_HI,
                        preferred_element_type=jnp.float32,
                    )
                if len(st.anti_h_ids):
                    vals_h0 = vals_h0 + jnp.einsum(
                        "wkh,hn->wkn", pre.oh_anti_h.astype(carry.anti_host.dtype),
                        carry.anti_host, precision=_HI,
                        preferred_element_type=jnp.float32,
                    )
                if len(st.pref_h_ids):
                    vals_h0 = vals_h0 + jnp.einsum(
                        "wkh,hn->wkn", pre.oh_pref_h, carry.pref_host, precision=_HI
                    )
            totals0 = jnp.einsum("wkg,g->wk", pre.oh_row, carry.match_total, precision=_HI)
            if need_vals:
                # Per-wave node→domain one-hot (scenario-shared) for expansion.
                dom_oh = (
                    pre.dmap[..., None] == jnp.arange(Dcap, dtype=jnp.float32)
                ).astype(jnp.float32)  # [W, KT, N, Dcap]
            if spread_dom_hilo and not st.seg_mode:
                # [W, N, Dcap+1]: spread-row domain one-hot + no-domain col
                # (built from dmap directly — dom_oh may be skipped).
                # seg_mode needs neither: domfeas rides the bit-OR reduce
                # and the score expansion is a tile/repeat.
                # bf16: 0/1 one-hots and the integer score values they meet
                # (≤ MAX_NODE_SCORE) are bf16-exact; accumulation stays f32
                # via preferred_element_type. Halves the dominant operand
                # traffic of both domain einsums.
                domoh2 = jnp.concatenate(
                    [
                        (
                            pre.dmap[:, o2][..., None]
                            == jnp.arange(Dcap, dtype=jnp.float32)
                        ).astype(jnp.bfloat16),
                        (pre.dmap[:, o2] < 0)[..., None].astype(jnp.bfloat16),
                    ],
                    axis=-1,
                )
            # #domains per row (for the domain-space spread min).
            nd_row = jnp.einsum(
                "wkg,g->wk", pre.oh_row, jnp.asarray(st.nd_g, jnp.float32),
                precision=_HI,
            )  # [W, KT]
        iota_n = jnp.arange(N)
        if Kdyn:
            # [K, N] override-node one-hots, built once per wave (f32: the
            # count deltas they meet are unbounded integers — bf16 would
            # round past 256).
            at_ov = (
                dyn.ov_nodes[:, None] == iota_n[None, :]
            ).astype(jnp.float32)
        R = carry.used.shape[0]
        if st.preemption:
            # Prefix-over-tiers stacks: [Tt+1, ...]; row t = aggregate over
            # tiers < t (wave-start values; in-wave corrections per pod).
            pfx_u = [jnp.zeros((R, N), jnp.float32)]
            pfx_n = [jnp.zeros((N,), jnp.float32)]
            mts = [jnp.full((N,), -1.0, jnp.float32)]
            for t in range(st.Tt):
                pfx_u.append(pfx_u[-1] + carry.used_tier[t])
                pfx_n.append(pfx_n[-1] + carry.npods_tier[t])
                mts.append(
                    jnp.maximum(mts[-1], jnp.where(carry.npods_tier[t] > 0, float(t), -1.0))
                )
            pfx_u = jnp.stack(pfx_u)  # [Tt+1, R, N]
            pfx_n = jnp.stack(pfx_n)  # [Tt+1, N]
            mts = jnp.stack(mts)  # [Tt+1, N]
            if FUSED_PREEMPT:
                # One packed [Tt+1, R+2, N] stack: each slot's tier gather
                # becomes a single dynamic read (rows [:R] usage, row R
                # pod counts, row R+1 max tier) instead of three. Pure
                # layout — every element is the same f32 value the
                # separate stacks hold.
                pfx_pack = jnp.concatenate(
                    [pfx_u, pfx_n[:, None, :], mts[:, None, :]], axis=1
                )
            preempted = jnp.zeros((), bool)
            ev_node = jnp.asarray(PAD, jnp.int32)
            ev_tier = jnp.zeros((), jnp.int32)
            ev_prior = jnp.zeros((), jnp.float32)
            ev_total = jnp.zeros((), jnp.float32)
            eu_acc = [jnp.zeros((), jnp.float32) for _ in range(R)]
            evicted = []  # per-slot "evicted mid-wave" flags
        choices, placeds, dom_ats = [], [], []
        for k in range(wave_width):
            s = jax.tree.map(lambda a: a[k], sb)

            # --- exact in-wave corrections from pods j<k -----------------
            # One-hots are rebuilt from the chosen-node index inside the
            # consuming fusions (never materialized as carried values).
            rows_corr = jnp.zeros((st.KT, Dcap), jnp.float32) if st.KT else None
            valh_corr = (
                jnp.zeros((st.KT, N), jnp.float32)
                if (st.KT and st.has_host_rows)
                else None
            )
            tot_corr = jnp.zeros((st.KT,), jnp.float32) if st.KT else None
            used_corr_r = [jnp.zeros((N,), jnp.float32) for _ in range(R)]
            if st.preemption and k > 0:
                # An earlier in-wave eviction frees wave-start usage at the
                # evicted node (evicted slots are excluded below).
                oh_e = (
                    preempted.astype(jnp.float32)
                    * (iota_n == ev_node).astype(jnp.float32)
                )
                for r in range(R):
                    used_corr_r[r] = used_corr_r[r] - eu_acc[r] * oh_e
            for j in range(k):
                wj = placeds[j].astype(jnp.float32)
                if st.preemption:
                    wj_used = wj * (1.0 - evicted[j].astype(jnp.float32))
                else:
                    wj_used = wj
                oh_j = (iota_n == choices[j]).astype(jnp.float32)
                for r in range(R):
                    used_corr_r[r] = used_corr_r[r] + wj_used * oh_j * sb.req[j, r]
                # Count corrections below keep evicted slots (phantom rule).
                if st.KT:
                    # domain of j's bound node under row (k, r)'s group
                    domat_r = jnp.einsum(
                        "g,rg->r", dom_ats[j], pre.oh_row[k], precision=_HI
                    )  # [KT]
                    ovr = pre.ov[j, k] * pre.coarse_row[k]  # [KT]
                    oh_d = (
                        domat_r[:, None] == jnp.arange(Dcap, dtype=jnp.float32)
                    ).astype(jnp.float32)
                    rows_corr = rows_corr + (wj * ovr)[:, None] * oh_d
                    if st.has_host_rows:
                        ovh = (
                            wj
                            * pre.ov[j, k]
                            * (1.0 - pre.coarse_row[k])
                            * (pre.row_g[k] >= 0)
                            * (domat_r >= 0)
                        )
                        # Domain-equality form: credits every node sharing
                        # the bound node's domain (== the bound node alone
                        # for singleton/hostname topologies).
                        valh_corr = valh_corr + ovh[:, None] * (
                            pre.dmap[k] == domat_r[:, None]
                        )
                    tot_corr = tot_corr + wj * pre.ov[j, k] * kmask["mc"] * (
                        domat_r >= 0
                    )

            # --- fused Filter + Score (bit-identical to v2) --------------
            # used1_r = per-resource used-after-this-pod planes, shared by
            # the fit mask and every fit scoring strategy.
            used1_r = [
                carry.used[r] + used_corr_r[r] + s.req[r] for r in range(R)
            ]
            alloc_r = [dc.allocatable[:, r] for r in range(R)]
            # Non-fit filters tracked separately: preemption candidacy
            # reuses them with the fit check replaced by fit-after-evict.
            feasible = jnp.ones(N, bool)
            if spec.fit:
                for r in range(R):
                    feasible = feasible & (used1_r[r] <= alloc_r[r] + 1e-6)
            fit_ok = feasible
            nonfit = jnp.ones(N, bool)
            if spec.taints:
                if st.use_tol_classes:
                    # Row select by class id — a dynamic slice reads ONE
                    # [N] row. (The old one-hot einsum contracted the whole
                    # [C, N] plane per pod: 40% of device time on the
                    # north-star profile.) Values identical: one-hot × f32
                    # picked the same row exactly.
                    tok_k = (
                        jax.lax.dynamic_index_in_dim(
                            cmasks["tol_ok"], sx.tol_class[k], 0, keepdims=False
                        )
                        > 0.5
                    )
                    traw_k = jax.lax.dynamic_index_in_dim(
                        cmasks["tol_raw"], sx.tol_class[k], 0, keepdims=False
                    )
                else:
                    tok_k, traw_k = pre.taint_ok[k], pre.taint_raw[k]
                nonfit = nonfit & tok_k
            if spec.node_affinity:
                if st.use_na_classes:
                    naok_k = (
                        jax.lax.dynamic_index_in_dim(
                            cmasks["na_ok"], sx.na_class[k], 0, keepdims=False
                        )
                        > 0.5
                    )
                    naraw_k = jax.lax.dynamic_index_in_dim(
                        cmasks["na_raw"], sx.na_class[k], 0, keepdims=False
                    )
                else:
                    naok_k, naraw_k = pre.na_ok[k], pre.na_raw[k]
                nonfit = nonfit & naok_k

            # Materialize `feasible` once: it feeds several reduce-rooted
            # kernels (domfeas, select). used1_r stays UN-materialized since
            # round 3 — its two consumers (the feasible fusion and the
            # select reduce's fit score) each re-derive it from carry.used
            # at the same read cost, and skipping the barrier removes the
            # R×[S, N] write per pod (~14% of device time on the profile).
            # Preemption still materializes (prefit re-reads used1_r).
            if st.preemption:
                used1_r = list(jax.lax.optimization_barrier(tuple(used1_r)))
            feasible = jax.lax.optimization_barrier(feasible)
            if st.KT:
                rows_k = rows0[k] + rows_corr  # [KT, Dcap]
                totals = totals0[k] + tot_corr
                if need_vals:
                    vals = _expand_rows(rows_k, dom_oh[k])
                    if st.has_host_rows:
                        vals = vals + vals_h0[k] + valh_corr
                    gvalid = pre.dmap[k] >= 0  # [KT, N]
                    if Kdyn:
                        # labels_dirty: corrections on top of the BASE
                        # expansion — for each perturbed node, swap in
                        # rows_k at its new domain and its new validity.
                        # PAD ids give all-zero one-hots. ONE [2KT, K] ×
                        # [K, N] matmul carries both the value deltas and
                        # the validity flips (a per-j Python loop fused
                        # badly: 1.8× on the config-3 dirty batch).
                        arange_d = jnp.arange(Dcap, dtype=jnp.float32)
                        ohn = (
                            pre.ov_new_row[k][..., None] == arange_d
                        ).astype(jnp.float32)  # [KT, K, Dcap]
                        oho = (
                            pre.ov_old_row[k][..., None] == arange_d
                        ).astype(jnp.float32)
                        newv = jnp.einsum("rjd,rd->rj", ohn, rows_k, precision=_HI)
                        oldv = jnp.einsum("rjd,rd->rj", oho, rows_k, precision=_HI)
                        delta = newv - oldv  # [KT, K]
                        if dyn_flip:
                            flip = (
                                (pre.ov_new_row[k] >= 0)
                                != (pre.ov_old_row[k] >= 0)
                            ).astype(jnp.float32)  # [KT, K]
                            corr = jnp.einsum(
                                "rj,jn->rn",
                                jnp.concatenate([delta, flip], axis=0),
                                at_ov,
                                precision=_HI,
                            )  # [2·KT, N]
                            vals = vals + corr[: st.KT]
                            gvalid = gvalid != (corr[st.KT :] > 0.5)
                        else:
                            # No key-presence changes in the whole batch:
                            # validity is untouched, only values shift.
                            corr = jnp.einsum(
                                "rj,jn->rn", delta, at_ov, precision=_HI
                            )
                            vals = vals + corr

            if spec.interpod and st.A:
                cnt = vals[o0:o1]
                term_ok = (cnt >= 1) & gvalid[o0:o1]
                boot = (totals[o0:o1] == 0) & pre.aff_selfm[k]
                valid = (pre.row_g[k, o0:o1] >= 0)[:, None]
                nonfit = nonfit & jnp.all(
                    jnp.where(valid, term_ok | boot[:, None], True), axis=0
                )
            if spec.interpod and st.B:
                viol = (vals[o1:o2] >= 1) & gvalid[o1:o2]
                valid = (pre.row_g[k, o1:o2] >= 0)[:, None]
                nonfit = nonfit & jnp.all(jnp.where(valid, ~viol, True), axis=0)
            if spec.interpod and st.MA:
                blocked = jnp.sum(vals[o4:o5], axis=0) > 0.5
                nonfit = nonfit & ~blocked
            if spec.spread and st.SP and st.has_dns:
                cnts = vals[o2:o3]
                gval = gvalid[o2:o3]
                # Min over domains — every existing domain has ≥1 node, so
                # min over valid domains == min over gvalid nodes. Coarse
                # rows reduce over [Dcap] (tiny); host rows (domain≈node)
                # need the node-space min.
                dval = (
                    pre.dex_row[k]
                    if dyn is not None
                    else (
                        jnp.arange(Dcap, dtype=jnp.float32)[None, :]
                        < nd_row[k, o2:o3][:, None]
                    )
                )  # [SP, Dcap]
                minv_dom = jnp.min(
                    jnp.where(dval, rows_k[o2:o3], jnp.inf), axis=1
                )
                if st.has_host_rows:
                    minv_node = jnp.min(jnp.where(gval, cnts, jnp.inf), axis=1)
                    minv = jnp.where(
                        pre.coarse_row[k, o2:o3] > 0.5, minv_dom, minv_node
                    )
                else:
                    minv = minv_dom
                has = jnp.isfinite(minv)
                c_ok = (
                    gval
                    & has[:, None]
                    & (cnts + pre.sp_selfm[k][:, None]
                       - jnp.where(has, minv, 0.0)[:, None]
                       <= pre.sp_skew[k][:, None])
                )
                nonfit = nonfit & jnp.all(
                    jnp.where(pre.sp_dns[k][:, None], c_ok, True), axis=0
                )

            feasible = fit_ok & nonfit
            any_f = None  # derived from the hi reduce when rows exist
            total = jnp.zeros(N, jnp.float32)
            if spec.fit and _on("NodeResourcesFit"):
                rw = np.asarray(spec.resource_weights, dtype=np.float32)
                if wvec is not None and spec.fit_strategy in (
                    "LeastAllocated", "MostAllocated"
                ):
                    raw = jnp.where(
                        wvec[T2.IDX_FIT_LEAST] > 0.5,
                        _fit_score_r(used1_r, alloc_r, rw, "LeastAllocated",
                                     spec.shape_x, spec.shape_y),
                        _fit_score_r(used1_r, alloc_r, rw, "MostAllocated",
                                     spec.shape_x, spec.shape_y),
                    )
                else:
                    raw = _fit_score_r(
                        used1_r, alloc_r, rw, spec.fit_strategy,
                        spec.shape_x, spec.shape_y,
                    )
                total = total + _w("NodeResourcesFit") * raw
            rows_n = []
            if spec.taints and spec.taint_score and _on("TaintToleration"):
                rows_n.append((traw_k, _w("TaintToleration"), False, True))
            if spec.node_affinity and _on("NodeAffinity"):
                rows_n.append((naraw_k, _w("NodeAffinity"), False, False))
            if spec.interpod and _on("InterPodAffinity"):
                raw = jnp.zeros(dc.allocatable.shape[0], jnp.float32)
                if st.PA:
                    raw = raw + jnp.einsum(
                        "p,pn->n", pre.row_w[k, o3:o4], vals[o3:o4], precision=_HI
                    )
                if st.MP:
                    raw = raw + jnp.sum(vals[o5:o6], axis=0)
                rows_n.append((raw, _w("InterPodAffinity"), True, False))
            sp_pack = None
            if (
                spec.spread
                and _on("PodTopologySpread")
                and st.SP
                and not spread_dom_hilo
            ):
                # Upstream scoring raw + ignored mask; extrema ride the
                # shared stacked reduce as an extra ±inf-pre-masked row.
                cnts = vals[o2:o3]
                gval = gvalid[o2:o3]
                raw_sp = jnp.zeros(N, jnp.float32)
                sp_ign = jnp.zeros(N, bool)
                for i in range(st.SP):
                    contrib = cnts[i] * pre.sp_w[k, i] + (
                        pre.sp_skew[k, i] - 1.0
                    )
                    raw_sp = raw_sp + jnp.where(
                        pre.sp_scored[k, i], contrib, 0.0
                    )
                    sp_ign = sp_ign | (pre.sp_scored[k, i] & ~gval[i])
                sp_pack = (jnp.floor(raw_sp + 0.5), sp_ign)
            if rows_n or sp_pack is not None:
                hi_rows = [jnp.where(feasible, r[0], -jnp.inf) for r in rows_n]
                lo_rows = [jnp.where(feasible, r[0], jnp.inf) for r in rows_n]
                if sp_pack is not None:
                    # Spread extrema run over feasible & ~ignored: its row
                    # is pre-masked with its own validity, then rides the
                    # same variadic reduce as the other score rows.
                    okn = feasible & ~sp_pack[1]
                    hi_rows.append(jnp.where(okn, sp_pack[0], -jnp.inf))
                    lo_rows.append(jnp.where(okn, sp_pack[0], jnp.inf))
                hi, lo = _hi_lo_premasked(
                    jnp.stack(hi_rows), jnp.stack(lo_rows)
                )
                # hi > -inf ⟺ some node is feasible: any() comes free.
                any_f = (
                    hi[0] > -jnp.inf if rows_n else jnp.any(feasible)
                )
                for i, (raw, wt, minmax, reverse) in enumerate(rows_n):
                    total = total + wt * _normalize_row(
                        raw, lo[i], hi[i], any_f, minmax, reverse
                    )
                if sp_pack is not None:
                    total = total + _w(
                        "PodTopologySpread"
                    ) * T2.spread_norm_from_extrema(
                        sp_pack[0], sp_pack[1], hi[-1], lo[-1],
                        jnp.any(pre.sp_scored[k]),
                        getattr(spec, "sp_norm_f32", False),
                    )
            else:
                any_f = None
            if (
                spec.spread
                and _on("PodTopologySpread")
                and st.SP
                and spread_dom_hilo
            ):
                # Upstream scoring ([K8S] scoring.go): cnt·log(size+2) +
                # (maxSkew−1), rounded, two-pass integer normalize.
                wt = _w("PodTopologySpread")
                # Domain-space form (SP == 1, coarse row): raw takes one
                # value per existing domain; label-less nodes are the
                # ignored set (the extra bucket), excluded from extrema
                # and normalized to 0.
                scored0 = pre.sp_scored[k, 0]
                raw_d = jnp.floor(
                    rows_k[o2] * pre.sp_w[k, 0] + (pre.sp_skew[k, 0] - 1.0) + 0.5
                )  # [Dcap] — floor(x+0.5) = upstream math.Round, x ≥ 0
                dval = (
                    jnp.arange(Dcap, dtype=jnp.float32) < nd_row[k, o2]
                )  # existing domains
                if st.seg_mode:
                    # Structured layout: per-domain feasibility via ONE
                    # full-width bitwise-OR reduce of (1 << dom(n)) — a
                    # lane-efficient [N]→scalar reduce (the reshape-any
                    # form reduced over the 8-wide minor axis at ~6% lane
                    # utilization; the one-hot matmul before it was ~12%
                    # of device time). Exact: for a PAD spread row the
                    # downstream out_d is masked to 0 by sp_scored either
                    # way, and any(domfeas) still equals any(feasible) —
                    # every node carries a domain under the pattern.
                    if st.seg_D <= 31:
                        # Bit-pack: per-domain feasibility in int32 bits.
                        if st.seg_mode == "stride":
                            dom_i = iota_n % st.seg_D
                        else:
                            dom_i = iota_n // (N // st.seg_D)
                        word = jax.lax.reduce(
                            jnp.where(
                                feasible,
                                jnp.left_shift(np.int32(1), dom_i),
                                np.int32(0),
                            ),
                            np.int32(0),
                            jax.lax.bitwise_or,
                            (0,),
                        )
                        core = (
                            jnp.right_shift(word, jnp.arange(st.seg_D)) & 1
                        ) > 0  # [D]
                    elif st.seg_mode == "stride":
                        # 32..Dcap domains: reshape-any (still cheaper
                        # than the [N, Dcap+1] one-hot einsum).
                        core = jnp.any(feasible.reshape(-1, st.seg_D), axis=0)
                    else:
                        core = jnp.any(feasible.reshape(st.seg_D, -1), axis=1)
                    domfeas = jnp.concatenate(
                        [core, jnp.zeros(Dcap + 1 - st.seg_D, bool)]
                    )
                else:
                    domfeas = (
                        jnp.einsum(
                            "n,nd->d", feasible.astype(jnp.bfloat16), domoh2[k],
                            precision=_HI, preferred_element_type=jnp.float32,
                        )
                        > 0.5
                    )  # [Dcap+1]
                okd = dval & domfeas[:Dcap]
                hi_sp = jnp.max(jnp.where(okd, raw_d, -jnp.inf))
                lo_sp = jnp.min(jnp.where(okd, raw_d, jnp.inf))
                has = hi_sp > -jnp.inf
                hi_i = jnp.where(has, hi_sp, 0.0).astype(jnp.int32)
                lo_i = jnp.where(has, lo_sp, 0.0).astype(jnp.int32)
                vals_d = (
                    np.int32(T2.MAX_NODE_SCORE)
                    * (hi_i + lo_i - raw_d.astype(jnp.int32))
                ) // jnp.where(hi_i > 0, hi_i, 1)
                out_d = jnp.where(
                    hi_i > 0,
                    vals_d.astype(jnp.float32),
                    np.float32(T2.MAX_NODE_SCORE),
                )
                out_d = jnp.where(dval & has & scored0, out_d, 0.0)
                if st.seg_mode == "stride":
                    # dom(n) = n % D: the expansion out_d[dom(n)] is a pure
                    # tile — no [N, D] one-hot read at all (the expansion
                    # dot was the single largest op after round-3's other
                    # cuts). PAD spread rows have out_d ≡ 0 → tile of 0.
                    out = jnp.tile(out_d[: st.seg_D], N // st.seg_D)
                elif st.seg_mode == "block":
                    out = jnp.repeat(out_d[: st.seg_D], N // st.seg_D)
                else:
                    # out_d holds integer scores in [0, 100] — bf16-exact.
                    out = jnp.einsum(
                        "nd,d->n",
                        domoh2[k][:, :Dcap],
                        out_d.astype(jnp.bfloat16),
                        precision=_HI, preferred_element_type=jnp.float32,
                    )
                if any_f is None:
                    any_f = jnp.any(domfeas)
                total = total + wt * out
            if any_f is None:
                any_f = jnp.any(feasible)

            if pack_select:
                node, _ = T2.select_node_packed(total, feasible)
            else:
                node, _ = select_node(total, feasible)
            placed = any_f & s.valid
            if st.preemption:
                tier_k = sx.tier[k]  # shared scalar
                if FUSED_PREEMPT:
                    pk = jax.lax.dynamic_index_in_dim(
                        pfx_pack, tier_k, axis=0, keepdims=False
                    )  # [R+2, N] packed lower-tier aggregates (wave start)
                    lt_u = pk[:R]  # [R, N] usage of tiers < tier_k
                    lt_np = pk[R]
                    mt0 = pk[R + 1]
                else:
                    lt_u = jax.lax.dynamic_index_in_dim(
                        pfx_u, tier_k, axis=0, keepdims=False
                    )  # [R, N] usage of tiers < tier_k (wave start)
                    lt_np = jax.lax.dynamic_index_in_dim(
                        pfx_n, tier_k, 0, False
                    )
                    mt0 = jax.lax.dynamic_index_in_dim(mts, tier_k, 0, False)
                lt_u_eff = [lt_u[r] for r in range(R)]
                lt_np_eff = lt_np
                mt_eff = mt0
                for j in range(k):
                    lowmask = (
                        placeds[j].astype(jnp.float32)
                        * (sx.tier[j] < tier_k).astype(jnp.float32)
                        * (sb.group[j] == PAD).astype(jnp.float32)
                    )
                    oh_j = lowmask * (iota_n == choices[j]).astype(jnp.float32)
                    for r in range(R):
                        lt_u_eff[r] = lt_u_eff[r] + oh_j * sb.req[j, r]
                    lt_np_eff = lt_np_eff + oh_j
                    mt_eff = jnp.maximum(
                        mt_eff, jnp.where(oh_j > 0, sx.tier[j].astype(jnp.float32), -1.0)
                    )
                prefit = jnp.ones(N, bool)
                for r in range(R):
                    prefit = prefit & (
                        used1_r[r] - lt_u_eff[r] <= alloc_r[r] + 1e-6
                    )
                cand = (
                    prefit
                    & nonfit
                    & (lt_np_eff >= 1)
                    & ~preempted
                    & ~any_f
                    & s.valid
                    & (s.group == PAD)
                    & (tier_k > 0)
                )
                # Rank (fewest victims, lowest max victim tier, lowest
                # index) — exact small ints in f32; mirrors sim.greedy.
                score = lt_np_eff * np.float32(1024.0) + mt_eff
                if FUSED_PREEMPT:
                    # One variadic reduce for (victim node, any candidate)
                    # — selection identical to the argmax + any pair.
                    pnode, p_ok = T2.masked_argmin(score, cand)
                else:
                    pnode = jnp.argmax(
                        jnp.where(cand, -score, -jnp.inf)
                    ).astype(jnp.int32)
                    p_ok = jnp.any(cand)
                evict_k = p_ok & ~any_f & s.valid
                node = jnp.where(evict_k, pnode, node)
                placed = placed | evict_k
                oh_p = evict_k.astype(jnp.float32) * (iota_n == node).astype(jnp.float32)
                for r in range(R):
                    eu_acc[r] = jnp.where(
                        evict_k, jnp.sum(lt_u[r] * oh_p), eu_acc[r]
                    )
                ev_prior = jnp.where(evict_k, jnp.sum(lt_np * oh_p), ev_prior)
                ev_total = jnp.where(evict_k, jnp.sum(lt_np_eff * oh_p), ev_total)
                ev_node = jnp.where(evict_k, node, ev_node)
                ev_tier = jnp.where(evict_k, tier_k, ev_tier)
                preempted = preempted | evict_k
                # Mark lower-tier non-gang slots already bound there evicted.
                for j in range(k):
                    evicted[j] = evicted[j] | (
                        evict_k
                        & (choices[j] == node)
                        & placeds[j]
                        & (sx.tier[j] < tier_k)
                        & (sb.group[j] == PAD)
                    )
                evicted.append(jnp.zeros((), bool))
            if maintain_dom:
                if st.single_topo and dyn is None:
                    # Every domain-bearing group shares ONE topology: the
                    # bound node's domain is a single dynamic read of the
                    # shared [N] map, broadcast over groups — instead of an
                    # einsum streaming the whole [G, N] table per pod.
                    dom1 = jax.lax.dynamic_index_in_dim(
                        sh.topo1_f, jnp.clip(node, 0), 0, keepdims=False
                    )
                    dom_at = jnp.where(
                        placed & (sh.has_dom_g > 0.5), dom1, float(PAD)
                    )
                else:
                    oh_n = ((iota_n == node) & (node >= 0)).astype(jnp.float32)
                    dom_at = jnp.einsum("gn,n->g", sh.gdom_f, oh_n, precision=_HI)
                    for j in range(Kdyn):
                        # Perturbed node bound: its per-group domain is the
                        # override (== base where that topology unchanged).
                        dom_at = jnp.where(
                            node == dyn.ov_nodes[j], dyn.ov_gdom[:, j], dom_at
                        )
                    # A miss (or padded slot) must not look like domain 0.
                    dom_at = jnp.where(placed, dom_at, float(PAD))
                dom_ats.append(dom_at)
            choices.append(node)
            placeds.append(placed)

        choice = jnp.stack(choices)  # [W]
        placed = jnp.stack(placeds)  # [W]
        if st.has_gangs:
            groups = sb.group
            same = (groups[:, None] == groups[None, :]) & (groups[:, None] >= 0)
            fail = jnp.any(same & ~placed[None, :], axis=1)
            commit = placed & ~fail
        else:
            commit = placed
        if st.preemption:
            evicted_w = jnp.stack(evicted)  # [W]
            # Phantom rule: counts commit for evicted slots too; usage and
            # the reported placement do not.
            commit_used = commit & ~evicted_w
        else:
            commit_used = commit
        final = jnp.where(commit_used, choice, PAD).astype(jnp.int32)

        # --- wave-end commit (gang rollback folded into the mask) --------
        wv = commit.astype(jnp.float32)  # [W]
        wv_used = commit_used.astype(jnp.float32)  # [W]
        # One-hots rebuilt from chosen-node indices, bf16 operands: exact
        # (0/1 values), half the einsum traffic of stacked f32 planes. Only
        # the host-plane / tier commits still consume them — the `used`
        # update itself is an unrolled elementwise add since round 3 (the
        # [W, N]×[W, R] dot emitted layout copies around the carry that
        # cost more than the dot; same f32 sum of the same multiset).
        need_oh_all = st.preemption or st.has_host_rows
        if need_oh_all:
            oh_all = (
                (iota_n[None, :] == choice[:, None]) & (choice[:, None] >= 0)
            ).astype(jnp.bfloat16)  # [W, N]
        if st.preemption:
            used = carry.used + jnp.einsum(
                "w,wn,wr->rn", wv_used, oh_all, sb.req,
                precision=_HI, preferred_element_type=jnp.float32,
            )
        else:
            coefs = wv_used[:, None] * sb.req  # [W, R] tiny
            rows_u = []
            for r in range(R):
                acc = carry.used[r]
                for w in range(wave_width):
                    acc = acc + jnp.where(
                        iota_n == choice[w], coefs[w, r], 0.0
                    )
                rows_u.append(acc)
            used = jnp.stack(rows_u)
        used_tier, npods_tier = carry.used_tier, carry.npods_tier
        if st.preemption:
            # Eviction: free the wave-start lower-tier usage at the node.
            oh_e = (
                preempted.astype(jnp.float32)
                * (iota_n == ev_node).astype(jnp.float32)
            )  # [N]
            used = used - jnp.stack([eu_acc[r] * oh_e for r in range(R)])
            nong = (sb.group == PAD).astype(jnp.float32)  # [W]
            tiers_w = sx.tier  # [W] shared
            if st.Tt and FUSED_PREEMPT:
                # Batched tier commit: one [Tt, W] slot-weight one-hot and
                # two einsums replace the per-tier Python loop (Tt× fewer
                # passes over the [W, N] placement one-hot). Each
                # (t, ·, n) output still reduces the SAME summands over w
                # — bit-parity with the loop form.
                wt_all = (
                    wv_used[None, :]
                    * nong[None, :]
                    * (
                        tiers_w[None, :] == jnp.arange(st.Tt)[:, None]
                    ).astype(jnp.float32)
                )  # [Tt, W]
                du_all = jnp.einsum(
                    "tw,wn,wr->trn", wt_all, oh_all, sb.req,
                    precision=_HI, preferred_element_type=jnp.float32,
                )
                dn_all = jnp.einsum(
                    "tw,wn->tn", wt_all, oh_all,
                    precision=_HI, preferred_element_type=jnp.float32,
                )
                zmask_all = (
                    preempted & (jnp.arange(st.Tt) < ev_tier)
                ).astype(jnp.float32)[:, None] * (
                    iota_n == ev_node
                ).astype(jnp.float32)[None, :]  # [Tt, N]
                used_tier = (
                    carry.used_tier * (1.0 - zmask_all)[:, None, :] + du_all
                )
                npods_tier = carry.npods_tier * (1.0 - zmask_all) + dn_all
            elif st.Tt:
                new_ut, new_np = [], []
                for t in range(st.Tt):
                    zmask = (
                        preempted & (jnp.asarray(t) < ev_tier)
                    ).astype(jnp.float32) * (
                        iota_n == ev_node
                    ).astype(jnp.float32)
                    w_t = wv_used * nong * (tiers_w == t).astype(jnp.float32)
                    du = jnp.einsum(
                        "w,wn,wr->rn", w_t, oh_all, sb.req,
                        precision=_HI, preferred_element_type=jnp.float32,
                    )
                    dn = jnp.einsum(
                        "w,wn->n", w_t, oh_all,
                        precision=_HI, preferred_element_type=jnp.float32,
                    )
                    new_ut.append(
                        carry.used_tier[t] * (1.0 - zmask)[None, :] + du
                    )
                    new_np.append(carry.npods_tier[t] * (1.0 - zmask) + dn)
                used_tier = jnp.stack(new_ut)
                npods_tier = jnp.stack(new_np)
        mc_dom, anti_dom, pref_dom = carry.mc_dom, carry.anti_dom, carry.pref_dom
        mc_host, anti_host, pref_host = carry.mc_host, carry.anti_host, carry.pref_host
        match_total = carry.match_total
        if maintain_dom:
            dom_all = jnp.stack(dom_ats)  # [W, G]
            oh_dom = (
                dom_all[:, :, None] == jnp.arange(Dcap, dtype=jnp.float32)
            ).astype(jnp.float32)  # [W, G, Dcap]
            cf = sh.coarse_f[None, :]

            def dom_commit(plane, vec):
                return plane + jnp.einsum(
                    "w,wg,wgd->gd", wv, vec * cf, oh_dom, precision=_HI
                )

            if st.maintain_mc:
                mc_dom = dom_commit(carry.mc_dom, pre.pmg_f)
            if st.maintain_anti:
                anti_dom = dom_commit(carry.anti_dom, pre.anti_g)
            if st.maintain_pref:
                pref_dom = dom_commit(carry.pref_dom, pre.pref_g)
            if st.A:
                has_dom = (dom_all >= 0).astype(jnp.float32)  # [W, G]
                match_total = carry.match_total + jnp.einsum(
                    "w,wg->g", wv, pre.pmg_f * has_dom, precision=_HI
                )

        def host_commit(plane, vec, ids):
            vh = vec[:, jnp.asarray(ids)]  # [W, H]
            if st.single_g[ids].all():
                # Singleton domains (hostname): the bound node IS the domain
                # — but only when it actually carries the topology label
                # (v2's node_has_dom gate; a partially-labeled topology must
                # not credit label-less nodes).
                has_dom_h = (
                    jnp.stack(dom_ats)[:, jnp.asarray(ids)] >= 0
                ).astype(jnp.float32)  # [W, H]
                delta = jnp.einsum(
                    "w,wh,wn->hn", wv, vh * has_dom_h, oh_all,
                    precision=_HI, preferred_element_type=jnp.float32,
                )
                # Cast back to the carry dtype: bf16 planes hold small
                # integers, exact through the add.
                return (plane.astype(jnp.float32) + delta).astype(plane.dtype)
            # General path: credit every node in the bound node's domain.
            gdom_h = sh.gdom_f[jnp.asarray(ids)]  # [H, N] (static row select)
            dom_at_h = jnp.stack(dom_ats)[:, jnp.asarray(ids)]  # [W, H]
            for w in range(wave_width):
                sel = (
                    (gdom_h == dom_at_h[w][:, None]) & (dom_at_h[w] >= 0)[:, None]
                ).astype(jnp.float32)
                plane = plane + (wv[w] * vh[w])[:, None] * sel
            return plane

        if len(st.mc_h_ids):
            mc_host = host_commit(carry.mc_host, pre.pmg_f, st.mc_h_ids)
        if len(st.anti_h_ids):
            anti_host = host_commit(carry.anti_host, pre.anti_g, st.anti_h_ids)
        if len(st.pref_h_ids):
            pref_host = host_commit(carry.pref_host, pre.pref_g, st.pref_h_ids)
        new_state = DevState3(
            used=used, mc_dom=mc_dom, anti_dom=anti_dom, pref_dom=pref_dom,
            mc_host=mc_host, anti_host=anti_host, pref_host=pref_host,
            match_total=match_total, used_tier=used_tier, npods_tier=npods_tier,
        )
        if st.preemption:
            # Eviction event for the host fix-up walk: victims from PRIOR
            # waves (ev_prior) are reconstructed deterministically from the
            # choice log; in-wave victims are already PAD in `final`.
            return new_state, (
                final, ev_node, ev_tier,
                ev_prior.astype(jnp.int32), ev_total.astype(jnp.int32),
            )
        return new_state, final

    return wave_step


def pack_select_ok(spec, w_cfg, n_nodes: int) -> bool:
    """Static gate for ops.tpu.select_node_packed (see its exactness
    bounds): integer non-negative weights on every ACTIVE score row keep
    the total an integer ≤ 100·Σw, so (total, node) packs exactly into
    f32 when 100·Σw ≤ PACK_MAX_TOTAL and N ≤ PACK_MAX_NODES."""
    w_active = [
        w_cfg.get(name, 1.0)
        for name, on in (
            ("NodeResourcesFit", spec.fit),
            ("TaintToleration", spec.taints and spec.taint_score),
            ("NodeAffinity", spec.node_affinity),
            ("InterPodAffinity", spec.interpod),
            ("PodTopologySpread", spec.spread),
        )
        if on and w_cfg.get(name, 1.0) != 0
    ]
    return (
        n_nodes <= T2.PACK_MAX_NODES
        and all(float(w).is_integer() and w >= 0 for w in w_active)
        and 100.0 * sum(w_active) <= T2.PACK_MAX_TOTAL
    )


def kind_masks(st: V3Static):
    """[KT] static 0/1 row masks by plane kind (mc/anti/pref sections)."""
    o0, o1, o2, o3, o4, o5, o6 = st.sections
    mc = np.zeros(st.KT, np.float32)
    mc[:o4] = 1.0
    anti = np.zeros(st.KT, np.float32)
    anti[o4:o5] = 1.0
    pref = np.zeros(st.KT, np.float32)
    pref[o5:o6] = 1.0
    return {
        "mc": jnp.asarray(mc),
        "anti": jnp.asarray(anti),
        "pref": jnp.asarray(pref),
    }
