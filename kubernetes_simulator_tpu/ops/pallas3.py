"""v4 engine: a whole chunk of waves as ONE Pallas kernel (coarse shapes).

The v3 wave scan is HBM-bound: every per-pod XLA op re-reads its [S, N]
operand planes from HBM — ~240 plane passes per 8-pod wave on the Borg
north-star shape (10k nodes), which at v5e HBM bandwidth IS the wall
clock (~183s of pure traffic for 10k×1M×128). This kernel keeps the
mutable state resident in VMEM for an entire chunk and streams only the
tiny slot data, leaving the VPU work as the bound.

Design notes (learned the slow way — the first cut was scalar-heavy and
LOST to v3 by 2.8×):
- Slot scalars live in SMEM (scalar-prefetch style BlockSpecs): VMEM
  vector→scalar extracts cost ~100 cycles each through memory.
- Spread counts are read from a node-space plane ``mc_node [G, Np]``
  derived IN-KERNEL from the carried ``mc_dom [G, Dcap]`` once per chunk,
  so the per-pod read is a plain row — no per-domain gathers.
- The per-pod blocks that most pods don't need (spread constraint, match
  -group updates, gang revert) are predicated with ``pl.when``.
- Everything vector-wise is lane-oriented; the only transposes are tiny
  (1, G) → (G, 1) columns guarded behind the same predicates.

Scope (static gate, :func:`eligible`): NodeResourcesFit (LeastAllocated)
+ TaintToleration via toleration classes (no PreferNoSchedule scoring) +
PodTopologySpread with at most ONE coarse constraint per pod (no host
rows), no InterPodAffinity / NodeAffinity terms, no preemption. Gangs
ARE handled (wave-deferred commit, in-kernel revert). Anything else
falls back to v3.

Parity: semantics mirror sim.greedy.greedy_replay (the anchor) — pod k
sees speculative binds of j<k, wave-end gang rollback, lowest-index
argmax tie-break, ops.tpu's exact LeastAllocated floor chain, and the
upstream spread scoring (node-space extrema are exactly the dom_hilo
extrema: every existing domain with a feasible node is represented).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..models.encode import PAD

MAX_NODE_SCORE = 100.0
_NEG = np.float32(-3.0e38)  # masked (base-infeasible) score
_SPREAD_BLOCK = np.float32(1.0e30)  # DoNotSchedule / missing-key penalty

MAX_DCAP = 128
MAX_G = 32
MAX_TOPO = 4


def eligible(st, spec, ec=None) -> bool:
    """Static shape gate for the v4 kernel (see module docstring)."""
    if ec is not None:
        gt = np.asarray(ec.group_topo[: st.G])
        if len({int(t) for t in gt if t >= 0}) > MAX_TOPO:
            return False
    return bool(
        not st.preemption
        and not st.has_host_rows
        and st.A == 0 and st.B == 0 and st.PA == 0
        and st.MA == 0 and st.MP == 0
        and st.SP <= 1
        and not spec.node_affinity
        and (not spec.taints or st.use_tol_classes)
        and not spec.taint_score
        and spec.fit and spec.fit_strategy == "LeastAllocated"
        and st.Dcap <= MAX_DCAP
        and st.G <= MAX_G
    )


class V4Static(NamedTuple):
    C: int  # waves per chunk
    W: int  # wave width
    R: int  # resources
    G: int  # match groups
    Dcap: int  # max coarse domains
    T: int  # distinct (referenced) topologies
    Ct: int  # toleration classes
    Np: int  # padded node count
    N: int  # real node count
    gdom_t: np.ndarray  # [T, Np] f32 node→domain per topology (PAD=-1)
    topo_of_g: tuple  # [G] static topology slot per group (-1 = none)
    sp_topo_slot: np.ndarray  # [G] per-group topology slot
    w_tab: np.ndarray  # [G] f32 spread weights log(size+2)
    nd_g: np.ndarray  # [G] domains per group


def build_v4_static(ec, st, chunk_waves: int, wave_width: int) -> V4Static:
    N = ec.num_nodes
    Np = ((N + 255) // 256) * 256
    G = st.G
    gt = np.asarray(ec.group_topo[:G])
    topos = sorted({int(t) for t in gt if t >= 0})
    assert len(topos) <= MAX_TOPO, "v4 gate should have rejected this"
    tslot = {t: i for i, t in enumerate(topos)}
    T = max(len(topos), 1)
    gdom_t = np.full((T, Np), float(PAD), np.float32)
    for t, i in tslot.items():
        gdom_t[i, :N] = ec.node_domain[t].astype(np.float32)
    topo_of_g = tuple(tslot.get(int(t), -1) for t in gt)
    w_tab = np.log(np.asarray(st.nd_g, np.float64) + 2.0).astype(np.float32)
    Ct = max(len(st.tol_rep), 1) if st.use_tol_classes else 1
    sp_topo_slot = np.array(
        [tslot.get(int(t), -1) for t in gt], dtype=np.int32
    )
    return V4Static(
        C=chunk_waves, W=wave_width, R=ec.num_resources, G=G,
        Dcap=st.Dcap, T=T, Ct=Ct, Np=Np, N=N,
        gdom_t=gdom_t, topo_of_g=topo_of_g, sp_topo_slot=sp_topo_slot,
        w_tab=w_tab, nd_g=np.asarray(st.nd_g),
    )


class V4Slots(NamedTuple):
    """Per-chunk slot tensors. All scalar-per-slot arrays are flattened to
    [C*W] (SMEM); ``pmg`` stays a VMEM tensor."""

    req: jax.Array  # [C*W*R] f32 (SMEM)
    valid: jax.Array  # [C*W] i32 (SMEM)
    group: jax.Array  # [C*W] i32 (SMEM)
    tol_class: jax.Array  # [C*W] i32 (SMEM)
    has_pmg: jax.Array  # [C*W] i32 (SMEM) — pod matches any group
    sp_g: jax.Array  # [C*W] i32 (SMEM)
    sp_t: jax.Array  # [C*W] i32 (SMEM)
    sp_skew: jax.Array  # [C*W] f32 (SMEM)
    sp_dns: jax.Array  # [C*W] i32 (SMEM)
    sp_scored: jax.Array  # [C*W] i32 (SMEM)
    sp_selfm: jax.Array  # [C*W] f32 (SMEM)
    sp_w: jax.Array  # [C*W] f32 (SMEM)
    sp_nd: jax.Array  # [C*W] f32 (SMEM)
    any_gang: jax.Array  # [C] i32 (SMEM) — wave contains gang slots
    pmg: jax.Array  # [C, W, G] f32 (VMEM)


def build_slots(v4: V4Static, st, ep, idx: np.ndarray) -> V4Slots:
    """Host-side slot gather for one chunk's wave rows ``idx [C, W]``."""
    C, W = idx.shape
    safe = np.clip(idx, 0, None)
    validb = idx >= 0
    valid = validb.astype(np.int32)
    G = v4.G
    pmg = ep.pod_matches_group[safe][:, :, :G].astype(np.float32)
    pmg = pmg * validb[:, :, None]
    group = np.where(validb, ep.group_id[safe], PAD).astype(np.int32)
    tol_c = (
        st.tol_class[safe] if st.tol_class.size else np.zeros_like(safe)
    ).astype(np.int32)
    if st.SP:
        sp_g = np.where(validb, ep.spread_g[safe, 0], PAD).astype(np.int32)
        gsafe = np.clip(sp_g, 0, None)
        has = sp_g >= 0
        sp_skew = np.where(has, ep.spread_skew[safe, 0], 0).astype(np.float32)
        sp_dns = (ep.spread_dns[safe, 0] & has).astype(np.int32)
        sp_scored = ((~ep.spread_dns[safe, 0]) & has).astype(np.int32)
        sp_selfm = np.where(
            has, ep.pod_matches_group[safe, gsafe], 0.0
        ).astype(np.float32)
        sp_t = np.clip(v4.sp_topo_slot[gsafe], 0, None).astype(np.int32)
        sp_w = np.where(has, v4.w_tab[gsafe], 0.0).astype(np.float32)
        sp_nd = np.where(has, v4.nd_g[gsafe], 0).astype(np.float32)
    else:
        sp_g = np.full((C, W), PAD, np.int32)
        sp_t = np.zeros((C, W), np.int32)
        sp_skew = np.zeros((C, W), np.float32)
        sp_dns = np.zeros((C, W), np.int32)
        sp_scored = np.zeros((C, W), np.int32)
        sp_selfm = np.zeros((C, W), np.float32)
        sp_w = np.zeros((C, W), np.float32)
        sp_nd = np.zeros((C, W), np.float32)
    flat = lambda a: jnp.asarray(np.ascontiguousarray(a).reshape(-1))
    return V4Slots(
        req=flat((ep.requests[safe] * validb[:, :, None]).astype(np.float32)),
        valid=flat(valid),
        group=flat(group),
        tol_class=flat(tol_c),
        has_pmg=flat((pmg.sum(axis=2) > 0).astype(np.int32)),
        sp_g=flat(sp_g),
        sp_t=flat(sp_t),
        sp_skew=flat(sp_skew),
        sp_dns=flat(sp_dns),
        sp_scored=flat(sp_scored),
        sp_selfm=flat(sp_selfm),
        sp_w=flat(sp_w),
        sp_nd=flat(sp_nd),
        any_gang=jnp.asarray(((group >= 0).any(axis=1)).astype(np.int32)),
        pmg=jnp.asarray(pmg),
    )


def _make_kernel(v4: V4Static, spec, *, has_gangs: bool, taints: bool,
                 spread: bool):
    C, W, R, G, Dcap, T, Np = v4.C, v4.W, v4.R, v4.G, v4.Dcap, v4.T, v4.Np
    w_cfg = dict(spec.weights)
    w_fit = np.float32(w_cfg.get("NodeResourcesFit", 1.0))
    w_sp = np.float32(w_cfg.get("PodTopologySpread", 1.0))
    rw = [float(x) for x in spec.resource_weights]
    score_rs = [r for r in range(R) if rw[r] != 0.0]
    wsum = np.float32(sum(rw[r] for r in score_rs))
    sp_f32 = bool(getattr(spec, "sp_norm_f32", False))

    def kernel(
        # SMEM scalar inputs
        req_s, valid_s, group_s, tolc_s, haspmg_s,
        spg_s, spt_s, spskew_s, spdns_s, spsc_s, spselfm_s, spw_s, spnd_s,
        anygang_s,
        # VMEM tensor inputs
        used0_ref, mc0_ref, alloc_ref, tol_ref, gdom_ref, tmask_ref, pmg_ref,
        # outputs
        used_ref, mc_ref, choice_ref,
        # scratch
        mcn_ref, nodes_ref, placed_ref, chrow_ref,
    ):
        iota_n = jax.lax.broadcasted_iota(jnp.int32, (1, Np), 1).astype(
            jnp.float32
        )
        iota_d_lane = jax.lax.broadcasted_iota(jnp.int32, (G, Dcap), 1).astype(
            jnp.float32
        )
        alloc_blk = alloc_ref[0, :, :]  # [R, Np] loop-invariant

        # Node-space count planes from the carried domain-space state:
        # mcn[g, n] = mc_dom[g, dom_g(n)] (0 where the node lacks the key).
        if spread:
            for g in range(G):
                t = v4.topo_of_g[g]
                if t < 0:
                    mcn_ref[g, :] = jnp.zeros((Np,), jnp.float32)
                    continue
                dom_row = gdom_ref[t, :].reshape(1, Np)
                acc = jnp.zeros((1, Np), jnp.float32)
                for d in range(int(v4.nd_g[g])):
                    acc = acc + jnp.where(
                        dom_row == np.float32(d), mc0_ref[0, g, d], 0.0
                    )
                mcn_ref[g, :] = acc.reshape(Np)

        used_ref[...] = used0_ref[...]

        def wave_body(c, mc_val):
            # mc (tiny) is value-carried; used lives in its VMEM ref —
            # carrying the [R, Np] plane as a loop value spilled and lost
            # ~40% to the ref form.
            base = c * W
            for k in range(W):
                o = base + k
                valid_k = valid_s[o] > 0
                req_col = jnp.concatenate(
                    [
                        jnp.full((1, 1), req_s[o * R + r], jnp.float32)
                        for r in range(R)
                    ],
                    axis=0,
                )  # [R, 1]
                used_blk = used_ref[0, :, :]
                used1_blk = used_blk + req_col
                fit_blk = (used1_blk <= alloc_blk + np.float32(1e-6)).astype(
                    jnp.float32
                )
                feas = jnp.min(fit_blk, axis=0, keepdims=True) > np.float32(
                    0.5
                )  # [1, Np]
                if taints:
                    trow = tol_ref[0, pl.ds(tolc_s[o], 1), :].reshape(1, Np)
                    feas = feas & (trow > np.float32(0.5))

                # LeastAllocated (exact _fit_score_r chain)
                acc = jnp.zeros((1, Np), jnp.float32)
                for r in score_rs:
                    alloc_r = alloc_blk[r, :].reshape(1, Np)
                    denom = jnp.where(alloc_r > 0, alloc_r, 1.0)
                    frac = jnp.where(
                        alloc_r > 0,
                        (alloc_r - used1_blk[r, :].reshape(1, Np)) / denom,
                        0.0,
                    )
                    frac = jnp.clip(frac, 0.0, 1.0)
                    acc = acc + jnp.floor(
                        frac * np.float32(MAX_NODE_SCORE)
                    ) * np.float32(rw[r])
                total = w_fit * (jnp.floor(acc / wsum) if wsum else acc)

                if spread:
                    g_k = spg_s[o]
                    has_sp = g_k >= 0
                    skew_k = spskew_s[o]
                    is_dns = spdns_s[o] > 0
                    scored_k = spsc_s[o] > 0
                    cnt_n = mcn_ref[pl.ds(jnp.maximum(g_k, 0), 1), :].reshape(
                        1, Np
                    )
                    dom_row = gdom_ref[pl.ds(spt_s[o], 1), :].reshape(1, Np)
                    labeled = dom_row >= np.float32(0)
                    minv = jnp.min(
                        jnp.where(labeled, cnt_n, np.float32(np.inf))
                    )
                    has_dom = spnd_s[o] > 0
                    minv0 = jnp.where(has_dom, minv, 0.0)
                    ok_n = labeled & has_dom & (
                        cnt_n + spselfm_s[o] - minv0 <= skew_k
                    )
                    raw_n = jnp.floor(
                        cnt_n * spw_s[o] + (skew_k - 1.0) + np.float32(0.5)
                    )
                    okn = feas & labeled
                    hi, lo = _hi_lo(jnp.where(okn, raw_n, jnp.nan))
                    has = hi > _NEG
                    if sp_f32:
                        hi_f = jnp.where(has, hi, 0.0)
                        lo_f = jnp.where(has, lo, 0.0)
                        pos = hi_f > 0
                        out_n = jnp.where(
                            pos,
                            jnp.floor(
                                (np.float32(MAX_NODE_SCORE)
                                 * (hi_f + lo_f - raw_n))
                                / jnp.where(pos, hi_f, 1.0)
                            ),
                            np.float32(MAX_NODE_SCORE),
                        )
                    else:
                        hi_i = jnp.where(has, hi, 0.0).astype(jnp.int32)
                        lo_i = jnp.where(has, lo, 0.0).astype(jnp.int32)
                        out_n = jnp.where(
                            hi_i > 0,
                            (
                                (np.int32(MAX_NODE_SCORE)
                                 * (hi_i + lo_i - raw_n.astype(jnp.int32)))
                                // jnp.where(hi_i > 0, hi_i, 1)
                            ).astype(jnp.float32),
                            np.float32(MAX_NODE_SCORE),
                        )
                    sc = jnp.where(labeled & has & scored_k, out_n, 0.0) * w_sp
                    pen = jnp.where(
                        is_dns & ~(ok_n & labeled), -_SPREAD_BLOCK, 0.0
                    )
                    total = total + jnp.where(has_sp, sc + pen, 0.0)

                # select: lowest-index argmax
                masked = jnp.where(feas, total, _NEG)
                mx = jnp.max(masked)
                any_f = mx > np.float32(-1.0e29)
                node_f = jnp.min(
                    jnp.where(feas & (masked == mx), iota_n, np.float32(Np))
                )
                placed = any_f & valid_k
                nodes_ref[k] = jnp.where(
                    placed, node_f.astype(jnp.int32), np.int32(PAD)
                )
                placed_ref[k] = placed.astype(jnp.int32)

                # speculative apply (value update — no VMEM traffic)
                oh_n = jnp.where((iota_n == node_f) & placed, 1.0, 0.0)
                used_ref[0, :, :] = used_blk + req_col * oh_n
                if spread:
                    do_mc = placed & (haspmg_s[o] > 0)
                    dom_at = [
                        jnp.sum(gdom_ref[t, :].reshape(1, Np) * oh_n)
                        for t in range(T)
                    ]
                    dom_col = jnp.zeros((G, 1), jnp.float32)
                    for t in range(T):
                        dom_col = dom_col + tmask_ref[:, t:t + 1] * dom_at[t]
                    pmg_row = pmg_ref[pl.ds(c, 1), k, :]  # [1, G]
                    pmg_col = jnp.transpose(pmg_row, (1, 0))  # [G, 1]
                    sel = jnp.where(do_mc, 1.0, 0.0)
                    hasd = dom_col >= 0
                    mc_val = mc_val + jnp.where(
                        (iota_d_lane == dom_col) & hasd, pmg_col * sel, 0.0
                    )

                    @pl.when(do_mc)
                    def _():
                        gdom_g = jnp.concatenate(
                            [
                                gdom_ref[max(v4.topo_of_g[g], 0), :]
                                .reshape(1, Np)
                                for g in range(G)
                            ],
                            axis=0,
                        )  # [G, Np]
                        mcn_ref[...] = mcn_ref[...] + jnp.where(
                            (gdom_g == dom_col) & hasd, pmg_col, 0.0
                        )

            # wave-end gang commit / revert
            for k in range(W):
                chrow_ref[k] = nodes_ref[k]
            if has_gangs:
                for k in range(W):
                    o = base + k
                    g_k = group_s[o]
                    fail = (
                        (group_s[base + 0] == g_k)
                        & (valid_s[base + 0] > 0)
                        & (placed_ref[0] == 0)
                    )
                    for j in range(1, W):
                        fail = fail | (
                            (group_s[base + j] == g_k)
                            & (valid_s[base + j] > 0)
                            & (placed_ref[j] == 0)
                        )
                    revert = (
                        (anygang_s[c] > 0)
                        & (g_k >= 0)
                        & (placed_ref[k] > 0)
                        & fail
                    )
                    rsel = jnp.where(revert, 1.0, 0.0)
                    node_k = nodes_ref[k]
                    oh_n = jnp.where(
                        iota_n == node_k.astype(jnp.float32), rsel, 0.0
                    )
                    req_col = jnp.concatenate(
                        [
                            jnp.full((1, 1), req_s[o * R + r], jnp.float32)
                            for r in range(R)
                        ],
                        axis=0,
                    )
                    used_ref[0, :, :] = used_ref[0, :, :] - req_col * oh_n
                    chrow_ref[k] = jnp.where(revert, np.int32(PAD), node_k)
                    if spread:
                        do_mc = revert & (haspmg_s[o] > 0)
                        dom_at = [
                            jnp.sum(gdom_ref[t, :].reshape(1, Np) * oh_n)
                            for t in range(T)
                        ]
                        dom_col = jnp.zeros((G, 1), jnp.float32)
                        for t in range(T):
                            dom_col = (
                                dom_col + tmask_ref[:, t:t + 1] * dom_at[t]
                            )
                        pmg_row = pmg_ref[pl.ds(c, 1), k, :]
                        pmg_col = jnp.transpose(pmg_row, (1, 0))
                        sel = jnp.where(do_mc, 1.0, 0.0)
                        hasd = dom_col >= 0
                        mc_val = mc_val - jnp.where(
                            (iota_d_lane == dom_col) & hasd,
                            pmg_col * sel, 0.0,
                        )

                        @pl.when(do_mc)
                        def _():
                            gdom_g = jnp.concatenate(
                                [
                                    gdom_ref[max(v4.topo_of_g[g], 0), :]
                                    .reshape(1, Np)
                                    for g in range(G)
                                ],
                                axis=0,
                            )
                            mcn_ref[...] = mcn_ref[...] - jnp.where(
                                (gdom_g == dom_col) & hasd, pmg_col, 0.0
                            )

            row = jnp.concatenate(
                [jnp.full((1, 1), chrow_ref[k], jnp.int32) for k in range(W)],
                axis=1,
            )
            choice_ref[0, pl.ds(c, 1), :] = row
            return mc_val

        mc_f = jax.lax.fori_loop(0, C, wave_body, mc0_ref[0, :, :])
        mc_ref[0, :, :] = mc_f

    return kernel


def _hi_lo(x):
    """(max, min) over non-NaN entries of ``x`` in one masked pair of
    reduces (NaN marks excluded lanes)."""
    isn = jnp.isnan(x)
    hi = jnp.max(jnp.where(isn, _NEG, x))
    lo = jnp.min(jnp.where(isn, np.float32(3.0e38), x))
    return hi, lo


def make_v4_chunk_fn(v4: V4Static, st, spec, interpret: bool = False):
    """chunk_fn(used [S,R,Np] f32, mc [S,G,Dcap] f32, alloc [S,R,Np],
    tol [S,Ct,Np] f32, slots) -> (used', mc', choices [S, C, W] i32)."""
    C, W, R, G, Dcap, Ct, Np = (
        v4.C, v4.W, v4.R, v4.G, v4.Dcap, v4.Ct, v4.Np,
    )
    kernel = _make_kernel(
        v4, spec,
        has_gangs=bool(st.has_gangs),
        taints=bool(spec.taints),
        spread=bool(spec.spread and st.SP),
    )
    gdom_c = jnp.asarray(v4.gdom_t)
    tmask_c = jnp.asarray(
        np.array(
            [
                [1.0 if v4.topo_of_g[g] == t else 0.0 for t in range(v4.T)]
                for g in range(v4.G)
            ],
            np.float32,
        )
    )  # [G, T]

    def chunk_fn(used, mc, alloc, tol, slots: V4Slots):
        S = used.shape[0]
        smem = pl.BlockSpec(memory_space=pltpu.SMEM)
        vmem = pl.BlockSpec(memory_space=pltpu.VMEM)

        def per_s(shape):
            return pl.BlockSpec(
                (1,) + shape, lambda s: (s, 0, 0), memory_space=pltpu.VMEM
            )

        out_shape = (
            jax.ShapeDtypeStruct((S, R, Np), jnp.float32),
            jax.ShapeDtypeStruct((S, G, Dcap), jnp.float32),
            jax.ShapeDtypeStruct((S, C, W), jnp.int32),
        )
        grid_spec = pl.GridSpec(
            grid=(S,),
            in_specs=[
                smem, smem, smem, smem, smem,  # req..has_pmg
                smem, smem, smem, smem, smem, smem, smem, smem,  # sp_*
                smem,  # any_gang
                per_s((R, Np)),  # used0
                per_s((G, Dcap)),  # mc0
                per_s((R, Np)),  # alloc
                per_s((Ct, Np)),  # tol
                vmem,  # gdom
                vmem,  # tmask
                vmem,  # pmg
            ],
            out_specs=(
                per_s((R, Np)),
                per_s((G, Dcap)),
                per_s((C, W)),
            ),
            scratch_shapes=[
                pltpu.VMEM((G, Np), jnp.float32),  # mc_node plane
                pltpu.SMEM((W,), jnp.int32),  # nodes
                pltpu.SMEM((W,), jnp.int32),  # placed
                pltpu.SMEM((W,), jnp.int32),  # final choices
            ],
        )
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=out_shape,
            interpret=interpret,
        )(
            slots.req, slots.valid, slots.group, slots.tol_class,
            slots.has_pmg,
            slots.sp_g, slots.sp_t, slots.sp_skew, slots.sp_dns,
            slots.sp_scored, slots.sp_selfm, slots.sp_w, slots.sp_nd,
            slots.any_gang,
            used, mc, alloc, tol, gdom_c, tmask_c, slots.pmg,
        )

    return chunk_fn


def pad_nodes(a: np.ndarray, n_pad: int, fill=0.0) -> np.ndarray:
    """Pad the last axis to ``n_pad`` with ``fill`` (host-side)."""
    pad = n_pad - a.shape[-1]
    if pad <= 0:
        return a
    widths = [(0, 0)] * a.ndim
    widths[-1] = (0, pad)
    return np.pad(a, widths, constant_values=fill)
