"""Device-path completions: pods with finite duration free their resources
and count contributions at chunk boundaries (SURVEY.md §2 L4 — "binding
updates state used by subsequent pods"; completions are the other half of
that contract). Anchor = greedy_replay(completions_chunk_waves=...)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import (
    Cluster,
    LabelSelector,
    Node,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
)
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


def test_completion_frees_capacity_changes_placement():
    # a holds the only cpu until t=5; b arrives at t=10 — it fits only if
    # the release actually happened. Releases run ONE CHUNK BEHIND
    # placements (the round-3 pipelining slack: boundary b sees chunks
    # ≤ b−2), so a zero-request filler chunk sits between them.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("a", requests={"cpu": 1}, arrival_time=0.0, duration=5.0),
        Pod("f", requests={}, arrival_time=6.0),
        Pod("b", requests={"cpu": 1}, arrival_time=10.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    res = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1).replay()
    assert res.assignments[0] == 0 and res.assignments[2] == 0
    assert res.placed == 3
    off = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, completions=False
    ).replay()
    assert off.assignments[2] == PAD  # without completions b never fits
    anchor = greedy_replay(ec, ep, cfg, wave_width=1, completions_chunk_waves=1)
    np.testing.assert_array_equal(res.assignments, anchor.assignments)


def test_completion_decrements_count_planes():
    # a (app=x) blocks b's required anti-affinity until it completes: the
    # release must decrement the match-count planes, not just resources.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 4})])
    anti = PodAffinitySpec(
        required=(
            PodAffinityTerm(
                LabelSelector.make({"app": "x"}), "kubernetes.io/hostname"
            ),
        )
    )
    pods = [
        Pod("a", labels={"app": "x"}, requests={"cpu": 1}, arrival_time=0.0,
            duration=3.0),
        Pod("f", requests={}, arrival_time=5.0),  # slack chunk
        Pod("b", requests={"cpu": 1}, arrival_time=10.0, pod_anti_affinity=anti),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    res = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1).replay()
    assert res.assignments[0] == 0 and res.assignments[2] == 0
    off = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, completions=False
    ).replay()
    assert off.assignments[2] == PAD
    anchor = greedy_replay(ec, ep, cfg, wave_width=1, completions_chunk_waves=1)
    np.testing.assert_array_equal(res.assignments, anchor.assignments)


def test_completions_parity_random_both_engines():
    cluster = make_cluster(12, seed=3, taint_fraction=0.2)
    pods, _ = make_workload(
        80, seed=3, arrival_rate=10.0, duration_mean=2.0,
        with_affinity=True, with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    anchor = greedy_replay(ec, ep, cfg, wave_width=4, completions_chunk_waves=4)
    for engine in ("v3", "v2"):
        dev = JaxReplayEngine(
            ec, ep, cfg, wave_width=4, chunk_waves=4, engine=engine
        ).replay()
        np.testing.assert_array_equal(dev.assignments, anchor.assignments), engine
    # Releases must actually matter on this trace, or the test is vacuous.
    off = greedy_replay(ec, ep, cfg, wave_width=4)
    assert (anchor.assignments != off.assignments).any()


def test_completions_checkpoint_resume_identical(tmp_path):
    cluster = make_cluster(10, seed=5)
    pods, _ = make_workload(120, seed=5, arrival_rate=20.0, duration_mean=1.5)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    full = JaxReplayEngine(ec, ep, cfg, wave_width=4, chunk_waves=4).replay()
    ck = str(tmp_path / "ck.npz")
    JaxReplayEngine(ec, ep, cfg, wave_width=4, chunk_waves=4).replay(
        checkpoint_path=ck, checkpoint_every=2
    )
    resumed = JaxReplayEngine(ec, ep, cfg, wave_width=4, chunk_waves=4).replay(
        checkpoint_path=ck, resume=True
    )
    np.testing.assert_array_equal(full.assignments, resumed.assignments)
    assert full.placed == resumed.placed


def test_gang_member_completions_release_individually():
    # Both gang members commit at t=0; each releases at its own finish time,
    # freeing capacity for later singles.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("g0", requests={"cpu": 1}, arrival_time=0.0, duration=2.0,
            pod_group="gang"),
        Pod("g1", requests={"cpu": 1}, arrival_time=0.0, duration=8.0,
            pod_group="gang"),
        Pod("f1", requests={}, arrival_time=12.0),  # slack chunk (W=2)
        Pod("f2", requests={}, arrival_time=13.0),
        Pod("s", requests={"cpu": 2}, arrival_time=20.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    res = JaxReplayEngine(ec, ep, cfg, wave_width=2, chunk_waves=1).replay()
    assert res.assignments[0] == 0 and res.assignments[1] == 0
    assert res.assignments[4] == 0  # both released by t=20
    anchor = greedy_replay(ec, ep, cfg, wave_width=2, completions_chunk_waves=1)
    np.testing.assert_array_equal(res.assignments, anchor.assignments)


def test_completions_resume_with_prebound(tmp_path):
    # Pre-bound pods never appear in waves; the resume reconstruction must
    # still know their releases were already applied (chunk −2), or it
    # subtracts them a second time and the planes go negative.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2}), Node("n1", {"cpu": 2})])
    pods = [
        Pod("pre", requests={"cpu": 1}, arrival_time=0.0, duration=1.0,
            node_name="n0"),
    ] + [
        Pod(f"p{i}", requests={"cpu": 1}, arrival_time=2.0 + i, duration=1.5)
        for i in range(8)
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    full = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=2).replay()
    ck = str(tmp_path / "ck.npz")
    JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=2).replay(
        checkpoint_path=ck, checkpoint_every=1
    )
    resumed = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=2).replay(
        checkpoint_path=ck, resume=True
    )
    np.testing.assert_array_equal(full.assignments, resumed.assignments)


def test_whatif_completions_scenario0_matches_single_replay():
    # What-if scenarios now release completed pods per scenario: the
    # unperturbed scenario must equal the single-chip replay (which has
    # completions), and a capacity-perturbed scenario must diverge the
    # usual way without breaking.
    from kubernetes_simulator_tpu.sim.whatif import (
        Perturbation,
        Scenario,
        WhatIfEngine,
    )

    cluster = make_cluster(10, seed=7)
    pods, _ = make_workload(150, seed=7, arrival_rate=15.0, duration_mean=2.0,
                            with_spread=True, with_tolerations=True)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = [
        Scenario(),
        Scenario([
            Perturbation("scale_capacity", nodes=np.arange(5),
                         resource="cpu", factor=0.5)
        ]),
    ]
    eng = WhatIfEngine(ec, ep, scen, cfg, wave_width=4, chunk_waves=4,
                       collect_assignments=True, completions=True)
    assert eng.completions_on
    res = eng.run()
    single = JaxReplayEngine(ec, ep, cfg, wave_width=4, chunk_waves=4).replay()
    np.testing.assert_array_equal(res.assignments[0], single.assignments)
    # completions must change the outcome on this trace (non-vacuous);
    # the default is ON since round 3, so force them off explicitly.
    off = WhatIfEngine(ec, ep, scen, cfg, wave_width=4, chunk_waves=4,
                       collect_assignments=True, completions=False).run()
    assert (off.assignments[0] != res.assignments[0]).any()


def test_whatif_device_release_path_matches_host_path():
    """The device-side release path (no per-chunk D2H; round 3) must agree
    with the host pending-fold path: same per-scenario placed counts and
    utilization. Gate sanity: collect_assignments forces the host path."""
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

    cluster = make_cluster(12, seed=3, taint_fraction=0.2)
    pods, _ = make_workload(
        120, seed=3, arrival_rate=12.0, duration_mean=2.0,
        with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = uniform_scenarios(ec, 4, seed=3)
    dev = WhatIfEngine(ec, ep, scen, cfg, chunk_waves=4)
    assert dev._completions_dev and not dev._need_choices
    r1 = dev.run()
    host = WhatIfEngine(ec, ep, scen, cfg, chunk_waves=4, collect_assignments=True)
    assert not host._completions_dev and host.completions_on
    r2 = host.run()
    np.testing.assert_array_equal(r1.placed, r2.placed)
    np.testing.assert_allclose(r1.utilization_cpu, r2.utilization_cpu, atol=1e-6)
    # Non-vacuous: completions change this trace's outcome.
    off = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, completions=False
    ).run()
    assert (off.placed != r1.placed).any() or (
        np.abs(off.utilization_cpu - r1.utilization_cpu) > 1e-4
    ).any()


@pytest.mark.slow
def test_whatif_device_release_full_plugin_envelope():
    """Round 4: the device-release path covers anti/pref count planes,
    multi-topology traces and singleton host-scale rows (the bench /
    config-3 workload shape). Device vs host pending-fold vs greedy
    anchor, plus the JaxReplayEngine twin, all value-identical."""
    from kubernetes_simulator_tpu.sim.whatif import (
        Scenario,
        WhatIfEngine,
        uniform_scenarios,
    )

    cluster = make_cluster(12, seed=5, taint_fraction=0.2)
    pods, _ = make_workload(
        140, seed=5, arrival_rate=14.0, duration_mean=2.0,
        with_affinity=True, with_spread=True, with_tolerations=True,
        gang_fraction=0.05, gang_size=2,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = uniform_scenarios(ec, 4, seed=5)
    dev = WhatIfEngine(ec, ep, scen, cfg, chunk_waves=4)
    # The point of this test: affinity terms force the planes the
    # round-3 gate excluded — the path must still be the device one.
    assert dev.static3.maintain_anti or dev.static3.maintain_pref
    assert dev.static3.has_host_rows or not dev.static3.single_topo
    assert dev._completions_dev
    r1 = dev.run()
    host = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, collect_assignments=True
    )
    assert not host._completions_dev
    r2 = host.run()
    np.testing.assert_array_equal(r1.placed, r2.placed)
    np.testing.assert_allclose(
        r1.utilization_cpu, r2.utilization_cpu, atol=1e-6
    )
    # Scenario 0 == the single-replay engine == the greedy anchor.
    single = JaxReplayEngine(ec, ep, cfg, chunk_waves=4).replay()
    anchor = greedy_replay(ec, ep, cfg, completions_chunk_waves=4)
    np.testing.assert_array_equal(single.assignments, anchor.assignments)
    assert int(r1.placed[0]) == int(
        (anchor.assignments[ep.bound_node == PAD] >= 0).sum()
    )
    # Non-vacuous: releases must matter on this trace.
    off = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, completions=False
    ).run()
    assert (off.placed != r1.placed).any()


def test_whatif_prebound_release_device_path():
    """Pre-bound pods live in vassign's static tail: their completion
    releases at the eligibility boundary through the device path, freeing
    capacity for later arrivals — pinned against the anchor."""
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("pre", requests={"cpu": 1}, arrival_time=0.0, duration=1.0,
            node_name="n0"),
        Pod("f1", requests={}, arrival_time=2.0),
        Pod("f2", requests={}, arrival_time=3.0),
        Pod("b", requests={"cpu": 1}, arrival_time=5.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    eng = WhatIfEngine(ec, ep, [Scenario()], cfg, wave_width=1, chunk_waves=1)
    assert eng._completions_dev
    res = eng.run()
    anchor = greedy_replay(ec, ep, cfg, wave_width=1, completions_chunk_waves=1)
    assert anchor.assignments[3] == 0  # b fits once pre released
    assert int(res.placed[0]) == anchor.placed == 3
