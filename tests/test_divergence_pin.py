"""Chunk-granularity divergence pin at PRODUCTION chunk sizes (round 4;
SURVEY.md §4.3 determinism row, VERDICT r2 task #8 / r3 #4): the device
engine's chunk-boundary completions vs the CPU event engine's
exact-timestamp semantics, measured as a placed-count bound on a
completion-heavy Borg-shaped trace whose duration/chunk-span AND
per-node-contention ratios match the production (north-star) regime.

Measured 2026-07-30 (CPU event engine = exact reference):
- 1250 nodes × 65536 tasks, mean_duration 28800 s (duration = 1.33×
  chunk span), C=2048 (4 chunks): gap 0.00% (65536/65536, retry on or
  off); C=4096 (2 chunks): gap 0.53% (65187).
- 1250 nodes × 32768 tasks, mean_duration 57600 s, C=2048 (2 chunks):
  gap 0.00% — the shape asserted below (CPU engine ~150 s).
- Cautionary negative shape: durations ≪ chunk span (100 nodes,
  duration 19 s vs 410 s span) batches all releases at a few boundaries
  and arrival-order greedy drops 89% of placements — granular
  completions need chunk span ≲ mean duration; see COVERAGE.md.
"""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.sim.borg import BorgSpec, make_borg_encoded
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine


@pytest.mark.slow
def test_chunk_granularity_divergence_production_chunks():
    ec, ep, _ = make_borg_encoded(
        BorgSpec(nodes=1250, tasks=32_768, seed=0, mean_duration=57_600.0)
    )
    cfg = FrameworkConfig()
    cpu = CpuReplayEngine(ec, ep, cfg).replay()
    assert cpu.placed > 0

    res = WhatIfEngine(ec, ep, [Scenario()], cfg, chunk_waves=2048).run()
    assert res.completions_on
    gap = abs(int(res.placed[0]) - cpu.placed) / cpu.placed
    # The coarseness is a NUMBER, not a vibe (measured 0.00% here; the
    # bound is deliberately loose against generator drift).
    assert gap <= 0.05, (gap, int(res.placed[0]), cpu.placed)

    # Retry at release boundaries only closes the gap further.
    rb = WhatIfEngine(
        ec, ep, [Scenario()], cfg, chunk_waves=2048, retry_buffer=2048
    ).run()
    gap_rb = abs(int(rb.placed[0]) - cpu.placed) / cpu.placed
    assert gap_rb <= gap + 1e-9, (gap_rb, gap)
