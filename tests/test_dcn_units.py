"""Single-process unit tests for the round-11 DCN plumbing: slicing
arithmetic, mesh localization, per-process output paths, DCN-aware
population fitting, the concurrent-safe compile cache, the
enable-before-initialize ordering contract, deterministic JSONL, and the
schema checker's round-11 fields — everything that doesn't need a real
2-process fleet (tests/test_dcn.py covers that)."""

import json
import os

import jax
import numpy as np
import pytest

from kubernetes_simulator_tpu.parallel import dcn
from kubernetes_simulator_tpu.parallel.mesh import (
    fit_population,
    make_mesh,
    spans_processes,
)

# -- slicing / mesh localization -------------------------------------------


def test_local_slice_contiguous_blocks(monkeypatch):
    monkeypatch.setattr(dcn, "process_info", lambda: (2, 0))
    assert dcn.local_slice(8) == slice(0, 4)
    monkeypatch.setattr(dcn, "process_info", lambda: (2, 1))
    assert dcn.local_slice(8) == slice(4, 8)
    monkeypatch.setattr(dcn, "process_info", lambda: (4, 2))
    assert dcn.local_slice(8) == slice(4, 6)


def test_local_slice_identity_single_process():
    assert dcn.local_slice(8) == slice(0, 8)


def test_spans_processes_and_localize_identity():
    """Single-process meshes never span; localize_mesh is the identity for
    them and for None (the production call sits unconditionally in
    WhatIfEngine.__init__, so the identity path IS the common path)."""
    mesh = make_mesh()
    assert not spans_processes(None)
    assert not spans_processes(mesh)
    assert dcn.localize_mesh(None) is None
    assert dcn.localize_mesh(mesh) is mesh


def test_output_path_for_process(monkeypatch):
    assert dcn.output_path_for_process(None) is None
    monkeypatch.setattr(dcn, "process_info", lambda: (2, 0))
    assert dcn.output_path_for_process("out.jsonl") == "out.jsonl"
    monkeypatch.setattr(dcn, "process_info", lambda: (2, 1))
    assert dcn.output_path_for_process("out.jsonl") == "out.jsonl.p1"


def test_gather_requires_initialized_coordinator():
    with pytest.raises(RuntimeError, match="not initialized"):
        dcn.gather("never", {"x": 1})


def test_maybe_init_noop_without_env(monkeypatch):
    for k in ("KSIM_DCN_COORD", "DCN_COORD", "KSIM_DCN_NPROC", "DCN_NPROC"):
        monkeypatch.delenv(k, raising=False)
    assert dcn.maybe_init_from_env() is False


def test_enable_cache_before_initialize_ordering(monkeypatch):
    """The regression pin for the round-11 ordering contract:
    ``maybe_init_from_env`` must configure the persistent compile cache
    BEFORE ``jax.distributed.initialize`` (a cache enabled after the
    backend exists misses the very compiles the DCN workers share)."""
    import kubernetes_simulator_tpu.parallel.mesh as mesh_mod
    import kubernetes_simulator_tpu.utils.compile_cache as cc

    calls = []
    monkeypatch.setattr(cc, "enable", lambda *a, **k: calls.append("cache"))
    monkeypatch.setattr(
        mesh_mod, "init_distributed",
        lambda **kw: calls.append(("init", kw["num_processes"],
                                   kw["process_id"])),
    )
    monkeypatch.setenv("KSIM_DCN_COORD", "127.0.0.1:1")
    monkeypatch.setenv("KSIM_DCN_NPROC", "2")
    monkeypatch.setenv("KSIM_DCN_PID", "1")
    assert dcn.maybe_init_from_env() is True
    assert calls == ["cache", ("init", 2, 1)]


# -- engine-level slicing (process count faked; construction only) ---------


def _tiny_batch(S):
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.synthetic import (
        make_cluster,
        make_workload,
    )
    from kubernetes_simulator_tpu.sim.whatif import uniform_scenarios

    cluster = make_cluster(6, seed=3)
    pods, _ = make_workload(16, seed=3)
    ec, ep = encode(cluster, pods)
    return ec, ep, uniform_scenarios(ec, S, seed=3, p_capacity=0.5)


def test_engine_slices_scenarios_per_process(monkeypatch):
    """With a faked 2-process world the engine keeps only its contiguous
    half of the scenario axis (construction only — running would need the
    real coordinator)."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine

    ec, ep, scenarios = _tiny_batch(8)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    eng = WhatIfEngine(ec, ep, scenarios, FrameworkConfig(), chunk_waves=4)
    assert eng._dcn_sliced
    assert eng.S_global == 8 and eng.S == 4
    assert eng._proc_lo == 4


def test_engine_replicates_on_uneven_batch(monkeypatch):
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine

    ec, ep, scenarios = _tiny_batch(7)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    monkeypatch.setattr(jax, "process_index", lambda: 0)
    eng = WhatIfEngine(ec, ep, scenarios, FrameworkConfig(), chunk_waves=4)
    assert not eng._dcn_sliced
    assert eng.S == 7


def test_engine_rejects_set_label_under_dcn(monkeypatch):
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.sim.whatif import (
        Perturbation,
        Scenario,
        WhatIfEngine,
    )

    ec, ep, _ = _tiny_batch(2)
    scenarios = [
        Scenario(),
        Scenario([Perturbation(
            "set_label", nodes=np.array([0]),
            key="topology.kubernetes.io/zone", value="zz",
        )]),
    ]
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(ValueError, match="set_label"):
        WhatIfEngine(ec, ep, scenarios, FrameworkConfig(), chunk_waves=4)


def test_single_process_run_untouched_by_dcn_paths():
    """The common case: no DCN env, no slicing, no gather, result stamps
    process_count=1 — and the replication counter stays zero (the
    local-mesh chunk loop never round-trips full tensors)."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine

    ec, ep, scenarios = _tiny_batch(8)
    g0 = dcn.GATHER_COUNT
    eng = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), mesh=make_mesh(),
        chunk_waves=4,
    )
    res = eng.run()
    assert not eng._dcn_sliced
    assert eng._replicate_count == 0
    assert dcn.GATHER_COUNT == g0
    assert res.process_count == 1
    assert res.n_devices == 8


# -- fit_population: DCN factorizations ------------------------------------


def test_fit_population_single_process_mesh():
    mesh = make_mesh()  # 8 devices (conftest forces 8 virtual CPUs)
    assert fit_population(5, 3, mesh) == 8  # 8*3 first multiple of 8
    assert fit_population(5, 8, mesh) == 5  # already divides
    assert fit_population(1, 1, None) == 1


def test_fit_population_dcn_no_mesh(monkeypatch):
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    # Mesh-less DCN sweep: the flat axis must still divide the process
    # count for the per-process slices to be even.
    assert fit_population(5, 3, None) == 6  # 6*3 even, 5*3 odd


def test_fit_population_dcn_local_mesh(monkeypatch):
    mesh = make_mesh()  # local 8 devices; x2 processes = 16 global
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    assert fit_population(5, 3, mesh) == 16  # 16*3 = 48 divides 16
    assert fit_population(4, 4, mesh) == 4  # 16 divides 16 already


# -- compile cache: atomic writes + ordering -------------------------------


def test_atomic_put_writes_whole_entries(tmp_path):
    """The monkeypatched LRUCache.put goes through a per-process temp file
    + os.replace: the entry appears complete, no temp droppings remain,
    and a second put of the same key is a no-op (first writer wins)."""
    from jax._src import lru_cache as _lru

    from kubernetes_simulator_tpu.utils.compile_cache import (
        patch_atomic_writes,
    )

    assert patch_atomic_writes() is True
    cache = _lru.LRUCache(str(tmp_path), max_size=-1)
    cache.put("entry", b"x" * 1024)
    assert cache.get("entry") == b"x" * 1024
    files = sorted(p.name for p in tmp_path.iterdir())
    assert "entry-cache" in files
    assert not [f for f in files if ".tmp." in f], files
    cache.put("entry", b"y" * 1024)  # concurrent-sibling replay: kept
    assert cache.get("entry") == b"x" * 1024
    with pytest.raises(ValueError, match="empty"):
        cache.put("", b"z")


# -- deterministic JSONL ---------------------------------------------------


def test_deterministic_jsonl_zeroes_wall_clock(tmp_path, monkeypatch):
    """KSIM_DETERMINISTIC_JSONL=1 pins ts/wall_clock_s/placements_per_sec
    to 0.0 (fields stay present as numbers — schema v2 requires them), so
    DCN parity runs can compare JSONL bytes."""
    from kubernetes_simulator_tpu.utils.metrics import (
        JsonlWriter,
        deterministic_jsonl,
        whatif_rows,
    )

    monkeypatch.delenv("KSIM_DETERMINISTIC_JSONL", raising=False)
    assert not deterministic_jsonl()
    monkeypatch.setenv("KSIM_DETERMINISTIC_JSONL", "1")
    assert deterministic_jsonl()

    class _Res:
        placed = np.array([3, 4], np.int32)
        unschedulable = np.array([1, 0], np.int32)
        total_placed = 7
        wall_clock_s = 1.25
        placements_per_sec = 5.6
        completions_on = True
        engine = "v3"
        utilization_cpu = None

    path = tmp_path / "d.jsonl"
    with JsonlWriter(str(path), context={"seed": 0}) as out:
        for row in whatif_rows(_Res()):
            out.write(row)
    rows = [json.loads(l) for l in path.read_text().splitlines()]
    assert all(r["ts"] == 0.0 for r in rows)
    assert rows[0]["wall_clock_s"] == 0.0
    assert rows[0]["placements_per_sec"] == 0.0
    # identical rows ⇒ identical bytes, run to run
    with JsonlWriter(str(tmp_path / "e.jsonl"), context={"seed": 0}) as out:
        for row in whatif_rows(_Res()):
            out.write(row)
    assert (tmp_path / "e.jsonl").read_bytes() == path.read_bytes()


# -- schema checker: round-11 fields ---------------------------------------


def test_schema_accepts_dcn_fields():
    import sys

    sys.path.insert(
        0,
        os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", "scripts")
        ),
    )
    from check_metrics_schema import validate_row

    row = {
        "ts": 0.0, "schema": 2, "seed": 0, "engine": "v3",
        "config_hash": "h", "kind": "whatif-aggregate",
        "scenarios": 8, "total_placed": 100, "wall_clock_s": 0.0,
        "placements_per_sec": 0.0, "completions_on": True,
        "process_count": 2, "n_devices": 8,
        "mesh_shape": {"scenario": 8},
        "dcn_scaling": {"process_count": 2},
    }
    assert validate_row(row) == []
    assert validate_row({**row, "process_count": "2"})
    assert validate_row({**row, "dcn_scaling": 3})


# -- round-12 heartbeats / attributed gather timeout ------------------------


class _FakeKV:
    """In-memory stand-in for the jaxlib coordination-service KV client."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        if not allow_overwrite and key in self.store:
            raise RuntimeError(f"key exists: {key}")
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        import time

        if key in self.store:
            return self.store[key]
        time.sleep(timeout_ms / 1000.0)
        raise RuntimeError(f"Deadline Exceeded: {key}")

    def key_value_dir_get(self, prefix):
        return [
            (k, v) for k, v in sorted(self.store.items())
            if k.startswith(prefix)
        ]


def _fleet(monkeypatch, nproc=2, pid=1):
    kv = _FakeKV()
    monkeypatch.setattr(dcn, "process_info", lambda: (nproc, pid))
    monkeypatch.setattr(dcn, "_client", lambda: kv)
    # The degraded-fleet hard exit must never arm inside the TEST
    # process (it would override pytest's own exit status).
    monkeypatch.setattr(dcn, "_degraded_exit_armed", [True])
    monkeypatch.setattr(dcn, "DEGRADED", set())
    return kv


def test_heartbeat_noop_single_process(monkeypatch):
    kv = _FakeKV()
    monkeypatch.setattr(dcn, "_client", lambda: kv)
    assert dcn.heartbeat(3) is False
    assert kv.store == {}


def test_heartbeat_publishes_full_beacon(monkeypatch):
    kv = _fleet(monkeypatch, nproc=2, pid=1)
    ok = dcn.heartbeat(
        3, total=10, block=(4, 8), wall_s=1.5,
        phases={"dispatch": 0.25}, state="run",
    )
    assert ok is True
    beat = json.loads(kv.store[f"{dcn.HB_PREFIX}/1"])
    assert beat["pid"] == 1
    assert beat["chunk"] == 3
    assert beat["state"] == "run"
    assert beat["total_chunks"] == 10
    assert beat["block"] == [4, 8]
    assert beat["wall_s"] == 1.5
    assert beat["phases"] == {"dispatch": 0.25}
    assert isinstance(beat["t"], float)
    # live-buffer gauge (jax.live_arrays is available in-process)
    assert isinstance(beat["live_buffers"], int)


def test_heartbeat_overwrites_one_key(monkeypatch):
    kv = _fleet(monkeypatch, nproc=2, pid=0)
    assert dcn.heartbeat(0)
    assert dcn.heartbeat(5, state="gather")
    keys = [k for k in kv.store if k.startswith(dcn.HB_PREFIX)]
    assert keys == [f"{dcn.HB_PREFIX}/0"]
    beat = json.loads(kv.store[keys[0]])
    assert beat["chunk"] == 5 and beat["state"] == "gather"


def test_heartbeat_file_mirror(tmp_path, monkeypatch):
    _fleet(monkeypatch, nproc=2, pid=1)
    monkeypatch.setenv("KSIM_DCN_HB_DIR", str(tmp_path))
    assert dcn.heartbeat(2, total=4)
    beat = json.loads((tmp_path / "p1.json").read_text())
    assert beat["chunk"] == 2 and beat["total_chunks"] == 4
    assert not list(tmp_path.glob(".p*.tmp")), "tmp file left behind"


def test_maybe_heartbeat_cadence(monkeypatch):
    kv = _fleet(monkeypatch, nproc=2, pid=0)
    # every=4: the start-of-replay beacon (chunk_done=-1) always fires,
    # then chunks 3, 7, ... ((chunk_done+1) % every == 0).
    assert dcn.maybe_heartbeat(-1, every=4) is True
    assert dcn.maybe_heartbeat(0, every=4) is False
    assert dcn.maybe_heartbeat(2, every=4) is False
    assert dcn.maybe_heartbeat(3, every=4) is True
    assert dcn.maybe_heartbeat(7, every=4) is True
    # 0 disables entirely (and short-circuits before any KV traffic).
    kv.store.clear()
    assert dcn.maybe_heartbeat(-1, every=0) is False
    assert kv.store == {}


def test_heartbeat_every_env_default(monkeypatch):
    _fleet(monkeypatch, nproc=2, pid=0)
    assert dcn.heartbeat_every() == 1
    monkeypatch.setenv("KSIM_DCN_HEARTBEAT_EVERY", "0")
    assert dcn.heartbeat_every() == 0
    assert dcn.maybe_heartbeat(-1) is False


def test_read_heartbeats_parses_and_skips_junk(monkeypatch):
    kv = _fleet(monkeypatch, nproc=2, pid=0)
    kv.store[f"{dcn.HB_PREFIX}/0"] = json.dumps({"pid": 0, "chunk": 7})
    kv.store[f"{dcn.HB_PREFIX}/1"] = "not json"
    kv.store[f"{dcn.HB_PREFIX}/xx"] = json.dumps({})
    beats = dcn.read_heartbeats()
    assert set(beats) == {0}
    assert beats[0]["chunk"] == 7


def test_gather_timeout_stale_beacon_fails_fast(monkeypatch):
    """A sibling whose beacon went stale past KSIM_DCN_STALL_S is
    presumed dead: the gather wait aborts IMMEDIATELY with an attributed
    DcnGatherTimeout — long before the full KSIM_DCN_TIMEOUT_S."""
    import time

    kv = _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "30")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.05")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 2, "total_chunks": 9, "state": "run",
         "t": time.time() - 10.0, "block": [4, 8]}
    )
    t0 = time.monotonic()
    with pytest.raises(dcn.DcnGatherTimeout) as ei:
        dcn._get_attributed(kv, "ksim/gather/1/x/1/n", 1, "x")
    assert time.monotonic() - t0 < 5.0, "did not fail fast"
    msg = str(ei.value)
    assert "process 1" in msg and "looks DEAD" in msg
    assert "last completed chunk 2/9" in msg
    assert "scenario block [4, 8)" in msg
    assert ei.value.missing == [1]
    assert 1 in ei.value.heartbeats


def test_gather_timeout_no_beacon_waits_full_deadline(monkeypatch):
    """No beacon is NO evidence of death (heartbeats may be disabled):
    the wait keeps round-11 semantics — full KSIM_DCN_TIMEOUT_S, then an
    attributed error naming the process that never published."""
    import time

    kv = _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "0.2")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.05")
    t0 = time.monotonic()
    with pytest.raises(dcn.DcnGatherTimeout) as ei:
        dcn._get_attributed(kv, "ksim/gather/1/x/1/n", 1, "x")
    assert time.monotonic() - t0 >= 0.15
    msg = str(ei.value)
    assert "timed out after KSIM_DCN_TIMEOUT_S=0.2s" in msg
    assert "no heartbeat ever received" in msg


def test_gather_wait_survives_fresh_beacon_then_delivers(monkeypatch):
    """A slow-but-alive sibling (fresh beacon) never trips the stall
    detector; the poll loop returns the value as soon as it lands."""
    import time

    kv = _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "10")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "60")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.02")
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 1, "t": time.time()}
    )
    calls = {"n": 0}
    real_get = kv.blocking_key_value_get

    def _late_get(key, timeout_ms):
        calls["n"] += 1
        if calls["n"] >= 3:
            kv.store.setdefault("k", "2")
        return real_get(key, timeout_ms)

    kv.blocking_key_value_get = _late_get
    assert dcn._get_attributed(kv, "k", 1, "x") == "2"
    assert calls["n"] >= 3


def test_jsonl_writer_stamps_process_under_dcn(tmp_path, monkeypatch):
    """Round 12: JSONL rows from a fleet carry process_id/process_count;
    single-process rows stay byte-unchanged (no stamp at all)."""
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter

    p1 = tmp_path / "single.jsonl"
    with JsonlWriter(str(p1)) as w:
        w.write({"kind": "x"})
    row = json.loads(p1.read_text())
    assert "process_id" not in row and "process_count" not in row

    monkeypatch.setattr(dcn, "process_info", lambda: (2, 1))
    p2 = tmp_path / "fleet.jsonl"
    with JsonlWriter(str(p2)) as w:
        w.write({"kind": "x"})
    row = json.loads(p2.read_text())
    assert row["process_id"] == 1 and row["process_count"] == 2


# -- round-15 recoverable work-queue ----------------------------------------


def test_recovery_knob_defaults(monkeypatch):
    for k in ("KSIM_DCN_RECOVER", "KSIM_DCN_CKPT_EVERY",
              "KSIM_DCN_MAX_CLAIMS", "KSIM_DCN_SPARES"):
        monkeypatch.delenv(k, raising=False)
    assert dcn.recover_enabled() is False
    assert dcn.ckpt_every() == 0
    assert dcn.max_claims() == 2
    assert dcn.spare_count() == 0
    monkeypatch.setenv("KSIM_DCN_RECOVER", "yes")
    monkeypatch.setenv("KSIM_DCN_CKPT_EVERY", "3")
    monkeypatch.setenv("KSIM_DCN_MAX_CLAIMS", "5")
    assert dcn.recover_enabled() is True
    assert dcn.ckpt_every() == 3
    assert dcn.max_claims() == 5
    monkeypatch.setenv("KSIM_DCN_CKPT_EVERY", "junk")
    monkeypatch.setenv("KSIM_DCN_MAX_CLAIMS", "0")
    assert dcn.ckpt_every() == 0
    assert dcn.max_claims() == 1  # floor: one claim generation always


def test_spares_shrink_worker_count_and_mirror_last_block(monkeypatch):
    monkeypatch.setattr(dcn, "process_info", lambda: (3, 2))
    monkeypatch.setenv("KSIM_DCN_SPARES", "1")
    assert dcn.worker_count() == 2
    assert dcn.is_spare() is True
    # The spare mirrors the LAST worker's block (shapes only — the
    # engine marks it _dcn_spare and never runs the chunks).
    assert dcn.local_slice(8) == slice(4, 8)
    monkeypatch.setattr(dcn, "process_info", lambda: (3, 1))
    assert dcn.is_spare() is False
    assert dcn.local_slice(8) == slice(4, 8)
    monkeypatch.setattr(dcn, "process_info", lambda: (3, 0))
    assert dcn.local_slice(8) == slice(0, 4)


def test_checkpoint_publish_load_roundtrip(monkeypatch):
    """publish_checkpoint → load_checkpoint round-trips the payload
    through the delta+zlib codec; the newest cursor wins; a torn blob
    (no ``/n`` manifest) is skipped; epochs are isolated."""
    kv = _fleet(monkeypatch, nproc=2, pid=1)
    pay0 = {"cursor": 1, "leaves": [np.arange(4096, dtype=np.int32)]}
    pay1 = {"cursor": 3, "leaves": [np.arange(4096, dtype=np.int32) * 2]}
    assert dcn.publish_checkpoint(1, pay0, (4, 8), epoch=7)
    assert dcn.publish_checkpoint(3, pay1, (4, 8), epoch=7)
    got = dcn.load_checkpoint(1, epoch=7)
    assert got is not None
    assert got["cursor"] == 3 and got["block"] == (4, 8)
    np.testing.assert_array_equal(
        got["payload"]["leaves"][0], pay1["leaves"][0]
    )
    assert got["payload"]["leaves"][0].dtype == np.int32
    # Torn blob: drop the manifest of the newest cursor — the reader
    # falls back to the older complete one.
    del kv.store[f"{dcn.CKPT_PREFIX}/7/1/4-8/3/n"]
    assert dcn.load_checkpoint(1, epoch=7)["cursor"] == 1
    # Epoch isolation: a previous replay's blobs are invisible.
    assert dcn.load_checkpoint(1, epoch=8) is None
    assert dcn.load_checkpoint(0, epoch=7) is None


def test_checkpoint_publish_noop_single_process(monkeypatch):
    kv = _FakeKV()
    monkeypatch.setattr(dcn, "_client", lambda: kv)
    assert dcn.publish_checkpoint(1, {"x": 1}, (0, 4)) is False
    assert kv.store == {}


def test_claim_cas_single_claimant_and_metadata_roundtrip(monkeypatch):
    """The write-once claim key admits exactly ONE claimant per
    generation; the loser reads the winner's metadata (claimant pid,
    block owner, generation) for attribution of a second failure."""
    kv = _fleet(monkeypatch, nproc=3, pid=0)
    assert dcn.try_claim(2, 0) is True
    # Same key from another pid: CAS loss.
    monkeypatch.setattr(dcn, "process_info", lambda: (3, 1))
    assert dcn.try_claim(2, 0) is False
    meta = dcn.read_claim(2, 0)
    assert meta["claimant"] == 0
    assert meta["for"] == 2
    assert meta["gen"] == 0
    assert isinstance(meta["t"], float)
    # Next generation is open, and namespaced separately.
    assert dcn.try_claim(2, 1) is True
    assert dcn.read_claim(2, 1)["claimant"] == 1
    assert dcn.read_claim(2, 2) is None


def test_recovery_heartbeat_names_claimed_block(monkeypatch):
    """Satellite: a recovering process beats under its OWN pid with the
    claimed block and the dead pid named, so a second failure during
    recovery is attributed to the claimant — round-tripped through
    read_heartbeats exactly as the stall detector reads it."""
    _fleet(monkeypatch, nproc=2, pid=0)
    assert dcn.heartbeat(
        -1, block=(4, 8), state="recover", extra={"recovering_for": 1}
    )
    beats = dcn.read_heartbeats()
    assert set(beats) == {0}
    beat = beats[0]
    assert beat["pid"] == 0  # the claimant's pid, never the dead one's
    assert beat["state"] == "recover"
    assert beat["recovering_for"] == 1
    assert beat["block"] == [4, 8]


def test_gather_wait_recovers_stale_sibling(monkeypatch, tmp_path):
    """With KSIM_DCN_RECOVER on and a recover callback, a stale sibling
    beacon triggers claim + re-execution + publication under the dead
    pid's keys instead of the attributed DcnGatherTimeout — and the
    claim/recovered events land in the KSIM_DCN_HB_DIR mirror."""
    import time

    kv = _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "30")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.05")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    monkeypatch.setenv("KSIM_DCN_RECOVER", "1")
    monkeypatch.setenv("KSIM_DCN_HB_DIR", str(tmp_path))
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 0, "state": "run", "t": time.time() - 10.0,
         "block": [4, 8]}
    )
    calls = []

    def _recover(p, gen):
        calls.append((p, gen))
        return {"placed": np.array([1, 2], np.int32)}

    got = dcn._get_attributed(
        kv, "ksim/gather/1/whatif/1/n", 1, "whatif", recover=_recover
    )
    assert calls == [(1, 0)]
    assert got == "1"  # the published manifest (one KV chunk)
    # Single-claimant key exists with our metadata.
    meta = dcn.read_claim(1, 0)
    assert meta["claimant"] == 0 and meta["for"] == 1
    # The dead pid's payload is decodable from its gather keys.
    part = dcn._decode_payload(
        [kv.store["ksim/gather/1/whatif/1/0"]]
    )
    np.testing.assert_array_equal(part["placed"], [1, 2])
    events = [
        json.loads(l)
        for l in (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    assert [e["event"] for e in events] == ["claim", "recovered"]
    assert all(e["claimant"] == 0 and e["for"] == 1 for e in events)


def test_gather_wait_defers_to_live_claimant(monkeypatch):
    """A CAS loser never re-executes the block: with a LIVE claimant
    (fresh claim or fresh beacon) it keeps polling for the claimant's
    publication of the dead pid's keys."""
    import time

    kv = _fleet(monkeypatch, nproc=3, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "30")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.05")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    monkeypatch.setenv("KSIM_DCN_RECOVER", "1")
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 0, "t": time.time() - 10.0}
    )
    # pid 2 already claimed gen 0 (fresh claim → benefit of the doubt
    # even before its first recovery beacon).
    kv.store[f"{dcn.CLAIM_PREFIX}/{dcn._seq}/whatif/1/0"] = json.dumps(
        {"claimant": 2, "for": 1, "gen": 0, "t": time.time()}
    )
    calls = {"n": 0}
    real_get = kv.blocking_key_value_get

    def _late_get(key, timeout_ms):
        calls["n"] += 1
        if calls["n"] >= 3:
            kv.store.setdefault("ksim/gather/1/whatif/1/n", "1")
        return real_get(key, timeout_ms)

    kv.blocking_key_value_get = _late_get

    def _never(p, gen):  # pragma: no cover - must not fire
        raise AssertionError("CAS loser re-executed the block")

    got = dcn._get_attributed(
        kv, "ksim/gather/1/whatif/1/n", 1, "whatif", recover=_never
    )
    assert got == "1"


def test_gather_wait_opens_next_generation_on_stale_claimant(monkeypatch):
    """Second failure during recovery: the gen-0 claimant's claim is old
    AND its beacon is stale → survivors open generation 1 and recover."""
    import time

    kv = _fleet(monkeypatch, nproc=3, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "30")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.05")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    monkeypatch.setenv("KSIM_DCN_RECOVER", "1")
    now = time.time()
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 0, "t": now - 10.0}
    )
    kv.store[f"{dcn.CLAIM_PREFIX}/{dcn._seq}/whatif/1/0"] = json.dumps(
        {"claimant": 2, "for": 1, "gen": 0, "t": now - 10.0}
    )
    kv.store[f"{dcn.HB_PREFIX}/2"] = json.dumps(
        {"pid": 2, "chunk": -1, "state": "recover", "t": now - 10.0}
    )
    calls = []

    def _recover(p, gen):
        calls.append((p, gen))
        return {"placed": np.array([7], np.int32)}

    got = dcn._get_attributed(
        kv, "ksim/gather/1/whatif/1/n", 1, "whatif", recover=_recover
    )
    assert got == "1" and calls == [(1, 1)]
    assert dcn.read_claim(1, 1)["claimant"] == 0


def test_gather_wait_exhausted_claims_raise_attributed(monkeypatch):
    """All claim generations stale → the attributed DcnGatherTimeout of
    round 12 fires after all (recovery never hides a lost fleet)."""
    import time

    kv = _fleet(monkeypatch, nproc=3, pid=0)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "30")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.05")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    monkeypatch.setenv("KSIM_DCN_RECOVER", "1")
    monkeypatch.setenv("KSIM_DCN_MAX_CLAIMS", "2")
    now = time.time()
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 0, "t": now - 10.0}
    )
    kv.store[f"{dcn.HB_PREFIX}/2"] = json.dumps(
        {"pid": 2, "chunk": -1, "t": now - 10.0}
    )
    for gen in range(2):
        kv.store[f"{dcn.CLAIM_PREFIX}/{dcn._seq}/whatif/1/{gen}"] = (
            json.dumps({"claimant": 2, "for": 1, "gen": gen,
                        "t": now - 10.0})
        )
    with pytest.raises(dcn.DcnGatherTimeout, match="looks DEAD"):
        dcn._get_attributed(
            kv, "ksim/gather/1/whatif/1/n", 1, "whatif",
            recover=lambda p, gen: {},
        )


def test_gather_wait_stale_beacon_still_fails_without_recover_knob(
    monkeypatch,
):
    """Recovery requires BOTH the env knob and a callback: with a
    callback but KSIM_DCN_RECOVER unset, round-12 fail-fast holds."""
    import time

    kv = _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.delenv("KSIM_DCN_RECOVER", raising=False)
    monkeypatch.setenv("KSIM_DCN_TIMEOUT_S", "30")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.05")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 2, "t": time.time() - 10.0}
    )
    with pytest.raises(dcn.DcnGatherTimeout, match="looks DEAD"):
        dcn._get_attributed(
            kv, "ksim/gather/1/whatif/1/n", 1, "whatif",
            recover=lambda p, gen: {},
        )


def test_snapshot_restore_carriers_roundtrip():
    """sim.jax_runtime snapshot/restore: positional leaf lists survive
    the host round-trip bit-exactly; shape/count mismatches refuse
    (callers then re-execute from chunk 0)."""
    from kubernetes_simulator_tpu.sim.jax_runtime import (
        restore_carriers,
        snapshot_carriers,
    )

    tree = {
        "states": (jax.numpy.arange(6).reshape(2, 3),
                   jax.numpy.ones((4,), jax.numpy.float32)),
        "retry": [jax.numpy.zeros((2, 2), jax.numpy.int32)],
    }
    leaves = snapshot_carriers(tree)
    assert all(isinstance(l, np.ndarray) for l in leaves)
    fresh = jax.tree_util.tree_map(lambda x: x * 0, tree)
    back = restore_carriers(fresh, leaves)
    np.testing.assert_array_equal(back["states"][0], tree["states"][0])
    np.testing.assert_array_equal(back["retry"][0], tree["retry"][0])
    with pytest.raises(ValueError, match="leaves"):
        restore_carriers(fresh, leaves[:-1])
    bad = list(leaves)
    bad[0] = np.zeros((9, 9))
    with pytest.raises(ValueError, match="shape"):
        restore_carriers(fresh, bad)


def test_schema_accepts_process_stamp():
    import sys

    sys.path.insert(
        0,
        os.path.abspath(
            os.path.join(os.path.dirname(__file__), "..", "scripts")
        ),
    )
    from check_metrics_schema import validate_row

    v2 = {
        "ts": 0.0, "schema": 2, "seed": 0, "engine": "v3",
        "config_hash": "h", "kind": "whatif-scenario",
        "scenario": 0, "placed": 3, "unschedulable": 0,
        "process_id": 1, "process_count": 2,
    }
    assert validate_row(v2) == []
    assert validate_row({**v2, "process_id": "1"})
    v3 = {
        "schema": 3, "run_type": "tune", "kind": "tune-round",
        "round": 0, "best_objective": 1.0, "round_best_objective": 1.0,
        "mean_objective": 1.0, "best_candidate": 0,
        "process_id": 0, "process_count": 2,
    }
    assert validate_row(v3) == []
    assert validate_row({**v3, "process_count": 2.5})
