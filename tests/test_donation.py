"""Donation audit for the chunk-dispatch hot path (round 11 satellite):
the per-chunk state updates (release folds, boundary deltas) donate the
outgoing state buffers, so steady-state replay re-uses allocations
instead of doubling them.

Two pins, on both engines that own a subtract-fold:

* no donation warnings — a donated buffer that XLA cannot re-use makes
  jax emit "Some donated buffers were not usable"; any such warning means
  the donation audit regressed (layout mismatch, an alias kept alive);
* stable live-buffer count — a second replay on the same engine must not
  grow ``jax.live_arrays()``: leaked per-chunk buffers accumulate there
  long before they show up as OOM at Borg scale.
"""

import gc
import warnings

import jax
import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios


def _trace(num_pods=24, num_nodes=5):
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(num_nodes)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=20.0)
        for i in range(num_pods)
    ]
    return encode(Cluster(nodes=nodes), pods)


def _live_count() -> int:
    gc.collect()
    return len(jax.live_arrays())


def _assert_no_donation_warnings(record):
    bad = [str(w.message) for w in record if "donat" in str(w.message).lower()]
    assert not bad, f"donation warnings: {bad}"


def test_whatif_completions_chunk_loop_donates_cleanly():
    """The what-if release fold (``_subtract_stacked_planes`` →
    ``_donated_subtract``) across several chunk boundaries: no donation
    warnings, and a replay on a warm engine leaves the live-buffer count
    where it was."""
    ec, ep = _trace()
    scenarios = uniform_scenarios(ec, 4, seed=1, p_capacity=0.5)
    eng = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), wave_width=4, chunk_waves=2,
    )
    placed = []
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        for _ in range(3):
            res = eng.run()
            placed.append(np.array(res.placed, copy=True))
            # Results hold zero-copy views of small fetched device
            # buffers (utilization) — drop them so the count below sees
            # only the ENGINE's steady state.
            del res
        baseline = _live_count()
        res = eng.run()
        placed.append(np.array(res.placed, copy=True))
        del res
        after = _live_count()
    _assert_no_donation_warnings(rec)
    for p in placed[1:]:
        np.testing.assert_array_equal(placed[0], p)
    assert after <= baseline, (
        f"live buffers grew across replays: {baseline} -> {after}"
    )


def test_replay_boundary_deltas_donate_cleanly():
    """The single-replay twins (``_apply_release`` /
    ``_apply_boundary_delta``) under the kube boundary mode with retry:
    same two pins on JaxReplayEngine."""
    ec, ep = _trace()
    eng = JaxReplayEngine(
        ec, ep, FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}]),
        wave_width=1, chunk_waves=4, preemption="kube", retry_buffer=16,
    )
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        first = eng.replay()
        second = eng.replay()  # warm: every lazy jit now built
        baseline = _live_count()
        third = eng.replay()
        after = _live_count()
    _assert_no_donation_warnings(rec)
    np.testing.assert_array_equal(first.assignments, second.assignments)
    np.testing.assert_array_equal(first.assignments, third.assignments)
    assert after <= baseline, (
        f"live buffers grew across replays: {baseline} -> {after}"
    )


def test_donated_subtract_matches_eager():
    """The donated fold is arithmetic-identical to the eager tree-map it
    replaced (and donation actually consumed the argument)."""
    ec, ep = _trace(num_pods=8, num_nodes=3)
    eng = WhatIfEngine(
        ec, ep, uniform_scenarios(ec, 2, seed=0),
        FrameworkConfig(), wave_width=4, chunk_waves=2,
    )
    a = {"u": jax.numpy.arange(12.0).reshape(3, 4)}
    b = {"u": jax.numpy.ones((3, 4))}
    out = eng._donated_subtract(a, b)
    np.testing.assert_allclose(
        np.asarray(out["u"]), np.arange(12.0).reshape(3, 4) - 1.0
    )
    assert a["u"].is_deleted(), "donated input survived — donation inert"
