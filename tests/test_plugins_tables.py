"""Table-driven plugin unit tests mirroring upstream kube-scheduler plugin
test tables ([K8S] semantics are the spec — SURVEY.md §4.1)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.models.core import (
    Cluster,
    LabelSelector,
    MatchExpression,
    Node,
    NodeAffinitySpec,
    NodeSelectorTerm,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.models.state import bind, init_state
from kubernetes_simulator_tpu.ops import cpu as K


def masks_for(cluster, pods, p=0, prebind=()):
    ec, ep = encode(cluster, pods)
    st = init_state(ec, ep)
    for pi, ni in prebind:
        bind(ec, ep, st, pi, ni)
    M = K.expr_match_matrix(ec)
    return ec, ep, st, M


class TestNodeResourcesFit:
    def test_over_under_commit_edges(self):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 2, "memory": 4 * 2**30})])
        pods = [
            Pod("fits-exact", requests={"cpu": 2}),
            Pod("over", requests={"cpu": 2.5}),
            Pod("mem-over", requests={"memory": 5 * 2**30}),
        ]
        ec, ep, st, _ = masks_for(cluster, pods)
        assert K.fit_mask(ec, st, ep, 0)[0]
        assert not K.fit_mask(ec, st, ep, 1)[0]
        assert not K.fit_mask(ec, st, ep, 2)[0]

    def test_fit_accounts_existing_usage(self):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 4})])
        pods = [Pod("a", requests={"cpu": 3}), Pod("b", requests={"cpu": 2})]
        ec, ep, st, _ = masks_for(cluster, pods, prebind=[(0, 0)])
        assert not K.fit_mask(ec, st, ep, 1)[0]

    def test_pods_slot_limit(self):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 100, "pods": 1})])
        pods = [Pod("a", requests={}), Pod("b", requests={})]
        ec, ep, st, _ = masks_for(cluster, pods, prebind=[(0, 0)])
        assert not K.fit_mask(ec, st, ep, 1)[0]

    def test_extended_resource(self):
        cluster = Cluster(
            nodes=[Node("gpu", {"cpu": 4, "nvidia.com/gpu": 2}), Node("plain", {"cpu": 4})]
        )
        pods = [Pod("wants-gpu", requests={"nvidia.com/gpu": 1})]
        ec, ep, st, _ = masks_for(cluster, pods)
        m = K.fit_mask(ec, st, ep, 0)
        assert m[0] and not m[1]

    def test_least_allocated_prefers_empty(self):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 4, "memory": 8 * 2**30}),
                                 Node("n1", {"cpu": 4, "memory": 8 * 2**30})])
        pods = [Pod("a", requests={"cpu": 2}), Pod("b", requests={"cpu": 1})]
        ec, ep, st, _ = masks_for(cluster, pods, prebind=[(0, 0)])
        w = np.zeros(ec.num_resources, dtype=np.float32)
        w[ec.vocab._r["cpu"]] = 1
        w[ec.vocab._r["memory"]] = 1
        s = K.least_allocated_score(ec, st, ep, 1, w)
        assert s[1] > s[0]

    def test_most_allocated_prefers_packed(self):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 4}), Node("n1", {"cpu": 4})])
        pods = [Pod("a", requests={"cpu": 2}), Pod("b", requests={"cpu": 1})]
        ec, ep, st, _ = masks_for(cluster, pods, prebind=[(0, 0)])
        w = np.zeros(ec.num_resources, dtype=np.float32)
        w[ec.vocab._r["cpu"]] = 1
        s = K.most_allocated_score(ec, st, ep, 1, w)
        assert s[0] > s[1]


class TestTaintToleration:
    """Toleration operator matrix ([K8S] v1.Toleration)."""

    CASES = [
        # (taint, toleration, tolerated?)
        (Taint("k", "v", "NoSchedule"), Toleration(key="k", operator="Equal", value="v"), True),
        (Taint("k", "v", "NoSchedule"), Toleration(key="k", operator="Equal", value="w"), False),
        (Taint("k", "v", "NoSchedule"), Toleration(key="k", operator="Exists"), True),
        (Taint("k", "v", "NoSchedule"), Toleration(key="other", operator="Exists"), False),
        (Taint("k", "v", "NoSchedule"), Toleration(key=None, operator="Exists"), True),
        (Taint("k", "v", "NoSchedule"),
         Toleration(key="k", operator="Equal", value="v", effect="NoExecute"), False),
        (Taint("k", "v", "NoExecute"),
         Toleration(key="k", operator="Equal", value="v", effect="NoExecute"), True),
    ]

    @pytest.mark.parametrize("taint,tol,want", CASES)
    def test_matrix(self, taint, tol, want):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 1}, taints=[taint])])
        pods = [Pod("p", tolerations=[tol])]
        ec, ep, st, _ = masks_for(cluster, pods)
        assert bool(K.taint_mask(ec, ep, 0)[0]) == want

    def test_prefer_no_schedule_scores_not_filters(self):
        cluster = Cluster(
            nodes=[Node("soft", {"cpu": 1}, taints=[Taint("k", "v", "PreferNoSchedule")]),
                   Node("clean", {"cpu": 1})]
        )
        pods = [Pod("p")]
        ec, ep, st, _ = masks_for(cluster, pods)
        assert K.taint_mask(ec, ep, 0).all()
        cnt = K.taint_prefer_count(ec, ep, 0)
        assert cnt[0] == 1 and cnt[1] == 0
        norm = K.normalize_max(cnt, np.array([True, True]), reverse=True)
        assert norm[1] > norm[0]


class TestNodeAffinity:
    """Operator matrix over required nodeSelectorTerms ([K8S] nodeaffinity)."""

    @pytest.mark.parametrize(
        "op,vals,labels,want",
        [
            ("In", ["ssd"], {"disk": "ssd"}, True),
            ("In", ["ssd"], {"disk": "hdd"}, False),
            ("In", ["ssd"], {}, False),
            ("NotIn", ["ssd"], {"disk": "hdd"}, True),
            ("NotIn", ["ssd"], {"disk": "ssd"}, False),
            ("NotIn", ["ssd"], {}, True),
            ("Exists", [], {"disk": "x"}, True),
            ("Exists", [], {}, False),
            ("DoesNotExist", [], {}, True),
            ("DoesNotExist", [], {"disk": "x"}, False),
            ("Gt", ["4"], {"disk": "9"}, True),
            ("Gt", ["4"], {"disk": "3"}, False),
            ("Gt", ["4"], {"disk": "abc"}, False),
            ("Lt", ["4"], {"disk": "3"}, True),
            ("Lt", ["4"], {"disk": "9"}, False),
        ],
    )
    def test_operator_matrix(self, op, vals, labels, want):
        cluster = Cluster(nodes=[Node("n0", {"cpu": 1}, labels=dict(labels))])
        pod = Pod(
            "p",
            node_affinity=NodeAffinitySpec(
                required=(NodeSelectorTerm((MatchExpression.make("disk", op, vals),)),)
            ),
        )
        ec, ep, st, M = masks_for(cluster, [pod])
        assert bool(K.node_affinity_mask(M, ep, 0)[0]) == want

    def test_terms_are_ored_expressions_anded(self):
        cluster = Cluster(
            nodes=[Node("n0", {"cpu": 1}, labels={"a": "1", "b": "2"}),
                   Node("n1", {"cpu": 1}, labels={"a": "1"}),
                   Node("n2", {"cpu": 1}, labels={"c": "3"})]
        )
        pod = Pod(
            "p",
            node_affinity=NodeAffinitySpec(
                required=(
                    NodeSelectorTerm(
                        (MatchExpression.make("a", "In", ["1"]), MatchExpression.make("b", "In", ["2"]))
                    ),
                    NodeSelectorTerm((MatchExpression.make("c", "In", ["3"]),)),
                )
            ),
        )
        ec, ep, st, M = masks_for(cluster, [pod])
        m = K.node_affinity_mask(M, ep, 0)
        assert m[0] and not m[1] and m[2]


class TestInterPodAffinity:
    def _cluster(self):
        return Cluster(
            nodes=[
                Node("a1", {"cpu": 8}, labels={"zone": "za"}),
                Node("a2", {"cpu": 8}, labels={"zone": "za"}),
                Node("b1", {"cpu": 8}, labels={"zone": "zb"}),
            ]
        )

    def test_required_affinity_needs_matching_pod_in_domain(self):
        pods = [
            Pod("web", labels={"app": "web"}),
            Pod(
                "follower",
                pod_affinity=PodAffinitySpec(
                    required=(PodAffinityTerm(LabelSelector.make({"app": "web"}), "zone"),)
                ),
            ),
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods, prebind=[(0, 0)])
        m = K.interpod_filter_mask(ec, st, ep, 1)
        assert m[0] and m[1] and not m[2]

    def test_bootstrap_self_match(self):
        """First pod matching its own affinity term may go anywhere [K8S]."""
        pods = [
            Pod(
                "seed",
                labels={"app": "web"},
                pod_affinity=PodAffinitySpec(
                    required=(PodAffinityTerm(LabelSelector.make({"app": "web"}), "zone"),)
                ),
            )
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods)
        assert K.interpod_filter_mask(ec, st, ep, 0).all()

    def test_anti_affinity_blocks_domain(self):
        pods = [
            Pod("lead", labels={"role": "leader"}),
            Pod(
                "rival",
                pod_anti_affinity=PodAffinitySpec(
                    required=(PodAffinityTerm(LabelSelector.make({"role": "leader"}), "zone"),)
                ),
            ),
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods, prebind=[(0, 0)])
        m = K.interpod_filter_mask(ec, st, ep, 1)
        assert not m[0] and not m[1] and m[2]

    def test_symmetric_anti_affinity(self):
        """A placed pod's anti-affinity term rejects matching newcomers."""
        pods = [
            Pod(
                "hermit",
                labels={"app": "web"},
                pod_anti_affinity=PodAffinitySpec(
                    required=(PodAffinityTerm(LabelSelector.make({"app": "web"}), "zone"),)
                ),
            ),
            Pod("web2", labels={"app": "web"}),
            Pod("other", labels={"app": "db"}),
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods, prebind=[(0, 0)])
        m_web = K.interpod_filter_mask(ec, st, ep, 1)
        m_db = K.interpod_filter_mask(ec, st, ep, 2)
        assert not m_web[0] and not m_web[1] and m_web[2]
        assert m_db.all()


class TestPodTopologySpread:
    def _cluster(self):
        return Cluster(
            nodes=[
                Node("a1", {"cpu": 8}, labels={"zone": "za"}),
                Node("b1", {"cpu": 8}, labels={"zone": "zb"}),
                Node("nolabel", {"cpu": 8}, labels={}),
            ]
        )

    def test_max_skew_boundary(self):
        sel = LabelSelector.make({"app": "web"})
        pods = [
            Pod("w1", labels={"app": "web"}),
            Pod("w2", labels={"app": "web"}),
            Pod(
                "w3",
                labels={"app": "web"},
                topology_spread=[
                    TopologySpreadConstraint(1, "zone", "DoNotSchedule", sel)
                ],
            ),
        ]
        # za has 2 pods, zb has 0 → placing in za gives skew 3 > 1; zb ok.
        ec, ep, st, _ = masks_for(self._cluster(), pods, prebind=[(0, 0), (1, 0)])
        m = K.spread_filter_mask(ec, st, ep, 2)
        assert not m[0] and m[1]
        # Node without the topology key always fails DoNotSchedule.
        assert not m[2]

    def test_schedule_anyway_does_not_filter(self):
        sel = LabelSelector.make({"app": "web"})
        pods = [
            Pod("w1", labels={"app": "web"}),
            Pod("w2", labels={"app": "web"},
                topology_spread=[TopologySpreadConstraint(1, "zone", "ScheduleAnyway", sel)]),
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods, prebind=[(0, 0)])
        m = K.spread_filter_mask(ec, st, ep, 1)
        assert m[0] and m[1]
        s = K.spread_score(ec, st, ep, 1)
        assert s[1] < s[0]  # zb less crowded → lower raw (better after reverse)

    def test_upstream_scoring_values(self):
        # [K8S] podtopologyspread scoring.go: raw = round(cnt·log(size+2) +
        # (maxSkew−1)) (int64(math.Round)); NormalizeScore =
        # 100·(max+min−s)//max.
        import math

        sel = LabelSelector.make({"app": "web"})
        pods = [
            Pod("w1", labels={"app": "web"}),
            Pod("w2", labels={"app": "web"}),
            Pod("w3", labels={"app": "web"},
                topology_spread=[
                    TopologySpreadConstraint(2, "zone", "ScheduleAnyway", sel)
                ]),
        ]
        ec, ep, st, _ = masks_for(
            self._cluster(), pods, prebind=[(0, 0), (1, 0)]
        )
        s = K.spread_score(ec, st, ep, 2)
        w = math.log(2 + 2)  # 2 zone domains
        # za: 2 matching pods → 2·log4 + 1 = 3.77 → ROUNDS to 4 (a floor
        # would give 3 — this case discriminates round from truncate).
        assert s[0] == math.floor(2 * w + 1 + 0.5) == 4
        assert s[1] == math.floor(0 * w + 1 + 0.5) == 1  # zb: empty
        assert s[2] == -1.0  # missing key → ignored sentinel
        out = K.spread_normalize(s, np.ones(3, bool))
        hi, lo = int(s[0]), int(s[1])
        assert out[0] == (100 * (hi + lo - hi)) // hi == 25
        assert out[1] == (100 * (hi + lo - lo)) // hi == 100
        assert out[2] == 0.0  # ignored normalizes to 0

    def test_dns_only_constraints_skip_scoring(self):
        # Only DoNotSchedule constraints → PreScore Skip (None): the
        # plugin contributes nothing to the weighted sum.
        sel = LabelSelector.make({"app": "web"})
        pods = [
            Pod("w1", labels={"app": "web"},
                topology_spread=[
                    TopologySpreadConstraint(1, "zone", "DoNotSchedule", sel)
                ]),
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods)
        assert K.spread_score(ec, st, ep, 0) is None

    def test_max_zero_normalizes_to_100(self):
        # Empty cluster state: all raw 0 (skew 1 → maxSkew−1 = 0) → every
        # non-ignored node scores MaxNodeScore, upstream maxScore==0 rule.
        sel = LabelSelector.make({"app": "web"})
        pods = [
            Pod("w1", labels={"app": "web"},
                topology_spread=[
                    TopologySpreadConstraint(1, "zone", "ScheduleAnyway", sel)
                ]),
        ]
        ec, ep, st, _ = masks_for(self._cluster(), pods)
        s = K.spread_score(ec, st, ep, 0)
        out = K.spread_normalize(s, np.ones(3, bool))
        assert out[0] == 100 and out[1] == 100 and out[2] == 0


class TestDefaultSpreadConstraints:
    def test_system_defaulting_injects_and_spreads(self):
        from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
        from kubernetes_simulator_tpu.models.encode import encode
        from kubernetes_simulator_tpu.plugins.builtin import inject_default_spread
        from kubernetes_simulator_tpu.sim.greedy import greedy_replay
        from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
        from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

        plugins = [
            {"name": "NodeResourcesFit"},
            {"name": "PodTopologySpread", "args": {"defaultingType": "System"}},
        ]
        cfg = FrameworkConfig(plugins=plugins)
        cluster = make_cluster(24, seed=11, num_zones=4)
        pods, _ = make_workload(60, seed=11)
        assert not any(p.topology_spread for p in pods)
        inject_default_spread(pods, cfg)
        # Every labeled pod got the hostname+zone ScheduleAnyway pair.
        assert all(len(p.topology_spread) == 2 for p in pods)
        assert all(
            c.when_unsatisfiable == "ScheduleAnyway"
            for p in pods for c in p.topology_spread
        )
        ec, ep = encode(cluster, pods)
        cpu = greedy_replay(ec, ep, cfg)
        dev = JaxReplayEngine(ec, ep, cfg).replay()
        np.testing.assert_array_equal(cpu.assignments, dev.assignments)

    def test_no_defaulting_without_config(self):
        from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
        from kubernetes_simulator_tpu.plugins.builtin import inject_default_spread
        from kubernetes_simulator_tpu.sim.synthetic import make_workload

        pods, _ = make_workload(10, seed=0)
        inject_default_spread(pods, FrameworkConfig())  # default plugin list
        assert not any(p.topology_spread for p in pods)

    def test_explicit_default_constraints(self):
        from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
        from kubernetes_simulator_tpu.plugins.builtin import inject_default_spread
        from kubernetes_simulator_tpu.sim.synthetic import make_workload

        plugins = [{
            "name": "PodTopologySpread",
            "args": {"defaultConstraints": [
                {"maxSkew": 1, "topologyKey": "topology.kubernetes.io/zone",
                 "whenUnsatisfiable": "DoNotSchedule"},
            ]},
        }]
        pods, _ = make_workload(10, seed=0)
        inject_default_spread(pods, FrameworkConfig(plugins=plugins))
        assert all(len(p.topology_spread) == 1 for p in pods)
        assert pods[0].topology_spread[0].when_unsatisfiable == "DoNotSchedule"
