"""Round-20 durable ground (ISSUE round 20): the filesystem-backed
durability journal (``KSIM_DCN_DURABLE_DIR`` / ``dcn.durable:``) that
makes WHOLE-FLEET death — coordinator included — restartable.

Fast, in-process pins (the live supervised-restart drills ride the slow
faultline fuzz suite): the journal mirror writes the same framed bytes
as the KV plane with manifest-last / temp-then-rename discipline;
``load_checkpoint`` seeds an EMPTY KV plane from the journal and walks
torn/truncated/stale journal blobs through the exact round-17
prior-complete-cursor fallback; ``wq_run`` adopts a dead fleet's
completed blocks without re-execution; the faultline ``all`` kill token
parses and fires for every pid while ``KSIM_DCN_RESTART_COUNT`` disarms
kill schedules in relaunched fleets; and the ``dcn.durable`` YAML
section round-trips with its validate_config refusals.
"""

import json
import os
import sys

import numpy as np
import pytest

sys.path.insert(
    0, os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
)

from kubernetes_simulator_tpu.parallel import dcn  # noqa: E402
from kubernetes_simulator_tpu.parallel import faultline  # noqa: E402
from kubernetes_simulator_tpu.utils.config import SimConfig  # noqa: E402


class _FakeKV:
    """In-memory stand-in for the jaxlib coordination-service KV client."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        if not allow_overwrite and key in self.store:
            raise RuntimeError(f"key exists: {key}")
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        import time

        if key in self.store:
            return self.store[key]
        time.sleep(timeout_ms / 1000.0)
        raise RuntimeError(f"Deadline Exceeded: {key}")

    def key_value_dir_get(self, prefix):
        return [
            (k, v) for k, v in sorted(self.store.items())
            if k.startswith(prefix)
        ]


def _fleet(monkeypatch, nproc=2, pid=1, journal=None):
    kv = _FakeKV()
    monkeypatch.setattr(dcn, "process_info", lambda: (nproc, pid))
    monkeypatch.setattr(dcn, "_client", lambda: kv)
    monkeypatch.setattr(dcn, "_degraded_exit_armed", [True])
    monkeypatch.setattr(dcn, "DEGRADED", set())
    if journal is not None:
        monkeypatch.setenv("KSIM_DCN_DURABLE_DIR", str(journal))
    else:
        monkeypatch.delenv("KSIM_DCN_DURABLE_DIR", raising=False)
    monkeypatch.delenv("KSIM_DCN_RESUME", raising=False)
    return kv


def _payload(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "cursor": 3,
        "leaves": {"states": rng.integers(-1, 64, size=(8, 16),
                                          dtype=np.int32)},
    }


# -- journal writer discipline ----------------------------------------------


def test_journal_blob_roundtrip_and_manifest_last(tmp_path, monkeypatch):
    """A mirrored blob reads back byte-identical through the full
    integrity stack, leaves no temp files, and a blob directory missing
    its manifest is invisible to the checkpoint-entry scan (the exact KV
    in-flight rule)."""
    monkeypatch.setenv("KSIM_DCN_DURABLE_DIR", str(tmp_path))
    pay = _payload(1)
    raw = dcn._encode_payload(pay)
    import zlib

    crc, blob_len = 0, 0
    for ch in raw:
        crc = zlib.crc32(ch.encode("ascii"), crc)
        blob_len += len(ch)
    manifest = json.dumps(
        {"n": len(raw), "crc": f"{crc & 0xFFFFFFFF:08x}", "len": blob_len},
        sort_keys=True,
    )
    sub = os.path.join("ckpt", "7", "1", "4-8", "3")
    assert dcn._journal_write_blob(
        sub, [dcn._frame_chunk(ch) for ch in raw], manifest
    )
    d = tmp_path / "ckpt" / "7" / "1" / "4-8" / "3"
    assert (d / "manifest.json").exists()
    assert not list(tmp_path.rglob("*.tmp")), "temp file left behind"
    got = dcn._journal_read_blob(sub)
    np.testing.assert_array_equal(
        got["leaves"]["states"], pay["leaves"]["states"]
    )
    # No manifest ⇒ in flight ⇒ skipped by the resume scan.
    os.remove(d / "manifest.json")
    assert dcn._journal_ckpt_entries(1, 7) == {}


def test_journal_write_noop_without_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("KSIM_DCN_DURABLE_DIR", raising=False)
    assert dcn.durable_dir() is None
    assert dcn._journal_write_blob("ckpt/1/0/0-4/0", ["x"], "{}") is False
    assert dcn._journal_write_json("wq/1/g/done/0", {"pid": 0}) is False
    assert not list(tmp_path.iterdir())


# -- checkpoint mirror + resume seeding --------------------------------------


def test_publish_checkpoint_mirrors_journal(tmp_path, monkeypatch):
    """publish_checkpoint writes the SAME framed bytes to the KV plane
    and the journal (manifest included), stamps the mirror in
    JOURNAL_STATS, and flags the ckpt_publish event — and with the
    journal unset the event is byte-unchanged from round 19."""
    kv = _fleet(monkeypatch, nproc=2, pid=1, journal=tmp_path)
    events = []
    monkeypatch.setattr(dcn, "EVENT_SINKS", [events.append])
    js0 = dcn.journal_stats()
    assert dcn.publish_checkpoint(3, _payload(2), (4, 8), epoch=7)
    js1 = dcn.journal_stats()
    assert js1["writes"] == js0["writes"] + 1
    assert js1["bytes"] > js0["bytes"]
    d = tmp_path / "ckpt" / "7" / "1" / "4-8" / "3"
    man = json.loads((d / "manifest.json").read_text())
    assert man == json.loads(kv.store["ksim/ckpt/7/1/4-8/3/n"])
    for j in range(int(man["n"])):
        assert (d / str(j)).read_text() == kv.store[f"ksim/ckpt/7/1/4-8/3/{j}"]
    pub = [e for e in events if e.get("kind") == "ckpt_publish"]
    assert pub and pub[-1].get("journal") == 1
    # Journal off: same publication, no journal key in the event.
    events.clear()
    kv2 = _fleet(monkeypatch, nproc=2, pid=1, journal=None)
    monkeypatch.setattr(dcn, "EVENT_SINKS", [events.append])
    assert dcn.publish_checkpoint(3, _payload(2), (4, 8), epoch=7)
    pub = [e for e in events if e.get("kind") == "ckpt_publish"]
    assert pub and "journal" not in pub[-1]
    assert kv2.store["ksim/ckpt/7/1/4-8/3/n"] == kv.store[
        "ksim/ckpt/7/1/4-8/3/n"
    ]


def test_load_checkpoint_seeds_fresh_kv_from_journal(tmp_path, monkeypatch):
    """The restart path: fleet A publishes with the journal on, dies;
    fleet B (EMPTY KV plane) load_checkpoints the same pid/epoch and
    gets A's newest checkpoint from the journal — with the
    journal_resume event mirrored for the watcher."""
    _fleet(monkeypatch, nproc=2, pid=1, journal=tmp_path)
    pay1, pay3 = _payload(3), _payload(4)
    assert dcn.publish_checkpoint(1, pay1, (4, 8), epoch=7)
    assert dcn.publish_checkpoint(3, pay3, (4, 8), epoch=7)
    # Fresh fleet: new KV store, same journal.
    _fleet(monkeypatch, nproc=2, pid=0, journal=tmp_path)
    events = []
    monkeypatch.setattr(dcn, "EVENT_SINKS", [events.append])
    js0 = dcn.journal_stats()
    got = dcn.load_checkpoint(1, epoch=7)
    assert got["cursor"] == 3 and got["block"] == (4, 8)
    np.testing.assert_array_equal(
        got["payload"]["leaves"]["states"], pay3["leaves"]["states"]
    )
    assert dcn.journal_stats()["resumes"] == js0["resumes"] + 1
    res = [e for e in events if e.get("event") == "journal_resume"]
    assert res and res[-1]["cursor"] == 3 and res[-1]["block"] == [4, 8]
    # before_cursor honored on journal candidates (the stale-payload
    # retry path): strictly older cursors only.
    assert dcn.load_checkpoint(1, epoch=7, before_cursor=3)["cursor"] == 1
    # Epoch isolation holds for the journal exactly like the KV plane.
    assert dcn.load_checkpoint(1, epoch=8) is None


def test_torn_journal_chunk_falls_back_to_prior_cursor(
    tmp_path, monkeypatch
):
    """Satellite 4: a journal blob torn by a crash (or the faultline
    torn-write injector) fails frame validation on resume and the reader
    falls back to the PRIOR complete durable cursor, counting the
    fallback in CRC_STATS."""
    _fleet(monkeypatch, nproc=2, pid=1, journal=tmp_path)
    assert dcn.publish_checkpoint(1, _payload(5), (4, 8), epoch=7)
    assert dcn.publish_checkpoint(3, _payload(6), (4, 8), epoch=7)
    # Tear the newest cursor's first chunk mid-file (manifest intact —
    # exactly what a crash between replace()s can leave).
    chunk = tmp_path / "ckpt" / "7" / "1" / "4-8" / "3" / "0"
    blob = chunk.read_text()
    chunk.write_text(blob[: len(blob) // 2])
    _fleet(monkeypatch, nproc=2, pid=0, journal=tmp_path)
    crc0 = dict(dcn.CRC_STATS)
    got = dcn.load_checkpoint(1, epoch=7)
    assert got["cursor"] == 1, "torn newest blob must not win"
    assert dcn.CRC_STATS["fallbacks"] > crc0["fallbacks"]
    # Truncated to nothing ⇒ same fallback; missing manifest ⇒ the
    # cursor is invisible (in-flight rule) rather than a fallback.
    chunk.write_text("")
    assert dcn.load_checkpoint(1, epoch=7)["cursor"] == 1
    os.remove(tmp_path / "ckpt" / "7" / "1" / "4-8" / "3" / "manifest.json")
    crc1 = dict(dcn.CRC_STATS)
    assert dcn.load_checkpoint(1, epoch=7)["cursor"] == 1
    assert dcn.CRC_STATS["fallbacks"] == crc1["fallbacks"]


# -- work-queue adoption -----------------------------------------------------


def test_wq_scan_adopts_done_blocks_and_rejects_torn(tmp_path, monkeypatch):
    """_journal_wq_scan adopts blocks whose done record AND result blob
    validate, drops a done record over a torn result (the block
    re-executes, counted as a CRC fallback), and surfaces the newest
    durable lease holder for unfinished blocks."""
    monkeypatch.setenv("KSIM_DCN_DURABLE_DIR", str(tmp_path))
    jbase = os.path.join("wq", "1", "g")
    for bid in (0, 1):
        assert dcn._journal_wq_result(jbase, bid, _payload(10 + bid))
        assert dcn._journal_write_json(
            os.path.join(jbase, "done", str(bid)),
            {"pid": 1, "gen": 0, "spec": False},
        )
    assert dcn._journal_write_json(
        os.path.join(jbase, "lease", "2"), {"pid": 1, "gen": 0, "t": 0.0}
    )
    # Tear block 1's result.
    chunk = tmp_path / "wq" / "1" / "g" / "result" / "1" / "0"
    chunk.write_text(chunk.read_text()[:10])
    crc0 = dict(dcn.CRC_STATS)
    adopted, hint = dcn._journal_wq_scan(1, "g", 3)
    assert sorted(adopted) == [0]
    meta, pay = adopted[0]
    assert meta["pid"] == 1
    np.testing.assert_array_equal(
        pay["leaves"]["states"], _payload(10)["leaves"]["states"]
    )
    assert hint == {2: 1}
    assert dcn.CRC_STATS["fallbacks"] > crc0["fallbacks"]


def test_wq_run_adopts_journal_without_reexecution(tmp_path, monkeypatch):
    """The tentpole resume bar, in-process: run a work queue with the
    journal on, then bring up a FRESH fleet (empty KV) over the same
    journal with KSIM_DCN_RESUME=1 — every block is adopted without
    calling execute, and the assembled gather is byte-identical."""
    monkeypatch.setenv("KSIM_DCN_STALL_S", "60")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.05")
    blocks = [(0, 4), (4, 8), (8, 12)]

    def execute(bid, lo, hi, resume_pid, gen, speculative, qd):
        return {"bid": bid, "rows": list(range(lo, hi))}

    _fleet(monkeypatch, nproc=1, pid=0, journal=tmp_path)
    monkeypatch.setattr(dcn, "_seq", 0)
    first = dcn.wq_run("g", blocks, execute)
    assert [p["bid"] for p in first] == [0, 1, 2]

    def boom(*a, **k):
        raise AssertionError("an adopted block must not re-execute")

    _fleet(monkeypatch, nproc=1, pid=0, journal=tmp_path)
    monkeypatch.setenv("KSIM_DCN_RESUME", "1")
    monkeypatch.setattr(dcn, "_seq", 0)
    events = []
    monkeypatch.setattr(dcn, "EVENT_SINKS", [events.append])
    js0 = dcn.journal_stats()
    second = dcn.wq_run("g", blocks, boom)
    assert second == first
    assert dcn.journal_stats()["adopted"] == js0["adopted"] + 3
    adopts = [e for e in events if e.get("event") == "journal_adopt"]
    assert sorted(e["block"] for e in adopts) == [0, 1, 2]
    # Without resume the journal alone changes nothing: the queue
    # re-executes (fresh KV again, resume off).
    _fleet(monkeypatch, nproc=1, pid=0, journal=tmp_path)
    monkeypatch.setattr(dcn, "_seq", 0)
    third = dcn.wq_run("g", blocks, execute)
    assert third == first


# -- faultline: the all token + restart disarm -------------------------------


def test_parse_kill_schedule_all_token():
    assert faultline.parse_kill_schedule("all@run:1") == [("all", "run", 1)]
    assert faultline.parse_kill_schedule("0@run:1,all@run:2") == [
        ("0", "run", 1), ("all", "run", 2),
    ]
    with pytest.raises(ValueError):
        faultline.parse_kill_schedule("some@run:1")


def test_maybe_kill_all_fires_and_restart_disarms(monkeypatch):
    """The ``all`` token kills EVERY pid (no CAS, coordinator included)
    — and any kill schedule is inert once KSIM_DCN_RESTART_COUNT > 0
    (the supervised relaunch replays the same config without re-dying
    at the same chunk)."""
    kills = []
    monkeypatch.setattr(faultline.os, "kill", lambda p, s: kills.append(p))
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_KILL", "all@run:1")
    monkeypatch.delenv("KSIM_DCN_RESTART_COUNT", raising=False)
    for pid in (0, 2):
        faultline.reset()
        monkeypatch.setenv("KSIM_DCN_PID", str(pid))
        faultline.maybe_kill(0, "run")
        assert kills == []  # below the chunk threshold
        faultline.maybe_kill(1, "run")
        assert kills == [os.getpid()]
        kills.clear()
    # A relaunched fleet replays the same schedule without dying.
    monkeypatch.setenv("KSIM_DCN_RESTART_COUNT", "1")
    faultline.reset()
    faultline.maybe_kill(1, "run")
    assert kills == []
    faultline.reset()


# -- config + validate refusals ----------------------------------------------


def _write(tmp_path, text):
    p = tmp_path / "c.yaml"
    p.write_text(text)
    return str(p)


def test_config_durable_parsing(tmp_path):
    cfg = SimConfig.load(_write(tmp_path, """
strategy: jax
dcn:
  recovery: {enable: true, checkpointEvery: 2}
  durable: {dir: /tmp/j, resume: true}
"""))
    assert cfg.dcn_durable.dir == "/tmp/j"
    assert cfg.dcn_durable.resume is True
    # Bare-string shorthand: dir only, no resume.
    cfg = SimConfig.load(_write(tmp_path, """
strategy: jax
dcn:
  recovery: {enable: true, checkpointEvery: 2}
  durable: /tmp/j2
"""))
    assert cfg.dcn_durable.dir == "/tmp/j2"
    assert cfg.dcn_durable.resume is False


def test_validate_refuses_durable_without_fleet(tmp_path, monkeypatch):
    from kubernetes_simulator_tpu.cli import _durable_errors

    monkeypatch.delenv("KSIM_DCN_NPROC", raising=False)
    cfg = SimConfig.load(_write(tmp_path, f"""
strategy: jax
dcn:
  recovery: {{enable: true, checkpointEvery: 1}}
  durable: {tmp_path / 'j'}
"""))
    errs = _durable_errors(cfg)
    assert any("dcn_launch" in e for e in errs)
    monkeypatch.setenv("KSIM_DCN_NPROC", "3")
    assert _durable_errors(cfg) == []


def test_validate_refuses_durable_without_checkpoints(tmp_path, monkeypatch):
    from kubernetes_simulator_tpu.cli import _durable_errors

    monkeypatch.setenv("KSIM_DCN_NPROC", "3")
    cfg = SimConfig.load(_write(tmp_path, f"""
strategy: jax
dcn:
  durable: {tmp_path / 'j'}
"""))
    errs = _durable_errors(cfg)
    assert any("checkpointEvery" in e for e in errs)
    # A work queue is a checkpoint cadence too (per-block epochs).
    cfg = SimConfig.load(_write(tmp_path, f"""
strategy: jax
dcn:
  workQueue: {{enable: true}}
  durable: {tmp_path / 'j'}
"""))
    assert _durable_errors(cfg) == []


def test_validate_refuses_resume_without_dir(tmp_path):
    from kubernetes_simulator_tpu.cli import _durable_errors

    cfg = SimConfig.load(_write(tmp_path, """
strategy: jax
dcn:
  recovery: {enable: true, checkpointEvery: 1}
  durable: {resume: true}
"""))
    errs = _durable_errors(cfg)
    assert any("resume" in e for e in errs)


def test_validate_accepts_example_config19(tmp_path, monkeypatch):
    from kubernetes_simulator_tpu.cli import validate_config

    monkeypatch.setenv("KSIM_DCN_NPROC", "3")
    path = os.path.join(
        os.path.dirname(__file__), "..", "examples", "config19_durable.yaml"
    )
    cfg = SimConfig.load(path)
    assert cfg.dcn_durable is not None and cfg.dcn_durable.dir
    # Point the journal at a writable scratch dir for the probe.
    cfg.dcn_durable.dir = str(tmp_path / "journal")
    assert validate_config(cfg) == []
