"""Policy tuner (round 9, sim.tuner): traced policy-vector parity vs the
static-weight programs, single-compile population sweeps, search
improvement on the held-out split, the CPU-oracle envelope, trajectory
determinism, and schema-v3 JSONL validation."""

import json
import os
import sys

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.ops import tpu as T
from kubernetes_simulator_tpu.parallel.mesh import fit_population, make_mesh
from kubernetes_simulator_tpu.plugins.builtin import tunable_parameters
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.tuner import (
    PolicyTuner,
    SearchSpace,
    make_objective,
)
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

_SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")
sys.path.insert(0, os.path.abspath(_SCRIPTS))

from check_metrics_schema import validate_file, validate_row  # noqa: E402


def small_case(seed=0, n=15, p=80):
    cluster = make_cluster(n, seed=seed, taint_fraction=0.1)
    pods, _ = make_workload(p, seed=seed, with_affinity=True, with_spread=True,
                            with_tolerations=True)
    return encode(cluster, pods)


def _tile(vec, s):
    return np.repeat(np.asarray(vec, np.float32)[None], s, axis=0)


def _default_vec(config=None):
    return SearchSpace.from_config(config).defaults


# -- traced policy vector vs the static-weight program ---------------------


def test_default_policy_vector_matches_static():
    """Weights equal to the config's own ⇒ bit-identical placements: the
    per-row normalize extrema are weight-independent, so a traced weight
    with the static value reproduces the static fold exactly."""
    ec, ep = small_case()
    cfg = FrameworkConfig()
    base = WhatIfEngine(ec, ep, [Scenario()] * 3, cfg,
                        collect_assignments=True).run()
    pol = WhatIfEngine(ec, ep, [Scenario()] * 3, cfg,
                       collect_assignments=True,
                       policies=_tile(_default_vec(cfg), 3)).run()
    assert (base.assignments == pol.assignments).all()
    assert (np.asarray(base.placed) == np.asarray(pol.placed)).all()


@pytest.mark.slow
def test_nondefault_weights_and_strategy_parity():
    """A non-default weight vector + the MostAllocated selector must match
    a static config carrying the same weights and strategy."""
    ec, ep = small_case(seed=2)
    weights = {"NodeResourcesFit": 2.5, "TaintToleration": 0.5,
               "NodeAffinity": 4.0, "InterPodAffinity": 1.5,
               "PodTopologySpread": 3.0}
    static_cfg = FrameworkConfig().with_policy(
        weights, fit_strategy="MostAllocated"
    )
    static = WhatIfEngine(ec, ep, [Scenario()] * 2, static_cfg,
                          collect_assignments=True).run()
    vec = np.array([weights[n] for n in T.POLICY_WEIGHT_COLS] + [0.0],
                   np.float32)  # fit_least=0 → MostAllocated
    traced = WhatIfEngine(ec, ep, [Scenario()] * 2, FrameworkConfig(),
                          collect_assignments=True,
                          policies=_tile(vec, 2)).run()
    assert (static.assignments == traced.assignments).all()


@pytest.mark.slow
def test_per_scenario_policies_differ():
    """Different vectors on different scenarios of ONE batch actually
    produce the per-policy outcomes (the population sweep mechanism)."""
    ec, ep = small_case(seed=1)
    cfg = FrameworkConfig()
    least = _default_vec(cfg).copy()
    most = least.copy()
    most[T.IDX_FIT_LEAST] = 0.0
    batch = WhatIfEngine(ec, ep, [Scenario()] * 2, cfg,
                         collect_assignments=True,
                         policies=np.stack([least, most])).run()
    ref_most = WhatIfEngine(
        ec, ep, [Scenario()],
        FrameworkConfig().with_policy({}, fit_strategy="MostAllocated"),
        collect_assignments=True,
    ).run()
    ref_least = WhatIfEngine(ec, ep, [Scenario()], cfg,
                             collect_assignments=True).run()
    assert (batch.assignments[0] == ref_least.assignments[0]).all()
    assert (batch.assignments[1] == ref_most.assignments[0]).all()


@pytest.mark.slow
def test_population_sweep_single_compile():
    """set_policies swaps values only — the chunk program must not
    recompile across rounds (the tuner's whole-search pin)."""
    ec, ep = small_case(seed=3, n=10, p=48)
    rng = np.random.default_rng(0)
    S = 6
    eng = WhatIfEngine(ec, ep, [Scenario()] * S, FrameworkConfig(),
                       policies=_tile(_default_vec(), S))
    eng.run()
    for _ in range(3):
        vals = rng.uniform(0.0, 10.0, size=(S, len(T.POLICY_COLS)))
        vals[:, T.IDX_FIT_LEAST] = (rng.random(S) < 0.5)
        eng.set_policies(vals.astype(np.float32))
        res = eng.run()
        assert res.placed.shape == (S,)
    assert eng._chunk_fn._cache_size() == 1


@pytest.mark.slow
def test_mesh_policy_sweep_matches_vmap():
    ec, ep = small_case(seed=4, n=12, p=64)
    cfg = FrameworkConfig()
    S = 8
    rng = np.random.default_rng(5)
    pol = rng.uniform(0.0, 8.0, size=(S, len(T.POLICY_COLS))).astype(np.float32)
    pol[:, T.IDX_FIT_LEAST] = (rng.random(S) < 0.5)
    vmapped = WhatIfEngine(ec, ep, [Scenario()] * S, cfg, policies=pol).run()
    meshed = WhatIfEngine(ec, ep, [Scenario()] * S, cfg, policies=pol,
                          mesh=make_mesh()).run()
    assert (np.asarray(vmapped.placed) == np.asarray(meshed.placed)).all()
    assert (
        np.asarray(vmapped.unschedulable) == np.asarray(meshed.unschedulable)
    ).all()


# -- guard rails -----------------------------------------------------------


def test_policies_rejected_on_unsupported_paths():
    # Finite durations so retry_buffer itself is a VALID configuration —
    # the error under test is the policies gate, not the retry gate.
    cluster = make_cluster(8, seed=0)
    pods, _ = make_workload(32, seed=0, duration_mean=0.5)
    ec, ep = encode(cluster, pods)
    pol = _tile(_default_vec(), 2)
    with pytest.raises(ValueError, match="policies"):
        WhatIfEngine(ec, ep, [Scenario()] * 2, FrameworkConfig(),
                     policies=pol, retry_buffer=8)
    with pytest.raises(ValueError, match="policies"):
        WhatIfEngine(ec, ep, [Scenario()] * 2, FrameworkConfig(),
                     policies=pol, preemption="tier")


def test_set_policies_shape_checked():
    ec, ep = small_case(seed=0, n=8, p=32)
    eng = WhatIfEngine(ec, ep, [Scenario()] * 2, FrameworkConfig(),
                       policies=_tile(_default_vec(), 2))
    with pytest.raises(ValueError):
        eng.set_policies(np.zeros((3, len(T.POLICY_COLS)), np.float32))
    with pytest.raises(ValueError):
        eng.set_policies(np.zeros((2, 3), np.float32))


def test_objective_validation():
    with pytest.raises(ValueError, match="unknown objective term"):
        make_objective({"nope": 1.0})
    with pytest.raises(ValueError, match="at least one term"):
        make_objective({})


def test_tunable_parameters_surface():
    params = {p["name"]: p for p in tunable_parameters(None)}
    assert list(params)[:5] == list(T.POLICY_WEIGHT_COLS)
    assert params["NodeResourcesFit.strategy"]["enabled"]
    # A plugin outside the config's list is marked disabled (its rows are
    # statically absent from the device program — searching it is noise).
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    params = {p["name"]: p for p in tunable_parameters(cfg)}
    assert not params["TaintToleration"]["enabled"]
    # Ratio base strategy has no traced selector.
    cfg = FrameworkConfig(plugins=[{
        "name": "NodeResourcesFit",
        "args": {"strategy": "RequestedToCapacityRatio"},
    }])
    params = {p["name"]: p for p in tunable_parameters(cfg)}
    assert not params["NodeResourcesFit.strategy"]["enabled"]


def test_fit_population_rounds_up():
    assert fit_population(5, 3, None) == 5
    mesh = make_mesh()
    P = fit_population(5, 3, mesh)
    assert P >= 5 and (P * 3) % mesh.devices.size == 0


# -- the search itself -----------------------------------------------------


def _fragmentation_case():
    """4 identical nodes × 4 cpu; 8 one-cpu pods arrive before two 4-cpu
    pods. The default LeastAllocated spreads the small pods two per node
    (no node can then host a large pod: 2 unschedulable); MostAllocated
    packs them onto two nodes and places everything — a policy the search
    must discover for a strict held-out win."""
    nodes = [Node(f"n{i}", capacity={"cpu": 4.0, "memory": 16.0})
             for i in range(4)]
    pods = [
        Pod(f"small-{i}", requests={"cpu": 1.0, "memory": 1.0},
            arrival_time=float(i))
        for i in range(8)
    ] + [
        Pod(f"large-{i}", requests={"cpu": 4.0, "memory": 4.0},
            arrival_time=float(8 + i))
        for i in range(2)
    ]
    return encode(Cluster(nodes=nodes), pods)


def test_tune_beats_default_on_heldout(tmp_path):
    ec, ep = _fragmentation_case()
    tuner = PolicyTuner(
        ec, ep, FrameworkConfig(),
        algo="cem", population=8, rounds=4, seed=0,
        train_scenarios=2, heldout_scenarios=2, scenario_seed=1,
        p_node_down=0.0, p_capacity=0.25, p_taint=0.0,
        chunk_waves=4,
    )
    res = tuner.run()
    assert res.compile_count == 1  # whole search, one executable
    assert res.best_policy["fitStrategy"] == "MostAllocated"
    assert res.heldout_objective > res.default_heldout_objective
    assert res.improved()
    # CPU oracle: greedy_replay with the winning weights re-derives the
    # device objective within the pinned envelope.
    assert res.cpu_envelope is not None
    assert res.cpu_envelope <= 1e-6
    assert res.evaluations == 4 * 8 * 2


def test_random_search_also_finds_packing():
    ec, ep = _fragmentation_case()
    res = PolicyTuner(
        ec, ep, FrameworkConfig(),
        algo="random", population=8, rounds=3, seed=2,
        train_scenarios=2, heldout_scenarios=1, scenario_seed=1,
        p_node_down=0.0, p_capacity=0.25, p_taint=0.0,
        chunk_waves=4, cpu_oracle=False,
    ).run()
    assert res.best_policy["fitStrategy"] == "MostAllocated"
    assert res.heldout_objective >= res.default_heldout_objective


# -- trajectory JSONL: determinism + schema v3 -----------------------------


def _tune_config(tmp_path, out_name):
    out = tmp_path / out_name
    cfg = tmp_path / f"{out_name}.yaml"
    cfg.write_text(
        "cluster:\n  synthetic: {nodes: 8, seed: 0}\n"
        "workload:\n  synthetic: {pods: 40, seed: 1}\n"
        "chunkWaves: 8\n"
        "tune:\n"
        "  algo: cem\n  population: 4\n  rounds: 2\n  seed: 3\n"
        "  objective: {placementRate: 1.0, unschedulable: -0.001}\n"
        "  scenarios: {train: 2, heldout: 1, seed: 0}\n"
        f"  output: {out}\n"
    )
    return cfg, out


def test_trajectory_deterministic_and_schema_v3(tmp_path):
    """Same seed + config ⇒ byte-identical trajectory files (rows carry no
    wall-clock), and every row validates as schema v3."""
    from kubernetes_simulator_tpu.cli import main as cli_main

    # The SAME config file twice (the context stamp hashes the config, so
    # a config differing only in output path would differ legitimately);
    # the output is renamed away between runs since JsonlWriter appends.
    cfg_a, out_a = _tune_config(tmp_path, "a.jsonl")
    assert cli_main(["tune", str(cfg_a)]) == 0
    first = tmp_path / "first.jsonl"
    out_a.rename(first)
    assert cli_main(["tune", str(cfg_a)]) == 0
    bytes_a = out_a.read_bytes()
    assert bytes_a == first.read_bytes()
    assert validate_file(str(out_a)) == []
    rows = [json.loads(l) for l in bytes_a.decode().splitlines()]
    assert all(r["schema"] == 3 and r["run_type"] == "tune" for r in rows)
    assert all("ts" not in r for r in rows)
    kinds = {r["kind"] for r in rows}
    assert kinds == {"tune-candidate", "tune-round", "tune-result"}
    final = rows[-1]
    assert final["kind"] == "tune-result"
    assert {"best_policy", "heldout_objective",
            "default_heldout_objective"} <= final.keys()


def test_schema_v3_checker_rejects_malformed():
    good = {"schema": 3, "run_type": "tune", "kind": "tune-round",
            "round": 0, "best_objective": 1.0, "round_best_objective": 1.0,
            "mean_objective": 0.5, "best_candidate": 0}
    assert validate_row(good) == []
    assert any("run_type" in e for e in validate_row(
        {"schema": 3, "kind": "tune-round"}))
    assert any("kind: unknown" in e for e in validate_row(
        {"schema": 3, "run_type": "tune", "kind": "tune-bogus"}))
    assert any("objective" in e for e in validate_row(
        {"schema": 3, "run_type": "tune", "kind": "tune-candidate",
         "round": 0, "candidate": 1, "policy": {}, "split": "train"}))


def test_cmd_tune_validates_objective_terms(tmp_path):
    """Round 13: host-mirror terms (latency quantiles, fragmentation)
    are only a config error under an EXPLICIT device evaluator — auto
    routes them to the CPU event engine instead."""
    from kubernetes_simulator_tpu.cli import main as cli_main

    cfg = tmp_path / "bad.yaml"
    cfg.write_text(
        "cluster:\n  synthetic: {nodes: 4, seed: 0}\n"
        "workload:\n  synthetic: {pods: 16, seed: 0}\n"
        "tune:\n"
        "  evaluator: device\n"
        "  objective: {latencyP99: -1.0}\n"
    )
    assert cli_main(["tune", str(cfg)]) == 2
    bad_cons = tmp_path / "bad_cons.yaml"
    bad_cons.write_text(
        "cluster:\n  synthetic: {nodes: 4, seed: 0}\n"
        "workload:\n  synthetic: {pods: 16, seed: 0}\n"
        "tune:\n"
        "  objective: {placementRate: 1.0}\n"
        "  constraints: [{metric: latencyP99}]\n"  # no bound
    )
    assert cli_main(["tune", str(bad_cons)]) == 2
    bad_eval = tmp_path / "bad_eval.yaml"
    bad_eval.write_text(
        "cluster:\n  synthetic: {nodes: 4, seed: 0}\n"
        "workload:\n  synthetic: {pods: 16, seed: 0}\n"
        "tune:\n"
        "  evaluator: gpu\n"
        "  objective: {placementRate: 1.0}\n"
    )
    assert cli_main(["tune", str(bad_eval)]) == 2


# -- constraint-aware objectives (round 13) --------------------------------


def test_constraint_validation():
    with pytest.raises(ValueError, match="exactly one of"):
        make_objective({"placementRate": 1.0},
                       [{"metric": "latencyP99"}])
    with pytest.raises(ValueError, match="exactly one of"):
        make_objective({"placementRate": 1.0},
                       [{"metric": "latencyP99", "max": 1.0, "min": 0.0}])
    with pytest.raises(ValueError, match="unknown metric"):
        make_objective({"placementRate": 1.0},
                       [{"metric": "nope", "max": 1.0}])
    with pytest.raises(ValueError, match="penalty"):
        make_objective({"placementRate": 1.0},
                       [{"metric": "latencyP99", "max": 1.0, "penalty": 0}])
    with pytest.raises(ValueError, match="unknown key"):
        make_objective({"placementRate": 1.0},
                       [{"metric": "latencyP99", "max": 1.0, "bogus": 2}])


def test_constraint_penalty_hinge():
    """max bounds penalize overshoot, min bounds penalize undershoot,
    NaN metric values (a scenario that bound nothing) violate nothing."""
    from types import SimpleNamespace

    _, _, fn = make_objective(
        {"utilizationCpu": 1.0},
        [{"metric": "latencyP99", "max": 2.0, "penalty": 10.0}],
    )
    res = SimpleNamespace(
        utilization_cpu=np.array([0.5, 0.5, 0.5]),
        latency_p99=np.array([1.0, 4.0, np.nan]),
    )
    np.testing.assert_allclose(fn(res), [0.5, 0.5 - 20.0, 0.5])
    _, _, fn = make_objective(
        {"utilizationCpu": 1.0},
        [{"metric": "packingEfficiency", "min": 0.9, "penalty": 1.0}],
    )
    res = SimpleNamespace(
        utilization_cpu=np.array([0.5, 0.5]),
        packing_efficiency=np.array([1.0, 0.4]),
    )
    np.testing.assert_allclose(fn(res), [0.5, 0.5 - 0.5])


def test_evaluator_resolution():
    ec, ep = _fragmentation_case()
    t = PolicyTuner(ec, ep, FrameworkConfig(), population=2, rounds=1,
                    objective={"placementRate": 1.0})
    assert t.evaluator == "device"  # auto keeps the batched sweep
    t = PolicyTuner(ec, ep, FrameworkConfig(), population=2, rounds=1,
                    objective={"utilizationCpu": 1.0},
                    constraints=[{"metric": "latencyP99", "max": 1.0}])
    assert t.evaluator == "cpu"  # auto routes host-mirror terms
    with pytest.raises(ValueError, match="evaluator='cpu'"):
        PolicyTuner(ec, ep, FrameworkConfig(), population=2, rounds=1,
                    objective={"latencyP99": -1.0}, evaluator="device")
    with pytest.raises(ValueError, match="evaluator must be"):
        PolicyTuner(ec, ep, FrameworkConfig(), population=2, rounds=1,
                    evaluator="gpu")


def _latency_fragmentation_case():
    """The fragmentation family with durations (round 13): 8 one-cpu
    smalls (duration 20) then two 4-cpu larges (infinite). EVERY policy
    eventually places everything, so the end-of-replay CPU utilization
    ties at 0.5 across the whole search space — but LeastAllocated
    spreads the smalls two per node, stranding the larges until the
    smalls drain (first-bind latency 16 virtual seconds), while
    MostAllocated packs two nodes and binds the larges on arrival."""
    nodes = [Node(f"n{i}", capacity={"cpu": 4.0, "memory": 16.0})
             for i in range(4)]
    pods = [
        Pod(f"small-{i}", requests={"cpu": 1.0, "memory": 1.0},
            arrival_time=float(i), duration=20.0)
        for i in range(8)
    ] + [
        Pod(f"large-{i}", requests={"cpu": 4.0, "memory": 4.0},
            arrival_time=float(8 + i))
        for i in range(2)
    ]
    return encode(Cluster(nodes=nodes), pods)


def test_latency_constraint_changes_winner():
    """Acceptance pin (round 13): on the latency-fragmentation family
    the unconstrained utilization objective ties everywhere (elitism
    keeps the default LeastAllocated incumbent), while the latency-
    constrained run must discover MostAllocated — a DIFFERENT winner."""
    ec, ep = _latency_fragmentation_case()
    kw = dict(
        algo="cem", population=8, rounds=3, seed=0,
        train_scenarios=2, heldout_scenarios=1, scenario_seed=1,
        p_node_down=0.0, p_capacity=0.0, p_taint=0.0,  # clean family
        evaluator="cpu",
    )
    unconstrained = PolicyTuner(
        ec, ep, FrameworkConfig(),
        objective={"utilizationCpu": 1.0}, **kw,
    ).run()
    constrained = PolicyTuner(
        ec, ep, FrameworkConfig(),
        objective={"utilizationCpu": 1.0},
        constraints=[{"metric": "latencyP99", "max": 1.0, "penalty": 1.0}],
        **kw,
    ).run()
    # Ties keep the incumbent: strict > never replaces the default.
    assert unconstrained.best_policy["fitStrategy"] == "LeastAllocated"
    assert constrained.best_policy["fitStrategy"] == "MostAllocated"
    assert (
        constrained.best_policy["fitStrategy"]
        != unconstrained.best_policy["fitStrategy"]
    )
    assert constrained.evaluator == "cpu"
    assert constrained.heldout_objective > constrained.default_heldout_objective
    assert constrained.improved()
    # Host evaluation: no device executable, no CPU-oracle re-run.
    assert constrained.compile_count is None
    assert constrained.cpu_objective is None
    # The tune-result row carries the constraint/evaluator provenance.
    final = constrained.trajectory[-1]
    assert final["kind"] == "tune-result"
    assert final["evaluator"] == "cpu"
    assert final["objective_constraints"] == [
        {"metric": "latencyP99", "penalty": 1.0, "max": 1.0}
    ]
