"""Round-18 work-stealing scenario-block queue (ISSUE round 18).

A DCN what-if fleet draining the KV-backed block queue must be
indistinguishable from the static-slicing run — which test_dcn.py
already pins against the single-process oracle — for ANY interleaving
of leases, steals and speculative re-executions. The suite sweeps
1/2/3-process fleets, uneven block sizes, the kube+series merge leg and
the node-sharded fork leg (tests/dcn_case_worker.py builders), plus the
robustness drills: an injected straggler resolved by speculative
re-execution (with the lease/speculate/block-done events pinned in the
fleet telemetry mirror) and a worker joining mid-replay.

The quick 2-process queue and uneven-block parity runs are tier-1; the
3-process sweep, the straggler drill and the late joiner ride slow
fleets. validate_config refusals for the ``dcn.workQueue`` YAML section
are pinned here too (single-process, fast).
"""

import functools
import json
import os
import socket
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

import dcn_case_worker as W  # noqa: E402

_WORKER = os.path.join(os.path.dirname(__file__), "dcn_case_worker.py")

# Heartbeats every chunk (lease renewals ride them), generous stall so
# XLA compile never looks like a dead holder, fast poll so Phase B picks
# up pending blocks promptly.
WQ_ENV = {
    "KSIM_DCN_WORKQUEUE": "1",
    "KSIM_DCN_HEARTBEAT_EVERY": "1",
    "KSIM_DCN_STALL_S": "120",
    "KSIM_DCN_POLL_S": "0.3",
}


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(cases, nproc: int = 2, extra_env=None, per_pid_env=None,
            timeout: int = 600) -> dict:
    """Spawn an nproc fleet over ``cases``; every process must exit 0
    and print an identical gathered result. ``extra_env`` applies to the
    whole fleet, ``per_pid_env`` ({pid: {...}}) to single members (the
    late-joiner knob)."""
    port = _free_port()
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={8 // nproc}",
        "KSIM_DCN_COORD": f"127.0.0.1:{port}",
        "KSIM_DCN_NPROC": str(nproc),
        "KSIM_DCN_CASES": ",".join(cases),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + [
                p
                for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p
            ]
        ),
        **(extra_env or {}),
    }
    procs = []
    for pid in range(nproc):
        env = dict(env_base, KSIM_DCN_PID=str(pid))
        env.update((per_pid_env or {}).get(pid, {}))
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail("DCN work-queue worker timed out")
            if "Multiprocess computations aren't implemented" in (out + err):
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                        q.wait()
                pytest.skip("jaxlib CPU backend lacks multiprocess execution")
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            lines = [
                l for l in out.splitlines()
                if l.startswith("DCN_CASES_RESULT ")
            ]
            assert lines, f"no result line:\n{out}\n{err}"
            outs.append(json.loads(lines[-1][len("DCN_CASES_RESULT "):]))
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
    for o in outs[1:]:
        assert o == outs[0], "processes disagree on the gathered result"
    return outs[0]


@functools.lru_cache(maxsize=None)
def _oracle(case: str):
    """Single-process reference (== the static-slicing gather, which
    test_dcn.py pins against this same oracle), through the JSON
    round-trip the worker results take."""
    out = W.run_cases([case], expect_dcn=False)
    return json.loads(json.dumps(out[case]))


def _events(hb_dir: str):
    path = os.path.join(hb_dir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


# -- queue-vs-static byte parity ---------------------------------------------


def test_wq_two_process_parity():
    """2-process fleet draining the queue (auto block size: one block
    per worker) on the kube+series merge leg — gather byte-identical to
    the static-slicing oracle, exactly ONE gather per replay (pinned
    in-worker)."""
    res = _launch(("wqmerge",), extra_env=WQ_ENV)
    assert res["wqmerge"] == _oracle("wqmerge")


def test_wq_uneven_block_parity():
    """blockSize=4 over S=6 leaves a ragged tail block of 2 — block
    boundaries that match no static slice. Concatenating blocks in block
    order must still reproduce the global scenario order bit-for-bit."""
    res = _launch(
        ("wqmerge",), extra_env=dict(WQ_ENV, KSIM_DCN_WQ_BLOCK="4"),
    )
    assert res["wqmerge"] == _oracle("wqmerge")


def test_wq_env_inert_single_process(monkeypatch):
    """KSIM_DCN_WORKQUEUE=1 without a DCN fleet (the 1-process 'fleet')
    is inert: the engine never slices, never gathers, and the result is
    the plain single-process run."""
    oracle = _oracle("wqmerge")  # computed BEFORE the env flips
    monkeypatch.setenv("KSIM_DCN_WORKQUEUE", "1")
    out = W.run_cases(["wqmerge"], expect_dcn=False)
    assert json.loads(json.dumps(out["wqmerge"])) == oracle


@pytest.mark.slow
def test_wq_three_process_parity():
    """3-process fleet over S=6 (two scenarios per block) on both the
    kube+series merge leg and the node-sharded fork leg."""
    res = _launch(("wqmerge", "wqfork"), nproc=3, extra_env=WQ_ENV)
    assert res["wqmerge"] == _oracle("wqmerge")
    assert res["wqfork"] == _oracle("wqfork")


@pytest.mark.slow
def test_wq_small_blocks_parity():
    """blockSize=1 over S=6 with 2 workers: three queue hand-offs per
    process beyond the static partition — maximal contention on the
    lease CAS — and the mesh-free gather still bit-matches."""
    res = _launch(
        ("wqmerge",), extra_env=dict(WQ_ENV, KSIM_DCN_WQ_BLOCK="1"),
    )
    assert res["wqmerge"] == _oracle("wqmerge")


# -- straggler resolved by speculation ---------------------------------------


@pytest.mark.slow
def test_wq_straggler_resolved_by_speculation(tmp_path):
    """Process 1 is slowed 4s per heartbeat from chunk 1 on (faultline
    ``slow`` class); the lease stall is pushed out of reach so only
    SPECULATIVE re-execution can resolve it. The fleet must finish with
    the straggler's own late result discarded as a duplicate — the
    direct witness that static slicing (which must wait for process 1's
    slice) would still be blocked at that point — and the gather must
    stay byte-identical to the no-straggler oracle. The lease /
    speculate / block-done(spec) / dup-discard chain is pinned in the
    fleet telemetry mirror (events.jsonl), attributed to the stolen
    block."""
    hb = tmp_path / "hb"
    hb.mkdir()
    res = _launch(
        ("wqmerge",),
        extra_env=dict(
            WQ_ENV,
            KSIM_DCN_SPECULATE="1",
            KSIM_DCN_RECOVER="1",
            KSIM_DCN_CKPT_EVERY="1",
            KSIM_DCN_STRAGGLER_S="1",
            KSIM_DCN_STALL_S="600",
            KSIM_DCN_HB_DIR=str(hb),
            KSIM_FAULTLINE="1",
            KSIM_FAULTLINE_SEED="18",
            KSIM_FAULTLINE_SLOW="1@1:4",
        ),
    )
    assert res["wqmerge"] == _oracle("wqmerge")
    evs = _events(str(hb))
    kinds = [e.get("event") for e in evs]
    assert kinds.count("lease") == 2, evs  # one gen-0 lease per block
    spec = [e for e in evs if e.get("event") == "speculate"]
    assert len(spec) == 1, evs  # one-shot election per (block, gen)
    assert spec[0]["from"] == 1, spec  # attributed to the straggler
    assert spec[0]["pid"] != 1, spec
    stolen = spec[0]["block"]
    done = [
        e for e in evs
        if e.get("event") == "block_done" and e.get("block") == stolen
    ]
    assert done and done[0]["spec"] is True, evs  # speculative win
    assert done[0]["pid"] == spec[0]["pid"], evs
    # The straggler finished AFTER the fleet already had its block: its
    # duplicate was discarded — under static slicing the replay would
    # still have been waiting on it.
    dup = [e for e in evs if e.get("event") == "dup_discard"]
    assert [e["pid"] for e in dup] == [1], evs
    assert dup[0]["block"] == stolen, evs
    assert "steal" not in kinds, evs  # resolved by speculation, not expiry


# -- true elastic join --------------------------------------------------------


@pytest.mark.slow
def test_wq_late_join_parity(tmp_path):
    """A third process registered as a joiner (KSIM_DCN_SPARES=1 — it
    owns no static block) defers its contribution by
    KSIM_DCN_JOIN_DELAY_S, then leases pending blocks from the queue.
    blockSize=1 leaves 6 blocks for 2 workers, so pending work exists
    when it wakes; the gather (assembled identically on all three
    processes, joiner included) stays byte-identical and the join event
    lands in the fleet telemetry mirror."""
    hb = tmp_path / "hb"
    hb.mkdir()
    res = _launch(
        ("wqmerge",),
        nproc=3,
        extra_env=dict(
            WQ_ENV,
            KSIM_DCN_WQ_BLOCK="1",
            KSIM_DCN_SPARES="1",
            KSIM_DCN_HB_DIR=str(hb),
        ),
        per_pid_env={2: {"KSIM_DCN_JOIN_DELAY_S": "1"}},
    )
    assert res["wqmerge"] == _oracle("wqmerge")
    evs = _events(str(hb))
    joins = [e for e in evs if e.get("event") == "join"]
    assert [e["pid"] for e in joins] == [2], evs
    leases = [e for e in evs if e.get("event") == "lease"]
    assert len(leases) == 6, evs  # every block leased exactly once at gen 0
    done = [e for e in evs if e.get("event") == "block_done"]
    assert sorted(e["block"] for e in done) == list(range(6)), evs


# -- validate_config refusals -------------------------------------------------


def _cfg(yaml_text, tmp_path):
    from kubernetes_simulator_tpu.utils.config import SimConfig

    p = tmp_path / "c.yaml"
    p.write_text(yaml_text)
    return SimConfig.load(str(p))


_BASE = """
strategy: jax
cluster: {synthetic: {nodes: 4, seed: 1}}
workload: {synthetic: {pods: 8, seed: 1}}
whatIf: {scenarios: 2, seed: 1}
"""


def test_validate_refuses_workqueue_without_fleet(tmp_path, monkeypatch):
    from kubernetes_simulator_tpu.cli import validate_config

    monkeypatch.delenv("KSIM_DCN_NPROC", raising=False)
    cfg = _cfg(_BASE + "dcn: {workQueue: {enable: true}}\n", tmp_path)
    errors = "\n".join(validate_config(cfg))
    assert "dcn.workQueue.enable" in errors
    assert "dcn_launch" in errors  # actionable: points at the launcher


def test_validate_refuses_speculation_without_checkpoints(tmp_path,
                                                          monkeypatch):
    from kubernetes_simulator_tpu.cli import validate_config

    monkeypatch.setenv("KSIM_DCN_NPROC", "2")
    cfg = _cfg(
        _BASE + "dcn: {workQueue: {enable: true, speculate: true}}\n",
        tmp_path,
    )
    errors = "\n".join(validate_config(cfg))
    assert "dcn.workQueue.speculate" in errors
    assert "checkpointEvery" in errors
    # With checkpoints on, the same config is clean.
    cfg2 = _cfg(
        _BASE
        + "dcn: {recovery: {enable: true, checkpointEvery: 2},\n"
        + "  workQueue: {enable: true, speculate: true}}\n",
        tmp_path,
    )
    assert not [
        e for e in validate_config(cfg2) if "workQueue" in e
    ], validate_config(cfg2)


def test_validate_refuses_bad_block_size(tmp_path, monkeypatch):
    from kubernetes_simulator_tpu.cli import validate_config

    monkeypatch.setenv("KSIM_DCN_NPROC", "2")
    cfg = _cfg(
        _BASE + "dcn: {workQueue: {enable: true, blockSize: -3}}\n",
        tmp_path,
    )
    errors = "\n".join(validate_config(cfg))
    assert "dcn.workQueue.blockSize" in errors


def test_validate_refuses_workqueue_without_heartbeats(tmp_path, monkeypatch):
    from kubernetes_simulator_tpu.cli import validate_config

    monkeypatch.setenv("KSIM_DCN_NPROC", "2")
    monkeypatch.setenv("KSIM_DCN_HEARTBEAT_EVERY", "0")
    cfg = _cfg(_BASE + "dcn: {workQueue: {enable: true}}\n", tmp_path)
    errors = "\n".join(validate_config(cfg))
    assert "heartbeat" in errors.lower()


def test_workqueue_knobs_without_enable_warn_only(tmp_path, caplog):
    import logging

    from kubernetes_simulator_tpu.cli import validate_config

    cfg = _cfg(
        _BASE + "dcn: {workQueue: {enable: false, blockSize: 2}}\n",
        tmp_path,
    )
    with caplog.at_level(logging.WARNING):
        errors = validate_config(cfg)
    assert not [e for e in errors if "workQueue" in e]
    assert any("workQueue" in r.message for r in caplog.records)


def test_validate_accepts_example_config17():
    from kubernetes_simulator_tpu.cli import validate_config
    from kubernetes_simulator_tpu.utils.config import SimConfig

    path = os.path.join(
        os.path.dirname(__file__), "..", "examples",
        "config17_workqueue.yaml",
    )
    cfg = SimConfig.load(path)
    assert cfg.dcn_workqueue is not None and cfg.dcn_workqueue.enable
    assert cfg.dcn_workqueue.speculate
    os.environ["KSIM_DCN_NPROC"] = "3"
    try:
        errors = [e for e in validate_config(cfg) if "workQueue" in e]
    finally:
        del os.environ["KSIM_DCN_NPROC"]
    assert errors == []
