"""Batch what-if tier preemption × completions (round 5, VERDICT r4
next #4 / missing #3): the combination is now a SUPPORTED no-mesh batch
configuration — eager eviction-aware host folds (the single-replay
round-4 mechanism S-stacked), tier-plane releases via compact device
scatters, evicted pods never release, completed pods never evicted.
Anchor: greedy_replay(preemption='tier', completions_chunk_waves=…) per
scenario; perturbed scenarios anchor to from-scratch single replays on
the equivalently perturbed cluster. Mesh batches stay arrivals-only
(loudly)."""

import warnings

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Taint
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import (
    Perturbation,
    Scenario,
    WhatIfEngine,
    uniform_scenarios,
)


def _contended(seed=2, nodes=8, pods_n=400):
    cluster = make_cluster(nodes, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(
        pods_n, seed=seed, with_spread=True, with_tolerations=True,
        duration_mean=20.0, arrival_rate=12.0,
    )
    return encode(cluster, pods)


@pytest.mark.slow
def test_unperturbed_matches_anchor_and_single_replay():
    ec, ep = _contended()
    cfg = FrameworkConfig()
    a = greedy_replay(ec, ep, cfg, preemption=True, completions_chunk_waves=4)
    eng = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, chunk_waves=4,
        preemption=True, collect_assignments=True,
    )
    assert eng.completions_on  # the round-4 gate is gone
    res = eng.run()
    np.testing.assert_array_equal(res.assignments[0], a.assignments)
    np.testing.assert_array_equal(res.assignments[1], a.assignments)
    assert int(res.placed[0]) == a.placed
    # Both mechanisms fire on this trace (non-vacuous), and completions
    # change the outcome vs arrivals-only.
    assert a.preemptions > 0
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        off = WhatIfEngine(
            ec, ep, [Scenario()], cfg, chunk_waves=4, preemption=True,
            completions=False,
        ).run()
    assert int(off.placed[0]) != a.placed
    # Tally path (no assignment collection) agrees with the collect path.
    res2 = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, chunk_waves=4,
        preemption=True,
    ).run()
    np.testing.assert_array_equal(res2.placed, res.placed)


def test_perturbed_scenarios_match_from_scratch_replays():
    """Each perturbed scenario must equal a from-scratch single replay
    (preemption × completions) on the equivalently perturbed cluster."""
    cluster = make_cluster(8, seed=2, taint_fraction=0.2)
    pods, _ = make_workload(
        300, seed=2, with_spread=True, with_tolerations=True,
        duration_mean=20.0, arrival_rate=12.0,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = [
        Scenario(),
        Scenario([Perturbation("scale_capacity", nodes=np.arange(3),
                               resource="cpu", factor=0.5)]),
        Scenario([Perturbation("add_taint", nodes=np.arange(2), key="k",
                               value="v", effect="NoSchedule")]),
    ]
    res = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, preemption=True,
        collect_assignments=True,
    ).run()

    cluster_half = make_cluster(8, seed=2, taint_fraction=0.2)
    for i in range(3):
        cluster_half.nodes[i].allocatable = {
            k: (v * 0.5 if k == "cpu" else v)
            for k, v in cluster_half.nodes[i].allocatable.items()
        }
    ec2, ep2 = encode(cluster_half, pods)
    ref2 = JaxReplayEngine(
        ec2, ep2, cfg, chunk_waves=4, preemption=True
    ).replay()
    np.testing.assert_array_equal(res.assignments[1], ref2.assignments)

    cluster_t = make_cluster(8, seed=2, taint_fraction=0.2)
    for i in range(2):
        cluster_t.nodes[i].taints.append(Taint("k", "v", "NoSchedule"))
    ec3, ep3 = encode(cluster_t, pods)
    ref3 = JaxReplayEngine(
        ec3, ep3, cfg, chunk_waves=4, preemption=True
    ).replay()
    np.testing.assert_array_equal(res.assignments[2], ref3.assignments)


def test_random_scenarios_tally_matches_collect():
    ec, ep = _contended(seed=3)
    scen = uniform_scenarios(ec, 6, seed=9, p_capacity=0.4, p_taint=0.2)
    cfg = FrameworkConfig()
    collect = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, preemption=True,
        collect_assignments=True,
    ).run()
    tally = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, preemption=True
    ).run()
    np.testing.assert_array_equal(collect.placed, tally.placed)
    assert collect.completions_on and tally.completions_on


def test_mesh_batch_stays_arrivals_only_loudly():
    from kubernetes_simulator_tpu.parallel.mesh import make_mesh

    ec, ep = _contended(seed=2, nodes=12, pods_n=64)
    scen = [Scenario()] * 8
    with pytest.warns(UserWarning, match="mesh"):
        eng = WhatIfEngine(
            ec, ep, scen, FrameworkConfig(), chunk_waves=4,
            preemption=True, mesh=make_mesh(),
        )
    assert not eng.completions_on
    with pytest.raises(ValueError, match="mesh"):
        WhatIfEngine(
            ec, ep, scen, FrameworkConfig(), chunk_waves=4,
            preemption=True, mesh=make_mesh(), completions=True,
        )
