"""Round 21 fleet black box: the causal trace identity module
(``parallel.trace``) — grammar units, the per-kind stamping rules at the
``dcn._mirror_event`` choke point, and the byte-identity parity bar:
trace stamping is READ-ONLY telemetry, so checkpoint blobs and the
coordination-plane bytes are identical with ``KSIM_TRACE`` on and off.
"""

import json
import os

import numpy as np
import pytest

from kubernetes_simulator_tpu.parallel import dcn, trace


class _FakeKV:
    """In-memory stand-in for the jaxlib coordination-service KV client."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        if not allow_overwrite and key in self.store:
            raise RuntimeError(f"key exists: {key}")
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms):
        import time

        if key in self.store:
            return self.store[key]
        time.sleep(timeout_ms / 1000.0)
        raise RuntimeError(f"Deadline Exceeded: {key}")

    def key_value_dir_get(self, prefix):
        return [
            (k, v) for k, v in sorted(self.store.items())
            if k.startswith(prefix)
        ]


def _fleet(monkeypatch, nproc=2, pid=1, journal=None):
    kv = _FakeKV()
    monkeypatch.setattr(dcn, "process_info", lambda: (nproc, pid))
    monkeypatch.setattr(dcn, "_client", lambda: kv)
    monkeypatch.setattr(dcn, "_degraded_exit_armed", [True])
    monkeypatch.setattr(dcn, "DEGRADED", set())
    if journal is not None:
        monkeypatch.setenv("KSIM_DCN_DURABLE_DIR", str(journal))
    else:
        monkeypatch.delenv("KSIM_DCN_DURABLE_DIR", raising=False)
    monkeypatch.delenv("KSIM_DCN_RESUME", raising=False)
    return kv


def _payload(seed=0):
    rng = np.random.default_rng(seed)
    return {
        "cursor": 3,
        "leaves": {"states": rng.integers(-1, 64, size=(8, 16),
                                          dtype=np.int32)},
    }


@pytest.fixture(autouse=True)
def _clean_ctx(monkeypatch):
    monkeypatch.delenv("KSIM_TRACE", raising=False)
    monkeypatch.setattr(trace, "CTX", [None])
    yield


# -- grammar -----------------------------------------------------------------


def test_trace_id_grammar():
    assert trace.block_trace(7) == "blk:7"
    assert trace.static_trace(2) == "blk:s2"
    assert trace.ckpt_trace(1, 3) == "ckpt:1:3"
    assert trace.exec_span(7, 0, 1) == "blk:7/exec.g0.p1"
    assert trace.exec_span(7, 2, 0) == "blk:7/exec.g2.p0"
    assert trace.spec_span(7, 1, 0) == "blk:7/spec.g1.p0"
    assert trace.publish_span(1, 3) == "ckpt:1:3/publish.p1"


def test_trace_for_key_covers_every_traced_plane():
    # Checkpoint keys: ksim/ckpt/<epoch>/<pid>/<lo>-<hi>/<cursor>[/leaf]
    assert trace.trace_for_key("ksim/ckpt/7/1/4-8/3/n") == "ckpt:1:3"
    assert trace.trace_for_key("ksim/ckpt/7/1/4-8/3/0") == "ckpt:1:3"
    assert trace.trace_for_key("ksim/ckpt/7/1/4-8/3") == "ckpt:1:3"
    # Claim keys: ksim/claim/<seq>/<name>/<dead_pid>/<gen>
    assert trace.trace_for_key("ksim/claim/2/block/1/0") == "blk:s1"
    # Work-queue keys: ksim/wq/<seq>/<name>/<sub>/<bid>
    for sub in ("lease", "renew", "done", "spec", "result"):
        assert trace.trace_for_key(f"ksim/wq/2/q/{sub}/5") == "blk:5"
    # Untraced planes degrade to None, never an error.
    assert trace.trace_for_key("ksim/hb/0") is None
    assert trace.trace_for_key("ksim/wq/2/q/assign/x") is None
    assert trace.trace_for_key("other/ckpt/7/1/4-8/3") is None
    assert trace.trace_for_key("") is None


# -- per-kind stamping rules -------------------------------------------------


def test_stamp_block_lifecycle_chain():
    lease = trace.stamp({"event": "lease", "pid": 0, "block": 4, "gen": 0})
    assert lease["trace"] == "blk:4"
    assert lease["span"] == "blk:4/exec.g0.p0"
    assert "parent" not in lease

    steal = trace.stamp(
        {"event": "steal", "pid": 1, "block": 4, "gen": 1, "from": 0}
    )
    assert steal["span"] == "blk:4/exec.g1.p1"
    assert steal["parent"] == "blk:4/exec.g0.p0"

    spec = trace.stamp(
        {"event": "speculate", "pid": 2, "block": 4, "gen": 1, "from": 1}
    )
    assert spec["span"] == "blk:4/spec.g1.p2"
    assert spec["parent"] == "blk:4/exec.g1.p1"

    done = trace.stamp(
        {"event": "block_done", "pid": 2, "block": 4, "gen": 1,
         "spec": True}
    )
    assert done["span"] == "blk:4/done.g1.p2"
    assert done["parent"] == "blk:4/spec.g1.p2"

    done_plain = trace.stamp(
        {"event": "block_done", "pid": 1, "block": 4, "gen": 1,
         "spec": False}
    )
    assert done_plain["parent"] == "blk:4/exec.g1.p1"

    lost = trace.stamp(
        {"event": "spec_lost", "pid": 2, "block": 4, "gen": 1}
    )
    assert lost["parent"] == "blk:4/spec.g1.p2"

    dup = trace.stamp(
        {"event": "dup_discard", "pid": 1, "block": 4, "gen": 1}
    )
    assert dup["parent"] == "blk:4/exec.g1.p1"


def test_stamp_adopt_claims_and_ckpt_hops():
    adopt = trace.stamp(
        {"event": "journal_adopt", "pid": 0, "block": 4, "gen": 1,
         "from": 2}
    )
    assert adopt["trace"] == "blk:4"
    assert adopt["span"] == "blk:4/adopt.p0"
    assert adopt["parent"] == "blk:4/done.g1.p2"

    claim0 = trace.stamp(
        {"event": "claim", "claimant": 0, "for": 1, "gen": 0}
    )
    assert claim0["trace"] == "blk:s1"
    assert claim0["span"] == "blk:s1/claim.g0.p0"
    assert "parent" not in claim0

    claim1 = trace.stamp(
        {"event": "claim", "claimant": 2, "for": 1, "gen": 1}
    )
    assert claim1["parent"] == "blk:s1/claim.g0"  # prefix, pid unknown

    rec = trace.stamp(
        {"event": "recovered", "claimant": 0, "for": 1, "gen": 0}
    )
    assert rec["span"] == "blk:s1/recover.g0.p0"
    assert rec["parent"] == "blk:s1/claim.g0.p0"

    # ckpt_publish names its kind under "kind" (test_durable pin).
    pub = trace.stamp({"kind": "ckpt_publish", "pid": 1, "cursor": 3})
    assert pub["trace"] == "ckpt:1:3"
    assert pub["span"] == "ckpt:1:3/publish.p1"

    load = trace.stamp(
        {"event": "ckpt_load", "pid": 1, "cursor": 3, "by": 0}
    )
    assert load["span"] == "ckpt:1:3/load.p0"
    assert load["parent"] == "ckpt:1:3/publish.p1"


def test_stamp_ctx_links_ckpt_to_block():
    trace.CTX[0] = "blk:s1"
    try:
        pub = trace.stamp({"kind": "ckpt_publish", "pid": 1, "cursor": 2})
        assert pub["link"] == "blk:s1"
        load = trace.stamp(
            {"event": "ckpt_load", "pid": 1, "cursor": 2, "by": 0}
        )
        assert load["link"] == "blk:s1"
    finally:
        trace.CTX[0] = None


def test_stamp_faults_follow_key_ctx_or_dead_pid():
    inj = trace.stamp(
        {"event": "fault_inject", "pid": 0, "class": "kv_error",
         "key": "ksim/wq/2/q/lease/5", "op": "set", "n": 3}
    )
    assert inj["trace"] == "blk:5"
    assert inj["span"] == "blk:5/fault_inject.kv_error.n3.p0"

    trace.CTX[0] = "blk:7"
    try:
        slow = trace.stamp(
            {"event": "fault_slow", "pid": 1, "class": "slow", "n": 0}
        )
        assert slow["trace"] == "blk:7"
    finally:
        trace.CTX[0] = None

    # A kill with no block context heads the dead pid's static-recovery
    # lifecycle — the survivor's claim shares the trace, so the
    # post-mortem flow arrow runs dead -> claimant.
    kill = trace.stamp(
        {"event": "fault_kill", "pid": 2, "class": "kill",
         "state": "run", "n": 0}
    )
    assert kill["trace"] == "blk:s2"

    # An untraceable fault still gets a span (instant marker), no trace.
    other = trace.stamp(
        {"event": "fault_inject", "pid": 0, "class": "file",
         "op": "mirror", "n": 1}
    )
    assert "trace" not in other
    assert other["span"].startswith("fault/")


def test_stamp_gate_idempotence_and_malformed_input(monkeypatch):
    monkeypatch.setenv("KSIM_TRACE", "0")
    ev = trace.stamp({"event": "lease", "pid": 0, "block": 4, "gen": 0})
    assert "trace" not in ev and "span" not in ev
    monkeypatch.delenv("KSIM_TRACE", raising=False)

    pre = {"event": "lease", "pid": 0, "block": 4, "gen": 0,
           "trace": "blk:99", "span": "blk:99/exec.g0.p0"}
    assert trace.stamp(dict(pre)) == pre  # pre-stamped: untouched

    # Malformed events degrade to no stamp, never an error.
    for bad in (
        {"event": "claim"},                      # no claimant/for
        {"event": "block_done", "pid": None},    # unstampable fields
        {"event": "steal", "pid": 0, "block": "x", "gen": "y"},
        {},
    ):
        out = trace.stamp(dict(bad))
        assert isinstance(out, dict)


# -- the choke point ---------------------------------------------------------


def test_mirror_event_stamps_every_sink(tmp_path, monkeypatch):
    """_mirror_event stamps BEFORE fan-out: EVENT_SINKS and the
    events.jsonl mirror see identical trace identity."""
    _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.setenv("KSIM_DCN_HB_DIR", str(tmp_path))
    seen = []
    monkeypatch.setattr(dcn, "EVENT_SINKS", [seen.append])
    dcn._mirror_event({"event": "lease", "pid": 0, "block": 9, "gen": 0})
    assert seen[0]["trace"] == "blk:9"
    rows = [
        json.loads(l) for l in
        (tmp_path / "events.jsonl").read_text().splitlines()
    ]
    assert rows[0]["trace"] == "blk:9"
    assert rows[0]["span"] == seen[0]["span"] == "blk:9/exec.g0.p0"


# -- byte-identity parity bar ------------------------------------------------


def test_checkpoint_bytes_identical_with_stamping_on_and_off(
    tmp_path, monkeypatch
):
    """The acceptance pin: trace stamping changes telemetry ONLY. The
    framed checkpoint chunk bytes on the KV plane and in the durable
    journal are byte-identical with KSIM_TRACE on and off; the manifest
    differs ONLY by its ``trace`` key and is the SAME string on both
    planes in both modes (the round-20 mirror-equality pin holds)."""
    stores = {}
    for mode, flag in (("on", "1"), ("off", "0")):
        monkeypatch.setenv("KSIM_TRACE", flag)
        journal = tmp_path / mode
        kv = _fleet(monkeypatch, nproc=2, pid=1, journal=journal)
        assert dcn.publish_checkpoint(3, _payload(5), (4, 8), epoch=7)
        stores[mode] = kv.store
        # KV manifest == journal manifest, byte for byte, in BOTH modes.
        man_disk = (
            journal / "ckpt" / "7" / "1" / "4-8" / "3" / "manifest.json"
        ).read_text()
        assert man_disk == kv.store["ksim/ckpt/7/1/4-8/3/n"]
    on, off = stores["on"], stores["off"]
    assert set(on) == set(off)
    man_on = json.loads(on["ksim/ckpt/7/1/4-8/3/n"])
    man_off = json.loads(off["ksim/ckpt/7/1/4-8/3/n"])
    assert man_on.pop("trace") == "ckpt:1:3"
    assert "trace" not in man_off
    assert man_on == man_off  # n / crc / len identical
    for key in on:
        if key.endswith("/n"):
            continue
        assert on[key] == off[key], f"chunk bytes differ at {key}"


def test_heartbeat_beacon_carries_generation_and_restart(
    tmp_path, monkeypatch
):
    """Round-21 beacon extras for dcn_launch --watch: the lease
    generation + block trace while holding a lease, and the supervised
    restart count when KSIM_DCN_RESTART_COUNT is exported."""
    _fleet(monkeypatch, nproc=2, pid=0)
    monkeypatch.setenv("KSIM_DCN_HB_DIR", str(tmp_path))
    monkeypatch.setenv("KSIM_DCN_RESTART_COUNT", "2")
    monkeypatch.setattr(
        dcn, "_ACTIVE_LEASE", [{"bid": 6, "gen": 1, "key": "k"}]
    )
    assert dcn.heartbeat(0, total=4, state="run")
    beat = json.loads((tmp_path / "p0.json").read_text())
    assert beat["wq_gen"] == 1
    assert beat["trace"] == "blk:6"
    assert beat["restart"] == 2
