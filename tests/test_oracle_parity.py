"""Randomized oracle ↔ numpy-path parity (SURVEY.md §4 tiers 1-2).

The pure-Python oracle interprets the object model directly; the numpy path
interprets the encoded tensors. For random clusters/pods every plugin's
filter mask and selection must agree exactly.
"""

import numpy as np
import pytest

from kubernetes_simulator_tpu.models.core import (
    Cluster,
    LabelSelector,
    MatchExpression,
    Node,
    NodeAffinitySpec,
    NodeSelectorTerm,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
    PreferredSchedulingTerm,
    Taint,
    Toleration,
    TopologySpreadConstraint,
    WeightedPodAffinityTerm,
)
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.models.state import bind, init_state
from kubernetes_simulator_tpu.ops import cpu as K
from kubernetes_simulator_tpu.plugins import oracle as O


def random_cluster_pods(seed: int, num_nodes: int = 12, num_pods: int = 30):
    rng = np.random.default_rng(seed)
    zones = ["za", "zb", "zc"]
    nodes = []
    for i in range(num_nodes):
        labels = {
            "zone": zones[int(rng.integers(3))],
            "disk": rng.choice(["ssd", "hdd"]),
            "gen": str(int(rng.integers(1, 9))),
        }
        taints = []
        if rng.random() < 0.3:
            taints.append(
                Taint(
                    rng.choice(["dedicated", "special"]),
                    rng.choice(["a", "b"]),
                    rng.choice(["NoSchedule", "PreferNoSchedule", "NoExecute"]),
                )
            )
        nodes.append(
            Node(
                f"n{i}",
                {"cpu": float(rng.integers(2, 16)), "memory": float(rng.integers(4, 64)) * 2**30},
                labels=labels,
                taints=taints,
            )
        )
    pods = []
    for j in range(num_pods):
        labels = {"app": rng.choice(["web", "db", "cache"]), "tier": rng.choice(["fe", "be"])}
        p = Pod(
            f"p{j}",
            labels=labels,
            requests={"cpu": float(rng.choice([0.5, 1, 2, 4])), "memory": float(rng.choice([1, 2, 8])) * 2**30},
            priority=int(rng.integers(0, 3)) * 100,
            arrival_time=float(j),
        )
        if rng.random() < 0.4:
            p.tolerations.append(
                Toleration(
                    key=rng.choice(["dedicated", "special"]),
                    operator=rng.choice(["Equal", "Exists"]),
                    value=rng.choice(["a", "b"]),
                )
            )
        r = rng.random()
        if r < 0.25:
            op = rng.choice(["In", "NotIn", "Exists", "DoesNotExist", "Gt", "Lt"])
            vals = {"In": ["ssd"], "NotIn": ["hdd"], "Exists": [], "DoesNotExist": [], "Gt": ["4"], "Lt": ["5"]}[op]
            key = "gen" if op in ("Gt", "Lt") else "disk"
            p.node_affinity = NodeAffinitySpec(
                required=(NodeSelectorTerm((MatchExpression.make(key, op, vals),)),)
            )
        elif r < 0.4:
            p.node_affinity = NodeAffinitySpec(
                preferred=(
                    PreferredSchedulingTerm(
                        weight=int(rng.integers(1, 50)),
                        term=NodeSelectorTerm((MatchExpression.make("disk", "In", ["ssd"]),)),
                    ),
                )
            )
        r = rng.random()
        if r < 0.15:
            p.pod_affinity = PodAffinitySpec(
                required=(PodAffinityTerm(LabelSelector.make({"app": str(labels["app"])}), "zone"),)
            )
        elif r < 0.3:
            p.pod_anti_affinity = PodAffinitySpec(
                required=(
                    PodAffinityTerm(
                        LabelSelector.make({"app": str(labels["app"])}), "kubernetes.io/hostname"
                    ),
                )
            )
        elif r < 0.45:
            p.pod_affinity = PodAffinitySpec(
                preferred=(
                    WeightedPodAffinityTerm(
                        int(rng.integers(1, 50)),
                        PodAffinityTerm(LabelSelector.make({"tier": "be"}), "zone"),
                    ),
                )
            )
        if rng.random() < 0.3:
            p.topology_spread.append(
                TopologySpreadConstraint(
                    max_skew=int(rng.choice([1, 2])),
                    topology_key="zone",
                    when_unsatisfiable=rng.choice(["DoNotSchedule", "ScheduleAnyway"]),
                    label_selector=LabelSelector.make({"app": str(labels["app"])}),
                )
            )
        pods.append(p)
    return Cluster(nodes=nodes), pods


@pytest.mark.parametrize("seed", range(6))
def test_filter_masks_match_oracle(seed):
    cluster, pods = random_cluster_pods(seed)
    ec, ep = encode(cluster, pods)
    st = init_state(ec, ep)
    ost = O.OracleState(cluster)
    M = K.expr_match_matrix(ec)
    rng = np.random.default_rng(seed + 99)

    for p_idx, pod in enumerate(pods):
        fit_np = K.fit_mask(ec, st, ep, p_idx)
        taint_np = K.taint_mask(ec, ep, p_idx)
        na_np = K.node_affinity_mask(M, ep, p_idx)
        ipa_np = K.interpod_filter_mask(ec, st, ep, p_idx)
        spr_np = K.spread_filter_mask(ec, st, ep, p_idx)
        for n_idx, node in enumerate(cluster.nodes):
            assert fit_np[n_idx] == O.fits_resources(ost, pod, node), (p_idx, n_idx, "fit")
            assert taint_np[n_idx] == O.tolerates_taints(pod, node), (p_idx, n_idx, "taint")
            assert na_np[n_idx] == O.node_affinity_ok(pod, node), (p_idx, n_idx, "nodeaff")
            assert ipa_np[n_idx] == O.interpod_ok(ost, pod, node), (p_idx, n_idx, "ipa")
            assert spr_np[n_idx] == O.spread_ok(ost, pod, node), (p_idx, n_idx, "spread")
        # Place the pod on a random feasible node in BOTH states and go on.
        mask = fit_np & taint_np & na_np & ipa_np & spr_np
        if mask.any():
            n_idx = int(rng.choice(np.nonzero(mask)[0]))
            bind(ec, ep, st, p_idx, n_idx)
            ost.bind(pod, cluster.nodes[n_idx].name)


@pytest.mark.parametrize("seed", range(4))
def test_scores_match_oracle(seed):
    cluster, pods = random_cluster_pods(seed, num_nodes=10, num_pods=20)
    ec, ep = encode(cluster, pods)
    st = init_state(ec, ep)
    ost = O.OracleState(cluster)
    M = K.expr_match_matrix(ec)
    weights = np.zeros(ec.num_resources, dtype=np.float32)
    weights[ec.vocab._r["cpu"]] = 1.0
    weights[ec.vocab._r["memory"]] = 1.0
    rng = np.random.default_rng(seed + 7)

    for p_idx, pod in enumerate(pods):
        la_np = K.least_allocated_score(ec, st, ep, p_idx, weights)
        naw_np = K.node_affinity_score(M, ep, p_idx)
        ipa_np = K.interpod_score(ec, st, ep, p_idx)
        spr_np = K.spread_score(ec, st, ep, p_idx)
        tt_np = K.taint_prefer_count(ec, ep, p_idx)
        for n_idx, node in enumerate(cluster.nodes):
            assert la_np[n_idx] == pytest.approx(
                O.least_allocated(ost, pod, node, {"cpu": 1.0, "memory": 1.0}), abs=1e-3
            )
            assert naw_np[n_idx] == pytest.approx(O.node_affinity_score(pod, node))
            assert ipa_np[n_idx] == pytest.approx(O.interpod_score(ost, pod, node)), (p_idx, n_idx)
            spr_o = O.spread_score(ost, pod, node)
            if spr_np is None:
                assert spr_o is None, (p_idx, n_idx)
            else:
                assert spr_np[n_idx] == pytest.approx(spr_o), (p_idx, n_idx)
            assert tt_np[n_idx] == O.prefer_no_schedule_count(pod, node)
        mask = K.fit_mask(ec, st, ep, p_idx) & K.taint_mask(ec, ep, p_idx)
        if mask.any():
            n_idx = int(rng.choice(np.nonzero(mask)[0]))
            bind(ec, ep, st, p_idx, n_idx)
            ost.bind(pod, cluster.nodes[n_idx].name)


def test_bind_unbind_roundtrip():
    cluster, pods = random_cluster_pods(3)
    ec, ep = encode(cluster, pods)
    st = init_state(ec, ep)
    snap = st.copy()
    from kubernetes_simulator_tpu.models.state import unbind

    for p in range(10):
        bind(ec, ep, st, p, p % ec.num_nodes)
    for p in range(10):
        unbind(ec, ep, st, p)
    assert np.allclose(st.used, snap.used)
    assert np.allclose(st.match_count, snap.match_count)
    assert np.allclose(st.anti_active, snap.anti_active)
    assert np.allclose(st.pref_wsum, snap.pref_wsum)
    assert (st.bound == snap.bound).all()
