"""CPU↔JAX parity — the load-bearing suite (SURVEY.md §4.2).

The numpy greedy wave replay and the jitted lax.scan replay implement the
same algorithm independently; placements must agree exactly on randomized
workloads covering every plugin, gangs included.
"""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.framework.registry import get_strategy
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import config1, make_cluster, make_workload


def assert_parity(cluster, pods, plugins=None, wave_width=8, **jax_kw):
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=plugins)
    cpu_res = greedy_replay(ec, ep, FrameworkConfig(plugins=plugins), wave_width=wave_width)
    jax_res = JaxReplayEngine(ec, ep, cfg, wave_width=wave_width, **jax_kw).replay()
    mismatch = np.nonzero(cpu_res.assignments != jax_res.assignments)[0]
    assert mismatch.size == 0, (
        f"{mismatch.size} mismatches, first at pod {mismatch[:5]}: "
        f"cpu={cpu_res.assignments[mismatch[:5]]} jax={jax_res.assignments[mismatch[:5]]}"
    )
    assert cpu_res.placed == jax_res.placed
    np.testing.assert_allclose(cpu_res.state.used, jax_res.state.used, atol=1e-3)
    np.testing.assert_allclose(
        cpu_res.state.match_count, jax_res.state.match_count, atol=1e-5
    )
    return cpu_res, jax_res


def test_parity_fit_only():
    cluster, pods, plugins = config1(num_nodes=40, num_pods=300)
    assert_parity(cluster, pods, plugins)


@pytest.mark.parametrize("seed", range(3))
def test_parity_full_plugin_set(seed):
    cluster = make_cluster(25, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(
        120, seed=seed, with_affinity=True, with_spread=True, with_tolerations=True
    )
    assert_parity(cluster, pods)


def test_parity_with_gangs():
    cluster = make_cluster(15, seed=5)
    pods, meta = make_workload(80, seed=5, gang_fraction=0.2, gang_size=3)
    assert meta["num_gangs"] > 0
    assert_parity(cluster, pods)


def test_parity_gang_infeasible_rolls_back_identically():
    # Two tiny nodes: a 4-pod gang of 1 cpu each (4 total) can never fully
    # fit (capacity 3), so gang rollback is exercised on both paths.
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod

    cluster = Cluster(nodes=[Node("n0", {"cpu": 2}), Node("n1", {"cpu": 1})])
    pods = []
    for g in range(3):
        for m in range(4):
            pods.append(
                Pod(
                    f"g{g}-m{m}",
                    requests={"cpu": 1},
                    arrival_time=float(g * 4 + m),
                    pod_group=f"gang-{g}",
                )
            )
    pods.append(Pod("single", requests={"cpu": 1}, arrival_time=100.0))
    # wave_width=4 → each gang gets its own wave, the singleton its own:
    # rollback happens at the gang's wave boundary, so the singleton sees a
    # clean cluster.
    cpu_res, jax_res = assert_parity(cluster, pods, wave_width=4)
    assert cpu_res.unschedulable == 12  # every gang rolled back
    assert cpu_res.assignments[-1] >= 0  # the singleton still fits


def test_parity_extended_resources_multitenant():
    cluster = make_cluster(20, seed=3, extended_resources={"google.com/tpu": (8, 0.3)})
    pods, _ = make_workload(
        100, seed=3, extended_resource=("google.com/tpu", 8, 0.3), gang_fraction=0.1, gang_size=4
    )
    assert_parity(cluster, pods)


def test_parity_chunked_equals_single_shot():
    cluster, pods, plugins = config1(num_nodes=20, num_pods=200)
    ec, ep = encode(cluster, pods)
    one = JaxReplayEngine(ec, ep, FrameworkConfig(plugins=plugins), chunk_waves=10_000).replay()
    many = JaxReplayEngine(ec, ep, FrameworkConfig(plugins=plugins), chunk_waves=4).replay()
    assert (one.assignments == many.assignments).all()


def test_registry_selects_jax():
    cluster, pods, plugins = config1(num_nodes=10, num_pods=40)
    ec, ep = encode(cluster, pods)
    eng = get_strategy("jax")(ec, ep, FrameworkConfig(plugins=plugins))
    res = eng.replay()
    assert res.placed == 40


def test_jax_determinism():
    cluster, pods, _ = config1(num_nodes=15, num_pods=100)
    ec, ep = encode(cluster, pods)
    r1 = JaxReplayEngine(ec, ep, FrameworkConfig(plugins=None)).replay()
    r2 = JaxReplayEngine(ec, ep, FrameworkConfig(plugins=None)).replay()
    assert (r1.assignments == r2.assignments).all()


def test_parity_bootstrap_on_domainless_node():
    """A pod placed via the bootstrap exception on a node WITHOUT the
    topology label must not count toward the group total — a later pod with
    the same required term still gets the bootstrap (regression: device
    match_total once counted domainless binds; ops/cpu.py total is
    match_count.sum which never sees them)."""
    from kubernetes_simulator_tpu.models.core import (
        Cluster, LabelSelector, Node, Pod, PodAffinitySpec, PodAffinityTerm,
    )

    zone = "topology.kubernetes.io/zone"
    nodes = [
        # Has the zone label but too small for any pod below.
        Node("n-zoned", capacity={"cpu": 0.5, "memory": 1, "pods": 10},
             labels={zone: "a"}),
        # Fits everything but has NO zone label → no domain under `zone`.
        Node("n-bare", capacity={"cpu": 8, "memory": 32, "pods": 10}),
    ]
    aff = PodAffinitySpec(
        required=(PodAffinityTerm(LabelSelector.make({"app": "x"}), zone),)
    )
    pods = [
        Pod("a", labels={"app": "x"}, requests={"cpu": 1}, arrival_time=0.0,
            pod_affinity=aff),
        Pod("b", labels={"app": "x"}, requests={"cpu": 1}, arrival_time=1.0,
            pod_affinity=aff),
    ]
    cpu_res, jax_res = assert_parity(Cluster(nodes=nodes), pods)
    # Both pods bootstrap onto the bare node; neither may be unschedulable.
    assert cpu_res.placed == 2


@pytest.mark.slow
def test_fused_eval_matches_reference_chain():
    """eval_pod_fused must be BIT-identical to the straight-line reference
    chain eval_pod — walks real waves, comparing mask and (feasible-masked)
    scores at every slot. This is what licenses the 'bit-identical' claims
    in ops/tpu.py and keeps the reference chain from rotting."""
    import jax

    from kubernetes_simulator_tpu.ops import tpu as T
    from kubernetes_simulator_tpu.sim.jax_runtime import StepSpec, eval_pod
    from kubernetes_simulator_tpu.sim.waves import pack_waves

    for seed in range(2):
        cluster = make_cluster(50, seed=seed, taint_fraction=0.3)
        pods, _ = make_workload(
            160, seed=seed, with_affinity=True, with_spread=True,
            with_tolerations=True, gang_fraction=0.1, gang_size=3,
        )
        ec, ep = encode(cluster, pods)
        spec = StepSpec.from_config(ec, FrameworkConfig(), ep)
        dc = T.DevCluster.from_encoded(ec)
        d = T.Derived.build(dc)
        sb = T.gather_slots(ep, pack_waves(ep, 8).idx)
        st = T.DevState.init(ec)
        for wi in range(sb.pod_id.shape[0]):
            slot_batch = jax.tree.map(lambda a: a[wi], sb)
            pre = T.build_wave_pre(dc, d, slot_batch, spec)
            widths = T.wave_widths(slot_batch, spec)
            for k in range(8):
                s = jax.tree.map(lambda a: a[k], slot_batch)
                p = jax.tree.map(lambda a: a[k], pre)
                f0, sc0 = eval_pod(dc, d, st, s, spec)
                f1, sc1, _ = T.eval_pod_fused(dc, d, st, s, p, spec, widths)
                np.testing.assert_array_equal(np.asarray(f0), np.asarray(f1))
                m = np.asarray(f0)
                np.testing.assert_array_equal(np.asarray(sc0)[m], np.asarray(sc1)[m])
                node, placed = T.select_node(sc0, f0)
                st = T.apply_binding(d, st, s, node, placed & s.valid)
