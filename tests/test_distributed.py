"""Multi-process jax.distributed (DCN) execution of the mesh-sharded
what-if (SURVEY §5 distributed communication backend; VERDICT r2 #5: the
path must have a passing caller, not just exist).

nproc subprocesses × 8//nproc virtual CPU devices join a local
coordinator; the scenario mesh spans all 8 global devices; per-scenario
placed counts must equal the single-process 8-device run bit-for-bit.
Default suite runs the 2-process split; the 4-process variant is slow."""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

_WORKER = os.path.join(os.path.dirname(__file__), "dcn_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


import functools


@functools.lru_cache(maxsize=1)
def _reference_placed_cached():
    return _reference_placed_impl()


def _reference_placed() -> np.ndarray:
    return _reference_placed_cached()


def _reference_placed_impl() -> np.ndarray:
    """Single-process 8-device reference (same trace/scenarios/seed)."""
    cluster = make_cluster(12, seed=21, taint_fraction=0.2)
    pods, _ = make_workload(
        48, seed=21, with_affinity=True, with_spread=True, with_tolerations=True
    )
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, 8, seed=21, p_capacity=0.5, p_taint=0.3)
    from kubernetes_simulator_tpu.parallel.mesh import make_mesh

    res = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), mesh=make_mesh(), chunk_waves=4
    ).run()
    return res.placed


def _run_dcn(nproc: int, timeout: int = 180) -> None:
    port = _free_port()
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": (
            f"--xla_force_host_platform_device_count={8 // nproc}"
        ),
        "DCN_COORD": f"127.0.0.1:{port}",
        "DCN_NPROC": str(nproc),
        # Workers import the repo package from the checkout. Any axon
        # sitecustomize dir is dropped: it pre-imports jax and initializes
        # the backend before jax.distributed gets a chance.
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + [
                p
                for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p
            ]
        ),
    }
    procs = []
    for pid in range(nproc):
        env = dict(env_base, DCN_PID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            try:
                # Healthy runs finish in ~35 s (round-4 measurement);
                # the bound catches a flaky coordinator bind without
                # turning the fast suite into a 7-minute hang (VERDICT
                # r3 weak #5 — the kill-on-failure cleanup below already
                # reaps the siblings).
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail("DCN worker timed out")
            if "Multiprocess computations aren't implemented" in (
                out + err
            ):
                # Capability gap in the installed jaxlib, not a repo
                # regression: this CPU runtime has no cross-process
                # execution support at all, so no DCN test can run here.
                # (Kill the siblings first — they'd block in the
                # coordinator otherwise.)
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                        q.wait()
                pytest.skip(
                    "jaxlib CPU backend lacks multiprocess execution"
                )
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            lines = [
                l for l in out.splitlines() if l.startswith("DCN_RESULT ")
            ]
            assert lines, f"no result line:\n{out}\n{err}"
            outs.append(
                np.asarray(json.loads(lines[-1][len("DCN_RESULT "):]))
            )
    finally:
        # A failed worker must not leave its sibling blocked in
        # jax.distributed.initialize (~300 s timeout) as an orphan.
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()

    # Every process holds the full (replicated-at-gather) result.
    for o in outs[1:]:
        np.testing.assert_array_equal(outs[0], o)
    np.testing.assert_array_equal(outs[0], _reference_placed())


@pytest.mark.slow
def test_two_process_dcn_matches_single_process():
    _run_dcn(2)


@pytest.mark.slow
def test_four_process_dcn_matches_single_process():
    """4 processes x 2 virtual devices each — the same mesh, a deeper
    process split (SURVEY §5 distributed backend: multi-host beyond a
    pair). Slow-marked; the wider budget absorbs 4 fresh per-process
    compiles on a loaded machine (it timed out at 180 s once when the
    full suite shared the host with a TPU run)."""
    _run_dcn(4, timeout=420)
