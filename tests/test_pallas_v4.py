"""v4 Pallas chunk kernel (ops.pallas3): parity vs v3 and the greedy
anchor, in interpreter mode on CPU. The engine is opt-in
(K8SIM_ENABLE_V4=1) until it beats the v3 scan on hardware — these tests
keep it correct while it is iterated on."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.sim.borg import BorgSpec, make_borg_encoded
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios


@pytest.fixture
def v4_on(monkeypatch):
    monkeypatch.setenv("K8SIM_ENABLE_V4", "1")


def test_v4_selected_and_matches_anchor(v4_on):
    ec, ep, _ = make_borg_encoded(BorgSpec(nodes=40, tasks=300, seed=0))
    scenarios = uniform_scenarios(
        ec, 2, seed=1, p_node_down=0.0, p_capacity=0.0, p_taint=0.0
    )
    eng = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), chunk_waves=8,
        collect_assignments=True, completions=False,
    )
    assert eng.engine == "v4"
    res = eng.run()
    anchor = greedy_replay(ec, ep, FrameworkConfig(), wave_width=8)
    np.testing.assert_array_equal(res.assignments[0], anchor.assignments)


@pytest.mark.slow
def test_v4_matches_v3_under_perturbations(v4_on, monkeypatch):
    # Heavy contention + gangs + node-down/capacity/taint perturbations.
    ec, ep, _ = make_borg_encoded(
        BorgSpec(nodes=12, tasks=800, seed=3, gang_fraction=0.3, max_gang=6)
    )
    scenarios = uniform_scenarios(
        ec, 3, seed=5, p_node_down=0.4, p_capacity=0.7, p_taint=0.5
    )
    eng4 = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), chunk_waves=16,
        collect_assignments=True, completions=False,
    )
    assert eng4.engine == "v4"
    res4 = eng4.run()
    monkeypatch.setenv("K8SIM_ENABLE_V4", "0")
    # v4 keeps no-completions semantics — compare v3 with them off too.
    eng3 = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), chunk_waves=16,
        collect_assignments=True, completions=False,
    )
    assert eng3.engine == "v3"
    res3 = eng3.run()
    np.testing.assert_array_equal(res4.placed, res3.placed)
    for s in range(3):
        np.testing.assert_array_equal(res4.assignments[s], res3.assignments[s])
    assert (res4.unschedulable > 0).any()  # the case actually contends


def test_v4_ineligible_shapes_fall_back(v4_on):
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

    cluster = make_cluster(16, seed=2)
    pods, _ = make_workload(50, seed=2, with_affinity=True)  # interpod terms
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, 2, seed=0)
    eng = WhatIfEngine(ec, ep, scenarios, FrameworkConfig(), chunk_waves=8)
    assert eng.engine == "v3"
