"""Single-process unit tests for the round-17 faultline plane: injector
determinism (same seed ⇒ same schedule, per-class stream independence),
kill-schedule parsing, CRC32+length checkpoint framing, the bounded
kv_retry backoff envelope, the corrupt-blob fallback in load_checkpoint,
the transient-vs-lost claim disambiguation, and the validate_config
refusals for the ``faultline:`` YAML section.  The multi-process
byte-parity property lives in tests/test_faultline_fuzz.py (slow)."""

import json
import logging
import os

import numpy as np
import pytest

from kubernetes_simulator_tpu.parallel import dcn, faultline

# -- kill-schedule grammar ---------------------------------------------------


def test_parse_kill_schedule_grammar():
    assert faultline.parse_kill_schedule("") == []
    assert faultline.parse_kill_schedule("1:0") == [("1", "run", 0)]
    assert faultline.parse_kill_schedule("1@recover:-1") == [
        ("1", "recover", -1)
    ]
    assert faultline.parse_kill_schedule("0@run:2, *@recover:-1") == [
        ("0", "run", 2),
        ("*", "recover", -1),
    ]


@pytest.mark.parametrize(
    "spec", ["1", "1@run", "x@run:0", "-2@run:0", "1@:0", "1@run:x"]
)
def test_parse_kill_schedule_refuses_malformed(spec):
    with pytest.raises(ValueError, match="faultline kill entry"):
        faultline.parse_kill_schedule(spec)


def test_parse_slow_schedule_grammar():
    """Round-18 straggler grammar: ``<pid>@<chunk>:<factor>`` — process
    ``pid`` sleeps ``factor`` seconds per run-state heartbeat from chunk
    ``chunk`` onward."""
    assert faultline.parse_slow_schedule("") == []
    assert faultline.parse_slow_schedule("1@2:0.5") == [(1, 2, 0.5)]
    assert faultline.parse_slow_schedule("0@0:4, 2@1:0.25") == [
        (0, 0, 4.0),
        (2, 1, 0.25),
    ]


@pytest.mark.parametrize(
    "spec", ["1", "1@1", "x@1:2", "-1@1:2", "1@x:2", "1@1:x", "1@1:-2"]
)
def test_parse_slow_schedule_refuses_malformed(spec):
    with pytest.raises(ValueError, match="faultline slow entry"):
        faultline.parse_slow_schedule(spec)


def test_parse_slow_schedule_refuses_wildcard():
    """No ``*`` in the slow grammar: a straggler is named so the
    schedule is a pure function of the config, not a CAS race."""
    with pytest.raises(ValueError, match="not allowed"):
        faultline.parse_slow_schedule("*@1:2")


def test_maybe_slow_fires_for_named_pid_in_run_state(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_DCN_PID", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_SLOW", "1@2:0.5")
    import time as _time

    naps = []
    monkeypatch.setattr(_time, "sleep", lambda s: naps.append(s))
    assert faultline.maybe_slow(0, "run") == 0.0  # below chunk threshold
    assert faultline.maybe_slow(2, "gather") == 0.0  # wrong state
    assert faultline.maybe_slow(2, "spec") == 0.0  # speculators never slowed
    assert faultline.maybe_slow(2, "run") == 0.5
    assert faultline.maybe_slow(3, "run") == 0.5  # every beat from thr on
    assert naps == [0.5, 0.5]
    assert faultline.injector().slow_count == 2


def test_maybe_slow_other_pid_never_fires(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_DCN_PID", "0")
    monkeypatch.setenv("KSIM_FAULTLINE_SLOW", "1@0:5")
    import time as _time

    monkeypatch.setattr(
        _time, "sleep",
        lambda s: pytest.fail("slow schedule fired for another pid"),
    )
    assert faultline.maybe_slow(3, "run") == 0.0


# -- injector determinism ----------------------------------------------------


def test_injector_same_seed_same_schedule():
    """The k-th decision of a class is a pure function of (seed, pid,
    class) — the contract the fuzz harness leans on."""
    a = faultline.Injector(seed=7, pid=1, kv_error_rate=0.3, torn_write_rate=0.5)
    b = faultline.Injector(seed=7, pid=1, kv_error_rate=0.3, torn_write_rate=0.5)
    seq_a = [a.hit("kv_error") for _ in range(64)]
    seq_b = [b.hit("kv_error") for _ in range(64)]
    assert seq_a == seq_b
    assert any(seq_a) and not all(seq_a)  # rate actually bites, bounded
    assert a.stats()["kv_error"] == sum(seq_a)


def test_injector_streams_are_independent():
    """Drawing from one class never shifts another: interleaving torn
    draws between kv_error draws leaves the kv_error schedule intact."""
    pure = faultline.Injector(seed=3, pid=0, kv_error_rate=0.4, torn_write_rate=0.4)
    mixed = faultline.Injector(seed=3, pid=0, kv_error_rate=0.4, torn_write_rate=0.4)
    want = [pure.hit("kv_error") for _ in range(32)]
    got = []
    for _ in range(32):
        mixed.hit("torn")
        got.append(mixed.hit("kv_error"))
        mixed.hit("stale")
    assert got == want


def test_injector_seed_and_pid_change_schedule():
    seqs = set()
    for seed, pid in [(7, 0), (8, 0), (7, 1)]:
        inj = faultline.Injector(seed=seed, pid=pid, kv_error_rate=0.5)
        seqs.add(tuple(inj.hit("kv_error") for _ in range(32)))
    assert len(seqs) == 3, "seed/pid must derive distinct streams"


def test_injector_zero_rate_never_draws():
    """rate <= 0 short-circuits without consuming the stream, so adding
    a disabled class to a run never perturbs the enabled ones."""
    inj = faultline.Injector(seed=5, pid=0, kv_error_rate=0.5)
    ref = faultline.Injector(seed=5, pid=0, kv_error_rate=0.5)
    out = []
    for _ in range(16):
        assert inj.hit("stale") is False  # rate 0.0
        out.append(inj.hit("kv_error"))
    assert out == [ref.hit("kv_error") for _ in range(16)]
    assert "stale" not in inj._rng  # never even built the stream


def test_injector_tear_mangles_deterministically():
    a = faultline.Injector(seed=11, pid=2)
    b = faultline.Injector(seed=11, pid=2)
    blob = "x" * 64
    torn_a = [a.tear(blob) for _ in range(8)]
    torn_b = [b.tear(blob) for _ in range(8)]
    assert torn_a == torn_b
    assert all(t != blob for t in torn_a)
    assert a.tear("") == ""


def test_from_env_reads_knobs(monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE_SEED", "9")
    monkeypatch.setenv("KSIM_DCN_PID", "2")
    monkeypatch.setenv("KSIM_FAULTLINE_KV_ERROR_RATE", "0.25")
    monkeypatch.setenv("KSIM_FAULTLINE_TORN_RATE", "0.5")
    monkeypatch.setenv("KSIM_FAULTLINE_KILL", "1@run:0")
    inj = faultline.from_env()
    assert inj.seed == 9 and inj.pid == 2
    assert inj.rates["kv_error"] == 0.25
    assert inj.rates["torn"] == inj.rates["file"] == 0.5
    assert inj.kill_entries == [("1", "run", 0)]


# -- KV proxy ---------------------------------------------------------------


class _FakeKV:
    """In-memory stand-in for the jaxlib coordination-service KV client."""

    def __init__(self):
        self.store = {}

    def key_value_set(self, key, value, allow_overwrite=False):
        if not allow_overwrite and key in self.store:
            raise RuntimeError(f"key exists: {key}")
        self.store[key] = value

    def blocking_key_value_get(self, key, timeout_ms=1000):
        if key in self.store:
            return self.store[key]
        raise RuntimeError(f"Deadline Exceeded: {key}")

    def key_value_dir_get(self, prefix):
        return [
            (k, v) for k, v in sorted(self.store.items())
            if k.startswith(prefix)
        ]


@pytest.fixture
def fl_off(monkeypatch):
    for k in list(os.environ):
        if k.startswith("KSIM_FAULTLINE"):
            monkeypatch.delenv(k, raising=False)
    faultline.reset()
    yield
    faultline.reset()


def test_wrap_kv_identity_when_off(fl_off):
    kv = _FakeKV()
    assert faultline.active() is False
    assert faultline.wrap_kv(kv) is kv
    assert faultline.wrap_kv(None) is None
    assert faultline.file_blob("beat") == "beat"


def test_wrap_kv_injects_errors_and_tears_ckpt_only(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_SEED", "17")
    monkeypatch.setenv("KSIM_FAULTLINE_KV_ERROR_RATE", "0.5")
    monkeypatch.setenv("KSIM_FAULTLINE_TORN_RATE", "1.0")
    kv = _FakeKV()
    proxy = faultline.wrap_kv(kv)
    assert proxy is not kv and proxy.raw is kv
    assert faultline.wrap_kv(kv) is proxy  # cached

    errors = 0
    for i in range(32):
        try:
            proxy.key_value_set(f"ksim/hb/{i}", "beat", allow_overwrite=True)
        except faultline.FaultlineInjected:
            errors += 1
    assert 0 < errors < 32
    # Non-checkpoint values are NEVER torn, even at torn rate 1.0.
    assert all(v == "beat" for k, v in kv.store.items())

    # Checkpoint chunks ARE torn (keep trying past injected errors).
    for i in range(8):
        try:
            proxy.key_value_set(f"ksim/ckpt/1/0/0-4/0/{i}", "A" * 32,
                                allow_overwrite=True)
        except faultline.FaultlineInjected:
            pass
    torn = [v for k, v in kv.store.items()
            if k.startswith("ksim/ckpt/") and v != "A" * 32]
    assert torn, "torn rate 1.0 must mangle checkpoint chunks"

    # faultline's own coordination keys bypass injection entirely.
    for _ in range(16):
        proxy.key_value_set("ksim/faultline/kill/0", "1", allow_overwrite=True)
    assert kv.store["ksim/faultline/kill/0"] == "1"


def test_proxy_stale_reads_return_previous_snapshot(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_SEED", "3")
    monkeypatch.setenv("KSIM_FAULTLINE_STALE_RATE", "1.0")
    kv = _FakeKV()
    proxy = faultline.wrap_kv(kv)
    kv.store["k"] = "v1"
    assert proxy.blocking_key_value_get("k") == "v1"  # no history yet
    kv.store["k"] = "v2"
    assert proxy.blocking_key_value_get("k") == "v1"  # stale snapshot
    kv.store["hb/0"] = "a"
    assert proxy.key_value_dir_get("hb") == [("hb/0", "a")]
    kv.store["hb/1"] = "b"
    assert proxy.key_value_dir_get("hb") == [("hb/0", "a")]  # stale dir


def test_maybe_kill_named_entry_fires_in_state(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_DCN_PID", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_KILL", "1@run:2")
    kills = []
    monkeypatch.setattr(faultline.os, "kill", lambda pid, sig: kills.append(sig))
    faultline.maybe_kill(0, "run")
    faultline.maybe_kill(2, "gather")  # wrong state
    assert kills == []
    faultline.maybe_kill(2, "run")
    assert kills == [faultline.signal.SIGKILL]


def test_maybe_kill_wildcard_never_matches_coordinator(fl_off, monkeypatch):
    """Process 0 hosts the jax.distributed coordination service — its
    death aborts every healthy task, so ``*`` entries skip it without
    even touching the kill CAS."""
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_DCN_PID", "0")
    monkeypatch.setenv("KSIM_FAULTLINE_KILL", "*@recover:-1")
    monkeypatch.setattr(
        faultline.os, "kill",
        lambda pid, sig: pytest.fail("'*' must never match the coordinator"),
    )
    faultline.maybe_kill(-1, "recover")
    faultline.maybe_kill(3, "recover")


def test_maybe_kill_other_pid_never_fires(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_DCN_PID", "0")
    monkeypatch.setenv("KSIM_FAULTLINE_KILL", "1@run:0")
    monkeypatch.setattr(
        faultline.os, "kill",
        lambda pid, sig: pytest.fail("kill fired for another pid"),
    )
    faultline.maybe_kill(5, "run")


# -- CRC framing -------------------------------------------------------------


def test_frame_roundtrip():
    for data in ["", "abc", "x" * 4096, json.dumps({"a": [1, 2]})]:
        framed = dcn._frame_chunk(data)
        assert framed.startswith("kf1:")
        assert dcn._unframe_chunk(framed) == data


def test_unframe_detects_torn_truncated_corrupt():
    framed = dcn._frame_chunk("hello world")
    with pytest.raises(ValueError, match="not framed"):
        dcn._unframe_chunk("hello world")
    with pytest.raises(ValueError, match="not framed|truncated"):
        dcn._unframe_chunk(framed[:6])
    with pytest.raises(ValueError, match="length mismatch"):
        dcn._unframe_chunk(framed[:-3])
    bad = framed[:-1] + chr(ord(framed[-1]) ^ 0x1)
    with pytest.raises(ValueError, match="CRC32 mismatch"):
        dcn._unframe_chunk(bad)


def test_injected_tear_always_caught_by_frame():
    """Every mangling the injector can produce (truncation or one-char
    flip) fails frame validation — the property the whole fallback
    chain rests on."""
    inj = faultline.Injector(seed=17, pid=0)
    framed = dcn._frame_chunk("payload-" * 16)
    for _ in range(64):
        torn = inj.tear(framed)
        assert torn != framed
        with pytest.raises(ValueError):
            dcn._unframe_chunk(torn)


# -- kv_retry backoff envelope ----------------------------------------------


def test_kv_retry_success_first_attempt_no_sleep():
    s0 = dcn.retry_stats()
    sleeps = []
    assert (
        dcn.kv_retry(lambda: 42, op="t", sleep=sleeps.append) == 42
    )
    assert sleeps == []
    s1 = dcn.retry_stats()
    assert s1["attempts"] == s0["attempts"] + 1
    assert s1["retries"] == s0["retries"]
    assert s1["giveups"] == s0["giveups"]


def test_kv_retry_backoff_bounds_and_giveup():
    """Delay before retry k is min(cap, base*2^k) * u with u in
    [0.5, 1.0] — bounded both sides, attempts exhausted ⇒ attributed
    DcnRetryError carrying op/key/attempts/last."""
    s0 = dcn.retry_stats()
    sleeps = []
    boom = RuntimeError("flaky")

    def _fail():
        raise boom

    with pytest.raises(dcn.DcnRetryError) as ei:
        dcn.kv_retry(
            _fail, op="heartbeat", key="ksim/hb/0",
            attempts=4, base_s=0.1, cap_s=0.25, sleep=sleeps.append,
        )
    assert len(sleeps) == 3  # n-1 backoffs for n attempts
    for k, d in enumerate(sleeps):
        env = min(0.25, 0.1 * 2.0 ** k)
        assert 0.5 * env <= d <= env, (k, d, env)
    assert sleeps[2] <= 0.25  # cap bites at k=2 (0.4 uncapped)
    e = ei.value
    assert e.op == "heartbeat" and e.key == "ksim/hb/0"
    assert e.attempts == 4 and e.last is boom
    assert "gave up after 4 attempts" in str(e)
    s1 = dcn.retry_stats()
    assert s1["attempts"] == s0["attempts"] + 4
    assert s1["retries"] == s0["retries"] + 3
    assert s1["giveups"] == s0["giveups"] + 1
    assert s1["backoff_s"] > s0["backoff_s"]


def test_kv_retry_recovers_after_transient():
    calls = {"n": 0}

    def _flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise RuntimeError("transient")
        return "ok"

    sleeps = []
    assert dcn.kv_retry(_flaky, op="t", attempts=4, base_s=0.01,
                        sleep=sleeps.append) == "ok"
    assert calls["n"] == 3 and len(sleeps) == 2


def test_kv_retry_jitter_injectable():
    sleeps = []

    def _fail():
        raise RuntimeError("x")

    with pytest.raises(dcn.DcnRetryError):
        dcn.kv_retry(_fail, op="t", attempts=3, base_s=0.2, cap_s=10.0,
                     sleep=sleeps.append, jitter=lambda: 1.0)
    assert sleeps == [0.2, 0.4]  # u=1.0 pins the upper envelope exactly


# -- checkpoint CRC fallback -------------------------------------------------


def _fleet(monkeypatch, nproc=2, pid=1):
    kv = _FakeKV()
    monkeypatch.setattr(dcn, "process_info", lambda: (nproc, pid))
    monkeypatch.setattr(dcn, "_client", lambda: kv)
    monkeypatch.setattr(dcn, "_degraded_exit_armed", [True])
    monkeypatch.setattr(dcn, "DEGRADED", set())
    return kv


def test_corrupt_newest_blob_falls_back_to_prior_epoch(monkeypatch):
    """The headline acceptance drill: deliberately corrupt the newest
    checkpoint blob — load_checkpoint detects it via the CRC frame and
    falls back to the newest PRIOR complete cursor."""
    kv = _fleet(monkeypatch, nproc=2, pid=1)
    pay0 = {"cursor": 1, "leaves": [np.arange(512, dtype=np.int32)]}
    pay1 = {"cursor": 3, "leaves": [np.arange(512, dtype=np.int32) * 3]}
    assert dcn.publish_checkpoint(1, pay0, (4, 8), epoch=7)
    assert dcn.publish_checkpoint(3, pay1, (4, 8), epoch=7)
    # Corrupt one chunk of the newest blob (flip a payload char).
    key = f"{dcn.CKPT_PREFIX}/7/1/4-8/3/0"
    v = kv.store[key]
    kv.store[key] = v[:-1] + chr(ord(v[-1]) ^ 0x1)
    c0 = dcn.crc_stats()
    got = dcn.load_checkpoint(1, epoch=7)
    assert got is not None and got["cursor"] == 1
    np.testing.assert_array_equal(
        got["payload"]["leaves"][0], pay0["leaves"][0]
    )
    c1 = dcn.crc_stats()
    assert c1["fallbacks"] == c0["fallbacks"] + 1
    assert c1["frames_bad"] == c0["frames_bad"] + 1
    # Corrupt the older blob too: nothing usable remains.
    key0 = f"{dcn.CKPT_PREFIX}/7/1/4-8/1/0"
    kv.store[key0] = kv.store[key0][:40]
    assert dcn.load_checkpoint(1, epoch=7) is None


def test_manifest_crc_guards_whole_blob(monkeypatch):
    """Per-chunk frames can all pass while a chunk is MISSING content
    relative to the manifest — the whole-blob crc/len in the JSON
    manifest catches chunk-level swaps."""
    kv = _fleet(monkeypatch, nproc=2, pid=0)
    pay = {"cursor": 2, "leaves": [np.ones(2048, np.int32)]}
    assert dcn.publish_checkpoint(2, pay, (0, 4), epoch=5)
    # Replace chunk 0 with a validly-framed but WRONG chunk.
    key = f"{dcn.CKPT_PREFIX}/5/0/0-4/2/0"
    kv.store[key] = dcn._frame_chunk("not-the-real-chunk")
    assert dcn.load_checkpoint(0, epoch=5) is None


def test_legacy_bare_int_manifest_still_loads(monkeypatch):
    """Pre-round-17 blobs (bare-int manifest, unframed chunks) load
    unvalidated — mixed-version tolerance."""
    kv = _fleet(monkeypatch, nproc=2, pid=1)
    chunks = dcn._encode_payload({"cursor": 0, "leaves": []})
    prefix = f"{dcn.CKPT_PREFIX}/3/1/4-8/0"
    for j, ch in enumerate(chunks):
        kv.store[f"{prefix}/{j}"] = ch
    kv.store[f"{prefix}/n"] = str(len(chunks))
    got = dcn.load_checkpoint(1, epoch=3)
    assert got is not None and got["cursor"] == 0
    assert got["payload"]["cursor"] == 0


def test_load_checkpoint_before_cursor_walks_older(monkeypatch):
    _fleet(monkeypatch, nproc=2, pid=1)
    for cur in (1, 3, 5):
        assert dcn.publish_checkpoint(
            cur, {"cursor": cur, "leaves": []}, (4, 8), epoch=2
        )
    assert dcn.load_checkpoint(1, epoch=2)["cursor"] == 5
    assert dcn.load_checkpoint(1, epoch=2, before_cursor=5)["cursor"] == 3
    assert dcn.load_checkpoint(1, epoch=2, before_cursor=3)["cursor"] == 1
    assert dcn.load_checkpoint(1, epoch=2, before_cursor=1) is None


def test_publish_checkpoint_retries_through_transient_faults(
    fl_off, monkeypatch
):
    """With faultline injecting KV set errors at a moderate rate, the
    bounded retries inside publish_checkpoint absorb them and the blob
    round-trips clean."""
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_SEED", "17")
    monkeypatch.setenv("KSIM_FAULTLINE_KV_ERROR_RATE", "0.2")
    monkeypatch.setenv("KSIM_DCN_RETRY_BASE_S", "0.001")
    raw = _FakeKV()
    monkeypatch.setattr(dcn, "process_info", lambda: (2, 1))
    monkeypatch.setattr(dcn, "_client", lambda: faultline.wrap_kv(raw))
    monkeypatch.setattr(dcn, "_degraded_exit_armed", [True])
    pay = {"cursor": 1, "leaves": [np.arange(256, dtype=np.int32)]}
    s0 = dcn.retry_stats()
    assert dcn.publish_checkpoint(1, pay, (4, 8), epoch=1)
    got = dcn.load_checkpoint(1, epoch=1)
    assert got is not None and got["cursor"] == 1
    np.testing.assert_array_equal(got["payload"]["leaves"][0],
                                  pay["leaves"][0])
    assert dcn.retry_stats()["retries"] > s0["retries"]


# -- claim disambiguation ----------------------------------------------------


def test_try_claim_transient_error_that_landed_counts_as_won(monkeypatch):
    """A transient set error is ambiguous — the CAS may have landed
    before the error surfaced. try_claim reads the key back and the
    VALUE decides."""
    kv = _fleet(monkeypatch, nproc=3, pid=0)
    monkeypatch.setenv("KSIM_DCN_RETRY_BASE_S", "0.001")
    monkeypatch.setenv("KSIM_DCN_RETRIES", "2")
    real_set = kv.key_value_set

    def _landed_then_error(key, value, allow_overwrite=False):
        real_set(key, value, allow_overwrite=allow_overwrite)
        raise RuntimeError("connection reset (but the set landed)")

    kv.key_value_set = _landed_then_error
    assert dcn.try_claim(2, 0) is True
    assert dcn.read_claim(2, 0)["claimant"] == 0


def test_try_claim_genuine_cas_loss_still_lost(monkeypatch):
    kv = _fleet(monkeypatch, nproc=3, pid=1)
    monkeypatch.setenv("KSIM_DCN_RETRY_BASE_S", "0.001")
    monkeypatch.setenv("KSIM_DCN_RETRIES", "2")
    kv.store[f"{dcn.CLAIM_PREFIX}/{dcn._seq}/whatif/2/0"] = json.dumps(
        {"claimant": 0, "for": 2, "gen": 0, "t": 1.0}
    )
    assert dcn.try_claim(2, 0) is False


# -- coordinator claims last -------------------------------------------------


def test_coordinator_defers_claim_to_live_sibling(monkeypatch):
    """Round 17: process 0 (the coordination-service host — the one
    process whose death is unsurvivable) gives a live sibling one stall
    window to claim a dead block before claiming itself. Here pid 2
    claims during the grace window, so pid 0 defers (returns True to
    keep polling) and never re-executes the block."""
    import time

    kv = _fleet(monkeypatch, nproc=3, pid=0)
    monkeypatch.setenv("KSIM_DCN_RECOVER", "1")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.5")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    now = time.time()
    kv.store[f"{dcn.HB_PREFIX}/1"] = json.dumps(
        {"pid": 1, "chunk": 0, "t": now - 10.0}
    )
    kv.store[f"{dcn.HB_PREFIX}/2"] = json.dumps(
        {"pid": 2, "chunk": 3, "t": now}
    )
    claim_key = f"{dcn.CLAIM_PREFIX}/{dcn._seq}/whatif/1/0"
    real_sleep = time.sleep

    def _sibling_claims(d):
        kv.store.setdefault(claim_key, json.dumps(
            {"claimant": 2, "for": 1, "gen": 0, "t": time.time()}
        ))
        real_sleep(0)

    monkeypatch.setattr(dcn.time, "sleep", _sibling_claims)
    ok = dcn._maybe_recover(
        kv, "ksim/gather/1", 1, "whatif",
        recover=lambda p, gen=0: pytest.fail(
            "coordinator re-executed a block a live sibling claimed"
        ),
    )
    assert ok is True
    assert json.loads(kv.store[claim_key])["claimant"] == 2


def test_coordinator_claims_when_no_live_sibling(monkeypatch):
    """Liveness: with every other process stale, the coordinator's
    grace window collapses immediately and it claims generation 0."""
    import time

    kv = _fleet(monkeypatch, nproc=3, pid=0)
    monkeypatch.setenv("KSIM_DCN_RECOVER", "1")
    monkeypatch.setenv("KSIM_DCN_STALL_S", "0.5")
    monkeypatch.setenv("KSIM_DCN_POLL_S", "0.01")
    now = time.time()
    for q in (1, 2):
        kv.store[f"{dcn.HB_PREFIX}/{q}"] = json.dumps(
            {"pid": q, "chunk": 0, "t": now - 10.0}
        )
    calls = []
    t0 = time.monotonic()
    ok = dcn._maybe_recover(
        kv, "ksim/gather/1", 1, "whatif",
        recover=lambda p, gen=0: (calls.append((p, gen)), {"x": 1})[1],
    )
    assert ok is True and calls == [(1, 0)]
    assert time.monotonic() - t0 < 0.4, "grace window should collapse"
    assert dcn.read_claim(1, 0)["claimant"] == 0


# -- heartbeat through injected faults --------------------------------------


def test_heartbeat_survives_transient_kv_errors(fl_off, monkeypatch):
    monkeypatch.setenv("KSIM_FAULTLINE", "1")
    monkeypatch.setenv("KSIM_FAULTLINE_SEED", "2")
    monkeypatch.setenv("KSIM_FAULTLINE_KV_ERROR_RATE", "0.3")
    monkeypatch.setenv("KSIM_DCN_RETRY_BASE_S", "0.001")
    raw = _FakeKV()
    monkeypatch.setattr(dcn, "process_info", lambda: (2, 1))
    monkeypatch.setattr(dcn, "_client", lambda: faultline.wrap_kv(raw))
    monkeypatch.setattr(dcn, "_degraded_exit_armed", [True])
    oks = [dcn.heartbeat(i, total=64, state="run") for i in range(64)]
    # With 2 bounded attempts at 30% error rate most beats land; a beat
    # that exhausts its budget returns False instead of raising.
    assert sum(oks) > 32
    assert f"{dcn.HB_PREFIX}/1" in raw.store


# -- config validation -------------------------------------------------------


def _cfg(yaml_text, tmp_path):
    from kubernetes_simulator_tpu.utils.config import SimConfig

    p = tmp_path / "c.yaml"
    p.write_text(yaml_text)
    return SimConfig.load(str(p))


_BASE = """
strategy: jax
cluster: {synthetic: {nodes: 4, seed: 1}}
workload: {synthetic: {pods: 8, seed: 1}}
whatIf: {scenarios: 2, seed: 1}
"""


def test_validate_refuses_bad_faultline(tmp_path):
    from kubernetes_simulator_tpu.cli import validate_config

    cfg = _cfg(
        _BASE
        + "faultline: {enabled: true, seed: -1, kvErrorRate: 1.5,\n"
        + "  kvDelayS: -0.5, kill: 'zz@run'}\n",
        tmp_path,
    )
    errors = "\n".join(validate_config(cfg))
    assert "faultline.seed" in errors
    assert "faultline.kvErrorRate" in errors
    assert "faultline.kvDelayS" in errors
    assert "faultline.kill" in errors


def test_validate_warns_injection_without_recovery(tmp_path, caplog):
    from kubernetes_simulator_tpu.cli import validate_config

    cfg = _cfg(
        _BASE + "faultline: {enabled: true, seed: 1, kvErrorRate: 0.1}\n",
        tmp_path,
    )
    with caplog.at_level(logging.WARNING):
        errors = validate_config(cfg)
    assert not [e for e in errors if "faultline" in e]
    assert any("dcn.recovery disabled" in r.message for r in caplog.records)


def test_validate_accepts_example_config16():
    from kubernetes_simulator_tpu.cli import validate_config
    from kubernetes_simulator_tpu.utils.config import SimConfig

    path = os.path.join(
        os.path.dirname(__file__), "..", "examples", "config16_faultline.yaml"
    )
    cfg = SimConfig.load(path)
    assert cfg.faultline is not None and cfg.faultline.enabled
    assert cfg.faultline.seed == 17
    assert cfg.faultline.kill == "1@run:0"
    errors = [e for e in validate_config(cfg) if "faultline" in e]
    assert errors == []


def test_faultline_section_absent_is_silent(tmp_path):
    from kubernetes_simulator_tpu.cli import _faultline_errors

    cfg = _cfg(_BASE, tmp_path)
    assert cfg.faultline is None
    assert _faultline_errors(cfg) == []
