"""Granularity-envelope guard (round 5, VERDICT r4 next #2): the
measured 89%-loss cliff — durations ≪ chunk arrival span — must not be
reachable silently. The guard warns with the measured reference and
auto-shrinks chunk_waves toward the duration scale; post-guard the cliff
shape recovers to the CPU event engine's counts (measured here: 86% loss
at C=2048 → 0.0% gap at the guarded C)."""

import warnings

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.granularity import SAFE_RATIO, assess
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.waves import pack_waves
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine


def _cliff_case():
    """Tight cluster, arrivals spanning ~400 s, 4 s durations: at
    C=2048 the whole trace is one chunk and nothing ever releases."""
    cluster = make_cluster(10, seed=0)
    pods, _ = make_workload(2000, seed=0, arrival_rate=5.0, duration_mean=4.0)
    return encode(cluster, pods)


@pytest.mark.slow
def test_cliff_recovers_under_guard():
    ec, ep = _cliff_case()
    cfg = FrameworkConfig()
    cpu = CpuReplayEngine(ec, ep, cfg).replay()
    # Guard OFF reproduces the documented cliff (>50% placement loss).
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        off = WhatIfEngine(
            ec, ep, [Scenario()], cfg, chunk_waves=2048,
            granularity_guard=False,
        ).run()
    assert int(off.placed[0]) < 0.5 * cpu.placed
    # Guard ON: warns, shrinks chunks, recovers to within 2% of the CPU
    # event engine (measured 0.0%).
    with pytest.warns(UserWarning, match="measured-safe"):
        eng = WhatIfEngine(ec, ep, [Scenario()], cfg, chunk_waves=2048)
    assert eng.chunk_waves < 2048
    on = eng.run()
    gap = abs(int(on.placed[0]) - cpu.placed) / cpu.placed
    assert gap <= 0.02, (int(on.placed[0]), cpu.placed)


def test_cliff_recovers_single_replay_engine():
    ec, ep = _cliff_case()
    cfg = FrameworkConfig()
    cpu = CpuReplayEngine(ec, ep, cfg).replay()
    with pytest.warns(UserWarning, match="measured-safe"):
        res = JaxReplayEngine(ec, ep, cfg, chunk_waves=2048).replay()
    gap = abs(res.placed - cpu.placed) / cpu.placed
    assert gap <= 0.02
    # Boundary mode (retry) takes the same guard, growing the buffer to
    # the new chunk burst.
    with pytest.warns(UserWarning, match="measured-safe"):
        rb = JaxReplayEngine(
            ec, ep, cfg, chunk_waves=2048, retry_buffer=8
        ).replay()
    assert abs(rb.placed - cpu.placed) / cpu.placed <= 0.02


def test_safe_shapes_untouched():
    """The headline regimes must pass through unchanged (measured:
    north-star C=4096 ratio 0.93, bench C=512 ratio 1.26, config-4
    C=2048 ratio 1.87 — all >= SAFE_RATIO)."""
    cluster = make_cluster(20, seed=1)
    pods, _ = make_workload(400, seed=1, arrival_rate=12.0, duration_mean=60.0)
    ec, ep = encode(cluster, pods)
    w = pack_waves(ep, 8)
    a = assess(ep, w.idx, 4)
    assert a.ratio >= SAFE_RATIO
    assert a.chunk_waves == 4
    # No warning on construction.
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        WhatIfEngine(ec, ep, [Scenario()], FrameworkConfig(), chunk_waves=4)


def test_beyond_cliff_at_floor_still_warns():
    """A trace outside the envelope run at chunk_waves <= the shrink
    floor has nothing to auto-shrink — it must STILL warn (the silent
    beyond-cliff run is the bug class this module exists for)."""
    ec, ep = _cliff_case()
    with pytest.warns(UserWarning, match="shrink floor"):
        WhatIfEngine(
            ec, ep, [Scenario()], FrameworkConfig(), chunk_waves=8
        )


def test_durationless_trace_exempt():
    cluster = make_cluster(10, seed=2)
    pods, _ = make_workload(200, seed=2)
    ec, ep = encode(cluster, pods)
    w = pack_waves(ep, 8)
    a = assess(ep, w.idx, 2048)
    assert a.ratio == np.inf and a.chunk_waves == 2048
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        WhatIfEngine(ec, ep, [Scenario()], FrameworkConfig(), chunk_waves=2048)
