"""Worker + case variant for the round-15 DCN recovery suite
(tests/test_dcn_recovery.py).

Everything rides tests/dcn_case_worker.py — same production init path
(``dcn.maybe_init_from_env``), same self-kill arming, same one-JSON-line
protocol — plus ONE extra case: ``recovery_fleet`` is ``fleetmerge``
with the strict per-process phase-prefix assertion loosened. Under
survivor recovery the dead process's part is re-executed by the
claimant, whose engine scopes its wall-clock phases under the
CLAIMANT's pid (honest attribution), so the merged fleet telemetry
carries fewer ``p<pid>/`` namespaces than a no-failure fleet — every
virtual-time-derived field still bit-matches the oracle, which is what
the payload compares.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import dcn_case_worker as W  # noqa: E402


def case_recovery_fleet():
    """Round-12 fleetmerge engine (kube+series, no-mesh DCN path) with
    the recovery-tolerant phase-prefix pin: a subset of the fleet's
    ``p<pid>/`` namespaces, never an unknown one."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.parallel import dcn
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node(f"n{i}", {"cpu": 4.0}) for i in range(4)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=20.0)
        for i in range(24)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    scenarios = [
        Scenario(),
        Scenario(events=[
            NodeEvent(time=6.0, kind="node_down", node=0),
            NodeEvent(time=14.0, kind="node_up", node=0),
        ]),
        Scenario(events=[NodeEvent(time=10.0, kind="node_down", node=1)]),
        Scenario(),
    ]
    eng = WhatIfEngine(
        ec, ep, scenarios, cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=32, telemetry="series",
    )
    res = eng.run()
    ft = res.fleet_telemetry
    assert ft is not None, "fleet_telemetry missing from what-if result"
    nproc, _ = dcn.process_info()
    prefixes = {k.split("/", 1)[0] for k in ft.phases}
    fleet = {f"p{i}" for i in range(max(nproc, 1))}
    assert prefixes and prefixes <= fleet, (prefixes, fleet)
    return eng, {
        "granularity": ft.granularity,
        "latency": ft.latency,
        "reasons": ft.reasons,
        "rejection_attempts": ft.rejection_attempts,
        "zero_latency_binds": int(ft.zero_latency_binds),
        "bind_values": [float(v) for v in ft.bind_latency.values()],
        "series_sha": W._sha(
            json.dumps(ft.series, sort_keys=True).encode()
        ),
        "events_len": len(ft.events),
    }


W.CASES["recovery_fleet"] = case_recovery_fleet


if __name__ == "__main__":
    sys.exit(W.main())
