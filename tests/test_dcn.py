"""Round-11 multi-host DCN parity suite (ISSUE round 11: process-local
folds, local-shard result fetch, ONE end-of-replay gather).

A 2-process CPU DCN replay must be indistinguishable from the
single-process mesh run: per-scenario results, collected assignment
matrices, deterministic JSONL bytes, checkpoint blob content and tuner
trajectories are all compared EXACTLY against a single-process oracle
computed in this test process from the SAME case builders
(tests/dcn_case_worker.py). The worker additionally pins the round-11
counters in-process: ``WhatIfEngine._replicate_count == 0`` (no
cross-process ``_fetch`` replication — the chunk loop is process-local)
and ``dcn.GATHER_COUNT`` advancing by exactly ONE per what-if replay.

The quick 2-process "plain" split is tier-1; the kube/chaos, tuner and
checkpoint cases plus the replicated-fallback batch ride one slow fleet.
"""

import functools
import json
import os
import socket
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(__file__))

import dcn_case_worker as W  # noqa: E402

_WORKER = os.path.join(os.path.dirname(__file__), "dcn_case_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(cases, nproc: int = 2, timeout: int = 300) -> dict:
    """Spawn the nproc-worker fleet over ``cases``; every worker must
    exit 0 and print an identical full result (the gather replicates the
    assembled batch to every process)."""
    port = _free_port()
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": f"--xla_force_host_platform_device_count={8 // nproc}",
        "KSIM_DCN_COORD": f"127.0.0.1:{port}",
        "KSIM_DCN_NPROC": str(nproc),
        "KSIM_DCN_CASES": ",".join(cases),
        # Workers import the repo package from the checkout; axon
        # sitecustomize dirs pre-import jax and must be dropped (same
        # hygiene as tests/test_distributed.py).
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + [
                p
                for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p
            ]
        ),
    }
    procs = []
    for pid in range(nproc):
        env = dict(env_base, KSIM_DCN_PID=str(pid))
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    outs = []
    try:
        for p in procs:
            try:
                out, err = p.communicate(timeout=timeout)
            except subprocess.TimeoutExpired:
                pytest.fail("DCN case worker timed out")
            if "Multiprocess computations aren't implemented" in (out + err):
                for q in procs:
                    if q.poll() is None:
                        q.kill()
                        q.wait()
                pytest.skip("jaxlib CPU backend lacks multiprocess execution")
            assert p.returncode == 0, f"worker failed:\n{out}\n{err}"
            lines = [
                l for l in out.splitlines()
                if l.startswith("DCN_CASES_RESULT ")
            ]
            assert lines, f"no result line:\n{out}\n{err}"
            outs.append(json.loads(lines[-1][len("DCN_CASES_RESULT "):]))
    finally:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
    for o in outs[1:]:
        assert o == outs[0], "processes disagree on the gathered result"
    return outs[0]


@functools.lru_cache(maxsize=None)
def _oracle(case: str):
    """Single-process reference, through the same JSON round-trip the
    worker results take (so int/float/None representations match)."""
    out = W.run_cases([case], expect_dcn=False)
    return json.loads(json.dumps(out[case]))


def test_two_process_plain_parity():
    """Mesh what-if with device boundary-retry + collected assignments +
    deterministic JSONL: the 2-process run's gathered result — including
    the JSONL file BYTES — equals the single-process mesh run's."""
    res = _launch(("plain",))
    assert res["plain"] == _oracle("plain")


@pytest.mark.slow
def test_two_process_kube_tuner_ckpt_parity():
    """One slow fleet over the remaining round-11 parity cases:
    kube/chaos timelines with series telemetry through the host mirrors,
    a CEM tuner whose per-sweep gathers make the trajectory
    process-count-independent, checkpoint blob content from the
    single-replay engine, the loud replicated fallback for a batch that
    does not divide over the processes, plus the round-12 merged fleet
    telemetry (2-process ReplayTelemetry.merge == 1-process oracle)."""
    cases = ("chaos", "tuner", "ckpt", "odd", "fleetmerge")
    res = _launch(cases, timeout=600)
    for c in cases:
        assert res[c] == _oracle(c), f"case {c} diverged"


@pytest.mark.slow
def test_killed_worker_fails_fast_attributed():
    """Round-12 liveness bar: SIGKILL one worker mid-replay (the worker
    self-kills after its chunk-0 heartbeat) and the SURVIVOR must abort
    the gather with an attributed error naming the dead process and its
    last completed chunk — well before KSIM_DCN_TIMEOUT_S (here 600s),
    because the dead worker's beacon goes stale past KSIM_DCN_STALL_S."""
    import time

    port = _free_port()
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "KSIM_DCN_COORD": f"127.0.0.1:{port}",
        "KSIM_DCN_NPROC": "2",
        "KSIM_DCN_CASES": "fleetmerge",
        # Fast-fail knobs: the full timeout is deliberately huge so the
        # test proves the STALL detector (not the deadline) fired.
        "KSIM_DCN_TIMEOUT_S": "600",
        "KSIM_DCN_STALL_S": "2",
        "KSIM_DCN_POLL_S": "0.3",
        "KSIM_DCN_HEARTBEAT_EVERY": "1",
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + [
                p
                for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p
            ]
        ),
    }
    t0 = time.monotonic()
    procs = []
    for pid in range(2):
        env = dict(env_base, KSIM_DCN_PID=str(pid))
        if pid == 1:
            env["KSIM_DCN_SELFKILL_AT_CHUNK"] = "0"
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    try:
        out0, err0 = procs[0].communicate(timeout=300)
        procs[1].wait(timeout=60)
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
        pytest.fail("survivor did not fail fast on a killed worker")
    elapsed = time.monotonic() - t0
    blob = out0 + err0
    if "Multiprocess computations aren't implemented" in blob:
        pytest.skip("jaxlib CPU backend lacks multiprocess execution")
    assert procs[1].returncode == -9, "worker 1 should have SIGKILLed itself"
    assert procs[0].returncode != 0, f"survivor exited 0:\n{blob}"
    assert "process 1" in blob, f"error does not name the dead process:\n{blob}"
    assert "last completed chunk" in blob, blob
    assert "looks DEAD" in blob, blob
    # Attributed failure must come from the stall detector, not the 600s
    # deadline (generous bound: replay + compile + stall window).
    assert elapsed < 240, f"survivor took {elapsed:.0f}s to fail"
