"""v3 engine (domain-space state, wave-deferred commits) must match the
v2 node-space engine and the CPU greedy oracle EXACTLY — including with
the host-plane path forced on (tiny dmax_coarse) and with the class-mask
fallback disabled/enabled."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


def _case(seed, n_nodes=60, n_pods=240):
    cluster = make_cluster(n_nodes, seed=seed, taint_fraction=0.3)
    pods, _ = make_workload(
        n_pods, seed=seed, with_affinity=True, with_spread=True,
        with_tolerations=True, gang_fraction=0.1, gang_size=3,
    )
    return encode(cluster, pods)


def _assert_same(ec, ep, **kw):
    cfg = FrameworkConfig()
    cpu = greedy_replay(ec, ep, cfg)
    v2 = JaxReplayEngine(ec, ep, cfg, engine="v2").replay()
    v3 = JaxReplayEngine(ec, ep, cfg, engine="v3", **kw).replay()
    np.testing.assert_array_equal(cpu.assignments, v2.assignments)
    np.testing.assert_array_equal(cpu.assignments, v3.assignments)
    np.testing.assert_allclose(v2.state.used, v3.state.used, atol=1e-3)
    np.testing.assert_allclose(v2.state.match_count, v3.state.match_count, atol=1e-5)
    np.testing.assert_allclose(v2.state.anti_active, v3.state.anti_active, atol=1e-5)
    return v3


@pytest.mark.parametrize(
    "seed", [0, pytest.param(1, marks=pytest.mark.slow),
             pytest.param(2, marks=pytest.mark.slow)]
)
def test_v3_matches_v2_and_cpu(seed):
    ec, ep = _case(seed)
    _assert_same(ec, ep)


@pytest.mark.slow
def test_v3_host_planes_forced():
    """dmax_coarse=4 pushes zone/rack groups onto the host-plane path —
    results must not change."""
    ec, ep = _case(3)
    _assert_same(ec, ep, dmax_coarse=4)


@pytest.mark.slow
def test_v3_class_fallback(monkeypatch):
    """Force the per-wave vmap fallback (as if every pod were distinct)."""
    from kubernetes_simulator_tpu.ops import tpu3 as V3

    monkeypatch.setattr(V3.V3Static, "MAX_CLASSES", 0)
    ec, ep = _case(4)
    _assert_same(ec, ep)


def test_v3_host_singleton_partial_labels():
    """Singleton host topology where some nodes LACK the label: binds onto
    label-less nodes must not credit the host planes (regression: the
    singleton commit fast path skipped v2's node_has_dom gate, making the
    symmetric-anti check wrongly block label-less nodes)."""
    from kubernetes_simulator_tpu.models.core import (
        Cluster, LabelSelector, Node, Pod, PodAffinitySpec, PodAffinityTerm,
    )

    key = "custom/slot"
    nodes = [
        Node(
            f"n{i}",
            capacity={"cpu": 4.0, "memory": 8 * 2**30, "pods": 20},
            labels=({key: f"s{i}"} if i % 3 != 0 else {}),  # every 3rd bare
        )
        for i in range(12)
    ]
    anti = PodAffinitySpec(
        required=(PodAffinityTerm(LabelSelector.make({"app": "a"}), key),)
    )
    pods = [
        Pod(f"p{i}", labels={"app": "a"}, requests={"cpu": 1.0},
            arrival_time=float(i), pod_anti_affinity=anti)
        for i in range(20)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    # dmax_coarse=0 forces every topology onto the host-plane path; the
    # custom key's domains are singletons.
    _assert_same(ec, ep, dmax_coarse=0)


@pytest.mark.slow
def test_v3_mesh_with_host_planes():
    """Mesh-sharded what-if on a trace whose anti terms ride a hostname
    topology (>128 domains → real host planes). Regression: the sharding
    proto state used width-1 planes and crashed in from_host."""
    import jax

    from kubernetes_simulator_tpu.parallel.mesh import make_mesh
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    cluster = make_cluster(150, seed=7)
    pods, _ = make_workload(200, seed=7, with_affinity=True)
    ec, ep = encode(cluster, pods)
    mesh = make_mesh(2)
    eng = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], FrameworkConfig(),
        mesh=mesh, collect_assignments=True,
    )
    assert eng.engine == "v3" and eng.static3.has_host_rows
    res = eng.run()
    single = JaxReplayEngine(ec, ep, FrameworkConfig()).replay()
    np.testing.assert_array_equal(res.assignments[0], single.assignments)


@pytest.mark.slow
def test_v3_checkpoint_resume_identical(tmp_path):
    ec, ep = _case(5, n_pods=400)
    cfg = FrameworkConfig()
    full = JaxReplayEngine(ec, ep, cfg, chunk_waves=8).replay()
    path = str(tmp_path / "v3.ck.npz")
    eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=8)
    eng.replay(checkpoint_path=path, checkpoint_every=2)
    resumed = JaxReplayEngine(ec, ep, cfg, chunk_waves=8).replay(
        checkpoint_path=path, resume=True
    )
    np.testing.assert_array_equal(full.assignments, resumed.assignments)


def test_bf16_host_planes_disabled_under_capacity_events():
    """capacity_scale node events can push per-node pod counts past the
    bf16 exactness bound — the engine must rebuild without bf16 planes."""
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent

    cluster = make_cluster(150, seed=7)
    pods, _ = make_workload(300, seed=7, with_affinity=True)
    ec, ep = encode(cluster, pods)
    eng = JaxReplayEngine(ec, ep, FrameworkConfig())
    if not (eng.static3.mc_h_bf16 or eng.static3.anti_h_bf16):
        pytest.skip("trace has no bf16 host planes")
    ev = [NodeEvent(time=1.0, kind="capacity_scale", node=0, scale=3.0)]
    res = eng.replay(node_events=ev)
    assert not (eng.static3.mc_h_bf16 or eng.static3.anti_h_bf16)
    assert res.placed > 0
