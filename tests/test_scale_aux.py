"""Borg-like trace generator, checkpoint/resume, config/CLI, metrics
(SURVEY.md §4.5, §5)."""

import json
import os

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.borg import BorgSpec, make_borg_encoded, make_borg_trace
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.utils.config import SimConfig, build_case


class TestBorg:
    def test_encoded_fast_path_structure(self):
        spec = BorgSpec(nodes=100, tasks=5000, seed=1)
        ec, ep, meta = make_borg_encoded(spec)
        assert ep.num_pods == 5000
        assert ec.num_nodes == 100
        assert meta["num_gangs"] > 0
        # Gang members are contiguous (wave packing requirement).
        gid = ep.group_id
        for g in np.unique(gid[gid >= 0]):
            idxs = np.nonzero(gid == g)[0]
            assert (np.diff(idxs) == 1).all()
            assert ep.pg_min_member[g] == idxs.size
        # Priorities are tiered.
        assert set(np.unique(ep.priority)) <= {0, 100, 200, 360, 450}
        # Arrivals sorted.
        assert (np.diff(ep.arrival) >= 0).all()

    def test_encoded_trace_replays_on_jax(self):
        spec = BorgSpec(nodes=60, tasks=2000, seed=2, max_gang=6)
        ec, ep, meta = make_borg_encoded(spec)
        res = JaxReplayEngine(ec, ep, FrameworkConfig(), wave_width=8).replay()
        assert res.placed > 1500
        assert res.placed + res.unschedulable == 2000

    def test_object_model_variant_matches_shape(self):
        class S:
            nodes, tasks, seed, gang_fraction, max_gang = 30, 300, 3, 0.1, 4

        cluster, pods = make_borg_trace(S)
        assert len(pods) == 300
        gangs = {p.pod_group for p in pods if p.pod_group}
        assert gangs
        ec, ep = encode(cluster, pods)
        res = JaxReplayEngine(ec, ep, FrameworkConfig()).replay()
        assert res.placed > 200


class TestCheckpoint:
    def test_resume_identical(self, tmp_path):
        from kubernetes_simulator_tpu.sim.synthetic import config1

        cluster, pods, plugins = config1(num_nodes=20, num_pods=300)
        ec, ep = encode(cluster, pods)
        cfg = FrameworkConfig(plugins=plugins)
        full = JaxReplayEngine(ec, ep, cfg, chunk_waves=8).replay()

        ck = str(tmp_path / "ck.npz")
        eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=8)
        eng.replay(checkpoint_path=ck, checkpoint_every=2)
        assert os.path.exists(ck)
        # Resume from the mid-run snapshot and finish.
        resumed = JaxReplayEngine(ec, ep, cfg, chunk_waves=8).replay(
            checkpoint_path=ck, resume=True
        )
        assert (resumed.assignments == full.assignments).all()
        assert resumed.placed == full.placed


class TestConfigCli:
    CFG = """
strategy: cpu
cluster:
  synthetic: {nodes: 20, seed: 0}
workload:
  synthetic: {pods: 50, seed: 0, affinity: true}
profile:
  plugins:
    - name: NodeResourcesFit
      args: {strategy: LeastAllocated}
    - name: TaintToleration
  weights: {NodeResourcesFit: 1, TaintToleration: 3}
whatIf:
  scenarios: 4
  seed: 1
"""

    def test_config_roundtrip(self, tmp_path):
        p = tmp_path / "cfg.yaml"
        p.write_text(self.CFG)
        cfg = SimConfig.load(str(p))
        assert cfg.strategy == "cpu"
        assert cfg.cluster.nodes == 20
        assert cfg.workload.pods == 50
        assert cfg.framework.plugins[0]["name"] == "NodeResourcesFit"
        assert cfg.whatif.scenarios == 4
        cluster, pods = build_case(cfg)
        assert len(cluster.nodes) == 20 and len(pods) == 50

    def test_cli_run_and_whatif(self, tmp_path, capsys):
        from kubernetes_simulator_tpu.cli import main

        out = tmp_path / "res.jsonl"
        p = tmp_path / "cfg.yaml"
        p.write_text(self.CFG + f"output: {out}\n")
        assert main(["run", str(p)]) == 0
        assert main(["run", str(p), "--strategy", "jax"]) == 0
        assert main(["what-if", str(p)]) == 0
        rows = [json.loads(l) for l in out.read_text().splitlines()]
        kinds = {r["kind"] for r in rows}
        assert "replay-cpu" in kinds and "replay-jax" in kinds
        assert "whatif-aggregate" in kinds and "whatif-scenario" in kinds
        agg = [r for r in rows if r["kind"] == "whatif-aggregate"][0]
        assert agg["total_placed"] > 0

    def test_profile_mode_collects_plugin_latency(self):
        from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
        from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

        cluster = make_cluster(10, seed=0)
        pods, _ = make_workload(30, seed=0, with_affinity=True)
        ec, ep = encode(cluster, pods)
        eng = CpuReplayEngine(ec, ep, FrameworkConfig(profile=True))
        eng.replay()
        assert any(k.startswith("Filter/") for k in eng.fw.plugin_time)
        assert any(k.startswith("Score/") for k in eng.fw.plugin_time)


class TestEncodedCli:
    def test_borg_config_uses_encoded_fast_path(self):
        # 250k tasks exceeds the object-model cap — the CLI must take the
        # template-expansion fast path (regression: config4_borg_1m.yaml
        # raised through build_case).
        from kubernetes_simulator_tpu.utils.config import SimConfig, build_encoded_case

        cfg = SimConfig.from_dict({
            "strategy": "jax",
            "workload": {"borg": {"nodes": 300, "tasks": 250_000, "seed": 1}},
        })
        ec, ep = build_encoded_case(cfg)
        assert ep.num_pods == 250_000 and ec.num_nodes == 300

    def test_borg_trace_path_config(self, tmp_path):
        from kubernetes_simulator_tpu.sim.borg import BorgSpec, export_trace_csv
        from kubernetes_simulator_tpu.utils.config import SimConfig, build_encoded_case

        path = tmp_path / "t.csv"
        export_trace_csv(BorgSpec(nodes=40, tasks=500, seed=2), path)
        cfg = SimConfig.from_dict({
            "workload": {"borg": {"nodes": 40, "tasks": 500, "seed": 2,
                                  "tracePath": str(path)}},
        })
        ec, ep = build_encoded_case(cfg)
        assert ep.num_pods == 500

    def test_cli_run_small_borg(self, tmp_path, capsys):
        import yaml

        from kubernetes_simulator_tpu.cli import main

        cfgp = tmp_path / "b.yaml"
        cfgp.write_text(yaml.safe_dump({
            "strategy": "jax",
            "workload": {"borg": {"nodes": 50, "tasks": 2000, "seed": 0}},
        }))
        assert main(["run", str(cfgp)]) == 0
        out = capsys.readouterr().out
        assert '"kind": "replay-jax"' in out


class TestValidate:
    def _write(self, tmp_path, doc):
        import yaml

        p = tmp_path / "cfg.yaml"
        p.write_text(yaml.safe_dump(doc))
        return str(p)

    def test_rejects_unknown_plugin_and_bad_gang(self, tmp_path, capsys):
        from kubernetes_simulator_tpu.cli import main

        cfg = self._write(
            tmp_path,
            {
                "strategy": "jax",
                "waveWidth": 4,
                "workload": {"borg": {"nodes": 10, "tasks": 100, "maxGang": 8}},
                "profile": {"plugins": [{"name": "NoSuchPlugin"}]},
            },
        )
        rc = main(["validate", cfg])
        out = capsys.readouterr().out
        assert rc == 1
        assert "unknown plugin 'NoSuchPlugin'" in out
        assert "exceeds" in out and "waveWidth" in out

    def test_rejects_missing_trace_file(self, tmp_path, capsys):
        from kubernetes_simulator_tpu.cli import main

        cfg = self._write(
            tmp_path,
            {
                "workload": {
                    "borg": {
                        "nodes": 10,
                        "tasks": 10,
                        "instanceEvents": "/no/such/file.csv",
                    }
                }
            },
        )
        rc = main(["validate", cfg])
        assert rc == 1
        assert "file not found" in capsys.readouterr().out

    def test_accepts_valid_config(self, capsys):
        from kubernetes_simulator_tpu.cli import main

        rc = main(["validate", "examples/config3_whatif_256.yaml"])
        out = capsys.readouterr().out
        assert rc == 0
        assert '"errors": []' in out

    def test_rejects_retry_buffer_with_completions_off(self, tmp_path, capsys):
        """ADVICE r4: retryBuffer + completions:false must fail at
        validate with a message naming completions, not later at engine
        construction with a release-path message that never mentions it."""
        from kubernetes_simulator_tpu.cli import main

        cfg = self._write(
            tmp_path,
            {
                "strategy": "jax",
                "whatIf": {
                    "scenarios": 4,
                    "retryBuffer": 64,
                    "completions": False,
                },
            },
        )
        rc = main(["validate", cfg])
        out = capsys.readouterr().out
        assert rc == 1
        assert "retryBuffer" in out and "completions" in out

    def test_non_bool_completions_raises_at_parse(self):
        """ADVICE r4: a string whatIf.completions (e.g. 'yes') must raise
        in SimConfig.from_dict, not silently behave as default-on."""
        import pytest

        from kubernetes_simulator_tpu.utils.config import SimConfig

        with pytest.raises(ValueError, match="whatIf.completions"):
            SimConfig.from_dict({"whatIf": {"completions": "yes"}})
        # int 0/1 and real bools still coerce.
        assert SimConfig.from_dict(
            {"whatIf": {"completions": 1}}
        ).whatif.completions is True
        assert SimConfig.from_dict(
            {"whatIf": {"completions": False}}
        ).whatif.completions is False

    def test_recovery_requires_dcn_fleet_and_heartbeats(
        self, tmp_path, capsys, monkeypatch
    ):
        """Round 15: dcn.recovery.enable outside a DCN fleet (no
        KSIM_DCN_NPROC) or with heartbeats disabled must refuse with a
        message naming the fix; inside a fleet with beacons on, the same
        config validates clean."""
        from kubernetes_simulator_tpu.cli import main

        monkeypatch.delenv("KSIM_DCN_NPROC", raising=False)
        monkeypatch.delenv("KSIM_DCN_HEARTBEAT_EVERY", raising=False)
        cfg = self._write(
            tmp_path,
            {
                "strategy": "jax",
                "whatIf": {"scenarios": 4},
                "dcn": {"recovery": {"enable": True, "checkpointEvery": 2}},
            },
        )
        rc = main(["validate", cfg])
        out = capsys.readouterr().out
        assert rc == 1
        assert "dcn_launch" in out and "KSIM_DCN_NPROC" in out

        monkeypatch.setenv("KSIM_DCN_NPROC", "2")
        monkeypatch.setenv("KSIM_DCN_HEARTBEAT_EVERY", "0")
        rc = main(["validate", cfg])
        out = capsys.readouterr().out
        assert rc == 1
        assert "KSIM_DCN_HEARTBEAT_EVERY" in out and "heartbeat" in out

        monkeypatch.delenv("KSIM_DCN_HEARTBEAT_EVERY", raising=False)
        rc = main(["validate", cfg])
        out = capsys.readouterr().out
        assert rc == 0
        assert '"errors": []' in out

    def test_recovery_value_checks_apply_even_disabled(
        self, tmp_path, capsys
    ):
        """checkpointEvery/maxClaims sanity is structural — it must not
        hide behind enable: true (a disabled-but-broken section would
        explode the day someone flips the switch)."""
        from kubernetes_simulator_tpu.cli import main

        cfg = self._write(
            tmp_path,
            {
                "dcn": {
                    "recovery": {
                        "enable": False,
                        "checkpointEvery": -1,
                        "maxClaims": 0,
                    }
                },
            },
        )
        rc = main(["validate", cfg])
        out = capsys.readouterr().out
        assert rc == 1
        assert "dcn.recovery.checkpointEvery" in out
        assert "dcn.recovery.maxClaims" in out

    def test_compile_cache_repeat_enable_reports_configured_dir(
        self, tmp_path
    ):
        """ADVICE r4: a second enable() with a different dir must return
        the dir JAX actually uses, not the ignored new one."""
        import pytest

        from kubernetes_simulator_tpu.utils import compile_cache as cc

        first = cc.enable()  # whatever conftest/env already configured
        if first is None:
            pytest.skip("compile cache disabled in this environment")
        assert cc.enable(str(tmp_path / "other_cache")) == first
