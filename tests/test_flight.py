"""Round 16: the flight recorder (sim.flight) and its bit-parity pin.

The contract under test: the recorder is a pure OBSERVER. Turning it on
changes no placement, no deterministic JSONL byte, and no checkpoint
blob byte across every engine mode it instruments — plain, nodeShards,
pagedWaves, kube-boundary — including a cross-mode resume. Its own
stream is schema-v6 valid, byte-stable for a fixed seed under
KSIM_DETERMINISTIC_JSONL, and carries the attribution the bottleneck
report names regimes from. Pager stall counters are pinned on a crafted
slow-page trace (a sleeping fetch) without any engine in the loop.
"""

import hashlib
import json
import os
import sys
import time

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.flight import (
    FLIGHT_WALL_FIELDS,
    FlightRecorder,
    FlightRecorderConfig,
    read_stream,
    rss_peak_mib,
)
from kubernetes_simulator_tpu.sim.jax_runtime import (
    JaxReplayEngine,
    _PodPager,
)
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

sys.path.insert(
    0,
    os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "scripts")
    ),
)


def _case(n_nodes=24, n_pods=160, seed=7):
    cluster = make_cluster(n_nodes, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(
        n_pods, seed=seed, with_affinity=True, with_spread=True,
        with_tolerations=True, gang_fraction=0.1, gang_size=4,
        duration_mean=40.0,
    )
    return encode(cluster, pods)


@pytest.fixture(scope="module")
def case():
    return _case()


# Engine-mode matrix: kwargs beyond (ec, ep, cfg, chunk_waves=4).
MODES = {
    "plain": {},
    "nodeShards": {"node_shards": 2},
    "pagedWaves": {"paged": True},
    "kube-boundary": {"preemption": "kube", "retry_buffer": 64},
}


def _stable_summary(res):
    row = dict(res.summary())
    for k in ("wall_clock_s", "placements_per_sec"):
        row.pop(k, None)
    return row


@pytest.mark.parametrize("mode", sorted(MODES))
def test_recorder_bit_parity(case, tmp_path, mode):
    """Recorder on vs off: assignments and stable summaries identical in
    every engine mode — the recorder never touches a device program."""
    ec, ep = case
    kw = dict(MODES[mode], chunk_waves=4, telemetry="off")
    off = JaxReplayEngine(ec, ep, FrameworkConfig(), **kw).replay()
    on = JaxReplayEngine(
        ec, ep, FrameworkConfig(),
        flight_recorder=str(tmp_path / f"{mode}.jsonl"), **kw,
    ).replay()
    np.testing.assert_array_equal(
        on.assignments, off.assignments,
        err_msg=f"{mode}: recorder-on assignments diverged",
    )
    assert _stable_summary(on) == _stable_summary(off)
    rows = read_stream(str(tmp_path / f"{mode}.jsonl"))
    assert rows and rows[0]["event"] == "start"
    assert rows[-1]["event"] == "end"
    assert any(r["event"] == "chunk" for r in rows)


def test_recorder_checkpoint_blobs_identical_and_cross_mode_resume(
    case, tmp_path
):
    """Checkpoint blobs byte-identical recorder on/off, and a blob
    written recorder-ON under nodeShards resumes recorder-OFF under
    pagedWaves (cross-mode resume) to the same end state."""
    ec, ep = case
    ref = JaxReplayEngine(
        ec, ep, FrameworkConfig(), chunk_waves=4, telemetry="off",
    ).replay()
    digests = {}
    for tag, rec in (("off", None), ("on", str(tmp_path / "fl.jsonl"))):
        p = tmp_path / f"ckpt_{tag}.npz"
        res = JaxReplayEngine(
            ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=2,
            telemetry="off", flight_recorder=rec,
        ).replay(checkpoint_path=str(p), checkpoint_every=2)
        np.testing.assert_array_equal(res.assignments, ref.assignments)
        digests[tag] = hashlib.sha256(p.read_bytes()).hexdigest()
    assert digests["on"] == digests["off"], (
        "flight recorder changed a checkpoint blob byte — it must be a "
        "pure observer"
    )
    # Recorder-on checkpoint blob events carry the real blob size.
    rows = read_stream(str(tmp_path / "fl.jsonl"))
    cks = [r for r in rows if r["event"] == "checkpoint"]
    assert cks and all(
        r["ckpt_bytes"] == os.path.getsize(tmp_path / "ckpt_on.npz")
        for r in cks[-1:]
    )
    # Cross-mode resume: sharded+recorded blob under a paged engine.
    res = JaxReplayEngine(
        ec, ep, FrameworkConfig(), chunk_waves=4, paged=True,
        telemetry="off",
    ).replay(checkpoint_path=str(tmp_path / "ckpt_on.npz"), resume=True)
    np.testing.assert_array_equal(res.assignments, ref.assignments)


def test_deterministic_jsonl_parity_and_byte_stability(
    case, tmp_path, monkeypatch
):
    """Under KSIM_DETERMINISTIC_JSONL: (a) the replay-result JSONL is
    byte-identical recorder on/off, (b) two recorder streams of the same
    seed are byte-identical to each other (every wall-derived field is
    zeroed, counts/virtual-times stay)."""
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter, replay_row

    monkeypatch.setenv("KSIM_DETERMINISTIC_JSONL", "1")
    ec, ep = case
    blobs = {}
    for tag, rec in (
        ("off", None),
        ("on1", str(tmp_path / "fl1.jsonl")),
        ("on2", str(tmp_path / "fl2.jsonl")),
    ):
        res = JaxReplayEngine(
            ec, ep, FrameworkConfig(), chunk_waves=4, telemetry="off",
            flight_recorder=rec,
        ).replay()
        p = tmp_path / f"res_{tag}.jsonl"
        with JsonlWriter(str(p)) as w:
            w.write(replay_row("replay-jax", res))
        blobs[tag] = p.read_bytes()
    assert blobs["off"] == blobs["on1"] == blobs["on2"]
    fl1 = (tmp_path / "fl1.jsonl").read_bytes()
    fl2 = (tmp_path / "fl2.jsonl").read_bytes()
    assert fl1 == fl2, "fixed-seed recorder streams are not byte-stable"
    for row in read_stream(str(tmp_path / "fl1.jsonl")):
        for k in FLIGHT_WALL_FIELDS:
            if k in row:
                assert row[k] == 0.0, f"{row['event']}: {k} not scrubbed"
        for v in (row.get("phases") or {}).values():
            assert v == 0.0


def test_flight_stream_validates_against_schema_v6(case, tmp_path):
    from check_metrics_schema import validate_file  # noqa: E402

    ec, ep = case
    path = str(tmp_path / "fl.jsonl")
    JaxReplayEngine(
        ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=2,
        paged=False, telemetry="summary", flight_recorder=path,
    ).replay()
    assert validate_file(path) == []
    rows = read_stream(path)
    assert all(r["schema"] == 7 for r in rows)
    # The sharded run's chunk rows carry the exchange attribution.
    cks = [r for r in rows if r["event"] == "chunk"]
    assert cks and all("exchange_est_s" in r for r in cks)


def test_pager_stall_counters_on_crafted_slow_page_trace():
    """Stall accounting pinned without an engine: a sleeping fetch, a
    prefetch-miss access pattern, exact stall counts and a wall lower
    bound. The counters are the recorder's pager evidence."""
    DELAY = 0.02
    fetched = []

    def slow_fetch(ci):
        fetched.append(ci)
        time.sleep(DELAY)
        return ci * 10

    pager = _PodPager(slow_fetch)
    assert (pager.depth, pager.stalls, pager.prefetches) == (0, 0, 0)
    # Chunk 0: nothing prefetched — a synchronous stall.
    assert pager.get(0) == 0
    assert pager.stalls == 1 and pager.stall_s >= DELAY
    assert pager.last_stall_s >= DELAY
    # Steady state: prefetch hides the fetch — no new stalls.
    pager.prefetch(1)
    assert pager.depth == 1 and pager.prefetches == 1
    assert pager.get(1) == 10
    assert pager.stalls == 1 and pager.depth == 0
    # Resume-style jump (prefetched 2, asked for 5): a second stall.
    pager.prefetch(2)
    assert pager.get(5) == 50
    assert pager.stalls == 2 and pager.stall_s >= 2 * DELAY
    assert fetched == [0, 1, 2, 5]


@pytest.mark.slow
def test_recorder_page_events_and_stall_rows(case, tmp_path):
    """A paged replay's recorder stream carries the pager gauges on
    chunk rows and a page event for the cold-start stall."""
    ec, ep = case
    path = str(tmp_path / "fl.jsonl")
    JaxReplayEngine(
        ec, ep, FrameworkConfig(), chunk_waves=4, paged=True,
        telemetry="off", flight_recorder=path,
    ).replay()
    rows = read_stream(path)
    pages = [r for r in rows if r["event"] == "page"]
    assert pages, "cold-start prefetch miss did not emit a page event"
    assert pages[0]["pager_stalls"] >= 1
    cks = [r for r in rows if r["event"] == "chunk"]
    assert all("pager_stalls" in r and "pager_depth" in r for r in cks)


def test_recorder_config_resolve_and_off_by_default(case):
    ec, ep = case
    eng = JaxReplayEngine(ec, ep, FrameworkConfig(), chunk_waves=4)
    assert eng.flight_recorder is None  # OFF by default
    assert FlightRecorderConfig.resolve(None) is None
    cfg = FlightRecorderConfig.resolve("x.jsonl")
    assert isinstance(cfg, FlightRecorderConfig) and cfg.every == 1
    assert FlightRecorderConfig.resolve(cfg) is cfg
    with pytest.raises(ValueError, match="flight_recorder"):
        FlightRecorderConfig.resolve(123)
    assert rss_peak_mib() > 0.0


def test_recorder_every_cadence(tmp_path):
    """every=N thins chunk rows to the cadence; start/end always emit."""
    rec = FlightRecorder(
        FlightRecorderConfig(path=str(tmp_path / "f.jsonl"), every=3)
    )
    for ci in range(7):
        rec.chunk(ci, dispatched=ci)
    rec.close()
    rows = read_stream(str(tmp_path / "f.jsonl"))
    assert [r["chunk"] for r in rows if r["event"] == "chunk"] == [0, 3, 6]
    assert rows[0]["event"] == "start" and rows[-1]["event"] == "end"


@pytest.mark.slow
def test_bottleneck_report_names_regime(case, tmp_path, capsys):
    """End to end: record a composed (sharded × paged is refused, so
    sharded) replay, run the report, get a named dominant regime with
    evidence."""
    from bottleneck_report import REGIMES, main as report_main  # noqa: E402

    ec, ep = case
    path = str(tmp_path / "fl.jsonl")
    JaxReplayEngine(
        ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=2,
        telemetry="summary", flight_recorder=path,
    ).replay()
    assert report_main([path]) == 0
    out = capsys.readouterr().out
    assert "DOMINANT REGIME:" in out
    assert any(r in out for r in REGIMES)
    assert "selection exchange" in out
    # Missing stream: exit 1 with a pointer, no traceback.
    assert report_main([str(tmp_path / "missing.jsonl")]) == 1


def test_bottleneck_report_synthetic_regimes(tmp_path):
    """Regime naming pinned on crafted streams: a stream dominated by
    pager stalls is pager-bound, one dominated by exchange time is
    exchange-bound, one dominated by folds is host-fold-bound."""
    from bottleneck_report import aggregate, attribute  # noqa: E402

    def _mk(name, rows):
        p = tmp_path / f"{name}.jsonl"
        p.write_text(
            "\n".join(
                json.dumps({"kind": "flight", "schema": 5, "ts": 0, **r})
                for r in rows
            )
            + "\n"
        )
        return str(p)

    pager_rows = [
        {"event": "chunk", "chunk": 0, "wall_s": 1.0,
         "phases": {"dispatch": 0.1}, "pager_stalls": 4,
         "pager_stall_s": 0.9},
    ]
    exch_rows = [
        {"event": "chunk", "chunk": 0, "wall_s": 1.0,
         "phases": {"dispatch": 0.1}, "exchange_probe_s": 0.001,
         "exchange_slots": 900, "exchange_est_s": 0.9},
    ]
    fold_rows = [
        {"event": "boundary_fold", "chunk": 0, "stall_s": 0.9,
         "wall_s": 0.9},
        {"event": "chunk", "chunk": 0, "wall_s": 1.0,
         "phases": {"dispatch": 0.1}},
    ]
    for name, rows, want in (
        ("pager", pager_rows, "pager-bound"),
        ("exch", exch_rows, "exchange-bound"),
        ("fold", fold_rows, "host-fold-bound"),
    ):
        ranked = attribute(aggregate(
            [json.loads(line) for line in open(_mk(name, rows))]
        ))
        assert ranked[0][0] == want, f"{name}: got {ranked[0]}"


def test_fleetwatch_flight_lines_tolerant(tmp_path):
    """dcn_launch --watch --flight: renders recorder gauges per process
    and tolerates a missing stream / torn tail entirely."""
    from dcn_launch import FleetWatch  # noqa: E402

    fl = tmp_path / "fl.jsonl"
    w = FleetWatch(str(tmp_path), 2, flight_path=str(fl))
    assert w.flight_lines() == []  # no stream yet: silent
    fl.write_text(
        json.dumps({"kind": "flight", "event": "chunk", "chunk": 3,
                    "rolling_pps": 1234.5, "pager_stalls": 2,
                    "exchange_est_s": 0.012, "rss_peak_mib": 300.0})
        + "\n"
    )
    (tmp_path / "fl.jsonl.p1").write_text('{"torn json\n')
    lines = w.flight_lines()
    assert len(lines) == 1
    assert "p0 flight chunk 3" in lines[0]
    assert "1234pps" in lines[0] or "1235pps" in lines[0]
    assert "stalls=2" in lines[0] and "exch=12.0ms" in lines[0]
    # Byte cursor: nothing new → nothing repeated.
    assert w.flight_lines() == []
    # Recorder off entirely: FleetWatch without a flight path is silent.
    assert FleetWatch(str(tmp_path), 2).flight_lines() == []


def test_fleetwatch_events_tail_survives_truncation(tmp_path):
    """Round 21: the --watch events tail consumes only complete lines,
    and a supervisor relaunch truncating events.jsonl underneath the
    tail resets the byte cursor instead of seeking past EOF."""
    from dcn_launch import FleetWatch  # noqa: E402

    ev = tmp_path / "events.jsonl"
    w = FleetWatch(str(tmp_path), 2)
    assert w.events() == []  # no file yet: silent

    ev.write_text(json.dumps({"event": "lease", "pid": 0, "block": 3}) + "\n")
    got = w.events()
    assert [e["event"] for e in got] == ["lease"]
    # Mid-write partial final line: held back until it completes.
    with open(ev, "a") as f:
        f.write('{"event": "steal", "pid": 1, "blo')
    assert w.events() == []
    with open(ev, "a") as f:
        f.write('ck": 3, "from": 0, "gen": 1}\n')
    assert [e["event"] for e in w.events()] == ["steal"]
    # Supervisor relaunch truncates the file to a new epoch's head: the
    # shrink resets the cursor and the new epoch's rows surface.
    ev.write_text(
        json.dumps({"event": "journal_adopt", "pid": 0, "block": 3,
                    "from": 1}) + "\n"
    )
    assert [e["event"] for e in w.events()] == ["journal_adopt"]


def test_fleetwatch_line_shows_generations_and_life(tmp_path):
    """Round 21 --watch extras: recovery claim generation, work-queue
    lease generation, and the supervised-restart life counter."""
    import time as _time

    from dcn_launch import FleetWatch  # noqa: E402

    w = FleetWatch(str(tmp_path), 2)
    now = _time.time()
    line = w.line({
        0: {"state": "recover", "recovering_for": 1, "recover_gen": 2,
            "chunk": 4, "total_chunks": 8, "t": now, "restart": 1},
        1: {"state": "run", "wq_block": 5, "wq_gen": 1,
            "leased_blocks": 1, "chunk": 6, "total_chunks": 8, "t": now},
    })
    assert "recovering-p1@g2" in line
    assert "life=1" in line
    assert "run@b5.g1" in line


def test_fleetwatch_event_line_renders_round21_kinds():
    """event_line covers the checkpoint and faultline trail kinds the
    round-21 black box stamps into the KV mirror."""
    from dcn_launch import FleetWatch  # noqa: E402

    el = FleetWatch.event_line
    assert "loads p1's checkpoint" in el(
        {"event": "ckpt_load", "by": 2, "pid": 1, "cursor": 4})
    assert "FALLS BACK" in el(
        {"event": "ckpt_fallback", "by": 2, "pid": 1})
    assert "FAULT-KILLED" in el(
        {"event": "fault_kill", "pid": 1, "state": "run"})
    assert "fault error injected on wq/0/lease/3" in el(
        {"event": "fault_inject", "pid": 1, "class": "error",
         "key": "wq/0/lease/3"})
    assert "fault slow_io injected" in el(
        {"event": "fault_slow", "pid": 1, "class": "slow_io"})
