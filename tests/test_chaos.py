"""Chaos campaigns (round 7): timed failure/recovery injection with
device-path eviction parity.

``node_down`` on the boundary-mode device path evicts bound pods with kube
NoExecute semantics — victims free resources through the keyed plane-op
log and re-enter the retry buffer exactly like preemption victims. The
CPU event engine is the parity oracle: at wave_width=1 / chunk_waves=1 on
queue-trivial traces the eviction path matches bit-for-bit, lazy and
eager boundary sync stay bit-identical, checkpoints carry the applied-
event cursor + timeline hash, and the what-if batch runs one timeline per
scenario through the per-scenario host mirrors."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import (
    CpuReplayEngine,
    NodeEvent,
    validate_node_events,
)
from kubernetes_simulator_tpu.sim.synthetic import make_chaos_timeline
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])


def _light_trace(num_pods=28, num_nodes=5, duration=30.0, seed=None):
    """Queue-trivial shape (the documented parity envelope): distinct
    strictly-increasing integer arrivals, priority 0, and load that fits
    the cluster even under the injected failures — the queue stays empty
    except for eviction victims, so no pod ever waits on a completion
    PAST the last arrival (device boundaries end there; the CPU engine
    keeps draining, which is the documented divergence outside this
    envelope)."""
    rng = np.random.default_rng(seed) if seed is not None else None
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(num_nodes)]
    pods = []
    for i in range(num_pods):
        d = duration if rng is None else float(rng.integers(30, 61))
        pods.append(
            Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
                duration=d)
        )
    return encode(Cluster(nodes=nodes), pods)


# All event times stay BELOW the last arrival (27): device boundaries end
# at the last wave, so a later event would fire on the CPU engine only.
EVS = [
    NodeEvent(time=8.0, kind="node_down", node=0),
    NodeEvent(time=18.0, kind="node_up", node=0),
    NodeEvent(time=24.0, kind="node_down", node=1),
]


def test_cpu_device_eviction_parity_and_lazy_eager():
    """W=1 / C=1 queue-trivial: device NoExecute eviction matches the CPU
    event engine bit-for-bit (assignments AND disruption counters), and
    lazy boundary sync stays bit-identical to eager with chaos on."""
    ec, ep = _light_trace()
    cfg = FIT_ONLY()
    cpu = CpuReplayEngine(ec, ep, cfg).replay(node_events=EVS)
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay(node_events=EVS)
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    assert dev.evictions == cpu.evictions > 0  # non-vacuous
    assert dev.evict_rescheduled == cpu.evict_rescheduled
    assert dev.evict_stranded == cpu.evict_stranded
    eager = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64, lazy_boundary=False,
    ).replay(node_events=EVS)
    np.testing.assert_array_equal(dev.assignments, eager.assignments)
    assert dev.evictions == eager.evictions
    assert dev.evict_latency_mean == eager.evict_latency_mean


def test_eviction_counters_distinct_from_preemption():
    """Chaos disruption is reported separately from scheduler-initiated
    preemption: a priority-0 chaos run has evictions > 0, preemptions
    == 0, and summary() carries the four eviction fields."""
    ec, ep = _light_trace()
    res = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay(node_events=EVS)
    assert res.evictions > 0 and res.preemptions == 0
    s = res.summary()
    for k in ("evictions", "evict_rescheduled", "evict_stranded",
              "evict_latency_mean"):
        assert k in s
    assert s["evictions"] == res.evictions


def test_checkpoint_resume_with_events(tmp_path):
    """The applied-event cursor rides the checkpoint blob: a resumed
    chaos replay equals the uninterrupted one exactly, and resuming under
    a DIFFERENT (or missing) timeline is rejected via the event hash."""
    ec, ep = _light_trace(num_pods=60, num_nodes=4)
    cfg = FIT_ONLY()
    evs = [
        NodeEvent(time=8.0, kind="node_down", node=0),
        NodeEvent(time=20.0, kind="node_up", node=0),
        NodeEvent(time=30.0, kind="node_down", node=2),
        NodeEvent(time=44.0, kind="node_up", node=2),
    ]
    mk = lambda: JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=4, preemption="kube",
        retry_buffer=64,
    )
    full = mk().replay(node_events=evs)
    assert full.evictions > 0
    ck = str(tmp_path / "chaos.npz")
    mk().replay(node_events=evs, checkpoint_path=ck, checkpoint_every=2)
    resumed = mk().replay(node_events=evs, checkpoint_path=ck, resume=True)
    np.testing.assert_array_equal(full.assignments, resumed.assignments)
    assert resumed.evictions == full.evictions
    assert resumed.evict_rescheduled == full.evict_rescheduled
    assert resumed.evict_latency_mean == full.evict_latency_mean
    changed = evs[:-1] + [NodeEvent(time=45.0, kind="node_down", node=2)]
    with pytest.raises(ValueError, match="different node_events"):
        mk().replay(node_events=changed, checkpoint_path=ck, resume=True)
    with pytest.raises(ValueError, match="different node_events"):
        mk().replay(checkpoint_path=ck, resume=True)


def test_whatif_per_scenario_timelines(tmp_path):
    """The batch engine runs one timed timeline per scenario: a scenario
    carrying the single-replay's events bit-matches that replay, and
    scenarios differing ONLY in failure timing produce differing
    disruption metrics."""
    ec, ep = _light_trace()
    cfg = FIT_ONLY()
    ev_late = [NodeEvent(time=25.0, kind="node_down", node=0)]
    single = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay(node_events=EVS)
    eng = WhatIfEngine(
        ec, ep,
        [Scenario(), Scenario(events=EVS), Scenario(events=ev_late)],
        cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64, collect_assignments=True,
    )
    res = eng.run()
    np.testing.assert_array_equal(res.assignments[1], single.assignments)
    assert int(res.evictions[0]) == 0  # clean reference scenario
    assert int(res.evictions[1]) == single.evictions
    assert int(res.evict_rescheduled[1]) == single.evict_rescheduled
    assert int(res.evict_stranded[1]) == single.evict_stranded
    assert float(res.evict_latency_mean[1]) == single.evict_latency_mean
    # timing-only difference → different disruption
    assert int(res.evictions[2]) != int(res.evictions[1])
    # engine reuse: the mutated alloc stacks were restored
    res2 = eng.run()
    np.testing.assert_array_equal(res.assignments[1], res2.assignments[1])
    np.testing.assert_array_equal(res.evictions, res2.evictions)


def test_whatif_timeline_guards():
    ec, ep = _light_trace(num_pods=4, num_nodes=2)
    with pytest.raises(ValueError, match="kube"):
        WhatIfEngine(
            ec, ep, [Scenario(events=EVS)], FIT_ONLY(), wave_width=1,
            chunk_waves=1,
        )
    with pytest.raises(ValueError, match="scenario 1"):
        WhatIfEngine(
            ec, ep,
            [Scenario(),
             Scenario(events=[NodeEvent(time=1.0, kind="node_down",
                                        node=99)])],
            FIT_ONLY(), wave_width=1, chunk_waves=1, preemption="kube",
            retry_buffer=8,
        )


def test_validation_actionable_on_every_engine():
    """Malformed timelines raise up front — same messages on the CPU and
    device engines, before any scheduling work happens."""
    ec, ep = _light_trace(num_pods=4, num_nodes=2)
    bad = {
        "unknown kind": [NodeEvent(time=1.0, kind="node_reboot", node=0)],
        "out of range": [NodeEvent(time=1.0, kind="node_down", node=7)],
        "must be sorted": [
            NodeEvent(time=5.0, kind="node_down", node=0),
            NodeEvent(time=1.0, kind="node_down", node=1),
        ],
        "finite value": [NodeEvent(time=-2.0, kind="node_down", node=0)],
        "without a prior node_down": [
            NodeEvent(time=1.0, kind="node_up", node=0)
        ],
    }
    dev = JaxReplayEngine(ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1)
    for pat, evs in bad.items():
        with pytest.raises(ValueError, match=pat):
            validate_node_events(evs, ec.num_nodes)
        with pytest.raises(ValueError, match=pat):
            CpuReplayEngine(ec, ep, FIT_ONLY()).replay(node_events=evs)
        with pytest.raises(ValueError, match=pat):
            dev.replay(node_events=evs)


def test_chaos_timeline_generator():
    """Seeded, sorted, validation-clean, MTBF/MTTR-shaped; mttr=0 keeps
    nodes down; max_events truncation never strands a node_up."""
    evs = make_chaos_timeline(50, seed=3, horizon=100.0, mtbf=40.0,
                              mttr=10.0, node_fraction=0.3)
    assert evs and evs == make_chaos_timeline(
        50, seed=3, horizon=100.0, mtbf=40.0, mttr=10.0, node_fraction=0.3
    )
    times = [e.time for e in evs]
    assert times == sorted(times) and times[-1] < 100.0
    assert validate_node_events(evs, 50) is evs
    pure_fail = make_chaos_timeline(50, seed=3, horizon=100.0, mtbf=20.0,
                                    mttr=0.0, node_fraction=0.5)
    assert pure_fail and all(e.kind == "node_down" for e in pure_fail)
    capped = make_chaos_timeline(50, seed=3, horizon=400.0, mtbf=30.0,
                                 mttr=10.0, node_fraction=1.0, max_events=9)
    assert len(capped) <= 9
    validate_node_events(capped, 50)
    with pytest.raises(ValueError, match="mtbf"):
        make_chaos_timeline(10, mtbf=0.0)


@pytest.mark.fuzz_quick
def test_seeded_chaos_slice():
    """Default-gate randomized chaos evidence: three seeded queue-trivial
    traces at ONE compile shape (same pod/node counts — only arrivals,
    durations and the seeded timeline vary) must hold CPU-vs-device
    eviction parity bit-for-bit."""
    cfg = FIT_ONLY()
    total = 0
    for seed in (1, 2, 3):
        ec, ep = _light_trace(num_pods=28, num_nodes=6, seed=seed)
        # mttr=0 (nodes stay down) keeps the comparison in the envelope:
        # a down→up pair landing between two arrivals would let the
        # device retry pass see the recovered node that the CPU rebind
        # (at the event instant) could not.
        evs = make_chaos_timeline(
            ec.num_nodes, seed=seed, horizon=float(ep.arrival.max()),
            mtbf=12.0, mttr=0.0, node_fraction=0.34,
        )
        cpu = CpuReplayEngine(ec, ep, cfg).replay(node_events=evs)
        dev = JaxReplayEngine(
            ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
            retry_buffer=64,
        ).replay(node_events=evs)
        np.testing.assert_array_equal(cpu.assignments, dev.assignments)
        assert dev.evictions == cpu.evictions, f"seed {seed}"
        assert dev.evict_rescheduled == cpu.evict_rescheduled, f"seed {seed}"
        total += dev.evictions
    assert total > 0  # non-vacuous across the slice


def test_cli_chaos_envelope_warning(caplog):
    """Config-validation-time envelope guard: chaos events beyond the
    trace's last arrival warn loudly — device engines replay no chunks
    past the final wave, so those events could only ever fire on the CPU
    engine (usually a mis-set chaos.horizon)."""
    import logging

    from kubernetes_simulator_tpu.cli import _chaos_timeline
    from kubernetes_simulator_tpu.utils.config import SimConfig

    ec, ep = _light_trace(num_pods=28, num_nodes=5)  # last arrival t=27
    cfg = SimConfig.from_dict({
        "chaos": {"horizon": 1000.0, "mtbf": 50.0, "mttr": 10.0,
                  "nodeFraction": 1.0},
    })
    with caplog.at_level(logging.WARNING, logger="k8sim"):
        events = _chaos_timeline(cfg, ec, ep, seed=0)
    assert any(e.time > 27.0 for e in events)
    assert "beyond the trace's last arrival" in caplog.text
    # Default horizon (None -> last arrival) stays inside the envelope.
    caplog.clear()
    cfg = SimConfig.from_dict({
        "chaos": {"mtbf": 5.0, "mttr": 2.0, "nodeFraction": 1.0},
    })
    with caplog.at_level(logging.WARNING, logger="k8sim"):
        events = _chaos_timeline(cfg, ec, ep, seed=0)
    assert events and all(e.time <= 27.0 for e in events)
    assert "beyond the trace's last arrival" not in caplog.text
