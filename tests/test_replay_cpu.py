"""CPU replay engine: config #1 baseline shape, determinism, gangs,
preemption, completions, failure injection (SURVEY.md §4.3, §4.6, §5)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.framework.registry import get_strategy
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine, NodeEvent
from kubernetes_simulator_tpu.sim.synthetic import config1, make_cluster, make_workload


def run(cluster, pods, plugins=None, **kw):
    ec, ep = encode(cluster, pods)
    eng = CpuReplayEngine(ec, ep, FrameworkConfig(plugins=plugins), **kw)
    return eng.replay(), ec, ep


def test_config1_places_everything():
    cluster, pods, plugins = config1(num_nodes=50, num_pods=300)
    res, ec, ep = run(cluster, pods, plugins)
    assert res.placed == 300
    assert res.unschedulable == 0
    assert res.placements_per_sec > 0


def test_determinism_same_seed_same_placements():
    cluster, pods, plugins = config1(num_nodes=30, num_pods=200)
    res1, _, _ = run(cluster, pods, plugins)
    cluster2, pods2, _ = config1(num_nodes=30, num_pods=200)
    res2, _, _ = run(cluster2, pods2, plugins)
    assert (res1.assignments == res2.assignments).all()


def test_full_plugin_set_runs():
    cluster = make_cluster(30, seed=1, taint_fraction=0.2)
    pods, _ = make_workload(150, seed=1, with_affinity=True, with_spread=True,
                            with_tolerations=True)
    res, ec, ep = run(cluster, pods)
    assert res.placed + res.unschedulable == 150
    assert res.placed > 100


def test_registry_selects_cpu():
    factory = get_strategy("cpu")
    cluster, pods, plugins = config1(num_nodes=10, num_pods=20)
    ec, ep = encode(cluster, pods)
    eng = factory(ec, ep, FrameworkConfig(plugins=plugins))
    assert eng.replay().placed == 20


def test_completions_free_resources():
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("a", requests={"cpu": 2}, arrival_time=0.0, duration=10.0),
        Pod("b", requests={"cpu": 2}, arrival_time=1.0),
    ]
    res, _, _ = run(cluster, pods)
    # b can't fit until a finishes at t=10, then must be placed.
    assert res.placed == 2
    assert res.virtual_makespan >= 10.0


def test_gang_all_or_nothing():
    # Gang of 3 needs 3 cpu total but cluster has 2 → nothing placed.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod(f"g{i}", requests={"cpu": 1}, arrival_time=float(i), pod_group="gang")
        for i in range(3)
    ]
    res, ec, ep = run(cluster, pods)
    assert res.placed == 0
    assert (res.assignments == PAD).all()
    # State must be fully rolled back (SURVEY.md §7 hard part #3).
    assert np.allclose(res.state.used, 0.0)


def test_gang_commits_when_feasible():
    cluster = Cluster(nodes=[Node("n0", {"cpu": 4})])
    pods = [
        Pod(f"g{i}", requests={"cpu": 1}, arrival_time=float(i), pod_group="gang")
        for i in range(3)
    ]
    res, _, _ = run(cluster, pods)
    assert res.placed == 3


def test_preemption_evicts_lower_priority():
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("low", requests={"cpu": 2}, priority=0, arrival_time=0.0),
        Pod("high", requests={"cpu": 2}, priority=1000, arrival_time=1.0),
    ]
    res, _, ep = run(cluster, pods)
    assert res.preemptions == 1
    assert res.assignments[1] == 0  # high ends up on the node
    # low was evicted and can never fit again → unschedulable.
    assert res.assignments[0] == PAD


def test_node_down_evicts_and_requeues():
    cluster = Cluster(nodes=[Node("n0", {"cpu": 4}), Node("n1", {"cpu": 4})])
    pods = [Pod("a", requests={"cpu": 2}, arrival_time=0.0)]
    ec, ep = encode(cluster, pods)
    eng = CpuReplayEngine(ec, ep, FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}]))
    first = eng.replay().assignments[0]
    ev = [NodeEvent(time=5.0, kind="node_down", node=int(first))]
    eng2 = CpuReplayEngine(ec, ep, FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}]))
    res = eng2.replay(node_events=ev)
    # Pod must end up on the surviving node.
    assert res.assignments[0] == 1 - int(first)


def test_priority_order_in_queue():
    # Two pods arrive simultaneously; capacity 1 → high priority wins.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("low", requests={"cpu": 1}, priority=0, arrival_time=0.0),
        Pod("high", requests={"cpu": 1}, priority=100, arrival_time=0.0),
    ]
    ec, ep = encode(cluster, pods)
    eng = CpuReplayEngine(
        ec, ep, FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}], enable_preemption=False)
    )
    res = eng.replay()
    assert res.assignments[1] == 0
    assert res.assignments[0] == PAD


def test_backoff_delays_retry_changing_outcome():
    # [K8S] backoff semantics (SURVEY.md §2 L3): pod a fails at t=0 (its
    # affinity target is absent) and starts a 1s backoff; when b's binding
    # at t=0.5 flushes the unschedulable set, a goes to the backoff queue —
    # not straight to active — so c (arriving t=0.9) takes the last cpu
    # before a's retry at t=1.0. Without backoff routing, a would retry at
    # t=0.5 and win the slot instead of c.
    from kubernetes_simulator_tpu.models.core import (
        LabelSelector,
        PodAffinitySpec,
        PodAffinityTerm,
    )

    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    aff = PodAffinitySpec(
        required=(
            PodAffinityTerm(LabelSelector.make({"app": "b"}), "kubernetes.io/hostname"),
        )
    )
    pods = [
        Pod("a", labels={"app": "a"}, requests={"cpu": 1}, arrival_time=0.0,
            pod_affinity=aff),
        Pod("b", labels={"app": "b"}, requests={"cpu": 1}, arrival_time=0.5),
        Pod("c", requests={"cpu": 1}, arrival_time=0.9),
    ]
    res, _, _ = run(cluster, pods)
    assert res.assignments[1] == 0 and res.assignments[2] == 0
    assert res.assignments[0] == PAD
    assert res.placed == 2 and res.unschedulable == 1


def test_gang_no_progress_terminates():
    # A gang that can never complete must not spin the virtual clock: the
    # first rollback retries members through backoff, the second (with no
    # committed cluster progress in between) parks them for good.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod(f"g{i}", requests={"cpu": 1}, arrival_time=0.0, pod_group="gang")
        for i in range(2)
    ]
    res, _, _ = run(cluster, pods, permit_timeout=50.0)
    assert res.placed == 0
    assert np.allclose(res.state.used, 0.0)
    assert res.virtual_makespan < 1000.0


def test_gang_members_do_not_preempt():
    # Speculative gang reserves must be cheaply revertible, so PostFilter
    # preemption is disabled for gang members: a gang that only fits by
    # evicting a victim does not place, and the victim stays bound.
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("victim", requests={"cpu": 1}, priority=0, arrival_time=0.0),
        Pod("ga", requests={"cpu": 1}, priority=1000, arrival_time=1.0,
            pod_group="gang"),
        Pod("gb", requests={"cpu": 1}, priority=1000, arrival_time=1.0,
            pod_group="gang"),
    ]
    res, _, _ = run(cluster, pods)
    assert res.assignments[0] == 0  # victim still on the node
    assert res.preemptions == 0
    assert res.placed == 1


@pytest.mark.slow
def test_preemption_at_scale_within_budget():
    # 5k nodes fully packed with low-priority pods; 400 high-priority pods
    # must each preempt. The incremental PostFilter (static filters hoisted,
    # node-local O(R) fit in the victim loop, state-free confirm) keeps
    # this within budget — the old full-mask recompute was pathological
    # at this size (VERDICT round-1 weak #4).
    import time

    from kubernetes_simulator_tpu.models.core import Cluster as _Cluster

    n_nodes = 5000
    cluster = _Cluster(
        nodes=[Node(f"n{i}", {"cpu": 2}) for i in range(n_nodes)]
    )
    pods = [
        Pod(f"low{i}", requests={"cpu": 2}, priority=0,
            arrival_time=float(i) * 1e-3)
        for i in range(n_nodes)
    ] + [
        Pod(f"hi{i}", requests={"cpu": 2}, priority=1000,
            arrival_time=10.0 + i * 1e-3)
        for i in range(400)
    ]
    ec, ep = encode(cluster, pods)
    eng = CpuReplayEngine(
        ec, ep, FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    )
    t0 = time.perf_counter()
    res = eng.replay()
    wall = time.perf_counter() - t0
    assert res.preemptions == 400
    # every high pod placed, each displacing one low pod
    assert (res.assignments[n_nodes:] >= 0).all()
    assert wall < 60.0, f"preemption-heavy 5k replay took {wall:.1f}s"
