"""Device-path unschedulable RETRY at release boundaries (round 4;
SURVEY.md §2 L3 — the [K8S] activeQ flush-on-event analogue for the
arrival-order device engine). Anchor = greedy_replay(retry_buffer=...);
the device twin is WhatIfEngine(retry_buffer=...)'s bounded boundary
retry pass."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine


def test_retry_places_after_release_tiny():
    # b fails while a holds the only cpu; a's completion frees it at a
    # boundary and the retry pass places b. Without retry b stays
    # unscheduled forever (the r01-r03 device semantics).
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("a", requests={"cpu": 1}, arrival_time=0.0, duration=3.0),
        Pod("b", requests={"cpu": 1}, arrival_time=1.0),
        Pod("f1", requests={}, arrival_time=6.0),
        Pod("f2", requests={}, arrival_time=8.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=1, completions_chunk_waves=1, retry_buffer=1
    )
    assert anchor.assignments[1] == 0  # b placed on retry
    assert anchor.placed == 4
    eng = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=1, chunk_waves=1,
        retry_buffer=1,
    )
    res = eng.run()
    assert int(res.placed[0]) == anchor.placed
    no_retry = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=1, chunk_waves=1
    ).run()
    assert int(no_retry.placed[0]) == 3  # b permanently missed


def test_retry_parity_random_contended():
    """Contended workload (tight capacity, short durations): device placed
    counts must equal the anchor's, scenario by scenario, and retry must
    place strictly more than no-retry (non-vacuous)."""
    cluster = make_cluster(3, seed=11)
    pods, _ = make_workload(
        120, seed=11, arrival_rate=60.0, duration_mean=1.5,
        with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    W, C, RB = 4, 4, 8
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=W, completions_chunk_waves=C,
        retry_buffer=RB,
    )
    eng = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=W, chunk_waves=C,
        retry_buffer=RB,
    )
    assert eng._completions_dev
    res = eng.run()
    assert int(res.placed[0]) == anchor.placed
    no_retry = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=W, chunk_waves=C
    ).run()
    assert anchor.placed > int(no_retry.placed[0])
    # The anchor's retried pods really are late placements, not arrivals.
    base = greedy_replay(
        ec, ep, cfg, wave_width=W, completions_chunk_waves=C
    )
    retried = (anchor.assignments >= 0) & (base.assignments == PAD)
    assert retried.any()


def test_retry_buffer_overflow_drops_newest():
    """With a 1-slot buffer only the FIRST failed pod retries; the rest
    stay permanently unscheduled — device and anchor agree."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("a", requests={"cpu": 1}, arrival_time=0.0, duration=2.0),
        Pod("b", requests={"cpu": 1}, arrival_time=0.5, duration=100.0),
        Pod("c", requests={"cpu": 1}, arrival_time=0.6, duration=100.0),
        Pod("f1", requests={}, arrival_time=5.0),
        Pod("f2", requests={}, arrival_time=8.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=1, completions_chunk_waves=1, retry_buffer=1
    )
    # b took the only buffer slot; c was dropped.
    assert anchor.assignments[1] == 0 and anchor.assignments[2] == PAD
    eng = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=1, chunk_waves=1,
        retry_buffer=1,
    )
    res = eng.run()
    assert int(res.placed[0]) == anchor.placed == 4
    # Round 6: the device retry path reports its FIFO-capacity drops on
    # the result, matching the host anchor's count (c overflowed).
    assert res.retry_dropped is not None
    assert int(res.retry_dropped[0]) == anchor.retry_dropped == 1


def test_retry_placed_pod_releases_later():
    """A pod placed on retry starts AT the boundary and must itself
    release t_b + duration later, freeing capacity for a third pod —
    pinned against the anchor's pending-release bookkeeping."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 1})])
    pods = [
        Pod("a", requests={"cpu": 1}, arrival_time=0.0, duration=2.0),
        Pod("b", requests={"cpu": 1}, arrival_time=0.5, duration=1.0),
        Pod("f1", requests={}, arrival_time=4.0),
        Pod("f2", requests={}, arrival_time=6.0),
        # b retried ~t=4, releases by t=6+; c then fits via retry too.
        Pod("c", requests={"cpu": 1}, arrival_time=5.0),
        Pod("f3", requests={}, arrival_time=8.0),
        Pod("f4", requests={}, arrival_time=10.0),
        Pod("f5", requests={}, arrival_time=12.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=1, completions_chunk_waves=1, retry_buffer=2
    )
    assert anchor.assignments[1] == 0 and anchor.assignments[4] == 0
    eng = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=1, chunk_waves=1,
        retry_buffer=2,
    )
    res = eng.run()
    assert int(res.placed[0]) == anchor.placed


def test_retry_requires_device_release_path():
    cluster = make_cluster(4, seed=0)
    pods, _ = make_workload(16, seed=0)  # no durations
    ec, ep = encode(cluster, pods)
    with pytest.raises(ValueError, match="retry_buffer requires"):
        WhatIfEngine(
            ec, ep, [Scenario()], FrameworkConfig(), retry_buffer=8
        )


def test_retry_gang_pods_excluded():
    """Gang pods never enter the retry buffer (all-or-nothing groups
    cannot re-commit individually) — device and anchor agree."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("a", requests={"cpu": 2}, arrival_time=0.0, duration=2.0),
        Pod("g0", requests={"cpu": 1}, arrival_time=0.5, pod_group="g"),
        Pod("g1", requests={"cpu": 1}, arrival_time=0.5, pod_group="g"),
        Pod("s", requests={"cpu": 1}, arrival_time=0.7),
        Pod("f1", requests={}, arrival_time=5.0),
        Pod("f2", requests={}, arrival_time=8.0),
        Pod("f3", requests={}, arrival_time=10.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=2, completions_chunk_waves=1, retry_buffer=2
    )
    # s retried and placed; the gang stays unplaced (never buffered).
    assert anchor.assignments[3] == 0
    assert anchor.assignments[1] == PAD and anchor.assignments[2] == PAD
    eng = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=2, chunk_waves=1,
        retry_buffer=2,
    )
    res = eng.run()
    assert int(res.placed[0]) == anchor.placed


def test_retry_multi_scenario_counts():
    """Perturbed scenarios run the same retry machinery per scenario;
    scenario 0 equals the anchor and a capacity-halved scenario places
    no more than the base."""
    from kubernetes_simulator_tpu.sim.whatif import Perturbation

    cluster = make_cluster(6, seed=13)
    pods, _ = make_workload(
        100, seed=13, arrival_rate=25.0, duration_mean=1.2,
        with_spread=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = [
        Scenario(),
        Scenario([
            Perturbation(
                "scale_capacity", nodes=np.arange(3), resource="cpu",
                factor=0.5,
            )
        ]),
    ]
    eng = WhatIfEngine(
        ec, ep, scen, cfg, wave_width=4, chunk_waves=4, retry_buffer=8
    )
    res = eng.run()
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=4, completions_chunk_waves=4, retry_buffer=8
    )
    assert int(res.placed[0]) == anchor.placed
    assert int(res.placed[1]) <= int(res.placed[0])


@pytest.mark.slow
def test_retry_full_plugin_envelope_parity():
    """Round 4 widening: retry works on traces WITH anti/pref count
    planes, multi-topology spread and singleton host rows — the pend
    release rides the same commit-block core as the static lists.
    Device placed counts == anchor, and retry matters."""
    cluster = make_cluster(3, seed=23)
    pods, _ = make_workload(
        150, seed=23, arrival_rate=60.0, duration_mean=1.5,
        with_affinity=True, with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    W, C, RB = 4, 4, 8
    eng = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=W, chunk_waves=C,
        retry_buffer=RB,
    )
    assert eng.static3.maintain_anti or eng.static3.maintain_pref
    assert eng.static3.has_host_rows or not eng.static3.single_topo
    res = eng.run()
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=W, completions_chunk_waves=C,
        retry_buffer=RB,
    )
    assert int(res.placed[0]) == anchor.placed
    no_retry = greedy_replay(
        ec, ep, cfg, wave_width=W, completions_chunk_waves=C
    )
    assert anchor.placed > no_retry.placed  # non-vacuous


def test_single_replay_engine_retry_matches_greedy():
    """Round 5 (VERDICT r4 next #3): retry_buffer on JaxReplayEngine —
    the config-4 CLI path can re-attempt failed pods. Host boundary pass
    (sim.boundary), bit-identical to greedy_replay(retry_buffer=...)."""
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine

    cluster = make_cluster(3, seed=11)
    pods, _ = make_workload(
        120, seed=11, arrival_rate=60.0, duration_mean=1.5,
        with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    anchor = greedy_replay(
        ec, ep, cfg, wave_width=4, completions_chunk_waves=4, retry_buffer=8
    )
    eng = JaxReplayEngine(
        ec, ep, cfg, wave_width=4, chunk_waves=4, retry_buffer=8
    ).replay()
    np.testing.assert_array_equal(anchor.assignments, eng.assignments)
    assert eng.placed == anchor.placed
    assert eng.retry_dropped == anchor.retry_dropped
    # Non-vacuous: retry places strictly more than the no-retry engine.
    no_retry = JaxReplayEngine(ec, ep, cfg, wave_width=4, chunk_waves=4).replay()
    assert eng.placed > no_retry.placed


@pytest.mark.slow
def test_single_replay_retry_borg_scale():
    """Borg-shaped mid-size trace through the config-4 path: retry places
    >= the no-retry count and parity with the anchor holds end-to-end."""
    from kubernetes_simulator_tpu.sim.borg import BorgSpec, make_borg_encoded
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.utils.config import BorgWorkloadSpec

    spec = BorgSpec.from_spec(BorgWorkloadSpec(nodes=400, tasks=20_000, seed=3))
    ec, ep, _ = make_borg_encoded(spec)
    cfg = FrameworkConfig()
    eng = JaxReplayEngine(
        ec, ep, cfg, chunk_waves=64, retry_buffer=256
    ).replay()
    anchor = greedy_replay(
        ec, ep, cfg, completions_chunk_waves=64, retry_buffer=256
    )
    np.testing.assert_array_equal(anchor.assignments, eng.assignments)
    no_retry = JaxReplayEngine(ec, ep, cfg, chunk_waves=64).replay()
    assert eng.placed >= no_retry.placed


def test_single_replay_retry_sees_node_events():
    """Boundary mode mirrors node events into the HOST cluster view (the
    retry pass must not place onto a downed node): n0 goes down before
    the blocked pod's retry; the retry lands on n1 instead, and the
    cluster's allocatable is restored after the run."""
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent

    cluster = Cluster(nodes=[Node("n0", {"cpu": 2}), Node("n1", {"cpu": 1})])
    pods = [
        # filler holds BOTH nodes so b must wait in the buffer.
        Pod("f0", requests={"cpu": 2}, arrival_time=0.0, duration=3.0),
        Pod("f1", requests={"cpu": 1}, arrival_time=0.0, duration=3.0),
        Pod("b", requests={"cpu": 1}, arrival_time=1.0),
        Pod("t1", requests={}, arrival_time=6.0),
        Pod("t2", requests={}, arrival_time=7.0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    saved = ec.allocatable.copy()
    res = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, retry_buffer=4
    ).replay(node_events=[NodeEvent(time=5.0, kind="node_down", node=0)])
    # b retried after the fillers released; n0 was down by then -> n1.
    assert res.assignments[2] == 1
    np.testing.assert_array_equal(ec.allocatable, saved)  # restored
    # Without the event, LeastAllocated prefers the emptier n0.
    res2 = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, retry_buffer=4
    ).replay()
    assert res2.assignments[2] == 0


def test_host_and_device_retry_paths_agree():
    """The single-replay HOST retry pass (sim.boundary) and the what-if
    DEVICE retry pass (the in-program boundary step) both anchor to
    greedy — pin their agreement with each other directly."""
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine

    cluster = make_cluster(3, seed=11)
    pods, _ = make_workload(
        120, seed=11, arrival_rate=60.0, duration_mean=1.5,
        with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    host = JaxReplayEngine(
        ec, ep, cfg, wave_width=4, chunk_waves=4, retry_buffer=8
    ).replay()
    dev = WhatIfEngine(
        ec, ep, [Scenario()], cfg, wave_width=4, chunk_waves=4,
        retry_buffer=8,
    ).run()
    assert int(dev.placed[0]) == host.placed
