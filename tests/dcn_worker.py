"""Worker for the multi-process DCN tests (SURVEY §5 distributed
backend): launched as one of DCN_NPROC subprocesses with 8//DCN_NPROC
virtual CPU devices each, joins the jax.distributed coordinator, runs a
tiny mesh-sharded what-if over the 8 GLOBAL devices, and prints
per-scenario placed counts as one JSON line.

Env (set by the parent test): DCN_COORD, DCN_NPROC, DCN_PID. Platform env
(JAX_PLATFORMS=cpu, --xla_force_host_platform_device_count=…) must be set
BEFORE jax import — the parent passes it through the environment, not
this module.
"""

import json
import os
import sys


def main() -> None:
    import jax

    # Persistent compile cache like the parent suite (conftest enables it
    # process-locally, which subprocesses would otherwise miss — their
    # from-scratch compiles are what the communicate() timeout guards).
    from kubernetes_simulator_tpu.utils.compile_cache import enable as _cc

    _cc()
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

    from kubernetes_simulator_tpu.parallel.mesh import init_distributed, make_mesh

    init_distributed(
        coordinator_address=os.environ["DCN_COORD"],
        num_processes=int(os.environ["DCN_NPROC"]),
        process_id=int(os.environ["DCN_PID"]),
    )
    nproc = int(os.environ["DCN_NPROC"])
    assert jax.process_count() == nproc
    assert jax.device_count() == 8, jax.devices()
    assert len(jax.local_devices()) == 8 // nproc

    import numpy as np

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

    cluster = make_cluster(12, seed=21, taint_fraction=0.2)
    pods, _ = make_workload(
        48, seed=21, with_affinity=True, with_spread=True, with_tolerations=True
    )
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, 8, seed=21, p_capacity=0.5, p_taint=0.3)
    mesh = make_mesh()  # 8 global devices across the processes
    res = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), mesh=mesh, chunk_waves=4
    ).run()
    print("DCN_RESULT " + json.dumps(res.placed.tolist()), flush=True)


if __name__ == "__main__":
    sys.exit(main())
