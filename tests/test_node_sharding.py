"""Round 14: intra-scenario node-plane sharding + paged pod waves.

The contract under test: ``node_shards`` and ``paged`` are pure
memory/latency knobs — placements, JSONL rows, and checkpoint blobs are
BIT-IDENTICAL across node_shards ∈ {1, 2, 4} and paged on/off. (The CPU
greedy-oracle link is transitive: sharded ≡ replicated here, replicated
≡ oracle in tests/test_oracle_parity.py.) Runs on the virtual 8-device
CPU mesh (conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8).

Also here: the paged-mode gang guard in pack_waves, the
KSIM_MAX_REPLICATED_BYTES refusal gate, the knob-combination validation
raises, and byte-parity for the round-14 DCN gather payload compression
(delta+zlib with raw-zlib overflow fallback).
"""

import hashlib
import json

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import (
    JaxReplayEngine,
    replicated_resident_bytes,
)
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


def _case(n_nodes=24, n_pods=220, seed=7):
    """Full plugin surface: taints, affinity/anti-affinity, spread,
    tolerations, gangs, finite durations (completions on)."""
    cluster = make_cluster(n_nodes, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(
        n_pods, seed=seed, with_affinity=True, with_spread=True,
        with_tolerations=True, gang_fraction=0.1, gang_size=4,
        duration_mean=40.0,
    )
    return encode(cluster, pods)


@pytest.fixture(scope="module")
def shard_results():
    """{node_shards: (engine, ReplayResult)} for the same trace."""
    ec, ep = _case()
    out = {}
    for s in (1, 2, 4):
        # telemetry="off": phase timers are wall clocks — the one field
        # family that legitimately differs across shard counts.
        eng = JaxReplayEngine(
            ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=s,
            telemetry="off",
        )
        out[s] = (eng, eng.replay())
    return out


def _stable_summary(res):
    """summary() minus the wall-clock-derived fields (the exact set the
    KSIM_DETERMINISTIC_JSONL scrub zeroes)."""
    row = dict(res.summary())
    for k in ("wall_clock_s", "placements_per_sec"):
        row.pop(k, None)
    return row


def test_shard_count_invariance(shard_results):
    _, ref = shard_results[1]
    for s in (2, 4):
        _, res = shard_results[s]
        np.testing.assert_array_equal(
            res.assignments, ref.assignments,
            err_msg=f"node_shards={s}: per-pod assignments diverged",
        )
        assert _stable_summary(res) == _stable_summary(ref), (
            f"node_shards={s}: result summary diverged"
        )


def test_jsonl_byte_identical(shard_results, tmp_path, monkeypatch):
    """The JSONL a run would emit is byte-identical across shard counts
    once wall-clock fields are scrubbed (KSIM_DETERMINISTIC_JSONL — the
    repo's standing rule: determinism lives in results, never timing)."""
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter, replay_row

    monkeypatch.setenv("KSIM_DETERMINISTIC_JSONL", "1")
    blobs = {}
    for s, (_, res) in shard_results.items():
        p = tmp_path / f"shards{s}.jsonl"
        with JsonlWriter(str(p)) as w:
            w.write(replay_row("replay-jax", res))
        blobs[s] = p.read_bytes()
        json.loads(blobs[s].splitlines()[-1])  # still valid JSONL
    assert blobs[1] == blobs[2] == blobs[4]


def test_checkpoint_blobs_identical_and_cross_resume(shard_results, tmp_path):
    """Checkpoints are written in HOST layout (sharded state is
    unsharded and sliced back to the real node count first), so the
    blob on disk is byte-identical across shard counts — and a
    replicated checkpoint resumes under a sharded engine."""
    eng1, ref = shard_results[1]
    eng4, _ = shard_results[4]
    digests = {}
    for s, eng in ((1, eng1), (4, eng4)):
        p = tmp_path / f"ckpt{s}.npz"
        res = eng.replay(checkpoint_path=str(p), checkpoint_every=2)
        np.testing.assert_array_equal(res.assignments, ref.assignments)
        digests[s] = hashlib.sha256(p.read_bytes()).hexdigest()
    assert digests[1] == digests[4], (
        "checkpoint blob differs between replicated and node-sharded "
        "engines — the sharded path is leaking device layout to disk"
    )
    # Replicated-written blob, sharded resume: identical end state.
    res = eng4.replay(checkpoint_path=str(tmp_path / "ckpt1.npz"), resume=True)
    np.testing.assert_array_equal(res.assignments, ref.assignments)


def test_paged_parity(shard_results):
    """Paged pod waves change residency, not results: paged ≡ unpaged on
    the replicated engine, and paged+sharded ≡ replicated."""
    ec, ep = _case()
    _, ref = shard_results[1]
    for shards in (1, 4):
        eng = JaxReplayEngine(
            ec, ep, FrameworkConfig(), chunk_waves=4,
            node_shards=shards, paged=True, telemetry="off",
        )
        res = eng.replay()
        np.testing.assert_array_equal(
            res.assignments, ref.assignments,
            err_msg=f"paged (node_shards={shards}): assignments diverged",
        )
        assert _stable_summary(res) == _stable_summary(ref)


def test_pack_waves_rejects_page_smaller_than_gang():
    """Satellite bugfix: a page smaller than the largest gang would
    split the gang across page evictions — refuse up front, actionably."""
    from kubernetes_simulator_tpu.sim.waves import pack_waves

    _, ep = _case(n_pods=64)
    pods, _ = make_workload(
        64, seed=7, gang_fraction=0.5, gang_size=8,
    )
    _, ep = encode(make_cluster(8, seed=7), pods)
    with pytest.raises(ValueError, match="largest gang"):
        pack_waves(ep, 8, page_pods=4)
    # Page >= largest gang: packs fine.
    assert pack_waves(ep, 8, page_pods=8).idx.shape[1] == 8


def test_replicated_refusal_gate(monkeypatch):
    """KSIM_MAX_REPLICATED_BYTES refuses the replicated path past the
    budget (pointing at node_shards/paged); the sharded engine
    constructs under the same budget."""
    ec, ep = _case(n_pods=64)
    assert replicated_resident_bytes(ec, ep) > 1000
    monkeypatch.setenv("KSIM_MAX_REPLICATED_BYTES", "1000")
    with pytest.raises(ValueError, match="KSIM_MAX_REPLICATED_BYTES"):
        JaxReplayEngine(ec, ep, FrameworkConfig())
    eng = JaxReplayEngine(ec, ep, FrameworkConfig(), node_shards=2)
    assert eng.node_shards == 2


def test_knob_combination_raises():
    ec, ep = _case(n_pods=64)
    with pytest.raises(ValueError, match="tier preemption"):
        JaxReplayEngine(
            ec, ep, FrameworkConfig(), node_shards=2, preemption="tier"
        )
    with pytest.raises(ValueError, match="paged=True is not supported"):
        JaxReplayEngine(ec, ep, FrameworkConfig(), paged=True, retry_buffer=8)


def test_whatif_rejects_node_shards():
    from kubernetes_simulator_tpu.sim.whatif import (
        WhatIfEngine,
        uniform_scenarios,
    )

    ec, ep = _case(n_pods=64)
    scen = uniform_scenarios(ec, 2, seed=0)
    with pytest.raises(NotImplementedError, match="node_shards"):
        WhatIfEngine(ec, ep, scen, FrameworkConfig(), node_shards=2)


# ── DCN gather payload compression (round-14 satellite) ──────────────


def _roundtrip(payload):
    from kubernetes_simulator_tpu.parallel.dcn import (
        _pack_leaf,
        _unpack_leaf,
        _walk_payload,
    )

    packed = _walk_payload(payload, _pack_leaf)
    return packed, _walk_payload(packed, _unpack_leaf)


def test_dcn_compression_byte_parity():
    from kubernetes_simulator_tpu.parallel.dcn import _PackedArray

    rng = np.random.default_rng(0)
    payload = {
        "assignments": rng.integers(-1, 500, size=(4, 4096), dtype=np.int32),
        "placed": rng.integers(0, 4096, size=(4,), dtype=np.int64),
        "util": rng.random((4,), dtype=np.float32),
        "nested": [np.arange(2048, dtype=np.int64), None],
        "tiny": np.arange(8, dtype=np.int32),  # below the size floor
    }
    packed, out = _roundtrip(payload)
    # The large int planes actually took the packed path...
    assert isinstance(packed["assignments"], _PackedArray)
    assert packed["assignments"].codec == "delta-zlib"
    # ...small/float leaves pass through untouched...
    assert packed["util"] is payload["util"]
    assert packed["tiny"] is payload["tiny"]
    # ...and the decode is byte-exact, dtype and shape included.
    for k in ("assignments", "placed", "util", "tiny"):
        assert out[k].dtype == payload[k].dtype
        np.testing.assert_array_equal(out[k], payload[k])
    np.testing.assert_array_equal(out["nested"][0], payload["nested"][0])
    assert out["nested"][1] is None


def test_dcn_compression_delta_overflow_fallback():
    """int64 values whose DELTAS fit int32 use the delta codec even when
    the values don't; deltas past int32 fall back to raw zlib — both
    byte-exact."""
    from kubernetes_simulator_tpu.parallel.dcn import _PackedArray

    # Monotone int64 whose VALUES overflow int32 but whose deltas (the
    # first delta is the first value — prepend 0) all fit -> delta-zlib.
    big_sorted = np.cumsum(np.full(4096, 1 << 20, dtype=np.int64))
    assert big_sorted.max() > np.iinfo(np.int32).max
    # Alternating extremes: deltas overflow int32 -> raw zlib fallback.
    extremes = np.empty(4096, dtype=np.int64)
    extremes[0::2], extremes[1::2] = np.iinfo(np.int64).min // 2, \
        np.iinfo(np.int64).max // 2
    packed, out = _roundtrip({"a": big_sorted, "b": extremes})
    assert isinstance(packed["a"], _PackedArray)
    assert packed["a"].codec == "delta-zlib"
    if isinstance(packed["b"], _PackedArray):  # incompressible may pass raw
        assert packed["b"].codec == "zlib"
    np.testing.assert_array_equal(out["a"], big_sorted)
    np.testing.assert_array_equal(out["b"], extremes)
