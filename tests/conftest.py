"""Test env: force JAX onto CPU with 8 virtual devices BEFORE jax imports,
so mesh/sharding tests run without TPUs (SURVEY.md §4.4)."""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
