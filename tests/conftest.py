"""Test env: force JAX onto CPU with 8 virtual devices, so mesh/sharding
tests run without TPUs (SURVEY.md §4.4).

The axon sitecustomize pre-imports jax with JAX_PLATFORMS=axon before
pytest starts, so setting env vars here is too late for the platform choice
— use jax.config.update instead (the backend is created lazily at first
use, which happens after conftest import). XLA_FLAGS is still read at
backend-creation time, so setting it here works.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache for the suite — a no-op on the CPU
# backend since round 6: warm-cache chunk executables deserialized
# nondeterministically wrong (see utils/compile_cache.py docstring), and
# every test here runs on CPU. enable() stays so a TPU-backed run of the
# suite still gets the warm start; KSIM_COMPILE_CACHE=1 forces it on CPU.
from kubernetes_simulator_tpu.utils.compile_cache import enable as _cc

if _cc() is not None:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
