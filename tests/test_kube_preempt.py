"""Kube-EXACT minimal-victims preemption on the device path (round 5,
VERDICT r4 next #1): ``preemption="kube"`` runs upstream defaultpreemption
semantics — fewest victims, lowest max victim priority, victims chosen
lowest-priority-first, only the victims needed for THIS pod's fit, FULL
count rewind — through the chunk-boundary pass (sim.boundary). The greedy
anchor and the device engine must agree exactly; at wave_width=1 /
chunk_waves=1 placements match CpuReplayEngine(enable_preemption=True) on
queue-trivial traces; at production chunk sizes the divergence is a
measured, asserted bound."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import (
    Cluster,
    LabelSelector,
    Node,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
)
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])


def _cpu(ec, ep, plugins=None):
    return CpuReplayEngine(
        ec, ep, FrameworkConfig(plugins=plugins, enable_preemption=True)
    ).replay()


def test_minimal_victims_not_evict_all_lower():
    """THE discriminator vs tier preemption: two lower-priority pods on
    the node, the preemptor needs only one slot — kube evicts exactly the
    single lowest-priority victim; tier would evict both."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("lo0", requests={"cpu": 1}, arrival_time=0.0, priority=0),
        Pod("lo5", requests={"cpu": 1}, arrival_time=1.0, priority=5),
        Pod("hi", requests={"cpu": 1}, arrival_time=2.0, priority=100),
    ]
    ec, ep = encode(cluster, pods)
    a = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=1, preemption="kube",
        completions_chunk_waves=1, retry_buffer=8,
    )
    assert list(a.assignments) == [PAD, 0, 0]  # lo0 out, lo5 kept
    assert a.preemptions == 1
    d = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=8,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions == 1
    c = _cpu(ec, ep, plugins=[{"name": "NodeResourcesFit"}])
    np.testing.assert_array_equal(a.assignments, c.assignments)
    # Tier semantics differ here — the deviation kube mode removes.
    t = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=1, preemption="tier",
        completions_chunk_waves=1,
    )
    assert t.preemptions == 2


def test_node_ranking_fewest_then_lowest_priority():
    """Candidate ranking: n0 needs two victims, n1 one — kube picks n1
    (fewest); among equal counts the lower max victim priority wins."""
    nodes = [Node("n0", {"cpu": 2}), Node("n1", {"cpu": 2}), Node("n2", {"cpu": 2})]
    # Pre-binds make the starting layout deterministic.
    pods = [
        Pod("a0", requests={"cpu": 1}, arrival_time=0.0, priority=10, node_name="n0"),
        Pod("a1", requests={"cpu": 1}, arrival_time=0.0, priority=10, node_name="n0"),
        Pod("b0", requests={"cpu": 2}, arrival_time=0.0, priority=20, node_name="n1"),
        Pod("c0", requests={"cpu": 2}, arrival_time=0.0, priority=5, node_name="n2"),
        Pod("hi", requests={"cpu": 2}, arrival_time=4.0, priority=100),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    a = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=1, preemption="kube",
        completions_chunk_waves=1, retry_buffer=8,
    )
    # One victim each on n1 (prio 20) and n2 (prio 5): kube prefers the
    # LOWEST max victim priority -> evicts c0 on n2.
    assert a.assignments[4] == 2
    assert a.assignments[3] == PAD
    assert a.assignments[2] == 1  # b0 untouched
    assert a.preemptions == 1
    d = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=8,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)


def test_count_rewind_unblocks_anti_affinity():
    """Victim eviction rewinds count planes EXACTLY (no phantom counts):
    evicting the anti-affinity blocker both frees resources and clears
    the symmetric anti term, so the preemptor passes the full re-check.
    Under tier semantics the phantom count would keep the node masked."""
    nodes = [Node("n0", {"cpu": 2}, labels={"kubernetes.io/hostname": "n0"})]
    anti = PodAffinitySpec(
        required=(
            PodAffinityTerm(
                label_selector=LabelSelector.make({"app": "x"}),
                topology_key="kubernetes.io/hostname",
            ),
        )
    )
    pods = [
        Pod("blocker", labels={"app": "x"}, requests={"cpu": 1},
            arrival_time=0.0, priority=0),
        Pod("hi", labels={"app": "y"}, requests={"cpu": 1},
            arrival_time=1.0, priority=100, pod_anti_affinity=anti),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(
        plugins=[{"name": "NodeResourcesFit"}, {"name": "InterPodAffinity"}]
    )
    a = greedy_replay(
        ec, ep, cfg, wave_width=1, preemption="kube",
        completions_chunk_waves=1, retry_buffer=8,
    )
    assert a.assignments[0] == PAD and a.assignments[1] == 0
    assert a.preemptions == 1
    d = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=8,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    c = _cpu(ec, ep, plugins=cfg.plugins)
    np.testing.assert_array_equal(a.assignments, c.assignments)


def test_victim_requeued_and_replaced():
    """Evicted victims re-enter the retry buffer ([K8S]: evicted pods go
    back through the queue) and can land on another node once capacity
    frees there."""
    nodes = [Node("n0", {"cpu": 2}), Node("n1", {"cpu": 2})]
    pods = [
        Pod("lo", requests={"cpu": 2}, arrival_time=0.0, priority=0,
            node_name="n0"),
        # Long-lived blocker holds n1 so hi MUST preempt on n0; its later
        # completion is what lets the evicted lo re-place.
        Pod("blk", requests={"cpu": 2}, arrival_time=0.0, duration=6.0,
            priority=50, node_name="n1"),
        Pod("hi", requests={"cpu": 2}, arrival_time=1.0, priority=100),
        Pod("t1", requests={}, arrival_time=2.0),
        Pod("t2", requests={}, arrival_time=7.0),
        Pod("t3", requests={}, arrival_time=8.0),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    a = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=1, preemption="kube",
        completions_chunk_waves=1, retry_buffer=8,
    )
    # hi evicts lo on n0 (lower max victim priority than blk on n1).
    assert a.assignments[2] == 0
    assert a.preemptions == 1
    assert a.assignments[0] == 1  # lo re-placed onto n1 after blk completed
    assert a.assignments[1] == 1  # blk completed: assignment kept
    d = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=8,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.preemptions == d.preemptions


def test_gangs_never_victims_and_never_preempt():
    """Gang members are ineligible as victims (their group would go
    partial) and never enter the preemption pass themselves."""
    nodes = [Node("n0", {"cpu": 2})]
    pods = [
        Pod("g0", requests={"cpu": 1}, arrival_time=0.0, priority=0,
            pod_group="g"),
        Pod("g1", requests={"cpu": 1}, arrival_time=0.0, priority=0,
            pod_group="g"),
        Pod("hi", requests={"cpu": 1}, arrival_time=1.0, priority=100),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    a = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=2, preemption="kube",
        completions_chunk_waves=1, retry_buffer=8,
    )
    assert a.assignments[0] == 0 and a.assignments[1] == 0
    assert a.assignments[2] == PAD  # no gang victims available
    assert a.preemptions == 0
    d = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=2, chunk_waves=1,
        preemption="kube", retry_buffer=8,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)


@pytest.mark.parametrize("seed", [0, 2, 3])
def test_device_matches_anchor_random(seed):
    """Over-committed random traces with priorities + durations: the
    engine must equal the greedy anchor EXACTLY while preemptions and
    completions both fire."""
    cluster = make_cluster(6, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(
        260, seed=seed, with_spread=True, with_tolerations=True,
        duration_mean=60.0, arrival_rate=8.0,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    a = greedy_replay(
        ec, ep, cfg, preemption="kube", completions_chunk_waves=4,
        retry_buffer=64,
    )
    d = JaxReplayEngine(
        ec, ep, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.placed == d.placed
    assert a.preemptions == d.preemptions
    assert a.retry_dropped == d.retry_dropped
    if seed != 0:
        assert a.preemptions > 0  # non-vacuous (seeds 2/3 measured >0)


def test_cpu_engine_parity_sequential_trace():
    """W=1 / C=1 on a queue-trivial trace (distinct arrivals, long
    durations): the boundary follows every pod, so kube-mode placements
    equal the CPU event engine's exactly — preemption timing included."""
    rng = np.random.default_rng(5)
    nodes = [
        Node(f"n{i}", {"cpu": 4.0, "memory": 8 * 2**30, "pods": 8})
        for i in range(5)
    ]
    pods = []
    for i in range(60):
        pods.append(
            Pod(
                f"p{i}",
                labels={"app": f"a{i % 4}"},
                requests={"cpu": float(rng.choice([1.0, 2.0])),
                          "memory": float(rng.choice([1, 2])) * 2**30},
                priority=int(rng.choice([0, 0, 50, 100])),
                arrival_time=float(i),  # distinct, strictly increasing
            )
        )
    ec, ep = encode(Cluster(nodes=nodes), pods)
    plugins = [{"name": "NodeResourcesFit"}, {"name": "TaintToleration"},
               {"name": "NodeAffinity"}]
    cfg = FrameworkConfig(plugins=plugins)
    a = greedy_replay(
        ec, ep, cfg, wave_width=1, preemption="kube",
        completions_chunk_waves=1, retry_buffer=64,
    )
    d = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    c = _cpu(ec, ep, plugins=plugins)
    np.testing.assert_array_equal(a.assignments, c.assignments)
    # Eviction COUNTS can differ by a hair (FIFO retry buffer vs the CPU
    # priority queue can evict-then-replace an extra victim on the way to
    # the same final state); the placement parity above is the claim.
    assert abs(a.preemptions - c.preemptions) <= 2
    assert a.preemptions > 0  # non-vacuous


def test_cpu_divergence_bounded_at_production_chunks():
    """At W=8 / C=4 on a contended trace with durations, kube-mode
    placements diverge from the CPU event engine only through chunk
    granularity (completion/preemption timing) — pin the placed-count
    divergence the way test_divergence_pin.py pins completions."""
    cluster = make_cluster(6, seed=2, taint_fraction=0.2)
    pods, _ = make_workload(
        260, seed=2, with_spread=True, with_tolerations=True,
        duration_mean=60.0, arrival_rate=8.0,
    )
    ec, ep = encode(cluster, pods)
    a = greedy_replay(
        ec, ep, FrameworkConfig(), preemption="kube",
        completions_chunk_waves=4, retry_buffer=64,
    )
    c = CpuReplayEngine(
        ec, ep, FrameworkConfig(enable_preemption=True)
    ).replay()
    placed_cpu = int((c.assignments[ep.bound_node == PAD] >= 0).sum())
    rel = abs(a.placed - placed_cpu) / max(placed_cpu, 1)
    assert rel <= 0.12, f"placed divergence {rel:.3f} vs CPU engine"


def test_retry_dropped_reported():
    """Buffer overflow is a REPORTED number (VERDICT r4 weak #2), on both
    the anchor and the engine."""
    nodes = [Node("n0", {"cpu": 1})]
    pods = [Pod("seed", requests={"cpu": 1}, arrival_time=0.0)]
    pods += [
        Pod(f"f{i}", requests={"cpu": 1}, arrival_time=1.0 + i)
        for i in range(20)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    a = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=1, completions_chunk_waves=1,
        retry_buffer=4,
    )
    assert a.retry_dropped > 0
    d = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, retry_buffer=4
    ).replay()
    assert d.retry_dropped == a.retry_dropped
    np.testing.assert_array_equal(a.assignments, d.assignments)


def test_guards():
    ec, ep = encode(
        Cluster(nodes=[Node("n0", {"cpu": 1})]),
        [Pod("p", requests={"cpu": 1}, arrival_time=0.0)],
    )
    with pytest.raises(ValueError, match="retry_buffer > 0"):
        JaxReplayEngine(ec, ep, FIT_ONLY(), preemption="kube")
    with pytest.raises(ValueError, match="retry_buffer > 0"):
        greedy_replay(
            ec, ep, FIT_ONLY(), preemption="kube",
            completions_chunk_waves=1,
        )
    with pytest.raises(ValueError, match="completions_chunk_waves"):
        greedy_replay(ec, ep, FIT_ONLY(), preemption="kube", retry_buffer=8)
    with pytest.raises(ValueError, match="tier"):
        JaxReplayEngine(ec, ep, FIT_ONLY(), preemption="tier", retry_buffer=8)
    with pytest.raises(ValueError):
        JaxReplayEngine(ec, ep, FIT_ONLY(), preemption="bogus")


def pack_len(ep):
    """Number of waves at the default W=8 (chunk-count bound helper)."""
    from kubernetes_simulator_tpu.sim.waves import pack_waves

    return pack_waves(ep, 8).idx.shape[0]


def test_boundary_mode_checkpoint_resume_identity(tmp_path):
    """Round 5: checkpoint/resume works in boundary mode — the host
    mirror (queues, pend list, counters) rides the checkpoint; a resumed
    kube replay must equal the uninterrupted one exactly."""
    cluster = make_cluster(6, seed=2, taint_fraction=0.2)
    pods, _ = make_workload(
        260, seed=2, with_spread=True, with_tolerations=True,
        duration_mean=60.0, arrival_rate=8.0,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    full = JaxReplayEngine(
        ec, ep, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay()
    assert full.preemptions > 0  # non-vacuous
    ckpt = str(tmp_path / "bm.npz")
    JaxReplayEngine(
        ec, ep, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay(checkpoint_path=ckpt, checkpoint_every=2)
    from kubernetes_simulator_tpu.sim.checkpoint import ReplayCheckpoint

    ck = ReplayCheckpoint.load(ckpt)
    num_chunks = -(-pack_len(ep) // 4)
    # The resume must RE-EXECUTE chunks, not just restore-and-report.
    assert ck.boundary is not None and 0 < ck.chunk_cursor < num_chunks
    resumed = JaxReplayEngine(
        ec, ep, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay(checkpoint_path=ckpt, resume=True)
    np.testing.assert_array_equal(full.assignments, resumed.assignments)
    assert resumed.placed == full.placed
    assert resumed.preemptions == full.preemptions
    assert resumed.retry_dropped == full.retry_dropped
    # Config mismatch on resume is rejected, not silently divergent.
    with pytest.raises(ValueError, match="retry_buffer=64"):
        JaxReplayEngine(
            ec, ep, cfg, chunk_waves=4, retry_buffer=64
        ).replay(checkpoint_path=ckpt, resume=True)
    with pytest.raises(ValueError, match="same"):
        JaxReplayEngine(
            ec, ep, cfg, chunk_waves=4, preemption="kube", retry_buffer=128
        ).replay(checkpoint_path=ckpt, resume=True)


def test_boundary_checkpoint_guards(tmp_path):
    """Plain checkpoints don't resume on boundary engines and vice
    versa; what-if forks reject boundary checkpoints."""
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    cluster = make_cluster(4, seed=1)
    pods, _ = make_workload(60, seed=1, duration_mean=20.0)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    plain_ck = str(tmp_path / "plain.npz")
    JaxReplayEngine(ec, ep, cfg, chunk_waves=2).replay(
        checkpoint_path=plain_ck, checkpoint_every=1
    )
    with pytest.raises(ValueError, match="boundary"):
        JaxReplayEngine(
            ec, ep, cfg, chunk_waves=2, retry_buffer=8
        ).replay(checkpoint_path=plain_ck, resume=True)
    bd_ck = str(tmp_path / "bd.npz")
    JaxReplayEngine(ec, ep, cfg, chunk_waves=2, retry_buffer=8).replay(
        checkpoint_path=bd_ck, checkpoint_every=1
    )
    with pytest.raises(ValueError, match="boundary-mode"):
        # The fork checkpoint loads lazily at run() (_init_states).
        WhatIfEngine(
            ec, ep, [Scenario()], cfg, fork_checkpoint=bd_ck
        ).run()


@pytest.mark.slow
def test_batch_whatif_kube_matches_single_replay():
    """Round 5 stretch: WhatIfEngine(preemption="kube") — per-scenario
    host mirrors run the exact PostFilter; the unperturbed scenario must
    equal the single-replay kube engine bit-for-bit, tally == collect."""
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    cluster = make_cluster(6, seed=2, taint_fraction=0.2)
    pods, _ = make_workload(
        260, seed=2, with_spread=True, with_tolerations=True,
        duration_mean=60.0, arrival_rate=8.0,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    single = JaxReplayEngine(
        ec, ep, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay()
    assert single.preemptions > 0  # non-vacuous
    res = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, chunk_waves=4,
        preemption="kube", retry_buffer=64, collect_assignments=True,
    ).run()
    np.testing.assert_array_equal(res.assignments[0], single.assignments)
    np.testing.assert_array_equal(res.assignments[1], single.assignments)
    assert int(res.placed[0]) == single.placed
    tally = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, chunk_waves=4,
        preemption="kube", retry_buffer=64,
    ).run()
    np.testing.assert_array_equal(tally.placed, res.placed)


def test_batch_whatif_kube_perturbed_matches_from_scratch():
    """A perturbed scenario must equal a from-scratch single-replay kube
    run on the equivalently perturbed cluster (the host mirror sees the
    scenario's own allocatable/taints)."""
    from kubernetes_simulator_tpu.models.core import Taint
    from kubernetes_simulator_tpu.sim.whatif import (
        Perturbation,
        Scenario,
        WhatIfEngine,
    )

    cluster = make_cluster(6, seed=2, taint_fraction=0.2)
    pods, _ = make_workload(
        260, seed=2, with_spread=True, with_tolerations=True,
        duration_mean=60.0, arrival_rate=8.0,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = [
        Scenario(),
        Scenario([Perturbation("scale_capacity", nodes=np.arange(2),
                               resource="cpu", factor=0.5)]),
        Scenario([Perturbation("add_taint", nodes=np.arange(2), key="kk",
                               value="vv", effect="NoSchedule")]),
    ]
    res = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, preemption="kube",
        retry_buffer=64, collect_assignments=True,
    ).run()

    ch = make_cluster(6, seed=2, taint_fraction=0.2)
    for i in range(2):
        ch.nodes[i].allocatable = {
            k: (v * 0.5 if k == "cpu" else v)
            for k, v in ch.nodes[i].allocatable.items()
        }
    ec2, ep2 = encode(ch, pods)
    ref = JaxReplayEngine(
        ec2, ep2, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay()
    np.testing.assert_array_equal(res.assignments[1], ref.assignments)

    ct = make_cluster(6, seed=2, taint_fraction=0.2)
    for i in range(2):
        ct.nodes[i].taints.append(Taint("kk", "vv", "NoSchedule"))
    ec3, ep3 = encode(ct, pods)
    ref3 = JaxReplayEngine(
        ec3, ep3, cfg, chunk_waves=4, preemption="kube", retry_buffer=64
    ).replay()
    np.testing.assert_array_equal(res.assignments[2], ref3.assignments)


def test_batch_whatif_kube_guards():
    from kubernetes_simulator_tpu.parallel.mesh import make_mesh
    from kubernetes_simulator_tpu.sim.whatif import (
        Perturbation,
        Scenario,
        WhatIfEngine,
    )

    cluster = make_cluster(12, seed=0, taint_fraction=0.2)
    pods, _ = make_workload(40, seed=0, with_tolerations=True)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    with pytest.raises(ValueError, match="retry_buffer > 0"):
        WhatIfEngine(ec, ep, [Scenario()], cfg, preemption="kube")
    with pytest.raises(ValueError, match="no-mesh"):
        WhatIfEngine(
            ec, ep, [Scenario()] * 8, cfg, preemption="kube",
            retry_buffer=8, mesh=make_mesh(),
        )
    with pytest.raises(ValueError, match="label"):
        WhatIfEngine(
            ec, ep,
            [Scenario([Perturbation(
                "set_label", nodes=np.array([0]),
                key="topology.kubernetes.io/zone", value="zz",
            )])],
            cfg, preemption="kube", retry_buffer=8,
        )


def test_batch_whatif_kube_reports_drops_and_rejects_completions_off():
    """Review r5: per-scenario eviction/drop counters surface on
    WhatIfResult (drops = placements lost to buffer CAPACITY), and an
    explicit completions=False is rejected like the single-replay twin."""
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node("n0", {"cpu": 1})]
    pods = [Pod("seed", requests={"cpu": 1}, arrival_time=0.0)]
    pods += [
        Pod(f"f{i}", requests={"cpu": 1}, arrival_time=1.0 + i)
        for i in range(20)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    res = WhatIfEngine(
        ec, ep, [Scenario()], FIT_ONLY(), wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=4,
    ).run()
    anchor = greedy_replay(
        ec, ep, FIT_ONLY(), wave_width=1, preemption="kube",
        completions_chunk_waves=1, retry_buffer=4,
    )
    assert int(res.retry_dropped[0]) == anchor.retry_dropped > 0
    assert int(res.preemptions[0]) == anchor.preemptions
    with pytest.raises(ValueError, match="completions"):
        WhatIfEngine(
            ec, ep, [Scenario()], FIT_ONLY(), preemption="kube",
            retry_buffer=4, completions=False,
        )
