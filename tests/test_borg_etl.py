"""Real Borg-2019 schema ETL (sim.borg_etl): round-trip on synthetic
files written in the actual collection_events / instance_events export
shape (the dataset itself is unreachable — SURVEY.md §2 trace driver)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.sim.borg import BorgSpec
from kubernetes_simulator_tpu.sim.borg_etl import Borg2019Etl, load_borg2019

_US = 1_000_000


def _write_trace(tmp_path, n_jobs=6, tasks_per_job=4):
    """Tiny trace in the v3 export schema: jobs 100..; jobs 0/2/4 live in
    alloc set 9000+j (gangs); odd instance 0 of every job FINISHes."""
    inst = tmp_path / "instance_events.csv"
    coll = tmp_path / "collection_events.csv"
    with open(coll, "w") as f:
        f.write("time,type,collection_id,priority,alloc_collection_id\n")
        for j in range(n_jobs):
            cid = 100 + j
            alloc = 9000 + j if j % 2 == 0 else 0
            f.write(f"{600 * _US},SUBMIT,{cid},{(j % 5) * 100},{alloc}\n")
    with open(inst, "w") as f:
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        for j in range(n_jobs):
            cid = 100 + j
            alloc = 9000 + j if j % 2 == 0 else 0
            prio = (j % 5) * 100
            for i in range(tasks_per_job):
                t = (600 + 10 * j + i) * _US
                f.write(
                    f"{t},0,{cid},{i},{prio},{alloc},0.05,0.01\n"
                )
            # instance 0 finishes 100s after its submit
            f.write(
                f"{(700 + 10 * j) * _US},FINISH,{cid},0,,,,\n"
            )
    return str(inst), str(coll)


def test_roundtrip_shapes_and_mapping(tmp_path):
    inst, coll = _write_trace(tmp_path)
    etl = Borg2019Etl(inst, coll, cpu_scale=8.0, mem_scale=16 * 2**30)
    cols = etl.read_cols()
    P = 24
    assert len(cols["arrival"]) == P
    # duplicate SUBMITs are impossible here; FINISH maps to duration 100s
    fin = np.isfinite(cols["duration"])
    assert fin.sum() == 6  # one per job
    assert np.allclose(cols["duration"][fin], 100.0)
    # alloc sets → gangs: even jobs gang (12 tasks), odd jobs not
    assert (cols["group_id"] >= 0).sum() == 12
    # normalized resources scaled into cluster units
    assert np.allclose(cols["cpu"], 0.05 * 8.0)
    # lead-in removed: first arrival at t=0
    assert cols["arrival"].min() == 0.0
    # gang members co-arrive and are index-adjacent
    g = cols["group_id"]
    for gid in np.unique(g[g >= 0]):
        at = np.nonzero(g == gid)[0]
        assert (np.diff(at) == 1).all()
        assert len(set(cols["arrival"][at])) == 1
    # toleration rule: priority < 120 tolerates batch taints
    assert (
        (cols["tolerates"] == 1) == (cols["priority"] <= 119)
    ).all()


def test_load_and_replay(tmp_path):
    inst, coll = _write_trace(tmp_path)
    spec = BorgSpec(nodes=20, tasks=24, seed=0)
    ec, ep, meta = load_borg2019(inst, spec, collection_events=coll)
    assert ep.num_pods == 24
    assert meta["num_gangs"] == 3
    from kubernetes_simulator_tpu.sim.greedy import greedy_replay

    res = greedy_replay(ec, ep, FrameworkConfig())
    assert res.placed == 24  # tiny requests all fit


def test_missing_submit_rejected(tmp_path):
    p = tmp_path / "empty.csv"
    p.write_text("time,type,collection_id,instance_index\n")
    with pytest.raises(ValueError, match="no instance SUBMIT"):
        Borg2019Etl(str(p)).read_cols()


def test_config_plumbing(tmp_path):
    inst, coll = _write_trace(tmp_path)
    from kubernetes_simulator_tpu.utils.config import (
        SimConfig,
        build_encoded_case,
    )

    cfg = SimConfig.from_dict(
        {
            "workload": {
                "borg": {
                    "nodes": 20,
                    "tasks": 24,
                    "instanceEvents": inst,
                    "collectionEvents": coll,
                }
            }
        }
    )
    ec, ep = build_encoded_case(cfg)
    assert ep.num_pods == 24


def test_rescheduled_instance_duration_uses_last_submit(tmp_path):
    # SUBMIT t=0, (evicted), re-SUBMIT t=1000, FINISH t=1100: arrival is
    # the first submit, duration the FINAL runtime (100s), not the
    # eviction-spanning lifetime (1100s).
    inst = tmp_path / "inst.csv"
    with open(inst, "w") as f:
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        f.write(f"{600 * _US},0,1,0,100,0,0.1,0.1\n")
        f.write(f"{700 * _US},4,1,0,,,,\n")  # EVICT
        f.write(f"{1600 * _US},0,1,0,100,0,0.1,0.1\n")  # re-SUBMIT
        f.write(f"{1700 * _US},6,1,0,,,,\n")  # FINISH
    cols = Borg2019Etl(str(inst)).read_cols()
    assert cols["arrival"][0] == 0.0
    assert np.isclose(cols["duration"][0], 100.0)


def test_native_ingest_matches_dictreader(tmp_path):
    """The native parser + vectorized aggregation must produce exactly the
    DictReader path's columns — including duplicate SUBMITs (first wins),
    EVICT→re-SUBMIT cycles (duration from the last submit), re-SUBMIT
    after FINISH (still running → inf), and job-level fallbacks."""
    from kubernetes_simulator_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    inst, coll = _write_trace(tmp_path)
    # Append the tricky event patterns.
    with open(inst, "a") as f:
        # duplicate SUBMIT for (100, 1) later — first must win
        f.write(f"{900 * _US},0,100,1,400,0,0.9,0.9\n")
        # EVICT → re-SUBMIT → FINISH for (101, 2)
        f.write(f"{800 * _US},EVICT,101,2,,,,\n")
        f.write(f"{820 * _US},SUBMIT,101,2,100,0,0.05,0.01\n")
        f.write(f"{880 * _US},FINISH,101,2,,,,\n")
        # re-SUBMIT after FINISH for (102, 3): still running → inf;
        # mixed-case type names must parse like _etype's v.upper()
        f.write(f"{730 * _US},Kill,102,3,,,,\n")
        f.write(f"{760 * _US},submit,102,3,,,0.05,0.01\n")
        # task with NO priority/alloc fields → collection_events fallback
        f.write(f"{910 * _US},SUBMIT,104,9,,,0.2,0.1\n")
    etl = Borg2019Etl(inst, coll)
    fast = etl._cols_from_raw(
        native.read_borg2019_events(inst),
        native.read_borg2019_events(coll),
    )
    slow = etl._cols_dictreader()
    assert set(fast) == set(slow)
    for k in slow:
        np.testing.assert_array_equal(fast[k], slow[k], err_msg=k)
    # And read_cols() takes the native path on this file.
    auto = etl.read_cols()
    for k in slow:
        np.testing.assert_array_equal(auto[k], slow[k], err_msg=k)


@pytest.mark.slow
def test_million_row_ingest_throughput(tmp_path):
    """VERDICT r2 #6 acceptance: a synthetic 1M-row real-schema file
    ingests in single-digit seconds (the DictReader path costs minutes at
    this size; the real table is billions of rows)."""
    import time

    from kubernetes_simulator_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    inst = tmp_path / "instance_events_1m.csv"
    R = 1_000_000
    rng = np.random.default_rng(0)
    t = (600 + rng.integers(0, 86_400, R)) * _US
    cid = 100 + rng.integers(0, 50_000, R)
    iidx = rng.integers(0, 200, R)
    prio = rng.choice([0, 100, 200, 360, 450], R)
    alloc = np.where(rng.random(R) < 0.3, 9000 + (cid % 1000), 0)
    cpu = rng.random(R).astype(np.float32) * 0.1
    # Chunked formatting: one big join per 100k rows.
    with open(inst, "w") as f:
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        for c0 in range(0, R, 100_000):
            c1 = min(c0 + 100_000, R)
            rows = [
                f"{t[i]},0,{cid[i]},{iidx[i]},{prio[i]},{alloc[i]},"
                f"{cpu[i]:.4f},0.01"
                for i in range(c0, c1)
            ]
            f.write("\n".join(rows) + "\n")

    etl = Borg2019Etl(str(inst))
    t0 = time.perf_counter()
    cols = etl.read_cols()
    wall = time.perf_counter() - t0
    assert len(cols["arrival"]) > 900_000  # (cid, iidx) mostly unique
    assert wall < 10.0, f"1M-row ingest took {wall:.1f}s (target <10s)"


def test_native_parse_skips_leading_comment_lines(tmp_path):
    """A '#'-comment line before the header must not be read AS the
    header (which would miss the required columns and silently disable
    the fast path) — count and parse agree on comment handling."""
    from kubernetes_simulator_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    inst = tmp_path / "inst.csv"
    with open(inst, "w") as f:
        f.write("# exported 2019-05-01\n")
        f.write("\n")
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        f.write(f"{600 * _US},0,1,0,100,0,0.1,0.1\n")
        f.write(f"{700 * _US},6,1,0,,,,\n")
    raw = native.read_borg2019_events(str(inst))
    assert raw is not None and raw["etype"].shape[0] == 2
    cols = Borg2019Etl(str(inst)).read_cols()
    assert len(cols["arrival"]) == 1
    assert np.isclose(cols["duration"][0], 100.0)


def test_native_int64_ids_exact(tmp_path):
    """Id columns above 2^53 must parse exactly (strtoll, not a double
    round-trip) — two ids that differ only in the low bits stay
    distinct tasks."""
    from kubernetes_simulator_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    big = (1 << 60) + 1  # collapses to 1<<60 through a double
    inst = tmp_path / "inst.csv"
    with open(inst, "w") as f:
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        f.write(f"{600 * _US},0,{big},0,100,0,0.1,0.1\n")
        f.write(f"{600 * _US},0,{big + 1},0,100,0,0.1,0.1\n")
    raw = native.read_borg2019_events(str(inst))
    assert raw is not None
    assert raw["cid"][0] == big and raw["cid"][1] == big + 1
    etl = Borg2019Etl(str(inst))
    cols = etl.read_cols()
    assert len(cols["arrival"]) == 2  # distinct tasks
    # The DictReader fallback must keep them distinct too (no float
    # round-trip through int(float(...)) — ids are INT64).
    assert len(etl._cols_dictreader()["arrival"]) == 2


def test_native_rejects_float_formatted_ids(tmp_path):
    """Scientific/decimal-formatted id fields (float-typed re-exports)
    must NOT be truncated by strtoll — the native parser bails and the
    DictReader fallback parses them via float."""
    from kubernetes_simulator_tpu import native

    if not native.available():
        pytest.skip("native toolchain unavailable")
    inst = tmp_path / "inst.csv"
    with open(inst, "w") as f:
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        f.write(f"{600 * _US},0,3.80226759816e+11,0,100,0,0.1,0.1\n")
    assert native.read_borg2019_events(str(inst)) is None  # fast path bails
    cols = Borg2019Etl(str(inst)).read_cols()  # falls back
    assert len(cols["arrival"]) == 1


def test_unsorted_trace_paths_value_identical(tmp_path):
    """On a trace NOT sorted by time, the native-raw and DictReader paths
    must still produce identical columns (advisor round-3): both anchor
    the duration at the MAX submit time (here 1600 → duration 100s, not
    the file-order-last submit at 600 → 1100s)."""
    inst = tmp_path / "inst.csv"
    with open(inst, "w") as f:
        f.write(
            "time,type,collection_id,instance_index,priority,"
            "alloc_collection_id,resource_request.cpus,"
            "resource_request.memory\n"
        )
        # File order: submit@t=1600, submit@t=600 (out of order),
        # FINISH@t=1700.
        f.write(f"{1600 * _US},0,1,0,100,0,0.1,0.1\n")
        f.write(f"{600 * _US},0,1,0,100,0,0.1,0.1\n")
        f.write(f"{1700 * _US},6,1,0,,,,\n")
    etl = Borg2019Etl(str(inst))
    slow = etl._cols_dictreader()
    from kubernetes_simulator_tpu import native

    if native.available():
        fast = etl._cols_from_raw(
            native.read_borg2019_events(str(inst)), None
        )
        for k in slow:
            np.testing.assert_array_equal(fast[k], slow[k], err_msg=k)
    assert np.isclose(slow["duration"][0], 100.0)
    # Arrival stays the FIRST submit in file order (insertion order) —
    # but its time is clamped at 0 after lead-removal either way.
    assert slow["arrival"][0] >= 0.0
