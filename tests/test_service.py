"""Simulator-as-a-service (round 22): resident engines serving batched
multi-tenant what-if queries.

The serving contract under test:

- **Bit-parity by construction** — a batched multi-tenant defrag query
  must answer byte-identically to a fresh one-off S=1 engine run of the
  SAME synthesized scenario (base-state perturbations + drain/recover
  timeline), including the per-scenario telemetry series. The service's
  ``query_scenario``/``base_scenario`` are the single source of truth
  shared with the oracles here.
- **Warm queries recompile nothing** — the pool engine's compiled-
  executable count stays pinned at 1 across batches (the same
  ``_chunk_fn._cache_size()`` pin the round-9 tuner uses), and
  ``api.Simulator.what_if`` reuses its resident engine the same way.
- **Bad input never tears down the pool** — a torn/malformed NDJSON
  line becomes a structured ``query-error`` row and the loop keeps
  serving; everything emitted validates as schema v7.
"""

import io
import json
import os
import sys

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import compiled_cache_size
from kubernetes_simulator_tpu.sim.service import (
    QueryService,
    max_engines_cap,
    serve_lines,
)
from kubernetes_simulator_tpu.sim.whatif import (
    Perturbation,
    Scenario,
    WhatIfEngine,
)

_SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")
sys.path.insert(0, os.path.abspath(_SCRIPTS))

from check_metrics_schema import validate_file  # noqa: E402

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])

# Queue-trivial shape (the documented parity envelope, as in
# test_chaos._light_trace but smaller): strictly-increasing integer
# arrivals, load that fits even with the drained nodes down.
ENGINE_KW = dict(wave_width=1, chunk_waves=1)


def _tiny_trace(num_pods=12, num_nodes=4):
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(num_nodes)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=30.0)
        for i in range(num_pods)
    ]
    return encode(Cluster(nodes=nodes), pods)


def _service(ec, ep, **kw):
    kw.setdefault("max_batch", 3)
    kw.setdefault("batch_deadline_s", 0.05)
    kw.setdefault("retry_buffer", 64)
    return QueryService(ec, ep, FIT_ONLY(), **kw, **ENGINE_KW)


class _ListWriter:
    def __init__(self):
        self.rows = []

    def write(self, row):
        self.rows.append(dict(row))


# ---------------------------------------------------------------------------
# admission / validation (no engine builds — cheap)


def test_parse_query_refusals():
    ec, ep = _tiny_trace(num_pods=2, num_nodes=2)
    svc = _service(ec, ep)
    with pytest.raises(ValueError, match="unknown query family"):
        svc.parse_query({"op": "repack", "nodes": [0]})
    with pytest.raises(ValueError, match="JSON object"):
        svc.parse_query(["defrag"])
    with pytest.raises(ValueError, match="nodes"):
        svc.parse_query({"op": "defrag"})
    with pytest.raises(ValueError, match="out of range"):
        svc.parse_query({"op": "defrag", "nodes": [99]})
    with pytest.raises(ValueError, match="unknown node name"):
        svc.parse_query({"op": "defrag", "nodes": ["nope"]})
    with pytest.raises(ValueError, match="drainAt"):
        svc.parse_query({"op": "defrag", "nodes": [0], "drainAt": -1.0})
    with pytest.raises(ValueError, match="recoverAt"):
        svc.parse_query(
            {"op": "defrag", "nodes": [0], "drainAt": 5.0, "recoverAt": 5.0}
        )
    with pytest.raises(ValueError, match="granularity"):
        svc.parse_query(
            {"op": "defrag", "nodes": [0], "granularity": "verbose"}
        )
    # Node names resolve, dedupe, and sort — the synthesized timeline is
    # deterministic regardless of request order.
    dq = svc.parse_query({"op": "defrag", "nodes": ["n1", 0, 1],
                          "drainAt": 5.0})
    assert dq.nodes == [0, 1]
    assert dq.tenant == "default" and dq.qid  # auto id
    # Duplicate in-flight ids are refused at submit.
    svc.submit({"op": "defrag", "tenant": "a", "id": "q1", "nodes": [0],
                "drainAt": 5.0})
    with pytest.raises(ValueError, match="duplicate query id"):
        svc.submit({"op": "defrag", "tenant": "a", "id": "q1",
                    "nodes": [1], "drainAt": 5.0})


def test_ctor_refusals_and_engine_cap():
    ec, ep = _tiny_trace(num_pods=2, num_nodes=2)
    with pytest.raises(ValueError, match="max_batch"):
        QueryService(ec, ep, FIT_ONLY(), max_batch=0)
    with pytest.raises(ValueError, match="batch_deadline_s"):
        QueryService(ec, ep, FIT_ONLY(), batch_deadline_s=0.0)
    with pytest.raises(ValueError, match="retry_buffer"):
        QueryService(ec, ep, FIT_ONLY(), retry_buffer=0)
    assert max_engines_cap(4) == 4
    os.environ["KSIM_SERVICE_MAX_ENGINES"] = "2"
    try:
        assert max_engines_cap(4) == 2  # operator env beats config
        assert _service(ec, ep, max_engines=8).max_engines == 2
    finally:
        del os.environ["KSIM_SERVICE_MAX_ENGINES"]


def test_base_state_mirror():
    """bind/release/evict deltas surface as synthesized scale_capacity
    perturbations — never a trace rebuild."""
    ec, ep = _tiny_trace(num_pods=2, num_nodes=3)
    svc = _service(ec, ep)
    assert svc.base_perturbations() == []
    svc.apply_bind("b1", "n0", {"cpu": 2.0})
    svc.apply_bind("b2", 0, {"cpu": 2.0})
    svc.apply_bind("b3", 1, {"cpu": 4.0})
    perts = svc.base_perturbations()
    assert [int(p.nodes[0]) for p in perts] == [0, 1]
    assert all(p.op == "scale_capacity" and p.resource == "cpu"
               for p in perts)
    # n0: 4 of 8 cpu committed -> factor 0.5; n1: 4 of 8 -> 0.5.
    assert perts[0].factor == pytest.approx(0.5)
    assert perts[1].factor == pytest.approx(0.5)
    assert svc.base_state() == {"binds": 3, "nodes_used": 2}
    svc.apply_release("b2")
    assert svc.base_perturbations()[0].factor == pytest.approx(0.75)
    assert svc.apply_evict("n1") == ["b3"]  # insertion order
    perts = svc.base_perturbations()
    assert len(perts) == 1 and int(perts[0].nodes[0]) == 0
    with pytest.raises(ValueError, match="already active"):
        svc.apply_bind("b1", 0, {"cpu": 1.0})
    with pytest.raises(ValueError, match="unknown bind"):
        svc.apply_release("b2")
    with pytest.raises(ValueError, match="unknown resource"):
        svc.apply_bind("b9", 0, {"unobtainium": 1.0})


def test_validate_config_refusals():
    from kubernetes_simulator_tpu.cli import _service_errors, validate_config
    from kubernetes_simulator_tpu.utils.config import SimConfig

    ok = SimConfig.from_dict({
        "strategy": "jax", "devicePreemption": "kube",
        "whatIf": {"retryBuffer": 64},
        "service": {"maxBatch": 2, "batchDeadlineS": 0.1,
                    "granularity": "series"},
    })
    assert _service_errors(ok) == []
    assert ok.service.max_batch == 2
    assert ok.service.batch_deadline_s == pytest.approx(0.1)
    bad = SimConfig.from_dict({
        "strategy": "jax", "devicePreemption": "kube",
        "whatIf": {"retryBuffer": 64},
        "nodeShards": 2,
        "service": {"batchDeadlineS": 0, "maxEngines": 0,
                    "granularity": "verbose"},
    })
    errs = "\n".join(_service_errors(bad))
    assert "nodeShards" in errs
    assert "batchDeadlineS: must be > 0" in errs
    assert "maxEngines" in errs
    assert "granularity" in errs
    # The kube-mirror requirement: defrag drains ride chaos eviction.
    no_kube = SimConfig.from_dict({"strategy": "jax", "service": {}})
    errs = "\n".join(_service_errors(no_kube))
    assert "devicePreemption: kube" in errs and "retryBuffer" in errs
    # And the section rides the full validate_config chain.
    assert any("service" in e for e in validate_config(bad))
    # A config without the section stays untouched.
    assert _service_errors(SimConfig.from_dict({"strategy": "jax"})) == []


# ---------------------------------------------------------------------------
# serving parity + warm path (engine builds — the expensive half)


def test_batched_multitenant_parity_bitmatch():
    """Satellite 3 + tentpole acceptance: K coalesced defrag queries from
    multiple tenants — on a LIVE base state, at series telemetry — answer
    byte-identically to K sequential one-off S=1 engines running the same
    synthesized scenarios."""
    ec, ep = _tiny_trace()
    svc = _service(ec, ep, granularity="series")
    svc.apply_bind("web-1", 0, {"cpu": 3.0})
    svc.apply_bind("web-2", 2, {"cpu": 2.0})
    wire = [
        {"op": "defrag", "tenant": "team-a", "id": "q1", "nodes": [3],
         "drainAt": 4.0, "recoverAt": 12.0},
        {"op": "defrag", "tenant": "team-b", "id": "q1", "nodes": [0, 1],
         "drainAt": 2.0},
        {"op": "defrag", "tenant": "team-a", "id": "q2", "nodes": ["n2"],
         "drainAt": 6.0, "recoverAt": 20.0},
    ]
    # Oracle scenarios BEFORE submit (same base state; parse_query is
    # side-effect-free on the mirror).
    oracle_scens = [svc.query_scenario(svc.parse_query(dict(q)))
                    for q in wire]
    for q in wire:
        svc.submit(q)  # 3rd submit fills max_batch=3 -> auto-flush
    rows_a = svc.poll("team-a")
    rows_b = svc.poll("team-b")
    assert [r["query"] for r in rows_a] == ["q1", "q2"]
    assert [r["query"] for r in rows_b] == ["q1"]
    by_wire = [rows_a[0], rows_b[0], rows_a[1]]
    for row in by_wire:
        assert row["warm"] is False and row["batch"] == 1
        assert row["batch_occupancy"] == 1.0
    for row, scen in zip(by_wire, oracle_scens):
        one = WhatIfEngine(
            ec, ep, [scen], FIT_ONLY(), preemption="kube",
            retry_buffer=64, telemetry="series", **ENGINE_KW,
        ).run()
        assert row["placed"] == int(one.placed[0])
        assert row["unschedulable"] == int(one.unschedulable[0])
        assert row["evictions"] == int(one.evictions[0])
        assert row["evict_rescheduled"] == int(one.evict_rescheduled[0])
        assert row["evict_stranded"] == int(one.evict_stranded[0])
        assert row["evict_latency_mean"] == float(one.evict_latency_mean[0])
        for k, arr in (("stranded_cpu", one.stranded_cpu),
                       ("frag_index_cpu", one.frag_index_cpu),
                       ("packing_efficiency", one.packing_efficiency)):
            if row[k] is not None:
                assert row[k] == float(arr[0])
        # Telemetry series: bit-identical per-scenario virtual-time
        # trajectories (granularity rides the pool key).
        view = one.scenario_telemetry[0].query_view()
        assert row["telemetry"]["series"] == view["series"]
    # The baseline slot sees the SAME live base state as the queries.
    assert by_wire[0]["baseline_stranded_cpu"] is not None
    st = svc.stats()
    assert st["queries"] == 3 and st["batches"] == 1
    assert st["cold_builds"] == 1 and st["warm_hits"] == 0
    assert st["compile_counts"] == {"defrag/series": 1}


def test_warm_queries_zero_recompile():
    """Tentpole acceptance: the second query against an identical-shape
    pool engine swaps scenario values only — the compiled-executable
    count stays 1 and the engine object is reused (no cold build)."""
    ec, ep = _tiny_trace()
    writer = _ListWriter()
    svc = _service(ec, ep, writer=writer)
    svc.submit({"op": "defrag", "tenant": "a", "id": "q1", "nodes": [1],
                "drainAt": 3.0})
    assert svc.flush() == 1  # partial batch: padded to the fixed shape
    (r1,) = svc.poll("a")
    assert r1["warm"] is False and r1["batch_occupancy"] < 1.0
    eng = next(iter(svc._pool.values()))
    svc.submit({"op": "defrag", "tenant": "a", "id": "q2",
                "nodes": [0, 2], "drainAt": 5.0, "recoverAt": 15.0})
    svc.flush()
    (r2,) = svc.poll("a")
    assert r2["warm"] is True
    assert next(iter(svc._pool.values())) is eng  # same resident engine
    st = svc.stats()
    assert st["cold_builds"] == 1 and st["warm_hits"] == 1
    assert st["compile_counts"] == {"defrag/summary": 1}
    if compiled_cache_size(eng._chunk_fn) is not None:
        assert compiled_cache_size(eng._chunk_fn) == 1
    # Writer saw admission + result rows, wall fields scrubbed-safe keys
    # present for the schema (values stay real without deterministic
    # mode).
    kinds = [r["kind"] for r in writer.rows]
    assert kinds.count("query") == 2 and kinds.count("query-result") == 2
    assert svc.close() == []  # nothing undelivered
    with pytest.raises(ValueError, match="closed"):
        svc.submit({"op": "defrag", "nodes": [0]})


def test_simulator_what_if_engine_reuse():
    """Satellite 1: repeated same-shape ``api.Simulator.what_if`` calls
    reuse ONE resident engine — compile count pinned at 1 — and the
    swapped-value answer bit-matches a fresh one-off build."""
    from kubernetes_simulator_tpu.api import Simulator

    nodes_l = [Node(f"n{i}", {"cpu": 8.0}) for i in range(3)]
    pods_l = [Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
                  duration=20.0) for i in range(8)]

    def _scens(factor):
        return [
            Scenario(),
            Scenario(perturbations=[Perturbation(
                op="scale_capacity", nodes=np.array([0]),
                resource="cpu", factor=factor,
            )]),
        ]

    sim = Simulator(nodes_and_pods := Cluster(nodes=nodes_l), pods_l,
                    strategy="jax",
                    plugins=[{"name": "NodeResourcesFit"}])
    res1 = sim.what_if(scenarios=_scens(0.5), **ENGINE_KW)
    eng = sim._whatif_cache[1]
    res2 = sim.what_if(scenarios=_scens(0.125), **ENGINE_KW)
    assert sim._whatif_cache[1] is eng  # resident, not rebuilt
    if compiled_cache_size(eng._chunk_fn) is not None:
        assert compiled_cache_size(eng._chunk_fn) == 1
    fresh = Simulator(nodes_and_pods, pods_l, strategy="jax",
                      plugins=[{"name": "NodeResourcesFit"}]).what_if(
        scenarios=_scens(0.125), **ENGINE_KW)
    np.testing.assert_array_equal(res2.placed, fresh.placed)
    np.testing.assert_array_equal(res2.unschedulable, fresh.unschedulable)
    assert res1.placed[1] >= res2.placed[1]  # tighter cap, fewer fits
    # A different batch shape misses the cache and rebuilds.
    res3 = sim.what_if(scenarios=_scens(0.5) + [Scenario()], **ENGINE_KW)
    assert sim._whatif_cache[1] is not eng
    assert len(res3.placed) == 3


def test_serve_lines_and_schema_v7(tmp_path):
    """Satellite 2 + v7 envelope: the serve loop turns torn/malformed
    NDJSON into ``query-error`` rows and keeps serving; every emitted
    row (admission, result, error, flight query events) validates as
    schema v7."""
    from kubernetes_simulator_tpu.sim.flight import (
        FlightRecorder,
        FlightRecorderConfig,
    )
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter

    ec, ep = _tiny_trace()
    out_path = str(tmp_path / "serve.jsonl")
    fl_path = str(tmp_path / "flight.jsonl")
    lines = io.StringIO(
        "\n".join([
            '{"op": "defrag", "tenant": "a", "id": "q1", "nodes": [1], '
            '"drainAt": 3.0}',
            '{"op": "defrag", "tenant": "a", "id": "q2", "nodes": [',  # torn
            "not json at all",
            '{"op": "warp", "nodes": [0]}',  # unknown family
            '{"op": "defrag", "nodes": [99]}',  # out of range
            "",  # blank lines are skipped, not errors
            '{"op": "defrag", "tenant": "b", "id": "q9", "nodes": [0, 2], '
            '"drainAt": 2.0, "recoverAt": 9.0}',
        ]) + "\n"
    )
    flight = FlightRecorder(FlightRecorderConfig(path=fl_path),
                            meta={"mode": "serve"})
    with JsonlWriter(out_path, context={"seed": 0, "engine": "jax",
                                        "config_hash": "t" * 12}) as out:
        svc = _service(ec, ep, max_batch=1, writer=out, flight=flight)
        stats = serve_lines(svc, lines, out)
    flight.close()
    assert stats["queries"] == 2 and stats["errors"] == 4
    assert stats["batches"] == 2  # max_batch=1: every valid line flushes
    rows = [json.loads(l) for l in open(out_path)]
    kinds = [r["kind"] for r in rows]
    assert kinds.count("query") == 2
    assert kinds.count("query-result") == 2
    assert kinds.count("query-error") == 4
    # The good query AFTER the bad lines was served — pool survived.
    assert kinds[-1] == "query-result"
    last = rows[-1]
    assert last["tenant"] == "b" and last["query"] == "q9"
    assert last["schema"] == 7
    errs = [r for r in rows if r["kind"] == "query-error"]
    assert all("error" in r and "raw" in r for r in errs)
    assert any("nodes" in r["raw"] for r in errs)  # torn line echoed
    # Everything written validates, including the flight 'query' events.
    assert validate_file(out_path) == []
    assert validate_file(fl_path) == []
    fl_rows = [json.loads(l) for l in open(fl_path)]
    q_events = [r for r in fl_rows if r.get("event") == "query"]
    assert len(q_events) == 2
    assert q_events[0]["warm"] is False and q_events[1]["warm"] is True
    assert q_events[1]["engines"] == 1


@pytest.mark.slow
def test_engine_pool_lru_soak():
    """Satellite 5 (slow-marked): a multi-granularity query mix under a
    capped pool — LRU eviction churns engines, every answer keeps
    bit-stable against its own re-ask, and the pool never exceeds the
    cap."""
    ec, ep = _tiny_trace()
    svc = _service(ec, ep, max_engines=1)
    first = {}
    for round_i in range(2):
        for gran in ("summary", "series"):
            svc.submit({"op": "defrag", "tenant": "t", "id": f"{gran}-{round_i}",
                        "nodes": [1], "drainAt": 3.0, "recoverAt": 10.0,
                        "granularity": gran})
            svc.flush()
            (row,) = svc.poll("t")
            assert len(svc._pool) <= 1
            key = (row["placed"], row["unschedulable"], row["evictions"],
                   row["evict_stranded"])
            if gran in first:
                assert first[gran] == key  # re-ask answers identically
            else:
                first[gran] = key
    st = svc.stats()
    assert st["cold_builds"] == 4  # every switch re-cold-builds at cap 1
    assert st["evicted_engines"] >= 3
    assert st["engines"] == 1
    svc.close()
