"""Round-17 faultline fuzz slice (slow): drive scripts/faultline_fuzz.py's
seeded crash schedules — always including the double-kill and the
recovering-claimant-kill — against live 3-worker fleets and pin the
acceptance bar: every surviving worker's end gather is BYTE-IDENTICAL to
the no-failure single-process oracle, named kills die with SIGKILL, at
least one worker survives every schedule, and a fired wildcard kill
leaves the claim-generation hand-off in the logs.

The schedules are a pure function of the seed, so a red run here
reproduces exactly with ``python scripts/faultline_fuzz.py --seed 17``.

Round 20 widens the bar with the two supervised durable-ground drills:
the coordinator SIGKILLed by name and the whole fleet killed at once,
both run under ``dcn_launch.py --supervise`` over a durability journal
and both required to end byte-identical to the oracle after a
relaunch-with-``--resume``.
"""

import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "scripts")
    ),
)

import faultline_fuzz as F  # noqa: E402

SEED = 17
N_SCHEDULES = 8


def test_mandatory_schedules_always_sampled():
    """Fast sanity (no fleet): the sampler always leads with the
    double-kill, claimant-kill, wq-straggler, wq-spec-kill,
    mid-publish-kill and the two supervised durable-ground drills,
    schedules are deterministic in the seed, and unsupervised kills
    never name the coordinator (supervised drills MAY — that is their
    whole point: the supervisor relaunches the fleet)."""
    scheds = F.sample_schedules(SEED, N_SCHEDULES)
    assert len(scheds) == N_SCHEDULES
    assert scheds[0]["name"] == "double-kill"
    assert scheds[0]["kill"] == "1@run:0,2@run:0"
    assert scheds[1]["name"] == "claimant-kill"
    assert "*@recover" in scheds[1]["kill"]
    assert scheds[2]["name"] == "wq-straggler"
    assert scheds[2]["wq"] and scheds[2]["slow"] == "1@1:4"
    assert "kill" not in scheds[2]
    assert scheds[3]["name"] == "wq-spec-kill"
    assert scheds[3]["wq"] and scheds[3]["kill"] == "*@spec:-1"
    assert scheds[4]["name"] == "mid-publish-kill"
    assert scheds[4]["kill"] == "*@run:1" and scheds[4]["torn_rate"] == 0.5
    assert scheds[5]["name"] == "coord-kill-restart"
    assert scheds[5]["kill"] == "0@run:1" and scheds[5]["supervised"]
    assert scheds[6]["name"] == "fleet-kill-restart"
    assert scheds[6]["kill"] == "all@run:1" and scheds[6]["supervised"]
    assert scheds[6]["torn_rate"] == 0.5
    assert scheds == F.sample_schedules(SEED, N_SCHEDULES)
    assert scheds != F.sample_schedules(SEED + 1, N_SCHEDULES)
    for sch in scheds:
        named, _ = F.named_kill_pids(sch)
        if sch.get("supervised"):
            # The round-20 drills kill the coordinator on purpose; the
            # supervisor's relaunch is what makes that survivable.
            assert 0 in named, sch
            continue
        assert 0 not in named, (
            "an unsupervised schedule must not kill the "
            "coordination-service host"
        )


@pytest.mark.slow
def test_fuzz_schedules_byte_identical_to_oracle(tmp_path):
    oracle = F.run_oracle()
    scheds = F.sample_schedules(SEED, N_SCHEDULES)
    failures = []
    for i, sched in enumerate(scheds):
        hb = tmp_path / f"hb{i}"
        hb.mkdir()
        out = F.run_schedule(sched, str(hb), timeout_s=600.0)
        if out["skip"]:
            pytest.skip("jaxlib CPU backend lacks multiprocess execution")
        failures.extend(F.check_schedule(sched, out, oracle))
        if sched["name"] == "double-kill":
            # Both named victims actually died concurrently and the
            # coordinator absorbed BOTH blocks.
            assert out["rcs"][1] == -9 and out["rcs"][2] == -9, out["rcs"]
            assert "claims dead process 1" in out["blob"]
            assert "claims dead process 2" in out["blob"]
        if sched["name"] == "claimant-kill":
            # The wildcard entry fired on the gen-0 claimant (worker 1 —
            # the coordinator defers claims while a live sibling can
            # absorb the block) and the survivor opened generation 1.
            killed = sorted(p for p, rc in out["rcs"].items() if rc == -9)
            assert killed == [1, 2], out["rcs"]
            assert "opening generation 1" in out["blob"], out["blob"][-2000:]
            assert "(gen 1)" in out["blob"], out["blob"][-2000:]
        if sched["name"] == "wq-straggler":
            # Nobody dies: the slowed holder is outrun by an idle
            # process's speculative re-execution, everyone exits clean.
            assert all(rc == 0 for rc in out["rcs"].values()), out["rcs"]
            assert "speculates block" in out["blob"], out["blob"][-2000:]
        if sched["name"] == "wq-spec-kill":
            # Exactly the speculator dies (the only process that ever
            # beacons state "spec"); the straggler's block still
            # completes via the gen-1 lease steal.
            killed = [p for p, rc in out["rcs"].items() if rc == -9]
            assert len(killed) == 1, out["rcs"]
            assert "speculates block" in out["blob"], out["blob"][-2000:]
            assert "steals block" in out["blob"], out["blob"][-2000:]
        if sched["name"] == "mid-publish-kill":
            # Round 19: exactly one worker dies in the window between
            # its device→host snapshot and the background publisher's
            # KV publication; a survivor claims the dead block from the
            # prior COMPLETE cursor (check_schedule already demanded the
            # "claims dead process" marker and oracle byte-parity).
            killed = [p for p, rc in out["rcs"].items() if rc == -9]
            assert len(killed) == 1, out["rcs"]
            assert "claims dead process" in out["blob"], out["blob"][-2000:]
        if sched["name"] in ("coord-kill-restart", "fleet-kill-restart"):
            # Round 20: the supervisor absorbed the (previously
            # unsurvivable) death, relaunched with --resume, and the
            # restarted fleet's gather matched the oracle byte-for-byte
            # (check_supervised demanded all three).  Pin the mechanics:
            # a relaunch marker and a clean supervisor exit.
            assert out.get("supervised"), out
            assert out["rcs"].get(0) == 0, out["blob"][-2000:]
            assert "relaunching with --resume" in out["blob"], (
                out["blob"][-2000:]
            )
    assert not failures, "\n".join(failures)
