"""Tier-1 schema gate: a fresh CLI run's JSONL must validate against
scripts/check_metrics_schema.py, and the checker must actually reject
malformed rows (no rubber stamp)."""

import json
import os
import sys

import pytest

_SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")
sys.path.insert(0, os.path.abspath(_SCRIPTS))

from check_metrics_schema import main, validate_file, validate_row  # noqa: E402

from kubernetes_simulator_tpu.cli import main as cli_main  # noqa: E402


@pytest.fixture()
def run_jsonl(tmp_path):
    cfg = tmp_path / "c.yaml"
    out = tmp_path / "out.jsonl"
    cfg.write_text(
        "strategy: cpu\n"
        "cluster:\n  synthetic: {nodes: 4, seed: 0}\n"
        "workload:\n  synthetic: {pods: 40, seed: 1}\n"
        "telemetry:\n  granularity: series\n"
        f"output: {out}\n"
    )
    assert cli_main(["run", str(cfg)]) == 0
    return str(out)


def test_cli_run_emits_valid_schema(run_jsonl):
    assert validate_file(run_jsonl) == []
    rows = [json.loads(l) for l in open(run_jsonl)]
    assert rows and rows[0]["schema"] == 7  # round 22: query service
    assert {"seed", "engine", "config_hash", "telemetry"} <= rows[0].keys()
    assert "fragmentation" in rows[0]
    assert main([run_jsonl]) == 0


def test_checker_rejects_malformed_rows(tmp_path):
    bad = tmp_path / "bad.jsonl"
    bad.write_text(
        json.dumps({"ts": 1.0, "schema": 2, "kind": "replay-cpu"}) + "\n"
        + json.dumps({"ts": 1.0, "schema": 99, "kind": "replay-cpu"}) + "\n"
        + "not json\n"
    )
    errs = validate_file(str(bad))
    assert any("seed" in e for e in errs)
    assert any("unknown version" in e for e in errs)
    assert any("invalid JSON" in e for e in errs)
    assert main([str(bad)]) == 1


def test_v1_rows_still_accepted():
    # Pre-versioning rows (no "schema" field) keep validating so old
    # result files don't rot.
    assert validate_row({"ts": 1.0, "kind": "replay-cpu", "placed": 3}) == []
    assert validate_row({"kind": "replay-cpu"}) == ["ts: missing"]


def test_whatif_rows_validate(tmp_path):
    cfg = tmp_path / "w.yaml"
    out = tmp_path / "w.jsonl"
    cfg.write_text(
        "strategy: jax\n"
        "cluster:\n  synthetic: {nodes: 4, seed: 0}\n"
        "workload:\n  synthetic: {pods: 40, seed: 1}\n"
        "whatIf:\n  scenarios: 2\n"
        "chunkWaves: 4\n"
        f"output: {out}\n"
    )
    assert cli_main(["what-if", str(cfg)]) == 0
    assert validate_file(str(out)) == []
    kinds = [json.loads(l)["kind"] for l in open(out)]
    assert kinds == ["whatif-aggregate", "whatif-scenario", "whatif-scenario"]
