"""What-if scenario engine: vmap correctness vs looped evaluation, mesh
sharding on the 8-device CPU mesh, perturbation semantics (SURVEY.md §4.4-5)."""

import numpy as np
import pytest

import jax

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.parallel.mesh import make_mesh
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import config1, make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import (
    Perturbation,
    Scenario,
    WhatIfEngine,
    uniform_scenarios,
)


def small_case(seed=0, n=15, p=80):
    cluster = make_cluster(n, seed=seed, taint_fraction=0.1)
    pods, _ = make_workload(p, seed=seed, with_affinity=True, with_spread=True,
                            with_tolerations=True)
    return encode(cluster, pods)


def test_base_scenario_matches_single_replay():
    """Scenario 0 (unperturbed) must equal the plain jax engine exactly."""
    ec, ep = small_case()
    cfg = FrameworkConfig()
    single = JaxReplayEngine(ec, ep, cfg).replay()
    eng = WhatIfEngine(ec, ep, [Scenario(), Scenario()], cfg, collect_assignments=True)
    res = eng.run()
    assert (res.assignments[0] == single.assignments).all()
    assert res.placed[0] == single.placed


def test_vmap_matches_looped_perturbed_scenarios():
    """Each perturbed scenario must equal a from-scratch single replay on
    the equivalently perturbed cluster (SURVEY.md §4.5)."""
    from kubernetes_simulator_tpu.models.core import Taint

    cluster = make_cluster(12, seed=3)
    pods, _ = make_workload(60, seed=3, with_tolerations=True)
    ec, ep = encode(cluster, pods)

    down = np.array([0, 1])
    scen = [
        Scenario(),
        Scenario([Perturbation("node_down", nodes=down)]),
        Scenario([Perturbation("scale_capacity", nodes=np.arange(6), resource="cpu", factor=0.5)]),
        Scenario([Perturbation("add_taint", nodes=np.arange(4), key="k", value="v",
                               effect="NoSchedule")]),
    ]
    res = WhatIfEngine(ec, ep, scen, FrameworkConfig(), collect_assignments=True).run()

    # Reference replays with the perturbation applied to the object model.
    cluster_down = make_cluster(12, seed=3)
    for i in down:
        cluster_down.nodes[i].allocatable = {k: 0.0 for k in cluster_down.nodes[i].allocatable}
    ec2, ep2 = encode(cluster_down, pods)
    ref = JaxReplayEngine(ec2, ep2, FrameworkConfig()).replay()
    assert (res.assignments[1] == ref.assignments).all()

    cluster_half = make_cluster(12, seed=3)
    for i in range(6):
        cluster_half.nodes[i].allocatable = {
            k: (v * 0.5 if k == "cpu" else v) for k, v in cluster_half.nodes[i].allocatable.items()
        }
    ec3, ep3 = encode(cluster_half, pods)
    ref3 = JaxReplayEngine(ec3, ep3, FrameworkConfig()).replay()
    assert (res.assignments[2] == ref3.assignments).all()

    cluster_taint = make_cluster(12, seed=3)
    for i in range(4):
        cluster_taint.nodes[i].taints.append(Taint("k", "v", "NoSchedule"))
    ec4, ep4 = encode(cluster_taint, pods)
    ref4 = JaxReplayEngine(ec4, ep4, FrameworkConfig()).replay()
    assert (res.assignments[3] == ref4.assignments).all()


def test_mesh_sharded_matches_unsharded():
    """shard_map-equivalent sharded run over 8 virtual devices must equal
    the single-device vmap bit-for-bit."""
    assert len(jax.devices()) == 8
    ec, ep = small_case(seed=7)
    scen = uniform_scenarios(ec, 16, seed=7)
    cfg = FrameworkConfig()
    plain = WhatIfEngine(ec, ep, scen, cfg, collect_assignments=True).run()
    mesh = make_mesh()
    sharded = WhatIfEngine(ec, ep, scen, cfg, mesh=mesh, collect_assignments=True).run()
    assert (plain.assignments == sharded.assignments).all()
    assert (plain.placed == sharded.placed).all()


@pytest.mark.slow
def test_node_down_reduces_capacity():
    ec, ep = small_case(seed=1, n=6, p=60)
    scen = [Scenario(), Scenario([Perturbation("node_down", nodes=np.arange(3))])]
    res = WhatIfEngine(ec, ep, scen, FrameworkConfig()).run()
    assert res.placed[1] <= res.placed[0]


def test_set_label_rederives_domains():
    """Moving nodes between zones must change spread domain counts."""
    from kubernetes_simulator_tpu.models.core import (
        Cluster, LabelSelector, Node, Pod, TopologySpreadConstraint,
    )

    nodes = [Node(f"n{i}", {"cpu": 100}, labels={"zone": "za" if i < 3 else "zb"})
             for i in range(4)]
    sel = LabelSelector.make({"app": "w"})
    pods = [
        Pod(f"p{i}", labels={"app": "w"},
            topology_spread=[TopologySpreadConstraint(1, "zone", "DoNotSchedule", sel)],
            arrival_time=float(i), requests={"cpu": 1})
        for i in range(8)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    # Scenario 1 moves n3 into za → single domain → skew constraint trivial.
    scen = [
        Scenario(),
        Scenario([Perturbation("set_label", nodes=np.array([3]), key="zone", value="za")]),
    ]
    res = WhatIfEngine(ec, ep, scen, FrameworkConfig(), collect_assignments=True).run()
    assert res.placed[0] == 8 and res.placed[1] == 8
    # In the base, placements must spread between za and zb nodes.
    a0 = res.assignments[0]
    assert (a0 < 3).any() and (a0 >= 3).any()


def test_scenario_count_must_divide_devices():
    ec, ep = small_case(seed=2, n=5, p=10)
    with pytest.raises(ValueError):
        WhatIfEngine(ec, ep, [Scenario()] * 3, mesh=make_mesh())


def test_injected_prefer_taint_reenables_score_row():
    """The taint score row is statically dropped when the base cluster has
    no PreferNoSchedule taints; a what-if scenario that injects one must
    re-enable it (scores change where the taint lands)."""
    from kubernetes_simulator_tpu.models.core import Taint

    cluster = make_cluster(12, seed=9)  # no taints in the base cluster
    pods, _ = make_workload(80, seed=9)
    ec, ep = encode(cluster, pods)
    from kubernetes_simulator_tpu.sim.jax_runtime import StepSpec

    assert not StepSpec.from_config(ec, FrameworkConfig(), ep).taint_score
    scen = [
        Scenario(),
        Scenario([Perturbation("add_taint", nodes=np.arange(6), key="soft",
                               value="x", effect="PreferNoSchedule")]),
    ]
    eng = WhatIfEngine(ec, ep, scen, FrameworkConfig(), collect_assignments=True)
    assert eng.spec.taint_score  # re-enabled by the injection
    res = eng.run()

    # Reference: from-scratch replay on the equivalently tainted cluster.
    cluster_t = make_cluster(12, seed=9)
    for n in cluster_t.nodes[:6]:
        n.taints.append(Taint("soft", "x", "PreferNoSchedule"))
    ec_t, ep_t = encode(cluster_t, pods)
    ref = JaxReplayEngine(ec_t, ep_t, FrameworkConfig()).replay()
    np.testing.assert_array_equal(res.assignments[1], ref.assignments)


def _force_v2(ec, ep, scen, cfg, **kw):
    """The v2 node-space engine as the labels_dirty parity pin."""
    eng = WhatIfEngine(ec, ep, scen, cfg, **kw)
    if eng.engine != "v2":
        eng.engine = "v2"
        eng._dyn = None
        eng._dyn_dev = None
        eng._slot_srcs = None
        eng._chunk_fn = eng._build_chunk_fn()
    return eng


@pytest.mark.slow
def test_labels_dirty_runs_v3_and_matches_v2_and_scratch():
    """Round-3 DynTables: label-perturbation batches stay on the v3 engine
    and must match BOTH the v2 parity engine and a from-scratch replay of
    each explicitly perturbed cluster. Cases: move to an existing value,
    a NEW value (appended domain id), emptying a domain (its last node
    moves out — the spread min must exclude it), a node GAINING the key,
    and mixed taint/capacity perturbations in the same batch."""
    import copy

    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

    cluster = make_cluster(18, seed=5, taint_fraction=0.1)
    zkey = "topology.kubernetes.io/zone"
    # Give one zone exactly one node (emptying case) and strip the key
    # from one node (gaining case).
    cluster.nodes[7].labels[zkey] = "zonly"
    del cluster.nodes[11].labels[zkey]
    pods, _ = make_workload(
        70, seed=5, with_affinity=True, with_spread=True, with_tolerations=True
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = [
        Scenario(),
        Scenario([  # existing value + capacity in one scenario
            Perturbation("set_label", nodes=np.array([0, 4]), key=zkey, value="zone-1"),
            Perturbation("scale_capacity", nodes=np.array([2]), resource="cpu", factor=0.5),
        ]),
        Scenario([  # NEW value → appended domain id
            Perturbation("set_label", nodes=np.array([1, 9]), key=zkey, value="zz-fresh"),
        ]),
        Scenario([  # empty the singleton zone
            Perturbation("set_label", nodes=np.array([7]), key=zkey, value="zone-0"),
        ]),
        Scenario([  # unlabeled node gains the key
            Perturbation("set_label", nodes=np.array([11]), key=zkey, value="zone-2"),
        ]),
        Scenario([  # taint-only scenario sharing the dirty batch
            Perturbation("add_taint", nodes=np.array([5]), key="wi", value="x", effect="NoSchedule"),
        ]),
    ]
    eng = WhatIfEngine(ec, ep, scen, cfg, chunk_waves=4, collect_assignments=True)
    assert eng.engine == "v3" and eng._dyn is not None
    res = eng.run()

    v2 = _force_v2(ec, ep, scen, cfg, chunk_waves=4, collect_assignments=True)
    assert v2.engine == "v2"
    res2 = v2.run()
    np.testing.assert_array_equal(res.assignments, res2.assignments)

    # From-scratch replay of each perturbed cluster (label/taint/capacity
    # applied to a copy, re-encoded) — chunk sizes aligned.
    for si, sc in enumerate(scen):
        c2 = copy.deepcopy(cluster)
        for pt in sc.perturbations:
            for n in np.asarray(pt.nodes).tolist():
                if pt.op == "set_label":
                    c2.nodes[n].labels[pt.key] = pt.value
                elif pt.op == "scale_capacity":
                    c2.nodes[n].allocatable = {
                        k: (v * pt.factor if k == "cpu" else v)
                        for k, v in c2.nodes[n].allocatable.items()
                    }
                elif pt.op == "add_taint":
                    from kubernetes_simulator_tpu.models.core import Taint

                    c2.nodes[n].taints.append(
                        Taint(pt.key, pt.value, pt.effect)
                    )
        ec2, ep2 = encode(c2, pods)
        single = JaxReplayEngine(ec2, ep2, cfg, chunk_waves=4).replay()
        np.testing.assert_array_equal(
            res.assignments[si], single.assignments,
            err_msg=f"scenario {si} diverged from from-scratch replay",
        )


@pytest.mark.slow
def test_labels_dirty_mesh_matches_unsharded():
    """DynTables shard over the scenario axis like every other per-scenario
    tensor: the 8-device mesh run must equal the unsharded batch."""
    ec, ep = small_case(seed=11, n=16, p=64)
    zkey = "topology.kubernetes.io/zone"
    rng = np.random.default_rng(11)
    scen = [Scenario()] + [
        Scenario([
            Perturbation(
                "set_label", nodes=rng.choice(16, 2, replace=False),
                key=zkey, value=f"zone-{rng.integers(0, 8)}",
            )
        ])
        for _ in range(7)
    ]
    cfg = FrameworkConfig()
    plain = WhatIfEngine(ec, ep, scen, cfg, chunk_waves=4, collect_assignments=True)
    assert plain.engine == "v3" and plain._dyn is not None
    res = plain.run()
    sharded = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, collect_assignments=True,
        mesh=make_mesh(),
    )
    assert sharded.engine == "v3" and sharded._dyn is not None
    res2 = sharded.run()
    np.testing.assert_array_equal(res.assignments, res2.assignments)


@pytest.mark.slow
def test_config5_scale_1024_scenarios_mesh():
    """[BASELINE] config #5 at its STATED scenario count: 1024 scenarios
    mesh-sharded over the 8 virtual devices (tiny nodes/pods so the smoke
    stays cheap — the point is exercising S=1024 end-to-end, 128
    scenarios per device, not just divisibility)."""
    assert len(jax.devices()) == 8
    ec, ep = small_case(seed=9, n=12, p=48)
    scen = uniform_scenarios(ec, 1024, seed=9)
    cfg = FrameworkConfig()
    mesh = make_mesh()
    res = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, mesh=mesh
    ).run()
    assert res.placed.shape == (1024,)
    assert int(res.placed[0]) > 0
    # Scenario 0 (unperturbed) equals the single-replay anchor.
    single = JaxReplayEngine(ec, ep, cfg, chunk_waves=4).replay()
    assert int(res.placed[0]) == int(
        (single.assignments[ep.bound_node == -1] >= 0).sum()
    )


def test_labels_dirty_with_completions_device_path():
    """Round 4: labels_dirty × completions — supported by the DEVICE
    release path (per-scenario domain corrections ride the commit
    blocks). Each perturbed scenario's placed count must equal a
    from-scratch replay of the explicitly perturbed cluster with
    completions on; the un-dirty twin batch confirms completions stay
    on (completions_on=True) rather than silently dropping."""
    import copy

    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

    cluster = make_cluster(6, seed=17, taint_fraction=0.1)
    zkey = "topology.kubernetes.io/zone"
    del cluster.nodes[5].labels[zkey]  # gaining case
    pods, _ = make_workload(
        400, seed=17, arrival_rate=40.0, duration_mean=1.5,
        with_spread=True, with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    scen = [
        Scenario(),
        Scenario([  # existing value move
            Perturbation("set_label", nodes=np.array([0, 3]), key=zkey,
                         value="zone-1"),
        ]),
        Scenario([  # NEW value → appended domain id
            Perturbation("set_label", nodes=np.array([2]), key=zkey,
                         value="zz-new"),
        ]),
        Scenario([  # unlabeled node gains the key
            Perturbation("set_label", nodes=np.array([5]), key=zkey,
                         value="zone-0"),
        ]),
    ]
    eng = WhatIfEngine(ec, ep, scen, cfg, chunk_waves=4)
    assert eng.engine == "v3" and eng._dyn is not None
    assert eng.completions_on and eng._completions_dev
    res = eng.run()
    assert res.completions_on

    for si, sc in enumerate(scen):
        c2 = copy.deepcopy(cluster)
        for pt in sc.perturbations:
            for n in np.asarray(pt.nodes).tolist():
                c2.nodes[n].labels[pt.key] = pt.value
        ec2, ep2 = encode(c2, pods)
        single = JaxReplayEngine(ec2, ep2, cfg, chunk_waves=4).replay()
        assert int(res.placed[si]) == single.placed, (
            f"scenario {si}: whatif {int(res.placed[si])} vs "
            f"from-scratch {single.placed}"
        )

    # Non-vacuous: completions change the outcome on this trace.
    off = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=4, completions=False
    ).run()
    assert (off.placed != res.placed).any()
