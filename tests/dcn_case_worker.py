"""Worker + shared case builders for the round-11 DCN parity suite
(tests/test_dcn.py).

Each builder constructs a deterministic workload, runs it, and reduces the
result to a JSON-serializable dict of exact values and content hashes. The
PARENT TEST imports the same builders to compute the single-process oracle,
so any drift between a 2-process DCN run and the single-process mesh run is
a bit-level diff of identical code paths — the parity bar of ISSUE round
11 (process-local folds, one end-of-replay gather).

As a script it is one of KSIM_DCN_NPROC worker processes: it joins the
coordinator through the PRODUCTION entry point (``dcn.maybe_init_from_env``
— the same enable-cache-then-initialize path scripts/dcn_launch.py
children take), runs the cases named in KSIM_DCN_CASES, pins the round-11
counters (zero ``_fetch`` replications, exactly ONE gather per what-if
replay) and prints everything as one JSON line.

Platform env (JAX_PLATFORMS=cpu, --xla_force_host_platform_device_count)
must be set by the parent BEFORE jax import.
"""

import hashlib
import json
import os
import sys
import tempfile


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def _arr_sha(a) -> str:
    """Content hash of an array: dtype + shape + raw little-endian bytes —
    equal hashes ⇔ bit-identical arrays."""
    import numpy as np

    a = np.ascontiguousarray(a)
    return _sha(
        f"{a.dtype.str}:{a.shape}:".encode() + a.tobytes()
    )


def _normalize_jsonl(data: bytes) -> bytes:
    """Strip the round-12 DCN process stamp (``process_id`` /
    ``process_count``) from every row so worker and oracle bytes compare.
    Single-process files have no stamp and round-trip byte-identically
    (JsonlWriter serializes with ``json.dumps`` defaults, as here)."""
    out = []
    for line in data.splitlines():
        row = json.loads(line)
        row.pop("process_id", None)
        row.pop("process_count", None)
        out.append(json.dumps(row).encode())
    return b"\n".join(out) + (b"\n" if out else b"")


def _assert_process_stamp(jsonl: bytes) -> None:
    """Every row of a fleet-written file must carry THIS worker's stamp;
    single-process rows must carry none (byte-compat with pre-round-12)."""
    from kubernetes_simulator_tpu.parallel import dcn

    nproc, pid = dcn.process_info()
    for line in jsonl.splitlines():
        row = json.loads(line)
        if nproc > 1:
            assert row.get("process_id") == pid, row
            assert row.get("process_count") == nproc, row
        else:
            assert "process_id" not in row and "process_count" not in row, row


def _deterministic_jsonl():
    """Context manager forcing KSIM_DETERMINISTIC_JSONL=1 (builders run it
    on BOTH sides so worker and oracle bytes are comparable)."""
    import contextlib

    @contextlib.contextmanager
    def _cm():
        old = os.environ.get("KSIM_DETERMINISTIC_JSONL")
        os.environ["KSIM_DETERMINISTIC_JSONL"] = "1"
        try:
            yield
        finally:
            if old is None:
                del os.environ["KSIM_DETERMINISTIC_JSONL"]
            else:
                os.environ["KSIM_DETERMINISTIC_JSONL"] = old

    return _cm()


# -- case builders (importable by the oracle) ------------------------------


def case_plain():
    """Mesh-sharded what-if with collected assignments, plus the full
    JSONL surface written under KSIM_DETERMINISTIC_JSONL — placed counts,
    assignment matrix, and the JSONL file bytes must all match the
    single-process mesh run (modulo the round-12 process stamp, which is
    asserted in-worker and stripped before hashing). (Boundary retry
    rides the kube chaos case — it is exclusive with
    collect_assignments.)"""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.parallel.mesh import make_mesh
    from kubernetes_simulator_tpu.sim.synthetic import (
        make_cluster,
        make_workload,
    )
    from kubernetes_simulator_tpu.sim.whatif import (
        WhatIfEngine,
        uniform_scenarios,
    )
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter, whatif_rows

    cluster = make_cluster(12, seed=21, taint_fraction=0.2)
    pods, _ = make_workload(
        48, seed=21, with_affinity=True, with_spread=True,
        with_tolerations=True,
    )
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, 8, seed=21, p_capacity=0.5, p_taint=0.3)
    eng = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), mesh=make_mesh(),
        chunk_waves=4, collect_assignments=True,
    )
    res = eng.run()

    with _deterministic_jsonl():
        fd, path = tempfile.mkstemp(suffix=".jsonl")
        os.close(fd)
        try:
            ctx = {"seed": 21, "engine": "v3", "config_hash": "dcn-parity"}
            with JsonlWriter(path, context=ctx) as out:
                for row in whatif_rows(res, {"mesh": True}):
                    out.write(row)
            jsonl = open(path, "rb").read()
        finally:
            os.unlink(path)

    _assert_process_stamp(jsonl)
    return eng, {
        "placed": res.placed.tolist(),
        "unschedulable": res.unschedulable.tolist(),
        "total_placed": int(res.total_placed),
        "assignments_sha": _arr_sha(res.assignments),
        "jsonl_sha": _sha(_normalize_jsonl(jsonl)),
        "jsonl_rows": len(jsonl.splitlines()),
    }


def case_chaos():
    """Kube boundary mode with per-scenario chaos timelines and series
    telemetry on the no-mesh path — exercises the process-LOCAL host
    mirrors and the telemetry leg of the gather payload (per-scenario
    ReplayTelemetry instances ride the pickle; only their
    virtual-time-derived fields are compared — phase timers are
    wall-clock)."""
    import math

    import numpy as np

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(5)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=30.0)
        for i in range(28)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    evs = [
        NodeEvent(time=8.0, kind="node_down", node=0),
        NodeEvent(time=18.0, kind="node_up", node=0),
        NodeEvent(time=24.0, kind="node_down", node=1),
    ]
    scenarios = [
        Scenario(),
        Scenario(events=evs),
        Scenario(events=[NodeEvent(time=25.0, kind="node_down", node=0)]),
        Scenario(events=[NodeEvent(time=4.0, kind="node_down", node=2)]),
    ]
    eng = WhatIfEngine(
        ec, ep, scenarios, cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=64, collect_assignments=True,
        telemetry="series",
    )
    res = eng.run()
    tel = [
        None if t is None else {
            "granularity": t.granularity,
            "latency": t.latency,
            "reasons": t.reasons,
            "rejection_attempts": t.rejection_attempts,
            "zero_latency_binds": t.zero_latency_binds,
            "bind_latency": {
                str(k): v for k, v in (t.bind_latency or {}).items()
            },
        }
        for t in (res.scenario_telemetry or [])
    ]
    return eng, {
        "placed": res.placed.tolist(),
        "evictions": res.evictions.tolist(),
        "evict_rescheduled": res.evict_rescheduled.tolist(),
        "evict_stranded": res.evict_stranded.tolist(),
        "evict_latency_mean": [
            float(x) for x in np.asarray(res.evict_latency_mean)
        ],
        "latency_p50": [
            None if math.isnan(x) else float(x)
            for x in np.asarray(res.latency_p50, np.float64)
        ],
        "assignments_sha": _arr_sha(res.assignments),
        "scenario_count": len(tel),
        "telemetry_sha": _sha(
            json.dumps(tel, sort_keys=True).encode()
        ),
    }


def case_tuner():
    """A small CEM policy search over the mesh — every sweep is a what-if
    replay that gathers objectives once, so the full trajectory (every
    candidate score, every round) must be process-count-independent."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.parallel.mesh import make_mesh
    from kubernetes_simulator_tpu.sim.tuner import PolicyTuner

    nodes = [Node(f"n{i}", capacity={"cpu": 4.0, "memory": 16.0})
             for i in range(4)]
    pods = [
        Pod(f"small-{i}", requests={"cpu": 1.0, "memory": 1.0},
            arrival_time=float(i))
        for i in range(8)
    ] + [
        Pod(f"large-{i}", requests={"cpu": 4.0, "memory": 4.0},
            arrival_time=float(8 + i))
        for i in range(2)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    res = PolicyTuner(
        ec, ep, FrameworkConfig(),
        algo="cem", population=4, rounds=2, seed=0,
        # Flat axes must divide the mesh: train = 4x2 = 8 rows, held-out
        # = 4x2 (winner + default) = 8 rows — both divide 8 devices
        # single-process and 4 local devices per DCN process.
        train_scenarios=2, heldout_scenarios=4, scenario_seed=1,
        p_node_down=0.0, p_capacity=0.25, p_taint=0.0,
        chunk_waves=4, mesh=make_mesh(), cpu_oracle=False,
    ).run()
    return None, {
        "best_policy": res.best_policy,
        "best_vector_sha": _arr_sha(res.best_vector),
        "train_objective": float(res.train_objective),
        "heldout_objective": float(res.heldout_objective),
        "default_heldout_objective": float(res.default_heldout_objective),
        "evaluations": int(res.evaluations),
        "trajectory_sha": _sha(
            json.dumps(res.trajectory, sort_keys=True).encode()
        ),
    }


def case_ckpt():
    """Single-replay kube/chaos run with mid-trace checkpointing: the
    checkpoint BLOB CONTENT (every array, bit-for-bit) and the final
    assignments must match the single-process run. Content hashes rather
    than file bytes: .npz is a zip whose member headers carry wall-clock
    mtimes."""
    import numpy as np

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent

    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(5)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=30.0)
        for i in range(28)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    evs = [
        NodeEvent(time=8.0, kind="node_down", node=0),
        NodeEvent(time=18.0, kind="node_up", node=0),
    ]
    fd, ck = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    os.unlink(ck)
    try:
        res = JaxReplayEngine(
            ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
            retry_buffer=64,
        ).replay(node_events=evs, checkpoint_path=ck, checkpoint_every=8)
        with np.load(ck) as z:
            blob_sha = _sha(
                b"".join(
                    k.encode() + b":" + _arr_sha(z[k]).encode()
                    for k in sorted(z.files)
                )
            )
    finally:
        if os.path.exists(ck):
            os.unlink(ck)
    return None, {
        "checkpoint_sha": blob_sha,
        "placed": int(res.placed),
        "evictions": int(res.evictions),
        "assignments_sha": _arr_sha(res.assignments),
    }


def case_odd():
    """A batch that does NOT divide over the processes (S=7, nproc=2):
    the engine warns and runs fully replicated — every process computes
    all scenarios, no gather fires, ``process_count`` stays 1 — and the
    results still match the single-process run."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.synthetic import (
        make_cluster,
        make_workload,
    )
    from kubernetes_simulator_tpu.sim.whatif import (
        WhatIfEngine,
        uniform_scenarios,
    )

    cluster = make_cluster(8, seed=5)
    pods, _ = make_workload(32, seed=5)
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, 7, seed=5, p_capacity=0.5, p_taint=0.2)
    eng = WhatIfEngine(ec, ep, scenarios, FrameworkConfig(), chunk_waves=4)
    res = eng.run()
    assert not eng._dcn_sliced
    assert eng._replicate_count == 0
    assert res.process_count == 1
    return None, {
        "placed": res.placed.tolist(),
        "unschedulable": res.unschedulable.tolist(),
        "total_placed": int(res.total_placed),
    }


def case_fleetmerge():
    """Round-12 fleet telemetry: kube+series what-if on the no-mesh DCN
    path. The MERGED ``WhatIfResult.fleet_telemetry`` rides the single
    end-of-replay gather, and every virtual-time-derived field — latency
    histogram over the union of first binds, key-wise rejection-counter
    sums, series concatenated in global scenario order — must bit-match
    the single-process oracle. Phase timers are wall-clock, so only their
    key STRUCTURE is pinned in-process: exactly one ``p<pid>/`` namespace
    per fleet member (``p0`` alone on the oracle side)."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.parallel import dcn
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node(f"n{i}", {"cpu": 4.0}) for i in range(4)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=20.0)
        for i in range(24)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    scenarios = [
        Scenario(),
        Scenario(events=[
            NodeEvent(time=6.0, kind="node_down", node=0),
            NodeEvent(time=14.0, kind="node_up", node=0),
        ]),
        Scenario(events=[NodeEvent(time=10.0, kind="node_down", node=1)]),
        Scenario(),
    ]
    eng = WhatIfEngine(
        ec, ep, scenarios, cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=32, telemetry="series",
    )
    res = eng.run()
    ft = res.fleet_telemetry
    assert ft is not None, "fleet_telemetry missing from what-if result"
    nproc, _ = dcn.process_info()
    prefixes = {k.split("/", 1)[0] for k in ft.phases}
    assert prefixes == {f"p{i}" for i in range(max(nproc, 1))}, prefixes
    return eng, {
        "granularity": ft.granularity,
        "latency": ft.latency,
        "reasons": ft.reasons,
        "rejection_attempts": ft.rejection_attempts,
        "zero_latency_binds": int(ft.zero_latency_binds),
        "bind_values": [float(v) for v in ft.bind_latency.values()],
        "series_sha": _sha(
            json.dumps(ft.series, sort_keys=True).encode()
        ),
        "events_len": len(ft.events),
    }


def case_wqmerge():
    """Round-18 work-queue merge case: kube+series what-if on the no-mesh
    DCN path with S=6 — divisible by 1-, 2- and 3-worker fleets and by
    the uneven block sizes the parity suite sweeps. Under the work queue
    the merged fleet telemetry keeps the EXECUTING processes' ``p<pid>/``
    phase namespaces (whoever won each block) with ``wq_block`` markers;
    statically it is exactly one namespace per process. Either way every
    virtual-time-derived payload field must bit-match the
    single-process oracle."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.parallel import dcn
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node(f"n{i}", {"cpu": 4.0}) for i in range(4)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=20.0)
        for i in range(24)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    scenarios = []
    for s in range(6):
        if s % 3 == 1:
            scenarios.append(Scenario(events=[
                NodeEvent(time=4.0 + s, kind="node_down", node=s % 4),
                NodeEvent(time=12.0 + s, kind="node_up", node=s % 4),
            ]))
        elif s % 3 == 2:
            scenarios.append(Scenario(events=[
                NodeEvent(time=6.0 + s, kind="node_down", node=(s + 1) % 4),
            ]))
        else:
            scenarios.append(Scenario())
    eng = WhatIfEngine(
        ec, ep, scenarios, cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=32, telemetry="series",
    )
    res = eng.run()
    ft = res.fleet_telemetry
    assert ft is not None, "fleet_telemetry missing from what-if result"
    nproc, _ = dcn.process_info()
    prefixes = {k.split("/", 1)[0] for k in ft.phases}
    if nproc > 1 and dcn.wq_enabled():
        # Phase timers keep the EXECUTING process's namespace (whoever
        # won each block) — a subset of the fleet when one process
        # drains several blocks — and the block executors stamp
        # wq_block markers.
        assert any(k.endswith("/wq_block") for k in ft.phases), (
            "work-queue run lost its wq_block phase attribution"
        )
        assert prefixes and prefixes <= {
            f"p{i}" for i in range(nproc)
        }, prefixes
    else:
        assert prefixes == {f"p{i}" for i in range(max(nproc, 1))}, prefixes
    return eng, {
        "granularity": ft.granularity,
        "latency": ft.latency,
        "reasons": ft.reasons,
        "rejection_attempts": ft.rejection_attempts,
        "zero_latency_binds": int(ft.zero_latency_binds),
        "bind_values": [float(v) for v in ft.bind_latency.values()],
        "series_sha": _sha(
            json.dumps(ft.series, sort_keys=True).encode()
        ),
        "events_len": len(ft.events),
    }


def case_wqfork():
    """Round-18 work-queue over the node-sharded (round-14) leg: every
    scenario forks from a checkpoint written by a
    ``JaxReplayEngine(node_shards=2)`` replay, then the S=6 what-if batch
    runs (under the queue when enabled) — placements and the collected
    assignment matrix must bit-match the single-process oracle."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.synthetic import (
        make_cluster,
        make_workload,
    )
    from kubernetes_simulator_tpu.sim.whatif import (
        Scenario,
        WhatIfEngine,
        uniform_scenarios,
    )

    cluster = make_cluster(10, seed=18)
    pods, _ = make_workload(80, seed=18, with_affinity=True, with_spread=True)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    fd, ck = tempfile.mkstemp(suffix=".npz")
    os.close(fd)
    os.unlink(ck)
    try:
        JaxReplayEngine(
            ec, ep, cfg, chunk_waves=5, node_shards=2,
        ).replay(checkpoint_path=ck, checkpoint_every=2)
        scenarios = [Scenario()] + list(
            uniform_scenarios(ec, 5, seed=18, p_capacity=0.5, p_taint=0.2)
        )
        eng = WhatIfEngine(
            ec, ep, scenarios, cfg, chunk_waves=5,
            collect_assignments=True, fork_checkpoint=ck,
        )
        res = eng.run()
    finally:
        if os.path.exists(ck):
            os.unlink(ck)
    return eng, {
        "placed": res.placed.tolist(),
        "unschedulable": res.unschedulable.tolist(),
        "total_placed": int(res.total_placed),
        "assignments_sha": _arr_sha(res.assignments),
    }


CASES = {
    "plain": case_plain,
    "chaos": case_chaos,
    "tuner": case_tuner,
    "ckpt": case_ckpt,
    "odd": case_odd,
    "fleetmerge": case_fleetmerge,
    "wqmerge": case_wqmerge,
    "wqfork": case_wqfork,
}


def run_cases(names, expect_dcn: bool):
    """Run the named cases in order, pinning the round-11 counters:
    zero cross-process ``_fetch`` replications ever, and under DCN exactly
    ONE gather per what-if replay (the tuner runs one replay per sweep)."""
    from kubernetes_simulator_tpu.parallel import dcn

    out = {}
    for name in names:
        g0 = dcn.GATHER_COUNT
        eng, payload = CASES[name]()
        delta = dcn.GATHER_COUNT - g0
        if eng is not None:
            assert eng._replicate_count == 0, (
                f"{name}: cross-process _fetch replication in chunk loop"
            )
            want = 1 if expect_dcn else 0
            assert delta == want, (
                f"{name}: {delta} gathers per replay, want {want}"
            )
        elif not expect_dcn:
            assert delta == 0, f"{name}: gathered in single-process run"
        out[name] = payload
    return out


def _arm_selfkill() -> None:
    """KSIM_DCN_SELFKILL_AT_CHUNK=<n> (round-12 killed-worker test): die
    with SIGKILL right after publishing the first heartbeat whose chunk
    cursor reaches <n>, simulating a worker lost mid-replay. Survivors
    must then fail FAST out of the gather with an attributed
    DcnGatherTimeout naming this pid and its last completed chunk."""
    at = os.environ.get("KSIM_DCN_SELFKILL_AT_CHUNK")
    if at is None:
        return
    import signal

    from kubernetes_simulator_tpu.parallel import dcn

    threshold = int(at)
    real = dcn.heartbeat

    def _hb(chunk, *a, **kw):
        ok = real(chunk, *a, **kw)
        if int(chunk) >= threshold:
            os.kill(os.getpid(), signal.SIGKILL)
        return ok

    dcn.heartbeat = _hb


def main() -> None:
    import jax

    from kubernetes_simulator_tpu.parallel import dcn

    assert dcn.maybe_init_from_env(), "KSIM_DCN_* env not set"
    _arm_selfkill()
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)
    nproc, pid = dcn.process_info()
    assert nproc == int(os.environ["KSIM_DCN_NPROC"]), nproc
    assert jax.device_count() == len(jax.local_devices()) * nproc

    names = os.environ["KSIM_DCN_CASES"].split(",")
    out = run_cases(names, expect_dcn=True)
    print("DCN_CASES_RESULT " + json.dumps(out), flush=True)


if __name__ == "__main__":
    sys.exit(main())
