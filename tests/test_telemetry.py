"""Telemetry layer (SURVEY.md §5): per-pod latency histograms,
filter-rejection attribution, virtual-time series, phase timers and the
Chrome-trace exporter.

The cross-engine contracts under test: at W=1 / C=1 on queue-trivial
traces the CPU event engine and the device path produce bit-identical
latency summaries and per-episode rejection reasons (the device is
chunk-granular but the crafted instants coincide); ``summary``
granularity never changes a device program; telemetry state never leaks
into checkpoint blobs."""

import json

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod, Taint
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_chaos_timeline
from kubernetes_simulator_tpu.sim.telemetry import (
    PHASE_NAMES,
    TelemetryConfig,
    latency_summary,
    write_chrome_trace,
)
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])


def _light_trace(num_pods=28, num_nodes=5, duration=30.0, seed=None):
    """Queue-trivial parity envelope (tests/test_chaos.py twin)."""
    rng = np.random.default_rng(seed) if seed is not None else None
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(num_nodes)]
    pods = []
    for i in range(num_pods):
        d = duration if rng is None else float(rng.integers(30, 61))
        pods.append(
            Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
                duration=d)
        )
    return encode(Cluster(nodes=nodes), pods)


# -- config / units -------------------------------------------------------


def test_granularity_validation():
    assert TelemetryConfig.resolve(None).granularity == "summary"
    assert TelemetryConfig.resolve("off").enabled is False
    assert TelemetryConfig.resolve("series").want_series
    assert not TelemetryConfig.resolve("series").want_timeline
    assert TelemetryConfig.resolve("timeline").want_timeline
    with pytest.raises(ValueError, match="granularity"):
        TelemetryConfig.resolve("verbose")


def test_latency_summary_exact():
    s = latency_summary(3, [0.5, 1.0, 4.0, 600.0])
    assert s["count"] == 7
    assert s["max"] == 600.0
    # method="lower" quantiles are exact data values (sorted multiset is
    # [0, 0, 0, 0.5, 1, 4, 600]; p99 index floors to 4.0 at n=7).
    assert s["p50"] == 0.5
    assert s["p99"] == 4.0
    assert s["buckets"]["le_0"] == 3
    assert s["buckets"]["le_0.5"] == 4
    assert s["buckets"]["le_4"] == 6
    assert s["buckets"]["le_512"] == 6  # 600 overflows every finite edge
    assert s["buckets"]["le_inf"] == 7
    assert latency_summary(0, []) is None


# -- engine off/summary behavior -----------------------------------------


def test_off_granularity_yields_none():
    ec, ep = _light_trace(num_pods=6, num_nodes=2)
    assert CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="off").replay(
    ).telemetry is None
    assert JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, telemetry="off"
    ).replay().telemetry is None


def test_default_summary_attached_both_engines():
    ec, ep = _light_trace(num_pods=6, num_nodes=2)
    for res in (
        CpuReplayEngine(ec, ep, FIT_ONLY()).replay(),
        JaxReplayEngine(ec, ep, FIT_ONLY(), wave_width=1,
                        chunk_waves=1).replay(),
    ):
        t = res.telemetry
        assert t is not None and t.granularity == "summary"
        assert t.latency["count"] == res.placed
        assert t.reasons is None  # series-only signal
        assert t.phases  # timers ran
        assert "telemetry" in res.summary()


def test_phase_timer_names_stable():
    """The instrumented phase names are API — scripts/northstar.py and
    bench consumers attribute wall-clock by these exact strings. The
    canonical tuple is PHASE_NAMES; a boundary-mode device replay must
    emit exactly that set (a rename or a new un-registered phase fails
    here first)."""
    assert PHASE_NAMES == (
        "dispatch", "device_wait", "boundary_fold", "host_mirror"
    )
    ec, ep = _light_trace(duration=10.0)  # releases fire inside the run
    res = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay()
    assert set(res.telemetry.phases) == set(PHASE_NAMES)


# -- rejection attribution parity (plain path, in-scan counters) ----------


def _reject_trace(num_pods=10):
    """n0 (cpu=2) fills after two pods; n1 is big but tainted NoSchedule.
    Every later pod fails with a two-plugin breakdown: NodeResourcesFit
    is charged n0 (first in Filter order), TaintToleration n1."""
    nodes = [
        Node("n0", {"cpu": 2.0}),
        Node("n1", {"cpu": 100.0},
             taints=[Taint("dedicated", "infra", "NoSchedule")]),
    ]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i))
        for i in range(num_pods)
    ]
    return encode(Cluster(nodes=nodes), pods)


@pytest.mark.parametrize("engine", ["v2", "v3"])
def test_plain_rejection_attribution_matches_cpu(engine):
    """Device in-scan [K] reject counters (series granularity) bit-match
    the CPU event engine's per-episode reasons at W=1/C=1 — including the
    v3 path, which swaps in the v2-reference instrumented program."""
    ec, ep = _reject_trace()
    cfg = FrameworkConfig()
    cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay()
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, engine=engine,
        telemetry="series",
    ).replay()
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    assert cpu.telemetry.reasons == dev.telemetry.reasons
    assert cpu.telemetry.reasons == {
        "NodeResourcesFit": 8, "TaintToleration": 8,
    }
    # Episode semantics: CPU backoff retries grow attempts, never reasons.
    assert sum(cpu.telemetry.rejection_attempts.values()) >= sum(
        cpu.telemetry.reasons.values()
    )
    # Plain-path device failures are terminal: attempts == reasons.
    assert dev.telemetry.rejection_attempts == dev.telemetry.reasons
    assert cpu.telemetry.latency == dev.telemetry.latency


def test_summary_granularity_keeps_device_program():
    """The default granularity must never swap in the instrumented chunk
    program (bench safety): the engine reuses the plain chunk_fn and the
    placements equal the off-telemetry run."""
    ec, ep = _reject_trace()
    eng = JaxReplayEngine(
        ec, ep, FrameworkConfig(), wave_width=1, chunk_waves=1,
        telemetry="summary",
    )
    res = eng.replay()
    assert not hasattr(eng, "_chunk_fn_rej")  # never built
    off = JaxReplayEngine(
        ec, ep, FrameworkConfig(), wave_width=1, chunk_waves=1,
        telemetry="off",
    ).replay()
    np.testing.assert_array_equal(res.assignments, off.assignments)


# -- boundary-retry latency parity ---------------------------------------


def test_boundary_retry_latency_matches_cpu():
    """Crafted coincidence trace: p1 fails at t=1 (node full), the slot
    frees at t=1.5, the CPU backoff expiry (1 + 1.0) and the device chunk
    boundary (arrival of p2) both land at t=2 → both engines record the
    SAME latency multiset {0, 0, 1.0} and one failed attempt."""
    nodes = [Node("n0", {"cpu": 1.0})]
    pods = [
        Pod("p0", requests={"cpu": 1.0}, arrival_time=0.0, duration=1.5),
        Pod("p1", requests={"cpu": 1.0}, arrival_time=1.0),
        Pod("p2", requests={"cpu": 0.0}, arrival_time=2.0),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FIT_ONLY()
    cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay()
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, retry_buffer=8,
        telemetry="series",
    ).replay()
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    for t in (cpu.telemetry, dev.telemetry):
        assert t.latency["count"] == 3
        assert t.zero_latency_binds == 2
        assert t.bind_latency == {1: 1.0}
        assert t.reasons == {"NodeResourcesFit": 1}
        assert t.rejection_attempts == {"NodeResourcesFit": 1}
    assert cpu.telemetry.latency == dev.telemetry.latency


@pytest.mark.fuzz_quick
def test_seeded_chaos_telemetry_parity():
    """Chaos fuzz slice (tests/test_chaos.py twin at series granularity):
    seeded queue-trivial traces with mttr=0 timelines must hold latency-
    histogram AND rejection-reason parity bit-for-bit alongside the
    existing assignment/eviction parity."""
    cfg = FIT_ONLY()
    evictions = 0
    for seed in (1, 2, 3):
        ec, ep = _light_trace(num_pods=28, num_nodes=6, seed=seed)
        evs = make_chaos_timeline(
            ec.num_nodes, seed=seed, horizon=float(ep.arrival.max()),
            mtbf=12.0, mttr=0.0, node_fraction=0.34,
        )
        cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay(
            node_events=evs
        )
        dev = JaxReplayEngine(
            ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
            retry_buffer=64, telemetry="series",
        ).replay(node_events=evs)
        np.testing.assert_array_equal(cpu.assignments, dev.assignments)
        assert cpu.telemetry.latency == dev.telemetry.latency, f"seed {seed}"
        assert cpu.telemetry.reasons == dev.telemetry.reasons, f"seed {seed}"
        evictions += dev.evictions
    assert evictions > 0  # non-vacuous


# -- checkpoint purity ----------------------------------------------------


def test_checkpoint_blob_identical_with_telemetry(tmp_path):
    """Telemetry state is NOT checkpoint state: boundary-mode blobs are
    bit-identical with telemetry off vs timeline."""
    ec, ep = _light_trace(num_pods=24, num_nodes=4)
    blobs = {}
    for gran in ("off", "timeline"):
        ck = str(tmp_path / f"ck_{gran}.npz")
        JaxReplayEngine(
            ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=4,
            preemption="kube", retry_buffer=64, telemetry=gran,
        ).replay(checkpoint_path=ck, checkpoint_every=3)
        blobs[gran] = np.load(ck, allow_pickle=True)
    off, tl = blobs["off"], blobs["timeline"]
    assert sorted(off.files) == sorted(tl.files)
    for k in off.files:
        np.testing.assert_array_equal(off[k], tl[k])


# -- what-if per-scenario latency ----------------------------------------


def test_whatif_kube_scenario_latency_quantiles():
    """Kube batches expose per-scenario latency quantiles; the clean
    scenario equals the single-replay telemetry, and the plain batch
    reports None."""
    ec, ep = _light_trace(num_pods=20, num_nodes=4)
    cfg = FIT_ONLY()
    evs = [e for e in make_chaos_timeline(
        ec.num_nodes, seed=7, horizon=float(ep.arrival.max()),
        mtbf=10.0, mttr=0.0, node_fraction=0.5,
    )]
    single = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay()
    res = WhatIfEngine(
        ec, ep, [Scenario(), Scenario(events=evs)], cfg, wave_width=1,
        chunk_waves=1, preemption="kube", retry_buffer=64,
        telemetry="series",
    ).run()
    assert res.latency_p50.shape == (2,)
    st = single.telemetry.latency
    assert float(res.latency_p50[0]) == st["p50"]
    assert float(res.latency_p99[0]) == st["p99"]
    assert res.scenario_telemetry[1].latency["count"] > 0
    plain = WhatIfEngine(ec, ep, [Scenario()], cfg, chunk_waves=4).run()
    assert plain.latency_p50 is None and plain.scenario_telemetry is None


# -- chrome trace exporter ------------------------------------------------


def test_chrome_trace_export(tmp_path):
    ec, ep = _light_trace(num_pods=12, num_nodes=3)
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent

    evs = [
        NodeEvent(time=4.0, kind="node_down", node=0),
        NodeEvent(time=9.0, kind="node_up", node=0),
    ]
    res = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="timeline").replay(
        node_events=evs
    )
    path = str(tmp_path / "trace.json")
    n = write_chrome_trace(path, res, arrival=ep.arrival, duration=ep.duration)
    with open(path) as f:
        doc = json.load(f)
    ev = doc["traceEvents"]
    assert len(ev) == n > 0
    phases = {e["ph"] for e in ev}
    assert "X" in phases and "M" in phases
    names = {e["name"] for e in ev}
    assert "node0 down" in names  # chaos span got stitched
    # Every pod span sits on the node it was bound to.
    for e in ev:
        if e["ph"] == "X" and e.get("pid") == 0 and e["name"].startswith("pod"):
            p = int(e["name"][3:])
            assert e["tid"] == int(res.assignments[p])


def test_series_attribution_fallback_notes(caplog, tmp_path):
    """series+ attribution fallback pin: in-scan tier preemption and
    checkpoint/resume each disable the instrumented chunk program with a
    log note — placements stay unchanged and latency/phase telemetry is
    still collected; only ``reasons`` goes dark."""
    import logging

    ec, ep = _reject_trace()
    cfg = FrameworkConfig()
    # Tier preemption: the instrumented program has no tier planes.
    ref = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                          preemption=True, telemetry="summary").replay()
    with caplog.at_level(logging.INFO, logger="k8sim"):
        res = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                              preemption=True, telemetry="series").replay()
    assert "not available with in-scan tier preemption" in caplog.text
    np.testing.assert_array_equal(ref.assignments, res.assignments)
    assert res.telemetry is not None and not res.telemetry.reasons
    assert res.telemetry.latency["count"] == res.placed
    # Checkpointing: the instrumented carry is not part of checkpoints.
    caplog.clear()
    plain = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                            telemetry="series").replay()
    with caplog.at_level(logging.INFO, logger="k8sim"):
        ck = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                             telemetry="series").replay(
            checkpoint_path=str(tmp_path / "ck.npz"), checkpoint_every=2,
        )
    assert "disabled under checkpoint/resume" in caplog.text
    np.testing.assert_array_equal(plain.assignments, ck.assignments)
    assert ck.telemetry is not None and not ck.telemetry.reasons
    assert plain.telemetry.reasons is not None  # instrumented run still works


# -- round 12: mergeable telemetry / fleet observability -------------------


def _mk_tel(vals, zero, reasons=None, attempts=None, series=None,
            phases=None, events=(), gran="series"):
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    t = ReplayTelemetry(
        granularity=gran,
        latency=latency_summary(zero, vals),
        phases=dict(phases or {}),
        bind_latency={i: v for i, v in enumerate(vals)},
        zero_latency_binds=zero,
    )
    t.reasons = reasons
    t.rejection_attempts = attempts
    t.series = series
    t.events = list(events)
    return t


def test_merge_partition_bit_parity():
    """The merge contract: merging disjoint halves reproduces EXACTLY the
    telemetry of the union — histogram, counters, raw values, series."""
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    a = _mk_tel([1.0, 4.0], 2, reasons={"A": 2}, attempts={"A": 3},
                series={"t": [0.0, 1.0], "queue": [1.0, 0.0]},
                phases={"dispatch": 0.5})
    b = _mk_tel([0.5], 1, reasons={"B": 1}, attempts={"A": 1, "B": 1},
                series={"t": [2.0], "queue": [2.0]},
                phases={"dispatch": 0.25, "device_wait": 0.1})
    whole = _mk_tel([1.0, 4.0, 0.5], 3, reasons={"A": 2, "B": 1},
                    attempts={"A": 4, "B": 1},
                    series={"t": [0.0, 1.0, 2.0], "queue": [1.0, 0.0, 2.0]})
    m = ReplayTelemetry.merge([a, b])
    assert m.latency == whole.latency
    assert m.reasons == whole.reasons
    assert m.rejection_attempts == whole.rejection_attempts
    assert m.series == whole.series
    assert m.zero_latency_binds == 3
    assert m.bind_latency == {0: 1.0, 1: 4.0, 2: 0.5}
    # Same-process merge (no process_ids): phase timers key-wise summed.
    assert m.phases == {"dispatch": 0.75, "device_wait": 0.1}


def test_merge_process_phase_namespaces():
    """With process_ids the wall clocks of different hosts stay DISTINCT
    (p<pid>/<phase>), and re-merging a merge never double-prefixes."""
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    a = _mk_tel([1.0], 0, phases={"dispatch": 0.5})
    b = _mk_tel([2.0], 0, phases={"dispatch": 0.25, "device_wait": 0.1})
    m = ReplayTelemetry.merge([a, b], process_ids=[0, 1])
    assert m.phases == {
        "p0/dispatch": 0.5, "p1/dispatch": 0.25, "p1/device_wait": 0.1,
    }
    # Latency is identical to the unprefixed merge (phases never feed it).
    assert m.latency == ReplayTelemetry.merge([a, b]).latency
    m2 = ReplayTelemetry.merge([m], process_ids=[7])
    assert m2.phases == m.phases  # "/" keys pass through unprefixed


def test_merge_edge_cases():
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    assert ReplayTelemetry.merge([]) is None
    assert ReplayTelemetry.merge([None, None]) is None
    a = _mk_tel([1.0], 0)
    # None parts are skipped, not counted.
    m = ReplayTelemetry.merge([None, a, None], process_ids=[0, 1, 2])
    assert m.latency["count"] == 1
    b = _mk_tel([], 0, gran="summary")
    with pytest.raises(ValueError, match="granularity"):
        ReplayTelemetry.merge([a, b])
    with pytest.raises(ValueError, match="process_ids"):
        ReplayTelemetry.merge([a], process_ids=[0, 1])
    # summary-granularity parts carry no counters/series: stays None.
    c = _mk_tel([2.0], 1, gran="summary")
    m = ReplayTelemetry.merge([b, c])
    assert m.reasons is None and m.series is None
    assert m.latency["count"] == 2


def test_merge_associative_on_results():
    """Partitioning 3 parts as (a+b)+c or a+(b+c) or all-at-once gives
    the same virtual-time-derived telemetry (the DCN fleet merge relies
    on this: per-process merges happen first, the gather merge second)."""
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    a = _mk_tel([1.0, 8.0], 1, reasons={"A": 1})
    b = _mk_tel([0.25], 0, reasons={"B": 2})
    c = _mk_tel([16.0], 2, reasons={"A": 3})
    flat = ReplayTelemetry.merge([a, b, c])
    left = ReplayTelemetry.merge([ReplayTelemetry.merge([a, b]), c])
    right = ReplayTelemetry.merge([a, ReplayTelemetry.merge([b, c])])
    for m in (left, right):
        assert m.latency == flat.latency
        assert m.reasons == flat.reasons
        assert m.bind_latency == flat.bind_latency
        assert m.zero_latency_binds == flat.zero_latency_binds


def test_whatif_fleet_telemetry_single_process():
    """Every what-if result now carries a merged fleet view: engine-level
    phase timers under the p0/ namespace (single process) and a latency
    histogram equal to the merge of the per-scenario telemetries."""
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    ec, ep = _light_trace(num_pods=20, num_nodes=4)
    res = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], FIT_ONLY(), wave_width=1,
        chunk_waves=1, preemption="kube", retry_buffer=64,
        telemetry="series",
    ).run()
    ft = res.fleet_telemetry
    assert ft is not None
    assert ft.granularity == "series"
    assert all(k.startswith("p0/") for k in ft.phases)
    assert {k.split("/", 1)[1] for k in ft.phases} <= set(PHASE_NAMES)
    oracle = ReplayTelemetry.merge(res.scenario_telemetry)
    assert ft.latency == oracle.latency
    assert ft.reasons == oracle.reasons
    # Plain batches (no per-scenario telemetry) still get the phase view.
    plain = WhatIfEngine(
        ec, ep, [Scenario()], FIT_ONLY(), chunk_waves=4,
    ).run()
    assert plain.fleet_telemetry is not None
    assert plain.fleet_telemetry.latency is None
    assert any(k.startswith("p0/") for k in plain.fleet_telemetry.phases)


def test_chrome_trace_merged_track_groups(tmp_path):
    """write_chrome_trace_merged renders one track group PER PROCESS
    (pids 2p/2p+1, suffixed names) while the single-result exporter keeps
    the pre-round-12 pid 0/1 layout byte-for-byte."""
    from kubernetes_simulator_tpu.sim.telemetry import (
        write_chrome_trace_merged,
    )

    ec, ep = _light_trace(num_pods=8, num_nodes=2)
    res = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="timeline").replay()
    single = str(tmp_path / "single.json")
    write_chrome_trace(single, res, arrival=ep.arrival, duration=ep.duration)
    with open(single) as f:
        names = {
            (e["pid"], e["args"]["name"])
            for e in json.load(f)["traceEvents"]
            if e["name"] == "process_name"
        }
    assert names == {(0, "cluster"), (1, "chaos")}

    merged = str(tmp_path / "merged.json")
    n = write_chrome_trace_merged(
        merged,
        [(res, ep.arrival, ep.duration), (res, ep.arrival, ep.duration)],
    )
    with open(merged) as f:
        ev = json.load(f)["traceEvents"]
    assert len(ev) == n
    names = {
        (e["pid"], e["args"]["name"])
        for e in ev if e["name"] == "process_name"
    }
    assert names == {
        (0, "cluster (p0)"), (1, "chaos (p0)"),
        (2, "cluster (p1)"), (3, "chaos (p1)"),
    }
    # Pod spans land inside their process's track group.
    assert {e["pid"] for e in ev if e["name"].startswith("pod")} == {0, 2}


def test_profiler_annotations_bit_parity(tmp_path, monkeypatch):
    """KSIM_PROFILE_DIR arms TraceAnnotation markers on every phase tick
    and chunk dispatch — results must stay bit-identical with the hooks
    on (no active trace needed: annotations outside a trace are no-ops)."""
    from kubernetes_simulator_tpu.utils import profiling

    monkeypatch.delenv("KSIM_PROFILE_DIR", raising=False)
    assert not profiling.profiling_active()
    ec, ep = _light_trace(num_pods=16, num_nodes=4)
    cfg = FIT_ONLY()
    off = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64, telemetry="series",
    ).replay()
    woff = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=64, telemetry="series",
    ).run()
    monkeypatch.setenv("KSIM_PROFILE_DIR", str(tmp_path))
    assert profiling.profiling_active()
    on = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64, telemetry="series",
    ).replay()
    won = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=64, telemetry="series",
    ).run()
    np.testing.assert_array_equal(off.assignments, on.assignments)
    assert off.telemetry.latency == on.telemetry.latency
    np.testing.assert_array_equal(woff.placed, won.placed)
    np.testing.assert_array_equal(
        np.asarray(woff.latency_p50, np.float64),
        np.asarray(won.latency_p50, np.float64),
    )


def test_live_buffer_stats_gauge():
    from kubernetes_simulator_tpu.utils.profiling import live_buffer_stats

    s = live_buffer_stats()
    assert isinstance(s.get("count"), int) and s["count"] >= 0
    assert isinstance(s.get("bytes"), int) and s["bytes"] >= 0
