"""Telemetry layer (SURVEY.md §5): per-pod latency histograms,
filter-rejection attribution, virtual-time series, phase timers and the
Chrome-trace exporter.

The cross-engine contracts under test: at W=1 / C=1 on queue-trivial
traces the CPU event engine and the device path produce bit-identical
latency summaries and per-episode rejection reasons (the device is
chunk-granular but the crafted instants coincide); ``summary``
granularity never changes a device program; telemetry state never leaks
into checkpoint blobs."""

import json

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod, Taint
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_chaos_timeline
from kubernetes_simulator_tpu.sim.telemetry import (
    PHASE_NAMES,
    TelemetryConfig,
    latency_summary,
    write_chrome_trace,
)
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])


def _light_trace(num_pods=28, num_nodes=5, duration=30.0, seed=None):
    """Queue-trivial parity envelope (tests/test_chaos.py twin)."""
    rng = np.random.default_rng(seed) if seed is not None else None
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(num_nodes)]
    pods = []
    for i in range(num_pods):
        d = duration if rng is None else float(rng.integers(30, 61))
        pods.append(
            Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
                duration=d)
        )
    return encode(Cluster(nodes=nodes), pods)


# -- config / units -------------------------------------------------------


def test_granularity_validation():
    assert TelemetryConfig.resolve(None).granularity == "summary"
    assert TelemetryConfig.resolve("off").enabled is False
    assert TelemetryConfig.resolve("series").want_series
    assert not TelemetryConfig.resolve("series").want_timeline
    assert TelemetryConfig.resolve("timeline").want_timeline
    with pytest.raises(ValueError, match="granularity"):
        TelemetryConfig.resolve("verbose")


def test_latency_summary_exact():
    s = latency_summary(3, [0.5, 1.0, 4.0, 600.0])
    assert s["count"] == 7
    assert s["max"] == 600.0
    # method="lower" quantiles are exact data values (sorted multiset is
    # [0, 0, 0, 0.5, 1, 4, 600]; p99 index floors to 4.0 at n=7).
    assert s["p50"] == 0.5
    assert s["p99"] == 4.0
    assert s["buckets"]["le_0"] == 3
    assert s["buckets"]["le_0.5"] == 4
    assert s["buckets"]["le_4"] == 6
    assert s["buckets"]["le_512"] == 6  # 600 overflows every finite edge
    assert s["buckets"]["le_inf"] == 7
    assert latency_summary(0, []) is None


# -- engine off/summary behavior -----------------------------------------


def test_off_granularity_yields_none():
    ec, ep = _light_trace(num_pods=6, num_nodes=2)
    assert CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="off").replay(
    ).telemetry is None
    assert JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, telemetry="off"
    ).replay().telemetry is None


def test_default_summary_attached_both_engines():
    ec, ep = _light_trace(num_pods=6, num_nodes=2)
    for res in (
        CpuReplayEngine(ec, ep, FIT_ONLY()).replay(),
        JaxReplayEngine(ec, ep, FIT_ONLY(), wave_width=1,
                        chunk_waves=1).replay(),
    ):
        t = res.telemetry
        assert t is not None and t.granularity == "summary"
        assert t.latency["count"] == res.placed
        assert t.reasons is None  # series-only signal
        assert t.phases  # timers ran
        assert "telemetry" in res.summary()


def test_phase_timer_names_stable():
    """The instrumented phase names are API — scripts/northstar.py and
    bench consumers attribute wall-clock by these exact strings. The
    canonical tuple is PHASE_NAMES; a boundary-mode device replay must
    emit exactly that set (a rename or a new un-registered phase fails
    here first)."""
    assert PHASE_NAMES == (
        "dispatch", "device_wait", "boundary_fold", "host_mirror"
    )
    ec, ep = _light_trace(duration=10.0)  # releases fire inside the run
    res = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay()
    assert set(res.telemetry.phases) == set(PHASE_NAMES)


# -- rejection attribution parity (plain path, in-scan counters) ----------


def _reject_trace(num_pods=10):
    """n0 (cpu=2) fills after two pods; n1 is big but tainted NoSchedule.
    Every later pod fails with a two-plugin breakdown: NodeResourcesFit
    is charged n0 (first in Filter order), TaintToleration n1."""
    nodes = [
        Node("n0", {"cpu": 2.0}),
        Node("n1", {"cpu": 100.0},
             taints=[Taint("dedicated", "infra", "NoSchedule")]),
    ]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i))
        for i in range(num_pods)
    ]
    return encode(Cluster(nodes=nodes), pods)


@pytest.mark.parametrize("engine", ["v2", "v3"])
def test_plain_rejection_attribution_matches_cpu(engine):
    """Device in-scan [K] reject counters (series granularity) bit-match
    the CPU event engine's per-episode reasons at W=1/C=1 — including the
    v3 path, which swaps in the v2-reference instrumented program."""
    ec, ep = _reject_trace()
    cfg = FrameworkConfig()
    cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay()
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, engine=engine,
        telemetry="series",
    ).replay()
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    assert cpu.telemetry.reasons == dev.telemetry.reasons
    assert cpu.telemetry.reasons == {
        "NodeResourcesFit": 8, "TaintToleration": 8,
    }
    # Episode semantics: CPU backoff retries grow attempts, never reasons.
    assert sum(cpu.telemetry.rejection_attempts.values()) >= sum(
        cpu.telemetry.reasons.values()
    )
    # Plain-path device failures are terminal: attempts == reasons.
    assert dev.telemetry.rejection_attempts == dev.telemetry.reasons
    assert cpu.telemetry.latency == dev.telemetry.latency


def test_summary_granularity_keeps_device_program():
    """The default granularity must never swap in the instrumented chunk
    program (bench safety): the engine reuses the plain chunk_fn and the
    placements equal the off-telemetry run."""
    ec, ep = _reject_trace()
    eng = JaxReplayEngine(
        ec, ep, FrameworkConfig(), wave_width=1, chunk_waves=1,
        telemetry="summary",
    )
    res = eng.replay()
    assert not hasattr(eng, "_chunk_fn_rej")  # never built
    off = JaxReplayEngine(
        ec, ep, FrameworkConfig(), wave_width=1, chunk_waves=1,
        telemetry="off",
    ).replay()
    np.testing.assert_array_equal(res.assignments, off.assignments)


# -- boundary-retry latency parity ---------------------------------------


def test_boundary_retry_latency_matches_cpu():
    """Crafted coincidence trace: p1 fails at t=1 (node full), the slot
    frees at t=1.5, the CPU backoff expiry (1 + 1.0) and the device chunk
    boundary (arrival of p2) both land at t=2 → both engines record the
    SAME latency multiset {0, 0, 1.0} and one failed attempt."""
    nodes = [Node("n0", {"cpu": 1.0})]
    pods = [
        Pod("p0", requests={"cpu": 1.0}, arrival_time=0.0, duration=1.5),
        Pod("p1", requests={"cpu": 1.0}, arrival_time=1.0),
        Pod("p2", requests={"cpu": 0.0}, arrival_time=2.0),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FIT_ONLY()
    cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay()
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, retry_buffer=8,
        telemetry="series",
    ).replay()
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    for t in (cpu.telemetry, dev.telemetry):
        assert t.latency["count"] == 3
        assert t.zero_latency_binds == 2
        assert t.bind_latency == {1: 1.0}
        assert t.reasons == {"NodeResourcesFit": 1}
        assert t.rejection_attempts == {"NodeResourcesFit": 1}
    assert cpu.telemetry.latency == dev.telemetry.latency


@pytest.mark.fuzz_quick
def test_seeded_chaos_telemetry_parity():
    """Chaos fuzz slice (tests/test_chaos.py twin at series granularity):
    seeded queue-trivial traces with mttr=0 timelines must hold latency-
    histogram AND rejection-reason parity bit-for-bit alongside the
    existing assignment/eviction parity."""
    cfg = FIT_ONLY()
    evictions = 0
    for seed in (1, 2, 3):
        ec, ep = _light_trace(num_pods=28, num_nodes=6, seed=seed)
        evs = make_chaos_timeline(
            ec.num_nodes, seed=seed, horizon=float(ep.arrival.max()),
            mtbf=12.0, mttr=0.0, node_fraction=0.34,
        )
        cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay(
            node_events=evs
        )
        dev = JaxReplayEngine(
            ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
            retry_buffer=64, telemetry="series",
        ).replay(node_events=evs)
        np.testing.assert_array_equal(cpu.assignments, dev.assignments)
        assert cpu.telemetry.latency == dev.telemetry.latency, f"seed {seed}"
        assert cpu.telemetry.reasons == dev.telemetry.reasons, f"seed {seed}"
        evictions += dev.evictions
    assert evictions > 0  # non-vacuous


# -- checkpoint purity ----------------------------------------------------


def test_checkpoint_blob_identical_with_telemetry(tmp_path):
    """Telemetry state is NOT checkpoint state: boundary-mode blobs are
    bit-identical with telemetry off vs timeline."""
    ec, ep = _light_trace(num_pods=24, num_nodes=4)
    blobs = {}
    for gran in ("off", "timeline"):
        ck = str(tmp_path / f"ck_{gran}.npz")
        JaxReplayEngine(
            ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=4,
            preemption="kube", retry_buffer=64, telemetry=gran,
        ).replay(checkpoint_path=ck, checkpoint_every=3)
        blobs[gran] = np.load(ck, allow_pickle=True)
    off, tl = blobs["off"], blobs["timeline"]
    assert sorted(off.files) == sorted(tl.files)
    for k in off.files:
        np.testing.assert_array_equal(off[k], tl[k])


# -- what-if per-scenario latency ----------------------------------------


def test_whatif_kube_scenario_latency_quantiles():
    """Kube batches expose per-scenario latency quantiles; the clean
    scenario equals the single-replay telemetry, and the plain batch
    reports None."""
    ec, ep = _light_trace(num_pods=20, num_nodes=4)
    cfg = FIT_ONLY()
    evs = [e for e in make_chaos_timeline(
        ec.num_nodes, seed=7, horizon=float(ep.arrival.max()),
        mtbf=10.0, mttr=0.0, node_fraction=0.5,
    )]
    single = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay()
    res = WhatIfEngine(
        ec, ep, [Scenario(), Scenario(events=evs)], cfg, wave_width=1,
        chunk_waves=1, preemption="kube", retry_buffer=64,
        telemetry="series",
    ).run()
    assert res.latency_p50.shape == (2,)
    st = single.telemetry.latency
    assert float(res.latency_p50[0]) == st["p50"]
    assert float(res.latency_p99[0]) == st["p99"]
    assert res.scenario_telemetry[1].latency["count"] > 0
    plain = WhatIfEngine(ec, ep, [Scenario()], cfg, chunk_waves=4).run()
    assert plain.latency_p50 is None and plain.scenario_telemetry is None


# -- chrome trace exporter ------------------------------------------------


def test_chrome_trace_export(tmp_path):
    ec, ep = _light_trace(num_pods=12, num_nodes=3)
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent

    evs = [
        NodeEvent(time=4.0, kind="node_down", node=0),
        NodeEvent(time=9.0, kind="node_up", node=0),
    ]
    res = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="timeline").replay(
        node_events=evs
    )
    path = str(tmp_path / "trace.json")
    n = write_chrome_trace(path, res, arrival=ep.arrival, duration=ep.duration)
    with open(path) as f:
        doc = json.load(f)
    ev = doc["traceEvents"]
    assert len(ev) == n > 0
    phases = {e["ph"] for e in ev}
    assert "X" in phases and "M" in phases
    names = {e["name"] for e in ev}
    assert "node0 down" in names  # chaos span got stitched
    # Every pod span sits on the node it was bound to.
    for e in ev:
        if e["ph"] == "X" and e.get("pid") == 0 and e["name"].startswith("pod"):
            p = int(e["name"][3:])
            assert e["tid"] == int(res.assignments[p])


def test_series_attribution_fallback_notes(caplog, tmp_path):
    """series+ attribution fallback pin: in-scan tier preemption and
    checkpoint/resume each disable the instrumented chunk program with a
    log note — placements stay unchanged and latency/phase telemetry is
    still collected; only ``reasons`` goes dark."""
    import logging

    ec, ep = _reject_trace()
    cfg = FrameworkConfig()
    # Tier preemption: the instrumented program has no tier planes.
    ref = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                          preemption=True, telemetry="summary").replay()
    with caplog.at_level(logging.INFO, logger="k8sim"):
        res = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                              preemption=True, telemetry="series").replay()
    assert "not available with in-scan tier preemption" in caplog.text
    np.testing.assert_array_equal(ref.assignments, res.assignments)
    assert res.telemetry is not None and not res.telemetry.reasons
    assert res.telemetry.latency["count"] == res.placed
    # Checkpointing: the instrumented carry is not part of checkpoints.
    caplog.clear()
    plain = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                            telemetry="series").replay()
    with caplog.at_level(logging.INFO, logger="k8sim"):
        ck = JaxReplayEngine(ec, ep, cfg, wave_width=1, chunk_waves=1,
                             telemetry="series").replay(
            checkpoint_path=str(tmp_path / "ck.npz"), checkpoint_every=2,
        )
    assert "disabled under checkpoint/resume" in caplog.text
    np.testing.assert_array_equal(plain.assignments, ck.assignments)
    assert ck.telemetry is not None and not ck.telemetry.reasons
    assert plain.telemetry.reasons is not None  # instrumented run still works
