"""Quiet-chunk fast path of the lazy boundary sync (round 6): on a
failure-free, release-free trace the boundary modes must never fold the
host mirror planes — the whole point of the lazy pass is that the
faithful modes are near-free when nothing happens — while staying
bit-equal to the eager path and, at wave_width=1 / chunk_waves=1, to
``CpuReplayEngine``."""

import numpy as np

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine


def _quiet_trace(n_pods=48, n_nodes=6):
    """Ample capacity, no durations: every pod places first try (no
    retry-buffer entries) and nothing ever completes (no releases)."""
    nodes = [Node(f"n{i}", {"cpu": 64, "memory": 256}) for i in range(n_nodes)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1, "memory": 2},
            arrival_time=float(i))
        for i in range(n_pods)
    ]
    return encode(Cluster(nodes=nodes), pods)


def test_quiet_chunks_skip_the_mirror_fold():
    ec, ep = _quiet_trace()
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    eng = JaxReplayEngine(
        ec, ep, cfg, wave_width=4, chunk_waves=2, retry_buffer=8
    )
    res = eng.replay()
    bops = eng._last_bops
    # Zero failures + zero releases => the plane log is never flushed and
    # no per-chunk fold ever touches the mirror planes.
    assert bops.plane_folds == 0
    assert not bops.retry_q
    assert res.placed == len(ep.arrival)


def test_lazy_matches_eager_bit_for_bit():
    ec, ep = _quiet_trace()
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    lazy = JaxReplayEngine(
        ec, ep, cfg, wave_width=4, chunk_waves=2, retry_buffer=8
    ).replay()
    eager_eng = JaxReplayEngine(
        ec, ep, cfg, wave_width=4, chunk_waves=2, retry_buffer=8,
        lazy_boundary=False,
    )
    eager = eager_eng.replay()
    # The eager reference path DOES fold every chunk.
    assert eager_eng._last_bops.plane_folds > 0
    np.testing.assert_array_equal(lazy.assignments, eager.assignments)
    assert lazy.placed == eager.placed


def test_quiet_path_matches_cpu_engine_at_fine_chunking():
    ec, ep = _quiet_trace(n_pods=24, n_nodes=4)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, retry_buffer=4
    ).replay()
    cpu = CpuReplayEngine(ec, ep, cfg).replay()
    np.testing.assert_array_equal(dev.assignments, cpu.assignments)
    assert dev.placed == cpu.placed
