"""Tier preemption on the greedy engines: the host anchor (sim.greedy
preemption=True) and the v3 device path must agree exactly; kube's
minimal-victims PostFilter stays in the CPU event engine
(tests/test_replay_cpu.py)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


def _tight_case(seed, n_nodes=30, n_pods=220, **wl):
    """Over-committed cluster so preemption actually fires."""
    cluster = make_cluster(n_nodes, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(n_pods, seed=seed, with_tolerations=True, **wl)
    return encode(cluster, pods)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_device_matches_anchor(seed):
    ec, ep = _tight_case(seed, with_spread=True)
    cfg = FrameworkConfig()
    a = greedy_replay(ec, ep, cfg, preemption=True)
    d = JaxReplayEngine(ec, ep, cfg, preemption=True).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.placed == d.placed
    assert a.preemptions == d.preemptions


def test_device_matches_anchor_with_gangs():
    ec, ep = _tight_case(7, gang_fraction=0.15, gang_size=3)
    cfg = FrameworkConfig()
    a = greedy_replay(ec, ep, cfg, preemption=True)
    d = JaxReplayEngine(ec, ep, cfg, preemption=True).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.preemptions == d.preemptions


def test_preemption_places_high_priority():
    nodes = [Node(f"n{i}", capacity={"cpu": 4.0, "memory": 8 * 2**30, "pods": 10})
             for i in range(4)]
    pods = [Pod(f"lo{i}", labels={"app": "lo"}, requests={"cpu": 1.0},
                priority=0, arrival_time=float(i)) for i in range(16)]
    pods += [Pod(f"hi{i}", labels={"app": "hi"}, requests={"cpu": 2.0},
                 priority=100, arrival_time=100.0 + i) for i in range(4)]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    off = JaxReplayEngine(ec, ep, FrameworkConfig()).replay()
    on = JaxReplayEngine(ec, ep, FrameworkConfig(), preemption=True).replay()
    hi = np.arange(16, 20)
    assert (off.assignments[hi] >= 0).sum() == 0
    assert (on.assignments[hi] >= 0).sum() >= 2  # once-per-wave cap
    assert on.preemptions > 0
    # Usage stays consistent: evicted pods freed their resources.
    used = on.state.used[:, ec.vocab._r["cpu"]]
    assert (used <= 4.0 + 1e-5).all()


@pytest.mark.slow
def test_whatif_preemption_matches_single_replay():
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    ec, ep = _tight_case(5, n_nodes=20, n_pods=160, with_spread=True)
    cfg = FrameworkConfig()
    eng = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg,
        collect_assignments=True, preemption=True,
    )
    res = eng.run()
    single = JaxReplayEngine(ec, ep, cfg, preemption=True).replay()
    np.testing.assert_array_equal(res.assignments[0], single.assignments)
    assert int(res.placed[0]) == single.placed
    # Tally path (no assignment collection) agrees.
    eng2 = WhatIfEngine(ec, ep, [Scenario(), Scenario()], cfg, preemption=True)
    res2 = eng2.run()
    np.testing.assert_array_equal(res2.placed, res.placed)


def test_preemption_guards():
    ec, ep = _tight_case(0)
    with pytest.raises(ValueError):
        JaxReplayEngine(ec, ep, FrameworkConfig(), engine="v2", preemption=True)
    with pytest.raises(ValueError):
        JaxReplayEngine(ec, ep, FrameworkConfig(), preemption=True).replay(
            checkpoint_path="/tmp/x.npz", checkpoint_every=1
        )
    # Host-plane rows (hostname anti terms at scale) are rejected.
    cluster = make_cluster(150, seed=1)
    pods, _ = make_workload(50, seed=1, with_affinity=True)
    ec2, ep2 = encode(cluster, pods)
    from kubernetes_simulator_tpu.ops import tpu3 as V3
    from kubernetes_simulator_tpu.sim.jax_runtime import StepSpec

    spec = StepSpec.from_config(ec2, FrameworkConfig(), ep2)
    if V3.V3Static.build(ec2, ep2, spec).has_host_rows:
        with pytest.raises(ValueError):
            JaxReplayEngine(ec2, ep2, FrameworkConfig(), preemption=True)


def test_prebound_pods_preempted_single_replay():
    """Pre-bound low-priority pods occupy the cluster; the replay engine's
    tier planes must see them (reviewer repro: what-if once silently
    ignored pre-bound usage)."""
    nodes = [Node(f"n{i}", capacity={"cpu": 2.0, "memory": 4 * 2**30, "pods": 5})
             for i in range(2)]
    pods = [Pod(f"pre{i}", labels={"app": "lo"}, requests={"cpu": 2.0},
                priority=0, arrival_time=0.0, node_name=f"n{i}")
            for i in range(2)]
    pods += [Pod(f"hi{i}", labels={"app": "hi"}, requests={"cpu": 2.0},
                 priority=100, arrival_time=10.0 + i) for i in range(2)]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    a = greedy_replay(ec, ep, FrameworkConfig(), preemption=True)
    d = JaxReplayEngine(ec, ep, FrameworkConfig(), preemption=True).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions >= 1
    assert (d.assignments[2:] >= 0).any()  # a hi pod got in
    assert (d.assignments[:2] == PAD).any()  # a pre-bound pod was evicted


def test_whatif_preemption_rejects_prebound():
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node("n0", capacity={"cpu": 2.0, "memory": 4 * 2**30, "pods": 5})]
    pods = [Pod("pre", labels={}, requests={"cpu": 1.0}, priority=0,
                arrival_time=0.0, node_name="n0"),
            Pod("hi", labels={}, requests={"cpu": 2.0}, priority=10,
                arrival_time=1.0)]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    with pytest.raises(ValueError):
        WhatIfEngine(ec, ep, [Scenario()], FrameworkConfig(), preemption=True)
