"""Tier preemption on the greedy engines: the host anchor (sim.greedy
preemption=True) and the v3 device path must agree exactly; kube's
minimal-victims PostFilter stays in the CPU event engine
(tests/test_replay_cpu.py)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


def _tight_case(seed, n_nodes=30, n_pods=220, **wl):
    """Over-committed cluster so preemption actually fires."""
    cluster = make_cluster(n_nodes, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(n_pods, seed=seed, with_tolerations=True, **wl)
    return encode(cluster, pods)


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_device_matches_anchor(seed):
    ec, ep = _tight_case(seed, with_spread=True)
    cfg = FrameworkConfig()
    a = greedy_replay(ec, ep, cfg, preemption=True)
    d = JaxReplayEngine(ec, ep, cfg, preemption=True).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.placed == d.placed
    assert a.preemptions == d.preemptions


def test_device_matches_anchor_with_gangs():
    ec, ep = _tight_case(7, gang_fraction=0.15, gang_size=3)
    cfg = FrameworkConfig()
    a = greedy_replay(ec, ep, cfg, preemption=True)
    d = JaxReplayEngine(ec, ep, cfg, preemption=True).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.preemptions == d.preemptions


def test_preemption_places_high_priority():
    nodes = [Node(f"n{i}", capacity={"cpu": 4.0, "memory": 8 * 2**30, "pods": 10})
             for i in range(4)]
    pods = [Pod(f"lo{i}", labels={"app": "lo"}, requests={"cpu": 1.0},
                priority=0, arrival_time=float(i)) for i in range(16)]
    pods += [Pod(f"hi{i}", labels={"app": "hi"}, requests={"cpu": 2.0},
                 priority=100, arrival_time=100.0 + i) for i in range(4)]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    off = JaxReplayEngine(ec, ep, FrameworkConfig()).replay()
    on = JaxReplayEngine(ec, ep, FrameworkConfig(), preemption=True).replay()
    hi = np.arange(16, 20)
    assert (off.assignments[hi] >= 0).sum() == 0
    assert (on.assignments[hi] >= 0).sum() >= 2  # once-per-wave cap
    assert on.preemptions > 0
    # Usage stays consistent: evicted pods freed their resources.
    used = on.state.used[:, ec.vocab._r["cpu"]]
    assert (used <= 4.0 + 1e-5).all()


@pytest.mark.slow
def test_whatif_preemption_matches_single_replay():
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    ec, ep = _tight_case(5, n_nodes=20, n_pods=160, with_spread=True)
    cfg = FrameworkConfig()
    eng = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg,
        collect_assignments=True, preemption=True,
    )
    res = eng.run()
    single = JaxReplayEngine(ec, ep, cfg, preemption=True).replay()
    np.testing.assert_array_equal(res.assignments[0], single.assignments)
    assert int(res.placed[0]) == single.placed
    # Tally path (no assignment collection) agrees.
    eng2 = WhatIfEngine(ec, ep, [Scenario(), Scenario()], cfg, preemption=True)
    res2 = eng2.run()
    np.testing.assert_array_equal(res2.placed, res.placed)


def test_preemption_guards():
    ec, ep = _tight_case(0)
    with pytest.raises(ValueError):
        JaxReplayEngine(ec, ep, FrameworkConfig(), engine="v2", preemption=True)
    with pytest.raises(ValueError):
        JaxReplayEngine(ec, ep, FrameworkConfig(), preemption=True).replay(
            checkpoint_path="/tmp/x.npz", checkpoint_every=1
        )
    # Host-plane rows (hostname anti terms at scale) are rejected.
    cluster = make_cluster(150, seed=1)
    pods, _ = make_workload(50, seed=1, with_affinity=True)
    ec2, ep2 = encode(cluster, pods)
    from kubernetes_simulator_tpu.ops import tpu3 as V3
    from kubernetes_simulator_tpu.sim.jax_runtime import StepSpec

    spec = StepSpec.from_config(ec2, FrameworkConfig(), ep2)
    if V3.V3Static.build(ec2, ep2, spec).has_host_rows:
        with pytest.raises(ValueError):
            JaxReplayEngine(ec2, ep2, FrameworkConfig(), preemption=True)


def test_prebound_pods_preempted_single_replay():
    """Pre-bound low-priority pods occupy the cluster; the replay engine's
    tier planes must see them (reviewer repro: what-if once silently
    ignored pre-bound usage)."""
    nodes = [Node(f"n{i}", capacity={"cpu": 2.0, "memory": 4 * 2**30, "pods": 5})
             for i in range(2)]
    pods = [Pod(f"pre{i}", labels={"app": "lo"}, requests={"cpu": 2.0},
                priority=0, arrival_time=0.0, node_name=f"n{i}")
            for i in range(2)]
    pods += [Pod(f"hi{i}", labels={"app": "hi"}, requests={"cpu": 2.0},
                 priority=100, arrival_time=10.0 + i) for i in range(2)]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    a = greedy_replay(ec, ep, FrameworkConfig(), preemption=True)
    d = JaxReplayEngine(ec, ep, FrameworkConfig(), preemption=True).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions >= 1
    assert (d.assignments[2:] >= 0).any()  # a hi pod got in
    assert (d.assignments[:2] == PAD).any()  # a pre-bound pod was evicted


def test_whatif_preemption_rejects_prebound():
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node("n0", capacity={"cpu": 2.0, "memory": 4 * 2**30, "pods": 5})]
    pods = [Pod("pre", labels={}, requests={"cpu": 1.0}, priority=0,
                arrival_time=0.0, node_name="n0"),
            Pod("hi", labels={}, requests={"cpu": 2.0}, priority=10,
                arrival_time=1.0)]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    with pytest.raises(ValueError):
        WhatIfEngine(ec, ep, [Scenario()], FrameworkConfig(), preemption=True)


def test_preemption_with_completions_tiny():
    """Round 4: preemption × completions is a supported device config.
    lo's completion (not an eviction) frees the node; hi then fits
    WITHOUT preempting mid. Releases drop the tier planes, so a later
    eviction check sees the freed capacity."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("lo", requests={"cpu": 2}, arrival_time=0.0, duration=3.0,
            priority=0),
        Pod("f1", requests={}, arrival_time=5.0),
        Pod("f2", requests={}, arrival_time=6.0),
        Pod("hi", requests={"cpu": 2}, arrival_time=10.0, priority=100),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    a = greedy_replay(
        ec, ep, cfg, wave_width=1, preemption=True,
        completions_chunk_waves=1,
    )
    assert a.assignments[0] == 0 and a.assignments[3] == 0
    assert a.preemptions == 0  # completion freed it, no eviction needed
    d = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption=True,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions == 0 and d.placed == a.placed


def test_preemption_evicts_then_victim_never_releases():
    """An evicted pod must NOT release resources at its old completion
    time (it no longer holds them) — the planes would go negative and
    later placements would over-fit. hi evicts lo; at lo's would-be
    completion nothing is released; a second 2-cpu pod must NOT fit
    while hi is running."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("lo", requests={"cpu": 2}, arrival_time=0.0, duration=6.0,
            priority=0),
        Pod("f1", requests={}, arrival_time=1.0, priority=200),
        Pod("f2", requests={}, arrival_time=2.0, priority=200),
        Pod("hi", requests={"cpu": 2}, arrival_time=3.0, duration=100.0,
            priority=100),
        Pod("f3", requests={}, arrival_time=7.0, priority=200),
        Pod("f4", requests={}, arrival_time=8.0, priority=200),
        # lo's arrival+duration (6.0) has passed; if its phantom release
        # fired, probe would fit. It must not.
        Pod("probe", requests={"cpu": 2}, arrival_time=9.0, priority=0),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    a = greedy_replay(
        ec, ep, cfg, wave_width=1, preemption=True,
        completions_chunk_waves=1,
    )
    assert a.assignments[0] == PAD  # evicted
    assert a.assignments[3] == 0
    assert a.assignments[6] == PAD  # no phantom release
    assert a.preemptions == 1
    d = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption=True,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions == 1


def test_completed_pod_not_evicted():
    """A completed pod keeps its assignment (it ran to completion) and
    must not appear as an eviction victim; its capacity is already free
    so hi fits without any preemption."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("lo", requests={"cpu": 2}, arrival_time=0.0, duration=1.0,
            priority=0),
        Pod("f1", requests={}, arrival_time=2.0),
        Pod("f2", requests={}, arrival_time=3.0),
        Pod("hi", requests={"cpu": 2}, arrival_time=5.0, priority=100),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    a = greedy_replay(
        ec, ep, cfg, wave_width=1, preemption=True,
        completions_chunk_waves=1,
    )
    assert a.assignments[0] == 0  # completed, assignment kept
    assert a.assignments[3] == 0
    assert a.preemptions == 0
    d = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption=True,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions == 0


@pytest.mark.parametrize(
    "seed", [pytest.param(2, marks=pytest.mark.slow), 3])
def test_preemption_completions_parity_random(seed):
    """Random over-committed workload WITH durations: device preemption ×
    completions must match the anchor exactly. Shape tuned so BOTH
    mechanisms fire (evictions occur AND completions change placements)."""
    ec, ep = _tight_case(
        seed, n_nodes=8, n_pods=400, with_spread=True,
        duration_mean=20.0, arrival_rate=12.0,
    )
    cfg = FrameworkConfig()
    a = greedy_replay(
        ec, ep, cfg, preemption=True, completions_chunk_waves=4
    )
    d = JaxReplayEngine(
        ec, ep, cfg, preemption=True, chunk_waves=4
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert a.placed == d.placed
    assert a.preemptions == d.preemptions
    # Non-vacuous: both mechanisms fire on this trace.
    assert a.preemptions > 0
    off = greedy_replay(ec, ep, cfg, preemption=True)
    assert (off.assignments != a.assignments).any()


def _replay_with_fusion(ec, ep, cfg, fused, **kw):
    """Build + replay inside a FUSED_PREEMPT patch window — the flag is
    read at trace time, so the program variant is picked here."""
    from kubernetes_simulator_tpu.ops import tpu3 as V3

    old = V3.FUSED_PREEMPT
    V3.FUSED_PREEMPT = fused
    try:
        return JaxReplayEngine(ec, ep, cfg, preemption=True, **kw).replay()
    finally:
        V3.FUSED_PREEMPT = old


# Tier mixes for the fused-program parity sweep (round 10): tier count
# drives the packed-prefix width AND the batched-commit einsum shapes, so
# sweep sparse/dense/skewed priority populations.
TIER_MIXES = [
    (0, 100),
    (0, 50, 100),
    (0, 10, 100, 1000),
    (0, 0, 0, 1000),  # skewed: one hot tier over a deep low-tier pool
]


@pytest.mark.parametrize("tiers", TIER_MIXES, ids=lambda t: "x".join(map(str, t)))
def test_fused_tier_mix_parity(tiers):
    """Fused preempt-select (ops.tpu3.FUSED_PREEMPT) vs the retained
    pre-fusion program vs the CPU anchor: bit-identical assignments,
    placement counts, eviction counts, and usage planes across tier
    mixes. Priorities ramp upward over arrival time so later tiers
    actually preempt earlier ones (non-vacuous: asserts evictions)."""
    n_pods = 72
    nodes = [
        Node(f"n{i}", capacity={"cpu": 4.0, "memory": 8 * 2**30, "pods": 12})
        for i in range(6)
    ]
    pods = [
        Pod(
            f"p{i}", labels={"app": f"a{i % 3}"},
            requests={"cpu": [0.5, 1.0, 2.0][i % 3]},
            priority=tiers[min(len(tiers) - 1, (i * len(tiers)) // n_pods)],
            arrival_time=float(i),
        )
        for i in range(n_pods)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig()
    a = greedy_replay(ec, ep, cfg, preemption=True)
    fused = _replay_with_fusion(ec, ep, cfg, True)
    pre = _replay_with_fusion(ec, ep, cfg, False)
    np.testing.assert_array_equal(fused.assignments, a.assignments)
    np.testing.assert_array_equal(fused.assignments, pre.assignments)
    assert fused.placed == a.placed == pre.placed
    assert fused.preemptions == a.preemptions == pre.preemptions
    assert fused.preemptions > 0  # the mix must actually exercise eviction
    np.testing.assert_array_equal(
        np.asarray(fused.state.used), np.asarray(pre.state.used)
    )


@pytest.mark.parametrize("seed", [0])
@pytest.mark.slow
def test_fused_matches_prefusion_random(seed):
    """Randomized over-committed traces (gangs, spread, tolerations):
    the fused and pre-fusion device programs must be BIT-identical —
    assignments and f32 usage planes. One seed here (tier-1 budget);
    the fuzz_quick slice flips the flag on every preempt trial."""
    ec, ep = _tight_case(seed, with_spread=True, gang_fraction=0.1,
                         gang_size=3)
    cfg = FrameworkConfig()
    fused = _replay_with_fusion(ec, ep, cfg, True)
    pre = _replay_with_fusion(ec, ep, cfg, False)
    np.testing.assert_array_equal(fused.assignments, pre.assignments)
    assert fused.placed == pre.placed
    assert fused.preemptions == pre.preemptions
    np.testing.assert_array_equal(
        np.asarray(fused.state.used), np.asarray(pre.state.used)
    )


def test_masked_argmin_matches_reference():
    """The fused victim-select helper must pick exactly what the
    argmax(where(mask, -score, -inf)) + any(mask) pair picked — including
    lowest-index tie-breaks and the all-masked-out case."""
    import jax.numpy as jnp

    from kubernetes_simulator_tpu.ops import tpu as T

    rng = np.random.default_rng(0)
    for _ in range(25):
        s = rng.integers(0, 5, 32).astype(np.float32)  # dense ties
        m = rng.random(32) < 0.4
        choice, ok = T.masked_argmin(jnp.asarray(s), jnp.asarray(m))
        if m.any():
            assert bool(ok)
            assert int(choice) == int(np.argmax(np.where(m, -s, -np.inf)))
        else:
            assert not bool(ok)
            assert int(choice) == PAD


def test_gang_completion_does_not_corrupt_tier_planes():
    """A completed GANG pod must not be subtracted from the tier planes
    (which never accumulate gang pods — gangs are not evictable): the
    corruption under-counted evictable usage and skipped required
    evictions (round-4 review repro)."""
    cluster = Cluster(nodes=[Node("n0", {"cpu": 2})])
    pods = [
        Pod("g0", requests={"cpu": 1}, arrival_time=0.0, duration=2.0,
            pod_group="g", priority=0),
        Pod("g1", requests={"cpu": 1}, arrival_time=0.0, duration=2.0,
            pod_group="g", priority=0),
        Pod("f1", requests={}, arrival_time=3.0, priority=200),
        Pod("f2", requests={}, arrival_time=4.0, priority=200),
        # lo refills the node after the gang completes...
        Pod("lo", requests={"cpu": 2}, arrival_time=5.0, duration=100.0,
            priority=0),
        Pod("f3", requests={}, arrival_time=6.0, priority=200),
        Pod("f4", requests={}, arrival_time=7.0, priority=200),
        # ...and hi must evict lo — negative tier planes would hide it.
        Pod("hi", requests={"cpu": 2}, arrival_time=8.0, priority=100),
    ]
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    a = greedy_replay(
        ec, ep, cfg, wave_width=2, preemption=True,
        completions_chunk_waves=1,
    )
    assert a.assignments[7] == 0 and a.preemptions == 1
    d = JaxReplayEngine(
        ec, ep, cfg, wave_width=2, chunk_waves=1, preemption=True,
    ).replay()
    np.testing.assert_array_equal(a.assignments, d.assignments)
    assert d.preemptions == a.preemptions
