import numpy as np

from kubernetes_simulator_tpu import (
    Cluster,
    LabelSelector,
    MatchExpression,
    Node,
    Pod,
    PodAffinitySpec,
    PodAffinityTerm,
    Taint,
    Toleration,
    encode,
)
from kubernetes_simulator_tpu.models.encode import PAD, TOL_WILDCARD
from kubernetes_simulator_tpu.utils.quantity import parse_quantity


def test_parse_quantity():
    assert parse_quantity("100m") == 0.1
    assert parse_quantity("2") == 2.0
    assert parse_quantity("1Ki") == 1024.0
    assert parse_quantity("1.5Gi") == 1.5 * 2**30
    assert parse_quantity("2k") == 2000.0
    assert parse_quantity(3) == 3.0


def _tiny():
    nodes = [
        Node("n0", {"cpu": 4, "memory": "8Gi"}, labels={"zone": "a"},
             taints=[Taint("dedicated", "gpu", )]),
        Node("n1", {"cpu": 8, "memory": "16Gi", "google.com/tpu": 4}, labels={"zone": "b"}),
    ]
    pods = [
        Pod("p0", requests={"cpu": 1}, labels={"app": "web"},
            tolerations=[Toleration(key="dedicated", operator="Exists")]),
        Pod("p1", requests={"cpu": "500m", "google.com/tpu": 2},
            pod_affinity=PodAffinitySpec(required=(
                PodAffinityTerm(LabelSelector.make({"app": "web"}), "zone"),
            ))),
    ]
    return Cluster(nodes=nodes), pods


def test_encode_shapes_and_vocab():
    cluster, pods = _tiny()
    ec, ep = encode(cluster, pods)
    assert ec.num_nodes == 2
    assert ep.num_pods == 2
    # cpu, memory, pods seeded + extended resource discovered
    assert "google.com/tpu" in ec.vocab.resources
    ri = ec.vocab._r["google.com/tpu"]
    assert ec.allocatable[1, ri] == 4
    assert ep.requests[1, ri] == 2
    # pods slot defaults
    pi = ec.vocab._r["pods"]
    assert ec.allocatable[0, pi] == 110
    assert ep.requests[0, pi] == 1
    # hostname label is implicit
    assert "kubernetes.io/hostname" in ec.vocab.keys


def test_encode_tolerations():
    cluster, pods = _tiny()
    ec, ep = encode(cluster, pods)
    # p0 tolerates key=dedicated with Exists → kv is PAD, key real
    assert ep.tol_key[0, 0] >= 0
    assert ep.tol_kv[0, 0] == PAD
    # p1 has no tolerations → padded row
    assert (ep.tol_key[1] < TOL_WILDCARD + 1).all() or ep.tol_key.shape[1] == 1


def test_encode_count_groups_and_domains():
    cluster, pods = _tiny()
    ec, ep = encode(cluster, pods)
    assert ec.num_groups == 1
    assert ep.aff_req[1, 0] == 0
    # zone domains: a→0, b→1 (sorted)
    ti = ec.vocab._t["zone"]
    assert ec.num_domains[ti] == 2
    assert ec.node_domain[ti, 0] == 0 and ec.node_domain[ti, 1] == 1
    # pod p0 (app=web) matches the group selector; p1 does not
    assert ep.pod_matches_group[0, 0]
    assert not ep.pod_matches_group[1, 0]


def test_encode_prebound_and_groups():
    cluster, pods = _tiny()
    pods[0].node_name = "n1"
    pods[0].pod_group = "g1"
    pods[1].pod_group = "g1"
    ec, ep = encode(cluster, pods)
    assert ep.bound_node[0] == 1 and ep.bound_node[1] == PAD
    assert ep.group_id[0] == ep.group_id[1] == 0
    assert ep.pg_min_member[0] == 2  # inferred from membership
