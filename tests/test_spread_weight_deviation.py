"""Quantify the PodTopologySpread static-weight deviation (round 5,
VERDICT r4 next #8; `ops/cpu.py::spread_weight` DOCUMENTED DEVIATION).

Upstream computes `topologyNormalizingWeight = log(size + 2)` with
`size` = distinct topology domains among the pod's FILTERED nodes each
cycle (hostname special-cased to `len(filteredNodes) - 2`); this
framework uses the STATIC cluster-wide domain count so the weight stays
a trace-time constant (a per-pod domain census would enter the device
hot loop). The two differ exactly when filtering excludes whole
domains. This file holds an upstream-faithful dynamic-weight oracle and
MEASURES the placement divergence on a trace engineered to maximize the
effect (taints exclude half the zones for half the pods), then asserts
the measured bound — turning the last "slightly" in the semantics docs
into a number.

Measured (2026-07-31, the numbers the docs now cite):

- SINGLE-topology spread (one zone constraint, half the zones filtered
  out): **0.00%** placement divergence on every seed. The weight
  multiplies every node's raw score by the same constant, and upstream's
  own NormalizeScore (100·(max+min−s)//max) is scale-invariant up to the
  integer rounding of `round(raw)` — only the +maxSkew−1 offset
  interacting with that rounding can flip a ranking, and a flip must
  then survive the weighted sum with the other plugins.
- MULTI-topology spread (zone + hostname constraints on the same pod,
  zones half-filtered): the weight error is now RELATIVE between the two
  terms, not a global scale — **5.4% of scheduling decisions flip**
  (50/919, same-state comparison along the static trajectory) and the
  cascade-inclusive assignment divergence is **14.1%** (181/1280 over 8
  seeds). Placed counts stay equal (ScheduleAnyway never gates).

So the deviation is immaterial for the common single-constraint shape
and material only when one pod spreads over multiple topologies AND
filtering excludes whole domains. A device-side fix is sketched in
COVERAGE.md (the wave step already computes per-row domain feasibility;
dynamic size = its popcount) — not taken this round: the static weight
is baked into the accumulated count planes and the f32-exactness proofs
(sp_norm_f32) bound it at trace time."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import (
    FrameworkConfig,
    SchedulerFramework,
)
from kubernetes_simulator_tpu.models.core import (
    Cluster,
    LabelSelector,
    Node,
    Pod,
    Taint,
    Toleration,
    TopologySpreadConstraint,
)
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.ops import cpu as K
from kubernetes_simulator_tpu.sim.greedy import greedy_replay


def _dynamic_spread_score(ec, st, pods, p, feasible):
    """Upstream-faithful raw spread score: per ScheduleAnyway constraint,
    weight = log(size + 2) with size = distinct domains of the key among
    the FILTERED (feasible) nodes — kubernetes.io/hostname special-cased
    to len(filteredNodes) − 2 ([K8S] podtopologyspread PreScore)."""
    gdom = K._group_dom_per_node(ec)
    cnt = K._counts_at_nodes(st.match_count, gdom)
    raw = np.zeros(ec.num_nodes, dtype=np.float32)
    ignored = np.zeros(ec.num_nodes, dtype=bool)
    any_scored = False
    for g, skew, dns in zip(pods.spread_g[p], pods.spread_skew[p], pods.spread_dns[p]):
        if g < 0 or dns:
            continue
        any_scored = True
        ti = ec.group_topo[g]
        if ec.vocab.topo_keys[ti] == "kubernetes.io/hostname":
            size = max(int(feasible.sum()) - 2, 0)
        else:
            doms = ec.node_domain[ti][feasible]
            size = len(np.unique(doms[doms >= 0]))
        w = np.float32(np.log(np.float64(size) + 2.0))
        raw = raw + (cnt[g] * w + np.float32(int(skew) - 1))
        ignored |= gdom[g] < 0
    if not any_scored:
        return None
    raw = np.floor(raw + np.float32(0.5))
    return np.where(ignored, np.float32(-1.0), raw)


def _oracle_replay(ec, ep, config):
    """W=1 greedy replay whose PodTopologySpread score uses the DYNAMIC
    upstream weight; everything else identical to the framework path."""
    fw = SchedulerFramework(ec, ep, config)
    from kubernetes_simulator_tpu.models.state import bind, init_state

    st = init_state(ec, ep)
    assignments = np.full(ep.num_pods, PAD, dtype=np.int32)
    for p in np.argsort(ep.arrival, kind="stable"):
        p = int(p)
        feasible = fw.feasible_mask(st, p)
        if not feasible.any():
            continue
        total = np.zeros(ec.num_nodes, dtype=np.float32)
        for pl in fw.plugins:
            w = fw.weights.get(pl.name, 1.0)
            if w == 0:
                continue
            if pl.name == "PodTopologySpread":
                raw = _dynamic_spread_score(ec, st, ep, p, feasible)
                if raw is None:
                    continue
                total += w * K.spread_normalize(raw, feasible)
            else:
                raw = pl.score(fw.ctx, st, p)
                if raw is not None:
                    total += w * pl.normalize(raw, feasible)
        node = int(np.argmax(np.where(feasible, total, -np.inf)))
        bind(ec, ep, st, p, node)
        assignments[p] = node
    return assignments


def _domain_excluding_case(seed):
    """4 zones; zones 2/3 fully tainted; half the pods tolerate nothing —
    for them, filtering excludes HALF the zone domains (upstream size 2
    vs static 4). Zone ScheduleAnyway spread on every pod."""
    rng = np.random.default_rng(seed)
    nodes = []
    for i in range(16):
        zone = i % 4
        taints = [Taint("dedicated", "x", "NoSchedule")] if zone >= 2 else []
        nodes.append(
            Node(
                f"n{i}",
                {"cpu": 8.0, "memory": 16 * 2**30, "pods": 20},
                labels={"topology.kubernetes.io/zone": f"z{zone}",
                        "kubernetes.io/hostname": f"n{i}"},
                taints=taints,
            )
        )
    spread = [
        TopologySpreadConstraint(
            max_skew=1,
            topology_key="topology.kubernetes.io/zone",
            when_unsatisfiable="ScheduleAnyway",
            label_selector=LabelSelector.make({"app": "a"}),
        )
    ]
    pods = []
    for i in range(120):
        tol = (
            [Toleration(key="dedicated", operator="Exists", effect="NoSchedule")]
            if rng.random() < 0.5
            else []
        )
        pods.append(
            Pod(
                f"p{i}",
                labels={"app": "a"},
                requests={"cpu": float(rng.choice([0.5, 1.0, 2.0]))},
                arrival_time=float(i),
                tolerations=tol,
                topology_spread=list(spread),
            )
        )
    return encode(Cluster(nodes=nodes), pods)


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_static_weight_divergence_is_bounded(seed):
    ec, ep = _domain_excluding_case(seed)
    cfg = FrameworkConfig()
    static = greedy_replay(ec, ep, cfg, wave_width=1)
    dynamic = _oracle_replay(ec, ep, cfg)
    mism = int((static.assignments != dynamic).sum())
    frac = mism / ep.num_pods
    # Measured: 0.00% on every seed (see module docstring for why the
    # scale-invariant normalize erases the constant-factor difference).
    # The bound leaves room for generator drift without letting the
    # deviation quietly become material.
    assert frac <= 0.02, (mism, ep.num_pods)


def _two_topo_case(seed, n_nodes=16, n_pods=160):
    """Zone + hostname ScheduleAnyway constraints on every pod; zones
    2/3 fully tainted, half the pods intolerant — for them the zone
    weight shrinks (2 of 4 domains filtered) while the hostname weight
    shrinks differently (len(filtered)−2), so the error is RELATIVE."""
    rng = np.random.default_rng(seed)
    nodes = []
    for i in range(n_nodes):
        zone = i % 4
        taints = [Taint("dedicated", "x", "NoSchedule")] if zone >= 2 else []
        nodes.append(
            Node(
                f"n{i}",
                {"cpu": 8.0, "memory": 16 * 2**30, "pods": 40},
                labels={"topology.kubernetes.io/zone": f"z{zone}",
                        "kubernetes.io/hostname": f"n{i}"},
                taints=taints,
            )
        )
    sel = LabelSelector.make({"app": "a"})
    spread = [
        TopologySpreadConstraint(1, "topology.kubernetes.io/zone",
                                 "ScheduleAnyway", sel),
        TopologySpreadConstraint(2, "kubernetes.io/hostname",
                                 "ScheduleAnyway", sel),
    ]
    pods = []
    for i in range(n_pods):
        tol = (
            [Toleration(key="dedicated", operator="Exists", effect="NoSchedule")]
            if rng.random() < 0.5
            else []
        )
        pods.append(
            Pod(
                f"p{i}", labels={"app": "a"},
                requests={"cpu": float(rng.choice([0.5, 1.0, 2.0]))},
                arrival_time=float(i), tolerations=tol,
                topology_spread=list(spread),
            )
        )
    return encode(Cluster(nodes=nodes), pods)


def test_multi_topology_divergence_measured():
    """The material case: cascade-inclusive assignment divergence stays
    within the measured envelope (14.1% over 8 seeds; bound 25%) and is
    non-zero (the deviation really shows here — if this starts passing
    with 0 mismatches, the measurement rig broke)."""
    tot_m = tot_p = 0
    for seed in (0, 1, 3):
        ec, ep = _two_topo_case(seed)
        cfg = FrameworkConfig()
        static = greedy_replay(ec, ep, cfg, wave_width=1)
        dynamic = _oracle_replay(ec, ep, cfg)
        tot_m += int((static.assignments != dynamic).sum())
        tot_p += ep.num_pods
    assert 0 < tot_m / tot_p <= 0.25, (tot_m, tot_p)


def test_multi_topology_per_decision_flip_rate():
    """Same-state comparison along the static trajectory — the cascade-
    free number (measured 5.4% over 8 seeds; bound 12%)."""
    from kubernetes_simulator_tpu.models.state import bind, init_state

    flips = decisions = 0
    for seed in (0, 1, 3):
        ec, ep = _two_topo_case(seed)
        fw = SchedulerFramework(ec, ep, FrameworkConfig())
        st = init_state(ec, ep)
        for p in np.argsort(ep.arrival, kind="stable"):
            p = int(p)
            feasible = fw.feasible_mask(st, p)
            if not feasible.any():
                continue
            tot_s = np.zeros(ec.num_nodes, np.float32)
            tot_d = np.zeros(ec.num_nodes, np.float32)
            for pl in fw.plugins:
                w = fw.weights.get(pl.name, 1.0)
                if w == 0:
                    continue
                if pl.name == "PodTopologySpread":
                    rs = pl.score(fw.ctx, st, p)
                    if rs is not None:
                        tot_s += w * pl.normalize(rs, feasible)
                    rd = _dynamic_spread_score(ec, st, ep, p, feasible)
                    if rd is not None:
                        tot_d += w * K.spread_normalize(rd, feasible)
                else:
                    raw = pl.score(fw.ctx, st, p)
                    if raw is not None:
                        v = w * pl.normalize(raw, feasible)
                        tot_s += v
                        tot_d += v
            cs = int(np.argmax(np.where(feasible, tot_s, -np.inf)))
            cd = int(np.argmax(np.where(feasible, tot_d, -np.inf)))
            flips += cs != cd
            decisions += 1
            bind(ec, ep, st, p, cs)  # follow the static trajectory
    assert 0 < flips / decisions <= 0.12, (flips, decisions)


def test_oracle_differs_from_static_raw_scores():
    """Non-vacuity: the dynamic oracle's RAW weights really differ from
    the static ones on the domain-excluding shape (so the placement
    agreement above is a measured result, not two identical
    implementations agreeing by construction)."""
    ec, ep = _domain_excluding_case(0)
    fw = SchedulerFramework(ec, ep, FrameworkConfig())
    from kubernetes_simulator_tpu.models.state import init_state

    st = init_state(ec, ep)
    # Find an intolerant pod (filtered to 2 of 4 zones).
    p = next(
        int(i)
        for i in range(ep.num_pods)
        if ep.tol_key.shape[1] == 0 or (ep.tol_key[i] < 0).all()
    )
    feasible = fw.feasible_mask(st, p)
    assert 0 < int(feasible.sum()) < ec.num_nodes
    g = int(ep.spread_g[p, 0])
    ti = ec.group_topo[g]
    doms = ec.node_domain[ti][feasible]
    dyn_size = len(np.unique(doms[doms >= 0]))
    static_size = int(ec.num_domains[ti])
    assert dyn_size < static_size  # filtering excluded whole domains
