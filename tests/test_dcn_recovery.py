"""Round-15 elastic recovery suite: SIGKILL one of 2 DCN workers
mid-replay WITH recovery enabled and the survivor must claim the dead
process's scenario block, resume it from the newest published
checkpoint, and complete the single end-of-replay gather with results
BYTE-IDENTICAL to a no-failure run (compared against the same
single-process oracles the round-11 parity suite uses).

Kill timing is chosen so a true checkpoint RESUME is exercised, not
just a from-scratch re-run: with KSIM_DCN_CKPT_EVERY=1 the victim
publishes its chunk-1 checkpoint BEFORE the heartbeat that triggers
the self-kill (publication is ordered first in the chunk loop), so the
survivor restores cursor 1 of 2 and replays only the remaining chunk.
The second case rides the kube host-mirror path, where checkpoints
don't apply and the claimed block deterministically re-executes from
chunk 0 — both recovery envelopes in one fleet.

The recovery-DISABLED behavior (round-12 attributed DcnGatherTimeout)
is pinned by tests/test_dcn.py::test_killed_worker_fails_fast_attributed,
which runs without KSIM_DCN_RECOVER — the default.
"""

import functools
import json
import os
import socket
import subprocess
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(__file__))

import dcn_case_worker as W  # noqa: E402
import dcn_recovery_worker  # noqa: E402,F401  (registers recovery_fleet)

_WORKER = os.path.join(os.path.dirname(__file__), "dcn_recovery_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@functools.lru_cache(maxsize=None)
def _oracle(case: str):
    """Single-process reference through the same JSON round-trip the
    worker results take (int/float/None representations match)."""
    out = W.run_cases([case], expect_dcn=False)
    return json.loads(json.dumps(out[case]))


@pytest.mark.slow
def test_survivor_recovers_killed_worker_byte_identical(tmp_path):
    """Worker 1 SIGKILLs itself after its chunk-0 heartbeat (its chunk-1
    checkpoint is already published); worker 0 must claim the block,
    resume the checkpoint, finish the replay, and return EXACTLY the
    no-failure gathered result for every case — plus mirror the claim
    and recovery events for dcn_launch --watch."""
    cases = ("plain", "recovery_fleet")
    port = _free_port()
    hb_dir = tmp_path / "hb"
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=4",
        "KSIM_DCN_COORD": f"127.0.0.1:{port}",
        "KSIM_DCN_NPROC": "2",
        "KSIM_DCN_CASES": ",".join(cases),
        # Round-15 recovery knobs: checkpoint every chunk, claim fast.
        "KSIM_DCN_RECOVER": "1",
        "KSIM_DCN_CKPT_EVERY": "1",
        "KSIM_DCN_TIMEOUT_S": "600",
        "KSIM_DCN_STALL_S": "2",
        "KSIM_DCN_POLL_S": "0.3",
        "KSIM_DCN_HEARTBEAT_EVERY": "1",
        "KSIM_DCN_HB_DIR": str(hb_dir),
        "PYTHONPATH": os.pathsep.join(
            [os.path.dirname(os.path.dirname(__file__))]
            + [
                p
                for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p
            ]
        ),
    }
    procs = []
    for pid in range(2):
        env = dict(env_base, KSIM_DCN_PID=str(pid))
        if pid == 1:
            env["KSIM_DCN_SELFKILL_AT_CHUNK"] = "0"
        procs.append(
            subprocess.Popen(
                [sys.executable, _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                text=True,
            )
        )
    try:
        out0, err0 = procs[0].communicate(timeout=600)
        procs[1].wait(timeout=60)
    except subprocess.TimeoutExpired:
        for q in procs:
            if q.poll() is None:
                q.kill()
                q.wait()
        pytest.fail("recovery fleet timed out")
    blob = out0 + err0
    if "Multiprocess computations aren't implemented" in blob:
        pytest.skip("jaxlib CPU backend lacks multiprocess execution")
    assert procs[1].returncode == -9, "worker 1 should have SIGKILLed itself"
    assert procs[0].returncode == 0, f"survivor failed:\n{blob}"

    # Byte-identical recovery: the survivor's gathered payloads equal
    # the single-process no-failure oracles for EVERY case, including
    # the deterministic JSONL hash inside case "plain".
    lines = [
        l for l in out0.splitlines() if l.startswith("DCN_CASES_RESULT ")
    ]
    assert lines, f"no result line:\n{blob}"
    res = json.loads(lines[-1][len("DCN_CASES_RESULT "):])
    for c in cases:
        assert res[c] == _oracle(c), f"case {c} diverged after recovery"

    # Claim protocol + checkpoint resume actually fired (not a silent
    # fall-through to some other path): worker 0 claimed worker 1's
    # block in both gathers, and the mesh case resumed mid-replay from
    # the published checkpoint.
    assert "claims dead process 1" in blob, blob
    assert "resumed process 1's block" in blob, blob
    assert "resumed and republished process 1's block" in blob, blob

    # The KV mirror carries the operator-visible rebalance trail
    # (dcn_launch --watch renders these live).
    events_path = hb_dir / "events.jsonl"
    assert events_path.exists(), "no events.jsonl in KSIM_DCN_HB_DIR"
    events = [
        json.loads(l)
        for l in events_path.read_text().splitlines()
        if l.strip()
    ]
    kinds = [(e.get("event"), e.get("claimant"), e.get("for"))
             for e in events]
    assert kinds.count(("claim", 0, 1)) == len(cases), kinds
    assert kinds.count(("recovered", 0, 1)) == len(cases), kinds
