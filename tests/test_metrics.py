"""utils.metrics coverage (satellite of the telemetry round): JSONL row
builders, the context-stamping JsonlWriter, and the BASELINE.md table
emitter."""

import json

import numpy as np

from kubernetes_simulator_tpu.sim.whatif import WhatIfResult
from kubernetes_simulator_tpu.utils.metrics import (
    SCHEMA_VERSION,
    JsonlWriter,
    baseline_table,
    config_hash,
    replay_row,
    whatif_rows,
)


def _plain_result(**kw):
    return WhatIfResult(
        placed=np.array([10, 9], np.int32),
        unschedulable=np.array([0, 1], np.int32),
        total_placed=19,
        wall_clock_s=0.5,
        placements_per_sec=38.0,
        utilization_cpu=np.array([0.25, 0.3]),
        **kw,
    )


def test_whatif_rows_plain_batch():
    rows = list(whatif_rows(_plain_result(), {"config": "c.yaml"}))
    agg, s0, s1 = rows
    assert agg["kind"] == "whatif-aggregate"
    assert agg["scenarios"] == 2 and agg["total_placed"] == 19
    assert agg["engine"] == "v3" and agg["config"] == "c.yaml"
    assert s0["kind"] == "whatif-scenario" and s0["scenario"] == 0
    assert s1["placed"] == 9 and s1["unschedulable"] == 1
    # No kube/chaos/telemetry signals ⇒ their fields stay absent.
    for k in ("preemptions", "evictions", "latency_p50"):
        assert k not in s0


def test_whatif_rows_kube_chaos_telemetry_fields():
    res = _plain_result(
        preemptions=np.array([2, 0], np.int32),
        retry_dropped=np.array([0, 1], np.int32),
        evictions=np.array([3, 0], np.int32),
        evict_rescheduled=np.array([2, 0], np.int32),
        evict_stranded=np.array([1, 0], np.int32),
        evict_latency_mean=np.array([1.25, 0.0]),
        latency_p50=np.array([0.0, np.nan]),
        latency_p90=np.array([2.0, np.nan]),
        latency_p99=np.array([4.0, np.nan]),
    )
    _, s0, s1 = list(whatif_rows(res))
    assert s0["preemptions"] == 2 and s0["retry_dropped"] == 0
    assert s0["evictions"] == 3 and s0["evict_latency_mean"] == 1.25
    assert s0["latency_p50"] == 0.0 and s0["latency_p99"] == 4.0
    # NaN (scenario bound nothing) serializes as null, not NaN.
    assert s1["latency_p50"] is None
    json.dumps(s1)  # must be valid JSON


def test_replay_row_carries_summary_and_extra():
    class R:
        def summary(self):
            return {"placed": 5, "unschedulable": 0}

    row = replay_row("replay-cpu", R(), {"config": "x.yaml"})
    assert row == {"kind": "replay-cpu", "placed": 5, "unschedulable": 0,
                   "config": "x.yaml"}
    bare = replay_row("replay-cpu", object())
    assert bare == {"kind": "replay-cpu"}


def test_jsonl_writer_stamps_and_context(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    ctx = {"seed": 7, "engine": "cpu", "config_hash": "abc123"}
    with JsonlWriter(path, context=ctx) as out:
        out.write({"kind": "replay-cpu", "placed": 1})
        # Explicit row keys beat context keys (whatif aggregate rows
        # carry the real engine).
        out.write({"kind": "whatif-aggregate", "engine": "v3"})
    rows = [json.loads(l) for l in open(path)]
    assert rows[0]["schema"] == SCHEMA_VERSION
    assert rows[0]["seed"] == 7 and rows[0]["engine"] == "cpu"
    assert rows[0]["ts"] > 0
    assert rows[1]["engine"] == "v3"


def test_jsonl_writer_closes_on_error(tmp_path):
    path = str(tmp_path / "rows.jsonl")
    try:
        with JsonlWriter(path) as out:
            out.write({"kind": "replay-cpu"})
            raise RuntimeError("replay blew up")
    except RuntimeError:
        pass
    assert out._f is None  # closed despite the error
    assert len(open(path).readlines()) == 1  # the row was flushed
    out.close()  # idempotent


def test_config_hash_stable_and_order_insensitive():
    a = config_hash({"x": 1, "y": {"z": 2}})
    b = config_hash({"y": {"z": 2}, "x": 1})
    assert a == b and len(a) == 12
    assert config_hash({"x": 2}) != a


def test_baseline_table():
    md = baseline_table([
        {"metric": "placements/sec", "value": "1.62M", "hardware": "v4-8",
         "source": "BENCH_r05"},
        {"kind": "whatif-aggregate", "placements_per_sec": 123.0},
    ])
    lines = md.splitlines()
    assert lines[0].startswith("| Metric ")
    assert "| placements/sec | 1.62M | v4-8 | BENCH_r05 |" in md
    assert "| whatif-aggregate | 123.0 | - | this run |" in md
