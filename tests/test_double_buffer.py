"""Double-buffered async dispatch (round 10): staging boundary b's
RELEASE passes before blocking on chunk b-1's failure scalar must be a
pure latency optimisation — results, disruption counters, and checkpoint
blobs are bit-identical with ``double_buffer`` on vs off, across plain
completions, the retry buffer, kube preemption, chaos eviction, and
checkpoint/resume."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim import boundary as B
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import NodeEvent
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])


def _trace(n_nodes=10, n_pods=96, seed=11, **kw):
    cluster = make_cluster(n_nodes, seed=seed)
    pods, _ = make_workload(
        n_pods, seed=seed, arrival_rate=30.0, duration_mean=8.0, **kw
    )
    return encode(cluster, pods)


def _pair(ec, ep, cfg, **kw):
    """Replay the same trace with double_buffer on and off; return both."""
    on = JaxReplayEngine(ec, ep, cfg, double_buffer=True, **kw).replay()
    off = JaxReplayEngine(ec, ep, cfg, double_buffer=False, **kw).replay()
    return on, off


def _assert_same(a, b):
    np.testing.assert_array_equal(a.assignments, b.assignments)
    assert a.placed == b.placed
    assert a.preemptions == b.preemptions
    assert a.evictions == b.evictions


def test_double_buffer_bit_identical_completions():
    """Completions + retry-buffer trace (the boundary mode the staging
    lives in): on == off, and the staged fast path actually engaged
    (boundary_retry called more often than the composed boundary() —
    non-vacuous)."""
    ec, ep = _trace()
    cfg = FrameworkConfig()
    calls = {"boundary": 0, "retry": 0}
    orig_b, orig_r = B.BoundaryOps.boundary, B.BoundaryOps.boundary_retry

    def count_b(self, b, t):
        calls["boundary"] += 1
        return orig_b(self, b, t)

    def count_r(self, b, t):
        calls["retry"] += 1
        return orig_r(self, b, t)

    B.BoundaryOps.boundary = count_b
    B.BoundaryOps.boundary_retry = count_r
    try:
        on, off = _pair(ec, ep, cfg, chunk_waves=3, retry_buffer=64,
                        granularity_guard=False)
    finally:
        B.BoundaryOps.boundary = orig_b
        B.BoundaryOps.boundary_retry = orig_r
    _assert_same(on, off)
    # boundary() composes boundary_retry, so a retry surplus counts the
    # boundaries served entirely from the staged release result.
    assert calls["retry"] > calls["boundary"], calls


def test_double_buffer_retry_and_preemption():
    """Retry buffer + kube preemption (the paths whose boundary reads the
    freshest mirror state) stay bit-identical."""
    ec, ep = _trace(n_nodes=6, n_pods=80, seed=5)
    on, off = _pair(
        ec, ep, FrameworkConfig(), chunk_waves=4, preemption="kube",
        retry_buffer=64, granularity_guard=False,
    )
    _assert_same(on, off)


def test_double_buffer_chaos_eviction():
    """Chaos timelines: staging is skipped exactly at boundaries where an
    event is due, so eviction ordering — and every disruption counter —
    is preserved."""
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(5)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=30.0)
        for i in range(28)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    evs = [
        NodeEvent(time=8.0, kind="node_down", node=0),
        NodeEvent(time=18.0, kind="node_up", node=0),
        NodeEvent(time=24.0, kind="node_down", node=1),
    ]
    mk = lambda dbuf: JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64, double_buffer=dbuf,
    ).replay(node_events=evs)
    on, off = mk(True), mk(False)
    _assert_same(on, off)
    assert on.evictions > 0  # non-vacuous
    assert on.evict_rescheduled == off.evict_rescheduled
    assert on.evict_latency_mean == off.evict_latency_mean


def test_double_buffer_checkpoint_blobs_identical(tmp_path):
    """Checkpoint blobs are written from the post-fold mirror, so the
    staged path must not perturb them: every array in every blob matches
    between on and off, and a cross-resume (blob written with one mode,
    resumed with the other) equals the uninterrupted run."""
    ec, ep = _trace(n_nodes=8, n_pods=64, seed=9)
    cfg = FrameworkConfig()
    mk = lambda dbuf: JaxReplayEngine(
        ec, ep, cfg, chunk_waves=2, preemption="kube", retry_buffer=64,
        double_buffer=dbuf, granularity_guard=False,
    )
    full = mk(True).replay()
    blobs = {}
    for dbuf in (True, False):
        ck = str(tmp_path / f"ck_{dbuf}.npz")
        mk(dbuf).replay(checkpoint_path=ck, checkpoint_every=2)
        with np.load(ck, allow_pickle=True) as z:
            blobs[dbuf] = {k: z[k].copy() for k in z.files}
    assert blobs[True].keys() == blobs[False].keys()
    for k in blobs[True]:
        np.testing.assert_array_equal(blobs[True][k], blobs[False][k],
                                      err_msg=f"blob field {k}")
    # Cross-mode resume: blob from double_buffer=False, resumed with True.
    ck = str(tmp_path / "ck_False.npz")
    resumed = mk(True).replay(checkpoint_path=ck, resume=True)
    _assert_same(full, resumed)


@pytest.mark.parametrize("knobs", [
    dict(with_affinity=True, with_spread=True, gang_fraction=0.2,
         gang_size=3),
])
@pytest.mark.slow
def test_double_buffer_feature_knobs(knobs):
    """Affinity/spread planes and gang scheduling ride the same boundary
    bookkeeping — on == off with every feature knob lit (one combined
    corner: tier-1 budget)."""
    ec, ep = _trace(n_nodes=8, n_pods=72, seed=3, **knobs)
    on, off = _pair(ec, ep, FrameworkConfig(), chunk_waves=4,
                    retry_buffer=32, granularity_guard=False)
    _assert_same(on, off)
