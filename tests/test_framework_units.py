"""Unit tests: scheduling queue semantics, normalize functions, scoring
strategies across backends, facade API, scale smoke (SURVEY.md §4.1, §4.5)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.queue import SchedulingQueue
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.ops import cpu as K


class TestQueue:
    def test_priority_then_fifo(self):
        q = SchedulingQueue()
        q.push(1, priority=0)
        q.push(2, priority=100)
        q.push(3, priority=100)
        assert q.pop() == 2  # higher priority first
        assert q.pop() == 3  # FIFO within priority
        assert q.pop() == 1
        assert q.pop() is None

    def test_backoff_is_exponential_and_capped(self):
        q = SchedulingQueue()
        for attempt, want_delay in [(0, 1.0), (1, 2.0), (2, 4.0), (3, 8.0), (4, 10.0), (5, 10.0)]:
            q.requeue_backoff(7, priority=0, now=100.0)
            assert q.next_backoff_time() == pytest.approx(100.0 + want_delay)
            q.flush_backoff(200.0)
            assert q.pop() == 7

    def test_unschedulable_flush(self):
        q = SchedulingQueue()
        q.mark_unschedulable(5, priority=10)
        assert len(q) == 0 and q.num_unschedulable == 1
        q.flush_unschedulable()
        assert q.pop() == 5 and q.num_unschedulable == 0

    def test_backoff_not_released_early(self):
        q = SchedulingQueue()
        q.requeue_backoff(1, priority=0, now=0.0)
        q.flush_backoff(0.5)
        assert q.pop() is None
        q.flush_backoff(1.5)
        assert q.pop() == 1

    def test_flush_unschedulable_routes_through_backoff(self):
        # [K8S] MoveAllToActiveOrBackoffQueue: a flushed pod whose backoff
        # has not expired lands in the backoff queue, not active.
        q = SchedulingQueue()
        q.mark_unschedulable(3, priority=0, now=10.0)  # attempt 1 → 1s
        q.flush_unschedulable(10.5)
        assert q.pop() is None and q.num_backoff == 1
        q.flush_backoff(11.0)
        assert q.pop() == 3

    def test_flush_unschedulable_expired_backoff_goes_active(self):
        q = SchedulingQueue()
        q.mark_unschedulable(3, priority=0, now=10.0)
        q.flush_unschedulable(11.5)
        assert q.pop() == 3 and q.num_backoff == 0


class TestNormalize:
    def test_normalize_max_basic(self):
        raw = np.array([0.0, 5.0, 10.0], dtype=np.float32)
        feas = np.array([True, True, True])
        out = K.normalize_max(raw, feas)
        assert list(out) == [0.0, 50.0, 100.0]
        rev = K.normalize_max(raw, feas, reverse=True)
        assert list(rev) == [100.0, 50.0, 0.0]

    def test_normalize_max_all_zero(self):
        raw = np.zeros(3, dtype=np.float32)
        feas = np.ones(3, dtype=bool)
        assert (K.normalize_max(raw, feas) == 0).all()
        assert (K.normalize_max(raw, feas, reverse=True) == 100).all()

    def test_normalize_max_ignores_infeasible_for_max(self):
        raw = np.array([1000.0, 5.0, 10.0], dtype=np.float32)
        feas = np.array([False, True, True])
        out = K.normalize_max(raw, feas)
        assert out[2] == 100.0

    def test_normalize_min_max_negative(self):
        raw = np.array([-10.0, 0.0, 10.0], dtype=np.float32)
        feas = np.ones(3, dtype=bool)
        out = K.normalize_min_max(raw, feas)
        assert list(out) == [0.0, 50.0, 100.0]

    def test_normalize_min_max_constant(self):
        raw = np.full(3, 7.0, dtype=np.float32)
        assert (K.normalize_min_max(raw, np.ones(3, bool)) == 0).all()


class TestScoringStrategies:
    """MostAllocated and RequestedToCapacityRatio parity across all three
    implementations (oracle formulas inline here)."""

    def _case(self):
        from kubernetes_simulator_tpu.sim.synthetic import config1

        cluster, pods, _ = config1(num_nodes=20, num_pods=150)
        return cluster, pods

    @pytest.mark.parametrize(
        "plugins",
        [
            [{"name": "NodeResourcesFit", "args": {"strategy": "MostAllocated"}}],
            [
                {
                    "name": "NodeResourcesFit",
                    "args": {
                        "strategy": "RequestedToCapacityRatio",
                        "shape": [
                            {"utilization": 0, "score": 0},
                            {"utilization": 50, "score": 9},
                            {"utilization": 100, "score": 3},
                        ],
                    },
                }
            ],
        ],
    )
    def test_cpu_jax_parity(self, plugins):
        from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
        from kubernetes_simulator_tpu.sim.greedy import greedy_replay
        from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine

        cluster, pods = self._case()
        ec, ep = encode(cluster, pods)
        cpu = greedy_replay(ec, ep, FrameworkConfig(plugins=plugins))
        jx = JaxReplayEngine(ec, ep, FrameworkConfig(plugins=plugins)).replay()
        assert (cpu.assignments == jx.assignments).all()


class TestFacade:
    def test_simulator_api(self):
        from kubernetes_simulator_tpu.api import Simulator
        from kubernetes_simulator_tpu.sim.synthetic import config1

        cluster, pods, plugins = config1(num_nodes=15, num_pods=60)
        sim = Simulator(cluster, pods, strategy="jax", plugins=plugins)
        res = sim.run()
        assert res.placed == 60
        wi = sim.what_if(num_scenarios=4, seed=1)
        assert wi.placed.shape == (4,)
        assert "cpu" in Simulator.strategies() and "jax" in Simulator.strategies()


@pytest.mark.slow
def test_scale_smoke_5k_nodes():
    """SURVEY.md §4.5: a 5k-node replay completes under a wall budget even
    on the CPU XLA backend (pods kept small to bound CI time)."""
    import time

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode as enc
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

    cluster = make_cluster(5000, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(3000, seed=0, with_affinity=True, with_spread=True,
                            with_tolerations=True)
    ec, ep = enc(cluster, pods)
    t0 = time.perf_counter()
    res = JaxReplayEngine(ec, ep, FrameworkConfig(), chunk_waves=256).replay()
    wall = time.perf_counter() - t0
    # Greedy has no retry loop, so a few DoNotSchedule spread pods may stay
    # unschedulable at arrival time.
    assert res.placed >= 2980
    assert wall < 120.0, f"5k-node smoke too slow: {wall:.1f}s"
