"""Continuous randomized parity evidence (VERDICT round-1 item 9): a
reduced-width seeded slice of scripts/fuzz_parity.py runs in CI under the
``fuzz`` marker. The full-width harness stays ad hoc (48+ trials)."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
)


@pytest.mark.fuzz
def test_seeded_fuzz_slice():
    from fuzz_parity import run_fuzz

    cases, fails = run_fuzz(trials=15, master=123)
    assert fails == 0
    assert cases >= 10  # most trials must actually produce comparisons
