"""Continuous randomized parity evidence (VERDICT round-1 item 9): a
reduced-width seeded slice of scripts/fuzz_parity.py runs in CI under the
``fuzz`` marker.

Round 5 (VERDICT r4 next #7): the full ad-hoc campaigns are now DURABLE —
``pytest -m fuzz_full`` replays the four pinned-seed campaigns
(masters 7/123/321/777, 25 trials each ⇒ ~160 comparison cases, the
round-4 evidence total, covering completions, tier preemption ×
completions, the what-if retry buffer, and the round-5 single-replay
retry / kube-preemption boundary pass). Budget ~7 min per campaign on a
warm compile cache (~30 min for all four; run a single one with
``-k 'campaign[7]'``). Run it before releases and whenever sim/greedy,
sim/boundary, sim/jax_runtime, sim/whatif or ops/tpu3 change semantics;
the 15-trial ``fuzz`` slice stays in the default marker set for cheap
regression signal."""

import os
import sys

import pytest

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts")
)


@pytest.mark.fuzz_quick
def test_seeded_fuzz_quick():
    """Round 6 (PR-2 S5): a seeded randomized parity pass that runs in
    the DEFAULT pytest gate (the marker is NOT in the addopts deselect
    list). Small-shape corner of the same knob space as ``fuzz``; sized
    to stay <=30s with the compile cache off."""
    from fuzz_parity import run_fuzz

    cases, fails = run_fuzz(trials=2, master=2026, quick=True)
    assert fails == 0
    assert cases >= 2


@pytest.mark.fuzz
@pytest.mark.slow
def test_seeded_fuzz_slice():
    """15-trial slice. Also ``slow`` since round 6: with the persistent
    compile cache off (CPU unsoundness — utils/compile_cache.py) every
    trial pays cold compiles and the slice runs minutes, well past the
    >25s slow bar; ``test_seeded_fuzz_quick`` keeps the default gate's
    randomized signal."""
    from fuzz_parity import run_fuzz

    cases, fails = run_fuzz(trials=15, master=123)
    assert fails == 0
    assert cases >= 10  # most trials must actually produce comparisons


@pytest.mark.fuzz_full
@pytest.mark.slow
@pytest.mark.parametrize("master", [7, 123, 321, 777])
def test_fuzz_campaign(master):
    """One pinned campaign of the round-4/5 evidence set (4 campaigns ×
    25 trials ≈ the 157-case ad-hoc total, re-runnable on demand)."""
    from fuzz_parity import run_fuzz

    cases, fails = run_fuzz(trials=25, master=master)
    assert fails == 0
    assert cases >= 20
