"""scripts/bench_compare.py (round 12): bench-archive diffing — headline
regression gating, phase-share drift notes, DCN scaling comparison, and
the BENCH_r* wrapper unwrap."""

import json
import os
import sys

import pytest

sys.path.insert(
    0,
    os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "scripts")
    ),
)

from bench_compare import (  # noqa: E402
    compare_pair,
    load_bench,
    main,
    phase_shares,
)


def _bench(value, phases=None, dcn=None, borg=None, recovery=None,
           headline=None, **top):
    detail = {}
    if phases is not None:
        detail["phases"] = phases
    if dcn is not None:
        detail["dcn_scaling"] = dcn
    if borg is not None:
        detail["borg_scale"] = borg
    if recovery is not None:
        detail["dcn_recovery"] = recovery
    if headline is not None:
        detail["borg_headline"] = headline
    return {"metric": "pps", "value": value, "unit": "1/s",
            "detail": detail, **top}


def _write(tmp_path, name, doc, wrap=False):
    p = tmp_path / name
    p.write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 0, "parsed": doc} if wrap else doc
    ))
    return str(p)


def test_load_bench_unwraps_archive(tmp_path):
    doc = _bench(100.0)
    raw = load_bench(_write(tmp_path, "raw.json", doc))
    wrapped = load_bench(_write(tmp_path, "wrap.json", doc, wrap=True))
    assert raw == wrapped == doc
    (tmp_path / "junk.json").write_text(json.dumps({"rows": []}))
    with pytest.raises(ValueError, match="not a bench result"):
        load_bench(str(tmp_path / "junk.json"))


def test_phase_shares():
    assert phase_shares({}) == {}
    assert phase_shares({"phases": {}}) == {}
    s = phase_shares({"phases": {"p0/dispatch": 3.0, "p0/device_wait": 1.0}})
    assert s == {"p0/dispatch": 0.75, "p0/device_wait": 0.25}


def test_headline_regression_flagged():
    reg, notes = compare_pair("a", _bench(100.0), "b", _bench(85.0), 0.10)
    assert len(reg) == 1 and "REGRESSION" in reg[0]
    # Within threshold: a note, not a regression.
    reg, notes = compare_pair("a", _bench(100.0), "b", _bench(95.0), 0.10)
    assert reg == [] and any("-5.0%" in n for n in notes)
    # Improvement is never a regression.
    reg, _ = compare_pair("a", _bench(100.0), "b", _bench(150.0), 0.10)
    assert reg == []


def test_phase_share_drift_is_note_not_regression():
    a = _bench(100.0, phases={"dispatch": 1.0, "device_wait": 1.0})
    b = _bench(100.0, phases={"dispatch": 9.0, "device_wait": 1.0})
    reg, notes = compare_pair("a", a, "b", b, 0.10)
    assert reg == []
    assert any("phase share dispatch" in n for n in notes)


def test_dcn_scaling_regression_flagged():
    a = _bench(100.0, dcn={"aggregate_pps": 1000.0})
    b = _bench(100.0, dcn={"aggregate_pps": 500.0})
    reg, _ = compare_pair("a", a, "b", b, 0.10)
    assert len(reg) == 1 and "aggregate_pps" in reg[0]


def _borg(pps, nodes=1000, pods=20000, shards=8, paged=True):
    return {"nodes": nodes, "pods": pods, "node_shards": shards,
            "paged": paged, "pps": pps}


def test_borg_scale_comparison():
    # Same shape, pps drop beyond threshold: REGRESSION.
    a = _bench(100.0, borg=_borg(5000.0))
    b = _bench(100.0, borg=_borg(4000.0))
    reg, _ = compare_pair("a", a, "b", b, 0.10)
    assert len(reg) == 1 and "borg_scale pps" in reg[0]
    # Within threshold: informational note.
    reg, notes = compare_pair("a", a, "b", _bench(100.0, borg=_borg(4900.0)),
                              0.10)
    assert reg == [] and any("borg_scale pps" in n for n in notes)
    # First appearance: informational, never a regression.
    reg, notes = compare_pair("a", _bench(100.0), "b", b, 0.10)
    assert reg == [] and any("first appearance" in n for n in notes)
    # Shape changed (different node count): pps not compared.
    reg, notes = compare_pair(
        "a", a, "b", _bench(100.0, borg=_borg(1.0, nodes=2000)), 0.10)
    assert reg == [] and any("shape changed" in n for n in notes)


def _headline(pps, nodes=1000, pods=20000, shards=8, paged=True,
              wall=4.0, stalls=0):
    return {"nodes": nodes, "pods": pods, "node_shards": shards,
            "paged": paged, "pps": pps, "wall_s": wall,
            "pager_stalls": stalls, "replicated_resident_mib": 12.5}


def test_borg_headline_comparison():
    # Round 16: same composed shape, pps drop beyond threshold regresses.
    a = _bench(100.0, headline=_headline(5000.0))
    b = _bench(100.0, headline=_headline(4000.0, wall=5.0, stalls=3))
    reg, notes = compare_pair("a", a, "b", b, 0.10)
    assert len(reg) == 1 and "borg_headline pps" in reg[0]
    # The wall and pager-stall lines ride along as notes, never gating.
    assert any("borg_headline wall_s" in n for n in notes)
    assert any("borg_headline pager_stalls" in n for n in notes)
    # Within threshold: informational note.
    reg, notes = compare_pair(
        "a", a, "b", _bench(100.0, headline=_headline(4900.0)), 0.10)
    assert reg == [] and any("borg_headline pps" in n for n in notes)
    # First appearance: informational, never a regression.
    reg, notes = compare_pair("a", _bench(100.0), "b", b, 0.10)
    assert reg == [] and any(
        "borg_headline: first appearance" in n for n in notes)
    # Shape changed: pps not compared.
    reg, notes = compare_pair(
        "a", a, "b", _bench(100.0, headline=_headline(1.0, shards=16)), 0.10)
    assert reg == [] and any(
        "borg_headline: shape changed" in n for n in notes)


def test_memory_watermarks_are_notes():
    # Round 16: top-level rss/residency watermarks never gate — a 10x RSS
    # growth is a note (the allocator moves, the gate is the pps).
    a = _bench(100.0, rss_peak_mib=300.0, replicated_resident_peak_mib=40.0)
    b = _bench(100.0, rss_peak_mib=3000.0, replicated_resident_peak_mib=80.0)
    reg, notes = compare_pair("a", a, "b", b, 0.10)
    assert reg == []
    assert any("rss_peak_mib: 300.0 -> 3000.0" in n for n in notes)
    assert any("replicated_resident_peak_mib" in n for n in notes)
    # First appearance when the old round predates the stamp.
    reg, notes = compare_pair("a", _bench(100.0), "b", b, 0.10)
    assert reg == [] and any(
        "rss_peak_mib: first appearance" in n for n in notes)


def test_dcn_recovery_block_is_informational_only():
    # Round 15: recovery costs price an OPT-IN feature (checkpoint
    # publication is off in the headline) — even a 100x wall blowup is a
    # note, never a regression.
    rec_a = {"ckpt_blob_mib": 1.2, "ckpt_encode_s": 0.01,
             "ckpt_publish_overhead_pct": 1.5,
             "recovery_restore_wall_s": 0.02}
    rec_b = {"ckpt_blob_mib": 1.2, "ckpt_encode_s": 1.0,
             "ckpt_publish_overhead_pct": 80.0,
             "recovery_restore_wall_s": 2.0}
    reg, notes = compare_pair(
        "a", _bench(100.0, recovery=rec_a),
        "b", _bench(100.0, recovery=rec_b), 0.10)
    assert reg == []
    assert any(
        "dcn_recovery ckpt_publish_overhead_pct" in n and "informational"
        in n for n in notes)
    assert any("dcn_recovery recovery_restore_wall_s" in n for n in notes)
    # First appearance: one summary note, no per-key diffs.
    reg, notes = compare_pair(
        "a", _bench(100.0), "b", _bench(100.0, recovery=rec_b), 0.10)
    assert reg == []
    assert any("dcn_recovery: first appearance" in n for n in notes)


def test_postmortem_block_is_informational_only():
    # Round 21: post-mortem reconstruction runs OFFLINE over a dead
    # run's artifacts — even a big audit-wall jump is a note, never a
    # regression; a causal-link collapse is visible the same way.
    pm_a = {"audit_wall_s": 0.01, "events_ingested": 40,
            "links_resolved": 30}
    pm_b = {"audit_wall_s": 1.5, "events_ingested": 40,
            "links_resolved": 2}
    a, b = _bench(100.0), _bench(100.0)
    a["detail"]["postmortem"] = pm_a
    b["detail"]["postmortem"] = pm_b
    reg, notes = compare_pair("a", a, "b", b, 0.10)
    assert reg == []
    assert any(
        "postmortem audit_wall_s" in n and "informational" in n
        for n in notes)
    assert any("postmortem links_resolved: 30 -> 2" in n for n in notes)
    # First appearance: one summary note.
    reg, notes = compare_pair("a", _bench(100.0), "b", b, 0.10)
    assert reg == []
    assert any("postmortem: first appearance" in n for n in notes)


def _service(qps, nodes=200, pods=2000, cold=3.0, warm=0.1):
    return {"nodes": nodes, "pods": pods, "warm_queries_per_sec": qps,
            "cold_latency_s": cold, "warm_latency_median_s": warm,
            "warm_speedup": round(cold / warm, 1)}


def test_service_block_gates_warm_qps():
    """Round 22: warm queries/sec through the serving plane gates like a
    headline pps at the same shape; cold start stays informational."""
    a = _bench(100.0)
    a["detail"]["service"] = _service(30.0)
    b = _bench(100.0)
    b["detail"]["service"] = _service(20.0)
    reg, _ = compare_pair("a", a, "b", b, 0.10)
    assert len(reg) == 1 and "service warm queries/sec" in reg[0]
    # Within threshold: note, and the latency fields ride as notes too.
    c = _bench(100.0)
    c["detail"]["service"] = _service(28.5, cold=2.0)
    reg, notes = compare_pair("a", a, "b", c, 0.10)
    assert reg == []
    assert any("service warm queries/sec" in n for n in notes)
    assert any("cold_latency_s" in n and "informational" in n
               for n in notes)
    # First appearance: informational.
    reg, notes = compare_pair("a", _bench(100.0), "b", b, 0.10)
    assert reg == [] and any(
        "service: first appearance" in n for n in notes)
    # Shape changed: warm qps not compared.
    d = _bench(100.0)
    d["detail"]["service"] = _service(1.0, nodes=400)
    reg, notes = compare_pair("a", a, "b", d, 0.10)
    assert reg == [] and any("service: shape changed" in n for n in notes)


def test_main_exit_codes(tmp_path, capsys):
    ok_a = _write(tmp_path, "a.json", _bench(100.0), wrap=True)
    ok_b = _write(tmp_path, "b.json", _bench(101.0))
    assert main([ok_a, ok_b]) == 0
    bad = _write(tmp_path, "c.json", _bench(50.0))
    assert main([ok_a, ok_b, bad]) == 1
    out = capsys.readouterr()
    assert "REGRESSION" in out.out
    # Tighter threshold flips the ok pair too.
    assert main(["--threshold", "0.001", ok_b, ok_a]) == 1
