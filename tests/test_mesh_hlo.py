"""The ×8 projection's missing evidence link (VERDICT r4 next #5 /
missing #1): the compiled mesh-sharded chunk program must contain NO
cross-scenario collective — a hidden all-reduce inside the chunk scan
would serialize the scenario mesh and the single-chip → v5e-8 projection
would die. SURVEY §5 asserts "collectives appear only at metric-gather
time"; this lowers the actual program on the virtual 8-device CPU mesh
(conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8) and
string-matches the optimized, SPMD-partitioned HLO. No TPU needed: the
partitioner that would insert collectives runs at compile time.

Round 10 made the mesh the DEFAULT headline configuration (bench.py runs
8 devices × 1024 scenarios) and moved the mesh chunk program to the
device-gather src signature with device-side releases — so this suite
now lowers those exact programs, at the headline scenario count as well
as the small smoke shape, plus the bucketed release program."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.parallel.mesh import (
    make_mesh,
    scenario_sharding,
    shard_scenario_tree,
)
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

# Optimized-HLO op names for every XLA cross-device primitive (start/done
# variants share these prefixes).
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
    "reduce-scatter",
    "partition-id",
    "send",  # point-to-point would be just as serializing
    "recv",
)


def test_detector_catches_real_collective():
    """Positive control: on this same mesh, a genuine cross-shard
    reduction MUST show up as an all-reduce in the compiled text — else
    the no-collectives assertions below would be vacuous (they were,
    until the mesh size guard: a 1-device mesh compiles everything
    collective-free)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubernetes_simulator_tpu.parallel.mesh import SCENARIO_AXIS

    mesh = make_mesh()
    assert mesh.devices.size == 8, "virtual 8-device mesh missing"
    f = jax.jit(
        lambda x: jnp.sum(x, axis=0),
        in_shardings=(NamedSharding(mesh, P(SCENARIO_AXIS)),),
        out_shardings=NamedSharding(mesh, P()),
    )
    txt = f.lower(jax.ShapeDtypeStruct((8, 16), jnp.float32)).compile().as_text()
    assert "all-reduce" in txt


def _mesh_engine(S: int, with_durations: bool) -> WhatIfEngine:
    cluster = make_cluster(12, seed=21, taint_fraction=0.2)
    # Durations short enough (and the pod stream long enough) that at
    # least one static release bucket lands inside the chunk horizon —
    # the release program below must have something to lower.
    pods, _ = make_workload(
        96 if with_durations else 48, seed=21, with_affinity=True,
        with_spread=True, with_tolerations=True,
        duration_mean=10.0 if with_durations else None,
    )
    ec, ep = encode(cluster, pods)
    scen = uniform_scenarios(ec, S, seed=21, p_capacity=0.5, p_taint=0.3)
    mesh = make_mesh()
    assert mesh.devices.size == 8, "virtual 8-device mesh missing"
    return WhatIfEngine(
        ec, ep, scen, FrameworkConfig(), mesh=mesh, chunk_waves=4
    )


def _chunk_args(eng: WhatIfEngine, with_durations: bool):
    """Reproduce run()'s first-chunk argument assembly for the mesh src
    path (round 10: device-gathered slots, device-side releases when
    durations are on) — dc/states scenario-sharded, sources replicated."""
    idx = eng.waves.idx
    C = min(eng.chunk_waves, max(idx.shape[0], 1))
    pad_to = ((idx.shape[0] + C - 1) // C) * C
    if pad_to != idx.shape[0]:
        idx = np.concatenate(
            [idx, np.full((pad_to - idx.shape[0], idx.shape[1]), PAD, np.int32)]
        )
    dc = shard_scenario_tree(eng.mesh, eng.sset.dc)
    states = shard_scenario_tree(eng.mesh, eng._init_states())
    srcs = eng._slot_srcs
    assert srcs is not None, "v3 mesh engine should pre-stage slot sources"
    idx0 = jnp.asarray(idx[:C])
    if not with_durations:
        return (dc, states, srcs[0], srcs[1], idx0), None
    # Completions-on (the north-star semantics): since round 10 the mesh
    # takes the DEVICE-release path — releases must not push the chunk
    # program into host folds, and must themselves stay collective-free.
    assert eng._completions_dev, (
        "device-release path should engage under a mesh (round 10)"
    )
    stg = eng._stage_dev_rel(idx, C)
    vassign = jax.jit(
        lambda a: jnp.broadcast_to(a[None], (eng.S,) + a.shape),
        out_shardings=scenario_sharding(eng.mesh),
    )(stg["va"])
    args = (dc, states, srcs[0], srcs[1], idx0, stg["b_c"][0], vassign)
    rel = None
    for rc in stg["rel_calls"]:
        if rc is not None:
            rel = (states, vassign) + rc
            break
    return args, rel


def _assert_no_collectives(txt: str) -> None:
    assert "ENTRY" in txt  # sanity: this is real HLO, not an empty string
    lines = txt.splitlines()
    hits = [
        ln.strip()
        for ln in lines
        for op in COLLECTIVE_OPS
        if f" {op}" in ln or ln.lstrip().startswith(op)
    ]
    assert not hits, (
        "mesh chunk program contains cross-device collectives — the "
        f"scenario axis is no longer embarrassingly parallel:\n"
        + "\n".join(hits[:10])
    )


# 8 = smoke shape; 1024 = the bench.py headline (8 devices × 128
# scenarios/device). The partitioner runs at compile time, so this pins
# the SHIPPED configuration collective-free, not just a toy.
@pytest.mark.parametrize(
    "S", [8, pytest.param(1024, marks=pytest.mark.slow)])
def test_mesh_chunk_program_has_no_collectives(S):
    eng = _mesh_engine(S, with_durations=False)
    args, _ = _chunk_args(eng, with_durations=False)
    _assert_no_collectives(eng._chunk_fn.lower(*args).compile().as_text())


@pytest.mark.parametrize(
    "S", [8, pytest.param(1024, marks=pytest.mark.slow)])
def test_mesh_chunk_program_no_collectives_with_completions(S):
    """The completions-on shape (the north-star semantics): releases run
    on-device under mesh since round 10, so both the chunk program and
    the bucketed release program must be collective-free."""
    eng = _mesh_engine(S, with_durations=True)
    args, rel = _chunk_args(eng, with_durations=True)
    _assert_no_collectives(eng._chunk_fn.lower(*args).compile().as_text())
    assert rel is not None, "expected at least one static release bucket"
    rel_fn = eng._release_fn(rel[2].shape[0])
    _assert_no_collectives(rel_fn.lower(*rel).compile().as_text())


# ── Node-sharded chunk program (round 14) ────────────────────────────
# The OTHER mesh axis: one scenario, node planes split across devices.
# Here collectives are not forbidden — they are RATIONED. The design
# claim ("one tiny (score, node-id) exchange per slot is the only
# collective in the chunk loop") is pinned by whitelisting the compiled
# op set: the winner exchange lowers to all-gather (+ all-reduce for
# the packed plugin folds; partition-id for global-id arithmetic), and
# anything else — all-to-all, permutes, point-to-point, reduce-scatter
# — means node planes are being reshuffled mid-scan.

NODE_SHARD_ALLOWED = frozenset({"all-gather", "all-reduce", "partition-id"})


def _collective_hits(txt):
    assert "ENTRY" in txt
    return sorted({
        op
        for ln in txt.splitlines()
        for op in COLLECTIVE_OPS
        if f" {op}" in ln or ln.lstrip().startswith(op)
    })


def _node_sharded_hlo(fit_only: bool) -> str:
    from kubernetes_simulator_tpu.ops import tpu as T
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
    from kubernetes_simulator_tpu.sim.synthetic import config1

    if fit_only:
        cluster, pods, _ = config1(24, 64, seed=3)
    else:
        cluster = make_cluster(24, seed=3, taint_fraction=0.2)
        pods, _ = make_workload(
            64, seed=3, with_affinity=True, with_spread=True,
            with_tolerations=True, gang_fraction=0.1, gang_size=4,
        )
    ec, ep = encode(cluster, pods)
    eng = JaxReplayEngine(
        ec, ep, FrameworkConfig(), node_shards=8, chunk_waves=4
    )
    state = eng._init_dev_state()
    C = min(eng.chunk_waves, eng.waves.idx.shape[0])
    src = T.gather_slots(eng.pods, eng.waves.idx[:C])
    return eng.chunk_fn.lower(eng.dc, state, src).compile().as_text()


def test_node_sharded_chunk_collectives_whitelisted():
    ops = _collective_hits(_node_sharded_hlo(fit_only=False))
    assert "all-gather" in ops, (
        "node-sharded chunk program lowered without the winner exchange "
        "— selection is no longer crossing shards (is the mesh real?)"
    )
    extra = set(ops) - NODE_SHARD_ALLOWED
    assert not extra, (
        "node-sharded chunk program contains collectives beyond the "
        f"per-slot selection/fold exchanges: {sorted(extra)} — node "
        "planes are being reshuffled inside the chunk scan"
    )


def _gather_row_widths(txt):
    """Per-shard row widths of every all-gather in the compiled program
    (the gathered operand is f32[nshards, width])."""
    return sorted({
        int(m.group(1))
        for m in re.finditer(r"= f32\[8,(\d+)\][^ ]* all-gather\(", txt)
    })


def test_node_sharded_fit_only_two_phase_exchange():
    """Round 19 slims the selection exchange to two phases: phase 1
    all-gathers ONLY the slim (score, global-node-id) pair — a 2-wide
    f32 row per shard — and phase 2 moves the winner's domain rows with
    a single owner-masked all-reduce. Fit-only drops the packed plugin
    folds, so the compiled op set is exactly those two exchanges plus
    the partition-id for global-id/owner arithmetic, and every gathered
    row is provably the slim pair, never the old (2+2G)-wide one."""
    txt = _node_sharded_hlo(fit_only=True)
    ops = _collective_hits(txt)
    assert set(ops) == {"all-gather", "all-reduce", "partition-id"}, (
        f"fit-only two-phase program op set drifted: {ops}"
    )
    assert _gather_row_widths(txt) == [2], (
        "two-phase phase-1 gather must move only the (score, id) pair — "
        f"saw per-shard row widths {_gather_row_widths(txt)}"
    )


def test_node_sharded_fit_only_legacy_single_exchange(monkeypatch):
    """The legacy single-exchange program (KSIM_TWO_PHASE_EXCHANGE=0)
    is still the round-14 shape: one wide all-gather carrying
    (score, id, gdom, hasdom) = 2+2G floats per shard, no all-reduce.
    Pinned so the A/B switch stays a real program-level fork."""
    monkeypatch.setenv("KSIM_TWO_PHASE_EXCHANGE", "0")
    txt = _node_sharded_hlo(fit_only=True)
    ops = _collective_hits(txt)
    assert "all-gather" in ops
    assert set(ops) <= {"all-gather", "partition-id"}, (
        f"legacy fit-only program grew extra collectives: {ops}"
    )
    widths = _gather_row_widths(txt)
    assert len(widths) == 1 and widths[0] > 2, (
        "legacy exchange should gather the combined (2+2G)-wide row — "
        f"saw {widths}"
    )
