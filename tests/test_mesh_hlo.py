"""The ×8 projection's missing evidence link (VERDICT r4 next #5 /
missing #1): the compiled mesh-sharded chunk program must contain NO
cross-scenario collective — a hidden all-reduce inside the chunk scan
would serialize the scenario mesh and the single-chip → v5e-8 projection
would die. SURVEY §5 asserts "collectives appear only at metric-gather
time"; this lowers the actual program on the virtual 8-device CPU mesh
(conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8) and
string-matches the optimized, SPMD-partitioned HLO. No TPU needed: the
partitioner that would insert collectives runs at compile time."""

import numpy as np

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.ops import tpu as T
from kubernetes_simulator_tpu.ops import tpu3 as V3
from kubernetes_simulator_tpu.parallel.mesh import make_mesh, replicate_tree, shard_scenario_tree
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

# Optimized-HLO op names for every XLA cross-device primitive (start/done
# variants share these prefixes).
COLLECTIVE_OPS = (
    "all-reduce",
    "all-gather",
    "all-to-all",
    "collective-permute",
    "collective-broadcast",
    "reduce-scatter",
    "partition-id",
    "send",  # point-to-point would be just as serializing
    "recv",
)


def test_detector_catches_real_collective():
    """Positive control: on this same mesh, a genuine cross-shard
    reduction MUST show up as an all-reduce in the compiled text — else
    the no-collectives assertions below would be vacuous (they were,
    until the mesh size guard: a 1-device mesh compiles everything
    collective-free)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kubernetes_simulator_tpu.parallel.mesh import SCENARIO_AXIS

    mesh = make_mesh()
    assert mesh.devices.size == 8, "virtual 8-device mesh missing"
    f = jax.jit(
        lambda x: jnp.sum(x, axis=0),
        in_shardings=(NamedSharding(mesh, P(SCENARIO_AXIS)),),
        out_shardings=NamedSharding(mesh, P()),
    )
    txt = f.lower(jax.ShapeDtypeStruct((8, 16), jnp.float32)).compile().as_text()
    assert "all-reduce" in txt


def _compiled_chunk_hlo(with_durations: bool) -> str:
    cluster = make_cluster(12, seed=21, taint_fraction=0.2)
    pods, _ = make_workload(
        48, seed=21, with_affinity=True, with_spread=True,
        with_tolerations=True,
        duration_mean=30.0 if with_durations else None,
    )
    ec, ep = encode(cluster, pods)
    scen = uniform_scenarios(ec, 8, seed=21, p_capacity=0.5, p_taint=0.3)
    mesh = make_mesh()
    assert mesh.devices.size == 8, "virtual 8-device mesh missing"
    eng = WhatIfEngine(
        ec, ep, scen, FrameworkConfig(), mesh=mesh, chunk_waves=4
    )
    # Reproduce run()'s first-chunk argument assembly (the mesh branch:
    # host-gathered slots replicated, dc/states scenario-sharded).
    idx = eng.waves.idx
    C = min(eng.chunk_waves, max(idx.shape[0], 1))
    rows = idx[:C]
    if rows.shape[0] < C:
        rows = np.concatenate(
            [rows, np.full((C - rows.shape[0], rows.shape[1]), PAD, np.int32)]
        )
    dc = shard_scenario_tree(eng.mesh, eng.sset.dc)
    states = shard_scenario_tree(eng.mesh, eng._init_states())
    slots = replicate_tree(eng.mesh, T.gather_slots(ep, rows))
    args = [dc, states, slots]
    if eng.engine == "v3":
        args.append(replicate_tree(eng.mesh, V3.gather_extra(eng.static3, rows)))
    return eng._chunk_fn.lower(*args).compile().as_text()


def _assert_no_collectives(txt: str) -> None:
    assert "ENTRY" in txt  # sanity: this is real HLO, not an empty string
    lines = txt.splitlines()
    hits = [
        ln.strip()
        for ln in lines
        for op in COLLECTIVE_OPS
        if f" {op}" in ln or ln.lstrip().startswith(op)
    ]
    assert not hits, (
        "mesh chunk program contains cross-device collectives — the "
        f"scenario axis is no longer embarrassingly parallel:\n"
        + "\n".join(hits[:10])
    )


def test_mesh_chunk_program_has_no_collectives():
    _assert_no_collectives(_compiled_chunk_hlo(with_durations=False))


def test_mesh_chunk_program_no_collectives_with_completions():
    """The completions-on shape (the north-star semantics): releases are
    host-fold deltas under mesh, so the chunk program must still be
    collective-free."""
    _assert_no_collectives(_compiled_chunk_hlo(with_durations=True))
