"""Native C++ layer: wave-packer parity with the Python reference packer,
and columnar trace CSV round-trip (SURVEY.md §2 trace driver; the native
runtime components the framework keeps outside Python)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu import native
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.borg import (
    BorgSpec,
    export_trace_csv,
    load_trace_csv,
    make_borg_encoded,
)
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.waves import WaveBatch, pack_waves


def _python_pack(ep, wave_width, order=None):
    """The original pure-Python packer (reference semantics)."""
    if order is None:
        unbound = np.nonzero(ep.bound_node == PAD)[0]
        order = unbound[np.argsort(ep.arrival[unbound], kind="stable")]
    members = {}
    for p in order:
        g = int(ep.group_id[p])
        if g != PAD:
            members.setdefault(g, []).append(int(p))
    waves, current, consumed = [], [], set()
    for p in order:
        p = int(p)
        if p in consumed:
            continue
        g = int(ep.group_id[p])
        batch = [p] if g == PAD else members[g]
        if len(current) + len(batch) > wave_width:
            waves.append(current)
            current = []
        current.extend(batch)
        consumed.update(batch)
    if current:
        waves.append(current)
    idx = np.full((max(len(waves), 1), wave_width), PAD, dtype=np.int32)
    for i, w in enumerate(waves):
        idx[i, : len(w)] = w
    return idx


@pytest.mark.skipif(not native.available(), reason="native lib unavailable")
class TestNativeWavepack:
    def test_parity_random_gangs(self):
        for seed in range(4):
            cluster = make_cluster(20, seed=seed)
            pods, _ = make_workload(
                500, seed=seed, gang_fraction=0.2, gang_size=5, with_affinity=True
            )
            _, ep = encode(cluster, pods)
            got = pack_waves(ep, 8)
            want = _python_pack(ep, 8)
            np.testing.assert_array_equal(got.idx, want)

    def test_parity_no_gangs_odd_width(self):
        cluster = make_cluster(10, seed=1)
        pods, _ = make_workload(97, seed=1, gang_fraction=0.0)
        _, ep = encode(cluster, pods)
        got = pack_waves(ep, 3)
        np.testing.assert_array_equal(got.idx, _python_pack(ep, 3))

    def test_empty(self):
        cluster = make_cluster(4, seed=0)
        pods, _ = make_workload(5, seed=0)
        _, ep = encode(cluster, pods)
        got = native.pack_waves_native(np.empty(0, np.int32), ep.group_id, 4)
        assert got.shape == (1, 4)
        assert (got == PAD).all()

    def test_oversized_gang_raises(self):
        cluster = make_cluster(4, seed=0)
        pods, _ = make_workload(12, seed=0, gang_fraction=1.0, gang_size=6)
        _, ep = encode(cluster, pods)
        with pytest.raises(ValueError):
            pack_waves(ep, 4)


class TestTraceRoundtrip:
    def test_csv_roundtrip_matches_direct_build(self, tmp_path):
        spec = BorgSpec(nodes=50, tasks=2000, seed=3)
        ec0, ep0, meta0 = make_borg_encoded(spec)
        path = tmp_path / "trace.csv"
        export_trace_csv(spec, path)
        ec1, ep1, meta1 = load_trace_csv(path, spec)
        assert meta1["num_gangs"] == meta0["num_gangs"]
        np.testing.assert_allclose(ep1.requests, ep0.requests, rtol=1e-5)
        np.testing.assert_array_equal(ep1.priority, ep0.priority)
        np.testing.assert_array_equal(ep1.group_id, ep0.group_id)
        np.testing.assert_allclose(ep1.arrival, ep0.arrival, atol=5e-5)
        np.testing.assert_array_equal(ep1.tol_key, ep0.tol_key)
        np.testing.assert_array_equal(ep1.spread_g, ep0.spread_g)
        np.testing.assert_array_equal(ec1.allocatable, ec0.allocatable)

    def test_sparse_gang_ids_remapped(self, tmp_path):
        # External traces carry sparse collection ids; pg_min_member is
        # indexed by gang id, so ids must be remapped to contiguous.
        path = tmp_path / "sparse.csv"
        lines = ["arrival_s,cpu,mem_bytes,priority,group_id,app_id,tolerates,duration_s"]
        gids = [7, 7, -1, 1000003, 1000003, 1000003, -1, 7]
        for i, g in enumerate(gids):
            lines.append(f"{i}.0,1.0,1000.0,100,{g},0,0,60.0")
        path.write_text("\n".join(lines) + "\n")
        spec = BorgSpec(nodes=10, tasks=len(gids), seed=0)
        _, ep, meta = load_trace_csv(path, spec)
        assert meta["num_gangs"] == 2
        np.testing.assert_array_equal(ep.group_id, [0, 0, PAD, 1, 1, 1, PAD, 0])
        np.testing.assert_array_equal(ep.pg_min_member, [3, 3])

    def test_headerless_csv_python_fallback(self, tmp_path, monkeypatch):
        path = tmp_path / "nohdr.csv"
        path.write_text("0.5,1.0,1000.0,100,-1,0,0,60.0\n1.5,2.0,2000.0,0,-1,1,1,30.0\n")
        monkeypatch.setenv("KSIM_NO_NATIVE", "1")
        monkeypatch.setattr(native, "_LIB", None)
        monkeypatch.setattr(native, "_TRIED", False)
        spec = BorgSpec(nodes=5, tasks=2, seed=0)
        _, ep, _ = load_trace_csv(path, spec)
        assert ep.num_pods == 2
        np.testing.assert_allclose(ep.arrival, [0.5, 1.5])

    @pytest.mark.parametrize("use_native", [False, True])
    def test_borg_scale_collection_ids(self, tmp_path, monkeypatch, use_native):
        # Real Borg 2019 collection ids exceed 2^31; both readers must
        # carry them un-truncated into the contiguous remap.
        if use_native and not native.available():
            pytest.skip("native lib unavailable")
        if not use_native:
            monkeypatch.setenv("KSIM_NO_NATIVE", "1")
            monkeypatch.setattr(native, "_LIB", None)
            monkeypatch.setattr(native, "_TRIED", False)
        path = tmp_path / "big.csv"
        g1, g2 = 380618516317, 380618516317 + (1 << 32)  # would collide in int32
        lines = ["arrival_s,cpu,mem_bytes,priority,group_id,app_id,tolerates,duration_s"]
        for i, g in enumerate([g1, g1, g2, g2, -1]):
            lines.append(f"{i}.0,1.0,1000.0,100,{g},0,0,60.0")
        path.write_text("\n".join(lines) + "\n")
        spec = BorgSpec(nodes=10, tasks=5, seed=0)
        _, ep, meta = load_trace_csv(path, spec)
        assert meta["num_gangs"] == 2
        np.testing.assert_array_equal(ep.group_id, [0, 0, 1, 1, PAD])
        np.testing.assert_array_equal(ep.pg_min_member, [2, 2])

    def test_comment_then_header_python_fallback(self, tmp_path, monkeypatch):
        # A '#' comment before the header must not push the header row into
        # the data (the one-line sniff bug); same rule as the native reader.
        path = tmp_path / "ch.csv"
        path.write_text(
            "# generated\n"
            "arrival_s,cpu,mem_bytes,priority,group_id,app_id,tolerates,duration_s\n"
            " 0.5,1.0,1000.0,100,-1,0,0,60.0\n"
            "1.5,2.0,2000.0,0,-1,1,1,30.0\n"
        )
        monkeypatch.setenv("KSIM_NO_NATIVE", "1")
        monkeypatch.setattr(native, "_LIB", None)
        monkeypatch.setattr(native, "_TRIED", False)
        spec = BorgSpec(nodes=5, tasks=2, seed=0)
        _, ep, _ = load_trace_csv(path, spec)
        assert ep.num_pods == 2
        np.testing.assert_allclose(ep.arrival, [0.5, 1.5])
        assert np.isfinite(ep.arrival).all()

    @pytest.mark.skipif(not native.available(), reason="native lib unavailable")
    def test_native_reader_used(self, tmp_path):
        spec = BorgSpec(nodes=10, tasks=100, seed=0)
        path = tmp_path / "t.csv"
        cols = export_trace_csv(spec, path)
        got = native.read_trace_csv(path)
        assert got is not None
        np.testing.assert_allclose(got["arrival"], cols["arrival"], atol=5e-5)
        np.testing.assert_array_equal(got["group_id"], cols["group_id"])
