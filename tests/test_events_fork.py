"""Device-path timed failure injection + what-if fork-from-checkpoint
(SURVEY.md §5)."""

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import NodeEvent
from kubernetes_simulator_tpu.sim.synthetic import config1, make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import Perturbation, Scenario, WhatIfEngine


def test_jax_timed_node_down_diverts_placements():
    cluster, pods, plugins = config1(num_nodes=4, num_pods=200)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig(plugins=plugins)
    base = JaxReplayEngine(ec, ep, cfg, chunk_waves=4).replay()
    # Down node 0 halfway through the arrival stream.
    mid_t = float(np.median(ep.arrival))
    eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=4)
    res = eng.replay(node_events=[NodeEvent(time=mid_t, kind="node_down", node=0)])
    # Events land at chunk boundaries: find the first chunk whose start wave
    # arrives at/after the event; from there no pod may use node 0.
    wave_t = eng._wave_start_times(eng.waves.idx)
    starts = np.arange(0, eng.waves.idx.shape[0], 4)
    boundary = next(c for c in starts if wave_t[c] >= mid_t)
    late_pods = eng.waves.idx[boundary:].reshape(-1)
    late_pods = late_pods[late_pods >= 0]
    assert not (res.assignments[late_pods] == 0).any()
    assert (base.assignments == 0).any()
    # Engine restores capacity for subsequent replays.
    again = eng.replay()
    assert (again.assignments == base.assignments).all()


def test_whatif_fork_from_checkpoint(tmp_path):
    cluster = make_cluster(10, seed=4)
    pods, _ = make_workload(160, seed=4, with_affinity=True, with_spread=True)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    ck = str(tmp_path / "fork.npz")
    # Replay the first half and snapshot (2 chunks of 5 waves = 80 pods).
    eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=5)
    full = eng.replay(checkpoint_path=ck, checkpoint_every=2)

    # Fork: base scenario continues unperturbed → must equal the full replay.
    scen = [Scenario(), Scenario([Perturbation("node_down", nodes=np.arange(5))])]
    wi = WhatIfEngine(
        ec, ep, scen, cfg, chunk_waves=5, collect_assignments=True, fork_checkpoint=ck
    )
    res = wi.run()
    assert (res.assignments[0] == full.assignments).all()
    # The perturbed branch diverges after the fork point but shares the prefix.
    prefix_pods = wi.waves.idx[: wi._fork_waves_done].reshape(-1)
    prefix_pods = prefix_pods[prefix_pods >= 0]
    assert (res.assignments[1][prefix_pods] == full.assignments[prefix_pods]).all()
    assert res.placed[1] <= res.placed[0]


@pytest.mark.slow
def test_whatif_fork_from_padded_checkpoint(tmp_path):
    """Regression: the source replay pads its wave list to a multiple of
    chunk_waves; a checkpoint taken past the real wave count must not make
    the fork treat padding waves as already-scheduled (IndexError before
    the clamp in WhatIfEngine._init_states)."""
    cluster = make_cluster(12, seed=7)
    # 90 pods / width 8 → 12 waves; chunk_waves=5 pads to 15.
    pods, _ = make_workload(90, seed=7, with_affinity=True, with_spread=True)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    ck = str(tmp_path / "ck.npz")
    eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=5)
    assert eng.waves.idx.shape[0] % 5 != 0  # the padding case
    full = eng.replay(checkpoint_path=ck, checkpoint_every=1)
    weng = WhatIfEngine(
        ec, ep, [Scenario(), Scenario()], cfg, chunk_waves=5,
        collect_assignments=True, fork_checkpoint=ck,
    )
    res = weng.run()
    # Checkpoint covered the whole trace → fork reproduces it exactly.
    assert (res.assignments[0] == full.assignments).all()


def test_whatif_fork_with_completions_no_double_release(tmp_path):
    """Fork + completions=True must seed the released mask from the source
    checkpoint: the saved state already carries pre-fork releases, so
    re-subtracting them at the first post-fork boundary (advisor round-2
    medium) over-frees resources and over-places pods. Boundary-aligned
    fork ⇒ scenario 0 must equal the uninterrupted completions-on replay."""
    cluster = make_cluster(8, seed=7)
    pods, _ = make_workload(200, seed=7, with_affinity=False, with_spread=True)
    # Finite durations so completions actually fire (short vs the trace).
    for i, p in enumerate(pods):
        p.duration = 0.5 + (i % 5) * 0.2
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()
    C = 4

    full = JaxReplayEngine(ec, ep, cfg, chunk_waves=C).replay()

    ck = str(tmp_path / "fork.npz")
    JaxReplayEngine(ec, ep, cfg, chunk_waves=C).replay(
        checkpoint_path=ck, checkpoint_every=3
    )
    from kubernetes_simulator_tpu.sim.checkpoint import ReplayCheckpoint

    saved = ReplayCheckpoint.load(ck)
    assert saved.released is not None and saved.released.any(), (
        "precondition: the source checkpoint must carry applied releases"
    )

    wi = WhatIfEngine(
        ec, ep, [Scenario()], cfg, chunk_waves=C,
        fork_checkpoint=ck, collect_assignments=True, completions=True,
    )
    res = wi.run()
    np.testing.assert_array_equal(res.assignments[0], full.assignments)

    # Pre-field checkpoints (released=None) reconstruct from the outs with
    # the LEGACY no-slack rule (such checkpoints can only have been written
    # by pre-slack code). A maskless checkpoint from a modern run is not a
    # state that can exist, so only the reconstruction plumbing is checked:
    # it must run and produce a released mask without crashing.
    from kubernetes_simulator_tpu.sim.jax_runtime import rebuild_fork_state

    C_src = saved.outs[0].shape[0]
    idx = JaxReplayEngine(ec, ep, cfg, chunk_waves=C).waves.idx
    pad_to = ((idx.shape[0] + C_src - 1) // C_src) * C_src
    if pad_to != idx.shape[0]:
        idx = np.concatenate(
            [idx, np.full((pad_to - idx.shape[0], idx.shape[1]), -1, np.int32)]
        )
    wt = np.where(
        idx[:, 0] >= 0, ep.arrival[np.clip(idx[:, 0], 0, None)], np.inf
    )
    _, rel_legacy = rebuild_fork_state(
        ep, idx, C_src, saved.outs, wt, saved.chunk_cursor, slack=0
    )
    _, rel_slack = rebuild_fork_state(
        ep, idx, C_src, saved.outs, wt, saved.chunk_cursor, slack=1
    )
    # Legacy rule releases at least as much (chunk b−1 pods included).
    assert (rel_legacy | rel_slack == rel_legacy).all()
