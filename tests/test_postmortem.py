"""scripts/fleet_postmortem.py (round 21): black-box reconstruction —
causal timeline merge, the six-invariant audit, Perfetto export with
cross-process flow arrows, and tolerance of torn/interleaved inputs
(truncated final line, missing flight sibling, out-of-order stamps —
always a partial timeline + warning, never a crash or a false
violation)."""

import json
import os
import subprocess
import sys

import pytest

sys.path.insert(
    0,
    os.path.abspath(
        os.path.join(os.path.dirname(__file__), "..", "scripts")
    ),
)

import fleet_postmortem as pm  # noqa: E402

from kubernetes_simulator_tpu.parallel import trace  # noqa: E402

_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def _write_events(run_dir, events, t0=100.0, step=0.25, tail=""):
    """Stamp the rows through the real trace module and write the
    events.jsonl mirror exactly as dcn._mirror_event does."""
    path = os.path.join(run_dir, "events.jsonl")
    with open(path, "a") as f:
        for i, ev in enumerate(events):
            ev = trace.stamp(dict(ev))
            ev.setdefault("t", t0 + i * step)
            f.write(json.dumps(ev, sort_keys=True) + "\n")
        if tail:
            f.write(tail)
    return path


def _healthy_events():
    """A fleet story exercising every lifecycle: block 0 stolen after a
    stale renewal, block 1 resolved by a speculative win, a checkpoint
    crossing processes, and an injected fault."""
    return [
        {"event": "lease", "pid": 0, "block": 0, "gen": 0},
        {"event": "steal", "pid": 1, "block": 0, "gen": 1, "from": 0,
         "renew_age_s": 9.5, "threshold_s": 6.0},
        {"event": "block_done", "pid": 1, "block": 0, "gen": 1,
         "spec": False},
        {"event": "dup_discard", "pid": 0, "block": 0, "gen": 0},
        {"event": "lease", "pid": 2, "block": 1, "gen": 0},
        {"event": "speculate", "pid": 0, "block": 1, "gen": 0, "from": 2,
         "renew_age_s": 4.0, "threshold_s": 3.0},
        {"event": "block_done", "pid": 0, "block": 1, "gen": 0,
         "spec": True},
        {"event": "spec_lost", "pid": 2, "block": 1, "gen": 0},
        {"kind": "ckpt_publish", "pid": 1, "cursor": 3, "block": [4, 8]},
        {"event": "ckpt_load", "pid": 1, "cursor": 3, "block": [4, 8],
         "by": 0},
        {"event": "fault_inject", "pid": 0, "class": "kv_error",
         "key": "ksim/wq/0/w/lease/0", "op": "set", "n": 1},
    ]


def _run(run_dir, **kw):
    return pm.run_postmortem(str(run_dir), quiet=True, **kw)


# -- healthy reconstruction --------------------------------------------------


def test_healthy_run_passes_audit_with_cross_process_flows(tmp_path):
    _write_events(tmp_path, _healthy_events())
    (tmp_path / "p0.json").write_text(
        json.dumps({"pid": 0, "state": "run", "chunk": 3})
    )
    out = tmp_path / "trace.json"
    report = _run(tmp_path, out=str(out))
    assert report["rc"] == 0
    assert report["violations"] == []
    assert report["events_ingested"] == 11
    assert report["beacons"] == 1
    assert report["links_resolved"] > 0
    assert all(v == "ok" for v in report["invariants"].values())

    tr = json.load(open(out))
    slices = [e for e in tr["traceEvents"] if e.get("ph") == "X"]
    assert {e["pid"] for e in slices} == {0, 1, 2}
    # Fault injections are instant markers, not slices.
    instants = [e for e in tr["traceEvents"] if e.get("ph") == "i"]
    assert len(instants) == 1
    # Flow arrows cross processes: blk:0 threads p0 -> p1, the ckpt
    # trace threads p1's publish to p0's load.
    flows = {}
    for e in tr["traceEvents"]:
        if e.get("ph") in ("s", "t", "f"):
            flows.setdefault(e["name"], set()).add(e["pid"])
    assert flows["blk:0"] == {0, 1}
    assert flows["ckpt:1:3"] == {0, 1}


# -- every invariant trips on its fixture ------------------------------------


def test_double_done_winner_trips(tmp_path):
    _write_events(tmp_path, _healthy_events() + [
        {"event": "block_done", "pid": 2, "block": 0, "gen": 0,
         "spec": False},
    ])
    report = _run(tmp_path)
    assert report["rc"] == 1
    v = report["violations"][0]
    assert v["invariant"] == "one-done-winner"
    assert v["trace"] == "blk:0"
    assert any(e.get("event") == "steal" for e in v["chain"])


def test_corrupted_done_ledger_trips_and_names_chain(tmp_path):
    """The acceptance fixture: a durable done ledger that names a
    DIFFERENT winner than the done-CAS trail exits nonzero with the
    invariant named and the block's full event chain printed."""
    _write_events(tmp_path, _healthy_events())
    led = tmp_path / "journal" / "wq" / "0" / "w" / "done"
    led.mkdir(parents=True)
    (led / "0").write_text(json.dumps({"pid": 2, "gen": 0}))  # lie
    p = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "fleet_postmortem.py"),
         str(tmp_path)],
        capture_output=True, text=True,
    )
    assert p.returncode == 1
    assert "VIOLATION one-done-winner [blk:0]" in p.stdout
    assert "offending event chain" in p.stdout
    assert '"event": "steal"' in p.stdout  # the chain is printed whole


def test_lease_gen_regression_trips(tmp_path):
    _write_events(tmp_path, [
        {"event": "lease", "pid": 0, "block": 3, "gen": 0},
        {"event": "steal", "pid": 1, "block": 3, "gen": 2, "from": 0},
        {"event": "steal", "pid": 2, "block": 3, "gen": 1, "from": 1},
    ])
    report = _run(tmp_path)
    assert report["invariants"]["lease-gen-monotonic"] == "violated"


def test_claim_gen_regression_trips(tmp_path):
    _write_events(tmp_path, [
        {"event": "claim", "claimant": 0, "for": 2, "gen": 1},
        {"event": "claim", "claimant": 1, "for": 2, "gen": 0},
    ])
    report = _run(tmp_path)
    assert report["invariants"]["lease-gen-monotonic"] == "violated"


def test_adopt_then_reexecution_trips(tmp_path):
    _write_events(tmp_path, [
        {"event": "journal_adopt", "pid": 0, "block": 5, "gen": 0,
         "from": 1},
        {"event": "steal", "pid": 2, "block": 5, "gen": 1, "from": 1},
    ])
    report = _run(tmp_path)
    assert report["invariants"]["adopt-no-reexec"] == "violated"


def test_resume_cursor_beyond_durable_cap_trips(tmp_path):
    _write_events(tmp_path, [
        {"kind": "ckpt_publish", "pid": 1, "cursor": 2, "block": [4, 8]},
        {"event": "ckpt_load", "pid": 1, "cursor": 6, "block": [4, 8],
         "by": 0},
    ])
    ck = tmp_path / "journal" / "ckpt" / "7" / "1" / "4-8" / "2"
    ck.mkdir(parents=True)
    (ck / "manifest.json").write_text('{"n": 1}')
    report = _run(tmp_path)
    assert report["invariants"]["resume-cursor-bounded"] == "violated"
    v = report["violations"][0]
    assert "6" in v["detail"] and "2" in v["detail"]


def test_premature_steal_trips(tmp_path):
    _write_events(tmp_path, [
        {"event": "lease", "pid": 0, "block": 2, "gen": 0},
        {"event": "steal", "pid": 1, "block": 2, "gen": 1, "from": 0,
         "renew_age_s": 0.5, "threshold_s": 6.0},
        {"event": "block_done", "pid": 1, "block": 2, "gen": 1,
         "spec": False},
    ])
    report = _run(tmp_path)
    assert report["invariants"]["steal-after-stale-renewal"] == "violated"


def test_dup_without_winner_trips(tmp_path):
    _write_events(tmp_path, [
        {"event": "lease", "pid": 0, "block": 9, "gen": 0},
        {"event": "dup_discard", "pid": 0, "block": 9, "gen": 0},
    ])
    report = _run(tmp_path)
    assert report["invariants"]["dup-has-winner"] == "violated"


def test_dup_with_ledger_winner_is_clean(tmp_path):
    """A winner killed between its done-CAS and the mirror write leaves
    only the durable ledger as evidence — that must satisfy the audit,
    not false-violate it."""
    _write_events(tmp_path, [
        {"event": "lease", "pid": 0, "block": 9, "gen": 0},
        {"event": "dup_discard", "pid": 0, "block": 9, "gen": 0},
    ])
    led = tmp_path / "journal" / "wq" / "0" / "w" / "done"
    led.mkdir(parents=True)
    (led / "9").write_text(json.dumps({"pid": 1, "gen": 0}))
    report = _run(tmp_path)
    assert report["rc"] == 0


def test_restart_reopens_gen_zero_without_false_violation(tmp_path):
    """A supervised restart legitimately re-leases a stolen-but-unfinished
    block at gen 0 in the fresh KV epoch — episode segmentation must not
    read that as a generation regression."""
    _write_events(tmp_path, [
        {"event": "lease", "pid": 0, "block": 1, "gen": 0},
        {"event": "steal", "pid": 1, "block": 1, "gen": 1, "from": 0},
        # fleet dies here; supervisor relaunches; fresh epoch:
        {"event": "lease", "pid": 2, "block": 1, "gen": 0},
        {"event": "block_done", "pid": 2, "block": 1, "gen": 0,
         "spec": False},
    ])
    report = _run(tmp_path)
    assert report["rc"] == 0, report["violations"]


# -- torn / interleaved inputs (satellite 3) ---------------------------------


def test_truncated_final_line_warns_never_crashes(tmp_path):
    _write_events(tmp_path, _healthy_events(),
                  tail='{"event": "lease", "pid":')
    report = _run(tmp_path)
    assert report["rc"] == 0
    assert report["events_ingested"] == 11
    assert any("torn final line" in w for w in report["warnings"])


def test_missing_events_file_degrades_to_warning(tmp_path):
    report = _run(tmp_path)
    assert report["rc"] == 0
    assert report["events_ingested"] == 0
    assert any("events.jsonl: missing" in w for w in report["warnings"])


def test_missing_flight_sibling_warns(tmp_path):
    _write_events(tmp_path, _healthy_events())
    flight = tmp_path / "flight.jsonl"
    flight.write_text(
        json.dumps({
            "kind": "flight", "schema": 6, "ts": 0.0, "event": "fleet",
            "fleet_event": "lease", "chunk": -1, "wall_s": 0.0,
            "pid": 0, "block": 0, "gen": 0,
        }) + "\n"
    )
    report = _run(tmp_path, flight=str(tmp_path / "missing.jsonl"))
    assert report["rc"] == 0
    assert any("missing" in w for w in report["warnings"])
    # And a present stream with a dead sibling still contributes rows.
    report = _run(tmp_path, flight=str(flight))
    assert report["rc"] == 0


def test_out_of_order_stamps_warn_and_resort(tmp_path):
    evs = _healthy_events()
    with open(tmp_path / "events.jsonl", "w") as f:
        for i, ev in enumerate(evs):
            ev = trace.stamp(dict(ev))
            # Process 2's clock runs 50s behind: its stamps interleave
            # out of order across processes.
            t = 100.0 + i * 0.25 - (50.0 if ev.get("pid") == 2 else 0.0)
            ev["t"] = t
            f.write(json.dumps(ev, sort_keys=True) + "\n")
    report = _run(tmp_path)
    assert report["rc"] == 0, report["violations"]
    assert any("out-of-order" in w for w in report["warnings"])


def test_torn_beacon_and_torn_ledger_warn(tmp_path):
    _write_events(tmp_path, _healthy_events())
    (tmp_path / "p1.json").write_text('{"pid": 1, "state"')  # torn
    led = tmp_path / "journal" / "wq" / "0" / "w" / "done"
    led.mkdir(parents=True)
    (led / "0").write_text('{"pid"')  # torn ledger record
    report = _run(tmp_path)
    assert report["rc"] == 0  # torn evidence is skipped, not violated
    assert any("torn beacon" in w for w in report["warnings"])
    assert any("torn ledger" in w for w in report["warnings"])


def test_malformed_rows_never_crash_the_audit(tmp_path):
    with open(tmp_path / "events.jsonl", "w") as f:
        f.write('{"event": "lease", "pid": "x", "block": "y", "gen": []}\n')
        f.write('[1, 2, 3]\n')  # non-dict row
        f.write('{"event": "steal", "pid": 1, "block": 2, "gen": "z", '
                '"trace": "blk:2"}\n')
        f.write('{"event": "ckpt_load", "pid": "a", "cursor": "b"}\n')
        f.write("not json at all\n")
    report = _run(tmp_path)
    assert report["rc"] == 0  # degraded evidence, no false violation


# -- schema + CLI ------------------------------------------------------------


def test_postmortem_jsonl_row_validates_v6(tmp_path, monkeypatch):
    monkeypatch.setenv("KSIM_DETERMINISTIC_JSONL", "1")
    _write_events(tmp_path, _healthy_events())
    out_jsonl = tmp_path / "pm.jsonl"
    report = _run(tmp_path, jsonl=str(out_jsonl))
    assert report["rc"] == 0
    row = json.loads(out_jsonl.read_text().splitlines()[0])
    assert row["kind"] == "postmortem"
    assert row["schema"] == 7
    assert row["ts"] == 0.0 and row["audit_wall_s"] == 0.0
    p = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "check_metrics_schema.py"),
         str(out_jsonl)],
        capture_output=True, text=True,
    )
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_rc2_on_missing_dir(tmp_path):
    p = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "scripts", "fleet_postmortem.py"),
         str(tmp_path / "nope")],
        capture_output=True, text=True,
    )
    assert p.returncode == 2
