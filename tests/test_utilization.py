"""Utilization economics (round 13): per-node utilization series,
fragmentation / stranded-capacity gauges, and their CPU↔device bit-parity.

Both engines funnel every gauge through the SAME float64 numpy helpers
(utils.metrics.utilization_means / series_gauges / fragmentation_gauges),
so wherever the two engines sample identical committed state the values
are bit-identical — the same oracle discipline as the round-7 latency
histograms. Parity envelopes exercised here:

* end-of-replay gauges — bit-identical whenever every release lands
  inside the replayed horizon (the CPU engine drains trailing
  completions past the last arrival; the device applies releases only
  at chunk boundaries), so traces here either finish their releases
  before the last arrival or run infinite durations;
* series samples — the device samples at chunk boundaries (pre-dispatch,
  post-release: exactly the CPU engine's post-events/pre-schedule
  instant), so samples at COMMON virtual times must bit-match.
"""

import json
import os
import sys

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.runtime import CpuReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_chaos_timeline
from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine
from kubernetes_simulator_tpu.utils.metrics import (
    fragmentation_gauges,
    round_fragmentation,
    series_gauges,
    utilization_means,
)

_SCRIPTS = os.path.join(os.path.dirname(__file__), "..", "scripts")
sys.path.insert(0, os.path.abspath(_SCRIPTS))

from check_metrics_schema import validate_file, validate_row  # noqa: E402

FIT_ONLY = lambda: FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])


def _series_at(tel):
    """{t: (util_cpu, frag_cpu)} for common-instant comparisons."""
    s = tel.series
    return {
        t: (u, f)
        for t, u, f in zip(s["t"], s["util_cpu"], s["frag_cpu"])
    }


def _assert_common_instants_match(cpu_tel, dev_tel, min_common=3):
    ca, da = _series_at(cpu_tel), _series_at(dev_tel)
    common = sorted(set(ca) & set(da))
    assert len(common) >= min_common
    for t in common:
        assert ca[t] == da[t], f"t={t}: cpu {ca[t]} != dev {da[t]}"
    return common


# -- gauge helpers (exact, hand-computed) ----------------------------------


def test_utilization_means_exact():
    used = np.array([[2.0, 4.0], [0.0, 0.0]])
    alloc = np.array([[4.0, 8.0], [4.0, 8.0]])
    u = utilization_means(used, alloc, {"cpu": 0, "memory": 1})
    assert u == {"cpu": 0.25, "memory": 0.25}
    # Zero-allocatable nodes (chaos node_down) contribute 0, not NaN.
    u = utilization_means(used, np.zeros_like(alloc), {"cpu": 0, "memory": 1})
    assert u == {"cpu": 0.0, "memory": 0.0}


def test_series_gauges_exact():
    used = np.array([[3.0, 1.0], [1.0, 1.0]])
    alloc = np.array([[4.0, 8.0], [4.0, 8.0]])
    g = series_gauges(used, alloc, {"cpu": 0, "memory": 1})
    assert g["util_cpu"] == 0.5
    assert g["util_mem"] == 0.125
    # free cpu: [1, 3] → frag = 1 - 3/4.
    assert g["frag_cpu"] == 0.25
    # Memory absent from the vocabulary → no util_mem key.
    g = series_gauges(used[:, :1], alloc[:, :1], {"cpu": 0})
    assert set(g) == {"util_cpu", "frag_cpu"}


def test_fragmentation_gauges_exact():
    alloc = np.array([[4.0], [4.0], [4.0]])
    used = np.array([[2.0], [2.0], [0.0]])
    pend = np.array([[3.0], [1.0]])  # largest pending wants 3 cpu
    fr = fragmentation_gauges(alloc, used, pend, {"cpu": 0})
    # Only n2 (4 free) fits the 3-cpu pod; n0/n1 strand 2 cpu each.
    assert fr["stranded"] == {"cpu": 4.0}
    assert fr["stranded_frac"] == {"cpu": 4.0 / 12.0}
    # free [2, 2, 4]: frag index = 1 - 4/8.
    assert fr["frag_index"] == {"cpu": 0.5}
    assert fr["pending"] == 2
    assert fr["nodes_active"] == 2
    assert fr["nodes_ideal"] == 1  # ceil(4 used / 4 cap)
    assert fr["packing_efficiency"] == 0.5
    # No pending pods → nothing stranded, packing still reported.
    fr = fragmentation_gauges(alloc, used, pend[:0], {"cpu": 0})
    assert fr["stranded"] == {"cpu": 0.0} and fr["pending"] == 0
    rounded = round_fragmentation(fr)
    assert rounded["stranded_frac"]["cpu"] == 0.0
    assert round_fragmentation(None) is None


def test_pending_fit_mask_eps():
    """The stranded fit test reuses the scheduler's own epsilon."""
    from kubernetes_simulator_tpu.ops.cpu import pending_fit_mask

    used = np.array([[3.0], [4.0]])
    alloc = np.array([[4.0], [4.0]])
    m = pending_fit_mask(used, alloc, np.array([1.0]))
    np.testing.assert_array_equal(m, [True, False])
    # Float dust within the scheduler's 1e-6 epsilon still fits.
    m = pending_fit_mask(used + 5e-7, alloc, np.array([1.0]))
    np.testing.assert_array_equal(m, [True, False])


# -- CPU engine ------------------------------------------------------------


def test_cpu_replay_carries_fragmentation():
    nodes = [Node(f"n{i}", {"cpu": 2.0}) for i in range(2)]
    pods = [
        Pod("p0", requests={"cpu": 2.0}, arrival_time=0.0),
        Pod("p1", requests={"cpu": 1.0}, arrival_time=1.0),
        # 2-cpu pod that can never fit once p0/p1 are down: 1 cpu free
        # on n1 is stranded for it.
        Pod("p2", requests={"cpu": 2.0}, arrival_time=2.0),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    res = CpuReplayEngine(ec, ep, FIT_ONLY()).replay()
    fr = res.fragmentation
    assert fr is not None and fr["pending"] == 1
    assert fr["stranded"]["cpu"] == 1.0  # n1's free cpu can't host p2
    assert res.summary()["fragmentation"] == round_fragmentation(fr)
    # Series granularity samples utilization at every event instant.
    tel = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="series").replay(
    ).telemetry
    assert {"t", "util_cpu", "frag_cpu"} <= set(tel.series)
    assert len(tel.series["util_cpu"]) == len(tel.series["t"])


# -- plain device path -----------------------------------------------------


def _release_trace(num_nodes=3, num_pods=12, duration=5.0):
    """Arrivals 1 s apart; every release lands before the last arrival,
    so both engines reach the identical end state."""
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(num_nodes)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=duration)
        for i in range(num_pods)
    ]
    return encode(Cluster(nodes=nodes), pods)


def test_plain_series_utilization_bit_parity():
    """Common-instant series parity on the plain path: the device samples
    at every chunk boundary (post-release, pre-dispatch) — exactly the
    CPU engine's post-events/pre-schedule sample of the same instant."""
    ec, ep = _release_trace()
    cpu = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="series").replay()
    dev = JaxReplayEngine(
        ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, telemetry="series"
    ).replay()
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    common = _assert_common_instants_match(
        cpu.telemetry, dev.telemetry, min_common=8
    )
    # Non-vacuous: utilization moved over the compared window.
    utils = [_series_at(cpu.telemetry)[t][0] for t in common]
    assert max(utils) > 0.0


def test_plain_end_gauges_bit_parity_infinite_durations():
    """No completions → both engines end on the identical committed
    state; utilization AND fragmentation dicts are bit-equal."""
    nodes = [Node(f"n{i}", {"cpu": 4.0}) for i in range(3)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 3.0}, arrival_time=float(i))
        for i in range(3)
    ] + [
        # Can never fit next to a 3-cpu tenant: strands 1 cpu per node.
        Pod("big", requests={"cpu": 2.0}, arrival_time=3.0),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    for gran in ("summary", "series"):
        cpu = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry=gran).replay()
        dev = JaxReplayEngine(
            ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=1, telemetry=gran
        ).replay()
        np.testing.assert_array_equal(cpu.assignments, dev.assignments)
        assert cpu.utilization == dev.utilization, gran
        assert cpu.fragmentation == dev.fragmentation, gran
    assert cpu.fragmentation["stranded"]["cpu"] == 3.0
    assert cpu.fragmentation["pending"] == 1


def test_off_and_summary_keep_gauges_and_program():
    """The gauges are end-of-replay host arithmetic: granularity off /
    summary must produce the same fragmentation as series (no sampling
    side-effects), and off still reports them (telemetry-independent)."""
    ec, ep = _release_trace(num_pods=8)
    frags = {}
    for gran in ("off", "summary", "series"):
        res = JaxReplayEngine(
            ec, ep, FIT_ONLY(), wave_width=1, chunk_waves=2, telemetry=gran
        ).replay()
        frags[gran] = res.fragmentation
        assert res.fragmentation is not None
    assert frags["off"] == frags["summary"] == frags["series"]


# -- boundary (retry) path -------------------------------------------------


def test_boundary_series_and_end_gauges_match_cpu():
    """Retry-path twin of the latency coincidence trace: a failed pod
    retries at the next boundary; utilization samples at common instants
    and the end gauges bit-match the event engine. The trailing zero-cpu
    arrival puts the last release inside the horizon for BOTH engines."""
    nodes = [Node("n0", {"cpu": 1.0})]
    pods = [
        Pod("p0", requests={"cpu": 1.0}, arrival_time=0.0, duration=1.5),
        Pod("p1", requests={"cpu": 1.0}, arrival_time=1.0, duration=2.0),
        Pod("p2", requests={"cpu": 0.0}, arrival_time=2.0),
        Pod("p3", requests={"cpu": 0.0}, arrival_time=5.0),
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FIT_ONLY()
    cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay()
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, retry_buffer=8,
        telemetry="series",
    ).replay()
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    assert {"retry_depth", "pend_depth", "util_cpu", "frag_cpu"} <= set(
        dev.telemetry.series
    )
    assert cpu.utilization == dev.utilization
    assert cpu.fragmentation == dev.fragmentation
    # The boundary sample is POST-retry-bind (like retry_depth); at t=5
    # nothing is in flight on either engine, so the instants agree.
    ca, da = _series_at(cpu.telemetry), _series_at(dev.telemetry)
    assert ca[5.0] == da[5.0] == (0.0, 0.0)


def test_chaos_eviction_utilization_parity():
    """Chaos eviction case (kube preemption, mttr=0 timelines): evicted
    pods rebind through the boundary retry queue; end-of-replay
    utilization + fragmentation stay bit-identical to the CPU oracle."""
    nodes = [Node(f"n{i}", {"cpu": 8.0}) for i in range(6)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i))
        for i in range(28)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FIT_ONLY()
    evs = make_chaos_timeline(
        ec.num_nodes, seed=2, horizon=float(ep.arrival.max()),
        mtbf=12.0, mttr=0.0, node_fraction=0.34,
    )
    cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay(
        node_events=evs
    )
    dev = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64, telemetry="series",
    ).replay(node_events=evs)
    assert dev.evictions > 0  # non-vacuous
    np.testing.assert_array_equal(cpu.assignments, dev.assignments)
    assert cpu.utilization == dev.utilization
    assert cpu.fragmentation == dev.fragmentation


@pytest.mark.fuzz_quick
def test_seeded_fuzz_utilization_parity():
    """Seeded slice: infinite-duration traces across capacities — end
    gauges bit-match on plain AND boundary paths; series samples match
    at every common instant."""
    for seed in (1, 2, 3):
        rng = np.random.default_rng(seed)
        nodes = [
            Node(f"n{i}", {"cpu": float(rng.integers(2, 9))})
            for i in range(5)
        ]
        pods = [
            Pod(f"p{i}", requests={"cpu": float(rng.integers(1, 4))},
                arrival_time=float(i))
            for i in range(24)
        ]
        ec, ep = encode(Cluster(nodes=nodes), pods)
        cfg = FIT_ONLY()
        cpu = CpuReplayEngine(ec, ep, cfg, telemetry="series").replay()
        for kw in (
            dict(wave_width=1, chunk_waves=1),
            dict(wave_width=1, chunk_waves=1, retry_buffer=16),
        ):
            dev = JaxReplayEngine(
                ec, ep, cfg, telemetry="series", **kw
            ).replay()
            np.testing.assert_array_equal(
                cpu.assignments, dev.assignments
            )
            assert cpu.utilization == dev.utilization, (seed, kw)
            assert cpu.fragmentation == dev.fragmentation, (seed, kw)


# -- what-if kube batches --------------------------------------------------


def test_whatif_scenario_fragmentation_bit_matches_single_replay():
    ec, ep = _release_trace(num_nodes=4, num_pods=16)
    cfg = FIT_ONLY()
    evs = make_chaos_timeline(
        ec.num_nodes, seed=7, horizon=float(ep.arrival.max()),
        mtbf=10.0, mttr=0.0, node_fraction=0.5,
    )
    single = JaxReplayEngine(
        ec, ep, cfg, wave_width=1, chunk_waves=1, preemption="kube",
        retry_buffer=64,
    ).replay()
    res = WhatIfEngine(
        ec, ep, [Scenario(), Scenario(events=evs)], cfg, wave_width=1,
        chunk_waves=1, preemption="kube", retry_buffer=64,
    ).run()
    assert res.stranded_cpu.shape == (2,)
    fr = single.fragmentation
    assert float(res.stranded_cpu[0]) == fr["stranded"]["cpu"]
    assert float(res.frag_index_cpu[0]) == fr["frag_index"]["cpu"]
    assert float(res.packing_efficiency[0]) == fr["packing_efficiency"]
    # Plain batches have no kube host mirrors → gauges absent, like the
    # latency quantiles.
    plain = WhatIfEngine(
        ec, ep, [Scenario()], cfg, chunk_waves=4, granularity_guard=False
    ).run()
    assert plain.stranded_cpu is None


# -- JSONL schema v4 + determinism ----------------------------------------


def test_replay_row_schema_v4(tmp_path):
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter, replay_row

    ec, ep = _release_trace(num_pods=8)
    res = CpuReplayEngine(ec, ep, FIT_ONLY()).replay()
    out = tmp_path / "r.jsonl"
    ctx = {"seed": 0, "engine": "cpu", "config_hash": "deadbeef"}
    with JsonlWriter(str(out), context=ctx) as w:
        w.write(replay_row("replay-cpu", res))
    assert validate_file(str(out)) == []
    row = json.loads(out.read_text())
    assert row["schema"] == 7
    assert set(row["fragmentation"]) == {
        "stranded", "stranded_frac", "frag_index", "packing_efficiency",
        "nodes_active", "nodes_ideal", "pending",
    }
    # The checker rejects a malformed fragmentation payload.
    bad = dict(row)
    bad["fragmentation"] = {"stranded": 3}
    assert any("fragmentation" in e for e in validate_row(bad))
    # v2 rows (pre round 13) keep validating byte-unchanged.
    v2 = dict(row)
    v2["schema"] = 2
    v2.pop("fragmentation")
    assert validate_row(v2) == []


def test_deterministic_jsonl_covers_fragmentation(tmp_path, monkeypatch):
    """KSIM_DETERMINISTIC_JSONL byte-parity covers the new fields: the
    gauges are virtual-time arithmetic, so two same-seed runs emit
    byte-identical rows with no new scrubs."""
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter, replay_row

    monkeypatch.setenv("KSIM_DETERMINISTIC_JSONL", "1")
    lines = []
    for name in ("a", "b"):
        ec, ep = _release_trace()
        res = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="off").replay()
        out = tmp_path / f"{name}.jsonl"
        ctx = {"seed": 0, "engine": "cpu", "config_hash": "deadbeef"}
        with JsonlWriter(str(out), context=ctx) as w:
            w.write(replay_row("replay-cpu", res))
        lines.append(out.read_bytes())
    assert lines[0] == lines[1]
    assert b"fragmentation" in lines[0]


# -- telemetry merge + chrome-trace counter tracks -------------------------


def test_merge_extends_to_utilization_series():
    from kubernetes_simulator_tpu.sim.telemetry import ReplayTelemetry

    a = ReplayTelemetry(
        granularity="series",
        series={"t": [0.0, 1.0], "util_cpu": [0.1, 0.2],
                "frag_cpu": [0.5, 0.4]},
    )
    b = ReplayTelemetry(
        granularity="series",
        series={"t": [0.0, 2.0], "util_cpu": [0.3, 0.4],
                "frag_cpu": [0.2, 0.1]},
    )
    m = ReplayTelemetry.merge([a, b], process_ids=[0, 1])
    assert m.series["util_cpu"] == [0.1, 0.2, 0.3, 0.4]
    assert m.series["frag_cpu"] == [0.5, 0.4, 0.2, 0.1]


def test_chrome_trace_counter_tracks(tmp_path):
    from kubernetes_simulator_tpu.sim.telemetry import (
        write_chrome_trace,
        write_chrome_trace_merged,
    )

    ec, ep = _release_trace(num_nodes=3, num_pods=9)
    res = CpuReplayEngine(ec, ep, FIT_ONLY(), telemetry="timeline").replay()
    path = str(tmp_path / "trace.json")
    write_chrome_trace(
        path, res, arrival=ep.arrival, duration=ep.duration,
        requests=ep.requests, rindex=ec.vocab._r,
    )
    with open(path) as f:
        ev = json.load(f)["traceEvents"]
    counters = [e for e in ev if e["ph"] == "C"]
    assert counters
    # Counter change-points reconstruct per-node usage from the pod
    # spans: values never exceed the node's capacity, and each track
    # drains to zero (every pod in this trace completes).
    by_node = {}
    for e in counters:
        assert 0.0 <= e["args"]["cpu"] <= 8.0
        by_node.setdefault(e["tid"], []).append((e["ts"], e["args"]["cpu"]))
    assert set(by_node) == {int(n) for n in res.assignments if n >= 0}
    for n, pts in by_node.items():
        assert max(v for _, v in pts) > 0.0
        assert sorted(pts)[-1][1] == 0.0
    # Without requests the export is byte-compatible with round 12 (no
    # counter events).
    write_chrome_trace(path, res, arrival=ep.arrival, duration=ep.duration)
    with open(path) as f:
        assert not [
            e for e in json.load(f)["traceEvents"] if e["ph"] == "C"
        ]
    # Merged fleet export: optional 4-tuples add per-process tracks.
    merged = str(tmp_path / "merged.json")
    write_chrome_trace_merged(
        merged,
        [(res, ep.arrival, ep.duration, ep.requests),
         (res, ep.arrival, ep.duration)],
        rindex=ec.vocab._r,
    )
    with open(merged) as f:
        ev = json.load(f)["traceEvents"]
    pids = {e["pid"] for e in ev if e["ph"] == "C"}
    assert pids == {0}  # only process 0 shipped requests


def test_fleet_watch_shows_utilization_gauge():
    sys.path.insert(0, os.path.abspath(_SCRIPTS))
    from dcn_launch import FleetWatch

    w = FleetWatch(hb_dir="/nonexistent", nproc=1)
    import time as _time

    line = w.line({0: {"state": "gather", "chunk": 4, "total_chunks": 4,
                       "t": _time.time(), "util_cpu": 0.4321}})
    assert "util=43.2%" in line
