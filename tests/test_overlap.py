"""Round 19 — overlap plane parity: the three stall-hiding features
(threaded pager, background checkpoint publication, slim two-phase
selection exchange) are pure LATENCY knobs. Placements, deterministic
JSONL and checkpoint blobs are BIT-IDENTICAL with each feature on vs
off, across nodeShards ∈ {1, 2, 4} × paged on/off × the kube-boundary
leg, including cross-mode resume (a checkpoint written with a feature
ON resumes with it OFF and vice versa). Runs on the virtual 8-device
CPU mesh (conftest forces XLA_FLAGS=--xla_force_host_platform_device_count=8).

Also here: the exchange payload-accounting pins (the two-phase exchange
provably moves fewer bytes per slot at every shard count and group
count), the round-19 pager resume-jump invalidation fix (a stale staged
page is discarded and counted, never silently under-reported as a plain
miss), the background publisher's single-flight/newest-wins/drain/error
unit semantics, and the ``overlap:`` config section's parsing and
validation refusals.
"""

import hashlib
import json

import numpy as np
import pytest

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.ops import tpu as T
from kubernetes_simulator_tpu.sim.jax_runtime import (
    JaxReplayEngine,
    _PodPager,
)
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload

# The three env gates, all default-ON.
GATE_EXCHANGE = "KSIM_TWO_PHASE_EXCHANGE"
GATE_PAGER = "KSIM_PAGER_THREAD"
GATE_CKPT = "KSIM_DCN_CKPT_ASYNC"


def _case(n_nodes=24, n_pods=160, seed=11):
    cluster = make_cluster(n_nodes, seed=seed, taint_fraction=0.2)
    pods, _ = make_workload(
        n_pods, seed=seed, with_affinity=True, with_spread=True,
        with_tolerations=True, gang_fraction=0.1, gang_size=4,
        duration_mean=40.0,
    )
    return encode(cluster, pods)


@pytest.fixture(scope="module")
def case():
    return _case()


def _stable_summary(res):
    row = dict(res.summary())
    for k in ("wall_clock_s", "placements_per_sec"):
        row.pop(k, None)
    return row


def _deterministic_jsonl(res, path, monkeypatch):
    from kubernetes_simulator_tpu.utils.metrics import JsonlWriter, replay_row

    monkeypatch.setenv("KSIM_DETERMINISTIC_JSONL", "1")
    with JsonlWriter(str(path)) as w:
        w.write(replay_row("replay-jax", res))
    return path.read_bytes()


# ── exchange payload accounting ──────────────────────────────────────


def test_exchange_payload_bytes_formula():
    """The analytic per-slot payload model the scaling probe and the
    whitelist tests rest on: a single shard exchanges nothing; the
    two-phase exchange receives (n−1)·2 floats of slim rows plus a
    ring all-reduce (2·(n−1)/n of the 2G dom row) — never MORE bytes
    than the legacy (n−1)·(2+2G) wide gather (equal at n = 2, where the
    reduce degenerates to a peer swap) and strictly fewer at n ≥ 3."""
    for n in (0, 1):
        assert T.exchange_payload_bytes(n, 8, True) == 0
        assert T.exchange_payload_bytes(n, 8, False) == 0
    for n in (2, 4, 8):
        for g in (1, 4, 32):
            legacy = T.exchange_payload_bytes(n, g, False)
            slim = T.exchange_payload_bytes(n, g, True)
            assert legacy == 4 * (n - 1) * (2 + 2 * g)
            assert slim == 4 * ((n - 1) * 2 + (2 * (n - 1) * 2 * g) // n)
            assert slim <= legacy, (n, g, slim, legacy)
            if n > 2:
                assert slim < legacy, (n, g, slim, legacy)
    # The win grows with shard count (the wide gather scales with n·G,
    # the psum's dom traffic does not).
    assert (
        T.exchange_payload_bytes(8, 32, False)
        / T.exchange_payload_bytes(8, 32, True)
        > T.exchange_payload_bytes(2, 32, False)
        / T.exchange_payload_bytes(2, 32, True)
    )


# ── two-phase exchange bit-parity ────────────────────────────────────


@pytest.fixture(scope="module")
def exchange_results(case):
    """{(shards, two_phase): (engine, ReplayResult)} over the same
    trace. Env is read at trace time, so each engine is constructed AND
    replayed (compiled) under its own gate value."""
    import os

    ec, ep = case
    out = {}
    for two_phase in (True, False):
        os.environ[GATE_EXCHANGE] = "1" if two_phase else "0"
        try:
            for s in (1, 2, 4):
                eng = JaxReplayEngine(
                    ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=s,
                    telemetry="off",
                )
                out[(s, two_phase)] = (eng, eng.replay())
        finally:
            os.environ.pop(GATE_EXCHANGE, None)
    return out


def test_two_phase_exchange_bit_parity(exchange_results):
    _, ref = exchange_results[(1, False)]
    for s in (1, 2, 4):
        for two_phase in (True, False):
            _, res = exchange_results[(s, two_phase)]
            np.testing.assert_array_equal(
                res.assignments, ref.assignments,
                err_msg=(
                    f"node_shards={s} two_phase={two_phase}: per-pod "
                    "assignments diverged"
                ),
            )
            assert _stable_summary(res) == _stable_summary(ref)


def test_two_phase_jsonl_byte_identical(
    exchange_results, tmp_path, monkeypatch
):
    blobs = {}
    for key, (_, res) in exchange_results.items():
        blobs[key] = _deterministic_jsonl(
            res, tmp_path / f"{key[0]}_{key[1]}.jsonl", monkeypatch
        )
    assert len(set(blobs.values())) == 1, (
        "deterministic JSONL differs across shards × exchange modes"
    )


def test_two_phase_checkpoint_blob_and_cross_mode_resume(
    exchange_results, tmp_path
):
    """Checkpoint blobs are byte-identical exchange on/off, and a blob
    written under one exchange mode resumes under the other."""
    eng_on, ref = exchange_results[(2, True)]
    eng_off, _ = exchange_results[(2, False)]
    digests = {}
    for name, eng in (("on", eng_on), ("off", eng_off)):
        p = tmp_path / f"ckpt_{name}.npz"
        res = eng.replay(checkpoint_path=str(p), checkpoint_every=2)
        np.testing.assert_array_equal(res.assignments, ref.assignments)
        digests[name] = hashlib.sha256(p.read_bytes()).hexdigest()
    assert digests["on"] == digests["off"], (
        "checkpoint blob depends on the exchange mode"
    )
    # Cross-mode resume: two-phase-written blob, legacy-compiled engine
    # (and the reverse).
    res = eng_off.replay(
        checkpoint_path=str(tmp_path / "ckpt_on.npz"), resume=True
    )
    np.testing.assert_array_equal(res.assignments, ref.assignments)
    res = eng_on.replay(
        checkpoint_path=str(tmp_path / "ckpt_off.npz"), resume=True
    )
    np.testing.assert_array_equal(res.assignments, ref.assignments)


# ── kube-boundary leg ────────────────────────────────────────────────


def test_kube_boundary_two_phase_parity_and_resume(case, tmp_path):
    """The kube PostFilter boundary path (retry buffer + minimal-victims
    preemption) under nodeShards: identical placements and checkpoint
    blobs exchange on/off, including a cross-mode resume."""
    import os

    ec, ep = case
    results = {}
    for two_phase in (True, False):
        os.environ[GATE_EXCHANGE] = "1" if two_phase else "0"
        try:
            eng = JaxReplayEngine(
                ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=2,
                preemption="kube", retry_buffer=16, telemetry="off",
            )
            p = tmp_path / f"kube_{two_phase}.npz"
            res = eng.replay(checkpoint_path=str(p), checkpoint_every=2)
            results[two_phase] = (eng, res, p)
        finally:
            os.environ.pop(GATE_EXCHANGE, None)
    _, ref, p_on = results[True]
    eng_off, res_off, p_off = results[False]
    np.testing.assert_array_equal(res_off.assignments, ref.assignments)
    assert _stable_summary(res_off) == _stable_summary(ref)
    assert (
        hashlib.sha256(p_on.read_bytes()).hexdigest()
        == hashlib.sha256(p_off.read_bytes()).hexdigest()
    )
    res = eng_off.replay(checkpoint_path=str(p_on), resume=True)
    np.testing.assert_array_equal(res.assignments, ref.assignments)


# ── threaded pager parity ────────────────────────────────────────────


@pytest.fixture(scope="module")
def pager_results(case):
    """{(shards, threaded): (engine, ReplayResult, flight_bytes)} for
    paged replays with the flight recorder on under the deterministic
    scrub — the stream itself must be byte-identical threaded on/off."""
    import os
    import tempfile

    ec, ep = case
    out = {}
    os.environ["KSIM_DETERMINISTIC_JSONL"] = "1"
    try:
        for threaded in (True, False):
            os.environ[GATE_PAGER] = "1" if threaded else "0"
            for s in (1, 2):
                fl = os.path.join(
                    tempfile.mkdtemp(prefix="ksim_ov_"), "fl.jsonl"
                )
                eng = JaxReplayEngine(
                    ec, ep, FrameworkConfig(), chunk_waves=4, node_shards=s,
                    paged=True, telemetry="off", flight_recorder=fl,
                )
                res = eng.replay()
                with open(fl, "rb") as f:
                    out[(s, threaded)] = (eng, res, f.read())
    finally:
        os.environ.pop(GATE_PAGER, None)
        os.environ.pop("KSIM_DETERMINISTIC_JSONL", None)
    return out


def test_threaded_pager_bit_parity(pager_results):
    _, ref, _ = pager_results[(1, False)]
    for (s, threaded), (_, res, _) in pager_results.items():
        np.testing.assert_array_equal(
            res.assignments, ref.assignments,
            err_msg=(
                f"node_shards={s} pager_thread={threaded}: assignments "
                "diverged"
            ),
        )
        assert _stable_summary(res) == _stable_summary(ref)


def test_threaded_pager_flight_stream_byte_identical(pager_results):
    """Under KSIM_DETERMINISTIC_JSONL the recorded stream is
    byte-identical threaded on/off at each shard count: miss counts are
    structural, wait/wall fields are scrubbed, and the row schema never
    leaks which thread fetched the page."""
    for s in (1, 2):
        assert pager_results[(s, True)][2] == pager_results[(s, False)][2], (
            f"node_shards={s}: flight stream differs threaded on/off"
        )


def test_threaded_pager_jsonl_byte_identical(
    pager_results, tmp_path, monkeypatch
):
    blobs = {
        key: _deterministic_jsonl(
            res, tmp_path / f"p{key[0]}_{key[1]}.jsonl", monkeypatch
        )
        for key, (_, res, _) in pager_results.items()
    }
    assert len(set(blobs.values())) == 1


# ── pager resume-jump invalidation (round-19 fix) ────────────────────


@pytest.mark.parametrize("threaded", [False, True])
def test_pager_resume_jump_invalidation(threaded):
    """Crafted resume jump: a staged prefetch for chunk 1 followed by
    ``get(5)`` (what a checkpoint-resume jump does) must DISCARD the
    stale page — counted as an invalidation — and re-issue a
    synchronous fetch counted as a stall. Previously the stale hit was
    silently served a plain miss with no invalidation signal, so flight
    streams under-reported resume-jump misses. The deterministic
    counters (stalls, invalidations, prefetches, served pages) are
    identical threaded on or off."""
    fetched = []

    def fetch(ci):
        fetched.append(ci)
        return ("page", ci)

    pager = _PodPager(fetch, threaded=threaded)
    try:
        assert (pager.stalls, pager.invalidations, pager.depth) == (0, 0, 0)
        # Cold start: synchronous miss.
        assert pager.get(0) == ("page", 0)
        assert (pager.stalls, pager.invalidations) == (1, 0)
        # Healthy prefetch hit: no new stall.
        pager.prefetch(1)
        assert pager.get(1) == ("page", 1)
        assert (pager.stalls, pager.invalidations) == (1, 0)
        # Resume jump: staged 2, asked for 5.
        pager.prefetch(2)
        assert pager.get(5) == ("page", 5)
        assert pager.invalidations == 1, "stale staged page not counted"
        assert pager.stalls == 2, "re-issued fetch must count as a stall"
        assert pager.depth == 0
        # The pager must have actually fetched chunk 5 (not served 2).
        assert fetched[-1] == 5
        # And recovers to normal operation afterwards.
        pager.prefetch(6)
        assert pager.get(6) == ("page", 6)
        assert (pager.stalls, pager.invalidations, pager.prefetches) == (
            2, 1, 3,
        )
    finally:
        pager.close()


# ── background publisher unit semantics ──────────────────────────────


def test_publisher_single_flight_newest_wins(monkeypatch):
    """Submits while a publication is in flight coalesce to the newest
    snapshot; drain() blocks until the KV plane holds the last-submitted
    cursor."""
    import threading

    from kubernetes_simulator_tpu.parallel import dcn

    published = []
    gate = threading.Event()

    def fake_publish(cursor, payload, block, epoch=None):
        gate.wait(timeout=10.0)
        published.append((cursor, payload, block, epoch))
        return True

    monkeypatch.setattr(dcn, "publish_checkpoint", fake_publish)
    start = dcn.bg_publish_stats()
    pub = dcn._CheckpointPublisher()
    pub.submit(1, "p1", (0, 4), 0)
    # Worker is blocked on the gate holding job 1 (or job 1 is still
    # pending) — these three coalesce down to the newest.
    pub.submit(2, "p2", (0, 4), 0)
    pub.submit(3, "p3", (0, 4), 0)
    pub.submit(4, "p4", (0, 4), 0)
    gate.set()
    pub.drain()
    cursors = [p[0] for p in published]
    assert cursors[-1] == 4, cursors
    # Single-flight: at most 2 publications ran (the in-flight one plus
    # the coalesced survivor), never all 4.
    assert len(published) <= 2, cursors
    stats = dcn.bg_publish_stats()
    assert stats["submitted"] - start["submitted"] == 4
    assert stats["coalesced"] - start["coalesced"] >= 2
    assert stats["drains"] - start["drains"] == 1


def test_publisher_error_reraised_attributed(monkeypatch):
    """An unexpected worker error is stored and re-raised at the next
    loop touch, attributed to the failing cursor."""
    from kubernetes_simulator_tpu.parallel import dcn

    def boom(cursor, payload, block, epoch=None):
        raise OSError("kv wire melted")

    monkeypatch.setattr(dcn, "publish_checkpoint", boom)
    pub = dcn._CheckpointPublisher()
    pub.submit(7, "p", (0, 4), 0)
    with pytest.raises(RuntimeError, match="cursor 7") as ei:
        pub.drain()
    assert isinstance(ei.value.__cause__, OSError)
    # The error is consumed: the publisher is usable again.
    monkeypatch.setattr(
        dcn, "publish_checkpoint",
        lambda *a, **k: True,
    )
    pub.submit(8, "p", (0, 4), 0)
    pub.drain()


def test_publish_checkpoint_async_single_process_noop(monkeypatch):
    """Outside a DCN fleet the async entry point no-ops like every
    coordination call — nothing is queued, nothing is spawned."""
    from kubernetes_simulator_tpu.parallel import dcn

    start = dcn.bg_publish_stats()
    assert dcn.publish_checkpoint_async(3, "p", (0, 4)) is False
    assert dcn.bg_publish_stats()["submitted"] == start["submitted"]
    dcn.drain_publisher()  # must not hang or raise


def test_ckpt_async_gate_falls_back_sync(monkeypatch):
    """Gate off → the async entry point routes to the synchronous
    publisher (same return contract), never the thread."""
    from kubernetes_simulator_tpu.parallel import dcn

    calls = []
    monkeypatch.setenv(GATE_CKPT, "0")
    monkeypatch.setattr(
        dcn, "publish_checkpoint",
        lambda *a, **k: calls.append(a) or True,
    )
    monkeypatch.setattr(dcn, "process_info", lambda: (3, 1))
    start = dcn.bg_publish_stats()
    assert dcn.publish_checkpoint_async(5, "p", (0, 4), epoch=0) is True
    assert len(calls) == 1 and calls[0][0] == 5
    assert dcn.bg_publish_stats()["submitted"] == start["submitted"]


# ── overlap config section ───────────────────────────────────────────


def test_overlap_spec_parsing():
    from kubernetes_simulator_tpu.utils.config import SimConfig

    cfg = SimConfig.from_dict({
        "strategy": "jax",
        "overlap": {"pagerThread": True, "twoPhaseExchange": False},
    })
    assert cfg.overlap.pager_thread is True
    assert cfg.overlap.background_publisher is None
    assert cfg.overlap.two_phase_exchange is False
    assert SimConfig.from_dict({}).overlap is None
    with pytest.raises(ValueError, match="overlap.pagerThread"):
        SimConfig.from_dict({"overlap": {"pagerThread": "yes"}})


def test_overlap_validation_refusals():
    """A gate explicitly enabled on a config lacking the machinery it
    overlaps is refused with an actionable message."""
    from kubernetes_simulator_tpu.cli import _overlap_errors
    from kubernetes_simulator_tpu.utils.config import SimConfig

    # pagerThread without pagedWaves.
    cfg = SimConfig.from_dict({
        "strategy": "jax", "overlap": {"pagerThread": True},
    })
    errs = _overlap_errors(cfg)
    assert any("pagedWaves" in e for e in errs), errs
    cfg = SimConfig.from_dict({
        "strategy": "jax", "pagedWaves": True,
        "overlap": {"pagerThread": True},
    })
    assert _overlap_errors(cfg) == []

    # backgroundPublisher without a checkpoint cadence.
    cfg = SimConfig.from_dict({
        "strategy": "jax", "overlap": {"backgroundPublisher": True},
    })
    errs = _overlap_errors(cfg)
    assert any("checkpoint" in e for e in errs), errs
    cfg = SimConfig.from_dict({
        "strategy": "jax",
        "dcn": {"recovery": {"enable": True, "checkpointEvery": 1}},
        "overlap": {"backgroundPublisher": True},
    })
    assert _overlap_errors(cfg) == []

    # Explicit opt-OUTs are always fine — they remove machinery, never
    # assume it.
    cfg = SimConfig.from_dict({
        "strategy": "jax",
        "overlap": {
            "pagerThread": False, "backgroundPublisher": False,
            "twoPhaseExchange": False,
        },
    })
    assert _overlap_errors(cfg) == []


def test_validate_accepts_example_config18():
    """The shipped round-19 example parses, carries all three gates
    (backgroundPublisher deliberately false — it is the fleet-only
    leg), and passes full validation with zero errors."""
    import os

    from kubernetes_simulator_tpu.cli import validate_config
    from kubernetes_simulator_tpu.utils.config import SimConfig

    path = os.path.join(
        os.path.dirname(__file__), "..", "examples", "config18_overlap.yaml"
    )
    cfg = SimConfig.load(path)
    assert cfg.node_shards == 2 and cfg.paged_waves
    assert cfg.overlap is not None
    assert cfg.overlap.pager_thread is True
    assert cfg.overlap.two_phase_exchange is True
    assert cfg.overlap.background_publisher is False
    assert cfg.flight_recorder is not None
    assert validate_config(cfg) == []
