"""Gate boundaries and exactness of the round-3 fast paths:

- ops.tpu.select_node_packed vs select_node (ties, boundary totals,
  all-infeasible) — the packed form must be bit-identical within its gate.
- tpu3.pack_select_ok gate edges (Σw·100 bound, node-count bound,
  fractional / negative / zero weights).
- V3Static seg_mode detection (stride / block / none) and the segmented
  domfeas path vs the one-hot matmul path on the same trace.
- single_topo dom_at fast path vs the [G, N] einsum (multi-topology traces
  must NOT take it).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import PAD, encode
from kubernetes_simulator_tpu.ops import tpu as T
from kubernetes_simulator_tpu.ops import tpu3 as V3
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine, StepSpec
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


# ---------------------------------------------------------------------------
# select_node_packed vs select_node
# ---------------------------------------------------------------------------


def _both(scores, feasible):
    n1, p1 = jax.jit(T.select_node)(scores, feasible)
    n2, p2 = jax.jit(T.select_node_packed)(scores, feasible)
    return (int(n1), bool(p1)), (int(n2), bool(p2))


def test_packed_matches_plain_on_ties_and_boundaries():
    rng = np.random.default_rng(0)
    N = 257
    for trial in range(50):
        # Integer totals up to the packing bound, dense ties.
        scores = rng.integers(0, T.PACK_MAX_TOTAL + 1, size=N).astype(np.float32)
        scores[rng.integers(0, N, size=N // 3)] = float(T.PACK_MAX_TOTAL)
        feasible = rng.random(N) < rng.choice([0.02, 0.5, 0.98])
        a, b = _both(jnp.asarray(scores), jnp.asarray(feasible))
        assert a == b, (trial, a, b)


def test_packed_all_infeasible_returns_pad():
    scores = jnp.zeros(64, jnp.float32)
    feasible = jnp.zeros(64, bool)
    a, b = _both(scores, feasible)
    assert a == (PAD, False) and b == (PAD, False)


def test_packed_max_total_exact_at_bound():
    # Max packed value must round-trip exactly at the documented bound.
    N = T.PACK_MAX_NODES
    v = float(T.PACK_MAX_TOTAL) * T.PACK_SHIFT + (T.PACK_SHIFT - 1.0)
    assert v < 2**24
    assert np.float32(v) == v  # integer < 2^24 is f32-exact


def test_pack_gate_edges():
    spec = StepSpec(
        fit=True, taints=False, node_affinity=False, interpod=False,
        spread=False,
    )
    ok = V3.pack_select_ok
    assert ok(spec, {"NodeResourcesFit": 1.0}, 16384)
    assert not ok(spec, {"NodeResourcesFit": 1.0}, 16385)  # node bound
    assert ok(spec, {"NodeResourcesFit": 10.0}, 100)  # 1000 <= 1023
    assert not ok(spec, {"NodeResourcesFit": 11.0}, 100)  # 1100 > 1023
    assert not ok(spec, {"NodeResourcesFit": 1.5}, 100)  # fractional
    assert not ok(spec, {"NodeResourcesFit": -1.0}, 100)  # negative
    # Zero-weight rows do not count toward the bound.
    assert ok(spec, {"NodeResourcesFit": 1.0, "PodTopologySpread": 0.0}, 100)
    # Inactive plugins do not count either.
    spec5 = StepSpec()
    w5 = {n: 3.0 for n in (
        "NodeResourcesFit", "TaintToleration", "NodeAffinity",
        "InterPodAffinity", "PodTopologySpread",
    )}
    assert not ok(spec5, w5, 100)  # 5*3*100 = 1500 > 1023
    spec2 = StepSpec(taints=False, node_affinity=False, interpod=False)
    assert ok(spec2, w5, 100)  # only fit+spread active: 600


# ---------------------------------------------------------------------------
# seg_mode detection + parity of the segmented domfeas path
# ---------------------------------------------------------------------------


def _spread_case(nodes=64, pods=160, seed=0):
    cluster = make_cluster(nodes, seed=seed, taint_fraction=0.0)
    pod_list, _ = make_workload(
        pods, seed=seed, with_affinity=False, with_spread=True,
        with_tolerations=False, gang_fraction=0.0,
    )
    return encode(cluster, pod_list)


def test_seg_mode_detected_stride():
    ec, ep = _spread_case()
    spec = StepSpec.from_config(ec, None, ep)
    st = V3.V3Static.build(ec, ep, spec)
    # make_cluster assigns zone = i % num_zones → stride pattern.
    assert st.single_topo
    assert st.seg_mode == "stride" and st.seg_D > 0


def test_seg_mode_block_and_none_detection():
    ec, ep = _spread_case()
    spec = StepSpec.from_config(ec, None, ep)
    st = V3.V3Static.build(ec, ep, spec)
    t0 = st.topo0
    N = ec.num_nodes
    D = int(ec.num_domains[t0])
    saved = ec.node_domain
    try:
        # Rewrite the node→domain map to a block layout.
        nd = saved.copy()
        nd[t0] = np.arange(N) // (N // D)
        ec.node_domain = nd
        assert V3.V3Static.build(ec, ep, spec).seg_mode == "block"
        # Scrambled layout → no pattern (keep it genuinely unstructured).
        nd2 = nd.copy()
        nd2[t0] = np.random.default_rng(0).permutation(nd[t0])
        ec.node_domain = nd2
        if (nd2[t0] == np.arange(N) % D).all() or (
            nd2[t0] == np.arange(N) // (N // D)
        ).all():  # pragma: no cover - astronomically unlikely
            pytest.skip("permutation landed on a structured layout")
        assert V3.V3Static.build(ec, ep, spec).seg_mode == ""
    finally:
        ec.node_domain = saved


def test_segmented_domfeas_matches_einsum_path():
    """Same trace through the seg path and the forced-einsum path must give
    identical assignments (greedy anchor pins both)."""
    ec, ep = _spread_case(nodes=48, pods=120, seed=3)
    cfg = FrameworkConfig()
    eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=8)
    assert eng.static3.seg_mode == "stride"
    res_seg = eng.replay()

    eng2 = JaxReplayEngine(ec, ep, cfg, chunk_waves=8)
    eng2.static3 = dataclasses.replace(eng2.static3, seg_mode="", seg_D=0)
    from kubernetes_simulator_tpu.sim.jax_runtime import (
        make_chunk_fn3_src, rep_slots_for,
    )

    eng2.chunk_fn = make_chunk_fn3_src(
        eng2.static3, eng2.shared3, rep_slots_for(eng2.static3, ep),
        eng2.wave_width, eng2.spec,
    )
    res_ein = eng2.replay()
    np.testing.assert_array_equal(res_seg.assignments, res_ein.assignments)

    anchor = greedy_replay(ec, ep, cfg)
    np.testing.assert_array_equal(res_seg.assignments, anchor.assignments)


def test_packed_select_off_matches_on():
    """Fractional weight disables packing; assignments must still match the
    anchor (plain select path)."""
    ec, ep = _spread_case(nodes=48, pods=120, seed=4)
    cfg = FrameworkConfig(weights={"PodTopologySpread": 1.5})
    from kubernetes_simulator_tpu.sim.jax_runtime import StepSpec as SS

    eng = JaxReplayEngine(ec, ep, cfg, chunk_waves=8)
    assert not V3.pack_select_ok(
        eng.spec, dict(eng.spec.weights), ec.num_nodes
    )
    res = eng.replay()
    anchor = greedy_replay(ec, ep, cfg)
    np.testing.assert_array_equal(res.assignments, anchor.assignments)


# ---------------------------------------------------------------------------
# single_topo dom_at fast path
# ---------------------------------------------------------------------------


def test_multi_topology_disables_single_topo():
    cluster = make_cluster(32, seed=1, taint_fraction=0.0)
    pods, _ = make_workload(
        96, seed=1, with_affinity=True, with_spread=True,
        with_tolerations=False, gang_fraction=0.0,
    )
    ec, ep = encode(cluster, pods)
    spec = StepSpec.from_config(ec, None, ep)
    st = V3.V3Static.build(ec, ep, spec)
    n_topos = len({
        int(t) for t, nd in zip(
            ec.group_topo[: st.G], st.nd_g
        ) if t >= 0 and nd > 0
    })
    assert st.single_topo == (n_topos <= 1)
    # Either way the engine must match the host anchor.
    cfg = FrameworkConfig()
    res = JaxReplayEngine(ec, ep, cfg, chunk_waves=8).replay()
    anchor = greedy_replay(ec, ep, cfg)
    np.testing.assert_array_equal(res.assignments, anchor.assignments)


def test_seg_mode_wide_domain_fallback_parity():
    """32..Dcap domains: seg_mode stays on (reshape-any domfeas, tile
    expansion) — the bit-pack int32 bound must not silently drop the
    structured fast path for wide stride layouts. Generator zone names
    sort lexicographically past 9 domains, so the 40-domain stride map is
    installed directly (every consumer downstream of encode reads
    node_domain/num_domains, not the raw labels)."""
    ec, ep = _spread_case(nodes=80, pods=200, seed=9)
    spec = StepSpec.from_config(ec, None, ep)
    t0 = V3.V3Static.build(ec, ep, spec).topo0
    ec.node_domain[t0] = (np.arange(ec.num_nodes) % 40).astype(np.int32)
    ec.num_domains[t0] = 40
    ec.max_domains = max(ec.max_domains, 40)
    st = V3.V3Static.build(ec, ep, spec)
    assert st.seg_mode == "stride" and st.seg_D == 40
    cfg = FrameworkConfig()
    res = JaxReplayEngine(ec, ep, cfg, chunk_waves=8).replay()
    anchor = greedy_replay(ec, ep, cfg)
    np.testing.assert_array_equal(res.assignments, anchor.assignments)
