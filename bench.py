"""Benchmark entry point — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric ([BASELINE]): pod-placements/sec. The reference publishes no
numbers (BASELINE.md), so ``vs_baseline`` is the speedup of the JAX what-if
path over this framework's own CPU default plugin path on the same
workload shape (per-placement rate ratio) — the honest available baseline.

Workload: batched what-if (config #3 shape) — S scenarios × full default
plugin set, measured on the real device; CPU rate measured on a pod
subsample (it is orders of magnitude slower).

Env knobs: BENCH_NODES, BENCH_PODS, BENCH_SCENARIOS, BENCH_CPU_PODS.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    nodes = int(os.environ.get("BENCH_NODES", 2000))
    pods_n = int(os.environ.get("BENCH_PODS", 20_000))
    S = int(os.environ.get("BENCH_SCENARIOS", 128))
    cpu_pods = int(os.environ.get("BENCH_CPU_PODS", 2000))

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.greedy import greedy_replay
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(
        pods_n, seed=0, with_affinity=True, with_spread=True, with_tolerations=True,
        gang_fraction=0.02, gang_size=4,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()

    # CPU default-path baseline on a subsample (same cluster).
    pods_small = pods[:cpu_pods]
    ec_s, ep_s = encode(cluster, pods_small)
    cpu_res = greedy_replay(ec_s, ep_s, FrameworkConfig())
    cpu_pps = cpu_res.placements_per_sec

    # JAX what-if batch: compile once (first run), then measure best-of-2
    # (the tunneled device occasionally stalls a single run by >10x).
    scenarios = uniform_scenarios(ec, S, seed=0)
    eng = WhatIfEngine(ec, ep, scenarios, cfg, chunk_waves=512)
    eng.run()  # warmup: compile + first execution
    res = eng.run()
    res2 = eng.run()
    if res2.wall_clock_s < res.wall_clock_s:
        res = res2

    value = res.placements_per_sec
    vs = value / cpu_pps if cpu_pps > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "pod-placements/sec (what-if %d scenarios x %d nodes x %d pods, full default plugin set)"
                % (S, nodes, pods_n),
                "value": round(value, 1),
                "unit": "placements/sec",
                "vs_baseline": round(vs, 2),
                "detail": {
                    "jax_wall_s": round(res.wall_clock_s, 3),
                    "jax_total_placed": res.total_placed,
                    "cpu_default_path_pps": round(cpu_pps, 1),
                    "scenario0_placed": int(res.placed[0]),
                    "device": _device_kind(),
                },
            }
        )
    )


def _device_kind() -> str:
    try:
        import jax

        return str(jax.devices()[0])
    except Exception as e:  # pragma: no cover
        return f"unavailable: {e}"


if __name__ == "__main__":
    main()
