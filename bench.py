"""Benchmark entry point — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric ([BASELINE]): pod-placements/sec. The reference publishes no
numbers (BASELINE.md), so ``vs_baseline`` is the speedup of the JAX what-if
path over this framework's own CPU default plugin path on the same
workload shape (per-placement rate ratio) — the honest available baseline.

Workload: batched what-if (config #3 shape) — S scenarios × full default
plugin set, measured on the real device; CPU rate measured on a pod
subsample (it is orders of magnitude slower).

Env knobs: BENCH_NODES, BENCH_PODS, BENCH_SCENARIOS, BENCH_CPU_PODS.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def main():
    nodes = int(os.environ.get("BENCH_NODES", 2000))
    pods_n = int(os.environ.get("BENCH_PODS", 20_000))
    S = int(os.environ.get("BENCH_SCENARIOS", 128))
    cpu_pods = int(os.environ.get("BENCH_CPU_PODS", 2000))

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.greedy import greedy_replay
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(
        pods_n, seed=0, with_affinity=True, with_spread=True, with_tolerations=True,
        gang_fraction=0.02, gang_size=4,
    )
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()

    # CPU default-path baseline on a subsample (same cluster).
    pods_small = pods[:cpu_pods]
    ec_s, ep_s = encode(cluster, pods_small)
    cpu_res = greedy_replay(ec_s, ep_s, FrameworkConfig())
    cpu_pps = cpu_res.placements_per_sec

    # JAX what-if batch: compile once (warmup run), then N timed runs.
    # The headline is the MEDIAN rate — the tunneled device occasionally
    # stalls a single run by >10x, and a single best-of-K number made
    # cross-round comparisons indistinguishable from noise (round-2
    # verdict); min/max/all walls ship in detail for spread inspection.
    runs = max(1, int(os.environ.get("BENCH_RUNS", 5)))
    scenarios = uniform_scenarios(ec, S, seed=0)
    eng = WhatIfEngine(ec, ep, scenarios, cfg, chunk_waves=512)
    eng.run()  # warmup: compile + first execution
    results = [eng.run() for _ in range(runs)]
    walls = sorted(r.wall_clock_s for r in results)
    med_wall = float(np.median(walls))
    res = results[0]  # placement counts are identical across runs
    value = res.total_placed / med_wall if med_wall > 0 else 0.0
    vs = value / cpu_pps if cpu_pps > 0 else 0.0
    print(
        json.dumps(
            {
                "metric": "pod-placements/sec (what-if %d scenarios x %d nodes x %d pods, full default plugin set)"
                % (S, nodes, pods_n),
                "value": round(value, 1),
                "unit": "placements/sec",
                "vs_baseline": round(vs, 2),
                "detail": {
                    "jax_wall_median_s": round(med_wall, 3),
                    "jax_wall_min_s": round(walls[0], 3),
                    "jax_wall_max_s": round(walls[-1], 3),
                    "jax_walls_s": [round(w, 3) for w in walls],
                    "timed_runs": runs,
                    "jax_total_placed": res.total_placed,
                    "cpu_default_path_pps": round(cpu_pps, 1),
                    "scenario0_placed": int(res.placed[0]),
                    "device": _device_kind(),
                },
            }
        )
    )


def _device_kind() -> str:
    try:
        import jax

        return str(jax.devices()[0])
    except Exception as e:  # pragma: no cover
        return f"unavailable: {e}"


if __name__ == "__main__":
    main()
