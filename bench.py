"""Benchmark entry point — prints ONE JSON line:
{"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Headline metric ([BASELINE]): pod-placements/sec. The reference publishes no
numbers (BASELINE.md), so ``vs_baseline`` is the speedup of the JAX what-if
path over this framework's own CPU default plugin path on the same
workload shape (per-placement rate ratio) — the honest available baseline.

Workload: batched what-if (config #3 shape) — S scenarios × full default
plugin set, measured on the real device. Since round 4 the headline
workload has finite pod durations (mean ``BENCH_DURATION_MEAN``), so the
number exercises the DEFAULT-ON chunk-granular completions machinery;
a durationless (arrivals-only) run ships in ``detail`` for cross-round
continuity with r01–r03. CPU rate is measured on a pod subsample of the
same workload (it is orders of magnitude slower).

Round 10: the headline is MESH-DEFAULT. When >1 accelerator is visible
the what-if engine runs shard_map over all of them and the scenario
count scales with the device count (BENCH_SCENARIOS per device — 128 ×
8 = 1024 on a v5e-8), with weak/strong-scaling reference runs in
``detail.scaling`` (see README § Performance for how to read them).
``n_devices`` / ``mesh_shape`` / ``scenarios`` are stamped at the TOP
level of the JSON line so BENCH_r0*.json rounds stay comparable across
configurations. On one device everything falls back to the r05
single-chip protocol unchanged. The durationless continuity run and the
tuner sweep intentionally STAY single-chip/per-device-shaped — they are
the cross-round continuity anchors.

Env knobs: BENCH_NODES, BENCH_PODS, BENCH_SCENARIOS (per device),
BENCH_CPU_PODS, BENCH_RUNS, BENCH_REF_RUNS (timed runs for the scaling
reference configurations), BENCH_DURATION_MEAN (seconds; 0 disables
durations), BENCH_TUNE_POP / BENCH_TUNE_SCEN (the ``tune_popsweep``
detail headline: candidate-policies/sec through the policy tuner's
batched sweep — the config2 search space, i.e. the full default plugin
set's 5 Score weights plus the NodeResourcesFit strategy selector; 0
population disables), BENCH_RECOVERY (0 skips the ``detail.dcn_recovery``
cost block), BENCH_RECOVERY_REPS, BENCH_CKPT_EVERY (cadence for the
fleet-only publication-overhead run), BENCH_DURABLE (0 skips the round-20
``detail.durable_ground`` durability-journal micro-bench: journal write
overhead vs the encode wall, cold-resume wall, adopted-block count),
BENCH_BORG / BENCH_BORG_NODES /
BENCH_BORG_PODS (borg_scale detail block), BENCH_HEADLINE /
BENCH_HEADLINE_NODES / BENCH_HEADLINE_PODS / BENCH_HEADLINE_FLIGHT
(round 16 ``borg_headline`` composed run — Borg-shaped trace through
nodeShards × pagedWaves with the flight recorder on).

Round 12: ``--profile`` (or ``KSIM_PROFILE_DIR=<dir>``) wraps the timed
headline runs in ``jax.profiler.trace`` with TraceAnnotation markers on
the PHASE_NAMES phases and chunk dispatch (utils.profiling) — load the
trace dir in TensorBoard/Perfetto; results are bit-identical with
profiling on or off. ``detail`` gains the engine-level wall-clock
``phases`` breakdown (from the fleet-merged telemetry, keys
``p<pid>/<phase>``) and a ``live_buffers`` watermark gauge
(``jax.live_arrays()`` count/bytes + backend peak bytes where reported).
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


def _overlap_block(ph, flight_rows):
    """Round 19 overlap accounting for the headline run: split the pager
    fetch wall into what the chunk loop actually waited on
    (exposed_stall_s — THE number the threaded pager shrinks) and the
    wall the background worker absorbed (hidden_prefetch_s), and stamp
    which overlap features were live so bench_compare can refuse
    apples-to-oranges diffs."""
    from kubernetes_simulator_tpu.ops import tpu as _T
    from kubernetes_simulator_tpu.sim.jax_runtime import (
        _pager_thread_enabled,
    )

    def _cum(field, cast=float):
        return max(
            (
                cast(r.get(field, 0))
                for r in flight_rows
                if r.get("event") == "chunk"
            ),
            default=cast(0),
        )

    exposed = _cum("pager_stall_s")
    prefetch = _cum("pager_prefetch_s")
    return {
        "exposed_stall_s": round(exposed, 4),
        "prefetch_wall_s": round(prefetch, 4),
        "hidden_prefetch_s": round(max(prefetch - exposed, 0.0), 4),
        "pager_waits": _cum("pager_waits", int),
        "pager_invalidations": _cum("pager_invalidations", int),
        "pager_threaded": bool(_pager_thread_enabled()),
        "two_phase_exchange": bool(_T.two_phase_exchange()),
    }


def main():
    if "--profile" in sys.argv[1:]:
        os.environ.setdefault(
            "KSIM_PROFILE_DIR", os.path.join(os.getcwd(), "ksim_profile")
        )
    nodes = int(os.environ.get("BENCH_NODES", 2000))
    pods_n = int(os.environ.get("BENCH_PODS", 20_000))
    S = int(os.environ.get("BENCH_SCENARIOS", 128))
    cpu_pods = int(os.environ.get("BENCH_CPU_PODS", 2000))
    # Mean pod runtime: the 20k-pod workload spans ~200 s of arrivals at
    # the default rate, so 50 s means most pods complete mid-replay and
    # several chunk boundaries carry real release work.
    dur_mean = float(os.environ.get("BENCH_DURATION_MEAN", 50.0))

    # DCN headline mode (round 11): under scripts/dcn_launch.py this
    # joins the coordinator (enabling the compile cache FIRST, per the
    # documented ordering); otherwise it is a no-op and the single-host
    # protocol below is unchanged.
    from kubernetes_simulator_tpu.parallel import dcn

    dcn.maybe_init_from_env()

    from kubernetes_simulator_tpu.utils.compile_cache import enable as _cc

    _cc()

    import jax

    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.parallel.mesh import make_mesh
    from kubernetes_simulator_tpu.sim.greedy import greedy_replay
    from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
    from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios
    from kubernetes_simulator_tpu.utils.metrics import round_fragmentation

    # Mesh-default headline (round 10): shard the scenario axis over every
    # visible device; scenario count scales with the device count so each
    # device keeps the r05 per-chip shape (weak-scaling protocol).
    ndev = len(jax.devices())  # GLOBAL under DCN (all processes' devices)
    nproc = jax.process_count()
    mesh = make_mesh() if ndev > 1 else None
    S_head = S * ndev if mesh is not None else S
    mesh_shape = (
        dict(zip(mesh.axis_names, (int(d) for d in mesh.devices.shape)))
        if mesh is not None
        else None
    )

    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)

    def _make_pods(duration_mean):
        pods, _ = make_workload(
            pods_n, seed=0, with_affinity=True, with_spread=True,
            with_tolerations=True, gang_fraction=0.02, gang_size=4,
            duration_mean=duration_mean or None,
        )
        return pods

    pods = _make_pods(dur_mean)
    ec, ep = encode(cluster, pods)
    cfg = FrameworkConfig()

    # CPU default-path baseline on a subsample (same workload incl.
    # durations — the greedy anchor mirrors the chunk-granular releases).
    pods_small = pods[:cpu_pods]
    ec_s, ep_s = encode(cluster, pods_small)
    cpu_res = greedy_replay(
        ec_s, ep_s, FrameworkConfig(),
        completions_chunk_waves=512 if dur_mean else None,
    )
    cpu_pps = cpu_res.placements_per_sec

    # JAX what-if batch: compile once (warmup run), then N timed runs.
    # The headline is the MEDIAN rate — the tunneled device occasionally
    # stalls a single run by >10x, and a single best-of-K number made
    # cross-round comparisons indistinguishable from noise (round-2
    # verdict); min/max/all walls ship in detail for spread inspection.
    runs = max(1, int(os.environ.get("BENCH_RUNS", 5)))

    def _timed(eng, n):
        eng.run()  # warmup: compile + first execution
        rs = [eng.run() for _ in range(n)]
        ws = sorted(r.wall_clock_s for r in rs)
        return rs[0], float(np.median(ws)), ws

    # Device-profiler hooks (round 12): the per-process trace lands in
    # KSIM_PROFILE_DIR (siblings suffix .p<pid> like every other sink).
    from kubernetes_simulator_tpu.utils.profiling import (
        device_trace,
        live_buffer_stats,
        profile_dir,
    )

    prof_dir = profile_dir()
    eng_head = WhatIfEngine(
        ec, ep, uniform_scenarios(ec, S_head, seed=0), cfg,
        chunk_waves=512, mesh=mesh,
    )
    if prof_dir:
        # Compile outside the trace: a multi-second first dispatch fills
        # the profiler's event buffer and truncates the annotations the
        # trace exists for.
        eng_head.run()
    with device_trace(dcn.output_path_for_process(prof_dir)):
        res, med_wall, walls = _timed(eng_head, runs)
    value = res.total_placed / med_wall if med_wall > 0 else 0.0
    vs = value / cpu_pps if cpu_pps > 0 else 0.0

    # Weak/strong-scaling references (mesh only). Weak: the r05 per-chip
    # shape (S scenarios, one device) — efficiency is per-device headline
    # rate over that. Strong: the SAME total scenario count on one device
    # — speedup is the headline rate over that. References get fewer
    # timed runs (they exist for the ratio, not the headline).
    # DCN-scaling block (round 11): per-process and aggregate pps next to
    # the PR-6 weak/strong block. The weak/strong/continuity/tuner
    # anchors are SINGLE-PROCESS references — under DCN they would be
    # silently re-shaped by the scenario slicing, so they are skipped
    # here and stay comparable by running bench.py without the launcher.
    dcn_block = {}
    if nproc > 1:
        dcn_block = {
            "dcn_scaling": {
                "process_count": nproc,
                "local_devices": ndev // nproc,
                "aggregate_pps": round(value, 1),
                "per_process_pps": round(value / nproc, 1),
                "local_wall_median_s": round(med_wall, 3),
                "single_process_reference": (
                    "run bench.py without dcn_launch.py for the "
                    "weak/strong + continuity anchors"
                ),
            }
        }

    # Elastic-recovery costs (round 15) — informational detail only
    # (bench_compare.py never gates on it). The headline timed runs
    # above keep checkpoint publication OFF (KSIM_DCN_CKPT_EVERY
    # defaults to 0), so ``value`` and the dcn_scaling block are
    # byte-unchanged by this block existing; it prices what turning
    # recovery on would cost:
    #   * codec walls: pack→pickle→b64 round-trip of a carrier-shaped
    #     snapshot (states [S_head, pods] + outs) — the per-publication
    #     CPU cost, and the restore cost a claimant pays before
    #     re-entering the chunk loop (failure DETECTION adds
    #     KSIM_DCN_STALL_S on top — a knob, not a measurement).
    #   * publish_overhead_pct: one extra replay with publication
    #     forced on (BENCH_CKPT_EVERY, default 8) against the headline
    #     median. Fleet-only — publish_checkpoint no-ops single-process
    #     — so the key is null outside dcn_launch.py.
    rec_block = {}
    if int(os.environ.get("BENCH_RECOVERY", "1") or 0):
        from kubernetes_simulator_tpu.parallel.dcn import (
            _decode_payload,
            _encode_payload,
        )

        rng = np.random.default_rng(15)
        snap = {
            "cursor": 7,
            "leaves": {
                "states": rng.integers(
                    -1, nodes, size=(S_head, len(pods)), dtype=np.int32
                ),
            },
            "outs": rng.random((S_head, 8)).astype(np.float32),
        }
        raw_mib = (
            snap["leaves"]["states"].nbytes + snap["outs"].nbytes
        ) / 2**20
        reps = max(1, int(os.environ.get("BENCH_RECOVERY_REPS", 3)))
        t0 = time.perf_counter()
        for _ in range(reps):
            chunks = _encode_payload(snap)
        enc_s = (time.perf_counter() - t0) / reps
        t0 = time.perf_counter()
        for _ in range(reps):
            _decode_payload(chunks)
        dec_s = (time.perf_counter() - t0) / reps

        publish_overhead_pct = None
        if nproc > 1 and med_wall > 0:
            prev_ck = os.environ.get("KSIM_DCN_CKPT_EVERY")
            os.environ["KSIM_DCN_CKPT_EVERY"] = str(
                max(1, int(os.environ.get("BENCH_CKPT_EVERY", 8)))
            )
            try:
                wall_ck = eng_head.run().wall_clock_s
            finally:
                if prev_ck is None:
                    os.environ.pop("KSIM_DCN_CKPT_EVERY", None)
                else:
                    os.environ["KSIM_DCN_CKPT_EVERY"] = prev_ck
            publish_overhead_pct = round(
                100.0 * (wall_ck - med_wall) / med_wall, 1
            )
        rec_block = {
            "dcn_recovery": {
                "recover_enabled": dcn.recover_enabled(),
                "ckpt_every": dcn.ckpt_every(),
                "ckpt_raw_mib": round(raw_mib, 2),
                "ckpt_blob_mib": round(
                    sum(len(c) for c in chunks) / 2**20, 2
                ),
                "ckpt_encode_s": round(enc_s, 4),
                "ckpt_publish_overhead_pct": publish_overhead_pct,
                "recovery_restore_wall_s": round(dec_s, 4),
            }
        }

    # Faultline costs (round 17) — informational detail only
    # (bench_compare.py never gates on it). Prices the hardening layer
    # under a FIXED injected schedule, no fleet needed:
    #   * retry_*: kv_retry absorbing a seeded 30% transient-error storm
    #     (tiny real backoff so the wall is the helper's, not a sleep).
    #   * crc_frame_*: CRC32+length framing overhead over a carrier-
    #     shaped blob, as a % of the round-14 codec's encode wall.
    #   * torn detection + fallback_recovery_wall_s: every blob the
    #     injector tears must be rejected by the frame check, and the
    #     wall is the full fallback path — reject the corrupt newest
    #     cursor, unframe + decode the prior complete one.
    fault_block = {}
    if int(os.environ.get("BENCH_FAULTLINE", "1") or 0):
        from kubernetes_simulator_tpu.parallel import faultline
        from kubernetes_simulator_tpu.parallel.dcn import (
            DcnRetryError,
            _decode_payload,
            _encode_payload,
            _frame_chunk,
            _unframe_chunk,
            kv_retry,
        )

        inj = faultline.Injector(seed=17, pid=0, kv_error_rate=0.3)

        def _flaky_op():
            if inj.hit("kv_error"):
                raise faultline.FaultlineInjected("bench")

        rs0 = dcn.retry_stats()
        n_ops, gaveup = 64, 0
        t0 = time.perf_counter()
        for _ in range(n_ops):
            try:
                kv_retry(
                    _flaky_op, op="bench", attempts=4,
                    base_s=1e-4, cap_s=4e-4,
                )
            except DcnRetryError:
                gaveup += 1
        retry_wall = time.perf_counter() - t0
        rs1 = dcn.retry_stats()

        rng_f = np.random.default_rng(17)
        snap_f = {
            "cursor": 3,
            "leaves": {
                "states": rng_f.integers(
                    -1, nodes, size=(256, 512), dtype=np.int32
                )
            },
        }
        t0 = time.perf_counter()
        raw_f = _encode_payload(snap_f)
        enc_f = time.perf_counter() - t0
        t0 = time.perf_counter()
        framed = [_frame_chunk(c) for c in raw_f]
        frame_s = time.perf_counter() - t0
        tear_inj = faultline.Injector(seed=17, pid=0, torn_write_rate=1.0)
        torn = [tear_inj.tear(c) for c in framed]
        detected = 0
        t0 = time.perf_counter()
        for bad in torn:
            try:
                _unframe_chunk(bad)
            except ValueError:
                detected += 1
        _decode_payload(_unframe_chunk(c) for c in framed)
        fallback_wall = time.perf_counter() - t0
        fault_block = {
            "fault_injection": {
                "injected_kv_error_rate": 0.3,
                "retry_ops": n_ops,
                "retry_count": rs1["retries"] - rs0["retries"],
                "retry_giveups": gaveup,
                "retry_wall_s": round(retry_wall, 4),
                "crc_frame_wall_s": round(frame_s, 4),
                "crc_frame_overhead_pct": round(
                    100.0 * frame_s / enc_f if enc_f > 0 else 0.0, 1
                ),
                "torn_injected": len(torn),
                "torn_detected": detected,
                "fallback_count": len(torn),
                "fallback_recovery_wall_s": round(fallback_wall, 4),
            }
        }

    # Work-queue accounting (round 18) — informational detail only
    # (bench_compare.py never gates on it). Populated when the timed
    # runs above actually drained the work-stealing queue (bench under
    # dcn_launch.py with KSIM_DCN_WORKQUEUE=1): this process's lease/
    # steal/speculation counters, the lease-renewal overhead as a share
    # of the headline median wall, and the lower-bound straggler wall
    # saved by speculative wins.
    wq_block = {}
    if dcn.wq_enabled():
        ws = dcn.wq_stats()
        renew_pct = None
        if nproc > 1 and med_wall > 0:
            renew_pct = round(100.0 * ws["renew_wall_s"] / med_wall, 2)
        wq_block = {
            "work_queue": {
                "block_size": dcn.wq_block_size() or None,
                "speculate": dcn.speculate_enabled(),
                "leases": ws["leases"],
                "steals": ws["steals"],
                "blocks_executed": ws["blocks_executed"],
                "spec_attempts": ws["spec_attempts"],
                "spec_wins": ws["spec_wins"],
                "spec_losses": ws["spec_losses"],
                "spec_wasted_chunks": ws["spec_wasted_chunks"],
                "dup_discards": ws["dup_discards"],
                "lease_renewals": ws["renewals"],
                "lease_renew_overhead_pct": renew_pct,
                "straggler_wall_saved_s": round(
                    ws["straggler_wall_saved_s"], 3
                ),
            }
        }

    # Durable-ground accounting (round 20) — informational detail only
    # (bench_compare.py diffs it without gating). Fleet-free micro-bench
    # of the durability-journal layer (parallel.dcn, KSIM_DCN_DURABLE_DIR)
    # in a throwaway directory:
    #   * journal_write_overhead_pct: the mirror wall (framed chunks +
    #     manifest-last, temp-then-rename) as a share of the encode+frame
    #     wall the publication already pays — the budget the round-19
    #     publisher thread hides it behind;
    #   * cold_resume_wall_s: walk the journal exactly as a restarted
    #     fleet's load_checkpoint would (namespace scan + full kf1/crc
    #     validation of the newest cursor per block);
    #   * adopted_blocks: completed work-queue blocks a fresh fleet
    #     adopts from the journal without re-execution (_journal_wq_scan
    #     over mirrored result blobs + done records).
    durable_block = {}
    if int(os.environ.get("BENCH_DURABLE", "1") or 0):
        import shutil
        import tempfile
        import zlib

        from kubernetes_simulator_tpu.parallel.dcn import (
            _encode_payload,
            _frame_chunk,
            _journal_ckpt_entries,
            _journal_read_blob,
            _journal_wq_result,
            _journal_wq_scan,
            _journal_write_blob,
            _journal_write_json,
        )

        jdir = tempfile.mkdtemp(prefix="ksim_bench_journal_")
        prev_jdir = os.environ.get("KSIM_DCN_DURABLE_DIR")
        os.environ["KSIM_DCN_DURABLE_DIR"] = jdir
        try:
            rng_d = np.random.default_rng(20)
            n_epochs, n_blocks = 8, 4
            snaps, encode_wall, mirror_wall = [], 0.0, 0.0
            for epoch_i in range(n_epochs):
                snap = {
                    "cursor": epoch_i,
                    "leaves": {
                        "states": rng_d.integers(
                            -1, nodes, size=(256, 512), dtype=np.int32
                        )
                    },
                }
                t0 = time.perf_counter()
                raw = _encode_payload(snap)
                crc, blob_len = 0, 0
                for ch in raw:
                    crc = zlib.crc32(ch.encode("ascii"), crc)
                    blob_len += len(ch)
                chunks = [_frame_chunk(ch) for ch in raw]
                manifest = json.dumps(
                    {"n": len(chunks), "crc": f"{crc & 0xFFFFFFFF:08x}",
                     "len": blob_len},
                    sort_keys=True,
                )
                encode_wall += time.perf_counter() - t0
                t0 = time.perf_counter()
                ok = _journal_write_blob(
                    os.path.join("ckpt", "1", "0", "0-64", str(epoch_i)),
                    chunks, manifest,
                )
                mirror_wall += time.perf_counter() - t0
                assert ok, "journal mirror failed in a fresh tempdir"
                snaps.append(snap)
            for bid in range(n_blocks):
                _journal_wq_result(
                    os.path.join("wq", "1", "bench"), bid, snaps[bid]
                )
                _journal_write_json(
                    os.path.join("wq", "1", "bench", "done", str(bid)),
                    {"pid": 0, "gen": 0, "spec": 0},
                )
            t0 = time.perf_counter()
            entries = _journal_ckpt_entries(0, 1)
            newest = max(int(cur) for _, cur in entries)
            _journal_read_blob(
                os.path.join("ckpt", "1", "0", "0-64", str(newest))
            )
            cold_wall = time.perf_counter() - t0
            adopted, _hint = _journal_wq_scan(1, "bench", n_blocks)
            durable_block = {
                "durable_ground": {
                    "journal_epochs": n_epochs,
                    "journal_write_wall_s": round(mirror_wall, 4),
                    "journal_write_overhead_pct": round(
                        100.0 * mirror_wall / encode_wall
                        if encode_wall > 0 else 0.0,
                        1,
                    ),
                    "cold_resume_wall_s": round(cold_wall, 4),
                    "cold_resume_cursors_seen": len(entries),
                    "adopted_blocks": len(adopted),
                }
            }
        finally:
            if prev_jdir is None:
                os.environ.pop("KSIM_DCN_DURABLE_DIR", None)
            else:
                os.environ["KSIM_DCN_DURABLE_DIR"] = prev_jdir
            shutil.rmtree(jdir, ignore_errors=True)

    scaling = {}
    if mesh is not None and nproc == 1:
        runs_ref = max(1, int(os.environ.get("BENCH_REF_RUNS", 2)))
        res_w, med_w, _ = _timed(
            WhatIfEngine(
                ec, ep, uniform_scenarios(ec, S, seed=0), cfg,
                chunk_waves=512,
            ),
            runs_ref,
        )
        weak_pps = res_w.total_placed / med_w if med_w > 0 else 0.0
        res_st, med_st, _ = _timed(
            WhatIfEngine(
                ec, ep, uniform_scenarios(ec, S_head, seed=0), cfg,
                chunk_waves=512,
            ),
            runs_ref,
        )
        strong_pps = res_st.total_placed / med_st if med_st > 0 else 0.0
        scaling = {
            "scaling": {
                "per_device_pps": round(value / ndev, 1),
                "weak": {
                    "single_chip_scenarios": S,
                    "single_chip_pps": round(weak_pps, 1),
                    "efficiency": round(
                        (value / ndev) / weak_pps if weak_pps > 0 else 0.0, 3
                    ),
                },
                "strong": {
                    "single_chip_scenarios": S_head,
                    "single_chip_pps": round(strong_pps, 1),
                    "speedup": round(
                        value / strong_pps if strong_pps > 0 else 0.0, 2
                    ),
                    "efficiency": round(
                        value / strong_pps / ndev if strong_pps > 0 else 0.0,
                        3,
                    ),
                },
                "reference_timed_runs": runs_ref,
            }
        }

    # Arrivals-only continuity run (the r01–r03 protocol, same shape
    # minus durations) so rounds stay comparable across the change.
    # Deliberately single-chip at the per-device scenario count: this is
    # the cross-round anchor, so its configuration never moves.
    cont = {}
    if dur_mean and nproc == 1:
        ec_c, ep_c = encode(cluster, _make_pods(None))
        eng_c = WhatIfEngine(
            ec_c, ep_c, uniform_scenarios(ec_c, S, seed=0), cfg,
            chunk_waves=512,
        )
        eng_c.run()
        runs_c = [eng_c.run() for _ in range(runs)]
        walls_c = sorted(r.wall_clock_s for r in runs_c)
        med_c = float(np.median(walls_c))
        cont = {
            "durationless_pps": round(
                runs_c[0].total_placed / med_c if med_c > 0 else 0.0, 1
            ),
            "durationless_wall_median_s": round(med_c, 3),
            "durationless_walls_s": [round(w, 3) for w in walls_c],
        }

    # Policy-tuner population sweep (round 9): P candidate policy vectors
    # × S_t train scenarios flattened onto the scenario axis, values
    # swapped between runs via set_policies — one compile, so the rate is
    # pure sweep throughput, the quantity a search round pays per
    # candidate. Same search space as examples/config2_full_plugins_5k
    # (all 5 default Score weights + the fit-strategy selector).
    tune_sweep = {}
    P_t = int(os.environ.get("BENCH_TUNE_POP", 16))
    S_t = int(os.environ.get("BENCH_TUNE_SCEN", 4))
    if P_t > 0 and nproc == 1:
        from kubernetes_simulator_tpu.ops import tpu as T

        rng = np.random.default_rng(0)
        K = len(T.POLICY_COLS)

        def _cands():
            c = rng.uniform(0.0, 10.0, size=(P_t, K)).astype(np.float32)
            c[:, T.IDX_FIT_LEAST] = (rng.random(P_t) < 0.5).astype(np.float32)
            return np.repeat(c, S_t, axis=0)

        train = uniform_scenarios(ec, S_t, seed=0)
        eng_t = WhatIfEngine(
            ec, ep, train * P_t, cfg, chunk_waves=512, policies=_cands(),
        )
        eng_t.run()  # warmup: compile + first execution
        walls_t = []
        for _ in range(runs):
            eng_t.set_policies(_cands())
            walls_t.append(eng_t.run().wall_clock_s)
        med_t = float(np.median(sorted(walls_t)))
        tune_sweep = {
            "tune_popsweep": {
                "candidate_policies_per_sec": round(
                    P_t / med_t if med_t > 0 else 0.0, 2
                ),
                "population": P_t,
                "train_scenarios": S_t,
                "wall_median_s": round(med_t, 3),
            }
        }

    # Borg-scale single scenario (round 14): ONE scenario whose node and
    # pod axes dwarf the headline shape (default 10k nodes × 100k pods on
    # accelerators; CPU meshes downscale so CI stays in budget), run
    # node-sharded over every local device with paged pod waves — the
    # configuration the replicated path cannot hold at Borg scale at all.
    # BENCH_BORG=0 disables; BENCH_BORG_NODES / BENCH_BORG_PODS resize.
    borg_block = {}
    if int(os.environ.get("BENCH_BORG", 1)) and nproc == 1 and ndev > 1:
        from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine

        on_cpu = jax.devices()[0].platform == "cpu"
        borg_nodes = int(
            os.environ.get("BENCH_BORG_NODES", 1000 if on_cpu else 10_000)
        )
        borg_pods = int(
            os.environ.get("BENCH_BORG_PODS", 20_000 if on_cpu else 100_000)
        )
        borg_cluster = make_cluster(borg_nodes, seed=0, taint_fraction=0.1)
        borg_pods_l, _ = make_workload(
            borg_pods, seed=0, with_affinity=True, with_spread=True,
            with_tolerations=True, gang_fraction=0.02, gang_size=4,
            duration_mean=dur_mean or None,
        )
        ec_b, ep_b = encode(borg_cluster, borg_pods_l)
        # Document the refusal the sharded mode exists to dodge: at the
        # flagship accelerator shape the REPLICATED planes bust a single
        # chip's HBM — probed via the residency estimate, not an OOM.
        from kubernetes_simulator_tpu.sim.jax_runtime import (
            replicated_resident_bytes,
        )
        replicated_bytes = replicated_resident_bytes(ec_b, ep_b)
        eng_b = JaxReplayEngine(
            ec_b, ep_b, cfg, chunk_waves=512, node_shards=ndev, paged=True,
        )
        eng_b.replay()  # warmup: compile + first execution
        runs_b = [
            eng_b.replay()
            for _ in range(max(1, int(os.environ.get("BENCH_REF_RUNS", 2))))
        ]
        walls_b = sorted(r.wall_clock_s for r in runs_b)
        med_b = float(np.median(walls_b))
        res_b = runs_b[0]
        borg_block = {
            "borg_scale": {
                "nodes": borg_nodes,
                "pods": borg_pods,
                "node_shards": ndev,
                "paged": True,
                "pps": round(
                    res_b.placed / med_b if med_b > 0 else 0.0, 1
                ),
                "wall_median_s": round(med_b, 3),
                "placed": int(res_b.placed),
                "replicated_resident_mib": round(
                    replicated_bytes / 2**20, 1
                ),
            }
        }

    # Borg-headline composed run (round 16): make_borg_encoded at the
    # BASELINE shape (BENCH_HEADLINE_NODES/PODS; CPU meshes downscale so
    # the CI gate stays in budget) through the FULL composed stack —
    # nodeShards over every local device × pagedWaves — with the flight
    # recorder ON. This is the 10k×1M run ROADMAP item 1 calls for,
    # instrumented: wall, pps, peak residency, per-phase shares and the
    # recorded stream's event count land in detail.borg_headline, and
    # the stream itself (path stamped) feeds scripts/bottleneck_report.py.
    # BENCH_HEADLINE=0 disables; BENCH_HEADLINE_FLIGHT overrides the sink.
    headline_block = {}
    if int(os.environ.get("BENCH_HEADLINE", 1)) and nproc == 1 and ndev > 1:
        import tempfile

        from kubernetes_simulator_tpu.sim.borg import (
            BorgSpec,
            make_borg_encoded,
        )
        from kubernetes_simulator_tpu.sim.flight import read_stream
        from kubernetes_simulator_tpu.sim.jax_runtime import (
            JaxReplayEngine,
            replicated_resident_bytes,
        )

        on_cpu = jax.devices()[0].platform == "cpu"
        h_nodes = int(
            os.environ.get("BENCH_HEADLINE_NODES", 1000 if on_cpu else 10_000)
        )
        h_pods = int(
            os.environ.get(
                "BENCH_HEADLINE_PODS", 20_000 if on_cpu else 1_000_000
            )
        )
        ec_h, ep_h, _ = make_borg_encoded(
            BorgSpec(nodes=h_nodes, tasks=h_pods, seed=0)
        )
        fl_path = os.environ.get("BENCH_HEADLINE_FLIGHT") or os.path.join(
            tempfile.mkdtemp(prefix="ksim_flight_"), "flight.jsonl"
        )
        eng_h = JaxReplayEngine(
            ec_h, ep_h, cfg, chunk_waves=512, node_shards=ndev, paged=True,
            telemetry="summary",
        )
        eng_h.replay()  # warmup: compile + first execution, recorder off
        eng_h.flight_recorder = fl_path  # record the timed run only
        t0_h = time.perf_counter()
        res_h = eng_h.replay()
        wall_h = time.perf_counter() - t0_h
        ph = dict(res_h.telemetry.phases) if res_h.telemetry else {}
        ph_total = sum(ph.values()) or 1.0
        flight_rows = read_stream(fl_path)
        headline_block = {
            "borg_headline": {
                "nodes": h_nodes,
                "pods": h_pods,
                "node_shards": ndev,
                "paged": True,
                "pps": round(
                    res_h.placed / wall_h if wall_h > 0 else 0.0, 1
                ),
                "wall_s": round(wall_h, 3),
                "placed": int(res_h.placed),
                "replicated_resident_mib": round(
                    replicated_resident_bytes(ec_h, ep_h) / 2**20, 1
                ),
                "phase_shares": {
                    k: round(v / ph_total, 3) for k, v in sorted(ph.items())
                },
                "flight_path": fl_path,
                "flight_events": len(flight_rows),
                "pager_stalls": max(
                    (
                        int(r.get("pager_stalls", 0))
                        for r in flight_rows
                        if r.get("event") == "chunk"
                    ),
                    default=0,
                ),
                # Overlap sub-block (round 19): how much of the three
                # former stalls is now hidden off the critical path.
                # exposed_stall_s is THE number the tentpole shrinks —
                # bench_compare flags its growth (pps stays the gate);
                # hidden_prefetch_s is pager fetch wall absorbed by the
                # background worker instead of the chunk loop.
                "overlap": _overlap_block(ph, flight_rows),
            }
        }

    # Resident query service (round 22): cold-start wall vs warm query
    # latency through the pooled-engine serving plane, plus coalesced
    # defrag throughput at full batch occupancy. The warm/cold ratio is
    # THE acceptance number — a warm query swaps scenario values against
    # the resident executable (zero recompilation, compile_counts pins
    # it), so it must come in >= 10x cheaper than the cold build.
    # BENCH_SERVICE=0 disables; BENCH_SERVICE_NODES/PODS resize.
    service_block = {}
    if int(os.environ.get("BENCH_SERVICE", 1)) and nproc == 1:
        from kubernetes_simulator_tpu.sim.service import QueryService

        s_nodes = int(os.environ.get("BENCH_SERVICE_NODES", 200))
        s_pods = int(os.environ.get("BENCH_SERVICE_PODS", 2000))
        s_rounds = int(os.environ.get("BENCH_SERVICE_ROUNDS", 4))
        cluster_s = make_cluster(s_nodes, seed=0)
        pods_s, _ = make_workload(
            s_pods, seed=0, duration_mean=dur_mean or None
        )
        ec_s, ep_s = encode(cluster_s, pods_s)
        svc = QueryService(ec_s, ep_s, cfg, max_batch=3, chunk_waves=512)
        rng_s = np.random.default_rng(0)
        qi = iter(range(10_000))

        def _defrag(i):
            picks = rng_s.choice(s_nodes, size=2, replace=False)
            return {"op": "defrag", "tenant": f"team-{i % 3}",
                    "id": f"q{i}", "nodes": [int(n) for n in picks],
                    "drainAt": 5.0, "recoverAt": 20.0}

        svc.submit(_defrag(next(qi)))
        svc.flush()
        cold_lat = float(svc.poll()[0]["latency_s"])
        warm_lats = []
        for _ in range(s_rounds):  # single-query flushes: pure latency
            svc.submit(_defrag(next(qi)))
            svc.flush()
            warm_lats.append(float(svc.poll()[0]["latency_s"]))
        warm_med = float(np.median(sorted(warm_lats)))
        t0_s = time.perf_counter()  # full-occupancy coalesced rounds
        n_coal = 0
        for _ in range(s_rounds):
            for _ in range(3):
                svc.submit(_defrag(next(qi)))  # 3rd submit auto-flushes
            n_coal += 3
        svc.poll()
        coal_wall = time.perf_counter() - t0_s
        st_s = svc.stats()
        svc.close()
        service_block = {
            "service": {
                "nodes": s_nodes,
                "pods": s_pods,
                "cold_latency_s": round(cold_lat, 3),
                "warm_latency_median_s": round(warm_med, 4),
                "warm_speedup": round(
                    cold_lat / warm_med if warm_med > 0 else 0.0, 1
                ),
                "warm_queries_per_sec": round(
                    n_coal / coal_wall if coal_wall > 0 else 0.0, 2
                ),
                "queries": st_s["queries"],
                "batches": st_s["batches"],
                "cold_builds": st_s["cold_builds"],
                "warm_hits": st_s["warm_hits"],
                "compile_counts": st_s["compile_counts"],
            }
        }

    # Memory watermarks (round 16): host RSS high-water + the PEAK
    # replicated-residency estimate across every workload this invocation
    # encoded — stamped at the TOP level of every bench JSON so the
    # BENCH_r* trajectory captures memory, not just pps.
    from kubernetes_simulator_tpu.sim.flight import rss_peak_mib
    from kubernetes_simulator_tpu.sim.jax_runtime import (
        replicated_resident_bytes as _rrb,
    )

    resident_peak_mib = _rrb(ec, ep) / 2**20
    for blk, key in (
        (borg_block.get("borg_scale"), "replicated_resident_mib"),
        (headline_block.get("borg_headline"), "replicated_resident_mib"),
    ):
        if blk:
            resident_peak_mib = max(resident_peak_mib, blk[key])

    line = json.dumps(
            {
                "metric": "pod-placements/sec (what-if %d scenarios x %d nodes x %d pods, full default plugin set, %s, %d device%s)"
                % (
                    S_head, nodes, pods_n,
                    "completions on"
                    if res.completions_on
                    else "arrivals-only",
                    ndev, "" if ndev == 1 else "s",
                ),
                "value": round(value, 1),
                "unit": "placements/sec",
                "vs_baseline": round(vs, 2),
                # Top-level provenance (round 10): rounds are only
                # comparable within a configuration — stamp it where the
                # round-over-round diff tooling looks first. Round 11
                # adds process_count (1 = the single-host protocol).
                "n_devices": ndev,
                "mesh_shape": mesh_shape,
                "scenarios": S_head,
                "process_count": nproc,
                # Round 16: memory watermarks on every bench line.
                "rss_peak_mib": rss_peak_mib(),
                "replicated_resident_peak_mib": round(resident_peak_mib, 1),
                "detail": {
                    "jax_wall_median_s": round(med_wall, 3),
                    "jax_wall_min_s": round(walls[0], 3),
                    "jax_wall_max_s": round(walls[-1], 3),
                    "jax_walls_s": [round(w, 3) for w in walls],
                    "timed_runs": runs,
                    "jax_total_placed": res.total_placed,
                    "completions_on": bool(res.completions_on),
                    "duration_mean_s": dur_mean,
                    "cpu_default_path_pps": round(cpu_pps, 1),
                    # Utilization economics (round 13): end-of-replay
                    # utilization + fragmentation gauges of the CPU
                    # baseline, and the what-if batch's mean scenario CPU
                    # utilization — bench_compare.py diffs these like the
                    # headline pps.
                    "utilization": {
                        "cpu_baseline_util_cpu": round(
                            cpu_res.utilization.get("cpu", 0.0), 6
                        ),
                        "cpu_baseline_fragmentation": round_fragmentation(
                            cpu_res.fragmentation
                        ),
                        "whatif_util_cpu_mean": round(
                            float(np.mean(res.utilization_cpu)), 6
                        ),
                    },
                    "scenario0_placed": int(res.placed[0]),
                    "device": _device_kind(),
                    # Round 12: engine wall-clock phase shares (fleet-
                    # merged, "p<pid>/<phase>" keys) + live-buffer/memory
                    # watermark after the timed runs.
                    "phases": (
                        dict(res.fleet_telemetry.phases)
                        if res.fleet_telemetry is not None
                        else {}
                    ),
                    "live_buffers": live_buffer_stats(),
                    **(
                        {"profile_dir": prof_dir} if prof_dir else {}
                    ),
                    **dcn_block,
                    **rec_block,
                    **fault_block,
                    **wq_block,
                    **durable_block,
                    **scaling,
                    **cont,
                    **tune_sweep,
                    **borg_block,
                    **headline_block,
                    **service_block,
                },
            }
        )
    # One JSON line per fleet: every process computes the identical
    # gathered result, only process 0 speaks.
    if jax.process_index() == 0:
        print(line)


def _device_kind() -> str:
    try:
        import jax

        return str(jax.devices()[0])
    except Exception as e:  # pragma: no cover
        return f"unavailable: {e}"


if __name__ == "__main__":
    main()
