#!/usr/bin/env python
"""Seeded crash-schedule fuzzer for the faultline plane (rounds 17-18).

Samples adversarial fault schedules — SIGKILL schedules (always
including the double-kill and the recovering-claimant-kill), transient
KV errors, added latency, torn checkpoint writes, stale reads, and the
round-18 work-queue drills (a deterministic straggler resolved by
speculative re-execution, and a speculator killed mid-speculation with
the block completing via the lease-expiry steal), plus the round-19
mid-publish kill (a worker SIGKILLed between its device→host snapshot
and the background publisher's KV publication, recovered from the prior
complete cursor) — runs each against a
3-worker DCN fleet with recovery enabled, and asserts the surviving
workers' end gathers are BYTE-IDENTICAL to a no-failure single-process
oracle.  The injector only ever touches the coordination plane or the
holder's wall-clock, so any divergence is a real semantics bug, not
noise.

Round 20 adds the two SUPERVISED drills of the durable-ground
acceptance bar, run through ``scripts/dcn_launch.py --supervise`` over
a durability journal: the coordinator SIGKILLed by name (``0@run:1`` —
previously the canonical unsurvivable death) and the whole fleet killed
mid-publish (``all@run:1`` under a 50% torn-write rate).  Both must end
with the supervisor relaunching the fleet with ``--resume`` and the
restarted fleet's gather byte-identical to the no-failure oracle.

Usage (also importable — tests/test_faultline_fuzz.py drives the same
functions from the pytest slow slice):

    python scripts/faultline_fuzz.py --schedules 5 --seed 17
    python scripts/faultline_fuzz.py --worker    # internal: fleet child
    python scripts/faultline_fuzz.py --oracle    # internal: oracle child

Both child modes print one ``FAULTLINE_RESULT <json>`` line; the worker
joins the coordinator through the production ``dcn.maybe_init_from_env``
path first.  Schedules are pure functions of ``--seed`` — a failure
reproduces with the same seed and schedule index.
"""

import argparse
import hashlib
import json
import os
import random
import socket
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SELF = os.path.abspath(__file__)

NPROC = 3
SCENARIOS = 12  # divisible by NPROC and by 1 (the oracle)
CHUNKS_PER_WORKER = SCENARIOS // NPROC  # wave_width=1, chunk_waves=1

SKIP_MARKER = "Multiprocess computations aren't implemented"


def _sha(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


# -- the workload (identical on worker and oracle sides) ---------------------


def build_payload() -> dict:
    """Run the fuzz workload and reduce the result to exact values and
    content hashes.  Kube boundary mode + series telemetry on the no-mesh
    DCN path — the same recovery-capable leg tests/test_dcn_recovery.py
    pins — sized so each of the 3 workers owns 4 single-scenario chunks
    (kill thresholds 0..3 all exercise a mid-block death).  Only
    virtual-time-derived fields ride the payload: phase timers are
    wall-clock and recovery legitimately re-namespaces them under the
    claimant's pid."""
    from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
    from kubernetes_simulator_tpu.models.core import Cluster, Node, Pod
    from kubernetes_simulator_tpu.models.encode import encode
    from kubernetes_simulator_tpu.sim.runtime import NodeEvent
    from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

    nodes = [Node(f"n{i}", {"cpu": 4.0}) for i in range(4)]
    pods = [
        Pod(f"p{i}", requests={"cpu": 1.0}, arrival_time=float(i),
            duration=20.0)
        for i in range(24)
    ]
    ec, ep = encode(Cluster(nodes=nodes), pods)
    cfg = FrameworkConfig(plugins=[{"name": "NodeResourcesFit"}])
    scenarios = []
    for s in range(SCENARIOS):
        if s % 3 == 1:
            scenarios.append(Scenario(events=[
                NodeEvent(time=4.0 + s, kind="node_down", node=s % 4),
                NodeEvent(time=12.0 + s, kind="node_up", node=s % 4),
            ]))
        elif s % 3 == 2:
            scenarios.append(Scenario(events=[
                NodeEvent(time=6.0 + s, kind="node_down", node=(s + 1) % 4),
            ]))
        else:
            scenarios.append(Scenario())
    eng = WhatIfEngine(
        ec, ep, scenarios, cfg, wave_width=1, chunk_waves=1,
        preemption="kube", retry_buffer=32, telemetry="series",
    )
    res = eng.run()
    ft = res.fleet_telemetry
    assert ft is not None, "fleet_telemetry missing from what-if result"
    return {
        "placed": res.placed.tolist(),
        "evictions": res.evictions.tolist(),
        "evict_rescheduled": res.evict_rescheduled.tolist(),
        "total_placed": int(res.total_placed),
        "granularity": ft.granularity,
        "latency": ft.latency,
        "reasons": ft.reasons,
        "rejection_attempts": ft.rejection_attempts,
        "zero_latency_binds": int(ft.zero_latency_binds),
        "bind_values": [float(v) for v in ft.bind_latency.values()],
        "series_sha": _sha(json.dumps(ft.series, sort_keys=True).encode()),
        "events_len": len(ft.events),
    }


def _emit(payload: dict) -> None:
    print("FAULTLINE_RESULT " + json.dumps(payload, sort_keys=True),
          flush=True)


def main_worker() -> int:
    from kubernetes_simulator_tpu.parallel import dcn

    assert dcn.maybe_init_from_env(), "KSIM_DCN_* env not set"
    _emit(build_payload())
    return 0


def main_oracle() -> int:
    _emit(build_payload())
    return 0


# -- schedule sampling -------------------------------------------------------

# The mandatory schedules of the acceptance bar: ≥2 concurrent worker
# deaths; a claimant killed at its first recovery beacon (the ``*``
# CAS entry — whichever survivor claims first dies, the other hands off
# via claim generation 1); two round-18 work-queue drills — a
# deterministic straggler resolved purely by speculative re-execution
# (lease expiry pushed out of reach), and a speculator SIGKILLed at its
# first ``spec`` beacon, after which the straggler's block still
# completes via the lease-expiry steal at generation 1; and the
# round-19 mid-publish kill — with checkpoint publication running on
# the background publisher thread, whichever worker first finishes its
# second chunk is SIGKILLed in the window between the synchronous
# device→host snapshot and the (possibly still in-flight) KV
# publication, under a 50% torn-write rate. The survivor must recover
# from the prior COMPLETE cursor (the manifest is written last, so a
# half-published epoch is invisible) and still gather byte-identical.
#
# Round 20 appends the two SUPERVISED durable-ground drills, which run
# under ``dcn_launch.py --supervise`` with a durability journal instead
# of a hand-rolled Popen fleet: the coordinator SIGKILLed by name
# (``0@run:1``), and the whole fleet killed at once (``all@run:1``)
# under a 50% torn-write rate that also tears journal files.  Both end
# only when the supervisor's relaunched fleet gathers byte-identical to
# the oracle — whole-fleet death is now inside the bar, not outside it.
MANDATORY = (
    {"name": "double-kill", "kill": "1@run:0,2@run:0", "seed": 1701},
    {"name": "claimant-kill", "kill": "2@run:0,*@recover:-1", "seed": 1702},
    {"name": "wq-straggler", "wq": 1, "slow": "1@1:4",
     "stall_s": 600, "straggler_s": 1.0, "seed": 1801},
    {"name": "wq-spec-kill", "wq": 1, "slow": "1@1:4",
     "kill": "*@spec:-1", "stall_s": 2, "straggler_s": 1.0, "seed": 1802},
    {"name": "mid-publish-kill", "kill": "*@run:1", "torn_rate": 0.5,
     "seed": 1901},
    {"name": "coord-kill-restart", "kill": "0@run:1", "supervised": 1,
     "seed": 2001},
    {"name": "fleet-kill-restart", "kill": "all@run:1", "torn_rate": 0.5,
     "supervised": 1, "seed": 2002},
)


def sample_schedules(seed: int, n: int):
    """``n`` fault schedules, a pure function of ``seed``.  The first
    seven are always the mandatory double-kill, claimant-kill,
    wq-straggler, wq-spec-kill, mid-publish-kill and the two supervised
    durable-ground drills (coord-kill-restart, fleet-kill-restart); the
    rest mix a random named kill (or none) with KV error/latency/torn/
    stale rates low enough that the bounded retries absorb them."""
    rng = random.Random(int(seed) * 9176 + 5)
    out = [dict(s) for s in MANDATORY]
    while len(out) < n:
        sch = {"name": f"rand{len(out)}", "seed": rng.randrange(1, 10 ** 6)}
        # Killable pids exclude 0: the coordinator hosts the
        # jax.distributed coordination service, whose death is
        # unsurvivable by construction (outside this fuzzer's bar).
        roll = rng.random()
        if roll < 0.45:
            pid = rng.randrange(1, NPROC)
            chunk = rng.randrange(CHUNKS_PER_WORKER - 1)
            sch["kill"] = f"{pid}@run:{chunk}"
        elif roll < 0.6:
            a, b = rng.sample(range(1, NPROC), 2)
            sch["kill"] = (
                f"{a}@run:{rng.randrange(2)},{b}@run:{rng.randrange(2)}"
            )
        sch["kv_error_rate"] = rng.choice([0.0, 0.02, 0.05])
        sch["kv_delay_rate"] = rng.choice([0.0, 0.05])
        sch["torn_rate"] = rng.choice([0.0, 0.25, 0.5])
        sch["stale_rate"] = rng.choice([0.0, 0.05])
        out.append(sch)
    return out


def named_kill_pids(sched: dict):
    """Pids a schedule kills unconditionally (named run-state entries
    with a reachable chunk threshold), and the count of ``*`` entries
    (each kills exactly one process, identity schedule-dependent)."""
    from kubernetes_simulator_tpu.parallel import faultline

    named, wildcard = set(), 0
    for pid_s, state, chunk in faultline.parse_kill_schedule(
        sched.get("kill", "")
    ):
        if pid_s == "*":
            wildcard += 1
        elif pid_s == "all":
            if state == "run" and chunk < CHUNKS_PER_WORKER:
                named.update(range(NPROC))
        elif state == "run" and chunk < CHUNKS_PER_WORKER:
            named.add(int(pid_s))
    return named, wildcard


# -- fleet orchestration -----------------------------------------------------


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _child_env(extra: dict) -> dict:
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "PYTHONPATH": os.pathsep.join(
            [_REPO]
            + [
                p
                for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
                if p and "axon" not in p
            ]
        ),
    }
    env.update({k: str(v) for k, v in extra.items()})
    return env


def run_oracle(timeout_s: float = 600.0) -> dict:
    """The no-failure reference payload, computed in a clean subprocess
    (no DCN env, no faultline) through the same JSON round-trip the
    worker results take."""
    env = _child_env({})
    for k in list(env):
        if k.startswith("KSIM_DCN") or k.startswith("KSIM_FAULTLINE"):
            del env[k]
    p = subprocess.run(
        [sys.executable, _SELF, "--oracle"],
        env=env, capture_output=True, text=True, timeout=timeout_s,
    )
    assert p.returncode == 0, f"oracle failed:\n{p.stdout}\n{p.stderr}"
    lines = [
        l for l in p.stdout.splitlines()
        if l.startswith("FAULTLINE_RESULT ")
    ]
    assert lines, f"oracle printed no result:\n{p.stdout}\n{p.stderr}"
    return json.loads(lines[-1][len("FAULTLINE_RESULT "):])


def run_supervised_schedule(sched: dict, hb_dir: str,
                            timeout_s: float = 600.0) -> dict:
    """Run one schedule through ``scripts/dcn_launch.py --supervise``
    over a durability journal.  The supervisor owns ports, pids and
    relaunch-with-``--resume``; the fault env rides through untouched
    (``maybe_kill`` self-disarms on KSIM_DCN_RESTART_COUNT > 0, so the
    kill fires only in the first life).  Worker 0 inherits the
    supervisor's stdout, so its FAULTLINE_RESULT lines — one per life —
    land in the captured blob; the LAST one is the restarted fleet's
    gather."""
    durable = os.path.join(hb_dir, "journal")
    os.makedirs(durable, exist_ok=True)
    env = _child_env({
        "KSIM_DCN_RECOVER": "1",
        "KSIM_DCN_CKPT_EVERY": "1",
        "KSIM_DCN_TIMEOUT_S": "600",
        "KSIM_DCN_STALL_S": sched.get("stall_s", 2),
        "KSIM_DCN_POLL_S": "0.3",
        "KSIM_DCN_HEARTBEAT_EVERY": "1",
        "KSIM_DCN_MAX_CLAIMS": "2",
        "KSIM_DCN_RETRY_BASE_S": "0.01",
        "KSIM_DCN_HB_DIR": hb_dir,
        "KSIM_FAULTLINE": "1",
        "KSIM_FAULTLINE_SEED": sched.get("seed", 0),
        "KSIM_FAULTLINE_KV_ERROR_RATE": sched.get("kv_error_rate", 0.0),
        "KSIM_FAULTLINE_KV_DELAY_RATE": sched.get("kv_delay_rate", 0.0),
        "KSIM_FAULTLINE_KV_DELAY_S": "0.01",
        "KSIM_FAULTLINE_TORN_RATE": sched.get("torn_rate", 0.0),
        "KSIM_FAULTLINE_STALE_RATE": sched.get("stale_rate", 0.0),
        "KSIM_FAULTLINE_KILL": sched.get("kill", ""),
        "KSIM_FAULTLINE_SLOW": sched.get("slow", ""),
    })
    # The supervisor assigns coordinator address, pids and nproc itself;
    # stray values from an outer fleet would poison its children.
    for k in ("KSIM_DCN_COORD", "KSIM_DCN_PID", "KSIM_DCN_NPROC",
              "KSIM_DCN_DURABLE_DIR", "KSIM_DCN_RESUME",
              "KSIM_DCN_RESTART_COUNT"):
        env.pop(k, None)
    cmd = [
        sys.executable, os.path.join(_REPO, "scripts", "dcn_launch.py"),
        "--nproc", str(NPROC), "--devices-per-proc", "2",
        "--supervise", "--durable", durable,
        "--max-restarts", "2", "--restart-backoff", "0.2",
        "--timeout", str(max(min(timeout_s / 2.0, 240.0), 60.0)),
        "--", sys.executable, _SELF, "--worker",
    ]
    try:
        p = subprocess.run(cmd, env=env, capture_output=True, text=True,
                           timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        blob = "\n".join(
            str(s or "") for s in (e.stdout, e.stderr)
        ) or "supervised fleet timed out"
        return {"skip": SKIP_MARKER in blob, "timeout": True,
                "supervised": True, "rcs": {}, "results": {}, "blob": blob}
    blob = (p.stdout or "") + "\n" + (p.stderr or "")
    results = {}
    lines = [
        l for l in (p.stdout or "").splitlines()
        if l.startswith("FAULTLINE_RESULT ")
    ]
    if p.returncode == 0 and lines:
        results[0] = json.loads(lines[-1][len("FAULTLINE_RESULT "):])
    return {
        "skip": SKIP_MARKER in blob,
        "timeout": False,
        "supervised": True,
        "rcs": {0: p.returncode},
        "results": results,
        "blob": blob,
    }


def run_schedule(sched: dict, hb_dir: str, timeout_s: float = 600.0) -> dict:
    """Run one schedule against a fresh 3-worker fleet.  Returns
    ``{"skip": bool, "rcs": {pid: rc}, "results": {pid: payload},
    "blob": str}`` — ``results`` holds every surviving worker's gathered
    payload.  Supervised schedules are delegated to
    ``run_supervised_schedule``."""
    if sched.get("supervised"):
        return run_supervised_schedule(sched, hb_dir, timeout_s=timeout_s)
    port = _free_port()
    base = _child_env({
        "KSIM_DCN_COORD": f"127.0.0.1:{port}",
        "KSIM_DCN_NPROC": NPROC,
        # Recovery knobs: checkpoint every chunk, claim fast, two
        # generations so a killed claimant hands off exactly once.
        "KSIM_DCN_RECOVER": "1",
        "KSIM_DCN_CKPT_EVERY": "1",
        "KSIM_DCN_TIMEOUT_S": "600",
        "KSIM_DCN_STALL_S": sched.get("stall_s", 2),
        "KSIM_DCN_POLL_S": "0.3",
        "KSIM_DCN_HEARTBEAT_EVERY": "1",
        "KSIM_DCN_MAX_CLAIMS": "2",
        "KSIM_DCN_RETRY_BASE_S": "0.01",
        "KSIM_DCN_HB_DIR": hb_dir,
        # The schedule itself.
        "KSIM_FAULTLINE": "1",
        "KSIM_FAULTLINE_SEED": sched.get("seed", 0),
        "KSIM_FAULTLINE_KV_ERROR_RATE": sched.get("kv_error_rate", 0.0),
        "KSIM_FAULTLINE_KV_DELAY_RATE": sched.get("kv_delay_rate", 0.0),
        "KSIM_FAULTLINE_KV_DELAY_S": "0.01",
        "KSIM_FAULTLINE_TORN_RATE": sched.get("torn_rate", 0.0),
        "KSIM_FAULTLINE_STALE_RATE": sched.get("stale_rate", 0.0),
        "KSIM_FAULTLINE_KILL": sched.get("kill", ""),
        "KSIM_FAULTLINE_SLOW": sched.get("slow", ""),
    })
    if sched.get("wq"):
        # Round-18 work-queue drills: leases + speculation ride the same
        # fleet; straggler_s far below the (possibly unreachable) lease
        # stall so speculation — not expiry — is what gets exercised.
        base.update({
            "KSIM_DCN_WORKQUEUE": "1",
            "KSIM_DCN_SPECULATE": "1",
            "KSIM_DCN_STRAGGLER_S": str(sched.get("straggler_s", 1.0)),
        })
    procs = []
    for pid in range(NPROC):
        procs.append(subprocess.Popen(
            [sys.executable, _SELF, "--worker"],
            env=dict(base, KSIM_DCN_PID=str(pid)),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = {}
    try:
        for pid, p in enumerate(procs):
            outs[pid] = p.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
        for pid, p in enumerate(procs):
            outs.setdefault(pid, ("", "fleet timed out"))
        return {
            "skip": False,
            "timeout": True,
            "rcs": {pid: p.returncode for pid, p in enumerate(procs)},
            "results": {},
            "blob": "\n".join(o + e for o, e in outs.values()),
        }
    blob = "\n".join(o + e for o, e in outs.values())
    results = {}
    for pid, p in enumerate(procs):
        if p.returncode == 0:
            lines = [
                l for l in outs[pid][0].splitlines()
                if l.startswith("FAULTLINE_RESULT ")
            ]
            if lines:
                results[pid] = json.loads(
                    lines[-1][len("FAULTLINE_RESULT "):]
                )
    return {
        "skip": SKIP_MARKER in blob,
        "timeout": False,
        "rcs": {pid: p.returncode for pid, p in enumerate(procs)},
        "results": results,
        "blob": blob,
    }


def check_supervised(sched: dict, out: dict, oracle: dict):
    """Assertions for a supervised drill: the kill must actually have
    forced a relaunch-with-``--resume``, the supervisor must end clean
    within its restart budget, and the restarted fleet's gather must be
    byte-identical to the no-failure oracle."""
    name = sched["name"]
    if out.get("timeout"):
        return [f"{name}: supervised fleet timed out"]
    fails = []
    rc = out["rcs"].get(0)
    if rc != 0:
        fails.append(f"{name}: supervisor exited {rc}")
    if "relaunching with --resume" not in out["blob"]:
        fails.append(
            f"{name}: the kill fired but no supervised relaunch "
            "appeared in the logs"
        )
    got = out["results"].get(0)
    if got is None:
        if rc == 0:
            fails.append(f"{name}: restarted fleet printed no result")
    elif got != oracle:
        diff = [k for k in oracle if got.get(k) != oracle[k]]
        fails.append(
            f"{name}: restarted fleet diverged from the no-failure "
            f"oracle in {diff}"
        )
    return fails


def check_schedule(sched: dict, out: dict, oracle: dict):
    """Byte-parity + liveness assertions for one schedule run.  Returns
    a list of failure strings (empty ⇒ the schedule passed)."""
    if sched.get("supervised"):
        return check_supervised(sched, out, oracle)
    fails = []
    if out.get("timeout"):
        return [f"{sched['name']}: fleet timed out"]
    named, wildcard = named_kill_pids(sched)
    rcs = out["rcs"]
    for pid in named:
        if rcs.get(pid) != -9:
            fails.append(
                f"{sched['name']}: pid {pid} should have been SIGKILLed "
                f"(rc {rcs.get(pid)})"
            )
    killed = sum(1 for rc in rcs.values() if rc == -9)
    if killed > len(named) + wildcard:
        fails.append(
            f"{sched['name']}: {killed} processes died, schedule allows "
            f"at most {len(named) + wildcard}"
        )
    survivors = [pid for pid, rc in rcs.items() if rc == 0]
    if not survivors:
        fails.append(f"{sched['name']}: no surviving worker (rcs {rcs})")
    if wildcard and killed > len(named):
        # A ``*`` entry fired — which hand-off marker to demand depends
        # on WHERE the wildcard struck. Work queue: the speculator died,
        # so the straggler's block must have completed via the
        # lease-expiry STEAL at the next lease generation. Static
        # slicing at a ``recover`` beacon: a claimant died mid-recovery,
        # so a survivor must have opened the next claim generation (the
        # fenced hand-off). Static slicing at a ``run`` beacon (the
        # round-19 mid-publish drill): an ordinary worker died, so a
        # survivor must have CLAIMED the dead process's block from its
        # last COMPLETE published cursor.
        from kubernetes_simulator_tpu.parallel import faultline

        wild_states = {
            state
            for pid_s, state, _ in faultline.parse_kill_schedule(
                sched.get("kill", "")
            )
            if pid_s == "*"
        }
        if sched.get("wq"):
            marker, what = "steals block", "lease steal"
        elif "recover" in wild_states:
            marker, what = "opening generation", "claim generation"
        else:
            marker, what = "claims dead process", "dead-process claim"
        if marker not in out["blob"]:
            fails.append(
                f"{sched['name']}: wildcard kill fired but no "
                f"{what} hand-off appeared in the logs"
            )
    if sched.get("wq") and sched.get("slow") and not sched.get("kill"):
        # Pure-straggler drill: with lease expiry out of reach, only a
        # speculative re-execution can have resolved the slowed holder.
        if "speculates block" not in out["blob"]:
            fails.append(
                f"{sched['name']}: straggler injected but no speculative "
                "re-execution appeared in the logs"
            )
    for pid in survivors:
        got = out["results"].get(pid)
        if got is None:
            fails.append(
                f"{sched['name']}: survivor {pid} printed no result"
            )
        elif got != oracle:
            diff = [k for k in oracle if got.get(k) != oracle[k]]
            fails.append(
                f"{sched['name']}: survivor {pid} diverged from the "
                f"no-failure oracle in {diff}"
            )
    return fails


_PM_MOD = [None]


def _postmortem_mod():
    """Load scripts/fleet_postmortem.py by path (scripts/ is not a
    package) and cache it — the fuzz loop audits every drill."""
    if _PM_MOD[0] is None:
        import importlib.util

        path = os.path.join(_REPO, "scripts", "fleet_postmortem.py")
        spec = importlib.util.spec_from_file_location(
            "fleet_postmortem", path
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        _PM_MOD[0] = mod
    return _PM_MOD[0]


def run_blackbox_audit(sched: dict, hb_dir: str):
    """Round-21 cap: after every drill, reconstruct the fleet's black
    box from its heartbeat-mirror directory (events.jsonl + beacons +
    the durable journal when the drill was supervised) and run the
    protocol-invariant audit.  Any violation is a drill failure — the
    post-mortem must hold even on runs that SIGKILLed processes
    mid-write.  Returns failure strings (empty ⇒ audit passed)."""
    pm = _postmortem_mod()
    try:
        report = pm.run_postmortem(hb_dir, quiet=True)
    except Exception as e:  # the tool must never crash on drill debris
        return [f"{sched['name']}: post-mortem crashed: {e!r}"]
    fails = [
        f"{sched['name']}: post-mortem invariant "
        f"{v['invariant']} violated [{v['trace']}]: {v['detail']}"
        for v in report["violations"]
    ]
    print(
        f"faultline fuzz: post-mortem {sched['name']}: "
        f"{report['events_ingested']} events, "
        f"{report['links_resolved']} causal links, audit "
        f"{'FAILED' if fails else 'ok'} "
        f"({report['audit_wall_s'] * 1000.0:.1f}ms)",
        flush=True,
    )
    return fails


def main_fuzz(seed: int, n: int, timeout_s: float) -> int:
    import tempfile

    print("faultline fuzz: oracle run (no failures) ...", flush=True)
    oracle = run_oracle(timeout_s=timeout_s)
    scheds = sample_schedules(seed, n)
    failures = []
    skipped = 0
    for i, sched in enumerate(scheds):
        desc = {k: v for k, v in sched.items() if k != "name"}
        print(f"faultline fuzz: [{i + 1}/{n}] {sched['name']} {desc}",
              flush=True)
        with tempfile.TemporaryDirectory() as hb:
            out = run_schedule(sched, hb, timeout_s=timeout_s)
            pm_fails = []
            if not out.get("skip") and not out.get("timeout"):
                pm_fails = run_blackbox_audit(sched, hb)
        if out["skip"]:
            skipped += 1
            print(
                f"faultline fuzz: [{i + 1}/{n}] SKIP (no multiprocess "
                "CPU backend)", flush=True,
            )
            continue
        fails = check_schedule(sched, out, oracle) + pm_fails
        if fails:
            failures.extend(fails)
            print(f"faultline fuzz: [{i + 1}/{n}] FAIL: {fails}",
                  flush=True)
            tail = "\n".join(out["blob"].splitlines()[-40:])
            print(tail, flush=True)
        else:
            survivors = [p for p, rc in out["rcs"].items() if rc == 0]
            print(
                f"faultline fuzz: [{i + 1}/{n}] ok — rcs {out['rcs']}, "
                f"{len(survivors)} survivor(s) byte-identical to oracle",
                flush=True,
            )
    if failures:
        print(f"faultline fuzz: {len(failures)} failure(s)", flush=True)
        return 1
    print(
        f"faultline fuzz: all {n - skipped} schedule(s) byte-identical "
        f"to the no-failure oracle ({skipped} skipped)", flush=True,
    )
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--worker", action="store_true",
                    help="internal: run as one fleet worker")
    ap.add_argument("--oracle", action="store_true",
                    help="internal: run the no-failure oracle")
    ap.add_argument("--schedules", type=int, default=8,
                    help="number of fault schedules to sample (>= 7 "
                         "includes the mandatory double-kill, "
                         "claimant-kill, wq-straggler, wq-spec-kill, "
                         "mid-publish-kill and the supervised "
                         "coord-kill-restart / fleet-kill-restart)")
    ap.add_argument("--seed", type=int, default=17)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-run timeout in seconds")
    args = ap.parse_args()
    if args.worker:
        return main_worker()
    if args.oracle:
        return main_oracle()
    return main_fuzz(args.seed, max(args.schedules, len(MANDATORY)),
                     args.timeout)


if __name__ == "__main__":
    sys.exit(main())
