#!/usr/bin/env python
"""Multi-host DCN harness (round 11): spawn N coordinator+worker
processes ON ONE MACHINE and run the same command in each.

    python scripts/dcn_launch.py --nproc 2 -- \
        python -m kubernetes_simulator_tpu what-if examples/whatif.yaml

    python scripts/dcn_launch.py --nproc 2 -- python bench.py --dcn

Each child gets ``KSIM_DCN_COORD`` / ``KSIM_DCN_NPROC`` / ``KSIM_DCN_PID``
(consumed by ``parallel.dcn.maybe_init_from_env`` — the CLI, bench.py and
scripts/northstar.py all call it on startup), plus
``--xla_force_host_platform_device_count`` so every process exposes
``--devices-per-proc`` virtual CPU devices — the same mechanism real
multi-host TPU uses, minus the hardware, so the DCN code path runs in CI.
Process 0's output streams through; siblings are captured and replayed on
failure. Any child failing kills the rest (a DCN replay cannot complete
with a hole in the scenario axis).
"""

from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import threading
import time


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def child_env(pid: int, nproc: int, port: int, devices_per_proc: int) -> dict:
    env = dict(os.environ)
    env["KSIM_DCN_COORD"] = f"127.0.0.1:{port}"
    env["KSIM_DCN_NPROC"] = str(nproc)
    env["KSIM_DCN_PID"] = str(pid)
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    env["XLA_FLAGS"] = " ".join(flags)
    return env


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument(
        "--devices-per-proc", type=int, default=4,
        help="virtual CPU devices per process (default 4: 2 procs "
             "reproduce the 8-device single-host mesh)",
    )
    ap.add_argument(
        "--timeout", type=float, default=900.0,
        help="kill the fleet after this many seconds",
    )
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run in every process (after --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (append: -- python -m ... )")
    if args.nproc < 1:
        ap.error("--nproc must be >= 1")

    port = free_port()
    procs, tails = [], []
    for pid in range(args.nproc):
        env = child_env(pid, args.nproc, port, args.devices_per_proc)
        if pid == 0:
            p = subprocess.Popen(cmd, env=env)
            tails.append(None)
        else:
            p = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            buf: list = []
            tails.append(buf)

            def drain(proc=p, sink=buf):
                for line in proc.stdout:
                    sink.append(line)

            threading.Thread(target=drain, daemon=True).start()
        procs.append(p)

    deadline = time.monotonic() + args.timeout
    rc = 0
    try:
        pending = set(range(args.nproc))
        while pending:
            if time.monotonic() > deadline:
                print(
                    f"dcn_launch: timeout after {args.timeout}s",
                    file=sys.stderr,
                )
                rc = 124
                break
            for i in sorted(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                pending.discard(i)
                if r != 0 and rc == 0:
                    rc = r
                    print(
                        f"dcn_launch: process {i} exited {r} — "
                        "killing the fleet", file=sys.stderr,
                    )
                    if tails[i]:
                        sys.stderr.writelines(
                            f"[p{i}] {line}" for line in tails[i][-50:]
                        )
            if rc:
                break
            time.sleep(0.1)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
    return rc


if __name__ == "__main__":
    sys.exit(main())
