#!/usr/bin/env python
"""Multi-host DCN harness (round 11): spawn N coordinator+worker
processes ON ONE MACHINE and run the same command in each.

    python scripts/dcn_launch.py --nproc 2 -- \
        python -m kubernetes_simulator_tpu what-if examples/whatif.yaml

    python scripts/dcn_launch.py --nproc 2 -- python bench.py --dcn

Each child gets ``KSIM_DCN_COORD`` / ``KSIM_DCN_NPROC`` / ``KSIM_DCN_PID``
(consumed by ``parallel.dcn.maybe_init_from_env`` — the CLI, bench.py and
scripts/northstar.py all call it on startup), plus
``--xla_force_host_platform_device_count`` so every process exposes
``--devices-per-proc`` virtual CPU devices — the same mechanism real
multi-host TPU uses, minus the hardware, so the DCN code path runs in CI.
Process 0's output streams through; siblings are captured and replayed on
failure. Any child failing kills the rest (a DCN replay cannot complete
with a hole in the scenario axis).

``--watch`` (round 12) tails the workers' liveness heartbeats
(parallel.dcn.heartbeat mirrors each beacon to ``$KSIM_DCN_HB_DIR``) and
prints fleet progress to stderr every couple of seconds: last completed
chunk and chunks/sec per process, a live-buffer gauge, and a straggler
flag for any process whose beacon went stale or whose chunk cursor trails
the fleet.

``--elastic N`` (round 15) launches N SPARE processes at the tail of the
pid range and turns survivor recovery on (``KSIM_DCN_RECOVER=1`` unless
already set): spares own no scenario block — they sit in the gather as
claim-eligible capacity — and a worker dying mid-replay no longer kills
the fleet; a survivor claims the dead block, resumes its newest
checkpoint (``KSIM_DCN_CKPT_EVERY``), and the launcher succeeds as long
as ANY process completes the gathered replay. ``--watch`` surfaces the
rebalance live: claim/recovered events from the KV mirror's
``events.jsonl`` plus ``recovering-p<dead>`` beacon states.

``--join N`` (round 18) launches N JOINER processes at the tail of the
pid range and turns the work-stealing scenario-block queue on
(``KSIM_DCN_WORKQUEUE=1`` unless already set). The jax.distributed
runtime barriers until every process CONNECTS, so a joiner connects at
launch like everyone else — what joins mid-replay is its CONTRIBUTION:
each joiner sleeps ``--join-delay`` seconds (staggered per joiner)
inside the queue driver, publishing a live ``join``-state beacon, then
leases whatever blocks are still pending. Unlike round-15 spares,
joiners (and every worker) can relieve a LIVE straggler, not just a
dead process. ``--watch`` renders the queue live: per-block lease
owners from the beacons, plus lease / steal / speculate / block-done /
join events.

``--supervise`` (round 20) closes the one hole every in-fleet mechanism
shares: whole-fleet death, coordinator included — the jax.distributed
KV store dies with process 0 and takes every lease, checkpoint and
result with it. With ``--durable DIR`` (or ``KSIM_DCN_DURABLE_DIR``)
the fleet mirrors all of that to a filesystem journal, and the
supervisor watches the launch: any attempt that ends without a single
completed process is relaunched — fresh coordination port, same
journal — with ``KSIM_DCN_RESUME=1`` and ``KSIM_DCN_RESTART_COUNT``
exported, under a bounded exponential-backoff restart budget
(``--max-restarts`` / ``--restart-backoff``). The resumed fleet adopts
completed work-queue blocks from the journal and resumes in-flight
blocks from their newest complete durable cursor; its end gather is
byte-identical to an uninterrupted run. ``--resume`` alone runs one
attempt seeded from an existing journal (no supervision loop).
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import socket
import subprocess
import sys
import tempfile
import threading
import time


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def child_env(
    pid: int,
    nproc: int,
    port: int,
    devices_per_proc: int,
    hb_dir: str = "",
    join_delay: float = 0.0,
    durable: str = "",
    resume: bool = False,
    restart_count: int = 0,
) -> dict:
    env = dict(os.environ)
    env["KSIM_DCN_COORD"] = f"127.0.0.1:{port}"
    env["KSIM_DCN_NPROC"] = str(nproc)
    env["KSIM_DCN_PID"] = str(pid)
    if hb_dir:
        env["KSIM_DCN_HB_DIR"] = hb_dir
    if durable:
        # Round 20 durable ground: the fleet mirrors checkpoints, queue
        # results and the done/lease ledger to this journal directory.
        env["KSIM_DCN_DURABLE_DIR"] = durable
    if resume:
        env["KSIM_DCN_RESUME"] = "1"
    if restart_count > 0:
        # Consumed by faultline (kill schedules fire only in the
        # original fleet) and visible to anything attributing restarts.
        env["KSIM_DCN_RESTART_COUNT"] = str(restart_count)
    if join_delay > 0:
        # Round 18 joiner: defer this process's work-queue contribution
        # (the coordination connect still happens at launch — the
        # runtime barriers on it; parallel.dcn.wq_run sleeps instead).
        env["KSIM_DCN_JOIN_DELAY_S"] = str(join_delay)
    env.setdefault("JAX_PLATFORMS", "cpu")
    flags = [
        f for f in env.get("XLA_FLAGS", "").split()
        if not f.startswith("--xla_force_host_platform_device_count")
    ]
    flags.append(
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    env["XLA_FLAGS"] = " ".join(flags)
    return env


class FleetWatch:
    """Heartbeat tail for ``--watch``: reads the ``p<pid>.json`` beacon
    mirrors, derives chunks/sec from consecutive samples, and flags
    stragglers (stale beacon, or a chunk cursor trailing the fleet leader
    by more than ``lag_frac`` of the replay)."""

    def __init__(
        self,
        hb_dir: str,
        nproc: int,
        stall_s: float = 60.0,
        lag_frac: float = 0.25,
        flight_path: str = "",
    ):
        self.hb_dir = hb_dir
        self.nproc = nproc
        self.stall_s = stall_s
        self.lag_frac = lag_frac
        self.flight_path = flight_path
        self._prev: dict = {}  # pid -> (chunk, t) of the last rate sample
        self._ev_pos = 0  # bytes of events.jsonl already surfaced
        self._fl_pos: dict = {}  # flight stream path -> byte cursor

    def flight_lines(self) -> list:
        """Round 16: recorder lines for live runs. Tails the flight
        stream at ``flight_path`` (process 0) and its ``.p<pid>``
        siblings with a byte cursor per file, and renders the newest
        chunk row of each as a one-line gauge: rolling placements/sec,
        pager stalls, exchange ms. Tolerant of a missing/partial stream
        — the recorder is off by default, and a mid-write tail just
        waits for the next interval."""
        if not self.flight_path:
            return []
        out = []
        for pid in range(self.nproc):
            path = (
                self.flight_path if pid == 0
                else f"{self.flight_path}.p{pid}"
            )
            try:
                with open(path) as f:
                    f.seek(self._fl_pos.get(path, 0))
                    blob = f.read()
                    self._fl_pos[path] = f.tell()
            except OSError:
                continue
            last = None
            stalls = None
            for line in blob.splitlines():
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail of a mid-write line
                if not isinstance(row, dict) or row.get("kind") != "flight":
                    continue
                if row.get("event") == "chunk":
                    last = row
                if row.get("pager_stalls") is not None:
                    stalls = int(row["pager_stalls"])
            if last is None:
                continue
            seg = (
                f"p{pid} flight chunk {last.get('chunk', '?')}"
                f" {float(last.get('rolling_pps', 0.0)):.0f}pps"
            )
            if stalls is not None:
                seg += f" stalls={stalls}"
            if last.get("exchange_est_s") is not None:
                seg += (
                    f" exch={1e3 * float(last['exchange_est_s']):.1f}ms"
                )
            if last.get("rss_peak_mib"):
                seg += f" rss={float(last['rss_peak_mib']):.0f}MiB"
            out.append(f"dcn_launch[watch]: {seg}")
        return out

    def events(self) -> list:
        """New claim/recovery events from the KV mirror's append-only
        ``events.jsonl`` (round 15: parallel.dcn._mirror_event) since the
        last call — the operator-visible trail of a live rebalance.
        Round 21: tolerant of a supervisor relaunch truncating the file
        mid-tail (a shrink resets the byte cursor to the new epoch's
        head) and of a mid-write partial final line (only complete
        lines are consumed; the tail waits for the next interval)."""
        path = os.path.join(self.hb_dir, "events.jsonl")
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() < self._ev_pos:
                    self._ev_pos = 0  # truncated underneath the tail
                f.seek(self._ev_pos)
                blob = f.read()
        except OSError:
            return []
        cut = blob.rfind(b"\n")
        if cut < 0:
            return []  # no complete line yet — keep the cursor put
        self._ev_pos += cut + 1
        out = []
        for line in blob[:cut].split(b"\n"):
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line.decode("utf-8", "replace"))
            except ValueError:
                continue
            if isinstance(row, dict):
                out.append(row)
        return out

    @staticmethod
    def event_line(e: dict) -> str:
        kind = e.get("event", "?")
        who = f"p{e.get('claimant', '?')}"
        dead = f"p{e.get('for', '?')}"
        wp = f"p{e.get('pid', '?')}"
        blk = f"block {e.get('block', '?')}"
        if kind == "claim":
            msg = (
                f"{who} CLAIMS dead {dead}'s block "
                f"(gen {e.get('gen', '?')})"
            )
        elif kind == "recovered":
            msg = (
                f"{who} RECOVERED {dead}'s block "
                f"in {float(e.get('wall_s', 0.0)):.1f}s"
            )
        # Round 18 work-queue trail (parallel.dcn.wq_run):
        elif kind == "lease":
            msg = f"{wp} leases {blk}"
        elif kind == "steal":
            msg = (
                f"{wp} STEALS {blk} from expired p{e.get('from', '?')} "
                f"(gen {e.get('gen', '?')})"
            )
        elif kind == "speculate":
            msg = (
                f"{wp} SPECULATES on straggler p{e.get('from', '?')}'s "
                f"{blk}"
            )
        elif kind == "block_done":
            msg = (
                f"{wp} completed {blk} in "
                f"{float(e.get('wall_s', 0.0)):.1f}s"
                + (" (speculative win)" if e.get("spec") else "")
            )
        elif kind in ("spec_lost", "dup_discard"):
            msg = (
                f"{wp}'s duplicate of {blk} discarded "
                f"(lost first-complete-wins)"
            )
        elif kind == "join":
            msg = f"{wp} JOINS the fleet mid-replay"
        # Round 20 durable-journal trail:
        elif kind == "journal_adopt":
            msg = (
                f"{wp} ADOPTS {blk} from the durable journal "
                f"(completed by dead fleet's p{e.get('from', '?')})"
            )
        elif kind == "journal_resume":
            msg = (
                f"{wp} RESUMES from durable checkpoint at chunk "
                f"{e.get('cursor', '?')}"
            )
        # Round 21 black-box trail:
        elif kind == "ckpt_load":
            msg = (
                f"p{e.get('by', '?')} loads {wp}'s checkpoint at chunk "
                f"{e.get('cursor', '?')}"
            )
        elif kind == "ckpt_fallback":
            msg = (
                f"p{e.get('by', '?')} FALLS BACK from {wp}'s torn "
                f"checkpoint at chunk {e.get('cursor', '?')}"
            )
        elif kind == "fault_kill":
            msg = f"{wp} FAULT-KILLED (state {e.get('state', '?')})"
        elif kind in ("fault_inject", "fault_slow"):
            msg = (
                f"{wp} fault {e.get('class', '?')} injected"
                + (f" on {e.get('key')}" if e.get("key") else "")
            )
        else:
            msg = json.dumps(e, sort_keys=True)
        return f"dcn_launch[watch]: {msg}"

    def read(self) -> dict:
        beats = {}
        for pid in range(self.nproc):
            try:
                with open(os.path.join(self.hb_dir, f"p{pid}.json")) as f:
                    beats[pid] = json.load(f)
            except (OSError, json.JSONDecodeError):
                continue
        return beats

    def line(self, beats: dict) -> str:
        now = time.time()
        max_chunk = max(
            (int(b.get("chunk", -1)) for b in beats.values()), default=-1
        )
        segs = []
        for pid in range(self.nproc):
            b = beats.get(pid)
            if b is None:
                segs.append(f"p{pid} —")
                continue
            chunk = int(b.get("chunk", -1))
            total = b.get("total_chunks")
            age = max(0.0, now - float(b.get("t", now)))
            prev = self._prev.get(pid)
            rate = ""
            if prev is not None and b.get("t", 0) > prev[1]:
                cps = (chunk - prev[0]) / (float(b["t"]) - prev[1])
                rate = f" {cps:.1f}ch/s"
            self._prev[pid] = (chunk, float(b.get("t", now)))
            lag = max_chunk - chunk
            straggler = age > self.stall_s or (
                total and lag > max(2, self.lag_frac * int(total))
            )
            state = b.get("state", "?")
            if state == "recover" and "recovering_for" in b:
                # Round 15: a claimant re-executing a dead sibling's
                # block beats under its OWN pid with the dead pid named
                # (round 21: plus the fenced claim generation).
                state = f"recovering-p{b['recovering_for']}"
                if "recover_gen" in b:
                    state += f"@g{b['recover_gen']}"
            if "wq_block" in b and int(b.get("leased_blocks", 0)):
                # Round 18: the lease this process is executing ("spec"
                # state = speculative re-execution of a straggler's
                # block). Round 21: plus the lease generation it holds.
                state = f"{state}@b{b['wq_block']}"
                if "wq_gen" in b:
                    state += f".g{b['wq_gen']}"
            seg = (
                f"p{pid} {state} "
                f"chunk {chunk}"
                + (f"/{total}" if total is not None else "")
                + rate
            )
            if "queue_depth" in b and not int(b.get("leased_blocks", 0)):
                # Idle-but-queue-pending vs stalled-holding-a-lease: the
                # round-18 beacon extras make the distinction explicit.
                seg += f" qd={b['queue_depth']}"
            if "live_buffers" in b:
                seg += f" live={b['live_buffers']}"
            if "util_cpu" in b:
                # Fleet utilization gauge (round 13): the end-of-replay
                # gather beacon carries the mean scenario CPU utilization.
                seg += f" util={float(b['util_cpu']):.1%}"
            if "restart" in b:
                # Round 21: which supervised life this process is on
                # (KSIM_DCN_RESTART_COUNT, exported by the relauncher).
                seg += f" life={b['restart']}"
            if straggler:
                seg += " [STRAGGLER]"
            segs.append(seg)
        return "dcn_launch[watch]: " + " | ".join(segs)

    def wq_line(self, beats: dict) -> str:
        """Round 18: one line of per-block lease owners, derived from the
        ``wq_block``/``leased_blocks`` beacon extras ('' when no process
        holds a queue lease — e.g. a static-slicing fleet)."""
        owners = {}
        for pid, b in beats.items():
            if int(b.get("leased_blocks", 0)) and "wq_block" in b:
                suffix = "*" if b.get("state") == "spec" else ""
                owners.setdefault(int(b["wq_block"]), []).append(
                    f"p{pid}{suffix}"
                )
        if not owners:
            return ""
        segs = [
            f"b{bid}→{'+'.join(sorted(pids))}"
            for bid, pids in sorted(owners.items())
        ]
        return (
            "dcn_launch[watch]: wq leases " + " ".join(segs)
            + " (* = speculative)"
        )


def launch_once(
    cmd,
    args,
    nproc: int,
    tolerant: bool,
    hb_dir: str,
    watch,
    attempt: int = 0,
    resume: bool = False,
    durable: str = "",
) -> int:
    """One fleet attempt: launch ``nproc`` processes on a fresh
    coordination port, monitor them to completion, and return the
    attempt's exit code (0 = at least one process — all of them, when
    ``tolerant`` is off — completed the replay). Extracted from main()
    in round 20 so ``--supervise`` can run it in a bounded restart
    loop; ``attempt``/``resume``/``durable`` ride into every child's
    environment."""
    port = free_port()
    procs, tails = [], []
    for pid in range(nproc):
        join_delay = 0.0
        if args.join and pid >= args.nproc:
            # Joiner k defers its contribution k×delay seconds so a
            # multi-joiner launch trickles capacity in, not all at once.
            join_delay = args.join_delay * (pid - args.nproc + 1)
        env = child_env(
            pid, nproc, port, args.devices_per_proc, hb_dir,
            join_delay=join_delay, durable=durable, resume=resume,
            restart_count=attempt,
        )
        if pid == 0:
            p = subprocess.Popen(cmd, env=env)
            tails.append(None)
        else:
            p = subprocess.Popen(
                cmd, env=env, stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT, text=True,
            )
            buf: list = []
            tails.append(buf)

            def drain(proc=p, sink=buf):
                for line in proc.stdout:
                    sink.append(line)

            threading.Thread(target=drain, daemon=True).start()
        procs.append(p)

    deadline = time.monotonic() + args.timeout
    next_watch = time.monotonic() + args.watch_interval
    rc = 0
    ok_exits = 0
    first_bad = 0
    try:
        pending = set(range(nproc))
        while pending:
            if watch is not None and time.monotonic() >= next_watch:
                next_watch = time.monotonic() + args.watch_interval
                for e in watch.events():
                    print(watch.event_line(e), file=sys.stderr)
                beats = watch.read()
                if beats:
                    print(watch.line(beats), file=sys.stderr)
                    wql = watch.wq_line(beats)
                    if wql:
                        print(wql, file=sys.stderr)
                for fl in watch.flight_lines():
                    print(fl, file=sys.stderr)
            if time.monotonic() > deadline:
                print(
                    f"dcn_launch: timeout after {args.timeout}s",
                    file=sys.stderr,
                )
                rc = 124
                break
            for i in sorted(pending):
                r = procs[i].poll()
                if r is None:
                    continue
                pending.discard(i)
                if r == 0:
                    ok_exits += 1
                    continue
                if first_bad == 0:
                    first_bad = r
                if tolerant:
                    # Round 15: with recovery on a dead worker's block is
                    # claimed by a survivor — the replay can still finish.
                    # Succeed iff ANY process completes the gathered
                    # result (checked after the loop).
                    print(
                        f"dcn_launch: process {i} exited {r} — recovery "
                        "enabled, fleet continues (a survivor claims the "
                        "block)", file=sys.stderr,
                    )
                    if tails[i]:
                        sys.stderr.writelines(
                            f"[p{i}] {line}" for line in tails[i][-20:]
                        )
                    continue
                rc = r
                print(
                    f"dcn_launch: process {i} exited {r} — "
                    "killing the fleet", file=sys.stderr,
                )
                if tails[i]:
                    sys.stderr.writelines(
                        f"[p{i}] {line}" for line in tails[i][-50:]
                    )
            if rc:
                break
            time.sleep(0.1)
        if watch is not None:
            for e in watch.events():
                print(watch.event_line(e), file=sys.stderr)
        if not rc and tolerant and not pending and ok_exits == 0:
            # Every process died before completing the gather — nothing
            # holds the merged replay, so the launch failed after all.
            rc = first_bad or 1
            print(
                "dcn_launch: no process completed the replay — "
                f"exit {rc}", file=sys.stderr,
            )
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for p in procs:
            p.wait()
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--nproc", type=int, default=2)
    ap.add_argument(
        "--devices-per-proc", type=int, default=4,
        help="virtual CPU devices per process (default 4: 2 procs "
             "reproduce the 8-device single-host mesh)",
    )
    ap.add_argument(
        "--timeout", type=float, default=900.0,
        help="kill the fleet after this many seconds",
    )
    ap.add_argument(
        "--watch", action="store_true",
        help="tail worker heartbeats and print fleet progress "
             "(chunks/sec per process, stragglers flagged) plus round-15 "
             "claim/recovery events to stderr",
    )
    ap.add_argument(
        "--elastic", type=int, default=0, metavar="SPARES",
        help="launch SPARES extra spare processes (no scenario block; "
             "claim-eligible capacity) and enable survivor recovery: a "
             "worker dying mid-replay no longer kills the fleet — the "
             "launch succeeds as long as any process completes "
             "(KSIM_DCN_SPARES / KSIM_DCN_RECOVER)",
    )
    ap.add_argument(
        "--join", type=int, default=0, metavar="JOINERS",
        help="round 18: launch JOINERS extra processes at the tail of "
             "the pid range and enable the work-stealing block queue "
             "(KSIM_DCN_WORKQUEUE=1 unless set): each joiner defers its "
             "queue contribution by --join-delay seconds (staggered), "
             "then leases pending blocks — true elastic capacity, not "
             "just dead-block claims",
    )
    ap.add_argument(
        "--join-delay", type=float, default=5.0, metavar="SECONDS",
        help="base contribution delay for --join processes (joiner k "
             "waits k×delay seconds; KSIM_DCN_JOIN_DELAY_S)",
    )
    ap.add_argument(
        "--watch-interval", type=float, default=2.0,
        help="seconds between --watch progress lines",
    )
    ap.add_argument(
        "--durable", default=os.environ.get("KSIM_DCN_DURABLE_DIR", ""),
        metavar="DIR",
        help="round 20: durability-journal directory "
             "(KSIM_DCN_DURABLE_DIR) — the fleet mirrors checkpoint "
             "blobs, work-queue results and the done/lease ledger there, "
             "so a whole-fleet crash is restartable with --resume or "
             "--supervise",
    )
    ap.add_argument(
        "--resume", action="store_true",
        help="round 20: seed the fleet from an existing --durable "
             "journal (KSIM_DCN_RESUME=1): completed blocks are adopted "
             "without re-execution, in-flight blocks resume from their "
             "newest complete durable cursor",
    )
    ap.add_argument(
        "--supervise", action="store_true",
        help="round 20: watch the fleet for whole-fleet death "
             "(coordinator included) and relaunch it with --resume on a "
             "fresh coordination port, under the --max-restarts / "
             "--restart-backoff budget; requires --durable",
    )
    ap.add_argument(
        "--max-restarts", type=int, default=3, metavar="N",
        help="restart budget for --supervise (default 3)",
    )
    ap.add_argument(
        "--restart-backoff", type=float, default=1.0, metavar="SECONDS",
        help="base delay before a supervised relaunch; doubles per "
             "attempt (default 1.0)",
    )
    ap.add_argument(
        "--flight", default=os.environ.get("KSIM_FLIGHT_WATCH", ""),
        metavar="PATH",
        help="round 16: with --watch, also tail this flight-recorder "
             "stream (process 0's path; .p<pid> siblings are tailed "
             "automatically) and print rolling pps / pager stalls / "
             "exchange ms per process — point it at the same path the "
             "children's flightRecorder: config writes. Missing streams "
             "are tolerated (the recorder is off by default)",
    )
    ap.add_argument("cmd", nargs=argparse.REMAINDER,
                    help="command to run in every process (after --)")
    args = ap.parse_args(argv)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        ap.error("no command given (append: -- python -m ... )")
    if args.nproc < 1:
        ap.error("--nproc must be >= 1")
    if args.elastic < 0:
        ap.error("--elastic must be >= 0")
    if args.join < 0:
        ap.error("--join must be >= 0")
    if args.join and args.elastic:
        ap.error(
            "--join and --elastic are mutually exclusive: joiners ride "
            "the work queue (any process leases any pending block), "
            "which subsumes spare capacity"
        )
    if args.join_delay < 0:
        ap.error("--join-delay must be >= 0")
    if args.supervise and not args.durable:
        ap.error(
            "--supervise requires --durable DIR (or KSIM_DCN_DURABLE_DIR)"
            ": without a journal there is nothing for a restarted fleet "
            "to resume from"
        )
    if args.resume and not args.durable:
        ap.error("--resume requires --durable DIR (or KSIM_DCN_DURABLE_DIR)")
    if args.max_restarts < 0:
        ap.error("--max-restarts must be >= 0")
    if args.restart_backoff < 0:
        ap.error("--restart-backoff must be >= 0")
    nproc = args.nproc + args.elastic + args.join
    elastic = args.elastic > 0
    if elastic:
        # Spares own no scenario block (parallel.dcn.spare_count); the
        # recovery knob defaults on so survivors/spare claim dead blocks.
        os.environ["KSIM_DCN_SPARES"] = str(args.elastic)
        os.environ.setdefault("KSIM_DCN_RECOVER", "1")
    if args.join:
        # Round 18 joiners are spare-pid processes under the work queue:
        # they own no static block, connect at launch (the runtime
        # barriers on connects) and defer their queue contribution.
        os.environ["KSIM_DCN_SPARES"] = str(args.join)
        os.environ.setdefault("KSIM_DCN_WORKQUEUE", "1")
    tolerant = elastic or str(
        os.environ.get("KSIM_DCN_RECOVER", "0")
    ).strip().lower() in ("1", "true", "yes", "on")

    hb_dir = ""
    watch = None
    if args.watch:
        hb_dir = tempfile.mkdtemp(prefix="ksim_hb_")
        watch = FleetWatch(
            hb_dir, nproc,
            stall_s=float(os.environ.get("KSIM_DCN_STALL_S", "60")),
            flight_path=args.flight,
        )
    try:
        if not args.supervise:
            return launch_once(
                cmd, args, nproc, tolerant, hb_dir, watch,
                attempt=0, resume=args.resume, durable=args.durable,
            )
        # Round 20 supervision loop: each attempt gets a fresh
        # coordination port (the old coordinator may have died holding
        # the socket); every relaunch resumes from the journal with the
        # attempt number exported. Whole-fleet death is exactly "the
        # attempt returned nonzero": a tolerant fleet already absorbs
        # partial death in-attempt, so a failed attempt means nobody
        # completed the replay — coordinator death included.
        attempt = 0
        while True:
            rc = launch_once(
                cmd, args, nproc, tolerant, hb_dir, watch,
                attempt=attempt,
                resume=args.resume or attempt > 0,
                durable=args.durable,
            )
            if rc == 0:
                if attempt > 0:
                    print(
                        f"dcn_launch: fleet completed after {attempt} "
                        "supervised restart(s)", file=sys.stderr,
                    )
                return 0
            if attempt >= args.max_restarts:
                print(
                    f"dcn_launch: restart budget exhausted after "
                    f"{attempt} restart(s) — exit {rc}", file=sys.stderr,
                )
                return rc
            delay = args.restart_backoff * (2 ** attempt)
            attempt += 1
            print(
                f"dcn_launch: whole fleet died (exit {rc}) — "
                f"relaunching with --resume in {delay:.1f}s "
                f"(attempt {attempt}/{args.max_restarts})",
                file=sys.stderr,
            )
            time.sleep(delay)
    finally:
        if hb_dir:
            shutil.rmtree(hb_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
