#!/usr/bin/env python
"""Fleet black box post-mortem (round 21): merge every artifact a DCN
run leaves behind into ONE causally-ordered timeline, export a
Perfetto/Chrome trace, and audit the fleet protocol invariants.

    python scripts/fleet_postmortem.py RUN_DIR [--out trace.json]
        [--flight PATH] [--journal PATH] [--supervisor-log PATH]
        [--jsonl PATH] [--quiet]

``RUN_DIR`` is the heartbeat mirror directory (``KSIM_DCN_HB_DIR``):
``events.jsonl`` (the dcn._mirror_event trail), ``p<pid>.json`` final
beacons, and — when the run was durable — a ``journal/`` tree
(``KSIM_DCN_DURABLE_DIR``). ``--flight`` names process 0's flight
stream (siblings at ``PATH.p<pid>``, the dcn suffix convention);
``--supervisor-log`` a captured ``dcn_launch --supervise`` transcript.

Every input is treated as potentially TORN (a SIGKILL drill writes
right up to the kill): a truncated final line, a missing per-process
file, or out-of-order timestamps degrade to a partial timeline plus a
warning — never a crash, never a false invariant violation.

The audit (exit 1 names the violated invariant and prints the block's
full event chain):

- ``one-done-winner``       exactly one done-CAS winner per block
                            episode, and the durable done ledger names
                            that winner
- ``lease-gen-monotonic``   lease/steal/claim generations never regress
- ``adopt-no-reexec``       a journal-adopted block is never re-executed
                            after the adoption
- ``resume-cursor-bounded`` a resumed cursor never exceeds the newest
                            published (and, when durable, the newest
                            complete durable) cursor
- ``steal-after-stale-renewal``  every steal observed a renewal older
                            than the stall threshold
- ``dup-has-winner``        every duplicate discard lost to a real
                            completion

``faultline_fuzz.py`` runs this tool over every drill's artifacts as
the final check after the byte-parity oracle (wired round 21).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
import zlib
from typing import Dict, List, Optional

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# Event kinds that open an execution attempt of a block (one "episode"
# runs from the first attempt to its done/adopt completion).
_ATTEMPT_KINDS = ("lease", "steal", "speculate")
_FAULT_KINDS = ("fault_inject", "fault_kill", "fault_slow")

# Flow-arrow phases for the Chrome trace: start / step / finish.
_EPS = 1e-3


def _int(v, default: int = 0) -> int:
    """Tolerant int coercion — torn inputs may hold any value."""
    try:
        return int(v)
    except (TypeError, ValueError):
        return default


def _emitting_pid(ev: dict) -> int:
    """The process that EMITTED the event (Perfetto track grouping):
    claim/recovered are emitted by the claimant, everything else by
    ``pid`` (for checkpoint events ``by`` — the loader — when present,
    since ``pid`` names the checkpoint OWNER there)."""
    kind = ev.get("event", ev.get("kind"))
    if kind in ("claim", "recovered"):
        return int(ev.get("claimant", -1))
    if kind in ("ckpt_load", "ckpt_fallback", "journal_resume"):
        return int(ev.get("by", ev.get("pid", -1)))
    try:
        return int(ev.get("pid", -1))
    except (TypeError, ValueError):
        return -1


def _read_jsonl_tolerant(path: str, warnings: List[str]):
    """Parse one line-delimited JSON file, tolerating a torn final line
    (SIGKILL mid-write) and arbitrary malformed lines. Returns a list
    of dict rows; a missing file returns [] with a warning."""
    rows = []
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError as e:
        warnings.append(f"{os.path.basename(path)}: unreadable ({e})")
        return rows
    lines = blob.split(b"\n")
    for i, line in enumerate(lines):
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line.decode("utf-8", "replace"))
        except ValueError:
            what = (
                "torn final line"
                if i >= len(lines) - 2
                else f"malformed line {i + 1}"
            )
            warnings.append(
                f"{os.path.basename(path)}: {what} skipped"
            )
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows


def load_events(run_dir: str, warnings: List[str]) -> List[dict]:
    """The primary source: ``events.jsonl`` (append-only, every process
    writes one line per fleet event, wall-stamped ``t``)."""
    path = os.path.join(run_dir, "events.jsonl")
    if not os.path.exists(path):
        warnings.append("events.jsonl: missing — timeline is partial")
        return []
    return _read_jsonl_tolerant(path, warnings)


def load_beacons(run_dir: str, warnings: List[str]) -> Dict[int, dict]:
    """Final heartbeat mirrors ``p<pid>.json`` (last state per process;
    faultline may have torn them — unparseable means absent)."""
    beacons: Dict[int, dict] = {}
    try:
        names = sorted(os.listdir(run_dir))
    except OSError:
        return beacons
    for name in names:
        if not (name.startswith("p") and name.endswith(".json")):
            continue
        try:
            pid = int(name[1:-5])
        except ValueError:
            continue
        try:
            with open(os.path.join(run_dir, name)) as f:
                beacons[pid] = json.load(f)
        except (OSError, ValueError):
            warnings.append(f"{name}: torn beacon skipped")
    return beacons


def load_flight_streams(
    flight: Optional[str], warnings: List[str]
) -> List[dict]:
    """Fleet rows from the per-process flight streams (``PATH`` +
    ``PATH.p<pid>`` siblings). Used to corroborate/extend the
    events.jsonl trail — fleet rows carry the same trace stamps. A
    missing sibling is a warning, not an error (the process may have
    died before its recorder opened)."""
    if not flight:
        return []
    rows = []
    base_dir = os.path.dirname(flight) or "."
    base_name = os.path.basename(flight)
    paths = [flight]
    try:
        for name in sorted(os.listdir(base_dir)):
            if name.startswith(base_name + ".p"):
                paths.append(os.path.join(base_dir, name))
    except OSError:
        pass
    missing = [p for p in paths if not os.path.exists(p)]
    for p in missing:
        warnings.append(
            f"flight stream {os.path.basename(p)}: missing — that "
            f"process's rows are absent from the timeline"
        )
    for p in paths:
        if p in missing:
            continue
        for row in _read_jsonl_tolerant(p, warnings):
            if row.get("kind") == "flight" and row.get("event") == "fleet":
                rows.append(row)
    return rows


def load_journal(journal: Optional[str], warnings: List[str]) -> dict:
    """Durable-journal facts for the audit: newest COMPLETE checkpoint
    cursor per (pid, block) — complete means ``manifest.json`` parses —
    and the work-queue done/lease ledgers."""
    out = {"ckpt": {}, "done": {}, "lease": {}}
    if not journal:
        return out
    if not os.path.isdir(journal):
        warnings.append(f"journal {journal}: missing — durable facts absent")
        return out
    ck = os.path.join(journal, "ckpt")
    if os.path.isdir(ck):
        for ep in sorted(os.listdir(ck)):
            for pid in sorted(
                os.listdir(os.path.join(ck, ep))
                if os.path.isdir(os.path.join(ck, ep)) else []
            ):
                pdir = os.path.join(ck, ep, pid)
                if not os.path.isdir(pdir):
                    continue
                for blk in sorted(os.listdir(pdir)):
                    bdir = os.path.join(pdir, blk)
                    if not os.path.isdir(bdir):
                        continue
                    for cur in sorted(os.listdir(bdir)):
                        man = os.path.join(bdir, cur, "manifest.json")
                        try:
                            with open(man) as f:
                                json.load(f)
                            cursor = int(cur)
                        except (OSError, ValueError):
                            continue  # in-flight / torn — not complete
                        key = (int(pid), blk)
                        if cursor > out["ckpt"].get(key, -(10**9)):
                            out["ckpt"][key] = cursor
    wq = os.path.join(journal, "wq")
    if os.path.isdir(wq):
        for seq in sorted(os.listdir(wq)):
            sdir = os.path.join(wq, seq)
            if not os.path.isdir(sdir):
                continue
            for name in sorted(os.listdir(sdir)):
                for sub in ("done", "lease"):
                    d = os.path.join(sdir, name, sub)
                    if not os.path.isdir(d):
                        continue
                    for bid in sorted(os.listdir(d)):
                        try:
                            with open(os.path.join(d, bid)) as f:
                                meta = json.load(f)
                            out[sub][int(bid)] = meta
                        except (OSError, ValueError):
                            warnings.append(
                                f"journal {sub}/{bid}: torn ledger "
                                f"record skipped"
                            )
    return out


def load_supervisor_log(
    path: Optional[str], warnings: List[str]
) -> dict:
    """Supervisor transcript facts: relaunch count (the
    ``KSIM_DCN_RESTART_COUNT`` lives the beacons also carry)."""
    info = {"relaunches": 0, "lines": 0}
    if not path:
        return info
    try:
        with open(path, errors="replace") as f:
            for line in f:
                info["lines"] += 1
                if "relaunching" in line:
                    info["relaunches"] += 1
    except OSError as e:
        warnings.append(f"supervisor log: unreadable ({e})")
    return info


def build_timeline(
    events: List[dict], flight_rows: List[dict], warnings: List[str]
) -> List[dict]:
    """One causally-ordered merged timeline. events.jsonl rows carry a
    wall stamp ``t``; flight fleet rows are deduplicated against them
    by span (both sides carry identical round-21 stamps) and slot in
    with the stream's ``ts`` when it is real, else by fill-forward
    order. Out-of-order stamps across processes demote to a warning +
    stable sort — never a crash."""
    timeline = []
    seen_spans = set()
    for i, ev in enumerate(events):
        e = dict(ev)
        e["_seq"] = i
        e["_t"] = float(ev.get("t", 0.0) or 0.0)
        timeline.append(e)
        if ev.get("span"):
            seen_spans.add((ev.get("span"), ev.get("event", ev.get("kind"))))
    base = len(timeline)
    for j, row in enumerate(flight_rows):
        kind = row.get("fleet_event")
        span = row.get("span")
        if span and (span, kind) in seen_spans:
            continue  # corroborates an events.jsonl row — already in
        e = {
            k: v for k, v in row.items()
            if k not in ("kind", "schema", "ts")
        }
        e["event"] = kind or "?"
        e.pop("fleet_event", None)
        e["_seq"] = base + j
        e["_t"] = float(row.get("ts", 0.0) or 0.0)
        e["_from_flight"] = 1
        timeline.append(e)
    # Fill-forward zero/absent stamps so file order is preserved for
    # deterministic-scrubbed streams.
    last = 0.0
    for e in timeline:
        if e["_t"] <= 0.0:
            e["_t"] = last
        last = e["_t"]
    # Out-of-order detection BEFORE the stable sort repairs it.
    prev = None
    disorder = 0
    for e in timeline:
        if prev is not None and e["_t"] < prev - _EPS:
            disorder += 1
        prev = e["_t"]
    if disorder:
        warnings.append(
            f"{disorder} event(s) carried out-of-order timestamps "
            f"across processes — timeline re-sorted (clock skew); "
            f"causal links follow trace ids, not wall order"
        )
    timeline.sort(key=lambda e: (e["_t"], e["_seq"]))
    return timeline


# ---------------------------------------------------------------------------
# Invariant audit


def _block_key(ev: dict):
    """Group key for block-lifecycle invariants: the trace id when
    stamped, else the raw block id (pre-round-21 event files)."""
    tr = ev.get("trace")
    if isinstance(tr, str) and tr.startswith("blk:"):
        return tr
    if ev.get("event") in (
        "lease", "steal", "speculate", "block_done", "spec_lost",
        "dup_discard", "journal_adopt",
    ) and ev.get("block") is not None and not isinstance(
        ev.get("block"), list
    ):
        return f"blk:{ev['block']}"
    return None


def audit(timeline: List[dict], journal: dict) -> List[dict]:
    """Run the six protocol invariants over the merged timeline.
    Returns violations: ``{"invariant", "trace", "detail", "chain"}``
    where ``chain`` is the full ordered event list for the offending
    block/cursor. Conservative by construction: an invariant whose
    evidence is absent (old event files, no journal) is SKIPPED, not
    violated — torn inputs degrade coverage, never correctness."""
    violations = []
    by_block: Dict[str, List[dict]] = {}
    for ev in timeline:
        key = _block_key(ev)
        if key is not None:
            by_block.setdefault(key, []).append(ev)

    def _chain(evs):
        return [
            {k: v for k, v in e.items() if not k.startswith("_")}
            for e in evs
        ]

    for trace_id, evs in sorted(by_block.items()):
        # Episode segmentation: within one wq_run a block's gen-0 lease
        # CAS can only be won once, so a SECOND gen-0 lease means a
        # fresh KV epoch — a later wq_run reusing block ids, or a
        # supervised restart re-executing an in-flight block. Each
        # episode is audited independently (a restart legitimately
        # re-opens gen 0 after the dead fleet's steals).
        episodes: List[List[dict]] = [[]]
        for e in evs:
            k = e.get("event")
            if (
                k == "lease"
                and _int(e.get("gen", 0) or 0) == 0
                and any(
                    x.get("event") in _ATTEMPT_KINDS
                    for x in episodes[-1]
                )
            ):
                episodes.append([])
            episodes[-1].append(e)
        for ep in episodes:
            dones = [e for e in ep if e.get("event") == "block_done"]
            adopts = [e for e in ep if e.get("event") == "journal_adopt"]
            attempts = [
                e for e in ep if e.get("event") in _ATTEMPT_KINDS
            ]
            dups = [
                e for e in ep
                if e.get("event") in ("dup_discard", "spec_lost")
            ]
            # 1. exactly one done-winner per block episode.
            if len(dones) > 1:
                violations.append({
                    "invariant": "one-done-winner",
                    "trace": trace_id,
                    "detail": (
                        f"{len(dones)} done-CAS winners: "
                        + ", ".join(
                            f"p{d.get('pid')}@g{d.get('gen')}"
                            for d in dones
                        )
                    ),
                    "chain": _chain(ep),
                })
            # 1b. the durable done ledger must name the winner.
            if len(dones) == 1 and trace_id.startswith("blk:"):
                tail = trace_id[4:]
                if tail.isdigit() and int(tail) in journal.get("done", {}):
                    led = journal["done"][int(tail)]
                    d = dones[0]
                    if (
                        _int(led.get("pid"), -1) != _int(d.get("pid"), -2)
                        or _int(led.get("gen", 0) or 0)
                        != _int(d.get("gen", 0) or 0)
                    ):
                        violations.append({
                            "invariant": "one-done-winner",
                            "trace": trace_id,
                            "detail": (
                                f"durable done ledger names "
                                f"p{led.get('pid')}@g{led.get('gen')} "
                                f"but the done-CAS winner was "
                                f"p{d.get('pid')}@g{d.get('gen')}"
                            ),
                            "chain": _chain(ep),
                        })
            # 2. lease/steal generations never regress.
            max_gen = -1
            for e in attempts:
                g = _int(e.get("gen", 0) or 0)
                if e.get("event") == "speculate":
                    continue  # speculation shares the holder's gen
                if g < max_gen:
                    violations.append({
                        "invariant": "lease-gen-monotonic",
                        "trace": trace_id,
                        "detail": (
                            f"{e.get('event')} at gen {g} after gen "
                            f"{max_gen} was already open"
                        ),
                        "chain": _chain(ep),
                    })
                    break
                max_gen = max(max_gen, g)
            # 3. adopted blocks never re-executed after the adoption.
            if adopts:
                t_adopt = min(a["_t"] for a in adopts)
                seq_adopt = min(a["_seq"] for a in adopts)
                re_exec = [
                    e for e in attempts
                    if (e["_t"], e["_seq"]) > (t_adopt, seq_adopt)
                ]
                if re_exec:
                    violations.append({
                        "invariant": "adopt-no-reexec",
                        "trace": trace_id,
                        "detail": (
                            f"{re_exec[0].get('event')} by "
                            f"p{re_exec[0].get('pid')} after the block "
                            f"was adopted from the durable journal"
                        ),
                        "chain": _chain(ep),
                    })
            # 5. every steal observed a stale renewal.
            for e in ep:
                if e.get("event") != "steal":
                    continue
                age = e.get("renew_age_s")
                thr = e.get("threshold_s")
                if age is None or thr is None:
                    continue  # pre-round-21 event file — no evidence
                try:
                    age, thr = float(age), float(thr)
                except (TypeError, ValueError):
                    continue  # torn row — not evidence
                if age + _EPS < thr:
                    violations.append({
                        "invariant": "steal-after-stale-renewal",
                        "trace": trace_id,
                        "detail": (
                            f"steal by p{e.get('pid')} with renewal "
                            f"age {age}s below the {thr}s stall "
                            f"threshold"
                        ),
                        "chain": _chain(ep),
                    })
            # 6. every duplicate discard lost to a real completion.
            # The winner's block_done event OR a durable done-ledger
            # entry counts — a winner killed between its CAS and the
            # mirror write leaves only the ledger as evidence.
            tail = trace_id.split(":", 1)[1] if ":" in trace_id else ""
            in_ledger = (
                tail.isdigit() and int(tail) in journal.get("done", {})
            )
            if dups and not dones and not adopts and not in_ledger:
                violations.append({
                    "invariant": "dup-has-winner",
                    "trace": trace_id,
                    "detail": (
                        f"{dups[0].get('event')} by "
                        f"p{dups[0].get('pid')} but no done-CAS winner "
                        f"exists for the block"
                    ),
                    "chain": _chain(ep),
                })

    # 2b. static recovery claims: generations never regress per trace.
    claims: Dict[str, int] = {}
    for ev in timeline:
        if ev.get("event") != "claim":
            continue
        tr = ev.get("trace") or f"blk:s{ev.get('for')}"
        g = _int(ev.get("gen", 0) or 0)
        if g < claims.get(tr, -1):
            violations.append({
                "invariant": "lease-gen-monotonic",
                "trace": tr,
                "detail": (
                    f"claim at gen {g} after gen {claims[tr]} was "
                    f"already open"
                ),
                "chain": [
                    {k: v for k, v in e.items() if not k.startswith("_")}
                    for e in timeline
                    if (e.get("trace") or f"blk:s{e.get('for')}") == tr
                ],
            })
        claims[tr] = max(claims.get(tr, -1), g)

    # 4. resumed cursor ≤ newest published / newest complete durable.
    published: Dict[int, int] = {}
    for ev in timeline:
        kind = ev.get("event", ev.get("kind"))
        if kind == "ckpt_publish":
            p = _int(ev.get("pid"), -1)
            published[p] = max(
                published.get(p, -(10**9)), _int(ev.get("cursor", 0))
            )
    for ev in timeline:
        if ev.get("event") not in ("ckpt_load", "journal_resume"):
            continue
        owner = _int(ev.get("pid"), -1)
        cursor = _int(ev.get("cursor", 0))
        caps = []
        blk = ev.get("block")
        if isinstance(blk, list) and len(blk) == 2:
            key = (owner, f"{blk[0]}-{blk[1]}")
            if key in journal.get("ckpt", {}):
                caps.append(journal["ckpt"][key])
        if owner in published:
            caps.append(published[owner])
        if not caps:
            continue  # no durable/published evidence — skip, not fail
        # Max of available evidence: the journal mirror is best-effort
        # and may lag the KV publish, so either source alone could
        # undercount and false-positive a legitimate resume.
        cap = max(caps)
        if cursor > cap:
            violations.append({
                "invariant": "resume-cursor-bounded",
                "trace": f"ckpt:{owner}:{cursor}",
                "detail": (
                    f"resumed cursor {cursor} exceeds the newest "
                    f"complete cursor {cap} for p{owner}"
                ),
                "chain": [
                    {k: v for k, v in e.items() if not k.startswith("_")}
                    for e in timeline
                    if _int(e.get("pid"), -2) == owner
                    and e.get("event", e.get("kind"))
                    in ("ckpt_publish", "ckpt_load", "journal_resume",
                        "ckpt_fallback")
                ],
            })
    return violations


# ---------------------------------------------------------------------------
# Perfetto / Chrome trace export


def _flow_groups(timeline: List[dict]) -> Dict[str, List[dict]]:
    """Events grouped by the trace id their flow arrow follows. An
    event with a ``link`` field joins BOTH groups — that is how a block
    arrow crosses a process death (dead pid's ckpt publish → survivor's
    load → recovery/steal)."""
    groups: Dict[str, List[dict]] = {}
    for ev in timeline:
        for key in (ev.get("trace"), ev.get("link")):
            if isinstance(key, str) and key:
                groups.setdefault(key, []).append(ev)
    return groups


def export_perfetto(
    timeline: List[dict], path: str, links_resolved: Optional[list] = None
) -> int:
    """Write a Chrome trace-event JSON: one track group per process,
    every fleet event a short slice (faultline injections as instant
    markers), and one flow arrow per trace id threading its hops in
    causal order — arrows cross track groups wherever a block changed
    hands. Returns the number of flow bindings emitted."""
    if not timeline:
        out = {"traceEvents": [], "displayTimeUnit": "ms"}
        with open(path, "w") as f:
            json.dump(out, f)
        return 0
    t0 = min(e["_t"] for e in timeline if e["_t"] > 0.0) if any(
        e["_t"] > 0.0 for e in timeline
    ) else 0.0
    events_out = []
    pids = sorted(
        {p for p in (_emitting_pid(e) for e in timeline) if p >= 0}
    )
    for p in pids:
        events_out.append({
            "name": "process_name", "ph": "M", "pid": p, "tid": 0,
            "args": {"name": f"ksim worker p{p}"},
        })
        events_out.append({
            "name": "thread_name", "ph": "M", "pid": p, "tid": 0,
            "args": {"name": "fleet events"},
        })

    def _us(e) -> int:
        return max(0, int(round((e["_t"] - t0) * 1e6)))

    for i, ev in enumerate(timeline):
        kind = str(ev.get("event", ev.get("kind", "?")))
        pid = _emitting_pid(ev)
        if pid < 0:
            pid = 0
        args = {
            k: v for k, v in ev.items() if not k.startswith("_")
        }
        name = ev.get("span") or kind
        if kind in _FAULT_KINDS:
            events_out.append({
                "name": name, "ph": "i", "s": "p",
                "pid": pid, "tid": 0, "ts": _us(ev),
                "cat": "faultline", "args": args,
            })
            continue
        cat = (
            str(ev.get("trace", "")).split(":", 1)[0]
            if ev.get("trace") else "fleet"
        )
        events_out.append({
            "name": name, "ph": "X", "dur": 500,
            "pid": pid, "tid": 0, "ts": _us(ev),
            "cat": cat or "fleet", "args": args,
        })
    flows = 0
    for trace_id, members in sorted(_flow_groups(timeline).items()):
        if len(members) < 2:
            continue
        fid = zlib.crc32(trace_id.encode()) & 0x7FFFFFFF
        ordered = sorted(members, key=lambda e: (e["_t"], e["_seq"]))
        for j, ev in enumerate(ordered):
            pid = _emitting_pid(ev)
            if pid < 0:
                pid = 0
            ph = "s" if j == 0 else ("f" if j == len(ordered) - 1 else "t")
            rec = {
                "name": trace_id, "ph": ph, "id": fid,
                "pid": pid, "tid": 0, "ts": _us(ev) + 1,
                "cat": "flow",
            }
            if ph == "f":
                rec["bp"] = "e"
            events_out.append(rec)
            flows += 1
    out = {"traceEvents": events_out, "displayTimeUnit": "ms"}
    with open(path, "w") as f:
        json.dump(out, f)
    return flows


def resolve_links(timeline: List[dict]) -> int:
    """Count parent/link references that resolve to an emitted span —
    the health gauge of the causal graph (bench_compare surfaces it)."""
    spans = {
        e.get("span") for e in timeline if isinstance(e.get("span"), str)
    }
    traces = {
        e.get("trace") for e in timeline
        if isinstance(e.get("trace"), str)
    }
    resolved = 0
    for e in timeline:
        par = e.get("parent")
        if isinstance(par, str) and (
            par in spans or any(
                isinstance(s, str) and s.startswith(par) for s in spans
            )
        ):
            resolved += 1
        link = e.get("link")
        if isinstance(link, str) and link in traces:
            resolved += 1
    return resolved


def run_postmortem(
    run_dir: str,
    flight: Optional[str] = None,
    journal: Optional[str] = None,
    supervisor_log: Optional[str] = None,
    out: Optional[str] = None,
    jsonl: Optional[str] = None,
    quiet: bool = False,
) -> dict:
    """Programmatic entry point (faultline_fuzz's cap and the tests).
    Returns the full report; ``rc`` is 0 (clean, possibly with
    warnings) or 1 (invariant violation)."""
    t_start = time.perf_counter()
    warnings: List[str] = []
    if journal is None:
        cand = os.path.join(run_dir, "journal")
        journal = cand if os.path.isdir(cand) else None
    events = load_events(run_dir, warnings)
    beacons = load_beacons(run_dir, warnings)
    flight_rows = load_flight_streams(flight, warnings)
    jfacts = load_journal(journal, warnings)
    sup = load_supervisor_log(supervisor_log, warnings)
    timeline = build_timeline(events, flight_rows, warnings)
    violations = audit(timeline, jfacts)
    links = resolve_links(timeline)
    flows = 0
    if out:
        flows = export_perfetto(timeline, out)
    wall = time.perf_counter() - t_start
    inv_names = (
        "one-done-winner", "lease-gen-monotonic", "adopt-no-reexec",
        "resume-cursor-bounded", "steal-after-stale-renewal",
        "dup-has-winner",
    )
    hit = {v["invariant"] for v in violations}
    report = {
        "rc": 1 if violations else 0,
        "run_dir": run_dir,
        "events_ingested": len(timeline),
        "flight_rows": len(flight_rows),
        "beacons": len(beacons),
        "links_resolved": links,
        "flow_bindings": flows,
        "relaunches": sup.get("relaunches", 0),
        "violations": violations,
        "warnings": warnings,
        "invariants": {
            n: ("violated" if n in hit else "ok") for n in inv_names
        },
        "audit_wall_s": round(wall, 6),
    }
    if jsonl:
        row = {
            "ts": time.time(),
            "schema": 7,  # rides the current JSONL rev (v7, round 22)
            "kind": "postmortem",
            "events_ingested": report["events_ingested"],
            "links_resolved": report["links_resolved"],
            "violations": len(violations),
            "warnings": len(warnings),
            "audit_wall_s": report["audit_wall_s"],
            "invariants": report["invariants"],
        }
        try:
            from kubernetes_simulator_tpu.utils.metrics import (
                deterministic_jsonl,
            )

            if deterministic_jsonl():
                row["ts"] = 0.0
                row["audit_wall_s"] = 0.0
        except Exception:
            pass
        with open(jsonl, "a") as f:
            f.write(json.dumps(row, sort_keys=True) + "\n")
    if not quiet:
        _print_report(report)
    return report


def _print_report(report: dict) -> None:
    print(
        f"fleet_postmortem: {report['events_ingested']} events "
        f"({report['flight_rows']} flight rows, "
        f"{report['beacons']} beacons), "
        f"{report['links_resolved']} causal links resolved, "
        f"{report['flow_bindings']} flow bindings, "
        f"audit {report['audit_wall_s'] * 1e3:.1f}ms"
    )
    for w in report["warnings"]:
        print(f"fleet_postmortem: warning: {w}")
    for name, verdict in report["invariants"].items():
        print(f"fleet_postmortem: invariant {name}: {verdict}")
    for v in report["violations"]:
        print(
            f"fleet_postmortem: VIOLATION {v['invariant']} "
            f"[{v['trace']}]: {v['detail']}"
        )
        print("fleet_postmortem: offending event chain:")
        for e in v["chain"]:
            print("  " + json.dumps(e, sort_keys=True))
    if not report["violations"]:
        print("fleet_postmortem: all invariants hold")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.split("\n\n")[0],
    )
    ap.add_argument("run_dir", help="heartbeat mirror dir (KSIM_DCN_HB_DIR)")
    ap.add_argument("--out", help="write a Perfetto/Chrome trace JSON here")
    ap.add_argument(
        "--flight",
        help="process 0's flight stream (siblings at PATH.p<pid>)",
    )
    ap.add_argument(
        "--journal",
        help="durable journal dir (default: RUN_DIR/journal when present)",
    )
    ap.add_argument("--supervisor-log", help="dcn_launch --supervise output")
    ap.add_argument(
        "--jsonl", help="append a schema-v7 'postmortem' summary row here"
    )
    ap.add_argument("--quiet", action="store_true")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.run_dir):
        print(f"fleet_postmortem: {args.run_dir}: not a directory")
        return 2
    report = run_postmortem(
        args.run_dir,
        flight=args.flight,
        journal=args.journal,
        supervisor_log=args.supervisor_log,
        out=args.out,
        jsonl=args.jsonl,
        quiet=args.quiet,
    )
    return report["rc"]


if __name__ == "__main__":
    sys.exit(main())
