"""Perf probe: how does per-pod step cost scale with S (scenarios) and N
(nodes)? Finds whether the wave scan is latency- or compute-bound.

``--dcn`` (round 11) adds the process-count axis to the trajectory: the
probe re-runs ITSELF under scripts/dcn_launch.py for each process count,
so the scaling record holds device-count sweeps (the default sweep below)
and DCN process-count sweeps side by side. Inside a DCN fleet every
process prints its local wall; read process 0's line (the others carry a
[pN] prefix only on failure).

``--exchange [OUT_JSON]`` (round 19) pins the per-slot selection-exchange
payload bytes and replay wall at node_shards ∈ {1, 2, 4, 8} into a JSON
that scripts/bench_compare.py diffs — payload growth at any shard count
gates there.
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import argparse
import subprocess
import time

import numpy as np

from kubernetes_simulator_tpu.parallel import dcn as _dcn

_dcn.maybe_init_from_env()

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios


def probe(nodes, pods_n, S, chunk_waves=256, mesh=None):
    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(
        pods_n, seed=0, with_affinity=True, with_spread=True, with_tolerations=True,
        gang_fraction=0.02, gang_size=4,
    )
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, S, seed=0)
    eng = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), chunk_waves=chunk_waves,
        mesh=mesh,
    )
    eng.run()  # warmup
    t0 = time.perf_counter()
    res = eng.run()
    wall = time.perf_counter() - t0
    per_pod_us = wall / pods_n * 1e6
    tag = f" nproc={res.process_count}" if res.process_count > 1 else ""
    print(
        f"S={S:4d} N={nodes:5d} P={pods_n:6d} G={ec.num_groups:3d} "
        f"wall={wall:6.2f}s agg={res.placements_per_sec/1e3:8.1f}k/s "
        f"us/pod-step={per_pod_us:7.1f}{tag}"
    , flush=True)


def default_sweep():
    for S in (8, 32, 128, 256):
        probe(2000, 10_000, S)
    probe(10_000, 10_000, 32)
    probe(10_000, 10_000, 128)


def node_probe(nodes, pods_n, node_shards, paged=False):
    """One single-scenario replay at N nodes — replicated planes when
    ``node_shards`` <= 1, node-sharded over that many devices otherwise
    (round 14 big-scenario mode)."""
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine

    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(
        pods_n, seed=0, with_affinity=True, with_spread=True,
        with_tolerations=True, gang_fraction=0.02, gang_size=4,
    )
    ec, ep = encode(cluster, pods)
    eng = JaxReplayEngine(
        ec, ep, FrameworkConfig(), node_shards=node_shards, paged=paged,
    )
    eng.replay()  # warmup (compile)
    t0 = time.perf_counter()
    res = eng.replay()
    wall = time.perf_counter() - t0
    mode = f"shards={node_shards}" if node_shards > 1 else "replicated"
    mode += "+paged" if paged else ""
    print(
        f"N={nodes:6d} P={pods_n:7d} {mode:>18s} wall={wall:6.2f}s "
        f"pps={res.placements_per_sec/1e3:8.1f}k/s",
        flush=True,
    )


def node_sweep(nodes_list, pods_n, paged=False):
    """Node-axis scaling at S=1 (round 14): each N runs replicated and
    node-sharded over all local devices, so the crossover where sharding
    starts paying (and the shapes the replicated path cannot hold at all)
    lands in the same scaling record as the S- and process-axis sweeps."""
    import jax

    ndev = len(jax.devices())
    for nodes in nodes_list:
        node_probe(nodes, pods_n, 1, paged=paged)
        if ndev > 1:
            node_probe(nodes, pods_n, ndev, paged=paged)


def exchange_sweep(out_path, nodes, pods_n):
    """Round 19: pin the per-slot selection-exchange payload at
    node_shards ∈ {1, 2, 4, 8}. Bytes are analytic
    (ops.tpu.exchange_payload_bytes — the implementation-neutral ring
    model, so the pin survives backend changes); walls are measured with
    a real node-sharded replay at every shard count the local device
    pool can host. The JSON lands under an ``exchange_sweep`` key that
    scripts/bench_compare.py diffs: payload growth at any shard count
    gates, wall moves are informational."""
    import json

    import jax

    from kubernetes_simulator_tpu.ops import tpu as T
    from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine

    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(
        pods_n, seed=0, with_affinity=True, with_spread=True,
        with_tolerations=True, gang_fraction=0.02, gang_size=4,
    )
    ec, ep = encode(cluster, pods)
    G = max(ec.num_groups, 1)
    two_phase = T.two_phase_exchange()
    ndev = len(jax.devices())
    points = []
    for n in (1, 2, 4, 8):
        pt = {
            "node_shards": n,
            "payload_bytes": T.exchange_payload_bytes(n, G, two_phase),
            "payload_bytes_legacy": T.exchange_payload_bytes(n, G, False),
            "wall_s": None,
        }
        if n <= max(ndev, 1):
            eng = JaxReplayEngine(
                ec, ep, FrameworkConfig(), node_shards=n,
            )
            eng.replay()  # warmup (compile)
            t0 = time.perf_counter()
            eng.replay()
            pt["wall_s"] = round(time.perf_counter() - t0, 3)
        points.append(pt)
        print(
            f"exchange @{n} shards: payload={pt['payload_bytes']}B/slot "
            f"(legacy {pt['payload_bytes_legacy']}B) wall={pt['wall_s']}",
            flush=True,
        )
    doc = {
        "exchange_sweep": {
            "nodes": nodes,
            "pods": pods_n,
            "groups": G,
            "two_phase": bool(two_phase),
            "points": points,
        }
    }
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
    print(f"exchange sweep -> {out_path}", flush=True)


def dcn_sweep(proc_counts, S, nodes, pods_n):
    """Re-launch this probe under scripts/dcn_launch.py once per process
    count — the DCN axis of the scaling trajectory (device-count sweeps
    stay in the default sweep)."""
    here = _os.path.abspath(__file__)
    launcher = _os.path.join(_os.path.dirname(here), "dcn_launch.py")
    for nproc in proc_counts:
        print(f"--- dcn axis: {nproc} process(es) ---", flush=True)
        cmd = [
            _sys.executable, launcher, "--nproc", str(nproc),
            "--devices-per-proc", "2", "--",
            _sys.executable, here, "--inner",
            "--scenarios", str(S), "--nodes", str(nodes),
            "--pods", str(pods_n),
        ]
        rc = subprocess.call(cmd)
        if rc != 0:
            print(f"dcn axis: nproc={nproc} FAILED rc={rc}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dcn", nargs="?", const="1,2", default=None,
                    help="comma list of process counts to sweep "
                         "(default '1,2')")
    ap.add_argument("--inner", action="store_true",
                    help="(internal) run one probe inside a DCN fleet")
    ap.add_argument("--scenarios", type=int, default=32)
    ap.add_argument("--nodes", type=str, default="2000",
                    help="node count (int) for --dcn/--inner, or a comma "
                         "list to run the round-14 node-axis sweep "
                         "(replicated vs node-sharded at S=1)")
    ap.add_argument("--pods", type=int, default=10_000)
    ap.add_argument("--paged", action="store_true",
                    help="stream pod pages in the node-axis sweep")
    ap.add_argument("--exchange", nargs="?", const="exchange_sweep.json",
                    default=None, metavar="OUT_JSON",
                    help="round-19 selection-exchange payload sweep at "
                         "node_shards 1/2/4/8 — writes a JSON "
                         "bench_compare.py can diff (payload growth "
                         "gates)")
    args = ap.parse_args()
    node_list = [int(x) for x in str(args.nodes).split(",") if x]
    if args.exchange:
        exchange_sweep(args.exchange, node_list[0], args.pods)
    elif args.inner:
        from kubernetes_simulator_tpu.parallel.mesh import make_mesh

        import jax

        mesh = make_mesh() if len(jax.devices()) > 1 else None
        probe(node_list[0], args.pods, args.scenarios, mesh=mesh)
    elif args.dcn is not None:
        dcn_sweep(
            [int(x) for x in args.dcn.split(",") if x],
            args.scenarios, node_list[0], args.pods,
        )
    elif len(node_list) > 1:
        node_sweep(node_list, args.pods, paged=args.paged)
    else:
        default_sweep()


if __name__ == "__main__":
    main()
