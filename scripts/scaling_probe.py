"""Perf probe: how does per-pod step cost scale with S (scenarios) and N
(nodes)? Finds whether the wave scan is latency- or compute-bound."""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import time

import numpy as np

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios


def probe(nodes, pods_n, S, chunk_waves=256):
    cluster = make_cluster(nodes, seed=0, taint_fraction=0.1)
    pods, _ = make_workload(
        pods_n, seed=0, with_affinity=True, with_spread=True, with_tolerations=True,
        gang_fraction=0.02, gang_size=4,
    )
    ec, ep = encode(cluster, pods)
    scenarios = uniform_scenarios(ec, S, seed=0)
    eng = WhatIfEngine(ec, ep, scenarios, FrameworkConfig(), chunk_waves=chunk_waves)
    eng.run()  # warmup
    t0 = time.perf_counter()
    res = eng.run()
    wall = time.perf_counter() - t0
    per_pod_us = wall / pods_n * 1e6
    print(
        f"S={S:4d} N={nodes:5d} P={pods_n:6d} G={ec.num_groups:3d} "
        f"wall={wall:6.2f}s agg={res.placements_per_sec/1e3:8.1f}k/s "
        f"us/pod-step={per_pod_us:7.1f}"
    , flush=True)


if __name__ == "__main__":
    for S in (8, 32, 128, 256):
        probe(2000, 10_000, S)
    probe(10_000, 10_000, 32)
    probe(10_000, 10_000, 128)
