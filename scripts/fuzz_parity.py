"""Randomized parity stress: host greedy anchor vs the v3 device engine
(and v2 cross-checks) across the full feature-knob space — affinity,
spread, tolerations, gangs, extended resources, forced host planes,
tier preemption, odd wave widths, and (round 4) finite durations with
chunk-granular completions, preemption × completions, and the boundary
retry buffer (what-if device path vs the anchor). Not part of the CI
suite (slow); run ad hoc before releases:

    JAX_PLATFORMS=cpu python scripts/fuzz_parity.py [trials] [master_seed]

A reduced-width seeded slice runs in CI: tests/test_fuzz_parity.py
(pytest -m fuzz) calls run_fuzz() below.
"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")
from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.models.encode import encode
from kubernetes_simulator_tpu.sim.greedy import greedy_replay
from kubernetes_simulator_tpu.sim.jax_runtime import JaxReplayEngine
from kubernetes_simulator_tpu.sim.synthetic import make_cluster, make_workload


def run_fuzz(trials: int, master: int, quick: bool = False):
  """(cases, fails) over ``trials`` randomized parity cases.

  ``quick=True`` (round 6, the default-gate ``fuzz_quick`` slice) keeps
  the knob distribution but caps trace shapes at 40 nodes / 200 pods and
  skips the what-if sub-trial (it compiles its own program per trial) so
  a handful of trials fit a <=30s budget with the compile cache off.
  The quick lists are prefixes of the full ones, so quick mode explores
  the small-shape corner of the same seeded space."""
  rng = np.random.default_rng(master)
  fails = 0
  cases = 0
  for trial in range(trials):
      seed = int(rng.integers(10_000))
      n_nodes = int(rng.choice([15, 40] if quick else [15, 40, 90, 160]))
      n_pods = int(rng.choice([80, 200] if quick else [80, 200, 400]))
      kw = dict(
          with_affinity=bool(rng.random() < 0.7),
          with_spread=bool(rng.random() < 0.7),
          with_tolerations=bool(rng.random() < 0.7),
          gang_fraction=float(rng.choice([0.0, 0.1, 0.25])),
          gang_size=int(rng.choice([2, 3, 5])),
      )
      ext = None
      if rng.random() < 0.3:
          ext = ("google.com/tpu", 8, 0.3)
      cluster = make_cluster(n_nodes, seed=seed, taint_fraction=float(rng.choice([0.0, 0.2, 0.5])),
                             num_zones=int(rng.choice([2, 4, 8])),
                             extended_resources={"google.com/tpu": (8, 0.25)} if ext else None)
      # Durations → chunk-granular completions (default ON in the device
      # engines; anchor mirrors with completions_chunk_waves).
      dm = float(rng.choice([0.0, 2.0, 8.0]))
      pods, _ = make_workload(
          n_pods, seed=seed, extended_resource=ext,
          arrival_rate=float(rng.choice([20.0, 60.0])),
          duration_mean=dm or None, **kw,
      )
      ec, ep = encode(cluster, pods)
      preempt = bool(rng.random() < 0.4)
      dmax = int(rng.choice([0, 4, 128])) if not preempt else 128
      cfg = FrameworkConfig()
      wave_width = int(rng.choice([5, 8, 13]))
      if kw["gang_fraction"] and kw["gang_size"] > wave_width:
          wave_width = 8
      C = int(rng.choice([4, 16]))
      try:
          a = greedy_replay(ec, ep, cfg, wave_width=wave_width, preemption=preempt,
                            completions_chunk_waves=C if dm else None)
          # granularity_guard=False throughout: the harness pins parity at
          # the EXPLICIT (C, RB) — the guard would rewrite them inside the
          # engines but not in the greedy anchor (its C/RB are arguments).
          d = JaxReplayEngine(ec, ep, cfg, wave_width=wave_width, chunk_waves=C,
                              dmax_coarse=dmax, preemption=preempt,
                              granularity_guard=False).replay()
          if preempt:
              # Round 10: the fused tier-preemption program vs the
              # retained pre-fusion program — sampled (each variant
              # compiles its own program) and BIT-exact when it runs.
              if rng.random() < (1.0 if quick else 0.4):
                  from kubernetes_simulator_tpu.ops import tpu3 as V3

                  old_f = V3.FUSED_PREEMPT
                  V3.FUSED_PREEMPT = not old_f
                  try:
                      d_alt = JaxReplayEngine(
                          ec, ep, cfg, wave_width=wave_width, chunk_waves=C,
                          dmax_coarse=dmax, preemption=True,
                          granularity_guard=False).replay()
                  finally:
                      V3.FUSED_PREEMPT = old_f
                  assert (d_alt.assignments == d.assignments).all(), (
                      f"fused/prefusion mismatch trial={trial} seed={seed}")
                  assert d_alt.placed == d.placed
                  assert d_alt.preemptions == d.preemptions
          else:
              v2 = JaxReplayEngine(ec, ep, cfg, wave_width=wave_width,
                                   chunk_waves=C, engine="v2",
                                   granularity_guard=False).replay()
              assert (v2.assignments == a.assignments).all(), f"v2 mismatch trial={trial}"

      except ValueError as e:
          if "host" in str(e):  # preemption+host-rows guard
              continue
          raise
      cases += 1
      mism = int((a.assignments != d.assignments).sum())
      ok = mism == 0 and a.placed == d.placed and a.preemptions == d.preemptions
      if not ok:
          fails += 1
          print(f"FAIL trial={trial} seed={seed} nodes={n_nodes} pods={n_pods} "
                f"kw={kw} preempt={preempt} dmax={dmax} W={wave_width} C={C} dm={dm} "
                f"mism={mism} placed {a.placed} vs {d.placed} "
                f"evict {a.preemptions} vs {d.preemptions}")
      # Round 5: single-replay boundary pass — retry_buffer on
      # JaxReplayEngine and kube-exact minimal-victims preemption
      # (sim.boundary), vs the greedy anchor. Sampled: each sub-trial
      # compiles nothing new (the boundary mode reuses the plain chunk
      # program), so this is cheap.
      if dm and rng.random() < 0.5:
          RB = int(rng.choice([16, 64]))
          kube = bool(rng.random() < 0.6)
          pk = "kube" if kube else False
          cases += 1
          ak = greedy_replay(ec, ep, cfg, wave_width=wave_width,
                             preemption=pk, completions_chunk_waves=C,
                             retry_buffer=RB)
          dk = JaxReplayEngine(ec, ep, cfg, wave_width=wave_width,
                               chunk_waves=C, preemption=pk,
                               retry_buffer=RB,
                               granularity_guard=False).replay()
          okk = (
              (ak.assignments == dk.assignments).all()
              and ak.placed == dk.placed
              and ak.preemptions == dk.preemptions
              and ak.retry_dropped == dk.retry_dropped
          )
          if not okk:
              fails += 1
              mismk = int((ak.assignments != dk.assignments).sum())
              print(f"KUBE-FAIL trial={trial} seed={seed} kube={kube} "
                    f"RB={RB} C={C} W={wave_width} mism={mismk} "
                    f"placed {ak.placed} vs {dk.placed} "
                    f"evict {ak.preemptions} vs {dk.preemptions}")
      # Boundary retry: the what-if device path vs the anchor (round-4
      # widened envelope — affinity/spread count planes included; only
      # preemption and DynTables stay out). Sampled at 40% — each retry
      # sub-trial compiles its own what-if program.
      if dm and not preempt and rng.random() < 0.4 and not quick:
          from kubernetes_simulator_tpu.sim.whatif import Scenario, WhatIfEngine

          RB = int(rng.choice([8, 32]))
          try:
              wi = WhatIfEngine(ec, ep, [Scenario()], cfg,
                                wave_width=wave_width, chunk_waves=C,
                                retry_buffer=RB, granularity_guard=False)
          except ValueError as e:
              # Only the retry-envelope rejection may be skipped; any
              # other construction error must fail the fuzz loudly.
              if "retry_buffer requires" not in str(e):
                  raise
              wi = None
          if wi is not None:
              cases += 1
              ar = greedy_replay(ec, ep, cfg, wave_width=wave_width,
                                 completions_chunk_waves=C, retry_buffer=RB)
              wres = wi.run()
              if int(wres.placed[0]) != ar.placed:
                  fails += 1
                  print(f"RETRY-FAIL trial={trial} seed={seed} RB={RB} C={C} "
                        f"W={wave_width} placed {int(wres.placed[0])} vs {ar.placed}")
  return cases, fails


if __name__ == "__main__":
  trials = int(sys.argv[1]) if len(sys.argv) > 1 else 48
  master = int(sys.argv[2]) if len(sys.argv) > 2 else 123
  cases, fails = run_fuzz(trials, master)
  print(f"{cases} cases, {fails} failures")
  sys.exit(1 if fails else 0)
