"""Profile the v3 what-if wave step on the north-star shape (VERDICT r2 #1:
"profile, THEN close the gap" — no more unprofiled kernel work).

Three measurements on one chip:
1. XLA cost analysis of the compiled chunk fn: total FLOPs + bytes accessed
   → achieved HBM bandwidth when divided by measured wall (v5e peak ≈ 819
   GB/s). If achieved ≈ peak, the step is traffic-bound and the bytes
   number IS the optimization target.
2. Measured wall per chunk (warm), → attempts/s and projected full-trace
   wall.
3. Optional ``jax.profiler`` trace (PROFILE_DIR=...): per-op self-time
   aggregated from the perfetto trace, grouped by fusion name — the
   op-level breakdown the round-2 verdict asked for.

Env knobs: NS_NODES, NS_TASKS, NS_S, NS_WAVE, NS_CHUNK, PROFILE_DIR,
PROFILE_CHUNKS (how many chunks to run under the trace).
"""

import os as _os
import sys as _sys

_sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))

import gzip
import json
import os
import time
from collections import defaultdict

import jax
import numpy as np

from kubernetes_simulator_tpu.framework.framework import FrameworkConfig
from kubernetes_simulator_tpu.ops import tpu as T
from kubernetes_simulator_tpu.sim.borg import BorgSpec, make_borg_encoded
from kubernetes_simulator_tpu.sim.whatif import WhatIfEngine, uniform_scenarios

V5E_PEAK_GBS = 819.0  # HBM bandwidth, TPU v5e (public spec)


def main():
    nodes = int(os.environ.get("NS_NODES", 10_000))
    tasks = int(os.environ.get("NS_TASKS", 100_000))
    S = int(os.environ.get("NS_S", 128))
    wave = int(os.environ.get("NS_WAVE", 8))
    chunk = int(os.environ.get("NS_CHUNK", 2048))
    prof_dir = os.environ.get("PROFILE_DIR", "")
    prof_chunks = int(os.environ.get("PROFILE_CHUNKS", 2))

    t0 = time.perf_counter()
    ec, ep, _ = make_borg_encoded(BorgSpec(nodes=nodes, tasks=tasks, seed=0))
    print(f"trace gen: {time.perf_counter() - t0:.1f}s", flush=True)

    scenarios = uniform_scenarios(ec, S, seed=0)
    # completions=False: profile the arrivals chunk program (the shared
    # core; the completions-on path adds the bucketed release fns and the
    # vassign fold on top — phase-attribute those with blocking timers,
    # the pattern in the round-4 COVERAGE perf log).
    eng = WhatIfEngine(
        ec, ep, scenarios, FrameworkConfig(), wave_width=wave,
        chunk_waves=chunk, completions=False,
    )
    print(f"engine: {eng.engine}  W={wave} C={chunk} S={S} N={nodes}", flush=True)
    assert eng.engine == "v3", "profiler targets the v3 scan"

    # One chunk's inputs, exactly as run() feeds them (fused-gather form).
    import jax.numpy as jnp

    idx = eng.waves.idx
    C = min(chunk, max(idx.shape[0], 1))
    states = eng._init_states()
    dc = eng.sset.dc
    src, xsrc = eng._slot_srcs
    idx_d = jnp.asarray(idx[:C])

    # --- 1. AOT cost analysis -------------------------------------------
    lowered = eng._chunk_fn.lower(dc, states, src, xsrc, idx_d)
    compiled = lowered.compile()
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
    except Exception as e:
        ca = {}
        print(f"cost_analysis unavailable: {e}", flush=True)
    flops = float(ca.get("flops", 0.0))
    bytes_acc = float(ca.get("bytes accessed", 0.0))
    print(
        f"cost analysis: flops={flops / 1e12:.3f} TF/chunk  "
        f"bytes={bytes_acc / 1e9:.3f} GB/chunk",
        flush=True,
    )

    # --- 2. Warm timing --------------------------------------------------
    # Run through the AOT-compiled executable — the jit dispatch cache is
    # separate from lower()/compile(), so calling eng._chunk_fn here would
    # compile the multi-minute chunk program a second time.
    def run_chunk(st):
        st, out = compiled(dc, st, src, xsrc, idx_d)
        return st, out

    states, out = run_chunk(states)  # warmup (already compiled; executes)
    jax.block_until_ready(out)
    walls = []
    for _ in range(3):
        t0 = time.perf_counter()
        states, out = run_chunk(states)
        jax.block_until_ready(out)
        walls.append(time.perf_counter() - t0)
    wall = float(np.median(walls))
    attempts = C * wave * S
    n_waves_total = eng.waves.idx.shape[0]
    print(
        f"chunk wall={wall:.3f}s (runs {['%.3f' % w for w in walls]})  "
        f"attempts/s={attempts / wall / 1e6:.2f}M  "
        f"achieved_bw={bytes_acc / wall / 1e9:.0f} GB/s "
        f"({100 * bytes_acc / wall / 1e9 / V5E_PEAK_GBS:.0f}% of v5e peak)  "
        f"flops_rate={flops / wall / 1e12:.2f} TF/s",
        flush=True,
    )
    per_wave_bytes = bytes_acc / C
    print(
        f"per-wave: {per_wave_bytes / 1e6:.1f} MB  "
        f"({per_wave_bytes / (S * nodes * 4) :.0f} [S,N]-f32-plane equivalents)",
        flush=True,
    )
    full_wall_proj = wall * (1_000_000 / (C * wave)) if tasks else 0.0
    print(
        f"projection to 1M tasks at this rate: {full_wall_proj:.0f}s per chip",
        flush=True,
    )

    # --- 3. Optional profiler trace -------------------------------------
    if prof_dir:
        with jax.profiler.trace(prof_dir):
            for _ in range(prof_chunks):
                states, out = run_chunk(states)
            jax.block_until_ready(out)
        print(f"profile written to {prof_dir}", flush=True)
        summarize_trace(prof_dir)


def summarize_trace(prof_dir: str, top: int = 40):
    """Aggregate device-lane op self-times from the newest perfetto trace
    under ``prof_dir`` (TensorBoard not needed)."""
    cands = []
    for root, _dirs, files in os.walk(prof_dir):
        for f in files:
            if f.endswith(".trace.json.gz") or f.endswith(".trace.json"):
                p = os.path.join(root, f)
                cands.append((os.path.getmtime(p), p))
    if not cands:
        print("no trace.json found under profile dir", flush=True)
        return
    path = max(cands)[1]
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    # Device lanes: pid/tid names containing "TPU"/"/device:" — fall back
    # to aggregating every complete event with a duration.
    pid_names = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pid_names[e.get("pid")] = e.get("args", {}).get("name", "")
    device_pids = {
        p for p, n in pid_names.items()
        if any(k in n for k in ("TPU", "Device", "device", "/device:"))
    }
    tot = defaultdict(float)
    cnt = defaultdict(int)
    for e in events:
        if e.get("ph") != "X":
            continue
        if device_pids and e.get("pid") not in device_pids:
            continue
        name = e.get("name", "?")
        tot[name] += float(e.get("dur", 0.0))
        cnt[name] += 1
    total = sum(tot.values())
    print(f"device op time total: {total / 1e6:.3f}s across {len(tot)} op names")
    for name, us in sorted(tot.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {us / 1e6:9.4f}s  {cnt[name]:6d}x  {name[:110]}")


if __name__ == "__main__":
    main()
