#!/usr/bin/env python
"""Compare bench result files (BENCH_r*.json) and flag regressions.

    python scripts/bench_compare.py BENCH_r04.json BENCH_r05.json [...]
    python scripts/bench_compare.py --threshold 0.15 BENCH_r*.json

Files are compared in the order given (oldest first — shell globs sort
BENCH_r01..rNN naturally). Each adjacent pair is diffed on:

- the headline metric (``value``, pod placements/sec): a drop of more
  than ``--threshold`` (default 10% — bench walls on shared CI hosts are
  noisy) is a REGRESSION;
- per-phase wall shares (``detail.phases``, round 12): a phase that
  grew its share of the total by more than ``threshold`` absolute is
  flagged (informational — phases shift when features land);
- DCN scaling (``detail.dcn_scaling.aggregate_pps`` and per-process
  pps where both files carry them): same threshold as the headline;
- Borg-scale block (``detail.borg_scale``, round 14): ``pps`` compared
  with the same threshold when both rounds ran the same shape
  (nodes/pods/node_shards/paged); first appearance or a reshaped run
  is informational only;
- utilization economics (``detail.utilization``, round 13): a relative
  drop in ``whatif_util_cpu_mean`` / ``cpu_baseline_util_cpu`` /
  packing efficiency beyond the threshold is a REGRESSION; growth in
  stranded capacity or the fragmentation index is informational (those
  gauges move whenever the workload mix does);
- elastic-recovery costs (``detail.dcn_recovery``, round 15): checkpoint
  codec walls and publication overhead are printed informationally and
  NEVER gate — the headline runs with checkpoint publication off, so
  these price an opt-in feature;
- Borg-headline composed block (``detail.borg_headline``, round 16):
  ``pps`` compared with the headline threshold when both rounds ran the
  same composed shape (nodes/pods/node_shards/paged); first appearance
  or a reshape is informational, and the wall / pager-stall / memory-
  watermark lines (top-level ``rss_peak_mib`` /
  ``replicated_resident_peak_mib``) never gate;
- overlap accounting (``detail.borg_headline.overlap``, round 19):
  exposed pager-stall growth beyond the threshold prints a loud
  REGRESSION note but never gates — pps remains the only headline gate;
  exchange-sweep files (``exchange_sweep`` key, written by
  ``scripts/scaling_probe.py --exchange``) ARE gated: per-slot selection
  payload bytes growing at any matching node_shards point exits nonzero;
- faultline hardening costs (``detail.fault_injection``, round 17):
  retry-helper wall, CRC framing overhead and the torn-blob fallback
  recovery wall under a fixed injected schedule — printed
  informationally and NEVER gate (injection is off in the headline).

Accepts both the archived wrapper shape ``{"n", "cmd", "rc", "parsed"}``
and a raw bench JSON line ``{"metric", "value", ...}``. Exits nonzero
iff any headline or dcn_scaling regression was flagged, so it can gate
CI; phase-share drift never fails the run on its own.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Tuple


def load_bench(path: str) -> dict:
    """Parsed bench payload from ``path`` (unwraps the BENCH_r* archive
    wrapper; raises ValueError when neither shape matches)."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and "parsed" in doc:
        doc = doc["parsed"]
    if not isinstance(doc, dict) or (
        "value" not in doc and "exchange_sweep" not in doc
    ):
        raise ValueError(
            f"{path}: not a bench result (no 'value' or 'exchange_sweep' "
            "field)"
        )
    return doc


def phase_shares(detail: dict) -> dict:
    """Per-phase fraction of the total phase wall ({} when absent)."""
    phases = detail.get("phases")
    if not isinstance(phases, dict) or not phases:
        return {}
    vals = {k: float(v) for k, v in phases.items()}
    total = sum(vals.values())
    if total <= 0:
        return {}
    return {k: v / total for k, v in vals.items()}


def _sweep_points(doc: dict) -> Optional[dict]:
    """{node_shards: point} for an exchange-sweep file, else None."""
    sw = doc.get("exchange_sweep")
    if not isinstance(sw, dict):
        return None
    return {
        int(p["node_shards"]): p
        for p in sw.get("points", [])
        if isinstance(p, dict) and "node_shards" in p
    }


def compare_pair(
    name_a: str, a: dict, name_b: str, b: dict, threshold: float
) -> Tuple[List[str], List[str]]:
    """(regressions, notes) for the pair old=a → new=b."""
    regressions: List[str] = []
    notes: List[str] = []

    # Exchange-sweep files (round 19, scripts/scaling_probe.py
    # --exchange): per-slot selection-exchange payload bytes pinned at
    # each node_shards point. Payload GROWTH at any matching point is a
    # gating regression — the two-phase slimming must not silently
    # regress — while wall moves are informational (probe walls on
    # shared CI hosts are noisy).
    ea, eb = _sweep_points(a), _sweep_points(b)
    if ea is not None or eb is not None:
        if ea is None or eb is None:
            notes.append(
                "exchange_sweep: only one side is a sweep file — "
                "nothing compared"
            )
            return regressions, notes
        for n in sorted(set(ea) & set(eb)):
            pa_b, pb_b = ea[n].get("payload_bytes"), eb[n].get("payload_bytes")
            if isinstance(pa_b, (int, float)) and isinstance(
                pb_b, (int, float)
            ):
                line = (
                    f"exchange payload_bytes @{n} shards: "
                    f"{pa_b} -> {pb_b}"
                )
                if pb_b > pa_b:
                    regressions.append(line + "  REGRESSION (payload grew)")
                else:
                    notes.append(line)
            wa_s, wb_s = ea[n].get("wall_s"), eb[n].get("wall_s")
            if isinstance(wa_s, (int, float)) and isinstance(
                wb_s, (int, float)
            ):
                notes.append(
                    f"exchange wall_s @{n} shards: {wa_s} -> {wb_s} "
                    "(informational)"
                )
        return regressions, notes

    va, vb = float(a["value"]), float(b["value"])
    if va > 0:
        delta = (vb - va) / va
        line = (
            f"headline {a.get('metric', 'value')}: "
            f"{va:.1f} -> {vb:.1f} ({delta:+.1%})"
        )
        if vb < va * (1.0 - threshold):
            regressions.append(line + "  REGRESSION")
        else:
            notes.append(line)

    da, db = a.get("detail") or {}, b.get("detail") or {}
    sa, sb = phase_shares(da), phase_shares(db)
    for k in sorted(set(sa) | set(sb)):
        grow = sb.get(k, 0.0) - sa.get(k, 0.0)
        if grow > threshold:
            notes.append(
                f"phase share {k}: {sa.get(k, 0.0):.1%} -> "
                f"{sb.get(k, 0.0):.1%} (grew {grow:+.1%})"
            )

    ua, ub = da.get("utilization"), db.get("utilization")
    if isinstance(ua, dict) and isinstance(ub, dict):
        fa = ua.get("cpu_baseline_fragmentation") or {}
        fb = ub.get("cpu_baseline_fragmentation") or {}
        gauges = {
            "util whatif_util_cpu_mean": (
                ua.get("whatif_util_cpu_mean"), ub.get("whatif_util_cpu_mean")
            ),
            "util cpu_baseline_util_cpu": (
                ua.get("cpu_baseline_util_cpu"),
                ub.get("cpu_baseline_util_cpu"),
            ),
            "util packing_efficiency": (
                fa.get("packing_efficiency"), fb.get("packing_efficiency")
            ),
        }
        for label, (ga, gb) in gauges.items():
            if (
                isinstance(ga, (int, float))
                and isinstance(gb, (int, float))
                and ga > 0
            ):
                delta = (gb - ga) / ga
                line = f"{label}: {ga:.4f} -> {gb:.4f} ({delta:+.1%})"
                if gb < ga * (1.0 - threshold):
                    regressions.append(line + "  REGRESSION")
                else:
                    notes.append(line)
        for label, ga, gb in (
            (
                "util stranded_frac(cpu)",
                (fa.get("stranded_frac") or {}).get("cpu"),
                (fb.get("stranded_frac") or {}).get("cpu"),
            ),
            (
                "util frag_index(cpu)",
                (fa.get("frag_index") or {}).get("cpu"),
                (fb.get("frag_index") or {}).get("cpu"),
            ),
        ):
            if (
                isinstance(ga, (int, float))
                and isinstance(gb, (int, float))
                and gb - ga > threshold
            ):
                notes.append(
                    f"{label}: {ga:.4f} -> {gb:.4f} "
                    f"(grew {gb - ga:+.4f} absolute)"
                )

    dsa, dsb = da.get("dcn_scaling"), db.get("dcn_scaling")
    if isinstance(dsa, dict) and isinstance(dsb, dict):
        for key in ("aggregate_pps", "per_process_pps"):
            pa, pb = dsa.get(key), dsb.get(key)
            if (
                isinstance(pa, (int, float))
                and isinstance(pb, (int, float))
                and pa > 0
            ):
                delta = (pb - pa) / pa
                line = f"dcn {key}: {pa:.1f} -> {pb:.1f} ({delta:+.1%})"
                if pb < pa * (1.0 - threshold):
                    regressions.append(line + "  REGRESSION")
                else:
                    notes.append(line)

    # Borg-scale single-scenario block (round 14): pps drop beyond the
    # threshold regresses — but ONLY when both rounds ran the same shape
    # (nodes/pods/node_shards); a reshaped or first-appearing block is
    # informational.
    bsa, bsb = da.get("borg_scale"), db.get("borg_scale")
    if isinstance(bsb, dict) and not isinstance(bsa, dict):
        notes.append(
            f"borg_scale: first appearance ({bsb.get('nodes')} nodes x "
            f"{bsb.get('pods')} pods, {bsb.get('node_shards')} shards, "
            f"pps={bsb.get('pps')})"
        )
    elif isinstance(bsa, dict) and isinstance(bsb, dict):
        same_shape = all(
            bsa.get(k) == bsb.get(k)
            for k in ("nodes", "pods", "node_shards", "paged")
        )
        pa, pb = bsa.get("pps"), bsb.get("pps")
        if not same_shape:
            notes.append(
                "borg_scale: shape changed "
                f"({bsa.get('nodes')}x{bsa.get('pods')}/"
                f"{bsa.get('node_shards')} -> {bsb.get('nodes')}x"
                f"{bsb.get('pods')}/{bsb.get('node_shards')}) — "
                "pps not compared"
            )
        elif (
            isinstance(pa, (int, float))
            and isinstance(pb, (int, float))
            and pa > 0
        ):
            delta = (pb - pa) / pa
            line = f"borg_scale pps: {pa:.1f} -> {pb:.1f} ({delta:+.1%})"
            if pb < pa * (1.0 - threshold):
                regressions.append(line + "  REGRESSION")
            else:
                notes.append(line)

    # Borg-headline composed run (round 16): same contract as borg_scale
    # — pps/wall regress only when both rounds ran the same composed
    # shape; first appearance or a reshape is informational. Memory
    # watermarks and pager stalls ride along as notes (they move when
    # the workload mix does, never gate).
    bha, bhb = da.get("borg_headline"), db.get("borg_headline")
    if isinstance(bhb, dict) and not isinstance(bha, dict):
        notes.append(
            f"borg_headline: first appearance ({bhb.get('nodes')} nodes x "
            f"{bhb.get('pods')} pods, {bhb.get('node_shards')} shards, "
            f"pps={bhb.get('pps')}, "
            f"resident={bhb.get('replicated_resident_mib')} MiB)"
        )
    elif isinstance(bha, dict) and isinstance(bhb, dict):
        same_shape = all(
            bha.get(k) == bhb.get(k)
            for k in ("nodes", "pods", "node_shards", "paged")
        )
        pa, pb = bha.get("pps"), bhb.get("pps")
        if not same_shape:
            notes.append(
                "borg_headline: shape changed "
                f"({bha.get('nodes')}x{bha.get('pods')}/"
                f"{bha.get('node_shards')} -> {bhb.get('nodes')}x"
                f"{bhb.get('pods')}/{bhb.get('node_shards')}) — "
                "pps not compared"
            )
        elif (
            isinstance(pa, (int, float))
            and isinstance(pb, (int, float))
            and pa > 0
        ):
            delta = (pb - pa) / pa
            line = f"borg_headline pps: {pa:.1f} -> {pb:.1f} ({delta:+.1%})"
            if pb < pa * (1.0 - threshold):
                regressions.append(line + "  REGRESSION")
            else:
                notes.append(line)
            wa, wb = bha.get("wall_s"), bhb.get("wall_s")
            if isinstance(wa, (int, float)) and isinstance(wb, (int, float)):
                notes.append(
                    f"borg_headline wall_s: {wa} -> {wb} (informational)"
                )
            st_a, st_b = bha.get("pager_stalls"), bhb.get("pager_stalls")
            if isinstance(st_a, int) and isinstance(st_b, int) and st_b > st_a:
                notes.append(
                    f"borg_headline pager_stalls: {st_a} -> {st_b} "
                    "(informational)"
                )
            # Overlap sub-block (round 19): exposed stall seconds are
            # THE wall the threaded pager hides — growth beyond the
            # threshold is loudly flagged as a REGRESSION note, but pps
            # above stays the only gate (stall walls on shared CI hosts
            # are noisy). Only compared when both rounds ran the same
            # overlap feature set.
            ova, ovb = bha.get("overlap"), bhb.get("overlap")
            if isinstance(ova, dict) and isinstance(ovb, dict):
                same_features = all(
                    ova.get(k) == ovb.get(k)
                    for k in ("pager_threaded", "two_phase_exchange")
                )
                ea_s = ova.get("exposed_stall_s")
                eb_s = ovb.get("exposed_stall_s")
                if not same_features:
                    notes.append(
                        "borg_headline overlap: feature set changed — "
                        "exposed stall not compared"
                    )
                elif isinstance(ea_s, (int, float)) and isinstance(
                    eb_s, (int, float)
                ):
                    line = (
                        f"borg_headline exposed_stall_s: {ea_s} -> {eb_s}"
                    )
                    if eb_s > ea_s * (1.0 + threshold) and eb_s - ea_s > 0.01:
                        notes.append(
                            line + "  REGRESSION (exposed stall grew; "
                            "non-gating — pps is the gate)"
                        )
                    else:
                        notes.append(line)
                    hb = ovb.get("hidden_prefetch_s")
                    if isinstance(hb, (int, float)) and hb > 0:
                        notes.append(
                            f"borg_headline hidden_prefetch_s: "
                            f"{ovb.get('hidden_prefetch_s')} "
                            "(absorbed off the critical path)"
                        )

    # Memory watermarks (round 16): top-level rss_peak_mib /
    # replicated_resident_peak_mib — informational trajectory, never a
    # gate (RSS moves with the allocator, residency with the shape).
    for key in ("rss_peak_mib", "replicated_resident_peak_mib"):
        ma, mb = a.get(key), b.get(key)
        if isinstance(ma, (int, float)) and isinstance(mb, (int, float)):
            notes.append(f"{key}: {ma} -> {mb} (informational)")
        elif isinstance(mb, (int, float)) and ma is None:
            notes.append(f"{key}: first appearance ({mb})")

    # Elastic-recovery costs (round 15): NEVER a regression — checkpoint
    # publication is off in the headline, so these walls price an opt-in
    # feature, and codec walls on shared CI hosts are noise-dominated.
    ra, rb = da.get("dcn_recovery"), db.get("dcn_recovery")
    if isinstance(rb, dict) and not isinstance(ra, dict):
        notes.append(
            "dcn_recovery: first appearance "
            f"(ckpt blob {rb.get('ckpt_blob_mib')} MiB, "
            f"encode {rb.get('ckpt_encode_s')}s, "
            f"restore {rb.get('recovery_restore_wall_s')}s)"
        )
    elif isinstance(ra, dict) and isinstance(rb, dict):
        for key in (
            "ckpt_encode_s",
            "recovery_restore_wall_s",
            "ckpt_publish_overhead_pct",
        ):
            ga, gb = ra.get(key), rb.get(key)
            if isinstance(ga, (int, float)) and isinstance(gb, (int, float)):
                notes.append(
                    f"dcn_recovery {key}: {ga} -> {gb} (informational)"
                )

    # Faultline hardening costs (round 17): NEVER a regression — the
    # block prices the retry helper / CRC framing / fallback path under
    # a fixed injected schedule; injection is off in the headline.
    fa, fb = da.get("fault_injection"), db.get("fault_injection")
    if isinstance(fb, dict) and not isinstance(fa, dict):
        notes.append(
            "fault_injection: first appearance "
            f"(retries {fb.get('retry_count')}, "
            f"torn detected {fb.get('torn_detected')}"
            f"/{fb.get('torn_injected')}, "
            f"fallback wall {fb.get('fallback_recovery_wall_s')}s)"
        )
    elif isinstance(fa, dict) and isinstance(fb, dict):
        for key in (
            "retry_wall_s",
            "crc_frame_overhead_pct",
            "fallback_recovery_wall_s",
        ):
            ga, gb = fa.get(key), fb.get(key)
            if isinstance(ga, (int, float)) and isinstance(gb, (int, float)):
                notes.append(
                    f"fault_injection {key}: {ga} -> {gb} (informational)"
                )

    # Work-queue accounting (round 18): NEVER a regression — the block
    # only exists when the bench ran under the opt-in work-stealing
    # queue, and steal/speculation counts are schedule-dependent, not
    # performance signals.
    wa, wb = da.get("work_queue"), db.get("work_queue")
    if isinstance(wb, dict) and not isinstance(wa, dict):
        notes.append(
            "work_queue: first appearance "
            f"(steals {wb.get('steals')}, "
            f"spec wins {wb.get('spec_wins')}, "
            f"wasted chunks {wb.get('spec_wasted_chunks')}, "
            f"renew overhead {wb.get('lease_renew_overhead_pct')}%, "
            f"straggler wall saved {wb.get('straggler_wall_saved_s')}s)"
        )
    elif isinstance(wa, dict) and isinstance(wb, dict):
        for key in (
            "steals",
            "spec_wins",
            "spec_wasted_chunks",
            "lease_renew_overhead_pct",
            "straggler_wall_saved_s",
        ):
            ga, gb = wa.get(key), wb.get(key)
            if isinstance(ga, (int, float)) and isinstance(gb, (int, float)):
                notes.append(
                    f"work_queue {key}: {ga} -> {gb} (informational)"
                )

    # Durability-journal accounting (round 20): informational, never a
    # regression — the block is a fleet-free micro-bench of the journal
    # mirror (which rides the background publisher, so it prices
    # durability, not the headline sync path) plus the cold-resume walk
    # a supervised restart pays once.
    ua, ub = da.get("durable_ground"), db.get("durable_ground")
    if isinstance(ub, dict) and not isinstance(ua, dict):
        notes.append(
            "durable_ground: first appearance "
            f"(journal write overhead {ub.get('journal_write_overhead_pct')}%"
            f", cold resume {ub.get('cold_resume_wall_s')}s, "
            f"adopted blocks {ub.get('adopted_blocks')})"
        )
    elif isinstance(ua, dict) and isinstance(ub, dict):
        for key in (
            "journal_write_overhead_pct",
            "cold_resume_wall_s",
            "adopted_blocks",
        ):
            ga, gb = ua.get(key), ub.get(key)
            if isinstance(ga, (int, float)) and isinstance(gb, (int, float)):
                notes.append(
                    f"durable_ground {key}: {ga} -> {gb} (informational)"
                )

    # Fleet black-box accounting (round 21): informational, never a
    # regression — post-mortem reconstruction runs OFFLINE over a dead
    # run's artifacts, so its cost is operator wall time, not fleet
    # time. Tracked so a causal-link resolution collapse (stamping
    # regression) or an audit-wall blow-up is visible in review.
    pa, pb = da.get("postmortem"), db.get("postmortem")
    if isinstance(pb, dict) and not isinstance(pa, dict):
        notes.append(
            "postmortem: first appearance "
            f"(audit wall {pb.get('audit_wall_s')}s, "
            f"events ingested {pb.get('events_ingested')}, "
            f"causal links resolved {pb.get('links_resolved')})"
        )
    elif isinstance(pa, dict) and isinstance(pb, dict):
        for key in ("audit_wall_s", "events_ingested", "links_resolved"):
            ga, gb = pa.get(key), pb.get(key)
            if isinstance(ga, (int, float)) and isinstance(gb, (int, float)):
                notes.append(
                    f"postmortem {key}: {ga} -> {gb} (informational)"
                )

    # Resident query service (round 22): warm throughput through the
    # pooled-engine serving plane gates like a headline pps — a drop
    # beyond the threshold at the SAME shape means warm queries started
    # recompiling or the batch coalescing broke. Cold-start wall and the
    # warm/cold speedup are informational (cold is paid once per pool
    # entry and moves with compiler versions, not with this repo).
    sva, svb = da.get("service"), db.get("service")
    if isinstance(svb, dict) and not isinstance(sva, dict):
        notes.append(
            f"service: first appearance ({svb.get('nodes')} nodes x "
            f"{svb.get('pods')} pods, warm qps="
            f"{svb.get('warm_queries_per_sec')}, "
            f"warm speedup {svb.get('warm_speedup')}x cold)"
        )
    elif isinstance(sva, dict) and isinstance(svb, dict):
        same_shape = all(
            sva.get(k) == svb.get(k) for k in ("nodes", "pods")
        )
        qa = sva.get("warm_queries_per_sec")
        qb = svb.get("warm_queries_per_sec")
        if not same_shape:
            notes.append(
                "service: shape changed "
                f"({sva.get('nodes')}x{sva.get('pods')} -> "
                f"{svb.get('nodes')}x{svb.get('pods')}) — "
                "warm qps not compared"
            )
        elif (
            isinstance(qa, (int, float))
            and isinstance(qb, (int, float))
            and qa > 0
        ):
            delta = (qb - qa) / qa
            line = (
                f"service warm queries/sec: {qa:.2f} -> {qb:.2f} "
                f"({delta:+.1%})"
            )
            if qb < qa * (1.0 - threshold):
                regressions.append(line + "  REGRESSION")
            else:
                notes.append(line)
        for key in ("cold_latency_s", "warm_latency_median_s",
                    "warm_speedup"):
            ga, gb = sva.get(key), svb.get(key)
            if isinstance(ga, (int, float)) and isinstance(gb, (int, float)):
                notes.append(
                    f"service {key}: {ga} -> {gb} (informational)"
                )
    return regressions, notes


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("files", nargs="+", help="bench JSON files, oldest first")
    ap.add_argument(
        "--threshold", type=float, default=0.10,
        help="relative drop that counts as a regression (default 0.10)",
    )
    args = ap.parse_args(argv)
    if len(args.files) < 2:
        ap.error("need at least two files to compare")

    benches = [(p, load_bench(p)) for p in args.files]
    any_regression = False
    for (pa, a), (pb, b) in zip(benches, benches[1:]):
        print(f"== {pa} -> {pb}")
        regressions, notes = compare_pair(pa, a, pb, b, args.threshold)
        for line in notes:
            print(f"   {line}")
        for line in regressions:
            print(f"   {line}")
        any_regression = any_regression or bool(regressions)
    if any_regression:
        print(
            f"bench_compare: REGRESSION beyond {args.threshold:.0%} "
            "threshold", file=sys.stderr,
        )
        return 1
    print(f"bench_compare: ok ({len(benches)} file(s), no regressions)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
